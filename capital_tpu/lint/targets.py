"""Flagship program targets for the lint gate (`make lint`).

The sanitizer is only as good as the programs it runs over; these builders
construct the repo's flagship entry points the same way the bench drivers
and the serve engine do — cholinv, cacqr, and one serve bucket ladder per
op — sized for a compile-only CPU CI pass (the invariants are properties of
the *program*, not of the wall clock; `make audit` already owns the big-N
drift runs).

Serve-bucket targets declare the same donation the engine would
(ServeConfig.donate semantics): the RHS batch for posv, the operand batch
for inv — and nothing for lstsq, whose (m, nrhs) RHS can never alias its
(n, nrhs) solution, which is exactly the donation-honored rule's point.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from capital_tpu.lint.program import ProgramTarget

TARGET_NAMES = ("cholinv", "cacqr", "serve", "batched_small", "serve_sched",
                "serve_traced", "cholinv_fused", "blocktri",
                "blocktri_partitioned", "arrowhead", "update_small",
                "refine", "session")


def _grid():
    from capital_tpu.parallel.topology import Grid

    return Grid.square(c=1, devices=jax.devices()[:1])


def cholinv_target(n: int = 512, dtype=jnp.float32) -> ProgramTarget:
    from capital_tpu.bench import drivers
    from capital_tpu.models import cholesky

    grid = _grid()
    cfg = cholesky.CholinvConfig(base_case_dim=drivers.pick_bc(n, 0))
    A = drivers._spd(n, dtype)

    def step(a):
        R, Rinv = cholesky.factor(grid, a, cfg)
        return R + Rinv

    return ProgramTarget(name=f"cholinv-n{n}", fn=step, args=(A,))


def cacqr_target(m: int = 4096, n: int = 256,
                 dtype=jnp.float32) -> ProgramTarget:
    from capital_tpu.bench import drivers
    from capital_tpu.models import cholesky, qr

    grid = _grid()
    bc = drivers.pick_bc(n, 0)
    cfg = qr.CacqrConfig(
        cholinv=cholesky.CholinvConfig(base_case_dim=bc),
    )
    A = jax.block_until_ready(
        jax.random.normal(jax.random.key(0), (m, n), dtype=dtype)
    )

    def step(a):
        Q, R = qr.factor(grid, a, cfg)
        return Q.at[: R.shape[0], : R.shape[1]].add(R.astype(Q.dtype))

    return ProgramTarget(name=f"cacqr-m{m}-n{n}", fn=step, args=(A,))


def serve_bucket_targets(
    n: int = 256, rows: int = 1024, nrhs: int = 8, capacity: int = 4,
    dtype=jnp.float32,
) -> list[ProgramTarget]:
    """One target per served op at one bucket shape, mirroring
    serve/engine._get_batched's executables and donation declarations."""
    from capital_tpu.serve import api

    dt = jnp.dtype(dtype)
    a_sq = jax.ShapeDtypeStruct((capacity, n, n), dt)
    b_sq = jax.ShapeDtypeStruct((capacity, n, nrhs), dt)
    a_tall = jax.ShapeDtypeStruct((capacity, rows, n), dt)
    b_tall = jax.ShapeDtypeStruct((capacity, rows, nrhs), dt)
    mk = f"b{capacity}-n{n}"
    return [
        ProgramTarget(
            name=f"serve-posv-{mk}", fn=api.batched("posv"),
            args=(a_sq, b_sq), donate_argnums=(1,),
        ),
        ProgramTarget(
            name=f"serve-lstsq-{mk}-m{rows}", fn=api.batched("lstsq"),
            args=(a_tall, b_tall),  # no donation: (m,nrhs) RHS can't alias
        ),
        ProgramTarget(
            name=f"serve-inv-{mk}", fn=api.batched("inv"),
            args=(a_sq,), donate_argnums=(0,),
        ),
    ]


def batched_small_targets(
    n: int = 64, rows: int = 256, nrhs: int = 4, capacity: int = 8,
    dtype=jnp.float32,
) -> list[ProgramTarget]:
    """Batched-grid small-N bucket programs (ops/batched_small), built the
    way serve/engine._get_batched builds them when ServeConfig.small_n_impl
    routes pallas: the fused posv and lstsq buckets plus the split
    potrf+potrs variant the autotune sweeps against them.

    No donation is declared: the kernels' RHS aliasing lives inside the
    ``pallas_call`` (``input_output_aliases``), which the CPU lint rig's
    interpret mode drops entirely — declaring a jit-level donation here
    would make the donation-honored rule fail for a platform reason, not a
    program one.  ``flops_audited=False`` for the same reason: the kernel
    flops execute inside the interpreted ``pallas_call``, invisible to
    XLA ``cost_analysis``, so the whole-program flops envelope would flag
    the rig rather than the program (ProgramTarget docstring)."""
    from capital_tpu.serve import api

    dt = jnp.dtype(dtype)
    a_sq = jax.ShapeDtypeStruct((capacity, n, n), dt)
    b_sq = jax.ShapeDtypeStruct((capacity, n, nrhs), dt)
    a_tall = jax.ShapeDtypeStruct((capacity, rows, n), dt)
    b_tall = jax.ShapeDtypeStruct((capacity, rows, nrhs), dt)
    mk = f"b{capacity}-n{n}"
    return [
        ProgramTarget(
            name=f"small-posv-{mk}", fn=api.batched("posv", impl="pallas"),
            args=(a_sq, b_sq), flops_audited=False,
        ),
        ProgramTarget(
            name=f"small-posv-split-{mk}",
            fn=api.batched("posv", impl="pallas_split"),
            args=(a_sq, b_sq), flops_audited=False,
        ),
        ProgramTarget(
            name=f"small-lstsq-{mk}-m{rows}",
            fn=api.batched("lstsq", impl="pallas"),
            args=(a_tall, b_tall), flops_audited=False,
        ),
    ]


def blocktri_target(
    nblocks: int = 4, b: int = 16, nrhs: int = 2, capacity: int = 4,
    dtype=jnp.float32,
) -> ProgramTarget:
    """The serve posv_blocktri bucket program (models/blocktri through
    api.batched, the executable engine._get_batched compiles): one fused
    factor+forward scan under ``BT::factor`` feeding the backward sweep
    under ``BT::solve`` — both phase tags under the phase-coverage rule,
    and the scan-carried pallas steps under cache-key hygiene.  Forced
    impl='pallas' so the lint sees the kernel route serve routes on TPU
    regardless of the CPU rig's default_impl answer.  ``flops_audited=
    False``: the chain flops execute inside interpreted ``pallas_call``
    scan bodies on the CPU rig, invisible to XLA ``cost_analysis`` (same
    reasoning as batched_small_targets).  No donation — the engine
    donates nothing for posv_blocktri (the packed (2, nblocks, b, b)
    operand can't alias the (nblocks, b, nrhs) solution shape-wise, and
    the RHS aliasing lives inside the kernels)."""
    from capital_tpu.serve import api

    dt = jnp.dtype(dtype)
    a_sds = jax.ShapeDtypeStruct((capacity, 2, nblocks, b, b), dt)
    b_sds = jax.ShapeDtypeStruct((capacity, nblocks, b, nrhs), dt)
    return ProgramTarget(
        name=f"serve-blocktri-b{capacity}-nb{nblocks}-bs{b}",
        fn=api.batched("posv_blocktri", impl="pallas"),
        args=(a_sds, b_sds), flops_audited=False,
    )


def blocktri_partitioned_target(
    nblocks: int = 8, b: int = 8, nrhs: int = 2, capacity: int = 2,
    partitions: int = 2, dtype=jnp.float32,
) -> ProgramTarget:
    """The partitioned-bucket serve posv_blocktri program (ServeConfig.
    blocktri_impl='partitioned' through api.batched — the executable an
    engine configured for the Spike driver compiles): the concurrent
    interior factor+widened solve and the parallel back-substitution
    under ``BT::partition``, the interface Schur assembly + reduced
    P-block chain under ``BT::reduce`` — both new phase tags under the
    phase-coverage rule, alongside the sequential target's ``BT::factor``
    / ``BT::solve`` which the reduced chain still emits.  Forced
    impl='pallas' so the widened interior scans ride the kernel route
    serve routes on TPU (partition_inner maps from the kernel flavor);
    ``flops_audited=False`` for the same interpret-rig reason as
    blocktri_target.  No donation (same shape argument)."""
    from capital_tpu.serve import api

    dt = jnp.dtype(dtype)
    a_sds = jax.ShapeDtypeStruct((capacity, 2, nblocks, b, b), dt)
    b_sds = jax.ShapeDtypeStruct((capacity, nblocks, b, nrhs), dt)
    return ProgramTarget(
        name=(f"serve-blocktri-par-b{capacity}-nb{nblocks}-bs{b}"
              f"-p{partitions}"),
        fn=api.batched("posv_blocktri", impl="pallas",
                       blocktri_impl="partitioned",
                       blocktri_partitions=partitions),
        args=(a_sds, b_sds), flops_audited=False,
    )


def arrowhead_target(
    nblocks: int = 4, b: int = 16, s: int = 4, nrhs: int = 2,
    capacity: int = 4, dtype=jnp.float32,
) -> ProgramTarget:
    """The serve posv_arrowhead bucket program (models/arrowhead through
    api.batched, the executable engine._get_batched compiles): the
    widened chain solve rides blocktri's ``BT::factor`` / ``BT::solve``
    scans unchanged, the Schur completion + corner factor lands under
    ``AH::schur`` and the border back-substitution under ``AH::border``
    — all four phase tags under the phase-coverage rule, and the packed
    operand unpack under cache-key hygiene (geometry comes from static
    shapes, never from traced values).  Forced impl='pallas' so the
    chain scans ride the kernel route serve routes on TPU regardless of
    the CPU rig's default_impl answer.  ``flops_audited=False``: the
    chain half executes inside interpreted ``pallas_call`` scan bodies
    on the CPU rig, invisible to XLA ``cost_analysis`` — the AH::*
    einsums alone would always undershoot the whole-program envelope
    (same reasoning as blocktri_target).  No donation — the engine
    donates nothing for posv_arrowhead: the packed (n_T + s, s + nrhs)
    tail feeds BOTH solve outputs (chain X and corner Xs), so neither
    output can safely alias it."""
    from capital_tpu.serve import api

    dt = jnp.dtype(dtype)
    a_sds = jax.ShapeDtypeStruct((capacity, 2, nblocks, b, b), dt)
    b_sds = jax.ShapeDtypeStruct((capacity, nblocks * b + s, s + nrhs), dt)
    return ProgramTarget(
        name=f"serve-arrowhead-b{capacity}-nb{nblocks}-bs{b}-s{s}",
        fn=api.batched("posv_arrowhead", impl="pallas"),
        args=(a_sds, b_sds), flops_audited=False,
    )


def update_small_target(
    n: int = 64, k: int = 4, capacity: int = 8, dtype=jnp.float32,
) -> ProgramTarget:
    """The online factor-maintenance bucket program (ops/update_small
    through api.batched, the executables serve/engine compiles for
    chol_update / chol_downdate traffic): one rank-k update under
    ``UP::update`` chained into the downdate back under ``UP::downdate``
    — both phase tags under the phase-coverage rule, and the masked
    hyperbolic-rotation sweep's pallas_call under cache-key hygiene.
    Forced impl='pallas' (n=64 is inside the small-N envelope) so the
    lint sees the kernel route serve routes on TPU regardless of the CPU
    rig's resolution.  ``flops_audited=False``: the sweep flops execute
    inside the interpreted ``pallas_call`` on the CPU rig, invisible to
    XLA ``cost_analysis`` (same reasoning as batched_small_targets).  No
    jit-level donation for the same interpret-rig reason — the engine's
    donate_argnums=(0,) on the R operand is honored only by the compiled
    TPU route."""
    from capital_tpu.serve import api

    dt = jnp.dtype(dtype)
    r_sds = jax.ShapeDtypeStruct((capacity, n, n), dt)
    v_sds = jax.ShapeDtypeStruct((capacity, n, k), dt)
    up = api.batched("chol_update", impl="pallas")
    dn = api.batched("chol_downdate", impl="pallas")

    def step(r, v):
        R1, i1 = up(r, v)
        R2, i2 = dn(R1, v)
        return R2, jnp.maximum(i1, i2)

    return ProgramTarget(
        name=f"update-small-b{capacity}-n{n}-k{k}", fn=step,
        args=(r_sds, v_sds), flops_audited=False,
    )


def refine_target(
    n: int = 64, nrhs: int = 4, capacity: int = 4, dtype=jnp.bfloat16,
) -> ProgramTarget:
    """The accuracy_tier='guaranteed' bucket program (robust/refine through
    api.batched — the 5-output executable serve/engine compiles for tiered
    posv traffic): low-dtype factor + upgraded-dtype correction sweeps
    under ``IR::residual`` / ``IR::correct`` — both phase tags under the
    phase-coverage rule.

    bf16 inputs on purpose: the guaranteed plan for bf16 factors in bf16
    and corrects in f32, so the WHOLE mixed-precision ladder stays below
    f64 — a program whose jaxpr emits zero float64 equations, which is
    exactly what rule_dtype_drift then proves (the rule exempts programs
    with wide INPUTS, so a narrow-input tier program is the only shape
    that makes the no-f64-leak claim checkable).  ``flops_audited=False``:
    the refinement loop's sweep count is data-dependent (lax.while_loop),
    while the phase registry prices exactly one sweep — the whole-program
    flops envelope would flag the design, not a bug (measured sweep counts
    live in serve stats' refine block instead).  No donation — the tiered
    program keeps both operands live across every sweep's residual."""
    from capital_tpu.serve import api

    dt = jnp.dtype(dtype)
    a_sds = jax.ShapeDtypeStruct((capacity, n, n), dt)
    b_sds = jax.ShapeDtypeStruct((capacity, n, nrhs), dt)

    solve = api.batched("posv", tier="guaranteed")

    def step(a, b):
        X, iters, converged, resid, info = solve(a, b)
        return X, iters, converged, resid, info

    return ProgramTarget(
        name=f"refine-posv-b{capacity}-n{n}", fn=step,
        args=(a_sds, b_sds), flops_audited=False,
    )


def session_targets(
    nblocks: int = 4, b: int = 16, nrhs: int = 2, capacity: int = 4,
    dtype=jnp.float32,
) -> list[ProgramTarget]:
    """The streaming-session bucket programs (serve/sessions protocol
    through api.batched — the executables engine._submit_session routes
    to; docs/SERVING.md 'Streaming sessions'): the shared open/append
    chain-extension program under ``SS::extend`` and the resident-factor
    sweep program under ``SS::solve`` — both phase tags under the
    phase-coverage rule.  Cache-key hygiene is the protocol's load-
    bearing claim: session ids resolve to resident factors HOST-side, so
    the programs see only bucket-shaped arrays — the 4-stack
    (capacity, 4, nblocks, b, b) = [D; C; L; Wt] solve packing and the
    (capacity, 2, nblocks, b, b) extend packing — and session churn can
    never recompile anything.  Forced impl='pallas' so the interior
    chain scans ride the kernel route serve routes on TPU;
    ``flops_audited=False`` for the same interpret-rig reason as
    blocktri_target.  No donation — the engine's no-donate rule for
    session ops: the landed (L, Wt) stack is concatenated onto the
    RESIDENT chain at the sink, so the operand must survive dispatch."""
    from capital_tpu.serve import api

    dt = jnp.dtype(dtype)
    a2_sds = jax.ShapeDtypeStruct((capacity, 2, nblocks, b, b), dt)
    carry_sds = jax.ShapeDtypeStruct((capacity, b, b), dt)
    a4_sds = jax.ShapeDtypeStruct((capacity, 4, nblocks, b, b), dt)
    b_sds = jax.ShapeDtypeStruct((capacity, nblocks, b, nrhs), dt)
    mk = f"b{capacity}-nb{nblocks}-bs{b}"
    return [
        ProgramTarget(
            name=f"serve-session-extend-{mk}",
            fn=api.batched("session_extend", impl="pallas"),
            args=(a2_sds, carry_sds), flops_audited=False,
        ),
        ProgramTarget(
            name=f"serve-session-solve-{mk}",
            fn=api.batched("session_solve", impl="pallas"),
            args=(a4_sds, b_sds), flops_audited=False,
        ),
    ]


def cholinv_fused_target(n: int = 512, dtype=jnp.float32) -> ProgramTarget:
    """The fused-recursion-tail cholinv program (CholinvConfig.
    tail_fuse_depth > 0): n=512 with bc=128 and depth 2 fuses the whole
    tree into ops/pallas_tpu.fused_tail, putting the ``CI::tail_fused``
    phase tag under the phase-coverage rule and the fused pallas_call's
    windowed-output aliasing under cache-key hygiene.  ``flops_audited=
    False`` because the fused factor+solve sweeps execute inside the
    interpreted ``pallas_call`` on the CPU lint rig, invisible to XLA
    ``cost_analysis`` (same reasoning as batched_small_targets)."""
    from capital_tpu.bench import drivers
    from capital_tpu.models import cholesky

    grid = _grid()
    cfg = cholesky.CholinvConfig(
        base_case_dim=128, mode="pallas", tail_fuse_depth=2,
    )
    A = drivers._spd(n, dtype)

    def step(a):
        R, Rinv = cholesky.factor(grid, a, cfg)
        return R + Rinv

    return ProgramTarget(
        name=f"cholinv-fused-n{n}", fn=step, args=(A,), flops_audited=False,
    )


def serve_sched_target(
    n: int = 64, nrhs: int = 4, capacity: int = 4, dtype=jnp.bfloat16,
) -> ProgramTarget:
    """The continuous scheduler's staged-dispatch program (serve/scheduler
    + executor; docs/SERVING.md): operand normalization under ``SV::stage``
    — the in-program half of the host->device staging the engine performs
    at submit — feeding one batched bucket dispatch under ``SV::dispatch``,
    the boundary the queue-wait/device latency split is measured across.

    bf16 inputs upcast to f32 at the stage boundary (a real convert
    equation, so the SV::stage tag survives into the jaxpr/HLO name
    stacks the sanitizer and xla_audit attribute by); n=64 keeps the
    dispatch on the batched-grid pallas route, so ``flops_audited=False``
    and no jit-level donation for the same interpret-rig reasons as the
    batched_small targets."""
    from capital_tpu.serve import api
    from capital_tpu.utils import tracing

    dt = jnp.dtype(dtype)
    a_sds = jax.ShapeDtypeStruct((capacity, n, n), dt)
    b_sds = jax.ShapeDtypeStruct((capacity, n, nrhs), dt)
    solve = api.batched("posv")

    def step(a, b):
        with tracing.scope("SV::stage"):
            a32 = a.astype(jnp.float32)
            b32 = b.astype(jnp.float32)
            # the identity-tail symmetrization pad_operands applies on the
            # host, in-program form: keeps the staged operand SPD under
            # the bf16 round-trip
            a32 = 0.5 * (a32 + jnp.swapaxes(a32, -1, -2))
        with tracing.scope("SV::dispatch"):
            X, info = solve(a32, b32)
        return X.astype(dt), info

    return ProgramTarget(
        name=f"serve-sched-posv-b{capacity}-n{n}", fn=step,
        args=(a_sds, b_sds), flops_audited=False,
    )


def serve_traced_target(
    n: int = 64, nrhs: int = 4, capacity: int = 4, dtype=jnp.float32,
) -> ProgramTarget:
    """The traced serve dispatch program: the serve_sched stage/dispatch
    pair with the per-request span stamping the engine performs around it
    (obs/spans.RequestTrace.extend) executed inline, exactly where the
    serve path stamps — before staging, at executable resolution, at
    dispatch issue.

    The property this target pins is the tracing tentpole's core claim:
    span stamps are a pure HOST-side observer.  They run at trace time,
    never become program equations, and above all never become host
    callbacks — ``rule_no_host_sync`` proves the traced program carries
    zero ``pure_callback``/``io_callback``/infeed primitives, because a
    span stamp that leaked into the program as a callback would serialize
    the very device stream it claims to observe.  The stamps must also
    not break phase coverage: ``SV::stage`` / ``SV::dispatch`` still name
    every flop.  ``flops_audited=False`` and no donation for the same
    interpret-rig reasons as serve_sched_target."""
    import time

    from capital_tpu.obs import spans
    from capital_tpu.serve import api
    from capital_tpu.utils import tracing

    dt = jnp.dtype(dtype)
    a_sds = jax.ShapeDtypeStruct((capacity, n, n), dt)
    b_sds = jax.ShapeDtypeStruct((capacity, n, nrhs), dt)
    solve = api.batched("posv")
    log = spans.TraceLog()

    def step(a, b):
        tr = log.start(0, "posv", time.monotonic())
        with tracing.scope("SV::stage"):
            # pad_operands' identity-tail symmetrization, in-program form
            a_sym = 0.5 * (a + jnp.swapaxes(a, -1, -2))
        tr.extend("admit")
        tr.extend("cache_lookup")
        with tracing.scope("SV::dispatch"):
            X, info = solve(a_sym, b)
        tr.extend("batch_form")
        return X, info

    return ProgramTarget(
        name=f"serve-traced-posv-b{capacity}-n{n}", fn=step,
        args=(a_sds, b_sds), flops_audited=False,
    )


def flagship_targets(names=None) -> list[ProgramTarget]:
    """The `make lint` program-pass set.  `names` filters to a subset of
    TARGET_NAMES (all three families by default)."""
    names = tuple(names) if names else TARGET_NAMES
    out: list[ProgramTarget] = []
    for name in names:
        if name == "cholinv":
            out.append(cholinv_target())
        elif name == "cacqr":
            out.append(cacqr_target())
        elif name == "serve":
            out.extend(serve_bucket_targets())
        elif name == "batched_small":
            out.extend(batched_small_targets())
        elif name == "serve_sched":
            out.append(serve_sched_target())
        elif name == "serve_traced":
            out.append(serve_traced_target())
        elif name == "cholinv_fused":
            out.append(cholinv_fused_target())
        elif name == "blocktri":
            out.append(blocktri_target())
        elif name == "blocktri_partitioned":
            out.append(blocktri_partitioned_target())
        elif name == "arrowhead":
            out.append(arrowhead_target())
        elif name == "update_small":
            out.append(update_small_target())
        elif name == "refine":
            out.append(refine_target())
        elif name == "session":
            out.extend(session_targets())
        else:
            raise ValueError(
                f"unknown lint target {name!r}; expected one of {TARGET_NAMES}"
            )
    return out
