"""The concurrency sanitizer, dynamic layer: a deterministic
interleaving explorer for the serve host plane.

The static layer (lint/concurrency.py) proves the LEXICAL discipline —
guarded attributes touched under their lock, no lock-order cycles — but
a lexically clean plane can still break its ledger identities under an
unlucky interleaving (a result landing between a kill and its sweep, an
eviction between a peek and a put).  This module makes those
interleavings a *search space* instead of a roll of the dice:

* a **cooperative scheduler** runs each scripted scenario's threads one
  at a time, choosing who proceeds at every yield point from a seeded
  RNG — so a schedule is a replayable list of thread names, not an OS
  accident;
* ``patched()`` swaps ``threading.Lock/RLock/Event`` for cooperative
  twins while a scenario is built and run, so the REAL production
  classes (Router and friends) hit yield points at exactly their real
  synchronization points — no test doubles of the code under test;
* after every step, when no cooperative lock is held, the scenario's
  probe exports the same stats blocks production emits and the formal
  registry (lint/invariants.py) checks every identity — an invariant
  that only holds at quiescence but breaks mid-schedule is precisely
  the bug class this layer exists to catch;
* a violation aborts the run and greedily **shrinks** the recorded
  schedule (fewer context switches, same violation) into the minimal
  failing trace the report prints — the repro a human can read.

Determinism contract: same scenario + same seed -> same choices -> same
trace (tests pin this).  Scenario code must therefore avoid control flow
on wall-clock time; the four shipped scenarios disable the router
heartbeat (``ping_interval_s=0``) for exactly this reason.

Semantics notes (documented, deliberate):

* an **unregistered** thread (the scheduler itself, running a probe)
  takes free cooperative locks silently and never yields — probes run
  only at lock-quiescent points, so the lock is always free;
* a timed ``Event.wait`` fires its timeout only under *starvation* (no
  other thread runnable) — a sound under-approximation that keeps
  schedules productive instead of spuriously timing out;
* all live threads blocked with no timed waiter = **deadlock**, reported
  as a violation with the trace that got there.

Host-only module: pure stdlib + numpy (the scripted replica moves no
device data); imports serve/ lazily inside the scenario builders so the
static pass can lint this file like any other.
"""

from __future__ import annotations

import _thread
import contextlib
import dataclasses
import random
import threading
from typing import Callable, Optional

from capital_tpu.lint import invariants, rules

INTERLEAVING = "interleaving-violation"

#: Captured at import: the real classes, immune to patched().
_REAL_THREAD = threading.Thread
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_EVENT = threading.Event

_MAX_STEPS = 5000


class _Abort(BaseException):
    """Raised inside scenario threads to unwind them at teardown; a
    BaseException so scenario code's ``except Exception`` can't eat it."""


@dataclasses.dataclass
class Violation:
    kind: str          # invariant | deadlock | scenario-check |
    #                    thread-exception | overrun
    messages: list
    step: int


@dataclasses.dataclass
class ScheduleResult:
    """One run: the schedule taken and what it found."""

    scenario: str
    seed: int
    choices: list      # thread name chosen at each step
    trace: list        # (step, thread, reason)
    violation: Optional[Violation]

    def render_trace(self) -> str:
        lines = [f"  step {s:3d}: {t:<12s} {r}" for s, t, r in self.trace]
        return "\n".join(lines)


class CoopThread:
    """One scripted thread under the scheduler: a real OS thread that
    only ever runs between a gate release and its next yield."""

    def __init__(self, sched: "CoopScheduler", name: str, fn: Callable):
        self.sched = sched
        self.name = name
        self.fn = fn
        self.gate = _thread.allocate_lock()
        self.gate.acquire()
        self.state = "ready"            # ready | blocked | finished
        self.blocked_on = None          # ("lock", lock) | ("event", ev, timeout)
        self.timed_out = False          # scheduler fired a starvation timeout
        self.error: Optional[BaseException] = None
        self.thread = _REAL_THREAD(target=self._main, daemon=True,
                                   name=f"coop-{name}")

    def _main(self):
        self.sched._by_ident[threading.get_ident()] = self
        self.gate.acquire()             # wait to be scheduled the first time
        try:
            if self.sched._aborting:
                raise _Abort()
            self.fn()
        except _Abort:
            pass
        except BaseException as e:      # lint: allow-broad-except — reported as a violation
            self.error = e
        finally:
            self.state = "finished"
            self.sched._gate.release()  # hand control back for good


class CoopScheduler:
    """The one-runnable-thread-at-a-time scheduler.  Every context
    switch is a (step, thread, reason) trace entry; the chosen thread
    names are the schedule, replayable via ``forced``."""

    def __init__(self, seed: int = 0, forced: Optional[list] = None):
        self.rng = random.Random(seed)
        self.forced = list(forced) if forced else []
        self.threads: list[CoopThread] = []
        self._by_ident: dict[int, CoopThread] = {}
        self._gate = _thread.allocate_lock()
        self._gate.acquire()
        self._aborting = False
        self._lock_seq = 0
        self.locks: list = []           # every coop lock built under patched()
        self.trace: list = []
        self.choices: list = []
        self.step = 0

    # ---- thread-side API ---------------------------------------------------

    def current(self) -> Optional[CoopThread]:
        return self._by_ident.get(threading.get_ident())

    def yield_point(self, reason: str = "yield") -> None:
        """Hand control to the scheduler; returns when re-scheduled.
        No-op from unregistered threads (probes never yield)."""
        t = self.current()
        if t is None:
            return
        t.blocked_on = ("yield", reason)
        self._switch(t)

    def _switch(self, t: CoopThread) -> None:
        self._gate.release()
        t.gate.acquire()
        if self._aborting:
            raise _Abort()

    def block_on_lock(self, t: CoopThread, lock) -> None:
        t.state = "blocked"
        t.blocked_on = ("lock", lock)
        self._switch(t)

    def wait_event(self, ev: "CoopEvent", timeout: Optional[float]) -> bool:
        t = self.current()
        if t is None:                   # unregistered: real (raw-lock) wait
            return ev._raw_wait(timeout)
        while not ev._flag:
            t.state = "blocked"
            t.blocked_on = ("event", ev, timeout)
            self._switch(t)
            if t.timed_out:
                t.timed_out = False
                return False
        return True

    # ---- scheduler loop ----------------------------------------------------

    def _runnable(self, t: CoopThread) -> bool:
        if t.state == "finished":
            return False
        if t.state == "ready":
            return True
        kind = t.blocked_on[0]
        if kind == "lock":
            return t.blocked_on[1]._free_for(t)
        if kind == "event":
            return t.blocked_on[1]._flag
        return True

    def _reason(self, t: CoopThread) -> str:
        if t.blocked_on is None:
            return "start"
        kind = t.blocked_on[0]
        if kind == "yield":
            return t.blocked_on[1]
        if kind == "lock":
            return f"acquire {t.blocked_on[1].name}"
        if kind == "event":
            return f"event-wait {'set' if t.blocked_on[1]._flag else 'wake'}"
        return kind

    def run(self, ctx: "ScenarioCtx", max_steps: int = _MAX_STEPS
            ) -> Optional[Violation]:
        for name, fn in ctx.threads:
            self.threads.append(CoopThread(self, name, fn))
        for t in self.threads:
            t.thread.start()
        violation: Optional[Violation] = None
        try:
            while True:
                live = [t for t in self.threads if t.state != "finished"]
                if not live:
                    break
                runnable = [t for t in live if self._runnable(t)]
                if not runnable:
                    timed = sorted(
                        (t for t in live if t.blocked_on
                         and t.blocked_on[0] == "event"
                         and t.blocked_on[2] is not None),
                        key=lambda t: t.name)
                    if timed:           # starvation: fire one timeout
                        timed[0].timed_out = True
                        runnable = [timed[0]]
                    else:
                        violation = Violation("deadlock", [
                            "all live threads blocked: " + ", ".join(
                                f"{t.name} on {self._reason(t)}"
                                for t in sorted(live, key=lambda x: x.name))
                        ], self.step)
                        break
                if self.step < len(self.forced):
                    chosen = next(
                        (t for t in runnable
                         if t.name == self.forced[self.step]), None)
                    if chosen is None:
                        chosen = sorted(runnable, key=lambda t: t.name)[0]
                else:
                    chosen = self.rng.choice(
                        sorted(runnable, key=lambda t: t.name))
                self.choices.append(chosen.name)
                self.trace.append(
                    (self.step, chosen.name, self._reason(chosen)))
                self.step += 1
                chosen.state = "ready"
                chosen.gate.release()
                self._gate.acquire()    # thread yielded, blocked or finished
                violation = self._check(ctx)
                if violation is not None:
                    break
                if self.step >= max_steps:
                    violation = Violation("overrun", [
                        f"schedule exceeded {max_steps} steps — a scenario "
                        "thread is not making progress"], self.step)
                    break
        finally:
            self._teardown()
        if violation is None:
            violation = self._thread_errors()
            if violation is None and ctx.finish is not None:
                msgs = ctx.finish()
                if msgs:
                    violation = Violation("scenario-check", list(msgs),
                                          self.step)
        return violation

    def _check(self, ctx: "ScenarioCtx") -> Optional[Violation]:
        v = self._thread_errors()
        if v is not None:
            return v
        quiescent = all(lk._owner is None for lk in self.locks)
        if quiescent and ctx.probe is not None:
            msgs = invariants.check(ctx.probe())
            if msgs:
                return Violation("invariant", msgs, self.step)
        if quiescent and ctx.check is not None:
            msgs = ctx.check()
            if msgs:
                return Violation("scenario-check", list(msgs), self.step)
        return None

    def _thread_errors(self) -> Optional[Violation]:
        errs = [t for t in self.threads if t.error is not None]
        if errs:
            return Violation("thread-exception", [
                f"{t.name}: {t.error!r}" for t in errs], self.step)
        return None

    def _teardown(self) -> None:
        """Unwind every live thread: each raises _Abort at its next wake
        and finishes (finally blocks still run — lock release is lenient
        during abort)."""
        self._aborting = True
        for _ in range(len(self.threads) * 4):
            live = [t for t in self.threads if t.state != "finished"]
            if not live:
                break
            t = live[0]
            t.gate.release()
            self._gate.acquire()
        for t in self.threads:
            t.thread.join(timeout=5.0)

    # ---- patched primitives ------------------------------------------------

    def _new_lock(self, reentrant: bool) -> "CoopLock":
        self._lock_seq += 1
        lk = CoopLock(self, f"{'rlock' if reentrant else 'lock'}"
                      f"#{self._lock_seq}", reentrant)
        self.locks.append(lk)
        return lk

    @contextlib.contextmanager
    def patched(self):
        """Swap threading.Lock/RLock/Event for cooperative twins bound
        to this scheduler, for the duration of one scenario build+run.
        Process-global by nature — run one scenario at a time."""
        saved = (threading.Lock, threading.RLock, threading.Event)
        threading.Lock = lambda: self._new_lock(False)
        threading.RLock = lambda: self._new_lock(True)
        threading.Event = lambda: CoopEvent(self)
        try:
            yield self
        finally:
            threading.Lock, threading.RLock, threading.Event = saved


class CoopLock:
    """Cooperative Lock/RLock.  Acquisition yields once (the exploration
    point) and then blocks cooperatively until free; release never
    yields, so finally-block unwinding can't deadlock the scheduler."""

    def __init__(self, sched: CoopScheduler, name: str, reentrant: bool):
        self.sched = sched
        self.name = name
        self.reentrant = reentrant
        self._owner = None              # CoopThread | "external"
        self._count = 0

    def _free_for(self, t) -> bool:
        return self._owner is None or (self.reentrant and self._owner is t)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t = self.sched.current()
        if t is None:
            # unregistered thread (probe): locks are free at quiescence,
            # and probes may re-enter (counters() -> replica_ids())
            if self._owner == "external" and self.reentrant:
                self._count += 1
                return True
            if self._owner is not None:
                raise RuntimeError(
                    f"unregistered thread acquiring held coop lock "
                    f"{self.name} (probe outside quiescence?)")
            self._owner, self._count = "external", 1
            return True
        if self.reentrant and self._owner is t:
            self._count += 1
            return True
        self.sched.yield_point(f"acquire {self.name}")
        while not self._free_for(t):
            if not blocking:
                return False
            self.sched.block_on_lock(t, self)
        self._owner, self._count = t, 1
        return True

    def release(self) -> None:
        t = self.sched.current()
        if self._owner is None:
            if self.sched._aborting:
                return
            raise RuntimeError(f"release of unheld coop lock {self.name}")
        if t is not None and self._owner is not t \
                and self._owner != "external" and not self.sched._aborting:
            raise RuntimeError(
                f"{t.name} releasing coop lock {self.name} owned by "
                f"{getattr(self._owner, 'name', self._owner)}")
        self._count -= 1
        if self._count <= 0:
            self._owner, self._count = None, 0

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self) -> bool:
        return self._owner is not None


class CoopEvent:
    """Cooperative Event.  set()/is_set() never yield; wait() from a
    scripted thread blocks cooperatively (timeouts fire only under
    starvation — see module docstring); wait() from an unregistered
    thread falls back to a raw-lock wait so threading.Thread's own
    _started handshake keeps working under patched()."""

    def __init__(self, sched: CoopScheduler):
        self.sched = sched
        self._flag = False
        self._raw = _thread.allocate_lock()
        self._raw.acquire()

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        self._flag = True
        if self._raw.locked():
            self._raw.release()

    def clear(self) -> None:
        self._flag = False
        self._raw.acquire(False)

    def _raw_wait(self, timeout: Optional[float]) -> bool:
        if self._flag:
            return True
        got = self._raw.acquire(True, -1 if timeout is None else timeout)
        if got:
            self._raw.release()
        return self._flag

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.sched.wait_event(self, timeout)


# ---- scenarios -------------------------------------------------------------


class ScenarioCtx:
    """What a builder hands the scheduler: the scripted threads, the
    per-step invariant probe (subject -> exported stats block), an
    optional per-step custom check, and an optional end-of-run check."""

    def __init__(self, threads, probe=None, check=None, finish=None):
        self.threads = list(threads)    # [(name, fn)]
        self.probe = probe              # () -> {subject: block}
        self.check = check              # () -> [violation str]
        self.finish = finish            # () -> [violation str]


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    description: str
    build: Callable[[CoopScheduler], ScenarioCtx]


class ScriptedReplica:
    """A host-only replica fake speaking the EngineReplica transport
    protocol the Router drives (submit/poll/drain/ladders/kill/...).
    Requests sit in an inbox until ``service()`` moves them to the
    outbox as ok results — the in-flight window every router race needs.
    Used only by the explorer scenarios; production code never sees it."""

    def __init__(self, replica_id: str, auto: bool = False):
        self.replica_id = replica_id
        self.fatal = None
        self.auto = auto                # answer at submit time
        self._killed = False
        self._inbox: list = []
        self._outbox: list = []
        self._pings = 0

    def alive(self) -> bool:
        return not self._killed

    def start(self):
        return self

    def ladders(self) -> dict:
        return {"buckets": [4, 8], "nrhs_buckets": [1, 4],
                "rows_buckets": [4, 8]}

    def submit(self, request_id: int, op: str, A, B=None, *,
               tier: str = "balanced", deadline_ms=None) -> None:
        if self._killed:
            raise OSError(f"replica {self.replica_id} is dead")
        self._inbox.append((request_id, op))
        if self.auto:
            self.service()

    def service(self, n: Optional[int] = None) -> int:
        """Move up to `n` pending requests (all, by default) to the
        outbox as ok results."""
        if self._killed:
            return 0
        moved = 0
        while self._inbox and (n is None or moved < n):
            rid, op = self._inbox.pop(0)
            self._outbox.append(("result", rid, {
                "request_id": rid, "op": op, "ok": True, "x": 0.0,
                "info": 0, "error": None, "bucket": None, "batched": False,
                "latency_s": 0.0,
            }))
            moved += 1
        return moved

    def poll(self) -> list:
        out, self._outbox = self._outbox, []
        return out

    def drain(self, timeout=None) -> bool:
        if self._killed:
            return False
        self.service()
        return True

    def warmup(self, specs, timeout=None) -> dict:
        return {"fresh": 0}

    def request_stats(self, timeout=None):
        return None

    def stop(self, timeout=None) -> bool:
        self._killed = True
        return True

    def kill(self) -> None:
        # in-flight inbox work is lost (never answered); the outbox —
        # results that raced the crash — survives for the final sweep
        self._killed = True

    def ping_async(self) -> int:
        if self._killed:
            raise OSError(f"replica {self.replica_id} is dead")
        self._pings += 1
        return self._pings


def _router(policy: str = "least_loaded"):
    from capital_tpu.serve.router import Router, RouterConfig

    # heartbeat off: its branches key on wall-clock time, which would
    # break the same-seed-same-trace determinism contract
    return Router(RouterConfig(policy=policy, ping_interval_s=0.0))


def _build_submit_vs_pump(sched: CoopScheduler) -> ScenarioCtx:
    """Clients submitting while the pump reaps: the no-drop identity
    must hold at every step, not just after the dust settles."""
    router = _router()
    reps = [ScriptedReplica("r0"), ScriptedReplica("r1")]
    for r in reps:
        router.add_replica(r)
    done = {"flag": False}
    tickets: list = []

    def client():
        for i in range(3):
            tickets.append(router.submit("posv", [[float(i + 2)]], [[1.0]]))
            sched.yield_point(f"submitted #{i}")
        for t in tickets:
            if not t.result(timeout=5.0).ok:
                raise AssertionError("scripted replica answered not-ok")
        done["flag"] = True

    def server():
        for _ in range(60):
            if done["flag"]:
                return
            for r in reps:
                r.service(n=1)
            sched.yield_point("serviced")

    def pump():
        for _ in range(60):
            if done["flag"]:
                return
            router.pump()
            sched.yield_point("pumped")

    def finish():
        missing = [t.request_id for t in tickets if t.response is None]
        return ([f"tickets never landed: {missing}"] if missing else [])

    return ScenarioCtx(
        threads=[("client", client), ("server", server), ("pump", pump)],
        probe=lambda: {invariants.ROUTER: router.counters()},
        finish=finish)


def _build_kill_vs_landing(sched: CoopScheduler) -> ScenarioCtx:
    """A replica kill racing its own landing result: whichever side wins
    each schedule, the ticket must land exactly once (first-result-wins;
    re-dispatch covers the loss) and no-drop must hold throughout."""
    router = _router()
    r0, r1 = ScriptedReplica("r0"), ScriptedReplica("r1")
    router.add_replica(r0)
    router.add_replica(r1)
    done = {"flag": False}
    tickets: list = []

    def client():
        # least_loaded ties break on replica id, so this lands on r0
        tickets.append(router.submit("posv", [[4.0]], [[1.0]]))
        sched.yield_point("submitted")
        if not tickets[0].result(timeout=5.0).ok:
            raise AssertionError("scripted replica answered not-ok")
        done["flag"] = True

    def server():
        for _ in range(60):
            if done["flag"]:
                return
            r0.service()
            r1.service()
            sched.yield_point("serviced")

    def killer():
        sched.yield_point("about to kill r0")
        router.kill_replica("r0")

    def pump():
        for _ in range(60):
            if done["flag"]:
                return
            router.pump()
            sched.yield_point("pumped")

    def finish():
        out = []
        if not tickets or tickets[0].response is None:
            out.append("the killed request never landed (dropped)")
        c = router.counters()
        if c["completed"] != 1:
            out.append(f"completed={c['completed']} != 1 "
                       "(first-result-wins broken)")
        return out

    return ScenarioCtx(
        threads=[("client", client), ("killer", killer),
                 ("server", server), ("pump", pump)],
        probe=lambda: {invariants.ROUTER: router.counters()},
        finish=finish)


def _build_evict_vs_append(sched: CoopScheduler) -> ScenarioCtx:
    """A session append landing while the FactorCache evicts under byte
    pressure — the exact window SolveEngine._session_extend_sink guards
    (peek, then concatenate, then put).  The scripted landing follows
    the engine's fixed contract: a mid-flight eviction must surface as a
    LOUD SessionEvicted, never a silently truncated re-install."""
    import numpy as np

    from capital_tpu.serve.factorcache import FactorCache

    blk = np.zeros((1, 8, 8), dtype=np.float32)   # 256 B per block
    cache = FactorCache(budget_bytes=3 * blk.nbytes)
    cache.put("sess", "session", (blk, blk), {"nblocks": 1})
    outcome: dict = {}

    def landing():
        ent = cache.peek("sess")
        sched.yield_point("peeked resident chain")
        if ent is None:
            if cache.evicted("sess"):
                outcome["result"] = "SessionEvicted: chain evicted mid-flight"
            else:
                outcome["result"] = "BUG: no entry and no tombstone"
            return
        L = np.concatenate([ent.arrays[0], blk], axis=0)
        sched.yield_point("concatenated suffix")
        cache.put("sess", "session", (L, L), {"nblocks": int(L.shape[0])})
        outcome["result"] = "installed"
        outcome["nblocks"] = int(L.shape[0])

    def evictor():
        big = np.zeros((2, 8, 8), dtype=np.float32)
        cache.put("other-a", "dense", (big,), {})
        sched.yield_point("installed other-a")
        cache.put("other-b", "dense", (big,), {})

    def finish():
        res = outcome.get("result")
        if res is None:
            return ["landing thread recorded no outcome"]
        if res.startswith("BUG"):
            return [res]
        if res == "installed" and outcome.get("nblocks") != 2:
            return [f"installed a truncated chain: nblocks="
                    f"{outcome.get('nblocks')} != 2"]
        return []

    return ScenarioCtx(
        threads=[("landing", landing), ("evictor", evictor)],
        probe=lambda: {invariants.FACTOR_CACHE: cache.stats()},
        finish=finish)


def _build_drain_vs_submit(sched: CoopScheduler) -> ScenarioCtx:
    """drain_replica racing submit on a single-replica router: every
    submit either admits (and must land) or is refused loudly by
    admission control — never queued into a draining replica silently."""
    router = _router()
    r0 = ScriptedReplica("r0", auto=True)
    router.add_replica(r0)
    done = {"flag": False}
    accepted: list = []
    rejected = {"n": 0}

    def ops():
        router.drain_replica("r0", timeout=5.0)
        sched.yield_point("drained r0")
        router.resume_replica("r0")

    def client():
        for i in range(2):
            try:
                accepted.append(
                    router.submit("posv", [[float(i + 2)]], [[1.0]]))
            except RuntimeError:
                rejected["n"] += 1      # admission control said no — fine
            sched.yield_point(f"attempt #{i}")
        for t in accepted:
            t.result(timeout=5.0)
        done["flag"] = True

    def pump():
        for _ in range(60):
            if done["flag"]:
                return
            router.pump()
            sched.yield_point("pumped")

    def finish():
        out = []
        if len(accepted) + rejected["n"] != 2:
            out.append(f"attempts split {len(accepted)} accepted + "
                       f"{rejected['n']} rejected != 2")
        missing = [t.request_id for t in accepted if t.response is None]
        if missing:
            out.append(f"admitted tickets never landed: {missing}")
        return out

    return ScenarioCtx(
        threads=[("ops", ops), ("client", client), ("pump", pump)],
        probe=lambda: {invariants.ROUTER: router.counters()},
        finish=finish)


#: The shipped sweep: one scenario per race class the serve plane runs.
SCENARIOS: tuple[Scenario, ...] = (
    Scenario("submit-vs-pump",
             "clients submitting while the pump thread reaps results",
             _build_submit_vs_pump),
    Scenario("kill-vs-landing",
             "replica kill racing its own landing result",
             _build_kill_vs_landing),
    Scenario("evict-vs-append",
             "session append landing while the FactorCache evicts",
             _build_evict_vs_append),
    Scenario("drain-vs-submit",
             "drain_replica racing submit admission",
             _build_drain_vs_submit),
)


# ---- running, shrinking, reporting -----------------------------------------


def run_schedule(scenario: Scenario, seed: int,
                 forced: Optional[list] = None,
                 max_steps: int = _MAX_STEPS) -> ScheduleResult:
    """One deterministic run of `scenario` under `seed` (or a forced
    choice list — unrunnable forced choices fall back to the first
    runnable thread, so shrunk schedules always replay)."""
    sched = CoopScheduler(seed=seed, forced=forced)
    with sched.patched():
        ctx = scenario.build(sched)
        violation = sched.run(ctx, max_steps=max_steps)
    return ScheduleResult(scenario=scenario.name, seed=seed,
                          choices=list(sched.choices),
                          trace=list(sched.trace), violation=violation)


def shrink(scenario: Scenario, result: ScheduleResult) -> ScheduleResult:
    """Greedy trace minimization: repeatedly try to extend the previous
    thread's run across a context switch; keep any rewrite that still
    reproduces the same violation kind.  The violation already ends the
    run, so the tail is minimal by construction."""
    if result.violation is None:
        return result
    kind = result.violation.kind
    best = result
    improved = True
    rounds = 0
    while improved and rounds < 20:
        improved = False
        rounds += 1
        for i in range(1, len(best.choices)):
            if best.choices[i] == best.choices[i - 1]:
                continue
            cand = (best.choices[:i] + [best.choices[i - 1]]
                    + best.choices[i + 1:])
            res = run_schedule(scenario, seed=result.seed, forced=cand)
            if res.violation is not None and res.violation.kind == kind \
                    and len(res.choices) <= len(best.choices):
                best = res
                improved = True
                break
    return best


def explore(scenario: Scenario, schedules: int, seed: int = 0
            ) -> tuple[Optional[ScheduleResult], int]:
    """Sweep `schedules` seeded runs; on the first violation, shrink it
    and return (minimal failing result, runs taken).  (None, schedules)
    when every schedule holds every invariant."""
    for i in range(schedules):
        res = run_schedule(scenario, seed=seed + i)
        if res.violation is not None:
            return shrink(scenario, res), i + 1
    return None, schedules


def violation_finding(scenario: Scenario, res: ScheduleResult
                      ) -> rules.Finding:
    v = res.violation
    return rules.make(
        INTERLEAVING, rules.ERROR, f"schedule:{scenario.name}",
        f"[{v.kind}] " + "; ".join(v.messages)
        + f" (seed={res.seed}, step={v.step}; minimal schedule:\n"
        + res.render_trace() + ")",
    )


def lint_schedules(schedules: int = 200, seed: int = 0,
                   scenarios: Optional[tuple] = None) -> list[rules.Finding]:
    """The dynamic layer: sweep every scenario; error findings carry the
    minimal failing trace, info findings record the clean sweep size (so
    the ledger block proves how hard the explorer actually looked)."""
    findings: list[rules.Finding] = []
    for sc in (scenarios if scenarios is not None else SCENARIOS):
        failing, runs = explore(sc, schedules, seed=seed)
        if failing is not None:
            findings.append(violation_finding(sc, failing))
        else:
            findings.append(rules.make(
                INTERLEAVING, rules.INFO, f"schedule:{sc.name}",
                f"{runs} seeded schedules swept, every invariant held",
            ))
    return rules.sort_findings(findings)


def fault_scenario(mod) -> Scenario:
    """The self-check scenario over the committed broken fixture
    (tests/fixtures/concurrency_fault.py): two threads hammer the
    deliberately unguarded RacyCounter; the lost update MUST surface as
    a scenario-check violation or the explorer is dead."""

    def build(sched: CoopScheduler) -> ScenarioCtx:
        c = mod.RacyCounter(yield_point=sched.yield_point)

        def worker():
            for _ in range(2):
                c.increment()

        def check():
            if c.count != c.increments:
                return [f"racy-counter lost update: count={c.count} != "
                        f"increments={c.increments}"]
            return []

        return ScenarioCtx(
            threads=[("w1", worker), ("w2", worker)],
            check=check, finish=check)

    return Scenario("self-check-racy-counter",
                    "the committed broken fixture must fail", build)
