"""The lint rules engine: findings, severities, baselines-aware gating.

CAPITAL's claim is that its schedules are *provably* communication-avoiding,
and PRs 1-4 turned the pieces of that proof into runtime invariants — the
phase-tagged cost model, copy_bytes=0 contracts, zero-steady-state-recompile
serving, donation on TPU.  Each invariant is enforced by example-specific
tests, which means a new schedule or a refactor can regress one silently as
soon as it steps off the tested examples.  This package checks the
invariants *statically*, on any traced program or source file, in the
program-analysis tradition of communication lower-bound checking (CA-CQR2,
arXiv:1710.08471; communication-optimal QR, arXiv:0809.2407): the program is
the object of proof, not the run.

This module is the policy-free core shared by the two passes:

* `Finding` — one rule violation, with a stable `fingerprint` that survives
  line-number churn (rule + target + message, not line), so the baseline
  file keeps suppressing a finding while unrelated code moves around it.
* severities — ``error`` (invariant broken), ``warn`` (smells that need a
  human), ``info`` (context the CLI prints but never gates on).
* `gate` — the exit-code policy for ``--fail-on``.

Rule implementations live in `capital_tpu.lint.program` (jaxpr/HLO rules)
and `capital_tpu.lint.source` (AST rules); the baseline file format in
`capital_tpu.lint.baseline`.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Iterable, Optional

ERROR = "error"
WARN = "warn"
INFO = "info"

#: Gate thresholds, most severe first.  ``--fail-on warn`` fails on warn OR
#: error; info never gates (it is context, not a violation).
SEVERITIES = (ERROR, WARN, INFO)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation.

    ``target`` is a source path for the source pass, a program name
    (``program:cholinv``) for the sanitizer; ``line`` is 1-based for source
    findings and 0 for program findings (a traced program has no single
    line).  ``message`` must identify the violation *content-wise* (the
    primitive, the tag, the constant's shape) because the fingerprint hangs
    off it — two different violations must not share a message within one
    (rule, target)."""

    rule: str
    severity: str
    target: str
    line: int
    message: str

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"unknown severity {self.severity!r}; use one of {SEVERITIES}"
            )

    @property
    def fingerprint(self) -> str:
        """Stable identity for baseline matching: deliberately excludes the
        line number so unrelated edits above a finding don't un-suppress
        it.  The cost is that N identical violations in one file share a
        fingerprint — acceptable: the baseline suppresses the *class*, and
        fixing one of N still leaves the rest suppressed until a
        --update-baseline refresh."""
        ident = f"{self.rule}|{self.target}|{self.message}"
        return hashlib.sha1(ident.encode()).hexdigest()[:16]

    def render(self) -> str:
        loc = f"{self.target}:{self.line}" if self.line else self.target
        return f"{self.severity.upper():5s} {self.rule:24s} {loc}: {self.message}"

    def asdict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint
        return d


def summarize(findings: Iterable[Finding]) -> dict[str, int]:
    """Severity -> count, with every severity present (zeros included) so
    the ledger block has a fixed shape."""
    out = {s: 0 for s in SEVERITIES}
    for f in findings:
        out[f.severity] += 1
    return out


def gate(findings: Iterable[Finding], fail_on: str = ERROR) -> bool:
    """True when the findings pass the gate (no finding at or above the
    ``fail_on`` severity).  ``fail_on`` is 'error' (default: warns pass) or
    'warn' (warns fail too); info never fails a gate."""
    if fail_on not in (ERROR, WARN):
        raise ValueError(f"--fail-on must be 'warn' or 'error', got {fail_on!r}")
    failing = (ERROR,) if fail_on == ERROR else (ERROR, WARN)
    return not any(f.severity in failing for f in findings)


def sort_findings(findings: Iterable[Finding]) -> list[Finding]:
    """Stable report order: severity (errors first), then target, line."""
    rank = {s: i for i, s in enumerate(SEVERITIES)}
    return sorted(
        findings, key=lambda f: (rank[f.severity], f.target, f.line, f.rule)
    )


def make(rule: str, severity: str, target: str, message: str,
         line: int = 0) -> Finding:
    """Terse constructor used by the rule implementations."""
    return Finding(rule=rule, severity=severity, target=target, line=line,
                   message=message)


@dataclasses.dataclass
class Report:
    """One pass's outcome after baseline application: what the CLI prints,
    gates on, and writes to the ledger."""

    pass_name: str  # "program" | "source"
    fresh: list[Finding]
    suppressed: list[Finding]
    baseline_path: Optional[str]

    def ok(self, fail_on: str = ERROR) -> bool:
        return gate(self.fresh, fail_on)

    def counts(self) -> dict[str, int]:
        return summarize(self.fresh)

    def block(self, fail_on: str = ERROR) -> dict:
        """The schema-tagged ``lint_report`` ledger payload
        (obs/ledger.validate_lint_report is the consumer contract)."""
        from capital_tpu.obs import ledger  # local: obs imports nothing from lint

        return {
            "schema_version": ledger.SCHEMA_VERSION,
            "pass": self.pass_name,
            "fail_on": fail_on,
            "ok": self.ok(fail_on),
            "counts": self.counts(),
            "suppressed": len(self.suppressed),
            "findings": [f.asdict() for f in sort_findings(self.fresh)],
        }
