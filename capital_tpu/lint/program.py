"""The program sanitizer: invariant rules over jaxpr + compiled HLO.

Each rule verifies one of the invariants PRs 1-4 established, on *any*
traced program — not just the examples the tests pin:

* ``phase-coverage`` — every FLOP-bearing equation is attributable to a
  registered `tracing.PHASE_REGISTRY` scope.  An untagged matmul lands in
  the 'other' bucket of every downstream view (trace tool, drift
  classifier, autotune tables): cost silently exempt from the
  communication-avoidance accounting.
* ``donation-honored`` — declared ``donate_argnums`` actually appear in the
  executable's ``input_output_alias``.  XLA drops unusable donations with
  only a Python warning; the serve engine's TPU auto-donation would turn
  into a silent peak-HBM regression.
* ``cache-key-hygiene`` — programs destined for an AOT cache (the
  SolveEngine) must not bake large constants (a captured operand becomes
  part of every cached executable — the exact hazard the serve engine's
  host-side fault tap exists to avoid) and should not carry weak-typed
  avals (weak/strong pairs of the same dtype compile twice and miss the
  cache).
* ``no-host-sync`` — no callbacks/infeed/outfeed inside hot-path programs:
  a host round-trip inside a serve executable stalls the device per batch.
* ``dtype-drift`` — no f32→f64 promotion leaks under the x64 rig (an
  accidental Python-float/np.float64 operand doubles every byte the
  schedule moves).
* ``collective-budget`` — compiled collective counts stay within the
  model's drift envelope, reusing `obs/xla_audit.drift` (same tolerance
  policy, same classifier) instead of duplicating HLO parsing.

All HLO logic is text-based and unit-testable without a device; the jaxpr
walk threads the enclosing equation's phase into sub-jaxprs (scan/cond
bodies lose their own name stacks, but the control-flow op itself carries
the scope it was traced under).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Optional, Sequence

import jax
import numpy as np

from capital_tpu.lint import rules
from capital_tpu.obs import xla_audit
from capital_tpu.utils import tracing

# -- rule names (the catalog docs/STATIC_ANALYSIS.md documents) -------------

PHASE_COVERAGE = "phase-coverage"
DONATION_HONORED = "donation-honored"
CACHE_KEY_HYGIENE = "cache-key-hygiene"
NO_HOST_SYNC = "no-host-sync"
DTYPE_DRIFT = "dtype-drift"
COLLECTIVE_BUDGET = "collective-budget"

PROGRAM_RULES = (
    PHASE_COVERAGE, DONATION_HONORED, CACHE_KEY_HYGIENE, NO_HOST_SYNC,
    DTYPE_DRIFT, COLLECTIVE_BUDGET,
)

#: Primitives whose cost the alpha-beta model prices — the ops that MUST sit
#: under a registered phase scope.  Elementwise/data-movement primitives are
#: deliberately absent: padding, masking, and glue legitimately happen
#: between scopes and carry no modeled flops.
FLOP_PRIMITIVES = frozenset({
    "dot_general", "conv_general_dilated", "cholesky", "triangular_solve",
    "lu", "qr", "householder_product", "svd", "eigh", "schur",
    "pallas_call",
})

#: Primitives that synchronize with the host mid-program.  Any of these in a
#: hot-path program stalls the device once per dispatch.
HOST_SYNC_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed",
})

#: Baked-constant threshold: anything a human would type inline (eye masks,
#: small index tables) passes; an operand-sized array does not.
BAKED_CONST_BYTES = 1024


@dataclasses.dataclass
class ProgramTarget:
    """One entry point under analysis.

    ``fn(*args)`` must be jit-traceable; ``args`` are concrete arrays or
    ShapeDtypeStructs.  ``donate_argnums`` is what the caller *declares* to
    jit — the donation rule checks the executable honors it.  ``cacheable``
    marks programs destined for an AOT executable cache (enables
    cache-key-hygiene); ``hot_path`` marks per-request/steady-state
    programs (enables no-host-sync).  ``flops_audited=False`` exempts the
    target from the whole-program flops envelope only — for programs whose
    compute lives inside interpret-mode ``pallas_call`` bodies, which XLA's
    ``cost_analysis`` cannot see on the CPU lint rig (the model prices the
    executed kernel flops; the emulated HLO reports ~none).  The collective
    side of the budget rule still runs."""

    name: str
    fn: Callable
    args: tuple
    donate_argnums: tuple[int, ...] = ()
    cacheable: bool = True
    hot_path: bool = True
    flops_audited: bool = True

    @property
    def target(self) -> str:
        return f"program:{self.name}"


# --------------------------------------------------------------------------
# jaxpr walking
# --------------------------------------------------------------------------


def _phase_of_stack(stack_str: str) -> Optional[str]:
    """Longest registered phase tag whose dotted form appears in a
    name-stack string — the same longest-first attribution
    obs/xla_audit._phase_of applies to HLO lines."""
    best = None
    for tag in tracing.PHASE_REGISTRY:
        dot = tag.replace("::", ".")
        if dot in stack_str and (best is None or len(dot) > len(
                best.replace("::", "."))):
            best = tag
    return best


def _sub_jaxprs(eqn):
    """Every sub-jaxpr reachable from one equation's params (scan/while
    bodies, cond branches, pjit/custom_* call jaxprs)."""
    for v in eqn.params.values():
        items = v if isinstance(v, (list, tuple)) else (v,)
        for item in items:
            inner = getattr(item, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield inner  # ClosedJaxpr -> Jaxpr
            elif hasattr(item, "eqns"):
                yield item  # bare Jaxpr


def iter_eqns(jaxpr, inherited: Optional[str] = None):
    """Yield ``(eqn, phase)`` over a jaxpr and all sub-jaxprs.  ``phase`` is
    the innermost registered tag from the equation's own name stack, else
    the phase inherited from the enclosing control-flow equation (inner
    jaxprs are traced with a fresh name stack, but the scan/cond op itself
    remembers the scope)."""
    for eqn in jaxpr.eqns:
        phase = _phase_of_stack(str(eqn.source_info.name_stack)) or inherited
        yield eqn, phase
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, phase)


def _jaxpr(tgt: ProgramTarget):
    return jax.make_jaxpr(lambda *a: tgt.fn(*a))(*tgt.args)


# --------------------------------------------------------------------------
# jaxpr rules
# --------------------------------------------------------------------------


def rule_phase_coverage(tgt: ProgramTarget, closed) -> list[rules.Finding]:
    counts: dict[str, int] = {}
    for eqn, phase in iter_eqns(closed.jaxpr):
        if eqn.primitive.name in FLOP_PRIMITIVES and phase is None:
            counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
    return [
        rules.make(
            PHASE_COVERAGE, rules.ERROR, tgt.target,
            f"{n} {prim} equation(s) outside every registered tracing.scope "
            "— their cost lands in the 'other' bucket of every downstream "
            "view (trace tool, drift classifier, autotune tables)",
        )
        for prim, n in sorted(counts.items())
    ]


def rule_no_host_sync(tgt: ProgramTarget, closed) -> list[rules.Finding]:
    counts: dict[str, int] = {}
    for eqn, _ in iter_eqns(closed.jaxpr):
        if eqn.primitive.name in HOST_SYNC_PRIMITIVES:
            counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
    return [
        rules.make(
            NO_HOST_SYNC, rules.ERROR, tgt.target,
            f"{n} {prim} op(s) in a hot-path program — each dispatch "
            "synchronizes with the host (robust/faultinject taps fire "
            "host-side at serve::ingest for exactly this reason)",
        )
        for prim, n in sorted(counts.items())
    ]


def _nbytes(const) -> int:
    nb = getattr(const, "nbytes", None)
    if nb is not None:
        return int(nb)
    return int(np.asarray(const).nbytes)


def rule_cache_key_hygiene(tgt: ProgramTarget, closed) -> list[rules.Finding]:
    out: list[rules.Finding] = []
    for const in closed.consts:
        nb = _nbytes(const)
        if nb > BAKED_CONST_BYTES:
            arr = np.asarray(const)
            out.append(rules.make(
                CACHE_KEY_HYGIENE, rules.ERROR, tgt.target,
                f"baked-in constant {arr.dtype}[{','.join(map(str, arr.shape))}] "
                f"({nb} bytes) captured by closure — it becomes part of "
                "every AOT cache entry compiled from this program; pass it "
                "as an argument instead",
            ))
    for i, aval in enumerate(closed.in_avals):
        if getattr(aval, "weak_type", False):
            out.append(rules.make(
                CACHE_KEY_HYGIENE, rules.WARN, tgt.target,
                f"weak-typed input aval #{i} ({aval.dtype}) — weak/strong "
                "operands of the same dtype trace to different cache keys "
                "and double-compile; normalize with jnp.asarray(x, dtype)",
            ))
    return out


def rule_dtype_drift(tgt: ProgramTarget, closed) -> list[rules.Finding]:
    wide = {np.dtype(np.float64), np.dtype(np.complex128)}
    in_wide = any(
        np.dtype(a.dtype) in wide
        for a in closed.in_avals if hasattr(a, "dtype")
    ) or any(np.asarray(c).dtype in wide for c in closed.consts)
    if in_wide:
        return []  # a genuinely f64 program is allowed to be f64 throughout
    counts: dict[str, int] = {}
    for eqn, _ in iter_eqns(closed.jaxpr):
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is not None and hasattr(aval, "dtype") and \
                    np.dtype(aval.dtype) in wide:
                counts[eqn.primitive.name] = counts.get(
                    eqn.primitive.name, 0) + 1
    return [
        rules.make(
            DTYPE_DRIFT, rules.ERROR, tgt.target,
            f"{n} {prim} equation(s) produce float64/complex128 from a "
            "narrower-typed program — an x64-rig promotion leak doubles "
            "every byte the schedule moves (check Python-float / "
            "np.float64 operands)",
        )
        for prim, n in sorted(counts.items())
    ]


# --------------------------------------------------------------------------
# HLO rules (pure text; unit-testable without a device)
# --------------------------------------------------------------------------

_ALIAS_ATTR = "input_output_alias={"
_ALIAS_ENTRY_RE = re.compile(r"\(\s*(\d+)\s*,")


def aliased_params(hlo_text: str) -> set[int]:
    """Parameter numbers that appear as alias sources in the module's
    ``input_output_alias`` attribute (entries are ``{out_idx}: (param,
    {param_idx}, kind)``).  Empty when the attribute is absent — XLA
    dropped every donation.  Brace-matched, not regexed: the attribute
    nests ``{}`` index tuples."""
    start = hlo_text.find(_ALIAS_ATTR)
    if start < 0:
        return set()
    i = start + len(_ALIAS_ATTR)
    depth = 1
    while i < len(hlo_text) and depth:
        if hlo_text[i] == "{":
            depth += 1
        elif hlo_text[i] == "}":
            depth -= 1
        i += 1
    body = hlo_text[start + len(_ALIAS_ATTR):i - 1]
    return {int(p) for p in _ALIAS_ENTRY_RE.findall(body)}


def check_donation_text(
    hlo_text: str, donate_argnums: Sequence[int], target: str,
) -> list[rules.Finding]:
    """Declared-vs-honored donation on compiled HLO text."""
    honored = aliased_params(hlo_text)
    return [
        rules.make(
            DONATION_HONORED, rules.ERROR, target,
            f"donated argument #{i} has no input_output_alias entry in the "
            "compiled executable — XLA dropped the donation (shape/layout "
            "mismatch with every output), so the buffer is double-resident "
            "in HBM for the program's lifetime",
        )
        for i in sorted(set(int(i) for i in donate_argnums))
        if i not in honored
    ]


def check_donation(compiled, donate_argnums: Sequence[int],
                   target: str = "program:<compiled>") -> list[rules.Finding]:
    """Donation rule on a compiled executable (jit().lower().compile()
    product) — also the `SolveEngine(validate=True)` cache-insert assert."""
    if not donate_argnums:
        return []
    return check_donation_text(compiled.as_text(), donate_argnums, target)


def rule_collective_budget(
    tgt: ProgramTarget,
    audit: xla_audit.ProgramAudit,
    recorder: tracing.Recorder,
    tol_ratio: float = 4.0,
    slack: int = 8,
    flops_tol_ratio: float = 2.0,
) -> list[rules.Finding]:
    """Compiled collectives within the xla_audit drift envelope: the same
    classifier `make audit` gates on, surfaced as lint findings so one
    report carries every invariant."""
    rep = xla_audit.drift(
        audit, recorder, tol_ratio=tol_ratio, slack=slack,
        flops_tol_ratio=flops_tol_ratio,
    )
    out = [
        rules.make(
            COLLECTIVE_BUDGET, rules.ERROR, tgt.target,
            f"phase {p.phase}: compiled {p.compiled_collectives} collectives "
            f"vs model {p.model_collectives} — beyond the drift envelope "
            f"(tol_ratio={tol_ratio}, slack={slack}); the schedule gained "
            "communication the model does not price",
        )
        for p in rep.phases if p.classification == xla_audit.UNDERCOUNT
    ]
    if not rep.flops_within and tgt.flops_audited:
        out.append(rules.make(
            COLLECTIVE_BUDGET, rules.WARN, tgt.target,
            f"whole-program flops drift: model {rep.model_flops:.3e} vs "
            f"compiled {rep.compiled_flops:.3e} (allowance "
            f"{flops_tol_ratio}x)",
        ))
    return out


# --------------------------------------------------------------------------
# orchestration
# --------------------------------------------------------------------------


def sanitize(
    tgt: ProgramTarget,
    *,
    tol_ratio: float = 4.0,
    slack: int = 8,
    flops_tol_ratio: float = 2.0,
    compile_program: bool = True,
) -> list[rules.Finding]:
    """Run every applicable program rule over one target.

    The jaxpr rules trace abstractly (`jax.make_jaxpr`); the HLO rules
    compile via a fresh jit wrapper (never the caller's cache entry — the
    same discipline as obs/xla_audit.audit).  ``compile_program=False``
    skips the compile-side rules (donation, collective-budget) for callers
    that only want the trace-side invariants."""
    closed = _jaxpr(tgt)
    findings: list[rules.Finding] = []
    findings += rule_phase_coverage(tgt, closed)
    if tgt.hot_path:
        findings += rule_no_host_sync(tgt, closed)
    if tgt.cacheable:
        findings += rule_cache_key_hygiene(tgt, closed)
    findings += rule_dtype_drift(tgt, closed)
    if compile_program:
        compiled = jax.jit(
            lambda *a: tgt.fn(*a), donate_argnums=tgt.donate_argnums,
        ).lower(*tgt.args).compile()
        if tgt.donate_argnums:
            findings += check_donation(compiled, tgt.donate_argnums,
                                       tgt.target)
        recorder = xla_audit.trace_model(tgt.fn, *tgt.args)
        audit = xla_audit.audit_compiled(compiled)
        findings += rule_collective_budget(
            tgt, audit, recorder, tol_ratio=tol_ratio, slack=slack,
            flops_tol_ratio=flops_tol_ratio,
        )
    return rules.sort_findings(findings)
