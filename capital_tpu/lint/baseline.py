"""The checked-in baseline/suppression file (``lint_baseline.jsonl``).

A lint gate that blocks on day-one findings never gets adopted; a gate that
silently grandfathers them never gets fixed.  The baseline is the middle
path: one JSONL record per *accepted* pre-existing finding (fingerprint +
enough human-readable context to review it in a diff), checked into the
repo.  Findings whose fingerprint appears in the baseline are reported as
``suppressed`` and don't gate; every fresh finding gates immediately.

Workflow (docs/STATIC_ANALYSIS.md):

* ``python -m capital_tpu.lint source --update-baseline`` rewrites the file
  from the current findings — run it when accepting a debt item, and review
  the diff like code (each line names the rule and message).
* ``--no-baseline`` ignores the file: the full-debt view, used by the tests
  to prove a suppressed finding still *exists* (baseline round-trip).
* Fixing a finding makes its baseline line dead weight; ``--update-baseline``
  garbage-collects it.

Fingerprints exclude line numbers on purpose (see rules.Finding.fingerprint)
so the baseline survives unrelated edits.
"""

from __future__ import annotations

import json
import os
from typing import Iterable

from capital_tpu.lint import rules

#: Default baseline location, relative to the repo root / CWD.
DEFAULT_PATH = "lint_baseline.jsonl"


def load(path: str) -> set[str]:
    """Fingerprint set of the baseline at `path`; empty when the file does
    not exist (a missing baseline means no accepted debt, not an error)."""
    if not os.path.exists(path):
        return set()
    fps: set[str] = set()
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                fps.add(str(rec["fingerprint"]))
            except (json.JSONDecodeError, KeyError, TypeError) as e:
                raise ValueError(
                    f"{path}:{i + 1}: malformed baseline line ({e}); fix or "
                    "regenerate with --update-baseline"
                ) from e
    return fps


def write(path: str, findings: Iterable[rules.Finding]) -> int:
    """Rewrite the baseline from `findings` (sorted, one JSON line each,
    deduplicated by fingerprint).  Returns the number of lines written."""
    seen: dict[str, rules.Finding] = {}
    for f in rules.sort_findings(findings):
        seen.setdefault(f.fingerprint, f)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as fh:
        for fp, f in sorted(seen.items(), key=lambda kv: (
                kv[1].rule, kv[1].target, kv[1].message)):
            fh.write(json.dumps({
                "fingerprint": fp,
                "rule": f.rule,
                "severity": f.severity,
                "target": f.target,
                "message": f.message,
            }) + "\n")
    return len(seen)


def apply(
    findings: Iterable[rules.Finding], fingerprints: set[str]
) -> tuple[list[rules.Finding], list[rules.Finding]]:
    """Split findings into (fresh, suppressed) against a fingerprint set."""
    fresh, suppressed = [], []
    for f in findings:
        (suppressed if f.fingerprint in fingerprints else fresh).append(f)
    return fresh, suppressed
