"""The source lint: AST rules enforcing the repo's hard-won coding rules.

* ``bare-except`` — ``except:`` swallows KeyboardInterrupt and bugs alike;
  the PR 2 class of incident (a bare except in the bench harness ate real
  schedule failures for two rounds).
* ``broad-except`` — ``except Exception`` (or BaseException) without a
  re-raise or a logging call in the handler.  Catch-and-drop turns every
  future bug into silence; the accepted spellings are (a) narrow the type,
  (b) re-raise after containment, (c) log what was swallowed, or (d) an
  explicit inline ``# noqa``/``# lint: allow-broad-except`` with a reason —
  visible suppression at the site, reviewable in diffs.
* ``compute-outside-scope`` — in ``models/``/``parallel/``/``ops/``,
  FLOP-bearing ``jnp.``/``lax.`` calls (and the ``@`` operator) must sit
  lexically inside a ``tracing.scope(...)`` block, or the op compiles with
  no phase metadata and the program sanitizer's phase-coverage rule fires
  downstream on every program that inlines it.  Severity warn: lexical
  analysis cannot see callers that wrap the whole function in a scope, so a
  human decides (fix, or baseline with a comment).
* ``unregistered-phase-tag`` — string literals passed to ``scope(...)`` or
  ``tap(point=...)`` must be in `tracing.PHASE_REGISTRY`.  scope() refuses
  unknown tags at trace time; this rule moves the failure to lint time,
  before a rarely-traced branch ships the ValueError to production.
* ``host-only-dispatch`` — the serve dispatch plane (``serve/router.py``,
  ``serve/replica.py``) must not import jax at module level: the router
  and its spawned client/worker shims run in processes that either never
  need a device runtime (pure host-side dispatch) or must apply their env
  overrides BEFORE jax initializes (the ProcessReplica spawn contract).
  Engine access goes through the lazy in-worker import; a module-level
  ``import jax`` here silently re-couples the dispatch plane to the
  device runtime.

Pure stdlib ``ast`` — no file is imported, so linting broken code or code
with heavy import side effects is safe.
"""

from __future__ import annotations

import ast
import os
from typing import Optional

from capital_tpu.lint import rules
from capital_tpu.utils import tracing

BARE_EXCEPT = "bare-except"
BROAD_EXCEPT = "broad-except"
COMPUTE_OUTSIDE_SCOPE = "compute-outside-scope"
UNREGISTERED_PHASE_TAG = "unregistered-phase-tag"
HOST_ONLY_DISPATCH = "host-only-dispatch"

SOURCE_RULES = (
    BARE_EXCEPT, BROAD_EXCEPT, COMPUTE_OUTSIDE_SCOPE, UNREGISTERED_PHASE_TAG,
    HOST_ONLY_DISPATCH,
)

#: Files (path suffixes) that form the serve dispatch plane: host-only by
#: contract, no module-level jax import allowed.
HOST_ONLY_FILES = (
    os.path.join("serve", "router.py"),
    os.path.join("serve", "replica.py"),
)

#: Module roots whose import at module level couples a file to the device
#: runtime (jax itself and its subpackages).
_DEVICE_ROOTS = frozenset({"jax", "jaxlib"})

#: FLOP-bearing jnp/lax entry points (mirrors program.FLOP_PRIMITIVES at the
#: API level: what lowers to those primitives).
FLOP_FNS = frozenset({
    "matmul", "dot", "einsum", "tensordot", "dot_general",
    "conv_general_dilated", "cholesky", "triangular_solve", "lu", "qr",
    "svd", "eigh",
})

#: Roots a FLOP call must hang off to count as traced compute (host numpy
#: is not traced and carries no phase metadata anyway).
_COMPUTE_ROOTS = frozenset({"jnp", "lax", "jax", "linalg"})

#: Method names whose presence in a broad-except handler counts as "logged".
_LOG_METHODS = frozenset({
    "debug", "info", "warning", "warn", "error", "exception", "critical",
})

#: Inline suppression markers on the ``except`` line itself.
_SUPPRESS_MARKERS = ("noqa", "lint: allow-broad-except")

#: Directories (package segments) where compute-outside-scope applies.
SCOPED_DIRS = ("models", "parallel", "ops")


def _attr_chain(node: ast.AST) -> list[str]:
    """['jnp', 'linalg', 'cholesky'] for jnp.linalg.cholesky; [] when the
    expression is not a plain dotted name."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _is_scope_call(node: ast.AST) -> bool:
    """True for ``scope(...)`` / ``tracing.scope(...)`` context managers
    (NOT platform_scope / named_scope — those don't tag phases)."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id == "scope"
    if isinstance(fn, ast.Attribute):
        return fn.attr == "scope"
    return False


def _is_logging_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in _LOG_METHODS:
        return True
    if isinstance(fn, ast.Name) and fn.id in ("warn", "log"):
        return True
    return False


def _handler_contains_exit(handler: ast.ExceptHandler) -> bool:
    """Re-raise or logging anywhere inside the handler body."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and _is_logging_call(node):
            return True
    return False


def _phase_literal(call: ast.Call) -> Optional[tuple[str, int]]:
    """(tag, lineno) when `call` is scope(<str-literal>) or
    tap(..., point=<str-literal>); None otherwise."""
    fn = call.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    if name == "scope" and call.args:
        arg = call.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value, arg.lineno
    if name == "tap":
        for kw in call.keywords:
            if kw.arg == "point" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value, kw.value.lineno
        if len(call.args) >= 2:
            arg = call.args[1]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return arg.value, arg.lineno
    return None


def _in_scoped_dir(path: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    return any(p in SCOPED_DIRS for p in parts)


def _flop_call_name(node: ast.AST) -> Optional[str]:
    """The FLOP function name when `node` is a jnp/lax compute call or an
    ``@`` matmul expression; None otherwise."""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
        return "@"
    if isinstance(node, ast.Call):
        chain = _attr_chain(node.func)
        if len(chain) >= 2 and chain[-1] in FLOP_FNS \
                and chain[0] in _COMPUTE_ROOTS:
            return ".".join(chain)
    return None


def lint_source(path: str, text: Optional[str] = None) -> list[rules.Finding]:
    """Every source finding for one file.  `text` overrides reading `path`
    (the tests lint synthetic snippets under invented paths)."""
    if text is None:
        with open(path) as f:
            text = f.read()
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        return [rules.make(
            "syntax", rules.ERROR, path,
            f"not parseable: {e.msg}", line=e.lineno or 0,
        )]
    lines = text.splitlines()
    findings: list[rules.Finding] = []

    def _suppressed(lineno: int) -> bool:
        if 0 < lineno <= len(lines):
            line = lines[lineno - 1]
            return any(m in line for m in _SUPPRESS_MARKERS)
        return False

    # -- except rules + phase-tag rule: flat walk --------------------------
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler):
            if node.type is None:
                findings.append(rules.make(
                    BARE_EXCEPT, rules.ERROR, path,
                    "bare `except:` swallows KeyboardInterrupt and bugs "
                    "alike — name the exception types",
                    line=node.lineno,
                ))
                continue
            tname = node.type.id if isinstance(node.type, ast.Name) else (
                node.type.attr if isinstance(node.type, ast.Attribute)
                else None)
            if tname in ("Exception", "BaseException") \
                    and not _handler_contains_exit(node) \
                    and not _suppressed(node.lineno):
                findings.append(rules.make(
                    BROAD_EXCEPT, rules.ERROR, path,
                    f"`except {tname}` without re-raise or logging — "
                    "narrow the type, re-raise after containment, log "
                    "what was swallowed, or suppress inline with a reason "
                    "(# lint: allow-broad-except)",
                    line=node.lineno,
                ))
        elif isinstance(node, ast.Call):
            lit = _phase_literal(node)
            if lit is not None and lit[0] not in tracing.PHASE_REGISTRY:
                findings.append(rules.make(
                    UNREGISTERED_PHASE_TAG, rules.ERROR, path,
                    f"phase tag {lit[0]!r} is not in tracing.PHASE_REGISTRY "
                    "— scope() will raise at trace time; register it (or "
                    "register_phase) so downstream views can bucket it",
                    line=lit[1],
                ))

    # -- host-only-dispatch: module-level device-runtime imports -----------
    norm = os.path.normpath(path)
    if any(norm.endswith(sfx) for sfx in HOST_ONLY_FILES):
        def _import_roots(node: ast.AST) -> list[tuple[str, int]]:
            if isinstance(node, ast.Import):
                return [(a.name.split(".")[0], node.lineno)
                        for a in node.names]
            if isinstance(node, ast.ImportFrom) and node.module:
                return [(node.module.split(".")[0], node.lineno)]
            return []

        def scan_module_level(node: ast.AST) -> None:
            # function bodies are exempt: the lazy in-worker import (after
            # the spawn child applies its env overrides) is the sanctioned
            # way to reach the engine from the dispatch plane
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                for root, lineno in _import_roots(child):
                    if root in _DEVICE_ROOTS and not _suppressed(lineno):
                        findings.append(rules.make(
                            HOST_ONLY_DISPATCH, rules.ERROR, path,
                            f"module-level `{root}` import in the serve "
                            "dispatch plane — router/replica must stay "
                            "host-only (import lazily inside the worker, "
                            "after env overrides apply)",
                            line=lineno,
                        ))
                scan_module_level(child)

        scan_module_level(tree)

    # -- compute-outside-scope: recursive walk with scope context ----------
    if _in_scoped_dir(path):
        def visit(node: ast.AST, covered: bool) -> None:
            if isinstance(node, ast.With):
                covered = covered or any(
                    _is_scope_call(item.context_expr) for item in node.items
                )
            name = _flop_call_name(node)
            if name is not None and not covered \
                    and not _suppressed(node.lineno):
                findings.append(rules.make(
                    COMPUTE_OUTSIDE_SCOPE, rules.WARN, path,
                    f"FLOP-bearing `{name}` outside every tracing.scope() "
                    "block — the op compiles with no phase metadata and "
                    "lands in 'other' in every downstream view",
                    line=node.lineno,
                ))
            for child in ast.iter_child_nodes(node):
                visit(child, covered)

        visit(tree, covered=False)
    return rules.sort_findings(findings)


def lint_tree(root: str) -> list[rules.Finding]:
    """Lint every ``*.py`` under `root` (skipping __pycache__), findings
    keyed by path relative to the current directory."""
    findings: list[rules.Finding] = []
    if os.path.isfile(root):
        return lint_source(root)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                findings.extend(lint_source(os.path.join(dirpath, fn)))
    return rules.sort_findings(findings)
