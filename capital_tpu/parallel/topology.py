"""Device-mesh topology: the TPU-native equivalent of CAPITAL's process grids.

The reference (src/util/topology.h) builds 3D process grids by splitting MPI
communicators: ``topo::square`` is a d x d x c grid (face d x d, replication
depth c) with named sub-communicators {world, row, column, slice, depth};
``topo::rect`` is a tunable c x d grid for tall-skinny QR with extra
{cube, column_contig, column_alt} sub-communicators (topology.h:16-143).

On TPU the whole layer collapses to a `jax.sharding.Mesh` with named axes
``('x', 'y', 'z')`` plus sharding helpers:

  - sub-communicator  ->  mesh axis name used by an axis-scoped collective
        row    comm (vary x, fixed y,z)  ->  collectives over axis 'x'
        column comm (vary y, fixed x,z)  ->  collectives over axis 'y'
        depth  comm (vary z)             ->  collectives over axis 'z'
        slice  comm (vary x,y)           ->  collectives over ('x', 'y')
        world                            ->  collectives over ('x', 'y', 'z')
  - grid coordinates (x,y,z)  ->  `jax.lax.axis_index` inside shard_map
  - communicator free/destructor -> nothing (meshes are cheap values)

Matrix distribution convention (used throughout the framework): a global
(M, N) array is **block**-distributed with rows split over mesh axis 'x' and
columns over mesh axis 'y', replicated over 'z' — i.e.
``NamedSharding(mesh, P('x', 'y'))``.  Note this deliberately differs from the
reference, which distributes *element-cyclically* over the PgridX x PgridY
face (structure.hpp strides global positions by the grid dims per local
element; matrix.hpp:6-18): cyclic layout exists there to load-balance
triangular work, which this framework instead handles with block-level
masking/predication, while contiguous blocks are what XLA/MXU tiling wants.
Matrix *content* stays comparable across the two layouts because fillers are
seeded from global coordinates (see utils/rand48.py).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("x", "y", "z")


def _infer_square_face(num_devices: int, c: int) -> int:
    """d = sqrt(P / c), the face dimension of a d x d x c grid.

    Mirrors topo::square's ``d = ceil(sqrt(size/c))`` (topology.h:76-78), but
    requires exact divisibility: TPU meshes cannot leave devices idle.
    """
    if num_devices % c != 0:
        raise ValueError(f"num_devices={num_devices} not divisible by c={c}")
    face = num_devices // c
    d = int(round(math.sqrt(face)))
    if d * d != face:
        raise ValueError(
            f"num_devices/c = {face} is not a perfect square; "
            f"cannot build a d x d x {c} grid from {num_devices} devices"
        )
    return d


def layout2_eligible(dx: int, dy: int, c: int) -> bool:
    """Whether the 2x2x2-subcube device ordering (layout=2) applies to this
    grid shape — the single source of truth for the fallback condition in
    _order_devices and for callers choosing a layout programmatically."""
    return dx % 2 == 0 and dy % 2 == 0 and c % 2 == 0


def _order_devices(
    devices: Sequence[jax.Device], dx: int, dy: int, c: int, layout: int
) -> np.ndarray:
    """Assign devices to (x, y, z) grid coordinates — the TPU analog of the
    reference's rank->coordinate ``layout`` variants (topology.h:77-123).

    On an MPI cluster the layout decides which ranks share a node; on a TPU
    slice it decides which mesh axes map to adjacent ICI links (device order
    is physical on real slices), so it is the same locality knob:

      0  depth-fastest (reference layout 0: z = rank % c) — consecutive
         devices stack along the replication axis, so the depth allreduce
         rides the shortest links.  The natural reshape.  NOTE the face
         orientation is transposed relative to the reference's coordinate
         assignment (topology.h:81-83 is z-fastest, then x, then y; this
         reshape is z, then y, then x): row- and column-broadcast locality
         are swapped, so layout-sweep rows here are not directly comparable
         against reference layout-0 data — compare 0 vs 1 vs 2 within this
         framework only.
      1  face-fastest (reference layout 1 family) — consecutive devices tile
         the d x d face first; row/column bcasts get the short links, depth
         gets the long ones.
      2  subcube blocking (reference layout 2, the 64-rank subcube variant,
         topology.h:104-123) — consecutive groups of 8 devices form 2x2x2
         subcubes, balancing all three axes; falls back to layout 0 when any
         dimension is odd.
    """
    dev = np.asarray(devices, dtype=object)
    if layout == 0:
        return dev.reshape(dx, dy, c)
    if layout == 1:
        return np.moveaxis(dev.reshape(c, dx, dy), 0, 2)
    if layout == 2:
        if not layout2_eligible(dx, dy, c):
            import warnings

            warnings.warn(
                f"layout=2 needs even grid dims, got {(dx, dy, c)}: "
                "falling back to layout 0 (a layout-0-vs-2 comparison on "
                "this grid would silently measure the same ordering)",
                stacklevel=3,
            )
            return dev.reshape(dx, dy, c)
        # consecutive groups of 8 devices form 2x2x2 subcubes, block-major
        # over the (dx/2, dy/2, c/2) grid of subcubes
        return (
            dev.reshape(dx // 2, dy // 2, c // 2, 2, 2, 2)
            .transpose(0, 3, 1, 4, 2, 5)
            .reshape(dx, dy, c)
        )
    raise ValueError(f"layout must be 0, 1, or 2, got {layout}")


@dataclasses.dataclass(frozen=True)
class Grid:
    """A d x d x c (or dx x dy x c) device grid backed by a jax Mesh.

    TPU-native stand-in for ``topo::square`` / ``topo::rect``
    (reference src/util/topology.h:16-143).

    Attributes:
      mesh: Mesh with axes ('x', 'y', 'z') of shape (dx, dy, c).
      c:    replication depth (the 'z' axis extent) — trades memory for
            communication exactly like the reference's rep_factor.
      num_chunks: SUMMA communication-pipelining granularity, carried on the
            topology exactly like the reference's ctor argument
            (topo::square(world, c, layout, num_chunks), topology.h:67):
            the explicit schedule splits each K-panel broadcast into this
            many slices so the compiler can overlap each slice's collective
            with the previous slice's local matmul (the Ibcast/Iallreduce
            pipeline of summa.hpp:196-215).  0/1 = unchunked.
      collective_concurrency: 'free' (default) lets XLA's latency-hiding
            scheduler put any number of the explicit schedule's collectives
            in flight; 'solo' chains every collective in a SUMMA invocation
            behind the previous one (optimization_barrier data dependency),
            so at most one is on the wire at a time — the runtime
            re-expression of the reference's COLLECTIVE_CONCURRENCY_SOLO
            congestion experiment (compile flag, summa.hpp:179-192,
            230-235).  The reference's LAYER variant (per-depth-layer
            serialization) is subsumed: each depth layer's collectives
            already form a chain per device in 'solo', and XLA schedules
            per-program, not per-layer.  Same bytes and collective count —
            only the overlap changes, which the alpha-beta cost model does
            not price (it models launches, the scheduler owns overlap).
    """

    mesh: Mesh
    num_chunks: int = 0
    collective_concurrency: str = "free"
    layout: int = 0  # record of the device-ordering knob used at
    # construction (the ordering itself lives in mesh.devices); carried so
    # sweep rows over the layout axis stay attributable (reference
    # topology.h ctor arg)

    # ---- constructors ------------------------------------------------------

    @staticmethod
    def square(
        c: int = 1,
        devices: Optional[Sequence[jax.Device]] = None,
        layout: int = 0,
        num_chunks: int = 0,
        collective_concurrency: str = "free",
    ) -> "Grid":
        """Build a d x d x c grid from all (or the given) devices.

        Reference: topo::square ctor, topology.h:67-131.  ``layout`` is the
        reference's rank->coordinate assignment knob (topology.h:77-123) —
        on TPU it is the device-order-into-mesh permutation, the lever that
        decides which mesh axes ride adjacent ICI links (see _order_devices).
        """
        devices = list(devices if devices is not None else jax.devices())
        d = _infer_square_face(len(devices), c)
        return Grid(
            mesh=Mesh(_order_devices(devices, d, d, c, layout), AXES),
            num_chunks=num_chunks,
            collective_concurrency=collective_concurrency,
            layout=layout,
        )

    @staticmethod
    def rect(
        dx: int,
        dy: int,
        c: int = 1,
        devices: Optional[Sequence[jax.Device]] = None,
        layout: int = 0,
        num_chunks: int = 0,
        collective_concurrency: str = "free",
    ) -> "Grid":
        """Build a dx x dy x c grid (tunable shape, reference topo::rect).

        Reference: topology.h:16-65.  The reference's rect grid carries extra
        sub-communicators (cube, column_contig, column_alt) used by
        cacqr's tunable sweep; here those become axis subsets at collective
        call sites (see models/qr.py).
        """
        devices = list(devices if devices is not None else jax.devices())
        if dx * dy * c != len(devices):
            raise ValueError(f"{dx}*{dy}*{c} != {len(devices)} devices")
        return Grid(
            mesh=Mesh(_order_devices(devices, dx, dy, c, layout), AXES),
            num_chunks=num_chunks,
            collective_concurrency=collective_concurrency,
            layout=layout,
        )

    @staticmethod
    def flat(devices: Optional[Sequence[jax.Device]] = None) -> "Grid":
        """A P x 1 x 1 grid: every device along 'x'.

        Used for the 1D tall-skinny regime (cacqr's c==1 path,
        reference cacqr.hpp:7-29) where the long axis is sharded over all
        devices and everything else is replicated.
        """
        devices = list(devices if devices is not None else jax.devices())
        dev = np.asarray(devices).reshape(len(devices), 1, 1)
        return Grid(mesh=Mesh(dev, AXES))

    # ---- geometry ----------------------------------------------------------

    @property
    def dx(self) -> int:
        return self.mesh.shape["x"]

    @property
    def dy(self) -> int:
        return self.mesh.shape["y"]

    @property
    def c(self) -> int:
        return self.mesh.shape["z"]

    @property
    def num_devices(self) -> int:
        return self.dx * self.dy * self.c

    @property
    def is_square(self) -> bool:
        return self.dx == self.dy

    @property
    def platform(self) -> str:
        """Platform of the mesh's devices ('tpu'/'cpu'/...).  Kernel dispatch
        must key off this, never jax.default_backend(): a CPU mesh can live in
        a TPU-backed process (the driver's multichip dryrun)."""
        return self.mesh.devices.ravel()[0].platform

    # ---- sharding helpers --------------------------------------------------

    def face_sharding(self) -> NamedSharding:
        """Block distribution over the grid face, replicated over depth.

        The standard layout for every distributed matrix in the framework:
        rows over 'x', columns over 'y' (reference matrix.hpp:6-18).
        """
        return NamedSharding(self.mesh, P("x", "y"))

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def rows_sharding(self) -> NamedSharding:
        """Long-axis distribution: rows over all three axes, cols replicated.

        The tall-skinny layout (reference: Q registered on the full c x d
        rect grid, cacqr.hpp:224)."""
        return NamedSharding(self.mesh, P(("x", "y", "z"), None))

    def spec(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def pin(self, x):
        """Constrain a 2D array to the face layout when its shape divides the
        face, else leave placement to XLA (uneven explicit shardings are
        rejected by jit; odd-sized recursion windows hit this).  The fallback
        is announced — a distributed run with a misaligned n would otherwise
        silently lose the intended layout (pad to a divisible size upstream
        to avoid it)."""
        if x.ndim == 2 and x.shape[0] % self.dx == 0 and x.shape[1] % self.dy == 0:
            return jax.lax.with_sharding_constraint(x, self.face_sharding())
        if self.num_devices > 1:
            from capital_tpu.utils import tracing

            tracing.note("pin::fallback")
            import warnings

            warnings.warn(
                f"Grid.pin: shape {tuple(x.shape)} does not divide the "
                f"{self.dx}x{self.dy} face; placement left to XLA",
                stacklevel=2,
            )
        return x

    # ---- shape utilities ---------------------------------------------------

    def face_tile(self, m: int, n: int) -> tuple[int, int]:
        """Padded global shape so (rows, cols) divide evenly over (dx, dy).

        The reference pads implicitly with zero rows/cols per-rank
        (structure.hpp:42-43, matrix.hpp:7-11); here padding happens once,
        globally, at the boundary (SURVEY §7.1 'pad-to-tile')."""
        pm = -(-m // self.dx) * self.dx
        pn = -(-n // self.dy) * self.dy
        return pm, pn

    def __repr__(self) -> str:  # pragma: no cover
        chunks = f", chunks={self.num_chunks}" if self.num_chunks > 1 else ""
        return (
            f"Grid({self.dx}x{self.dy}x{self.c}, "
            f"{self.mesh.devices.ravel()[0].platform}{chunks})"
        )


def cpu_grid_square(c: int = 1, n: Optional[int] = None) -> Grid:
    """Square grid over host-platform (CPU) devices — the multi-chip test rig.

    The reference tests distributed behavior by oversubscribed ``mpirun -n 8``
    (SURVEY §4); the equivalent here is N virtual CPU devices via
    ``--xla_force_host_platform_device_count`` (see tests/conftest.py).
    """
    devices = jax.devices("cpu")
    if n is not None:
        if n > len(devices):
            raise ValueError(
                f"requested {n} CPU devices but only {len(devices)} exist "
                "(raise --xla_force_host_platform_device_count)"
            )
        devices = devices[:n]
    return Grid.square(c=c, devices=devices)
