"""SUMMA: distributed matrix multiplication on the device grid.

TPU-native re-design of the reference's 3D SUMMA (src/alg/matmult/summa/
summa.hpp).  The reference implements C = alpha*op(A)op(B) + beta*C on a
d x d x c process grid by broadcasting A-panels along the row communicator and
B-panels along the column communicator from depth-dependent roots, running a
local MKL gemm, and allreducing partial C over the depth communicator
(summa.hpp:177-249), with an optional chunked Ibcast/Iallreduce pipeline
(num_chunks, summa.hpp:196-215).  Overloads cover gemm, in-place triangular
trmm, and syrk-via-transpose (summa.hpp:7-161).

Here the same capability is expressed two ways, selectable per call:

* ``mode='xla'`` (default): the contraction is written as a plain jnp matmul
  with sharding constraints pinning operands and result to the grid face; the
  XLA SPMD partitioner plans the panel gathers and the depth psum itself.
  This is the idiomatic TPU path — GSPMD already implements SUMMA-family
  schedules, and the latency-hiding scheduler overlaps the collectives the
  way the reference's chunked pipeline does by hand.

* ``mode='explicit'``: a shard_map kernel that owns the schedule exactly like
  the reference owns its MPI calls: ring all_gathers realize the row/column
  panel broadcasts (amortized — same (d-1)/d bytes as d ring bcasts, one
  collective per operand per chunk), K-segments partitioned over the depth
  axis 'z' (the 2.5D flop split), per-segment dead-block skipping for
  triangular operands/outputs, and a chunked psum over 'z' (the reference's
  MPI_Iallreduce collect, summa.hpp:236-248).  This path is the control
  knob for communication research and is benchmarked against 'xla'.

* ``mode='pallas'``: trmm/syrk route through the live-tile-enumerated Pallas
  kernels (ops/pallas_tpu.py), which skip the dead triangle's blocks on the
  MXU — the ~2x flop saving the reference gets from BLAS trmm/syrk, measured
  1.4-1.65x on v5e at 8192^2.  Currently single-device grids only (the local
  compute of a distributed call; triangular structure does not tile cleanly
  over block-distributed shards), so distributed calls and gemm (where XLA's
  dense matmul is already optimal) fall back to 'xla'.

Triangular structure (trmm) and symmetric rank-k updates (syrk) are expressed
as masked gemms: dense tiles + elementwise masks fuse into the matmul and keep
the MXU full, replacing the reference's packed-storage policies (SURVEY §7.1).

All functions take and return **global** jax Arrays (any sharding; they pin
layouts internally) and are jit-compatible.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from capital_tpu.ops import masking, pallas_tpu
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import jax_compat, tracing


@dataclasses.dataclass(frozen=True)
class GemmArgs:
    """Mirror of blas::ArgPack_gemm (reference src/blas/engine.h:72-94)."""

    alpha: float = 1.0
    beta: float = 0.0
    trans_a: bool = False
    trans_b: bool = False
    precision: str | None = None  # None = context default; 'highest' = f32 MXU


@dataclasses.dataclass(frozen=True)
class TrmmArgs:
    """Mirror of blas::ArgPack_trmm (reference src/blas/engine.h:96-112)."""

    side: str = "L"  # 'L': B <- alpha*op(A)B ; 'R': B <- alpha*B*op(A)
    uplo: str = "U"
    trans_a: bool = False
    diag: str = "N"  # 'N' non-unit, 'U' unit diagonal
    alpha: float = 1.0
    precision: str | None = None


@dataclasses.dataclass(frozen=True)
class SyrkArgs:
    """Mirror of blas::ArgPack_syrk (reference src/blas/engine.h:114-130)."""

    uplo: str = "U"
    trans: bool = False  # False: C = a*A*Aᵀ + b*C ; True: C = a*AᵀA + b*C
    alpha: float = 1.0
    beta: float = 0.0
    precision: str | None = None


# --------------------------------------------------------------------------
# explicit shard_map schedule
# --------------------------------------------------------------------------


def _seg_live_a_global(xi, s, ch, mb, lk, w, a_uplo):
    # A columns of (segment s, chunk ch): [s*lk + ch*w, +w); rows of this
    # device's block: [xi*mb, +mb).  Live = intersects the stored triangle.
    lo = s * lk + ch * w
    if a_uplo == "U":
        return xi * mb < lo + w  # ∃ row <= col
    return (xi + 1) * mb - 1 >= lo  # 'L': ∃ row >= col


def _seg_live_b_global(yi, s, ch, nb, lk, w, b_uplo):
    # B rows of (segment s, chunk ch); cols of this block: [yi*nb, +nb)
    lo = s * lk + ch * w
    if b_uplo == "U":
        return lo < (yi + 1) * nb
    return lo + w - 1 >= yi * nb


def tile_cyclic_perm(m: int, d: int, tile: int):
    """Row permutation realizing block-cyclic-over-tiles distribution on a
    d-row face: original row-tile g lands on device row g % d, local slot
    g // d — the reference's element-cyclic balancing idea
    (structure.hpp:80-85) at MXU-tile granularity, so whole tiles stay
    dead/alive and remain skippable.  Returns (perm, inv) as numpy index
    arrays: X[perm] is the cyclic layout, Y[inv] undoes it."""
    import numpy as np

    if m % (d * tile):
        raise ValueError(f"tile_cyclic_perm: {d} devices x tile {tile} must tile {m}")
    nt = m // tile
    order = [g for xi in range(d) for g in range(xi, nt, d)]
    perm = np.concatenate([np.arange(g * tile, (g + 1) * tile) for g in order])
    inv = np.empty_like(perm)
    inv[perm] = np.arange(m)
    return perm, inv


def cyclic_window(V: jnp.ndarray, view, d: int, tile: int) -> jnp.ndarray:
    """Extract the LOGICAL window ``view = (r0, c0, rows, cols)`` of a buffer
    stored in the PERSISTENT symmetric tile-cyclic layout V = X[perm][:, perm]
    (perm = tile_cyclic_perm(p, d, tile)) — without un-permuting.

    The layout is d contiguous device chunks per axis, chunk s holding
    original tiles ≡ s (mod d) ascending; a window aligned to d*tile is a
    CONTIGUOUS slice of every chunk, so extraction is reshape + static slice
    (shard-local under P('x','y'): the sliced axes are the unsharded
    within-chunk ones).  The result is itself in window-local tile-cyclic
    layout on both axes, and that local perm depends only on (extent, d,
    tile) — never on the offset — which is what lets every aligned window of
    the recursion interoperate (models/cholesky.py threads whole factors
    through this)."""
    r0, c0, rows, cols = view
    p, pc = V.shape
    g = d * tile
    if r0 % g or c0 % g or rows % g or cols % g or p % g or pc % g:
        raise ValueError(
            f"cyclic_window: view {view} of {(p, pc)} must align to "
            f"d*tile = {g}"
        )
    W = V.reshape(d, p // g, tile, pc)[:, r0 // g : (r0 + rows) // g]
    W = W.reshape(rows, pc)
    W = W.reshape(rows, d, pc // g, tile)[:, :, c0 // g : (c0 + cols) // g]
    return W.reshape(rows, cols)


def cyclic_window_update(
    V: jnp.ndarray, W: jnp.ndarray, view, d: int, tile: int
) -> jnp.ndarray:
    """Write a window-local tile-cyclic result W back into the window `view`
    of the persistent-layout buffer V (inverse of cyclic_window; V is
    consumed).  Touches only the window's chunk slices — the read-modify-
    write is band-sized, not buffer-sized (the whole-buffer dus round-trip
    this layout exists to remove)."""
    r0, c0, rows, cols = view
    p, pc = V.shape
    g = d * tile
    if r0 % g or c0 % g or rows % g or cols % g or p % g or pc % g:
        raise ValueError(
            f"cyclic_window_update: view {view} of {(p, pc)} must align to "
            f"d*tile = {g}"
        )
    a, b = r0 // g, (r0 + rows) // g
    e, f = c0 // g, (c0 + cols) // g
    V4 = V.reshape(d, p // g, tile, pc)
    band = V4[:, a:b].reshape(rows, pc)
    band = (
        band.reshape(rows, d, pc // g, tile)
        .at[:, :, e:f]
        .set(W.astype(V.dtype).reshape(rows, d, f - e, tile))
        .reshape(rows, pc)
    )
    return V4.at[:, a:b].set(band.reshape(d, b - a, tile, pc)).reshape(p, pc)


def _pick_cyclic_tile(grid: Grid, dim: int, override: int) -> int:
    """The ONE tile auto-pick + eligibility rule for balance='tile_cyclic'
    (trmm rows / syrk output): ~4 local tiles per device unless overridden;
    returns 0 when the topology/shape cannot take the cyclic schedule
    (c==1 square faces with d>1, tile tiling the global dim)."""
    d = grid.dx
    tile = override
    if tile == 0 and d > 1:
        base = dim // d // 4
        if dim // d >= 128:
            # MXU granularity: the schedule's skipping premise is whole
            # 128-aligned tiles, so the auto-pick must be a 128 multiple
            # (ragged sub-128 row slices waste the MXU and misalign the
            # cost model's granularity).  Search DOWN from ~4 tiles/device
            # for one that tiles the dim, and require more tiles than
            # devices — at nt == d the "cyclic" permutation is the
            # identity: zero balancing but two priced row-shuffles.
            t = max(base // 128 * 128, 128)
            while t >= 128 and (dim % (d * t) or dim // t <= d):
                t -= 128
            if t >= 128:
                tile = t
        elif base > 0 and (dim // d) % 4 == 0:
            # sub-MXU shapes (CPU-mesh tests, tiny problems): alignment is
            # moot; keep the 4-tiles-per-device heuristic
            tile = base
    ok = (
        grid.c == 1
        and grid.dx == grid.dy
        and d > 1
        and tile > 0
        and dim % (d * tile) == 0
    )
    return tile if ok else 0


def tri_fractions(
    grid: Grid,
    M: int,
    K: int,
    N: int,
    a_uplo: str | None = None,
    b_uplo: str | None = None,
    out_uplo: str | None = None,
    cyclic_rows: int = 0,
    cyclic_out: int = 0,
) -> tuple[float, float]:
    """(mean_frac, max_frac) of the dense per-device contraction that the
    explicit schedule actually EXECUTES under dead-segment/dead-output
    skipping, by enumerating the same liveness predicates the schedule
    compiles in (the functions above — one source of truth).

    mean = volumetric view; max = the critical-path device.  With block
    distribution a triangular operand leaves the fullest block row
    executing every segment (max_frac = 1.0) while the emptiest runs ~1/d
    — the load imbalance the reference's element-cyclic distribution
    (structure.hpp:80-85) avoids by construction.  cyclic_rows models the
    tile-cyclic balanced schedule instead (balance='tile_cyclic' on trmm):
    per-row-tile skipping makes max ≈ mean.  Used for the
    flops_vol/flops_max columns of the cost model (VERDICT r2 #4)."""
    d, c = grid.dx, grid.c
    if grid.num_devices == 1 or (a_uplo is None and b_uplo is None and out_uplo is None):
        return 1.0, 1.0
    if grid.dy != d or d % max(1, c) or M % d or K % d or N % d:
        return 1.0, 1.0  # shapes the explicit schedule would reject: dense model
    q = max(1, grid.num_chunks)
    lk = K // d
    if lk % q:
        return 1.0, 1.0
    w = lk // q
    mb, nb = M // d, N // d
    spl = d // c
    if cyclic_rows:
        # balanced schedule: per (local row-tile, segment, chunk) liveness
        # against the ORIGINAL tile index g = t*d + xi — same predicate as
        # the compiled schedule (_seg_live_a_global at tile granularity)
        tile = cyclic_rows
        if c != 1 or a_uplo is None or tile > mb or mb % tile:
            return 1.0, 1.0  # shapes the cyclic schedule would reject
        ntl = mb // tile
        fracs = []
        for xi in range(d):
            live = 0
            for t in range(ntl):
                g = t * d + xi
                for s in range(d):
                    for ch in range(q):
                        live += bool(
                            _seg_live_a_global(g, s, ch, tile, lk, w, a_uplo)
                        )
            fracs.append(live / (ntl * d * q))
        return sum(fracs) / len(fracs), max(fracs)
    if cyclic_out:
        # balanced tri-output (syrk): per local output TILE PAIR liveness
        # against original tile indices (gi, gj) — same predicate as the
        # compiled cyclic_out schedule
        tile = cyclic_out
        if (
            c != 1 or out_uplo is None or a_uplo is not None
            or b_uplo is not None or M != N or mb % tile
        ):
            return 1.0, 1.0
        ntl = mb // tile
        fracs = []
        for xi in range(d):
            for yi in range(d):
                live = sum(
                    (ti * d + xi <= tj * d + yi)
                    if out_uplo == "U"
                    else (ti * d + xi >= tj * d + yi)
                    for ti in range(ntl)
                    for tj in range(ntl)
                )
                fracs.append(live / (ntl * ntl))
        return sum(fracs) / len(fracs), max(fracs)
    fracs = []
    for zi in range(c):
        segs = (
            range(d) if c == 1 else [zi * spl + i for i in range(spl)]
        )
        denom = len(segs) * q
        for xi in range(d):
            for yi in range(d):
                if out_uplo is not None:
                    o_live = (
                        xi * mb < (yi + 1) * nb
                        if out_uplo == "U"
                        else (xi + 1) * mb - 1 >= yi * nb
                    )
                    if not o_live:
                        fracs.append(0.0)
                        continue
                live = 0
                for s in segs:
                    for ch in range(q):
                        la = (
                            _seg_live_a_global(xi, s, ch, mb, lk, w, a_uplo)
                            if a_uplo is not None
                            else True
                        )
                        lb = (
                            _seg_live_b_global(yi, s, ch, nb, lk, w, b_uplo)
                            if b_uplo is not None
                            else True
                        )
                        live += bool(la and lb)
                fracs.append(live / denom)
    return sum(fracs) / len(fracs), max(fracs)


def _shard_kernels_gate(
    grid: Grid,
    M: int,
    K: int,
    N: int,
    a_uplo: str | None,
    b_uplo: str | None,
    out_uplo: str | None,
    cyclic_rows: int = 0,
    cyclic_out: int = 0,
) -> bool:
    """Does the explicit schedule route its local compute through the
    live-tile Mosaic kernels per shard?  (round 5 — d == 1 grids with
    128-aligned blocks and static liveness; see _explicit_matmul.)  ONE
    predicate shared by the router and the cost model, so the executed
    view (flops_vol/flops_max) prices the tile skipping exactly when it
    happens."""
    d, c = grid.dx, grid.c
    q = max(1, grid.num_chunks)
    structured = (
        a_uplo is not None or b_uplo is not None or out_uplo is not None
    )
    if not (structured and d == 1 and grid.dy == 1 and c == 1 and q == 1):
        return False
    if cyclic_rows or cyclic_out:
        return False
    if M % d or K % d or N % d:
        return False
    mb, nb, lk = M // d, N // d, K // d
    return mb % 128 == 0 and nb % 128 == 0 and lk % 128 == 0


def _sched_blocks(mb: int, K: int, nb: int) -> tuple[int, int, int]:
    """(bm, bk, bn) tile sizes for the runtime-scheduled route: the largest
    of 512/256/128 dividing the extent AND leaving >= 4 tiles (skipping
    granularity — a single whole-extent tile can never be skipped), else
    the SMALLEST divisor (maximum granularity), else 0 (cannot tile)."""

    def pick(x: int) -> int:
        for b in (512, 256, 128):
            if x % b == 0 and x // b >= 4:
                return b
        for b in (128, 256, 512):
            if x % b == 0:
                return b
        return 0

    return pick(mb), pick(K), pick(nb)


def _sched_pairs(grid, M, K, N, a_uplo, b_uplo):
    """Per-device tile schedules for the d > 1 scheduled-kernel trmm route
    (round 5): (TO, KO, FI, LA) int32 arrays of shape (d, L) — device i's
    live (tile, k-tile) pairs, padded to the maximum by repeating the last
    pair with first=last=0 (safe no-ops, pallas_tpu.sched_matmul) — plus
    the executed fraction L/(nt*nk) and the block sizes.  None when the
    shapes cannot tile.  Every device runs L steps (SPMD lockstep makes
    the fullest device the wall time regardless), so the padded schedule
    costs nothing over the ideal."""
    import numpy as _np

    d = grid.dx
    mb, nb = M // d, N // d
    bm, bk, bn = _sched_blocks(mb, K, nb)
    if not (bm and bk and bn):
        return None
    uplo = a_uplo if a_uplo is not None else b_uplo
    a_side = a_uplo is not None
    bt = bm if a_side else bn
    nt, nk = (mb if a_side else nb) // bt, K // bk
    per_dev = []
    for xi in range(d):
        pairs = []
        for t in range(nt):
            r0 = xi * (mb if a_side else nb) + t * bt
            for k in range(nk):
                c0 = k * bk
                if a_side:
                    # A (M, K) triangular: row-tile origin r0, K origin c0
                    live = (c0 < r0 + bt) if uplo == "L" else (c0 + bk > r0)
                else:
                    # B (K, N) triangular: K origin c0 (rows), col origin r0
                    live = (c0 + bk > r0) if uplo == "L" else (c0 < r0 + bt)
                if live:
                    pairs.append((t, k))
        if not pairs:
            return None
        per_dev.append(pairs)
    L = max(len(p) for p in per_dev)
    TO = _np.zeros((d, L), _np.int32)
    KO = _np.zeros((d, L), _np.int32)
    FI = _np.zeros((d, L), _np.int32)
    LA = _np.zeros((d, L), _np.int32)
    for xi, pairs in enumerate(per_dev):
        for idx, (t, k) in enumerate(pairs):
            TO[xi, idx], KO[xi, idx] = t, k
            FI[xi, idx] = 1 if idx == 0 or pairs[idx - 1][0] != t else 0
            LA[xi, idx] = (
                1 if idx == len(pairs) - 1 or pairs[idx + 1][0] != t else 0
            )
        TO[xi, len(pairs):], KO[xi, len(pairs):] = pairs[-1]
    frac = L / float(nt * nk)
    if frac >= 1.0:
        # nothing skippable at this tiling (e.g. a single whole-extent
        # tile): the kernel adds bookkeeping over the segment loop for no
        # executed-flop win — stay on the segment path
        return None
    return (
        (jnp.asarray(TO), jnp.asarray(KO), jnp.asarray(FI), jnp.asarray(LA)),
        frac,
        (bm, bn, bk),
    )


def _sched_pairs_cyclic(grid, M, K, N, a_uplo, b_uplo, t):
    """_sched_pairs for the PERSISTENT tile-cyclic layout
    (balance='tile_cyclic_persistent'): the triangular operand's cyclic axis
    (rows for side L / cols for side R) AND the contraction axis are both
    stored in tile_cyclic_perm order, so liveness is evaluated at ORIGINAL
    tile indices — local storage tile j on device i is original tile j*d+i,
    and gathered storage K-tile kt (contributed by device kt // (K/(d*t)),
    slot kt mod that) is original K-tile (kt % nkc)*d + kt // nkc.  The
    tile size is pinned to the layout's t on the cyclic axes; the dense
    free axis picks the usual 512/256/128.  Under a cyclic K the interval
    segment predicates of the block schedule are simply WRONG (dead
    K-ranges are no longer contiguous), so there is no segment-skipping
    middle ground: callers fall back to a dense contraction on None."""
    import numpy as _np

    d = grid.dx
    a_side = a_uplo is not None
    uplo = a_uplo if a_side else b_uplo
    loc = M // d if a_side else N // d  # triangular/cyclic axis, local
    dense = N // d if a_side else M // d  # dense free axis, local
    if loc % t or K % (d * t):
        return None
    bfree = next((b for b in (512, 256, 128) if dense % b == 0), dense)
    ntl, nkc = loc // t, K // (d * t)
    nkt = d * nkc
    per_dev = []
    for xi in range(d):
        pairs = []
        for j in range(ntl):
            g = j * d + xi  # original tile on the cyclic output axis
            for kt in range(nkt):
                gk = (kt % nkc) * d + kt // nkc  # original K tile
                if a_side:
                    # A (M, K) triangular: U keeps cols >= rows
                    live = gk >= g if uplo == "U" else gk <= g
                else:
                    # B (K, N) triangular: U keeps rows <= cols
                    live = gk <= g if uplo == "U" else gk >= g
                if live:
                    pairs.append((j, kt))
        if not pairs:
            return None
        per_dev.append(pairs)
    L = max(len(p) for p in per_dev)
    TO = _np.zeros((d, L), _np.int32)
    KO = _np.zeros((d, L), _np.int32)
    FI = _np.zeros((d, L), _np.int32)
    LA = _np.zeros((d, L), _np.int32)
    for xi, pairs in enumerate(per_dev):
        for idx, (j, k) in enumerate(pairs):
            TO[xi, idx], KO[xi, idx] = j, k
            FI[xi, idx] = 1 if idx == 0 or pairs[idx - 1][0] != j else 0
            LA[xi, idx] = (
                1 if idx == len(pairs) - 1 or pairs[idx + 1][0] != j else 0
            )
        TO[xi, len(pairs):], KO[xi, len(pairs):] = pairs[-1]
    # padded lockstep like _sched_pairs; the cyclic layout makes per-device
    # live counts near-equal, so L ~ the volumetric mean — max == mean is
    # the whole point of the persistent layout
    frac = L / float(ntl * nkt)
    blocks = (t, bfree, t) if a_side else (bfree, t, t)
    return (
        (jnp.asarray(TO), jnp.asarray(KO), jnp.asarray(FI), jnp.asarray(LA)),
        frac,
        blocks,
    )


def _shard_sched_gate(grid, M, K, N, a_uplo, b_uplo, out_uplo,
                      cyclic_rows=0, cyclic_out=0):
    """Does the d > 1 explicit schedule route through the runtime-scheduled
    per-shard kernels?  trmm shapes only (exactly one triangular operand);
    c == 1, unchunked, tileable.  Shared by the router and the cost model
    like _shard_kernels_gate."""
    d, c = grid.dx, grid.c
    q = max(1, grid.num_chunks)
    if not (d > 1 and grid.dy == d and c == 1 and q == 1):
        return None
    if (a_uplo is None) == (b_uplo is None) or out_uplo is not None:
        return None
    if cyclic_rows or cyclic_out:
        return None
    if M % d or K % d or N % d:
        return None
    return _sched_pairs(grid, M, K, N, a_uplo, b_uplo)


def _explicit_matmul(
    grid: Grid,
    A: jnp.ndarray,
    B: jnp.ndarray,
    precision: str | None = None,
    a_uplo: str | None = None,
    b_uplo: str | None = None,
    out_uplo: str | None = None,
    cyclic_rows: int = 0,
    cyclic_out: int = 0,
    sched=None,
) -> jnp.ndarray:
    """C = A @ B with the explicit SUMMA schedule on the d x d x c grid.
    `sched` forwards _matmul's already-built device schedule (the cost
    model evaluates the same gate; building the O(d·nt·nk) arrays twice
    per trace would be pure waste) — direct callers may omit it.

    Schedule (the reference's distribute/compute/collect, summa.hpp:177-249,
    re-expressed with the collectives TPU SPMD actually has):

      c == 1:  a_row = all_gather(A block, 'y')   # the d per-step row-comm
               b_col = all_gather(B block, 'x')   # Bcasts of summa.hpp:185-193
               acc  += a_row @ b_col               # amortized into one ring
                                                   # gather per operand: same
                                                   # (d-1)/d * bytes as d ring
                                                   # bcasts, 1 collective vs d
      c  > 1:  for each of this layer's d/c K-steps:
                 a_panel = psum(mask(y == k, A chunk), 'y')  # root bcast as
                 b_panel = psum(mask(x == k, B chunk), 'x')  # masked psum
                 acc += a_panel @ b_panel
               # per-step bcasts move only the layer's 1/c of the panels —
               # the 2.5D comm saving (topology.h:76-78); an amortized
               # full-row gather here would pay c/2 x the bytes (masked psum
               # costs 2x a ring bcast per panel, but c x fewer panels move).
      C = psum(acc, 'z')                  # depth collect (summa.hpp:236)

    (A true per-step one-to-many broadcast has no native SPMD primitive, so
    the two encodings above trade bytes against synchronization: the
    amortized gather is ring-bcast-byte-optimal and wins whenever a layer
    needs every panel (c == 1, and ties at c == 2); the masked psum pays 2x
    per moved panel but scales with the depth split.  tracing.gemm_cost
    prices whichever this function emits.)

    K-segments are assigned to depth layers contiguously — layer z owns
    segments [z*d/c, (z+1)*d/c).

    With grid.num_chunks = q > 1 both gathers and the depth collect are
    split into q independent slices — the reference's Ibcast/Iallreduce
    pipeline (summa.hpp:196-215, 239-248): each slice is a separate
    collective the latency-hiding scheduler can overlap with the previous
    slice's local matmul, and peak memory for the gathered row/col drops by
    q.  The chunk loop is unrolled at trace time (static shapes).

    Triangular structure (the distributed dead-block saving, reference
    summa.hpp:47-161 via local BLAS trmm/syrk):
      a_uplo/b_uplo — the operand *as passed* is upper/lower triangular
          (already masked by the caller); K-segments entirely inside its
          dead triangle for this device's block row/column are skipped with
          lax.cond, so the dead half of a distributed trmm never reaches
          the MXU.  Volumetric flops drop ~2x; note the *critical path* is
          still the fullest block row (block distribution does not load-
          balance a triangle the way the reference's element-cyclic layout
          does — that rebalancing is a layout choice, not a schedule one).
      out_uplo — only that triangle of C is needed: devices whose C block
          is entirely dead skip all local compute (syrk's saving; the
          caller symmetrizes or reads the live triangle only).

    Local accumulation is f32 for sub-f32 inputs (the pallas kernels'
    accumulator discipline); each layer's partial is cast back to the wire
    dtype before the depth psum, so collect bytes match the operand dtype.
    """
    d, c = grid.dx, grid.c
    if grid.dy != d:
        raise ValueError("explicit SUMMA requires a square grid face")
    if d % c != 0:
        raise ValueError(f"depth c={c} must divide face d={d}")
    (M, K), (K2, N) = A.shape, B.shape
    if K != K2:
        raise ValueError(f"inner dims mismatch: {A.shape} @ {B.shape}")
    if M % d or K % d or N % d:
        raise ValueError(f"global dims {(M, K, N)} must be divisible by d={d}")

    if cyclic_rows:
        # tile-cyclic row balance: A's rows (and the output's) are in
        # tile_cyclic_perm order — local row-tile t on device xi is
        # ORIGINAL tile t*d + xi, and per-(tile, segment) liveness is
        # tested against the original index, so every device carries an
        # equal share of the triangle's live work (max-per-process ==
        # volumetric, vs 1.0 under contiguous blocks — see tri_fractions)
        if c != 1 or a_uplo is None or b_uplo is not None or out_uplo is not None:
            raise ValueError(
                "cyclic_rows supports the c==1 triangular-A (side-L trmm) "
                "schedule only"
            )
        if (M // d) % cyclic_rows:
            raise ValueError(
                f"cyclic tile {cyclic_rows} must divide the local rows {M // d}"
            )
    if cyclic_out:
        # tile-cyclic SYMMETRIC-output balance (syrk): BOTH output axes are
        # in tile_cyclic_perm order (C_p = A_pᵀA_p with A's columns
        # permuted), so local output tile (ti, tj) on device (xi, yi) is
        # ORIGINAL tile pair (ti*d + xi, tj*d + yi) and the dead-triangle
        # skip tests original indices — every device carries ~half the
        # tile pairs regardless of position
        if c != 1 or out_uplo is None or a_uplo is not None or b_uplo is not None:
            raise ValueError(
                "cyclic_out supports the c==1 tri-output (syrk) schedule only"
            )
        if (M // d) % cyclic_out or (N // d) % cyclic_out or M != N:
            raise ValueError(
                f"cyclic_out tile {cyclic_out} must tile the square local "
                f"block {(M // d, N // d)}"
            )

    spl = d // c  # K-segments owned by each depth layer
    q = max(1, grid.num_chunks)
    lk = K // d  # local K extent (A cols = B rows per device)
    if lk % q:
        raise ValueError(f"num_chunks={q} must divide the local K extent {lk}")
    w = lk // q  # K-slice width per chunk, per segment
    mb, nb = M // d, N // d
    wire_dtype = jnp.result_type(A, B)
    acc_dtype = jnp.promote_types(wire_dtype, jnp.float32)

    def _seg_live_a(xi, s, ch):
        return _seg_live_a_global(xi, s, ch, mb, lk, w, a_uplo)

    def _seg_live_b(yi, s, ch):
        return _seg_live_b_global(yi, s, ch, nb, lk, w, b_uplo)

    solo = getattr(grid, "collective_concurrency", "free") == "solo"

    # round 5 (VERDICT r4 #2, second half): route the LOCAL compute of the
    # explicit schedule through the live-tile Mosaic kernels per shard —
    # the reference's per-rank BLAS trmm/syrk saving at tile granularity
    # (blas/interface.hpp:74-97) instead of K-segment granularity.  Inside
    # shard_map the partitioning is manual, so the single-device kernels
    # compile unchanged (the fused-CQR2 finding).  First increment: d == 1
    # grids, where liveness is static — this is exactly the configuration
    # that prices the mesh machinery's overhead (the DISTRIBUTED.md
    # single-chip constant), and tile skipping removes its 2x flop
    # penalty.  d > 1 needs runtime (device-indexed) schedules and stays
    # on the K-segment path.  check_vma is disabled on this route: the
    # kernels' out_shapes carry no varying-axes annotation, and the
    # guarded-zeros vma logic is never reached.
    shard_kernels = _shard_kernels_gate(
        grid, M, K, N, a_uplo, b_uplo, out_uplo, cyclic_rows, cyclic_out
    )
    if shard_kernels:
        tracing.note("explicit::shard_kernels")
        sched = None
    elif sched is None:  # direct callers: build what _matmul forwards
        sched = _shard_sched_gate(
            grid, M, K, N, a_uplo, b_uplo, out_uplo, cyclic_rows, cyclic_out
        )
    if sched is not None:
        tracing.note("explicit::shard_sched")

    def kernel(a, b):
        # a: (M/d, K/d) block at (x, y);  b: (K/d, N/d) block at (x, y)
        xi = lax.axis_index("x")
        yi = lax.axis_index("y")
        zi = lax.axis_index("z")

        # collective_concurrency='solo' (Grid knob — the reference's
        # COLLECTIVE_CONCURRENCY_SOLO congestion experiment,
        # summa.hpp:179-192): chain every collective behind the previous
        # one with an optimization_barrier data dependency, so at most one
        # is in flight.  `chain` threads a token value through each
        # collective's INPUT; 'free' mode is the identity.
        token = [None]

        def chain(x):
            if not solo:
                return x
            if token[0] is not None:
                x, _ = lax.optimization_barrier((x, token[0]))
            return x

        def stamp(res):
            if solo:
                # tie the token to one element (cheap; keeps the barrier
                # operand small and the dependency real)
                token[0] = lax.slice(res.reshape(-1), (0,), (1,))
            return res

        if shard_kernels:
            a_ch = stamp(lax.all_gather(chain(a), "y", axis=1, tiled=True))
            b_ch = stamp(lax.all_gather(chain(b), "x", axis=0, tiled=True))
            if out_uplo is not None:
                part = pallas_tpu.tri_matmul(
                    a_ch, b_ch, out_uplo=out_uplo, precision=precision
                )
            else:
                part = pallas_tpu.tri_matmul(
                    a_ch, b_ch, a_uplo=a_uplo, b_uplo=b_uplo,
                    precision=precision,
                )
            return part.astype(wire_dtype)
        if sched is not None:
            # d > 1: each device selects ITS OWN tile schedule by mesh
            # position and runs the scheduled kernel on the gathered slabs
            (TO, KO, FI, LA), _, blocks = sched
            a_ch = stamp(lax.all_gather(chain(a), "y", axis=1, tiled=True))
            b_ch = stamp(lax.all_gather(chain(b), "x", axis=0, tiled=True))
            sel = xi if a_uplo is not None else yi
            part = pallas_tpu.sched_matmul(
                a_ch, b_ch,
                jnp.take(TO, sel, axis=0), jnp.take(KO, sel, axis=0),
                jnp.take(FI, sel, axis=0), jnp.take(LA, sel, axis=0),
                tri_side="a" if a_uplo is not None else "b",
                blocks=blocks, precision=precision,
            )
            return part.astype(wire_dtype)

        # every liveness test guards ONLY local matmuls, never a collective:
        # the gathers run unconditionally on all devices (a collective under
        # a device-varying cond would desynchronize the mesh)
        out_live = None
        if out_uplo is not None:
            out_live = (
                xi * mb < (yi + 1) * nb
                if out_uplo == "U"
                else (xi + 1) * mb - 1 >= yi * nb
            )

        def guarded(live, mm, *operands, shape=None):
            if live is None:
                return mm()
            # the zero branch must carry the same varying-manual-axes type as
            # the matmul branch (cond requires equal output types under
            # shard_map's replication checking): mark it varying over the
            # union of the operands' axes
            vma: set = set()
            for r in operands:
                vma |= jax_compat.vma_of(r)
            zeros = jnp.zeros(shape or (mb, nb), dtype=acc_dtype)
            if vma:
                zeros = jax_compat.pcast(zeros, tuple(sorted(vma)), to="varying")
            return lax.cond(live, mm, lambda: zeros)

        def matmul_term(live, a_op, b_op):
            return guarded(
                live,
                lambda: jnp.matmul(
                    a_op, b_op, precision=precision,
                    preferred_element_type=acc_dtype,
                ),
                a_op, b_op,
            )

        acc = jnp.zeros((mb, nb), dtype=acc_dtype)
        if c == 1:
            for ch in range(q):
                # gathered chunk: segment-major — segment s holds global
                # K-range [s*lk + ch*w, +w), contributed by device s of the
                # gather axis; A's and B's segment decompositions of K match
                # because the face is square
                a_ch = stamp(lax.all_gather(
                    chain(a[:, ch * w : (ch + 1) * w]), "y", axis=1, tiled=True
                ))
                b_ch = stamp(lax.all_gather(
                    chain(b[ch * w : (ch + 1) * w, :]), "x", axis=0, tiled=True
                ))
                if cyclic_out:
                    # balanced tri-output skipping: per LOCAL OUTPUT TILE
                    # PAIR — original tile pair (gi, gj) is live iff it
                    # touches the stored triangle of the UN-permuted C
                    T = cyclic_out
                    for ti in range(mb // T):
                        gi = ti * d + xi
                        a_t = lax.slice_in_dim(a_ch, ti * T, (ti + 1) * T, axis=0)
                        for tj in range(nb // T):
                            gj = tj * d + yi
                            live = gi <= gj if out_uplo == "U" else gi >= gj
                            tile_mm = guarded(
                                live,
                                lambda a_=a_t, tj_=tj: jnp.matmul(
                                    a_,
                                    lax.slice_in_dim(
                                        b_ch, tj_ * T, (tj_ + 1) * T, axis=1
                                    ),
                                    precision=precision,
                                    preferred_element_type=acc_dtype,
                                ),
                                a_t, b_ch,
                                shape=(T, T),
                            )
                            acc = acc.at[
                                ti * T : (ti + 1) * T, tj * T : (tj + 1) * T
                            ].add(tile_mm)
                elif a_uplo is None and b_uplo is None:
                    acc = acc + matmul_term(out_live, a_ch, b_ch)
                elif cyclic_rows:
                    # balanced skipping: per LOCAL ROW-TILE x segment —
                    # each tile row-band contracts only the K-segments
                    # intersecting its ORIGINAL tile's live range (the
                    # SAME predicate as block mode, applied at tile
                    # granularity with the original tile index g)
                    tile = cyclic_rows
                    for t in range(mb // tile):
                        g = t * d + xi  # traced original row-tile index
                        a_t = lax.slice_in_dim(
                            a_ch, t * tile, (t + 1) * tile, axis=0
                        )
                        for s in range(d):
                            live = _seg_live_a_global(
                                g, s, ch, tile, lk, w, a_uplo
                            )
                            a_ts = lax.slice_in_dim(
                                a_t, s * w, (s + 1) * w, axis=1
                            )
                            b_s = lax.slice_in_dim(
                                b_ch, s * w, (s + 1) * w, axis=0
                            )
                            band = guarded(
                                live,
                                lambda a_=a_ts, b_=b_s: jnp.matmul(
                                    a_, b_, precision=precision,
                                    preferred_element_type=acc_dtype,
                                ),
                                a_ts, b_s,
                                shape=(tile, nb),
                            )
                            acc = acc.at[t * tile : (t + 1) * tile].add(band)
                else:
                    # triangular operand: per-segment liveness — dead
                    # segments never reach the MXU (summa.hpp:47-161's
                    # saving, at K-segment granularity)
                    for s in range(d):
                        a_s = lax.slice_in_dim(
                            a_ch, s * w, (s + 1) * w, axis=1
                        )
                        b_s = lax.slice_in_dim(
                            b_ch, s * w, (s + 1) * w, axis=0
                        )
                        live = None
                        if a_uplo is not None:
                            live = _seg_live_a(xi, s, ch)
                        if b_uplo is not None:
                            lb = _seg_live_b(yi, s, ch)
                            live = lb if live is None else jnp.logical_and(live, lb)
                        if out_live is not None:
                            live = (
                                out_live
                                if live is None
                                else jnp.logical_and(live, out_live)
                            )
                        acc = acc + matmul_term(live, a_s, b_s)
        else:
            # per-step masked-psum broadcast of this layer's own d/c panels
            # (the 2.5D comm saving); the liveness conds still skip the
            # matmul of dead panels, but the bcast itself is unconditional
            for i in range(spl):
                k = zi * spl + i  # traced: the layer's i-th global K-step
                for ch in range(q):
                    a_sl = a[:, ch * w : (ch + 1) * w]
                    b_sl = b[ch * w : (ch + 1) * w, :]
                    a_panel = stamp(lax.psum(
                        chain(jnp.where(yi == k, a_sl, jnp.zeros_like(a_sl))), "y"
                    ))
                    b_panel = stamp(lax.psum(
                        chain(jnp.where(xi == k, b_sl, jnp.zeros_like(b_sl))), "x"
                    ))
                    live = None
                    if a_uplo is not None:
                        live = _seg_live_a(xi, k, ch)
                    if b_uplo is not None:
                        lb = _seg_live_b(yi, k, ch)
                        live = lb if live is None else jnp.logical_and(live, lb)
                    if out_live is not None:
                        live = (
                            out_live
                            if live is None
                            else jnp.logical_and(live, out_live)
                        )
                    acc = acc + matmul_term(live, a_panel, b_panel)

        part = acc.astype(wire_dtype)  # collect in the wire dtype
        if c == 1:
            return part
        # chunked depth collect (the reference's Iallreduce slices,
        # summa.hpp:239-248): q independent psums over column slices —
        # uneven widths when q does not divide the block; zero-width tails
        # (q > nb) are skipped, so min(q, nb) psums are emitted, which is
        # what tracing.gemm_cost counts
        widths = [nb // q + (1 if j < nb % q else 0) for j in range(q)]
        pieces, off = [], 0
        for wd in widths:
            if wd:
                pieces.append(stamp(lax.psum(chain(part[:, off : off + wd]), "z")))
                off += wd
        return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces, axis=1)

    return jax_compat.shard_map(
        kernel,
        mesh=grid.mesh,
        in_specs=(P("x", "y"), P("x", "y")),
        out_specs=P("x", "y"),
        check_vma=not (shard_kernels or sched is not None),
    )(grid.pin(A), grid.pin(B))


# --------------------------------------------------------------------------
# public ops
# --------------------------------------------------------------------------


def _matmul(
    grid: Grid,
    A: jnp.ndarray,
    B: jnp.ndarray,
    mode: str,
    precision: str | None = None,
    a_uplo: str | None = None,
    b_uplo: str | None = None,
    out_uplo: str | None = None,
    cyclic_rows: int = 0,
    cyclic_out: int = 0,
    sched_override=None,
) -> jnp.ndarray:
    """The uplo flags describe triangular structure of the (already masked)
    operands/result; only mode='explicit' exploits them (dead K-segments /
    dead output blocks skipped per device).  The homogeneous model count
    (`flops`) stays dense; the executed views carry the skipping:
    flops_vol (mean over devices) and flops_max (the critical-path device,
    which with block distribution still runs up to the full contraction —
    see tri_fractions).  sched_override hands in an externally built
    per-device tile schedule (_sched_pairs_cyclic — the persistent layout,
    whose liveness the gates here cannot derive from shapes alone)."""
    # cost-model attribution (no-op without an active tracing.Recorder)
    M, K, N = A.shape[0], A.shape[1], B.shape[1]
    flops, comm, ncoll = tracing.gemm_cost(
        grid, M, N, K, jnp.result_type(A, B)
    )
    if mode == "explicit":
        sched = None
        if sched_override is not None:
            sched = sched_override
            mean_f = max_f = sched[1]
        elif _shard_kernels_gate(
            grid, M, K, N, a_uplo, b_uplo, out_uplo, cyclic_rows, cyclic_out
        ):
            # per-shard live-tile kernels: same /2 executed convention as
            # the single-device pallas branches (tile skipping)
            mean_f = max_f = 0.5
        elif (
            sched := _shard_sched_gate(
                grid, M, K, N, a_uplo, b_uplo, out_uplo, cyclic_rows,
                cyclic_out,
            )
        ) is not None:
            # runtime-scheduled per-shard kernels: every device runs the
            # padded maximum schedule, so mean == max == L/(nt*nk)
            mean_f = max_f = sched[1]
        else:
            mean_f, max_f = tri_fractions(
                grid, M, K, N, a_uplo, b_uplo, out_uplo,
                cyclic_rows=cyclic_rows, cyclic_out=cyclic_out,
            )
    else:
        mean_f = max_f = 1.0  # dense+mask executes the full contraction
    tracing.emit(
        flops=flops, comm_bytes=comm, collectives=ncoll,
        flops_vol=flops * mean_f, flops_max=flops * max_f,
    )
    if mode in ("xla", "pallas"):  # gemm has no dead blocks: XLA is optimal
        return grid.pin(jnp.matmul(grid.pin(A), grid.pin(B), precision=precision))
    if mode == "explicit":
        return _explicit_matmul(
            grid, A, B, precision, a_uplo, b_uplo, out_uplo, cyclic_rows,
            cyclic_out, sched=sched,
        )
    raise ValueError(f"unknown summa mode {mode!r}")


@pallas_tpu.scoped_by_grid
def gemm(
    grid: Grid,
    A: jnp.ndarray,
    B: jnp.ndarray,
    C: jnp.ndarray | None = None,
    args: GemmArgs = GemmArgs(),
    mode: str = "xla",
) -> jnp.ndarray:
    """C = alpha * op(A) @ op(B) + beta * C  (reference summa.hpp:7-44)."""
    Aop = A.T if args.trans_a else A
    Bop = B.T if args.trans_b else B
    if args.beta != 0.0 and C is None:
        raise ValueError("beta != 0 requires the accumulate operand C")
    out = _matmul(grid, Aop, Bop, mode, args.precision)
    if args.alpha != 1.0:
        out = args.alpha * out
    if args.beta != 0.0:
        out = out + args.beta * grid.pin(C)
    return grid.pin(out)


def _take_view(X, view):
    if X is None or view is None:
        return X
    return pallas_tpu._window(X, view)


def _i32_off(off):
    # i32 start indices for dynamic_update_slice on sharded buffers: under
    # x64 a Python-int index lowers as s64 and the 0.4.x SPMD partitioner
    # compares it against its own s32 shard offsets (hlo-verifier rejection)
    return tuple(jnp.int32(o) for o in off)


def _persistent_params(grid: Grid, mode: str, cyclic_tile: int, who: str):
    """Validate a balance='tile_cyclic_persistent' call.  Unlike
    'tile_cyclic' (a schedule preference with a benign block fallback),
    'persistent' is a STORAGE contract: the caller asserts the passed
    buffers are in the symmetric tile-cyclic layout, so any silent fallback
    would read them as block-ordered and compute garbage — violations
    raise."""
    d = grid.dx
    q = max(1, grid.num_chunks)
    if (
        mode != "explicit" or grid.c != 1 or grid.dy != d or d < 2
        or q != 1 or cyclic_tile < 1
    ):
        raise ValueError(
            f"{who}: balance='tile_cyclic_persistent' requires "
            "mode='explicit' on an unchunked c==1 square face with d>1 and "
            f"an explicit cyclic_tile >= 1 (the layout's tile); got "
            f"mode={mode!r}, grid {grid.dx}x{grid.dy}x{grid.c}, chunks={q}, "
            f"cyclic_tile={cyclic_tile}"
        )
    return d, cyclic_tile


def _copy_bytes_of(*terms) -> float:
    """Sum of (factor, array) HBM-copy prices: factor counts reads+writes
    of the moved array (2.0 = one read + one write)."""
    return float(
        sum(f * a.size * jnp.dtype(a.dtype).itemsize for f, a in terms)
    )


def _trmm_persistent(
    grid, A, B, args, mode, a_view, b_view, out, out_off, cyclic_tile
):
    """trmm where EVERY passed buffer is stored in the persistent symmetric
    tile-cyclic layout V = X[perm][:, perm] (models/cholesky.py's
    balance='tile_cyclic_persistent'): window reads are chunk-local
    reshapes (cyclic_window), the triangle mask tests original indices
    (masking.take_triangle_cyclic), liveness is scheduled per original
    tile (_sched_pairs_cyclic -> pallas_tpu.sched_matmul with the layout's
    tile), and the product emerges ALREADY in layout — zero per-call row
    shuffles, where balance='tile_cyclic' pays two per call."""
    d, t = _persistent_params(grid, mode, cyclic_tile, "trmm")
    if args.diag == "U":
        raise ValueError(
            "tile_cyclic_persistent trmm does not support diag='U'"
        )
    Aw = cyclic_window(A, a_view, d, t) if a_view is not None else A
    Bw = cyclic_window(B, b_view, d, t) if b_view is not None else B
    T = masking.take_triangle_cyclic(Aw, args.uplo, d, t)
    Top = T.T if args.trans_a else T
    eff_uplo = (
        args.uplo if not args.trans_a else ("L" if args.uplo == "U" else "U")
    )
    # residual data motion: the windows/mask/transpose still materialize,
    # but WINDOW-sized and shuffle-free — price it so the ledger separates
    # this residue from the full-buffer copies the layout removed
    cb = _copy_bytes_of((2.0, Aw))  # triangle mask
    if a_view is not None:
        cb += _copy_bytes_of((2.0, Aw))
    if args.trans_a:
        cb += _copy_bytes_of((2.0, Aw))
    if b_view is not None:
        cb += _copy_bytes_of((2.0, Bw))
    if args.side == "L":
        sched = _sched_pairs_cyclic(
            grid, Top.shape[0], Top.shape[1], Bw.shape[1], eff_uplo, None, t
        )
        if sched is None:
            tracing.note("trmm::persistent_dense")
            res = _matmul(grid, Top, Bw, mode, args.precision)
        else:
            tracing.note("trmm::persistent_cyclic")
            res = _matmul(
                grid, Top, Bw, mode, args.precision, a_uplo=eff_uplo,
                sched_override=sched,
            )
    elif args.side == "R":
        sched = _sched_pairs_cyclic(
            grid, Bw.shape[0], Bw.shape[1], Top.shape[1], None, eff_uplo, t
        )
        if sched is None:
            tracing.note("trmm::persistent_dense")
            res = _matmul(grid, Bw, Top, mode, args.precision)
        else:
            tracing.note("trmm::persistent_cyclic")
            res = _matmul(
                grid, Bw, Top, mode, args.precision, b_uplo=eff_uplo,
                sched_override=sched,
            )
    else:
        raise ValueError(f"side must be 'L' or 'R', got {args.side!r}")
    if args.alpha != 1.0:
        res = args.alpha * res
    if out is not None:
        # band-sized read-modify-write, not the whole-buffer dus round-trip
        cb += _copy_bytes_of((4.0, res))
        tracing.emit(copy_bytes=cb / grid.num_devices)
        view = (out_off[0], out_off[1], res.shape[0], res.shape[1])
        return grid.pin(cyclic_window_update(out, res, view, d, t))
    tracing.emit(copy_bytes=cb / grid.num_devices)
    return grid.pin(res)


def _syrk_persistent(grid, A, C, args, mode, a_view, c_view, in_place,
                     cyclic_tile):
    """syrk under the persistent layout: the cyclic_out schedule of
    _explicit_matmul IS window-local cyclic liveness (original tile pair
    (ti*d+xi, tj*d+yi)), so the balanced contraction runs unchanged — what
    disappears are the three per-call shuffles balance='tile_cyclic' pays
    (A's free axis in, both output axes out): operands arrive and the
    update leaves in layout.  Symmetrization is cyclic-aware — the live
    triangle sits at ORIGINAL indices (masking.take_triangle_cyclic), and
    transposing a both-axes-same-perm matrix stays in layout."""
    d, t = _persistent_params(grid, mode, cyclic_tile, "syrk")
    Aw = cyclic_window(A, a_view, d, t) if a_view is not None else A
    cb = _copy_bytes_of((2.0, Aw))  # the .T below
    if a_view is not None:
        cb += _copy_bytes_of((2.0, Aw))
    Aop = (Aw.T, Aw) if args.trans else (Aw, Aw.T)
    D = _matmul(
        grid, Aop[0], Aop[1], mode, args.precision, out_uplo=args.uplo,
        cyclic_out=t,
    )
    tracing.note("syrk::persistent_cyclic")
    live = masking.take_triangle_cyclic(D, args.uplo, d, t)
    strict = masking.take_triangle_cyclic(D, args.uplo, d, t, strict=True)
    out = live + transpose(grid, strict)
    cb += _copy_bytes_of((4.0, D))  # the two mask materializations
    if args.alpha != 1.0:
        out = args.alpha * out
    if args.beta != 0.0:
        Cw = cyclic_window(C, c_view, d, t) if c_view is not None else C
        out = out + args.beta * grid.pin(Cw)
        if c_view is not None:
            cb += _copy_bytes_of((2.0, Cw))
    if in_place:
        r0, c0 = (c_view[0], c_view[1]) if c_view is not None else (0, 0)
        cb += _copy_bytes_of((4.0, out))
        tracing.emit(copy_bytes=cb / grid.num_devices)
        view = (r0, c0, out.shape[0], out.shape[1])
        return grid.pin(cyclic_window_update(C, out, view, d, t))
    tracing.emit(copy_bytes=cb / grid.num_devices)
    return grid.pin(out)


@pallas_tpu.scoped_by_grid
def trmm(
    grid: Grid,
    A: jnp.ndarray,
    B: jnp.ndarray,
    args: TrmmArgs = TrmmArgs(),
    mode: str = "xla",
    *,
    a_view: tuple[int, int, int, int] | None = None,
    b_view: tuple[int, int, int, int] | None = None,
    out: jnp.ndarray | None = None,
    out_off: tuple[int, int] = (0, 0),
    balance: str = "block",
    cyclic_tile: int = 0,
) -> jnp.ndarray:
    """B <- alpha * op(tri(A)) @ B   (side L)   or   alpha * B @ op(tri(A))
    (side R) — reference summa.hpp:47-83.

    balance='tile_cyclic' (explicit mode, side L, c==1 square faces):
    rows are redistributed block-cyclically over MXU-sized tiles
    (tile_cyclic_perm) so every device executes an equal share of the
    triangle — the reference's element-cyclic load balancing
    (structure.hpp:80-85) at tile granularity, which keeps dead tiles
    whole and skippable.  The critical-path device drops from the full
    dense contraction to the volumetric mean (tri_fractions; max = mean).
    The standalone call pays two row-shuffles (permute the triangular
    operand in, un-permute the product out — priced into the cost model);
    an algorithm adopting the cyclic layout persistently pays them once.
    cyclic_tile overrides the auto-picked tile (local rows / 4).
    Unsupported combinations fall back to the block schedule with a
    tracing note.

    balance='tile_cyclic_persistent' (explicit mode, both sides): the
    caller asserts EVERY passed buffer — operands, `out`, and the views
    into them — is already stored in the symmetric tile-cyclic layout
    V = X[perm][:, perm] with tile `cyclic_tile` (models/cholesky.py
    permutes once per matrix lifetime).  Window reads become chunk-local
    reshapes (cyclic_window), liveness is scheduled per original tile, and
    the product emerges in layout: the two per-call shuffles of
    'tile_cyclic' and the whole-buffer dus round-trip disappear.  This is
    a storage contract, not a preference — unsupported topologies raise
    instead of falling back (a block-ordered read of a cyclic buffer would
    be garbage).

    The triangular operand is dense + masked; the mask fuses into the matmul
    (no packed storage — SURVEY §7.1).  mode='pallas' on a single-device
    grid skips the dead blocks on the MXU instead (ops/pallas_tpu.py).

    a_view/b_view select static windows of the passed buffers as the
    operands, and out/out_off writes the result into a window of `out`
    (returning the whole updated buffer).  On the single-device pallas path
    these compile to offset index maps / an in-place aliased write (no slice
    or scatter materialization, ops/pallas_tpu.py); every other path
    materializes the windows and a dynamic_update_slice — identical
    semantics, so callers can be written once against views (the recursion
    in models/cholesky.py is)."""
    a_dims = (a_view[2], a_view[3]) if a_view is not None else A.shape
    b_dims = (b_view[2], b_view[3]) if b_view is not None else B.shape
    if (
        mode in ("pallas", "explicit")
        and grid.num_devices == 1
        and args.diag != "U"
        and balance != "tile_cyclic_persistent"
    ):
        if balance == "tile_cyclic":
            # single-device kernels skip dead tiles directly; the balanced
            # schedule does not apply — honor the fallback-with-a-note
            # contract instead of silently dropping the request
            tracing.note("trmm::tile_cyclic_fallback")
        flops, comm, ncoll = tracing.gemm_cost(
            grid, b_dims[0], b_dims[1], a_dims[0], jnp.result_type(A, B)
        )
        if mode == "explicit":
            # copy-free d==1 route (the single-chip constant of the explicit
            # path, DISTRIBUTED.md): at one device every liveness predicate
            # is static, so the schedule the K-segment path would run is
            # exactly what the aliasing pallas kernels already execute —
            # minus the take_triangle copy, the window materializations and
            # the whole-buffer dus round-trip below.  Ride the kernels.
            # Cost convention follows explicit::shard_kernels: homogeneous
            # model count stays dense, executed views carry the /2.
            tracing.note("explicit::copy_free")
            tracing.emit(
                flops=flops, comm_bytes=comm, collectives=ncoll,
                flops_vol=flops / 2, flops_max=flops / 2,
            )
        else:
            tracing.emit(flops=flops / 2, comm_bytes=comm, collectives=ncoll)
        if args.side == "L":
            return pallas_tpu.tri_matmul(
                A, B, a_uplo=args.uplo, a_trans=args.trans_a,
                alpha=args.alpha, precision=args.precision,
                a_view=a_view, b_view=b_view, out=out, out_off=out_off,
            )
        elif args.side == "R":
            return pallas_tpu.tri_matmul(
                B, A, b_uplo=args.uplo, b_trans=args.trans_a,
                alpha=args.alpha, precision=args.precision,
                a_view=b_view, b_view=a_view, out=out, out_off=out_off,
            )
        raise ValueError(f"side must be 'L' or 'R', got {args.side!r}")
    if balance == "tile_cyclic_persistent":
        return _trmm_persistent(
            grid, A, B, args, mode, a_view, b_view, out, out_off, cyclic_tile
        )
    Aw = _take_view(A, a_view)
    Bw = _take_view(B, b_view)
    T = masking.take_triangle(Aw, args.uplo)
    if args.diag == "U":
        T = masking.with_unit_diagonal(T)
    Top = T.T if args.trans_a else T
    # structure of the operand *as passed* to the schedule: transposing a
    # triangular matrix flips its triangle — explicit mode uses this to skip
    # dead K-segments per device (summa.hpp:47-161's trmm saving)
    eff_uplo = (
        args.uplo if not args.trans_a else ("L" if args.uplo == "U" else "U")
    )
    res = None
    if balance == "tile_cyclic":
        M = Top.shape[0] if args.side == "L" else 0
        tile = (
            _pick_cyclic_tile(grid, M, cyclic_tile)
            if (mode == "explicit" and args.side == "L")
            else 0
        )
        if tile:
            perm, inv = tile_cyclic_perm(M, grid.dx, tile)
            # two row-shuffles priced like grid transposes (block
            # exchanges across the face): the M x M triangular operand in,
            # the M x N product out
            comm_a, nc_a = tracing.transpose_cost(grid, M, M, Top.dtype)
            comm_o, nc_o = tracing.transpose_cost(grid, M, Bw.shape[1], Top.dtype)
            tracing.emit(comm_bytes=comm_a + comm_o, collectives=nc_a + nc_o)
            res = _matmul(
                grid, grid.pin(Top[jnp.asarray(perm)]), Bw, mode,
                args.precision, a_uplo=eff_uplo, cyclic_rows=tile,
            )
            res = grid.pin(res[jnp.asarray(inv)])
        else:
            tracing.note("trmm::tile_cyclic_fallback")
    if res is None:
        if args.side == "L":
            res = _matmul(grid, Top, Bw, mode, args.precision, a_uplo=eff_uplo)
        elif args.side == "R":
            res = _matmul(grid, Bw, Top, mode, args.precision, b_uplo=eff_uplo)
        else:
            raise ValueError(f"side must be 'L' or 'R', got {args.side!r}")
    if args.alpha != 1.0:
        res = args.alpha * res
    # copy-bytes attribution of this materializing path (the term the
    # copy-free d==1 route and the persistent layout shrink): triangle mask,
    # window slices, transpose, and the write-back round-trip — each priced
    # as read + write of the moved array, per device
    cb = _copy_bytes_of((2.0, T))  # take_triangle
    if a_view is not None:
        cb += _copy_bytes_of((2.0, T))
    if args.diag == "U":
        cb += _copy_bytes_of((2.0, T))
    if args.trans_a:
        cb += _copy_bytes_of((2.0, T))
    if b_view is not None:
        cb += _copy_bytes_of((2.0, Bw))
    if out is not None:
        cb += _copy_bytes_of((2.0, out))  # whole-buffer dus round-trip
        tracing.emit(copy_bytes=cb / grid.num_devices)
        return grid.pin(
            lax.dynamic_update_slice(out, res.astype(out.dtype), _i32_off(out_off))
        )
    tracing.emit(copy_bytes=cb / grid.num_devices)
    return grid.pin(res)


@pallas_tpu.scoped_by_grid
def syrk(
    grid: Grid,
    A: jnp.ndarray,
    C: jnp.ndarray | None = None,
    args: SyrkArgs = SyrkArgs(),
    mode: str = "xla",
    *,
    a_view: tuple[int, int, int, int] | None = None,
    c_view: tuple[int, int, int, int] | None = None,
    in_place: bool = False,
    balance: str = "block",
    cyclic_tile: int = 0,
) -> jnp.ndarray:
    """Symmetric rank-k update (reference summa.hpp:86-161, which lowers syrk
    to an explicit grid transpose + gemm; here the transpose is a logical
    .T — XLA emits the collective-permute when resharding is needed).

    trans=False: C = alpha*A@Aᵀ + beta*C;  trans=True: C = alpha*Aᵀ@A + beta*C.
    In 'xla' mode (and 'explicit' on a mesh) the full dense symmetric
    result is computed (MXU-friendly); callers that need only a triangle
    mask the output.  mode='pallas' — and 'explicit' on a SINGLE-device
    grid, which rides the same copy-free kernels — instead honors
    args.uplo: only that triangle of the result is valid — with beta=0 the
    dead half is zeroed, with beta!=0 it is UNDEFINED (the fused in-kernel
    beta*C accumulate never visits dead tiles) — so callers must read only
    the args.uplo triangle (models/cholesky.py symmetrizes its base-case
    panel from 'U').

    balance='tile_cyclic_persistent': storage contract as in trmm — all
    buffers are in the symmetric tile-cyclic layout; the balanced
    cyclic_out contraction runs without the three per-call shuffles of
    'tile_cyclic', the symmetrize is cyclic-aware, and in_place writes
    back through cyclic_window_update (band-sized, not buffer-sized).

    in_place (requires beta != 0 and a c_view): the update is written back
    INTO the C buffer at the c_view window and the whole updated buffer is
    returned — the caller must treat the passed-in C value as consumed.
    On the pallas path this is a tile-local read-modify-write through
    ``input_output_aliases`` (no fresh result allocation: cholinv's Schur
    chain of Σ(n/2ᵏ)² intermediate buffers disappears, which is what lets
    the n=49152 flagship fit one v5e HBM — see docs/PERF.md); other modes
    materialize the window result and dynamic_update_slice it back, same
    semantics.  The dead (non-args.uplo) half of the window keeps the
    buffer's previous contents on the aligned pallas path.
    """
    if args.beta != 0.0 and C is None:
        raise ValueError("beta != 0 requires the accumulate operand C")
    if in_place and (args.beta == 0.0 or C is None):
        raise ValueError("in_place syrk requires the accumulate operand C")
    if (
        mode in ("pallas", "explicit")
        and grid.num_devices == 1
        and balance != "tile_cyclic_persistent"
    ):
        if balance == "tile_cyclic":
            # same contract as trmm's pallas branch: the kernel skips dead
            # tiles itself, so the cyclic schedule is a no-op here — note it
            tracing.note("syrk::tile_cyclic_fallback")
        # mode='pallas' honors args.uplo: only that triangle of the product
        # is computed; skipping the symmetric redundancy is where the ~1.65x
        # comes from.  beta*C accumulates INSIDE the kernel at flush time
        # (one C-tile read per live output tile instead of a full-matrix
        # slice + add downstream), which leaves the dead half UNDEFINED when
        # beta != 0 — callers must read only the args.uplo triangle
        # (models/cholesky.py symmetrizes its base-case panel from 'U').
        a_dims = (a_view[2], a_view[3]) if a_view is not None else A.shape
        n_out = a_dims[1] if args.trans else a_dims[0]
        k_in = a_dims[0] if args.trans else a_dims[1]
        flops, comm, ncoll = tracing.gemm_cost(
            grid, n_out, n_out, k_in, jnp.result_type(A)
        )
        if mode == "explicit":
            # copy-free d==1 route, same reasoning as trmm's: at one device
            # the explicit schedule's liveness is static and the aliasing
            # kernels execute it without the materialization chain below.
            # NOTE the contract narrows to the pallas one — only the
            # args.uplo triangle of the result is valid (beta=0 zeroes the
            # dead half, beta!=0 leaves it undefined); the in-repo explicit
            # consumers (models/cholesky.py, the CQR gram) already read
            # only that triangle, exactly as they do under mode='pallas'.
            tracing.note("explicit::copy_free")
            tracing.emit(
                flops=flops, comm_bytes=comm, collectives=ncoll,
                flops_vol=flops / 2, flops_max=flops / 2,
            )
        else:
            tracing.emit(flops=flops / 2, comm_bytes=comm, collectives=ncoll)
        out_kw = {}
        if in_place:
            out_kw = dict(
                out=C,
                out_off=(c_view[0], c_view[1]) if c_view is not None else (0, 0),
            )
        return pallas_tpu.tri_matmul(
            A, A,
            a_trans=args.trans, b_trans=not args.trans,
            out_uplo=args.uplo, alpha=args.alpha, precision=args.precision,
            a_view=a_view, b_view=a_view,
            c=C, c_view=c_view, beta=args.beta,
            **out_kw,
        )
    if balance == "tile_cyclic_persistent":
        return _syrk_persistent(
            grid, A, C, args, mode, a_view, c_view, in_place, cyclic_tile
        )
    Aw = _take_view(A, a_view)
    if balance == "tile_cyclic" and mode != "explicit":
        # xla/pallas modes have no balanced schedule to route to — say so
        # in the recorder instead of silently dropping the request (same
        # contract as trmm's fallback note)
        tracing.note("syrk::tile_cyclic_fallback")
    if mode == "explicit":
        # compute only the args.uplo triangle's blocks (devices with a fully
        # dead C block skip all local flops), then symmetrize — one grid
        # transpose, the same data motion the reference's syrk-via-transpose
        # already pays (summa.hpp:86-161); the dense-result contract of this
        # mode is preserved.
        # balance='tile_cyclic': C's OUTPUT tile indices are block-cyclic
        # over devices (permute A's free axis in, un-permute C's rows+cols
        # out), so every device carries ~half the live tile pairs instead
        # of whole blocks being dead — the syrk analog of trmm's balanced
        # schedule (see trmm's docstring; same decision calculus).
        cyc = 0
        perm = inv = None
        if balance == "tile_cyclic":
            n_out = Aw.shape[1] if args.trans else Aw.shape[0]
            T = _pick_cyclic_tile(grid, n_out, cyclic_tile)
            if T:
                perm, inv = tile_cyclic_perm(n_out, grid.dx, T)
                pj = jnp.asarray(perm)
                Aw = Aw[:, pj] if args.trans else Aw[pj, :]
                cyc = T
                # three shuffles, each priced at its true shape: the whole
                # A operand in, then C's rows AND cols out (two n_out²
                # motions — D[inv][:, inv])
                ca, na = tracing.transpose_cost(grid, *Aw.shape, Aw.dtype)
                cc, nc = tracing.transpose_cost(grid, n_out, n_out, Aw.dtype)
                tracing.emit(comm_bytes=ca + 2 * cc, collectives=na + 2 * nc)
            else:
                tracing.note("syrk::tile_cyclic_fallback")
        Aop = (Aw.T, Aw) if args.trans else (Aw, Aw.T)
        D = _matmul(
            grid, Aop[0], Aop[1], mode, args.precision, out_uplo=args.uplo,
            cyclic_out=cyc,
        )
        if cyc:
            ij = jnp.asarray(inv)
            D = grid.pin(D[ij][:, ij])
        if args.uplo == "U":
            out = jnp.triu(D) + transpose(grid, jnp.triu(D, 1))
        else:
            out = jnp.tril(D) + transpose(grid, jnp.tril(D, -1))
    else:
        Aop = (Aw.T, Aw) if args.trans else (Aw, Aw.T)
        out = _matmul(grid, Aop[0], Aop[1], mode, args.precision)
    if args.alpha != 1.0:
        out = args.alpha * out
    # copy-bytes attribution (see trmm): the .T operand, window slices, the
    # symmetrize's two triangle masks, and the write-back round-trip
    cb = _copy_bytes_of((2.0, Aw))
    if a_view is not None:
        cb += _copy_bytes_of((2.0, Aw))
    if mode == "explicit":
        cb += _copy_bytes_of((4.0, out))
    if args.beta != 0.0:
        Cw = _take_view(C, c_view)
        out = out + args.beta * grid.pin(Cw)
        if c_view is not None:
            cb += _copy_bytes_of((2.0, Cw))
    if in_place:
        off = (c_view[0], c_view[1]) if c_view is not None else (0, 0)
        cb += _copy_bytes_of((2.0, C))  # whole-buffer dus round-trip
        tracing.emit(copy_bytes=cb / grid.num_devices)
        return grid.pin(
            lax.dynamic_update_slice(C, out.astype(C.dtype), _i32_off(off))
        )
    tracing.emit(copy_bytes=cb / grid.num_devices)
    return grid.pin(out)


def transpose(grid: Grid, A: jnp.ndarray) -> jnp.ndarray:
    """Grid transpose: Aᵀ re-pinned to the face layout.

    Reference util::transpose swaps blocks with the mirrored grid rank via
    MPI_Sendrecv_replace (util.hpp:232-247); on TPU the same data motion is
    XLA's collective-permute, emitted from the layout constraint."""
    comm, ncoll = tracing.transpose_cost(grid, A.shape[0], A.shape[1], A.dtype)
    tracing.emit(comm_bytes=comm, collectives=ncoll)
    return grid.pin(A.T)
