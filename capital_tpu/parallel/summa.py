"""SUMMA: distributed matrix multiplication on the device grid.

TPU-native re-design of the reference's 3D SUMMA (src/alg/matmult/summa/
summa.hpp).  The reference implements C = alpha*op(A)op(B) + beta*C on a
d x d x c process grid by broadcasting A-panels along the row communicator and
B-panels along the column communicator from depth-dependent roots, running a
local MKL gemm, and allreducing partial C over the depth communicator
(summa.hpp:177-249), with an optional chunked Ibcast/Iallreduce pipeline
(num_chunks, summa.hpp:196-215).  Overloads cover gemm, in-place triangular
trmm, and syrk-via-transpose (summa.hpp:7-161).

Here the same capability is expressed two ways, selectable per call:

* ``mode='xla'`` (default): the contraction is written as a plain jnp matmul
  with sharding constraints pinning operands and result to the grid face; the
  XLA SPMD partitioner plans the panel gathers and the depth psum itself.
  This is the idiomatic TPU path — GSPMD already implements SUMMA-family
  schedules, and the latency-hiding scheduler overlaps the collectives the
  way the reference's chunked pipeline does by hand.

* ``mode='explicit'``: a shard_map kernel that owns the schedule exactly like
  the reference owns its MPI calls: a step loop over K-panel broadcasts
  (masked-psum bcast from the owning row/column — the collective analog of
  MPI_Bcast from a root), local dot_general per step, K-steps partitioned
  over the depth axis 'z' (the 2.5D flop split), and a final psum over 'z'
  (the reference's MPI_Allreduce collect, summa.hpp:236).  This path is the
  control knob for communication research and is benchmarked against 'xla'.

* ``mode='pallas'``: trmm/syrk route through the live-tile-enumerated Pallas
  kernels (ops/pallas_tpu.py), which skip the dead triangle's blocks on the
  MXU — the ~2x flop saving the reference gets from BLAS trmm/syrk, measured
  1.4-1.65x on v5e at 8192^2.  Currently single-device grids only (the local
  compute of a distributed call; triangular structure does not tile cleanly
  over block-distributed shards), so distributed calls and gemm (where XLA's
  dense matmul is already optimal) fall back to 'xla'.

Triangular structure (trmm) and symmetric rank-k updates (syrk) are expressed
as masked gemms: dense tiles + elementwise masks fuse into the matmul and keep
the MXU full, replacing the reference's packed-storage policies (SURVEY §7.1).

All functions take and return **global** jax Arrays (any sharding; they pin
layouts internally) and are jit-compatible.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from capital_tpu.ops import masking, pallas_tpu
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import tracing


@dataclasses.dataclass(frozen=True)
class GemmArgs:
    """Mirror of blas::ArgPack_gemm (reference src/blas/engine.h:72-94)."""

    alpha: float = 1.0
    beta: float = 0.0
    trans_a: bool = False
    trans_b: bool = False
    precision: str | None = None  # None = context default; 'highest' = f32 MXU


@dataclasses.dataclass(frozen=True)
class TrmmArgs:
    """Mirror of blas::ArgPack_trmm (reference src/blas/engine.h:96-112)."""

    side: str = "L"  # 'L': B <- alpha*op(A)B ; 'R': B <- alpha*B*op(A)
    uplo: str = "U"
    trans_a: bool = False
    diag: str = "N"  # 'N' non-unit, 'U' unit diagonal
    alpha: float = 1.0
    precision: str | None = None


@dataclasses.dataclass(frozen=True)
class SyrkArgs:
    """Mirror of blas::ArgPack_syrk (reference src/blas/engine.h:114-130)."""

    uplo: str = "U"
    trans: bool = False  # False: C = a*A*Aᵀ + b*C ; True: C = a*AᵀA + b*C
    alpha: float = 1.0
    beta: float = 0.0
    precision: str | None = None


# --------------------------------------------------------------------------
# explicit shard_map schedule
# --------------------------------------------------------------------------


def _explicit_matmul(
    grid: Grid, A: jnp.ndarray, B: jnp.ndarray, precision: str | None = None
) -> jnp.ndarray:
    """C = A @ B with the explicit SUMMA step schedule on the d x d x c grid.

    Schedule (mirrors summa.hpp:177-249, re-expressed with axis collectives):
      for step k in this layer's share of the d K-panels:
        a_panel = bcast(A[:, k-panel] from grid column y==k)   # row comm bcast
        b_panel = bcast(B[k-panel, :] from grid row x==k)      # column comm bcast
        acc += a_panel @ b_panel                               # local gemm
      C = psum(acc, 'z')                                       # depth collect

    Bcast-from-root is realized as psum of a root-masked operand — the
    standard axis-collective encoding of MPI_Bcast.  K-steps are split
    contiguously over the depth axis: layer z handles steps
    [z*d/c, (z+1)*d/c), which is the 2.5D replication trade (topology.h:76-78
    replication depth c).

    With grid.num_chunks > 1 each K-panel's broadcast is further split into
    that many K-slices — the reference's chunked Ibcast pipeline
    (summa.hpp:196-215): each slice is an independent collective the
    latency-hiding scheduler can overlap with the previous slice's local
    matmul.  The chunk loop is unrolled at trace time (static shapes).
    """
    d, c = grid.dx, grid.c
    if grid.dy != d:
        raise ValueError("explicit SUMMA requires a square grid face")
    if d % c != 0:
        raise ValueError(f"depth c={c} must divide face d={d}")
    (M, K), (K2, N) = A.shape, B.shape
    if K != K2:
        raise ValueError(f"inner dims mismatch: {A.shape} @ {B.shape}")
    if M % d or K % d or N % d:
        raise ValueError(f"global dims {(M, K, N)} must be divisible by d={d}")

    steps_per_layer = d // c
    q = max(1, grid.num_chunks)
    if (K // d) % q:
        raise ValueError(
            f"num_chunks={q} must divide the local K panel extent {K // d}"
        )
    ck = K // d // q

    def kernel(a, b):
        # a: (M/d, K/d) block at (x, y);  b: (K/d, N/d) block at (x, y)
        xi = lax.axis_index("x")
        yi = lax.axis_index("y")
        zi = lax.axis_index("z")

        acc = jnp.zeros((a.shape[0], b.shape[1]), dtype=jnp.result_type(a, b))
        for i in range(steps_per_layer):
            k = zi * steps_per_layer + i
            for ch in range(q):
                a_sl = a[:, ch * ck : (ch + 1) * ck]
                b_sl = b[ch * ck : (ch + 1) * ck, :]
                a_panel = lax.psum(
                    jnp.where(yi == k, a_sl, jnp.zeros_like(a_sl)), "y"
                )
                b_panel = lax.psum(
                    jnp.where(xi == k, b_sl, jnp.zeros_like(b_sl)), "x"
                )
                acc = acc + jnp.matmul(a_panel, b_panel, precision=precision)
        return lax.psum(acc, "z")

    return jax.shard_map(
        kernel,
        mesh=grid.mesh,
        in_specs=(P("x", "y"), P("x", "y")),
        out_specs=P("x", "y"),
    )(grid.pin(A), grid.pin(B))


# --------------------------------------------------------------------------
# public ops
# --------------------------------------------------------------------------


def _matmul(
    grid: Grid,
    A: jnp.ndarray,
    B: jnp.ndarray,
    mode: str,
    precision: str | None = None,
) -> jnp.ndarray:
    # cost-model attribution (no-op without an active tracing.Recorder)
    flops, comm, ncoll = tracing.gemm_cost(
        grid, A.shape[0], B.shape[1], A.shape[1], jnp.result_type(A, B)
    )
    tracing.emit(flops=flops, comm_bytes=comm, collectives=ncoll)
    if mode in ("xla", "pallas"):  # gemm has no dead blocks: XLA is optimal
        return grid.pin(jnp.matmul(grid.pin(A), grid.pin(B), precision=precision))
    if mode == "explicit":
        return _explicit_matmul(grid, A, B, precision)
    raise ValueError(f"unknown summa mode {mode!r}")


@pallas_tpu.scoped_by_grid
def gemm(
    grid: Grid,
    A: jnp.ndarray,
    B: jnp.ndarray,
    C: jnp.ndarray | None = None,
    args: GemmArgs = GemmArgs(),
    mode: str = "xla",
) -> jnp.ndarray:
    """C = alpha * op(A) @ op(B) + beta * C  (reference summa.hpp:7-44)."""
    Aop = A.T if args.trans_a else A
    Bop = B.T if args.trans_b else B
    if args.beta != 0.0 and C is None:
        raise ValueError("beta != 0 requires the accumulate operand C")
    out = _matmul(grid, Aop, Bop, mode, args.precision)
    if args.alpha != 1.0:
        out = args.alpha * out
    if args.beta != 0.0:
        out = out + args.beta * grid.pin(C)
    return grid.pin(out)


def _take_view(X, view):
    if X is None or view is None:
        return X
    return pallas_tpu._window(X, view)


@pallas_tpu.scoped_by_grid
def trmm(
    grid: Grid,
    A: jnp.ndarray,
    B: jnp.ndarray,
    args: TrmmArgs = TrmmArgs(),
    mode: str = "xla",
    *,
    a_view: tuple[int, int, int, int] | None = None,
    b_view: tuple[int, int, int, int] | None = None,
    out: jnp.ndarray | None = None,
    out_off: tuple[int, int] = (0, 0),
) -> jnp.ndarray:
    """B <- alpha * op(tri(A)) @ B   (side L)   or   alpha * B @ op(tri(A))
    (side R) — reference summa.hpp:47-83.

    The triangular operand is dense + masked; the mask fuses into the matmul
    (no packed storage — SURVEY §7.1).  mode='pallas' on a single-device
    grid skips the dead blocks on the MXU instead (ops/pallas_tpu.py).

    a_view/b_view select static windows of the passed buffers as the
    operands, and out/out_off writes the result into a window of `out`
    (returning the whole updated buffer).  On the single-device pallas path
    these compile to offset index maps / an in-place aliased write (no slice
    or scatter materialization, ops/pallas_tpu.py); every other path
    materializes the windows and a dynamic_update_slice — identical
    semantics, so callers can be written once against views (the recursion
    in models/cholesky.py is)."""
    a_dims = (a_view[2], a_view[3]) if a_view is not None else A.shape
    b_dims = (b_view[2], b_view[3]) if b_view is not None else B.shape
    if mode == "pallas" and grid.num_devices == 1 and args.diag != "U":
        flops, comm, ncoll = tracing.gemm_cost(
            grid, b_dims[0], b_dims[1], a_dims[0], jnp.result_type(A, B)
        )
        tracing.emit(flops=flops / 2, comm_bytes=comm, collectives=ncoll)
        if args.side == "L":
            return pallas_tpu.tri_matmul(
                A, B, a_uplo=args.uplo, a_trans=args.trans_a,
                alpha=args.alpha, precision=args.precision,
                a_view=a_view, b_view=b_view, out=out, out_off=out_off,
            )
        elif args.side == "R":
            return pallas_tpu.tri_matmul(
                B, A, b_uplo=args.uplo, b_trans=args.trans_a,
                alpha=args.alpha, precision=args.precision,
                a_view=b_view, b_view=a_view, out=out, out_off=out_off,
            )
        raise ValueError(f"side must be 'L' or 'R', got {args.side!r}")
    Aw = _take_view(A, a_view)
    Bw = _take_view(B, b_view)
    T = masking.take_triangle(Aw, args.uplo)
    if args.diag == "U":
        T = masking.with_unit_diagonal(T)
    Top = T.T if args.trans_a else T
    if args.side == "L":
        res = _matmul(grid, Top, Bw, mode, args.precision)
    elif args.side == "R":
        res = _matmul(grid, Bw, Top, mode, args.precision)
    else:
        raise ValueError(f"side must be 'L' or 'R', got {args.side!r}")
    if args.alpha != 1.0:
        res = args.alpha * res
    if out is not None:
        return grid.pin(lax.dynamic_update_slice(out, res.astype(out.dtype), out_off))
    return grid.pin(res)


@pallas_tpu.scoped_by_grid
def syrk(
    grid: Grid,
    A: jnp.ndarray,
    C: jnp.ndarray | None = None,
    args: SyrkArgs = SyrkArgs(),
    mode: str = "xla",
    *,
    a_view: tuple[int, int, int, int] | None = None,
    c_view: tuple[int, int, int, int] | None = None,
) -> jnp.ndarray:
    """Symmetric rank-k update (reference summa.hpp:86-161, which lowers syrk
    to an explicit grid transpose + gemm; here the transpose is a logical
    .T — XLA emits the collective-permute when resharding is needed).

    trans=False: C = alpha*A@Aᵀ + beta*C;  trans=True: C = alpha*Aᵀ@A + beta*C.
    In 'xla'/'explicit' modes the full dense symmetric result is computed
    (MXU-friendly); callers that need only a triangle mask the output.
    mode='pallas' (single-device grid) instead honors args.uplo: only that
    triangle of the result is valid — with beta=0 the dead half is zeroed,
    with beta!=0 it is UNDEFINED (the fused in-kernel beta*C accumulate
    never visits dead tiles) — so callers must read only the args.uplo
    triangle (models/cholesky.py symmetrizes its base-case panel from 'U').
    """
    if args.beta != 0.0 and C is None:
        raise ValueError("beta != 0 requires the accumulate operand C")
    if mode == "pallas" and grid.num_devices == 1:
        # mode='pallas' honors args.uplo: only that triangle of the product
        # is computed; skipping the symmetric redundancy is where the ~1.65x
        # comes from.  beta*C accumulates INSIDE the kernel at flush time
        # (one C-tile read per live output tile instead of a full-matrix
        # slice + add downstream), which leaves the dead half UNDEFINED when
        # beta != 0 — callers must read only the args.uplo triangle
        # (models/cholesky.py symmetrizes its base-case panel from 'U').
        a_dims = (a_view[2], a_view[3]) if a_view is not None else A.shape
        n_out = a_dims[1] if args.trans else a_dims[0]
        k_in = a_dims[0] if args.trans else a_dims[1]
        flops, comm, ncoll = tracing.gemm_cost(
            grid, n_out, n_out, k_in, jnp.result_type(A)
        )
        tracing.emit(flops=flops / 2, comm_bytes=comm, collectives=ncoll)
        return pallas_tpu.tri_matmul(
            A, A,
            a_trans=args.trans, b_trans=not args.trans,
            out_uplo=args.uplo, alpha=args.alpha, precision=args.precision,
            a_view=a_view, b_view=a_view,
            c=C, c_view=c_view, beta=args.beta,
        )
    Aw = _take_view(A, a_view)
    Aop = (Aw.T, Aw) if args.trans else (Aw, Aw.T)
    out = _matmul(grid, Aop[0], Aop[1], mode, args.precision)
    if args.alpha != 1.0:
        out = args.alpha * out
    if args.beta != 0.0:
        out = out + args.beta * grid.pin(_take_view(C, c_view))
    return grid.pin(out)


def transpose(grid: Grid, A: jnp.ndarray) -> jnp.ndarray:
    """Grid transpose: Aᵀ re-pinned to the face layout.

    Reference util::transpose swaps blocks with the mirrored grid rank via
    MPI_Sendrecv_replace (util.hpp:232-247); on TPU the same data motion is
    XLA's collective-permute, emitted from the layout constraint."""
    return grid.pin(A.T)
