from capital_tpu.parallel.topology import Grid, cpu_grid_square  # noqa: F401
