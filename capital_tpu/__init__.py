"""capital_tpu — a TPU-native communication-avoiding dense linear algebra framework.

A ground-up JAX / XLA / Pallas re-design of the capabilities of the reference
CAPITAL library (communication-avoiding parallel schedules for dense matrix
factorizations): 3D SUMMA matrix multiplication, communication-optimal recursive
Cholesky factorization with simultaneous triangular inverse, communication-
avoiding CholeskyQR2 for tall-skinny matrices, distributed triangular inversion,
Newton-Schulz iterative inversion, and the surrounding validation / benchmark /
autotune harness.

Where the reference expresses parallelism through MPI communicator splits over a
d x d x c process grid (reference: src/util/topology.h) and delegates local
compute to MKL BLAS/LAPACK (reference: src/blas/interface.hpp,
src/lapack/interface.hpp), this framework expresses the same schedules on a TPU
device mesh: axis-scoped collectives (psum, all_gather, ppermute) inside
shard_map over ICI/DCN, dense masked tiles instead of packed triangular
storage, lax.linalg plus Pallas kernels for panel factorizations, and
trace-time block scheduling in place of runtime recursion.

Package layout:
  parallel/  - device-mesh topology, collectives, SUMMA (reference L2 + L4 matmult)
  ops/       - local compute engines: BLAS/LAPACK equivalents, masks, Pallas kernels
               (reference L3' src/blas + src/lapack)
  models/    - the algorithm families: cholesky (cholinv), qr (cacqr),
               inverse (rectri/newton), trsm (reference L4 src/alg)
  utils/     - deterministic fillers, residual validation, tracing, config
               (reference src/util + test/ + critter shims)
  bench/     - benchmark drivers (reference bench/)
  autotune/  - config sweep harness (reference autotune/)
  native/    - C++ host engine (ctypes): coordinate-seeded fillers, layout
               repacks, and the alpha-beta schedule planner, with NumPy
               fallbacks (the host-native remainder of the reference's C++)
"""

__version__ = "0.1.0"


def __getattr__(name: str):
    # Grid resolves lazily (PEP 562): importing it pulls in jax, and the
    # host-only serve processes (router pumps, spawned loadgen clients)
    # import this package without ever needing a device runtime.
    if name == "Grid":
        from capital_tpu.parallel.topology import Grid

        globals()["Grid"] = Grid
        return Grid
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
