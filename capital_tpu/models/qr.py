"""cacqr: communication-avoiding CholeskyQR2 for tall-skinny QR.

TPU-native re-design of qr::cacqr (reference src/alg/qr/cacqr/), the
CA-CQR2 algorithm (IPDPS'19, arXiv:1710.08471): for tall-skinny A (M x N,
M >> N), one *sweep* is

    G = AᵀA          (gram — the only global reduction)
    R = chol(G)      (small N x N factorization)
    Q = A · R⁻¹      (tall-skinny scaling)

CQR2 runs two sweeps and merges R = R2·R1, recovering orthogonality to
machine precision (cacqr.hpp:181-210).

The reference dispatches on grid shape (cacqr.hpp:229-245):
  c == 1  'invoke_1d'  : local syrk + MPI_Allreduce(world) + local LAPACK
  c == d  'invoke_3d'  : gram via bcast/reduce pipeline + cholinv on the gram
                          on the cube's square sub-grid + SUMMA trmm
  1<c<d   'sweep_tune' : same with the column reduction split over
                          column_contig/column_alt sub-communicators

On a TPU mesh the three regimes collapse to one question — *where does the
N x N gram live?* — so this module exposes two paths and an auto rule:

  regime='1d'   : A is sharded along its long axis over every device
                  (Grid.rows_sharding); the gram psum is the single
                  collective; chol+inverse run replicated on every chip.
                  This is the reference's 1D path and the right choice
                  whenever N is small enough that the N x N gram fits
                  replicated (the common tall-skinny case).
  regime='dist' : A is face-sharded; the gram forms via distributed syrk and
                  **cholinv.factor runs on the gram** exactly like the
                  reference wires its 3D path into cholinv (cacqr.hpp:103);
                  Q = A·R⁻¹ via SUMMA trmm, or the blocked triangular solve
                  when complete_inv=False (cacqr.hpp:46-73).
  regime='auto' : '1d' when the grid is flat or N <= dist_threshold,
                  else 'dist'.

The reference's tunable grid shape (topo::rect c,d sweep) maps to how the
caller constructs the Grid (Grid.rect(dx, dy, c)) — mesh shape is the
runtime knob that replaces communicator re-splitting (SURVEY §2.5).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from capital_tpu.models import cholesky
from capital_tpu.models.cholesky import CholinvConfig
from capital_tpu.ops import lapack, pallas_tpu
from capital_tpu.parallel import summa
from capital_tpu.parallel.summa import GemmArgs, SyrkArgs, TrmmArgs
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import tracing


@dataclasses.dataclass(frozen=True)
class CacqrConfig:
    """Mirror of qr::cacqr::info (reference cacqr.h:17-45).

    num_iter: 1 = CholeskyQR, 2 = CholeskyQR2 (the reference's `variant`
        driver knob, bench/qr/cacqr.cpp:14).
    regime: '1d' | 'dist' | 'auto' (see module docstring).
    dist_threshold: in 'auto', gram sizes above this go distributed.
    cholinv: configuration for the nested Cholesky when regime='dist'
        (the reference nests its cholinv pack the same way, cacqr.cpp:38-40).
        cholinv.complete_inv=False switches Q formation to the blocked
        triangular solve (reference cacqr.hpp:46-73).
    """

    num_iter: int = 2
    regime: str = "auto"
    dist_threshold: int = 4096
    cholinv: CholinvConfig = CholinvConfig()
    mode: str = "xla"
    precision: str | None = "highest"  # gram/scaling matmul precision: the
    # gram AᵀA is the numerically critical contraction of CholeskyQR — at
    # the TPU default (bf16 passes) orthogonality degrades ~200x for f32
    # inputs; 'highest' keeps it f32-grade


# --------------------------------------------------------------------------
# sweeps
# --------------------------------------------------------------------------


def _sweep_1d(
    grid: Grid, A: jnp.ndarray, cfg: CacqrConfig
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One CQR sweep, 1D regime (reference sweep_1d, cacqr.hpp:7-29).

    A arrives sharded along rows over the whole mesh; the gram contraction
    AᵀA is written globally and pinned replicated — XLA emits the local
    partial product and the all-axis psum, the exact analog of the
    reference's local syrk + MPI_Allreduce over world (cacqr.hpp:14-25).

    On a single device with cfg.mode='pallas' both big contractions route
    through the live-tile kernels — the reference's local cblas_dsyrk /
    cblas_dtrmm flop savings (cacqr.hpp:14,25): the gram computes only the
    upper triangle of AᵀA (~half the mn² flops) and Q = A·R⁻¹ skips R⁻¹'s
    dead lower blocks; the Cholesky pair then reads only the gram's valid
    upper triangle (potrf_trtri_upper).
    """
    m, n = A.shape
    precision = cfg.precision
    use_pallas = cfg.mode == "pallas" and grid.num_devices == 1
    A = lax.with_sharding_constraint(A, grid.rows_sharding())
    # phase tags follow the reference symbols CQR::gram / CQR::formR
    # (cacqr.hpp:82-116)
    with tracing.scope("CQR::gram"):
        if use_pallas:
            # summa.syrk emits its own (halved) cost attribution
            G = summa.syrk(
                grid, A,
                args=SyrkArgs(trans=True, precision=precision), mode="pallas",
            )
        else:
            comm, ncoll = tracing.allreduce_cost(grid, n, n, A.dtype, axes="all")
            tracing.emit(
                flops=2.0 * m * n * n / grid.num_devices,
                comm_bytes=comm, collectives=ncoll,
            )
            G = lax.with_sharding_constraint(
                jnp.matmul(A.T, A, precision=precision),
                grid.replicated_sharding(),
            )
    with tracing.scope("CQR::chol"):
        tracing.emit(flops=tracing.potrf_trtri_flops(n))
        if use_pallas:
            # the pallas syrk left the gram's lower half dead/undefined
            R, Rinv = lapack.potrf_trtri_upper(G)
        else:
            R, Rinv = lapack.potrf_trtri(G, uplo="U")
    with tracing.scope("CQR::formR"):
        if use_pallas:
            Q = summa.trmm(
                grid, Rinv, A,
                TrmmArgs(side="R", uplo="U", precision=precision),
                mode="pallas",
            )
        else:
            tracing.emit(flops=2.0 * m * n * n / grid.num_devices)
            Q = lax.with_sharding_constraint(
                jnp.matmul(A, Rinv, precision=precision), grid.rows_sharding()
            )
    return Q, R


def _sweep_dist(
    grid: Grid, A: jnp.ndarray, cfg: CacqrConfig
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One CQR sweep, distributed regime (reference sweep_3d, cacqr.hpp:82-116).

    Gram via distributed syrk, then **cholinv on the gram** (the wiring at
    cacqr.hpp:103), then Q = A·R⁻¹ by SUMMA trmm — or, when cholinv is run
    without the completed inverse, the 2x2 blocked solve (cacqr.hpp:46-73).
    """
    A = grid.pin(A)
    with tracing.scope("CQR::gram"):
        G = summa.syrk(
            grid, A, args=SyrkArgs(trans=True, precision=cfg.precision), mode=cfg.mode
        )
    with tracing.scope("CQR::chol"):
        R, Rinv = cholesky.factor(grid, G, cfg.cholinv)
    with tracing.scope("CQR::formR"):
        if cfg.cholinv.complete_inv:
            Q = summa.trmm(
                grid, Rinv, A,
                TrmmArgs(side="R", uplo="U", precision=cfg.precision), mode=cfg.mode,
            )
        else:
            Q = solve_blocked(grid, A, R, Rinv, cfg)
    return Q, R


def solve_blocked(
    grid: Grid,
    A: jnp.ndarray,
    R: jnp.ndarray,
    Rinv: jnp.ndarray,
    cfg: CacqrConfig,
) -> jnp.ndarray:
    """X = A·R⁻¹ from the *partial* inverse: the 2x2 blocked triangular solve
    that is the reference's de-facto distributed TRSM (cacqr.hpp:46-73).

    With R = [[R11, R12], [0, R22]] and only R11⁻¹, R22⁻¹ available (the
    complete_inv=False contract of cholinv):

        X1 = A1 · R11⁻¹
        X2 = (A2 − X1·R12) · R22⁻¹
    """
    n = R.shape[0]
    n1 = cholesky.top_split(n, cfg.cholinv)
    if n1 == n:
        # single base-case window: Rinv is already the full inverse
        return summa.trmm(
            grid, Rinv, A,
            TrmmArgs(side="R", uplo="U", precision=cfg.precision), mode=cfg.mode,
        )
    A1, A2 = A[:, :n1], A[:, n1:]
    R11inv, R22inv = Rinv[:n1, :n1], Rinv[n1:, n1:]
    R12 = R[:n1, n1:]
    X1 = summa.trmm(
        grid, R11inv, A1,
        TrmmArgs(side="R", uplo="U", precision=cfg.precision), mode=cfg.mode,
    )
    A2p = summa.gemm(
        grid, X1, R12, A2,
        GemmArgs(alpha=-1.0, beta=1.0, precision=cfg.precision), mode=cfg.mode,
    )
    X2 = summa.trmm(
        grid, R22inv, A2p,
        TrmmArgs(side="R", uplo="U", precision=cfg.precision), mode=cfg.mode,
    )
    return grid.pin(jnp.concatenate([X1, X2], axis=1))


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------


def _pick_regime(grid: Grid, n: int, cfg: CacqrConfig) -> str:
    if cfg.regime != "auto":
        return cfg.regime
    if grid.dy == 1 and grid.c == 1:
        return "1d"
    return "1d" if n <= cfg.dist_threshold else "dist"


@pallas_tpu.scoped_by_grid
def factor(
    grid: Grid, A: jnp.ndarray, cfg: CacqrConfig = CacqrConfig()
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """QR of tall-skinny A: returns (Q, R) with A = QR, R upper triangular.

    Equivalent of qr::cacqr::factor (cacqr.hpp:216-245); jit-friendly.
    num_iter=2 (CQR2) merges the two sweeps' triangular factors with a
    trmm, R = R2·R1 (cacqr.hpp:181-189, 204-210).
    """
    m, n = A.shape
    if m < n:
        raise ValueError(f"cacqr expects tall-skinny input, got {A.shape}")
    if cfg.num_iter not in (1, 2):
        raise ValueError(f"num_iter must be 1 (CQR) or 2 (CQR2), got {cfg.num_iter}")
    regime = _pick_regime(grid, n, cfg)
    sweep = (
        (lambda a: _sweep_1d(grid, a, cfg))
        if regime == "1d"
        else (lambda a: _sweep_dist(grid, a, cfg))
    )
    Q, R = sweep(A)
    if cfg.num_iter == 2:
        Q, R2 = sweep(Q)
        # merge R = R2 · R1: both upper triangular; small local/distributed trmm
        # (reference cacqr.hpp:181-189, 204-210)
        with tracing.scope("CQR::merge"):
            if regime == "1d":
                tracing.emit(flops=2.0 * R.shape[0] ** 3)
                R = jnp.matmul(jnp.triu(R2), jnp.triu(R), precision=cfg.precision)
            else:
                R = summa.trmm(
                    grid, R2, R,
                    TrmmArgs(side="L", uplo="U", precision=cfg.precision), mode=cfg.mode,
                )
    return Q, R


def apply_Q(
    grid: Grid,
    Q: jnp.ndarray,
    X: jnp.ndarray,
    mode: str = "xla",
    precision: str | None = "highest",
) -> jnp.ndarray:
    """Q @ X (reference apply_Q = SUMMA gemm, cacqr.hpp:272-280)."""
    return summa.gemm(grid, Q, X, args=GemmArgs(precision=precision), mode=mode)


def apply_QT(
    grid: Grid,
    Q: jnp.ndarray,
    X: jnp.ndarray,
    mode: str = "xla",
    precision: str | None = "highest",
) -> jnp.ndarray:
    """Qᵀ @ X.  The reference left this as static_assert(0) (cacqr.hpp:284);
    implemented here — it is just the transposed gemm."""
    return summa.gemm(
        grid, Q, X, args=GemmArgs(trans_a=True, precision=precision), mode=mode
    )
