"""cacqr: communication-avoiding CholeskyQR2 for tall-skinny QR.

TPU-native re-design of qr::cacqr (reference src/alg/qr/cacqr/), the
CA-CQR2 algorithm (IPDPS'19, arXiv:1710.08471): for tall-skinny A (M x N,
M >> N), one *sweep* is

    G = AᵀA          (gram — the only global reduction)
    R = chol(G)      (small N x N factorization)
    Q = A · R⁻¹      (tall-skinny scaling)

CQR2 runs two sweeps and merges R = R2·R1, recovering orthogonality to
machine precision (cacqr.hpp:181-210).

The reference dispatches on grid shape (cacqr.hpp:229-245):
  c == 1  'invoke_1d'  : local syrk + MPI_Allreduce(world) + local LAPACK
  c == d  'invoke_3d'  : gram via bcast/reduce pipeline + cholinv on the gram
                          on the cube's square sub-grid + SUMMA trmm
  1<c<d   'sweep_tune' : same with the column reduction split over
                          column_contig/column_alt sub-communicators

On a TPU mesh the three regimes collapse to one question — *where does the
N x N gram live?* — so this module exposes two paths and an auto rule:

  regime='1d'   : A is sharded along its long axis over every device
                  (Grid.rows_sharding); the gram psum is the single
                  collective; chol+inverse run replicated on every chip.
                  This is the reference's 1D path and the right choice
                  whenever N is small enough that the N x N gram fits
                  replicated (the common tall-skinny case).
  regime='dist' : A is face-sharded; the gram forms via distributed syrk and
                  **cholinv.factor runs on the gram** exactly like the
                  reference wires its 3D path into cholinv (cacqr.hpp:103);
                  Q = A·R⁻¹ via SUMMA trmm, or the blocked triangular solve
                  when complete_inv=False (cacqr.hpp:46-73).
  regime='auto' : '1d' when the grid is flat or N <= dist_threshold,
                  else 'dist'.

The reference's tunable grid shape (topo::rect c,d sweep) maps to how the
caller constructs the Grid (Grid.rect(dx, dy, c)) — mesh shape is the
runtime knob that replaces communicator re-splitting (SURVEY §2.5).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from capital_tpu.models import cholesky
from capital_tpu.models.cholesky import CholinvConfig
from capital_tpu.ops import lapack, pallas_tpu
from capital_tpu.parallel import summa
from capital_tpu.parallel.summa import GemmArgs, SyrkArgs, TrmmArgs
from capital_tpu.parallel.topology import Grid
from capital_tpu.robust import faultinject, recovery
from capital_tpu.robust import config as config_mod
from capital_tpu.robust.config import RobustConfig, RobustInfo
from capital_tpu.utils import jax_compat, tracing


@dataclasses.dataclass(frozen=True)
class CacqrConfig:
    """Mirror of qr::cacqr::info (reference cacqr.h:17-45).

    num_iter: 1 = CholeskyQR, 2 = CholeskyQR2 (the reference's `variant`
        driver knob, bench/qr/cacqr.cpp:14).
    regime: '1d' | 'dist' | 'auto' (see module docstring).
    dist_threshold: in 'auto', gram sizes above this go distributed.
    cholinv: configuration for the nested Cholesky when regime='dist'
        (the reference nests its cholinv pack the same way, cacqr.cpp:38-40).
        cholinv.complete_inv=False switches Q formation to the blocked
        triangular solve (reference cacqr.hpp:46-73).
    """

    num_iter: int = 2
    regime: str = "auto"
    dist_threshold: int = 4096
    cholinv: CholinvConfig = CholinvConfig()
    mode: str = "xla"
    precision: str | None = "highest"  # gram/scaling matmul precision: the
    # gram AᵀA is the numerically critical contraction of CholeskyQR — at
    # the TPU default (bf16 passes) orthogonality degrades ~200x for f32
    # inputs; 'highest' keeps it f32-grade
    fused_g: int = 0  # in-kernel column split of the fused tall-pass
    # kernels: executed flops are (g+1)/2g of dense at zero extra HBM
    # traffic (all sub-products VMEM-resident).  0 = auto
    # (qr_fused.pick_g: largest eligible in {8,4,2})
    robust: RobustConfig | None = None  # breakdown detection + shifted-
    # CholeskyQR recovery (docs/ROBUSTNESS.md): factor() returns
    # (Q, R, RobustInfo) instead of (Q, R), every Cholesky site is guarded,
    # and a detected breakdown re-factors the shifted gram + escalates to a
    # third sweep (sCQR3) when the orthogonality gate still fails.  On a
    # multi-device grid the guarded sweeps run unfused (traced status
    # values cannot escape the fused pipeline's shard_map body).


# --------------------------------------------------------------------------
# robust session: collects per-site CholEvents while factor() traces
# --------------------------------------------------------------------------


class _Session:
    """One robust factor() invocation: the active RobustConfig plus the
    CholEvents its guarded sites record (trace-order, so the aggregate in
    _finish_robust is deterministic)."""

    def __init__(self, rcfg: RobustConfig):
        self.rcfg = rcfg
        self.events: list = []


_ROBUST: list[_Session] = []


def _chol_site(G: jnp.ndarray, m_rows: int, chol_fn):
    """Factor a gram at one Cholesky site.  Outside a robust session this
    is chol_fn(G) verbatim — zero overhead on the default path.  Inside
    one, the site is wrapped in recovery.guarded_chol (detection + shifted
    retry) and its CholEvent lands on the session."""
    if not _ROBUST:
        return chol_fn(G)
    ses = _ROBUST[-1]
    R, Rinv, ev = recovery.guarded_chol(G, m_rows, ses.rcfg, chol_fn)
    ses.events.append(ev)
    return R, Rinv


# --------------------------------------------------------------------------
# sweeps
# --------------------------------------------------------------------------


def _col_blocks(n: int) -> int:
    """Column-block count for the triangular-blocked gram/scaling.  Fixed at
    2 (or 1 = unblocked for small/unaligned n): these tall-skinny products
    sit near the HBM roofline, and each extra split re-reads more of A —
    measured on v5e at 1M x 1024 bf16, g=4 with per-block products cost 5x
    the A traffic plus XLA relayout copies and ran 1.5x SLOWER than dense
    (86 vs 57 ms/iter device time); g=2 over contiguous slabs is the only
    split whose flop saving (25%) exceeds its traffic increase."""
    if n % 2 == 0 and (n // 2) % 128 == 0 and n // 2 >= 256:
        return 2
    return 1


def _sweep_1d(
    grid: Grid, A: jnp.ndarray, cfg: CacqrConfig
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One CQR sweep, 1D regime (reference sweep_1d, cacqr.hpp:7-29).

    A arrives sharded along rows over the whole mesh; gram contractions are
    written globally and pinned replicated — XLA emits the local partial
    product and the all-axis psum, the exact analog of the reference's
    local syrk + MPI_Allreduce over world (cacqr.hpp:14-25).

    The triangular flop savings of the reference's local cblas_dsyrk /
    cblas_dtrmm (cacqr.hpp:14,25), measured into this shape on v5e at
    1M x 1024 bf16 (the BASELINE-adjacent row):

      * gram — **XLA-level row blocking**: G[i, i*nb:] = A_iᵀ · A[:, i*nb:]
        computes only the upper block-rows off one contiguous trailing slab
        per row (lower blocks are transposes, n x n elementwise);
        (g+1)/2g of dense flops at minimum extra A-traffic.
      * scaling — Q = A·R⁻¹ through the live-tile trmm kernel with column
        blocks sized to the triangle (bn = bk = n/g): 3/4 executed flops at
        g=2, output written once, row-major, no assembly.

    Rejected alternatives, with v5e measurements: per-256-block XLA
    products (5x A traffic + whole-Q relayout copies: 86 ms/iter vs dense
    57), column-slab threading between sweeps (XLA assigns the slabs mixed
    layouts and re-layouts the assembled Q: ~13 ms/iter of copies), and
    default-block pallas routing (an n=1024 triangle is a single tile at
    deep-K defaults — no skipping happens, 76 ≈ 78 TF/s dense).
    """
    m, n = A.shape
    precision = cfg.precision
    g = _col_blocks(n)
    nb = n // g
    A = lax.with_sharding_constraint(A, grid.rows_sharding())
    live_frac = (g + 1) / (2.0 * g) if g > 1 else 1.0
    # phase tags follow the reference symbols CQR::gram / CQR::formR
    # (cacqr.hpp:82-116)
    with tracing.scope("CQR::gram"):
        comm, ncoll = tracing.allreduce_cost(grid, n, n, A.dtype, axes="all")
        tracing.emit(
            flops=2.0 * m * n * n / grid.num_devices * live_frac,
            # blocked gram: one psum per block-row product of live_frac of
            # the n x n bytes in total (g collectives, not one)
            comm_bytes=comm * live_frac,
            collectives=ncoll * (g if g > 1 else 1),
        )
        if g > 1:
            # each block-row partial is pinned replicated BEFORE the
            # transpose/concat assembly: the cost model above prices g
            # reductions of live_frac·n² bytes total, and without the
            # constraint GSPMD is free to sink the psum past the assembly
            # and move the dense n² in one collective (ADVICE r2) — the
            # constraint makes the modeled schedule the emitted one
            # (pinned by TestGramEmission1d)
            grows = [
                lax.with_sharding_constraint(
                    jnp.matmul(
                        A[:, i * nb : (i + 1) * nb].T,
                        A[:, i * nb :],
                        precision=precision,
                    ),
                    grid.replicated_sharding(),
                )
                for i in range(g)
            ]
            G = jnp.concatenate(
                [
                    jnp.concatenate(
                        [
                            grows[j][:, (i - j) * nb : (i - j + 1) * nb].T
                            for j in range(i)
                        ]
                        + [grows[i]],
                        axis=1,
                    )
                    for i in range(g)
                ],
                axis=0,
            )
        else:
            G = jnp.matmul(A.T, A, precision=precision)
        G = lax.with_sharding_constraint(G, grid.replicated_sharding())
        G = faultinject.tap(G)
    with tracing.scope("CQR::chol"):
        tracing.emit(flops=tracing.potrf_trtri_flops(n))
        R, Rinv = _chol_site(G, m, lambda g_: lapack.potrf_trtri(g_, uplo="U"))
    with tracing.scope("CQR::formR"):
        # the live-tile kernel is an explicit mode choice (the bench driver's
        # 'auto' resolves to pallas on one TPU); other modes take the dense
        # matmul — on CPU the interpreter would be orders of magnitude slower
        # nb <= 2048 is the live-tile kernel's VMEM envelope at these
        # blocks ((bm, nb, nb) + f32 acc): nb=4096 blows Mosaic's scoped
        # limit ("112.00M of 100.00M", n=8192) — wider shapes take the
        # dense matmul (the CQR2 path covers them with the panel tier)
        tri_kernel = (
            g > 1
            and grid.num_devices == 1
            and cfg.mode == "pallas"
            and n // g <= 2048
        )
        # live_frac applies only where the tri kernel actually skips dead
        # blocks; the multi-device path executes the dense matmul
        tracing.emit(
            flops=2.0 * m * n * n / grid.num_devices
            * (live_frac if tri_kernel else 1.0)
        )
        if tri_kernel:
            # live-tile trmm with triangle-sized column blocks (bn = bk =
            # n/g); bm capped at the kernel's large-tile budget.  Measured
            # at 1M x 1024 bf16 on v5e (device-trace kernel totals/sweep):
            # 512 blocks 10.7 ms (3/4 executed at 154 TF/s), 256 blocks
            # 13.9 ms (5/8 executed but per-tile efficiency collapses) —
            # finer blocks lose more to tile overhead than they save in
            # dead flops
            bm = min(1024, pallas_tpu._round_up(m, 128))
            Q = pallas_tpu.tri_matmul(
                A, Rinv, b_uplo="U", blocks=(bm, nb, nb), precision=precision
            )
        else:
            Q = jnp.matmul(A, jnp.triu(Rinv), precision=precision)
        Q = lax.with_sharding_constraint(Q, grid.rows_sharding())
    return Q, R


def _gram_chol(grid: Grid, G: jnp.ndarray, cfg: CacqrConfig, m_rows: int):
    """(R, R⁻¹) of the UPPER-VALID gram, shared by every fused/panel tier.

    Wide grams route through the recursive cholinv: the whole-matrix lax
    chol+solve serializes its panel sweep (measured 10.7 ms at n=4096 ≈
    17 TF/s); the framework's own factor does the same job in ~3.9 ms.
    cholinv reads ONLY the upper triangle (its potrf_trtri_upper
    base-case contract, verified bit-identical under a garbage lower
    half), so the gram kernels' upper-block-row output feeds it with NO
    symmetric-assembly pass; below the crossover the upper-valid factor
    pair does the same.  The caller's nested cholinv config carries the
    --bc knob; complete_inv is FORCED True — these tiers multiply by the
    full triangular inverse (the partial-inverse contract is the dist
    regime's blocked solve, solve_blocked)."""
    n = G.shape[0]
    if n >= 2048 and grid.num_devices == 1:
        # robust=None on the NESTED config: the session's guarded_chol
        # owns detection here — a 3-tuple from cholinv would break the
        # (R, Rinv) contract every tier builds on
        ccfg = dataclasses.replace(
            cfg.cholinv, mode=cfg.mode, precision=cfg.precision,
            complete_inv=True, robust=None,
        )
        return _chol_site(G, m_rows, lambda g_: cholesky.factor(grid, g_, ccfg))
    return _chol_site(G, m_rows, lapack.potrf_trtri_upper)


def _cqr2_fused(
    grid: Grid, A: jnp.ndarray, cfg: CacqrConfig, g: int, plan: str = "full"
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """CQR2 through the fused tall-pass kernels (ops/qr_fused.py): sweep 1's
    gram in one A read, sweep 1's scale and sweep 2's gram in one shared
    pass (Q1 is written once and its gram taken from registers — the
    re-read the unfused pipeline pays is gone), then the standard blocked
    scale and triangular merge.  `g` is the in-kernel column split
    (executed flops (g+1)/2g of dense at zero extra HBM — VERDICT r3 #1).
    Numerically the same pipeline as two _sweep_1d calls (grams from the
    rounded Q, f32 accumulation) up to reduction association order.
    `plan` picks the tier (qr_fused.fused_plan): 'full' shares sweep 1's
    scale and sweep 2's gram in one scale_gram pass; 'split' (wide n) runs
    them as two kernels to stay inside the per-kernel VMEM envelopes."""
    from capital_tpu.ops import qr_fused

    m, n = A.shape
    precision = cfg.precision
    live = qr_fused.live_fraction(g)

    def _chol(G):
        return _gram_chol(grid, G, cfg, m)

    def _gram_out(Gu):
        # both chol routes read only the valid upper triangle — the
        # symmetric assembly pass (n² of block transposes + re-layout,
        # ~3 ms/iter inside the gram scopes at n=4096) is never needed
        return faultinject.tap(Gu.astype(A.dtype))

    with tracing.scope("CQR::gram"):
        tracing.emit(flops=2.0 * m * n * n * live)
        G1 = _gram_out(qr_fused.gram_blocked(A, g=g, precision=precision))
    with tracing.scope("CQR::chol"):
        tracing.emit(flops=tracing.potrf_trtri_flops(n))
        R1, R1inv = _chol(G1)
    with tracing.scope("CQR::fused"):
        # scale1 (live) + gram2 (live): one shared read of A on the 'full'
        # tier; the wide-n 'split' tier runs them as two kernels (sweep 2's
        # gram re-reads the written Q1 — one extra HBM pass, every in-kernel
        # flop saving kept; see qr_fused.fused_plan)
        tracing.emit(flops=2.0 * m * n * n * (live + live))
        if plan == "split":
            Q1 = qr_fused.scale_blocked(
                A, jnp.triu(R1inv), g=g, precision=precision
            )
            G2 = qr_fused.gram_blocked(Q1, g=g, precision=precision)
        else:
            Q1, G2 = qr_fused.scale_gram(
                A, jnp.triu(R1inv), g=g, precision=precision
            )
        G2 = _gram_out(G2)
    with tracing.scope("CQR::chol"):
        tracing.emit(flops=tracing.potrf_trtri_flops(n))
        R2, R2inv = _chol(G2)
    with tracing.scope("CQR::formR"):
        tracing.emit(flops=2.0 * m * n * n * live)
        Q = qr_fused.scale_blocked(Q1, jnp.triu(R2inv), g=g, precision=precision)
    with tracing.scope("CQR::merge"):
        tracing.emit(flops=2.0 * n**3)
        R = jnp.matmul(jnp.triu(R2), jnp.triu(R1), precision=precision)
    return Q, R


def _cqr2_panels(
    grid: Grid, A: jnp.ndarray, cfg: CacqrConfig, c: int = 512
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """CQR2 for very wide n — past EVERY fused kernel's VMEM envelope
    (qr_fused.fused_plan tier 'panels').  Pure-XLA panel pipeline with the
    same triangular flop structure the kernels exploit:

      gram:  column-panel j needs only rows [0, (j+1)c) — the product
             X[:, :(j+1)c]ᵀ · X[:, jc:(j+1)c] IS the valid upper part,
             zero-padded below (cholinv's upper-only read contract).
      scale: Q[:, jc:(j+1)c] = X[:, :(j+1)c] · R⁻¹[: (j+1)c, panel]
             (upper-triangular R⁻¹: the zero lower blocks never load).

    Executed flops are (g+1)/2g of dense, like the kernels.  The extra
    operand reads (panel j re-reads X's leading columns) that made the
    XLA-level split a measured LOSER at n=1024 (docs/PERF.md round-2) are
    noise here: arithmetic intensity ~n/(g+1) ≈ 512 flops/byte at n=8192,
    far above the v5e compute/bandwidth ratio (~240) — the pipeline is
    MXU-bound, XLA pipelines the HBM traffic under it.  The n×n gram
    factor rides the recursive cholinv (n ≥ 2048 always holds here)."""
    m, n = A.shape
    g = n // c
    precision = cfg.precision
    live = (g + 1) / (2.0 * g)

    def _chol(G):
        return _gram_chol(grid, G, cfg, m)

    def gram(X):
        cols = []
        for j in range(g):
            P = jnp.matmul(
                X[:, : (j + 1) * c].T, X[:, j * c : (j + 1) * c],
                precision=precision,
            )
            cols.append(jnp.pad(P, ((0, n - (j + 1) * c), (0, 0))))
        return faultinject.tap(jnp.concatenate(cols, axis=1).astype(A.dtype))

    def scale(X, Rinv):
        Rt = jnp.triu(Rinv)
        return jnp.concatenate(
            [
                jnp.matmul(
                    X[:, : (j + 1) * c],
                    Rt[: (j + 1) * c, j * c : (j + 1) * c],
                    precision=precision,
                )
                for j in range(g)
            ],
            axis=1,
        ).astype(A.dtype)

    with tracing.scope("CQR::gram"):
        tracing.emit(flops=2.0 * m * n * n * live)
        G1 = gram(A)
    with tracing.scope("CQR::chol"):
        tracing.emit(flops=tracing.potrf_trtri_flops(n))
        R1, R1inv = _chol(G1)
    with tracing.scope("CQR::fused"):
        tracing.emit(flops=2.0 * m * n * n * (live + live))
        Q1 = scale(A, R1inv)
        G2 = gram(Q1)
    with tracing.scope("CQR::chol"):
        tracing.emit(flops=tracing.potrf_trtri_flops(n))
        R2, R2inv = _chol(G2)
    with tracing.scope("CQR::formR"):
        tracing.emit(flops=2.0 * m * n * n * live)
        Q = scale(Q1, R2inv)
    with tracing.scope("CQR::merge"):
        tracing.emit(flops=2.0 * n**3)
        R = jnp.matmul(jnp.triu(R2), jnp.triu(R1), precision=precision)
    return Q, R


def _cqr2_fused_sharded(
    grid: Grid, A: jnp.ndarray, cfg: CacqrConfig, g: int, plan: str = "full"
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The fused CQR2 pipeline on a mesh: the SAME Mosaic kernels, run PER
    SHARD inside one shard_map over the row-sharded operand (VERDICT r4 #2
    — the reference gets its local-BLAS flop saving on every rank,
    blas/interface.hpp:74-97; here every chip runs the fused tall-pass
    kernels on its own m/p rows).  Mosaic custom calls cannot be GSPMD-
    partitioned (the round-4 AOT finding), but inside shard_map the
    partitioning is manual — each shard's kernel call is a single-device
    program, so the same `vma`-annotated kernels compile for the 8-chip
    topology (witnessed by bench.aot65536 --alg cacqr).

    Per shard:  G1 += psum(gram(A_loc));  chol+inv replicated;
    (Q1_loc, G2_part) = scale_gram(A_loc, R1inv);  G2 = psum;  chol+inv;
    Q_loc = scale_blocked(Q1_loc, R2inv);  R = R2·R1.  The two psums are
    the pipeline's ONLY collectives — identical to the unfused 1d tree
    (reference MPI_Allreduce over world, cacqr.hpp:14-25)."""
    from capital_tpu.ops import qr_fused

    n = A.shape[1]
    precision = cfg.precision
    live = qr_fused.live_fraction(g)
    axes = ("x", "y", "z")

    def body(a_loc):
        # trace-time emissions run once, inside the body: all quantities
        # are already per-device (the Recorder's convention — _sweep_1d
        # divides global flops by num_devices to land at the same figures)
        m_loc = a_loc.shape[0]
        comm, ncoll = tracing.allreduce_cost(grid, n, n, jnp.float32, axes="all")
        with tracing.scope("CQR::gram"):
            tracing.emit(
                flops=2.0 * m_loc * n * n * live, comm_bytes=comm,
                collectives=ncoll,
            )
            G1u = lax.psum(
                qr_fused.gram_blocked(a_loc, g=g, precision=precision), axes
            )
            # the psum'd gram keeps the kernel's upper-block-row validity;
            # the upper-valid factor pair reads only that triangle, so no
            # per-shard symmetric assembly pass (same rule as _cqr2_fused)
            G1 = G1u.astype(A.dtype)
        with tracing.scope("CQR::chol"):
            tracing.emit(flops=tracing.potrf_trtri_flops(n))
            R1, R1inv = lapack.potrf_trtri_upper(G1)
        with tracing.scope("CQR::fused"):
            tracing.emit(
                flops=2.0 * m_loc * n * n * (live + live), comm_bytes=comm,
                collectives=ncoll,
            )
            if plan == "split":
                Q1 = qr_fused.scale_blocked(
                    a_loc, jnp.triu(R1inv), g=g, precision=precision
                )
                G2u = qr_fused.gram_blocked(Q1, g=g, precision=precision)
            else:
                Q1, G2u = qr_fused.scale_gram(
                    a_loc, jnp.triu(R1inv), g=g, precision=precision
                )
            G2 = lax.psum(G2u, axes).astype(A.dtype)
        with tracing.scope("CQR::chol"):
            tracing.emit(flops=tracing.potrf_trtri_flops(n))
            R2, R2inv = lapack.potrf_trtri_upper(G2)
        with tracing.scope("CQR::formR"):
            tracing.emit(flops=2.0 * m_loc * n * n * live)
            Q = qr_fused.scale_blocked(
                Q1, jnp.triu(R2inv), g=g, precision=precision
            )
        with tracing.scope("CQR::merge"):
            tracing.emit(flops=2.0 * n**3)
            R = jnp.matmul(jnp.triu(R2), jnp.triu(R1), precision=precision)
        return Q, R

    # check_vma=False: pallas's interpret-mode evaluator (the CPU test rig)
    # builds its grid-carry init with empty varying-axes and trips the vma
    # matcher against the per-shard operands — an interpreter limitation,
    # not a replication hazard: R is computed identically on every shard
    # from psum'd grams (gated by the mesh tests' residual checks), and the
    # Mosaic path also compiles under check_vma=True (the vma-annotated
    # out_shapes stay for that).
    Q, R = jax_compat.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=P(axes, None),
        out_specs=(P(axes, None), P()),
        check_vma=False,
    )(lax.with_sharding_constraint(A, grid.rows_sharding()))
    return Q, R


def _sweep_dist(
    grid: Grid, A: jnp.ndarray, cfg: CacqrConfig
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One CQR sweep, distributed regime (reference sweep_3d, cacqr.hpp:82-116).

    Gram via distributed syrk, then **cholinv on the gram** (the wiring at
    cacqr.hpp:103), then Q = A·R⁻¹ by SUMMA trmm — or, when cholinv is run
    without the completed inverse, the 2x2 blocked solve (cacqr.hpp:46-73).
    """
    A = grid.pin(A)
    with tracing.scope("CQR::gram"):
        G = summa.syrk(
            grid, A, args=SyrkArgs(trans=True, precision=cfg.precision), mode=cfg.mode
        )
        G = faultinject.tap(G)
    with tracing.scope("CQR::chol"):
        ccfg = dataclasses.replace(cfg.cholinv, robust=None)
        R, Rinv = _chol_site(
            G, A.shape[0], lambda g_: cholesky.factor(grid, g_, ccfg)
        )
    with tracing.scope("CQR::formR"):
        if cfg.cholinv.complete_inv:
            Q = summa.trmm(
                grid, Rinv, A,
                TrmmArgs(side="R", uplo="U", precision=cfg.precision), mode=cfg.mode,
            )
        else:
            Q = solve_blocked(grid, A, R, Rinv, cfg)
    return Q, R


def solve_blocked(
    grid: Grid,
    A: jnp.ndarray,
    R: jnp.ndarray,
    Rinv: jnp.ndarray,
    cfg: CacqrConfig,
) -> jnp.ndarray:
    """X = A·R⁻¹ from the *partial* inverse: the 2x2 blocked triangular solve
    that is the reference's de-facto distributed TRSM (cacqr.hpp:46-73).

    With R = [[R11, R12], [0, R22]] and only R11⁻¹, R22⁻¹ available (the
    complete_inv=False contract of cholinv):

        X1 = A1 · R11⁻¹
        X2 = (A2 − X1·R12) · R22⁻¹
    """
    n = R.shape[0]
    n1 = cholesky.top_split(n, cfg.cholinv)
    if n1 == n:
        # single base-case window: Rinv is already the full inverse
        return summa.trmm(
            grid, Rinv, A,
            TrmmArgs(side="R", uplo="U", precision=cfg.precision), mode=cfg.mode,
        )
    A1, A2 = A[:, :n1], A[:, n1:]
    R11inv, R22inv = Rinv[:n1, :n1], Rinv[n1:, n1:]
    R12 = R[:n1, n1:]
    X1 = summa.trmm(
        grid, R11inv, A1,
        TrmmArgs(side="R", uplo="U", precision=cfg.precision), mode=cfg.mode,
    )
    A2p = summa.gemm(
        grid, X1, R12, A2,
        GemmArgs(alpha=-1.0, beta=1.0, precision=cfg.precision), mode=cfg.mode,
    )
    X2 = summa.trmm(
        grid, R22inv, A2p,
        TrmmArgs(side="R", uplo="U", precision=cfg.precision), mode=cfg.mode,
    )
    return grid.pin(jnp.concatenate([X1, X2], axis=1))


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------


def pallas_coupled(
    grid: Grid, n: int, mode: str, m: int | None = None, dtype=None
) -> bool:
    """True when a 1d factor's outputs ride ops XLA cannot slice into (Q
    through pallas custom calls — the blocked/fused kernels engaged — and R
    through a whole-input potrf chain), making a one-element benchmark
    carry measurement-safe (harness.timed_loop coupling='elem').  Lives
    HERE, next to the kernel gating it mirrors (_sweep_1d's tri_kernel +
    qr_fused.fused_plan): if the routing changes, this predicate must
    change with it — a stale copy in a driver would let the simplifier
    silently narrow the measured work.

    On a mesh the per-shard fused pipeline (round 5) is the only pallas
    route; deciding it needs the full (m, dtype) question — callers that
    cannot supply them get the conservative False (full-consumption
    coupling is always measurement-safe, just slower)."""
    from capital_tpu.ops import qr_fused

    if grid.num_devices == 1:
        if mode != "pallas":
            return False
        if m is not None and dtype is not None:
            # the authoritative answer: which tier does factor() route to?
            # 'full'/'split' ride Mosaic custom calls (coupled); 'panels'
            # is pure XLA (one-element consumption would let the
            # simplifier drop every other panel — NOT coupled); None
            # falls to the sweeps' tri-kernel predicate below
            g = qr_fused.pick_g(n)
            plan = (
                qr_fused.fused_plan(grid, m, n, mode, g=g, dtype=dtype)
                if g
                else None
            )
            if plan is not None:
                return plan != "panels"
        # sweeps path (or an m/dtype-less caller, which never benches the
        # wide shapes): the nb cap mirrors _sweep_1d's tri_kernel envelope
        return _col_blocks(n) > 1 and n // _col_blocks(n) <= 2048
    if m is None or dtype is None:
        return False
    g = qr_fused.pick_g(n)
    plan = (
        qr_fused.fused_plan(grid, m, n, mode, g=g, dtype=dtype) if g else None
    )
    return plan is not None and plan != "panels"


def _pick_regime(grid: Grid, n: int, cfg: CacqrConfig) -> str:
    # validate up front: an unknown string used to fall through to the dist
    # path silently, turning a typo ('1D', 'fused', ...) into a whole
    # different algorithm with no signal
    if cfg.regime not in ("1d", "dist", "auto"):
        raise ValueError(
            f"unknown regime {cfg.regime!r}; expected '1d', 'dist' or 'auto'"
        )
    if cfg.regime != "auto":
        return cfg.regime
    if grid.dy == 1 and grid.c == 1:
        return "1d"
    return "1d" if n <= cfg.dist_threshold else "dist"


def _factor_core(
    grid: Grid, A: jnp.ndarray, cfg: CacqrConfig, regime: str
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The regime dispatch + sweep pipeline shared by the plain and robust
    entries (factor)."""
    m, n = A.shape
    if regime == "1d":
        from capital_tpu.ops import qr_fused

        g = qr_fused.pick_g(n, cfg.fused_g)
        plan = (
            qr_fused.fused_plan(grid, m, n, cfg.mode, g=g, dtype=A.dtype)
            if cfg.num_iter == 2 and g
            else None
        )
        if plan == "panels":
            # pure-XLA panel pipeline: single-device wide n (the mesh 1d
            # path never engages the crashing kernel route)
            if grid.num_devices == 1:
                return _cqr2_panels(grid, A, cfg)
        elif plan:
            if grid.num_devices == 1:
                return _cqr2_fused(grid, A, cfg, g, plan)
            if not _ROBUST:
                return _cqr2_fused_sharded(grid, A, cfg, g, plan)
            # robust multi-device: the session's traced event values cannot
            # escape the shard_map body — the guarded sweeps run unfused
        Q, R = _sweep_1d(grid, A, cfg)
        if cfg.num_iter == 2:
            Q, R2 = _sweep_1d(grid, Q, cfg)
            with tracing.scope("CQR::merge"):
                tracing.emit(flops=2.0 * R.shape[0] ** 3)
                R = jnp.matmul(jnp.triu(R2), jnp.triu(R), precision=cfg.precision)
        return Q, R
    Q, R = _sweep_dist(grid, A, cfg)
    if cfg.num_iter == 2:
        Q, R2 = _sweep_dist(grid, Q, cfg)
        # merge R = R2 · R1: both upper triangular; small distributed trmm
        # (reference cacqr.hpp:181-189, 204-210)
        with tracing.scope("CQR::merge"):
            R = summa.trmm(
                grid, R2, R,
                TrmmArgs(side="L", uplo="U", precision=cfg.precision), mode=cfg.mode,
            )
    return Q, R


def _finish_robust(grid: Grid, A, Q, R, cfg: CacqrConfig, ses: _Session):
    """Aggregate the session's CholEvents into a RobustInfo and, on
    breakdown, run the escalation ladder: the sCQR3 third sweep (one more
    muted gram + guarded chol + scale) when the orthogonality gate of the
    recovered Q still exceeds tolerance, then — under rcfg.tsqr — the
    blocked Householder TSQR rung (ops/tsqr at the always-f64 escalation
    dtype) when even sCQR3 leaves the gate failing.  Everything is
    lax.cond-gated, so the healthy path executes only the O(n²) status
    reductions.  RobustInfo.gate records WHICH gate a surviving nonzero
    info came from (GATE_ORTHO vs GATE_RESIDUAL, robust/config.py)."""
    rcfg = ses.rcfg
    m, n = Q.shape[0], R.shape[0]
    if ses.events:
        infos = jnp.stack([jnp.asarray(ev.info, jnp.int32) for ev in ses.events])
        sigmas = jnp.stack(
            [jnp.asarray(ev.sigma, jnp.float32) for ev in ses.events]
        )
        infos_after = jnp.stack(
            [jnp.asarray(ev.info_after, jnp.int32) for ev in ses.events]
        )
        breakdown = jnp.sum((infos != 0).astype(jnp.int32))
        shifted = jnp.sum((sigmas > 0).astype(jnp.int32))
        sigma = jnp.max(sigmas)
        info = jnp.max(infos_after)
    else:
        breakdown = jnp.int32(0)
        shifted = jnp.int32(0)
        sigma = jnp.float32(0.0)
        info = jnp.int32(0)
    escalated = jnp.int32(0)
    ortho = jnp.float32(-1.0)
    ortho_failed = jnp.bool_(False)
    info3 = jnp.int32(0)
    if rcfg.escalate and ses.events:
        tol = rcfg.ortho_tol
        if tol is None:
            tol = 100.0 * n * recovery.unit_roundoff(Q.dtype)

        def _broke(args):
            Q0, R0 = args
            # CQR::recover scope: named HLO attribution for the audit layer;
            # muted so the cost model keeps describing the healthy path
            # (both cond branches trace — an emit here would double-count)
            with tracing.scope("CQR::recover"), tracing.muted():
                G3 = lax.with_sharding_constraint(
                    jnp.matmul(Q0.T, Q0, precision=cfg.precision),
                    grid.replicated_sharding(),
                )
                gate = (
                    jnp.linalg.norm(G3 - jnp.eye(n, dtype=G3.dtype))
                    / jnp.sqrt(jnp.asarray(n, G3.dtype))
                ).astype(jnp.float32)

                def _polish(args2):
                    Q1, R1 = args2
                    R3, R3inv, ev3 = recovery.guarded_chol(
                        G3, m, rcfg,
                        lambda g_: lapack.potrf_trtri(g_, uplo="U"),
                    )
                    Qp = lax.with_sharding_constraint(
                        jnp.matmul(
                            Q1, jnp.triu(R3inv), precision=cfg.precision
                        ),
                        grid.rows_sharding(),
                    )
                    Rp = jnp.matmul(
                        jnp.triu(R3), jnp.triu(R1), precision=cfg.precision
                    )
                    # re-measure AFTER the third sweep: ortho must report
                    # the returned Q, not the one the escalation replaced
                    G4 = lax.with_sharding_constraint(
                        jnp.matmul(Qp.T, Qp, precision=cfg.precision),
                        grid.replicated_sharding(),
                    )
                    gate2 = (
                        jnp.linalg.norm(G4 - jnp.eye(n, dtype=G4.dtype))
                        / jnp.sqrt(jnp.asarray(n, G4.dtype))
                    ).astype(jnp.float32)
                    return Qp, Rp, jnp.int32(1), ev3.info_after, gate2

                def _skip(args2):
                    Q1, R1 = args2
                    return Q1, R1, jnp.int32(0), jnp.int32(0), gate

                Qn, Rn, esc, info3, gate_f = lax.cond(
                    gate > tol, _polish, _skip, (Q0, R0)
                )
            return Qn, Rn, esc, gate_f, info3

        def _fine(args):
            Q0, R0 = args
            return Q0, R0, jnp.int32(0), jnp.float32(-1.0), jnp.int32(0)

        Q, R, escalated, ortho, info3 = lax.cond(
            breakdown > 0, _broke, _fine, (Q, R)
        )
        # the sentinel condition: every chol after recovery was clean, yet
        # the final orthogonality gate still fails — cond(A) is beyond what
        # sCQR3 can repair at this precision (per shifted sweep cond drops
        # only by ~sqrt(shift_c*u*(m*n+n(n+1))); in f32 that's a factor of
        # a few — see docs/ROBUSTNESS.md).
        unrecovered = (escalated > 0) & (ortho > tol)
        if rcfg.tsqr:
            # the rung above sCQR3: re-factor A itself with the blocked
            # Householder TSQR at the escalation dtype (always-f64 rule,
            # recovery.escalation_dtype) — no gram, so cond(A) up to ~u⁻¹
            # recovers where every CQR-family sweep stalls.  Gated on the
            # same traced predicate; muted like the other recovery work.
            ct = recovery.escalation_dtype(Q.dtype)
            tol_e = 100.0 * n * recovery.unit_roundoff(ct)

            def _tsqr_rung(args):
                Q1, R1 = args
                with tracing.scope("CQR::recover"), tracing.muted():
                    from capital_tpu.ops import tsqr as tsqr_mod

                    Qt, Rt = tsqr_mod.tsqr(
                        A.astype(ct), precision=cfg.precision
                    )
                    gate_t = tsqr_mod.ortho_gate(Qt, cfg.precision)
                    return Qt.astype(Q1.dtype), Rt.astype(R1.dtype), gate_t

            def _keep_qr(args):
                Q1, R1 = args
                return Q1, R1, ortho

            Q, R, ortho = lax.cond(unrecovered, _tsqr_rung, _keep_qr, (Q, R))
            escalated = jnp.where(unrecovered, jnp.int32(2), escalated)
            # recovered iff the f64-measured gate now passes the f64 tol —
            # the sentinel (and gate code) below read the updated verdict
            unrecovered = unrecovered & (ortho > tol_e)
        ortho_failed = unrecovered
        info = jnp.maximum(
            jnp.maximum(info, info3),
            jnp.where(unrecovered, jnp.int32(n + 2), jnp.int32(0)),
        )
    # which gate does a nonzero info describe?  The ortho-gate sentinel
    # outranks residual statuses (it is the TSQR-escalatable case the
    # routing exists to distinguish — robust/config.GATE_* vocabulary).
    gate_code = jnp.where(
        ortho_failed,
        jnp.int32(config_mod.GATE_ORTHO),
        jnp.where(
            jnp.maximum(info, info3) > 0,
            jnp.int32(config_mod.GATE_RESIDUAL),
            jnp.int32(config_mod.GATE_NONE),
        ),
    )
    return Q, R, RobustInfo(
        info=info, breakdown=breakdown, shifted=shifted, sigma=sigma,
        escalated=escalated, ortho=ortho, gate=gate_code,
    )


@pallas_tpu.scoped_by_grid
def factor(grid: Grid, A: jnp.ndarray, cfg: CacqrConfig = CacqrConfig()):
    """QR of tall-skinny A: returns (Q, R) with A = QR, R upper triangular.

    Equivalent of qr::cacqr::factor (cacqr.hpp:216-245); jit-friendly.
    num_iter=2 (CQR2) merges the two sweeps' triangular factors with a
    trmm, R = R2·R1 (cacqr.hpp:181-189, 204-210).

    With cfg.robust set the return is (Q, R, RobustInfo): every Cholesky
    site is breakdown-guarded, broken grams re-factor with the sCQR shift,
    and the sCQR3 third sweep runs when the recovered Q's orthogonality
    gate still exceeds tolerance (docs/ROBUSTNESS.md).  RobustInfo.info is
    the residual status AFTER recovery — nonzero means the result is still
    bad (e.g. a non-finite input) and must not be trusted.
    """
    m, n = A.shape
    if m < n:
        raise ValueError(f"cacqr expects tall-skinny input, got {A.shape}")
    if cfg.num_iter not in (1, 2):
        raise ValueError(f"num_iter must be 1 (CQR) or 2 (CQR2), got {cfg.num_iter}")
    regime = _pick_regime(grid, n, cfg)
    if cfg.robust is None:
        return _factor_core(grid, A, cfg, regime)
    ses = _Session(cfg.robust)
    _ROBUST.append(ses)
    try:
        Q, R = _factor_core(grid, A, cfg, regime)
    finally:
        _ROBUST.pop()
    return _finish_robust(grid, A, Q, R, cfg, ses)


def apply_Q(
    grid: Grid,
    Q: jnp.ndarray,
    X: jnp.ndarray,
    mode: str = "xla",
    precision: str | None = "highest",
) -> jnp.ndarray:
    """Q @ X (reference apply_Q = SUMMA gemm, cacqr.hpp:272-280)."""
    return summa.gemm(grid, Q, X, args=GemmArgs(precision=precision), mode=mode)


def apply_QT(
    grid: Grid,
    Q: jnp.ndarray,
    X: jnp.ndarray,
    mode: str = "xla",
    precision: str | None = "highest",
) -> jnp.ndarray:
    """Qᵀ @ X.  The reference left this as static_assert(0) (cacqr.hpp:284);
    implemented here — it is just the transposed gemm."""
    return summa.gemm(
        grid, Q, X, args=GemmArgs(trans_a=True, precision=precision), mode=mode
    )
