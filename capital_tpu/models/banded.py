"""Symmetric banded systems on the blocktri fast path (round 13).

A symmetric positive-definite banded matrix with bandwidth ``u`` (``u``
sub/super-diagonals) IS a block-tridiagonal chain once re-blocked at any
block size ``b >= u``: every entry ``A[p, q]`` with ``|p - q| <= u`` lands
either inside a diagonal block ``D_i`` or inside the coupling ``C_i``
between ADJACENT blocks — never further, which is exactly the chain
contract ``models/blocktri`` factors at O(nblocks·b³).  This module is
the thin adapter: gather the LAPACK-style band storage into ``(D, C)``
chain blocks (a vectorized index map, no Python loop over n), pad the
tail block's diagonal with identity rows so the chain length divides,
and ride ``blocktri.posv`` unchanged — sequential scan or the
partitioned Spike driver, whichever the dispatch picks for the geometry.

Band storage follows ``scipy.linalg.solveh_banded`` exactly (the parity
test's reference): ``ab`` has shape ``(u + 1, n)``; in LOWER form
``ab[d, i] = A[i + d, i]`` (main diagonal in row 0), in UPPER form
``ab[u + i - j, j] = A[i, j]`` for ``i <= j`` (main diagonal in the last
row).  The identity padding keeps the padded matrix SPD and the padded
solution rows exactly zero for zero RHS rows, so un-padding is a slice.

Round 15 adds the BORDERED variant: a banded matrix plus ``s`` explicit
dense rows/columns coupling every unknown to a small dense corner —
the classic bordered-banded system (constrained splines, periodic
boundary wrap-around, equality-constrained banded least squares).  The
same re-blocking plus a column-chunking of the border rows lands it on
``models/arrowhead.posv`` unchanged (``solveh_bordered``).
"""

from __future__ import annotations

import jax.numpy as jnp

from capital_tpu.models import arrowhead, blocktri

__all__ = ["resolve_block", "to_blocktri", "solveh_banded",
           "solveh_bordered"]

#: default re-blocking size floor: blocks this small under-fill even the
#: CPU scan steps; the bandwidth still wins when it is larger.
_MIN_BLOCK = 8


def resolve_block(u: int, n: int, block: int = 0) -> int:
    """The chain block size a bandwidth-``u`` re-blocking uses: any
    ``b >= max(u, 1)`` is correct (couplings then never span more than
    one block boundary); the default takes ``max(u, 8)`` capped at ``n``
    so a narrow band still forms reasonably sized scan steps.  An
    explicit ``block`` below the bandwidth is an error, not a silent
    widening — the caller sized a bucket with it."""
    if block:
        if block < max(u, 1):
            raise ValueError(
                f"banded: block {block} is below the bandwidth {u} — "
                "couplings would span non-adjacent blocks"
            )
        return block
    return max(u, _MIN_BLOCK, 1) if n >= _MIN_BLOCK else max(u, 1, n)


def _lower_form(ab, lower: bool):
    """Canonicalize band storage to LOWER form (``ab[d, i] = A[i+d, i]``).

    The upper form stores ``A[i, j] = ab[u + i - j, j]`` (i <= j); the
    lower entry ``A[i + d, i] = A[i, i + d]`` therefore sits at
    ``ab[u - d, i + d]`` — a diagonal-wise roll, vectorized here."""
    ab = jnp.asarray(ab)
    if ab.ndim != 2:
        raise ValueError(f"banded: ab must be 2-D (u+1, n), got {ab.shape}")
    if lower:
        return ab
    u, n = ab.shape[0] - 1, ab.shape[1]
    d = jnp.arange(u + 1)[:, None]
    i = jnp.arange(n)[None, :]
    src = jnp.clip(i + d, 0, n - 1)
    return jnp.where(i + d < n, ab[u - d, src], 0)


def to_blocktri(ab, *, lower: bool = False, block: int = 0):
    """Re-block band storage into the blocktri chain ``(D, C, n)``.

    Returns ``D (nblocks, b, b)``, ``C (nblocks, b, b)`` (``C[0] = 0``,
    ``C[i]`` couples block i to i−1 — the chain convention) and the
    original order ``n``; ``nblocks·b >= n`` with identity rows padding
    the tail block's diagonal.  Pure gather: ``D_i[r, c] =
    ab[|r−c|, i·b + min(r, c)]`` and ``C_i[r, c] = ab[b + r − c,
    (i−1)·b + c]``, each masked to the band."""
    ab = _lower_form(ab, lower)
    u, n = ab.shape[0] - 1, ab.shape[1]
    if n == 0:
        raise ValueError("banded: empty operand (n = 0)")
    b = resolve_block(u, n, block)
    nblocks = -(-n // b)
    pad = nblocks * b - n
    abp = jnp.pad(ab, ((0, 0), (0, pad)))
    r = jnp.arange(b)[:, None]
    c = jnp.arange(b)[None, :]
    i = jnp.arange(nblocks)[:, None, None]
    # diagonal blocks: band row |r−c|, band column at the block offset
    dband = jnp.abs(r - c)
    dcol = i * b + jnp.minimum(r, c)
    D = jnp.where(dband <= u, abp[jnp.minimum(dband, u), dcol], 0)
    # identity on padded diagonal rows keeps the chain SPD and the
    # padded solution rows at exactly zero for zero RHS rows
    D = D + jnp.where((i * b + r >= n) & (r == c),
                      jnp.ones((), abp.dtype), 0)
    # couplings: A[i·b + r, (i−1)·b + c] sits on band row b + r − c,
    # which is inside the band only for the block's upper-right corner
    cband = b + r - c
    ccol = jnp.clip((i - 1) * b + c, 0, nblocks * b - 1)
    C = jnp.where((cband <= u) & (i >= 1),
                  abp[jnp.minimum(cband, u), ccol], 0)
    return D, C, n


def solveh_banded(ab, rhs, *, lower: bool = False, block: int = 0,
                  **posv_kwargs):
    """Solve the SPD banded system — ``scipy.linalg.solveh_banded``'s
    calling convention on the blocktri fast path.  ``rhs`` is ``(n,)`` or
    ``(n, k)``; returns ``x`` of the same shape.  Extra keyword arguments
    flow to ``blocktri.posv`` unchanged (impl / partitions /
    partition_inner / precision — so a banded solve can ride the
    partitioned driver exactly like a native chain).  Raises on reported
    breakdown like scipy (the chain's global potrf info, mapped to the
    padded order's first failing leading minor)."""
    D, C, n = to_blocktri(ab, lower=lower, block=block)
    rhs = jnp.asarray(rhs, D.dtype)
    squeeze = rhs.ndim == 1
    if squeeze:
        rhs = rhs[:, None]
    if rhs.shape[0] != n:
        raise ValueError(
            f"banded: rhs has {rhs.shape[0]} rows, operand order is {n}"
        )
    nblocks, b = D.shape[0], D.shape[1]
    Bp = jnp.pad(rhs, ((0, nblocks * b - n), (0, 0)))
    Bp = Bp.reshape(nblocks, b, rhs.shape[1])
    X, info = blocktri.posv(D[None], C[None], Bp[None], **posv_kwargs)
    bad = int(info[0])
    if bad:
        raise ValueError(
            f"banded: leading minor of order {bad} is not positive "
            "definite (blocktri posv info)"
        )
    x = X[0].reshape(nblocks * b, rhs.shape[1])[:n]
    return x[:, 0] if squeeze else x


def solveh_bordered(ab, border, corner, rhs, rhs_corner, *,
                    lower: bool = False, block: int = 0, **posv_kwargs):
    """Solve the SPD bordered-banded system on the arrowhead fast path.

    The matrix is ``[[T, Bᵀ], [B, S]]`` with ``T`` banded in
    ``solveh_banded`` storage (``ab``, same ``lower`` convention),
    ``border`` the explicit dense rows ``B`` of shape ``(s, n)``, and
    ``corner`` the ``(s, s)`` dense block ``S``.  Re-blocks ``ab`` into
    the chain exactly like ``solveh_banded``, chunks the border columns
    into the per-block ``(s, b)`` coupling blocks ``models/arrowhead``
    expects (zero columns over the identity tail padding keep the padded
    matrix SPD and the arrowhead math exact), and rides
    ``arrowhead.posv`` unchanged — extra keyword arguments flow through
    (impl / partitions / partition_inner / precision).  ``rhs`` is
    ``(n,)`` or ``(n, k)`` with ``rhs_corner`` matching over ``(s,)``;
    returns ``(x, x_corner)`` of those shapes.  Breakdown raises like
    ``solveh_banded``, with corner pivots reported in the ORIGINAL
    bordered order ``n + s`` (the tail-padding offset is subtracted —
    docs/ROBUSTNESS.md, corner pivot offset)."""
    D, C, n = to_blocktri(ab, lower=lower, block=block)
    border = jnp.asarray(border, D.dtype)
    corner = jnp.asarray(corner, D.dtype)
    if border.ndim != 2 or border.shape[1] != n:
        raise ValueError(
            f"banded: border must be (s, n) = (s, {n}) dense rows, got "
            f"{border.shape}"
        )
    s = border.shape[0]
    if corner.shape != (s, s):
        raise ValueError(
            f"banded: corner must be (s, s) = ({s}, {s}), got {corner.shape}"
        )
    rhs = jnp.asarray(rhs, D.dtype)
    rhs_corner = jnp.asarray(rhs_corner, D.dtype)
    squeeze = rhs.ndim == 1
    if squeeze:
        rhs, rhs_corner = rhs[:, None], rhs_corner[:, None]
    if rhs.shape[0] != n or rhs_corner.shape[0] != s:
        raise ValueError(
            f"banded: rhs/rhs_corner have {rhs.shape[0]}/"
            f"{rhs_corner.shape[0]} rows, operand orders are {n}/{s}"
        )
    nblocks, b = D.shape[0], D.shape[1]
    pad = nblocks * b - n
    # border columns chunk into per-block (s, b) couplings; the padded
    # tail columns are zero, so the identity diagonal rows stay decoupled
    F = jnp.pad(border, ((0, 0), (0, pad))).reshape(s, nblocks, b)
    F = jnp.swapaxes(F, 0, 1)
    Bp = jnp.pad(rhs, ((0, pad), (0, 0))).reshape(nblocks, b, rhs.shape[1])
    X, Xs, info = arrowhead.posv(
        D[None], C[None], F[None], corner[None], Bp[None],
        rhs_corner[None], **posv_kwargs)
    bad = int(info[0])
    if bad:
        if bad > nblocks * b:
            bad -= pad  # corner pivots back to the unpadded order
        raise ValueError(
            f"banded: leading minor of order {bad} is not positive "
            "definite (arrowhead posv info)"
        )
    x = X[0].reshape(nblocks * b, rhs.shape[1])[:n]
    xs = Xs[0]
    return (x[:, 0], xs[:, 0]) if squeeze else (x, xs)
