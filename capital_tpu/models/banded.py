"""Symmetric banded systems on the blocktri fast path (round 13).

A symmetric positive-definite banded matrix with bandwidth ``u`` (``u``
sub/super-diagonals) IS a block-tridiagonal chain once re-blocked at any
block size ``b >= u``: every entry ``A[p, q]`` with ``|p - q| <= u`` lands
either inside a diagonal block ``D_i`` or inside the coupling ``C_i``
between ADJACENT blocks — never further, which is exactly the chain
contract ``models/blocktri`` factors at O(nblocks·b³).  This module is
the thin adapter: gather the LAPACK-style band storage into ``(D, C)``
chain blocks (a vectorized index map, no Python loop over n), pad the
tail block's diagonal with identity rows so the chain length divides,
and ride ``blocktri.posv`` unchanged — sequential scan or the
partitioned Spike driver, whichever the dispatch picks for the geometry.

Band storage follows ``scipy.linalg.solveh_banded`` exactly (the parity
test's reference): ``ab`` has shape ``(u + 1, n)``; in LOWER form
``ab[d, i] = A[i + d, i]`` (main diagonal in row 0), in UPPER form
``ab[u + i - j, j] = A[i, j]`` for ``i <= j`` (main diagonal in the last
row).  The identity padding keeps the padded matrix SPD and the padded
solution rows exactly zero for zero RHS rows, so un-padding is a slice.
"""

from __future__ import annotations

import jax.numpy as jnp

from capital_tpu.models import blocktri

__all__ = ["resolve_block", "to_blocktri", "solveh_banded"]

#: default re-blocking size floor: blocks this small under-fill even the
#: CPU scan steps; the bandwidth still wins when it is larger.
_MIN_BLOCK = 8


def resolve_block(u: int, n: int, block: int = 0) -> int:
    """The chain block size a bandwidth-``u`` re-blocking uses: any
    ``b >= max(u, 1)`` is correct (couplings then never span more than
    one block boundary); the default takes ``max(u, 8)`` capped at ``n``
    so a narrow band still forms reasonably sized scan steps.  An
    explicit ``block`` below the bandwidth is an error, not a silent
    widening — the caller sized a bucket with it."""
    if block:
        if block < max(u, 1):
            raise ValueError(
                f"banded: block {block} is below the bandwidth {u} — "
                "couplings would span non-adjacent blocks"
            )
        return block
    return max(u, _MIN_BLOCK, 1) if n >= _MIN_BLOCK else max(u, 1, n)


def _lower_form(ab, lower: bool):
    """Canonicalize band storage to LOWER form (``ab[d, i] = A[i+d, i]``).

    The upper form stores ``A[i, j] = ab[u + i - j, j]`` (i <= j); the
    lower entry ``A[i + d, i] = A[i, i + d]`` therefore sits at
    ``ab[u - d, i + d]`` — a diagonal-wise roll, vectorized here."""
    ab = jnp.asarray(ab)
    if ab.ndim != 2:
        raise ValueError(f"banded: ab must be 2-D (u+1, n), got {ab.shape}")
    if lower:
        return ab
    u, n = ab.shape[0] - 1, ab.shape[1]
    d = jnp.arange(u + 1)[:, None]
    i = jnp.arange(n)[None, :]
    src = jnp.clip(i + d, 0, n - 1)
    return jnp.where(i + d < n, ab[u - d, src], 0)


def to_blocktri(ab, *, lower: bool = False, block: int = 0):
    """Re-block band storage into the blocktri chain ``(D, C, n)``.

    Returns ``D (nblocks, b, b)``, ``C (nblocks, b, b)`` (``C[0] = 0``,
    ``C[i]`` couples block i to i−1 — the chain convention) and the
    original order ``n``; ``nblocks·b >= n`` with identity rows padding
    the tail block's diagonal.  Pure gather: ``D_i[r, c] =
    ab[|r−c|, i·b + min(r, c)]`` and ``C_i[r, c] = ab[b + r − c,
    (i−1)·b + c]``, each masked to the band."""
    ab = _lower_form(ab, lower)
    u, n = ab.shape[0] - 1, ab.shape[1]
    if n == 0:
        raise ValueError("banded: empty operand (n = 0)")
    b = resolve_block(u, n, block)
    nblocks = -(-n // b)
    pad = nblocks * b - n
    abp = jnp.pad(ab, ((0, 0), (0, pad)))
    r = jnp.arange(b)[:, None]
    c = jnp.arange(b)[None, :]
    i = jnp.arange(nblocks)[:, None, None]
    # diagonal blocks: band row |r−c|, band column at the block offset
    dband = jnp.abs(r - c)
    dcol = i * b + jnp.minimum(r, c)
    D = jnp.where(dband <= u, abp[jnp.minimum(dband, u), dcol], 0)
    # identity on padded diagonal rows keeps the chain SPD and the
    # padded solution rows at exactly zero for zero RHS rows
    D = D + jnp.where((i * b + r >= n) & (r == c),
                      jnp.ones((), abp.dtype), 0)
    # couplings: A[i·b + r, (i−1)·b + c] sits on band row b + r − c,
    # which is inside the band only for the block's upper-right corner
    cband = b + r - c
    ccol = jnp.clip((i - 1) * b + c, 0, nblocks * b - 1)
    C = jnp.where((cband <= u) & (i >= 1),
                  abp[jnp.minimum(cband, u), ccol], 0)
    return D, C, n


def solveh_banded(ab, rhs, *, lower: bool = False, block: int = 0,
                  **posv_kwargs):
    """Solve the SPD banded system — ``scipy.linalg.solveh_banded``'s
    calling convention on the blocktri fast path.  ``rhs`` is ``(n,)`` or
    ``(n, k)``; returns ``x`` of the same shape.  Extra keyword arguments
    flow to ``blocktri.posv`` unchanged (impl / partitions /
    partition_inner / precision — so a banded solve can ride the
    partitioned driver exactly like a native chain).  Raises on reported
    breakdown like scipy (the chain's global potrf info, mapped to the
    padded order's first failing leading minor)."""
    D, C, n = to_blocktri(ab, lower=lower, block=block)
    rhs = jnp.asarray(rhs, D.dtype)
    squeeze = rhs.ndim == 1
    if squeeze:
        rhs = rhs[:, None]
    if rhs.shape[0] != n:
        raise ValueError(
            f"banded: rhs has {rhs.shape[0]} rows, operand order is {n}"
        )
    nblocks, b = D.shape[0], D.shape[1]
    Bp = jnp.pad(rhs, ((0, nblocks * b - n), (0, 0)))
    Bp = Bp.reshape(nblocks, b, rhs.shape[1])
    X, info = blocktri.posv(D[None], C[None], Bp[None], **posv_kwargs)
    bad = int(info[0])
    if bad:
        raise ValueError(
            f"banded: leading minor of order {bad} is not positive "
            "definite (blocktri posv info)"
        )
    x = X[0].reshape(nblocks * b, rhs.shape[1])[:n]
    return x[:, 0] if squeeze else x
