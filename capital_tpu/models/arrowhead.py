"""Block-arrowhead Cholesky: a blocktri chain plus a low-rank border.

The shape (ROADMAP item 2(b) — the constrained-least-squares and
Kalman-with-global-state workload) is an SPD matrix

        A = [[T, Bᵀ],
             [B, S ]]

where T is a block-tridiagonal SPD chain (nblocks blocks of size b,
n_T = nblocks·b), B is a THIN border (s rows, s ≪ n_T) coupling every
chain block to a small dense corner S (s × s).  Factoring A dense costs
O((n_T + s)³); riding the chain structure costs

        O(nblocks·b³  +  nblocks·b²·s  +  s³)
          chain factor   border solves   corner chol

— the same structural win `models/blocktri` proved for the pure chain
(PERF.md rounds 10/13), extended by a Schur-complement completion:

        T = L̃·L̃ᵀ                      (blocktri factor, UNCHANGED)
        Z_B = T⁻¹·Bᵀ                  (border columns through the chain)
        S̃  = S − B·Z_B                (Schur complement of T in A)
        S̃  = L_S·L_Sᵀ                 (one dense s×s Cholesky)

and the solve A·[x_T; x_S] = [b_T; b_S] completes as

        Z_r = T⁻¹·b_T
        y   = b_S − B·Z_r
        x_S = L_S⁻ᵀ·(L_S⁻¹·y)
        x_T = Z_r − Z_B·x_S.

**One widened chain solve.**  Z_r and Z_B come out of a SINGLE
`blocktri.posv` call on the widened RHS [b_T | Bᵀ] (k + s columns).
That is deliberate: `posv` is the only blocktri entry point the
partitioned (Spike) driver serves — `factor`/`solve` are sequential-scan
only — so solving the border columns through `posv` is what lets the
whole arrowhead ride `impl='partitioned'` unchanged (the acceptance
criterion "partitioned-chain path works under the border solve").  The
chain work prices itself under blocktri's own BT::* phases at the
widened k + s column count; only the completion the arrowhead ADDS is
priced here, under AH::schur (border gemm + corner chol) and AH::border
(corner RHS correction, corner triangular solves, chain
back-substitution) — see tracing.arrowhead_schur_flops /
arrowhead_border_flops.

**Breakdown coordinates.**  The chain factor reports a LAPACK potrf
status over n_T (blocktri's per-block min-combine); the corner Cholesky
is checked post-hoc by `robust/detect.factor_info` over s.  Both fold
through `detect.combine_block_infos` with the corner window at diagonal
offset n_T, so a returned info = k is 1-based in WHOLE-MATRIX
coordinates: k ≤ n_T is a chain pivot, n_T < k ≤ n_T + s is a corner
pivot (the Schur complement went indefinite — T healthy but A not SPD),
and n_T + s + 1 is the off-diagonal-NaN sentinel.  A chain breakdown
NaN-poisons Z_B and hence S̃, so the corner window also flags — the
min-combine's pivot precedence keeps the EARLIER chain pivot
(docs/ROBUSTNESS.md "Corner pivots in whole-matrix coordinates").

**Serve packing.**  `posv_arrowhead` (serve/batching.py) carries the
chain as the posv_blocktri pack A = (2, nblocks, b, b) and everything
else — border, corner, RHS — as ONE (n_T + s, s + k) tail operand:
column block [:s] is the dense system's last s columns [Bᵀ; S], column
block [s:] is the full RHS [b_T; b_S].  `pack`/`unpack` are that
layout's host/trace-side codecs; geometry (nblocks, b, s, k) reads back
from static shapes alone, so bucket resolution never touches values.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from capital_tpu.models import blocktri
from capital_tpu.robust import detect
from capital_tpu.utils import tracing


def _check_arrowhead(D, C, F, S, B=None, Bs=None, op="arrowhead"):
    """Shape-validate the arrowhead operand family (the chain pair D/C is
    re-checked by blocktri itself; this layer owns the border/corner)."""
    if D.ndim != 4 or D.shape[-1] != D.shape[-2]:
        raise ValueError(
            f"{op}: D must be (batch, nblocks, b, b), got {D.shape}")
    batch, nblocks, b, _ = D.shape
    if F.ndim != 4 or F.shape[:2] != (batch, nblocks) or F.shape[-1] != b:
        raise ValueError(
            f"{op}: F must be (batch, nblocks, s, b) riding D {D.shape}, "
            f"got {F.shape}")
    s = F.shape[2]
    if s < 1:
        raise ValueError(f"{op}: border must have s >= 1 rows, got s={s}")
    if S.shape != (batch, s, s):
        raise ValueError(
            f"{op}: S must be (batch, s, s) = ({batch}, {s}, {s}) riding "
            f"F {F.shape}, got {S.shape}")
    if B is not None:
        if B.ndim != 4 or B.shape[:3] != (batch, nblocks, b):
            raise ValueError(
                f"{op}: B must be (batch, nblocks, b, k) riding D "
                f"{D.shape}, got {B.shape}")
        if Bs.shape != (batch, s, B.shape[-1]):
            raise ValueError(
                f"{op}: Bs must be (batch, s, k) = ({batch}, {s}, "
                f"{B.shape[-1]}) riding B {B.shape}, got {Bs.shape}")


def _combine_info(chain_info, corner_info, nblocks: int, b: int, s: int):
    """Fold the chain's global status (over n_T, sentinel n_T + 1) and the
    corner's local status (over s) into one whole-matrix potrf status.
    Feeding the chain info as a (0, n_T) window is exact: local w in
    [1, n_T] maps to itself and the w == n_T + 1 sentinel maps to the
    global n + 1 sentinel (combine_block_infos' nw + 1 rule)."""
    n_t = nblocks * b
    start = jnp.zeros(chain_info.shape, jnp.int32)
    return detect.combine_block_infos(
        start, [(0, n_t, chain_info), (n_t, s, corner_info)], n_t + s)


def _corner_factor(F, Zb, S, precision):
    """AH::schur — assemble S̃ = S − B·Z_B by one batched gemm reduction
    over the chain blocks and factor it dense.  `lax.linalg.cholesky`
    reads only the lower triangle, so the numerically-unsymmetric upper
    half of S̃ never feeds the factor."""
    batch, nblocks, s, b = F.shape
    with tracing.scope("AH::schur"):
        tracing.emit(
            flops=batch * tracing.arrowhead_schur_flops(nblocks, b, s))
        stilde = S - jnp.einsum("znsb,znbt->zst", F, Zb,
                                precision=precision)
        ls = jnp.linalg.cholesky(stilde)
        corner_info = jax.vmap(detect.factor_info)(ls)
    return stilde, ls, corner_info


def posv(D, C, F, S, B, Bs, *, block: int = 0, seg: int = 0,
         precision: str | None = "highest", impl: str = "auto",
         interpret: bool | None = None, partitions: int = 0,
         partition_inner: str = "auto"):
    """Factor-and-solve the block-arrowhead system A·[x_T; x_S] = [B; Bs].

    Operands:
      D, C — the chain's (batch, nblocks, b, b) diagonal / sub-diagonal
             blocks, exactly blocktri.posv's contract (C[:, 0] ignored);
      F    — the border, (batch, nblocks, s, b): F[:, i] couples chain
             block i to the corner (the dense border is their horizontal
             concatenation, s × n_T);
      S    — the (batch, s, s) dense SPD corner;
      B    — the chain RHS, (batch, nblocks, b, k) (blocked like D);
      Bs   — the corner RHS, (batch, s, k).

    `impl` / `partitions` / `partition_inner` pass straight through to
    the ONE widened blocktri.posv call (module docstring) — sequential
    scan and the partitioned Spike driver both serve the border columns.

    Returns (X, Xs, info): X (batch, nblocks, b, k) chain solution
    blocked like B, Xs (batch, s, k) corner solution, info (batch,)
    int32 whole-matrix potrf status over n = nblocks·b + s (module
    docstring "Breakdown coordinates")."""
    _check_arrowhead(D, C, F, S, B, Bs, op="arrowhead posv")
    batch, nblocks, b, _ = D.shape
    s, k = F.shape[2], B.shape[-1]
    # one widened chain solve: [Z_r | Z_B] = T⁻¹·[B | Bᵀ]
    ft = jnp.swapaxes(F, -1, -2)  # (batch, nblocks, b, s)
    z, chain_info = blocktri.posv(
        D, C, jnp.concatenate([B, ft], axis=-1), block=block, seg=seg,
        precision=precision, impl=impl, interpret=interpret,
        partitions=partitions, partition_inner=partition_inner)
    zr, zb = z[..., :k], z[..., k:]
    _, ls, corner_info = _corner_factor(F, zb, S, precision)
    with tracing.scope("AH::border"):
        tracing.emit(
            flops=batch * tracing.arrowhead_border_flops(nblocks, b, s, k))
        # corner RHS correction y = b_S − B·Z_r, the two (s, s) triangular
        # corner solves, and the chain back-substitution X = Z_r − Z_B·X_s
        t1 = Bs - jnp.einsum("znsb,znbk->zsk", F, zr, precision=precision)
        t2 = lax.linalg.triangular_solve(ls, t1, left_side=True, lower=True)
        xs = lax.linalg.triangular_solve(ls, t2, left_side=True, lower=True,
                                         transpose_a=True)
        x = zr - jnp.einsum("znbs,zsk->znbk", zb, xs, precision=precision)
    return x, xs, _combine_info(chain_info, corner_info, nblocks, b, s)


def schur(D, C, F, S, *, block: int = 0, seg: int = 0,
          precision: str | None = "highest", impl: str = "auto",
          interpret: bool | None = None, partitions: int = 0,
          partition_inner: str = "auto"):
    """The completion HALF of the factorization, exposed for audits and
    benches: border solve Z_B = T⁻¹·Bᵀ, Schur complement
    S̃ = S − B·Z_B, and its dense Cholesky L_S.

    Returns (Zb, Stilde, Ls, info): Zb (batch, nblocks, b, s) blocked
    like the chain, Stilde/Ls (batch, s, s), info (batch,) in
    whole-matrix coordinates like `posv` (the chain status comes from
    the border solve's factor).  `make bench-arrowhead` gates
    ‖L_S·L_Sᵀ − S̃‖ against an f64 NumPy Schur reference through this
    entry point."""
    _check_arrowhead(D, C, F, S, op="arrowhead schur")
    batch, nblocks, b, _ = D.shape
    s = F.shape[2]
    zb, chain_info = blocktri.posv(
        D, C, jnp.swapaxes(F, -1, -2), block=block, seg=seg,
        precision=precision, impl=impl, interpret=interpret,
        partitions=partitions, partition_inner=partition_inner)
    stilde, ls, corner_info = _corner_factor(F, zb, S, precision)
    return zb, stilde, ls, _combine_info(chain_info, corner_info,
                                         nblocks, b, s)


def assemble(D, C, F, S):
    """Materialize the dense (batch, n, n) arrowhead, n = nblocks·b + s —
    test/bench reference only (the point of the module is to never build
    this on the serve path)."""
    _check_arrowhead(D, C, F, S, op="arrowhead assemble")
    batch, nblocks, _, b = D.shape
    s = F.shape[2]
    td = blocktri.assemble(D, C)
    # border rows: (batch, nblocks, s, b) -> (batch, s, nblocks·b)
    bd = jnp.swapaxes(F, 1, 2).reshape(batch, s, nblocks * b)
    top = jnp.concatenate([td, jnp.swapaxes(bd, -1, -2)], axis=-1)
    bot = jnp.concatenate([bd, S], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def pack(F, S, B, Bs):
    """Encode (border, corner, RHS) as serve's (batch, n_T + s, s + k)
    tail operand (module docstring "Serve packing"): rows [:n_T] are the
    chain rows (Bᵀ beside the blocked-flat RHS), rows [n_T:] are the
    corner rows (S beside the corner RHS)."""
    batch, nblocks, s, b = F.shape
    k = B.shape[-1]
    n_t = nblocks * b
    top = jnp.concatenate(
        [jnp.swapaxes(F, -1, -2).reshape(batch, n_t, s),
         B.reshape(batch, n_t, k)], axis=-1)
    bot = jnp.concatenate([S, Bs], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def unpack(P, nblocks: int, b: int):
    """Invert `pack` from static shapes alone: s = rows − nblocks·b,
    k = cols − s.  Returns (F, S, B, Bs)."""
    batch, rows, cols = P.shape
    n_t = nblocks * b
    s = rows - n_t
    k = cols - s
    if s < 1 or k < 0:
        raise ValueError(
            f"arrowhead unpack: packed {P.shape} cannot carry an "
            f"nblocks={nblocks}, b={b} chain (need rows > {n_t})")
    ft = P[:, :n_t, :s].reshape(batch, nblocks, b, s)
    return (jnp.swapaxes(ft, -1, -2), P[:, n_t:, :s],
            P[:, :n_t, s:].reshape(batch, nblocks, b, k), P[:, n_t:, s:])
