"""cholinv: communication-optimal recursive Cholesky + triangular inverse.

The flagship algorithm (reference src/alg/cholesky/cholinv/), re-designed for
TPU.  For SPD A it computes the upper-triangular factor R (A = RᵀR) and,
simultaneously, R⁻¹ — the pair that lets CholeskyQR2 and the SPD inverse
avoid distributed triangular solves.

Reference schedule (cholinv.hpp:87-165), preserved here:

    recurse(A):
      1. R11, R11inv = recurse(A11)                       # top-left
      2. R12 = R11⁻ᵀ · A12                                # TRSM phase (trmm)
      3. A22' = A22 − R12ᵀ·R12                            # Schur update (syrk)
      4. R22, R22inv = recurse(A22')
      5. R12inv = −R11inv · R12 · R22inv                  # inverse completion
         (skipped at the top level when complete_inv=False)

TPU re-design decisions (SURVEY §7.1):

* The reference's runtime window recursion over matrix views
  (`_restrict_`/cursor arithmetic, cholinv.hpp:107-142) becomes **trace-time
  Python recursion over static slices**: each (n, config) pair traces once
  and compiles to a single XLA program.  The reference's two-pass
  simulate/execute split (allocation dry-run at cholinv.hpp:22-26) maps to
  plan (host Python, `plan()`) vs execute (the traced `factor()`).
* Power-of-two padding (reference get_next_power2, util.hpp:249-264, and the
  trueLocalDimension plumbing) becomes one SPD-safe global pad: embed A in
  [[A, 0], [0, I]], factor, crop — the identity block factors to itself and
  never pollutes the A block.
* Base-case gather over the slice communicator + block↔cyclic repack + local
  LAPACK (policy.h:160-224) becomes a sharding constraint (XLA emits the
  all_gather) + lax.linalg on the replicated panel.  See
  utils/config.py:BaseCasePolicy for how the reference's four replication
  policies map.
* Mixed precision: trailing updates run in the input dtype (bf16-friendly);
  the base-case factorization runs in `base_case_dtype` (default f32 for
  low-precision inputs) — panel factorizations are the numerically fragile
  step, trailing matmuls are not.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from capital_tpu.ops import lapack
from capital_tpu.parallel import summa
from capital_tpu.parallel.summa import SyrkArgs, TrmmArgs
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import tracing
from capital_tpu.utils.config import BaseCasePolicy


@dataclasses.dataclass(frozen=True)
class CholinvConfig:
    """User configuration — mirrors cholesky::cholinv::info inputs
    (reference cholinv.h:16-44).

    complete_inv: compute the full R⁻¹ (True) or leave the off-diagonal
        block of the top-level inverse zero (False) — callers like cacqr's
        blocked solve use the diagonal inverse blocks + R12 instead
        (cacqr.hpp:46-73).
    split: recursion split shift — the top window is n >> split, so split=1
        halves (reference cholinv.hpp:15-18 semantics).
    base_case_dim: recursion bottoms out at windows <= this size.  Replaces
        the reference's sign/multiplier encoding (bc_mult_dim) with the size
        itself.
    policy: base-case replication strategy (see BaseCasePolicy).
    mode: SUMMA execution mode for the trmm/syrk phases
        ('xla'|'explicit'|'pallas' — 'pallas' skips dead triangular blocks
        on the MXU for single-device grids, parallel/summa.py).
    base_case_dtype: dtype for the base-case potrf+trtri; None means f32
        when the input is narrower than f32, else the input dtype.
    """

    complete_inv: bool = True
    split: int = 1
    base_case_dim: int = 256
    policy: BaseCasePolicy = BaseCasePolicy.REPLICATE_COMM_COMP
    mode: str = "xla"
    base_case_dtype: Optional[jnp.dtype] = None
    precision: Optional[str] = "highest"  # matmul precision for f32 inputs on
    # TPU: 'highest' keeps the trmm/syrk phases at full f32 (the MXU default
    # of bf16 passes costs ~3 decimal digits in the factor); set None to
    # inherit the context default when chasing raw throughput


# --------------------------------------------------------------------------
# plan: the host-side schedule (reference `simulate`, cholinv.hpp:50-83)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PlanNode:
    """One recursion window: [off, off+n) on the diagonal."""

    off: int
    n: int
    is_base: bool
    top: tuple["PlanNode", "PlanNode"] | None = None  # (A11-node, A22-node)


def padded_dim(n: int, base_case_dim: int) -> int:
    """Smallest base_case_dim * 2^k >= n (reference pads to a power of two,
    util.hpp:249-264; anchoring at the base-case size keeps every window an
    exact multiple of it)."""
    p = min(base_case_dim, n)
    while p < n:
        p *= 2
    return p


def top_split(n: int, cfg: CholinvConfig) -> int:
    """Column index where factor()'s top-level recursion splits the (cropped)
    n x n output — i.e. the boundary of the zeroed off-diagonal block of Rinv
    when complete_inv=False.  Shared by cacqr's blocked solve so the two
    modules cannot drift apart on padding/plan details.  Returns n when the
    whole matrix is a single base-case window (no split)."""
    node = plan(padded_dim(n, cfg.base_case_dim), cfg)
    return n if node.is_base else min(node.top[0].n, n)


def plan(n: int, cfg: CholinvConfig, off: int = 0) -> PlanNode:
    """Build the recursion schedule for a (padded) window of size n.

    Pure host computation — this is the analog of the reference's simulate
    pass: everything shape-dependent is decided here, once, before tracing.
    """
    if cfg.split < 1:
        raise ValueError(f"split must be >= 1 (split={cfg.split} would not shrink the window)")
    if n <= cfg.base_case_dim:
        return PlanNode(off=off, n=n, is_base=True)
    n1 = max(cfg.base_case_dim, n >> cfg.split)
    left = plan(n1, cfg, off)
    right = plan(n - n1, cfg, off + n1)
    return PlanNode(off=off, n=n, is_base=False, top=(left, right))


# --------------------------------------------------------------------------
# execute: the traced recursion (reference `invoke`, cholinv.hpp:87-165)
# --------------------------------------------------------------------------


def _base_case(
    grid: Grid, A: jnp.ndarray, cfg: CholinvConfig
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Leaf factorization: gather + local potrf/trtri (policy.h:160-224).

    REPLICATE_* policies pin the panel replicated (XLA emits one all_gather
    over the mesh; every chip factors the panel redundantly — the TPU-optimal
    choice).  NO_REPLICATION_* leaves placement to the SPMD partitioner, the
    analog of the reference's root-rank strategies.
    """
    bc_dtype = cfg.base_case_dtype
    if bc_dtype is None:
        bc_dtype = A.dtype if jnp.dtype(A.dtype).itemsize >= 4 else jnp.float32
    # phase tag CI::factor_diag (reference cholinv.hpp:94-99)
    with tracing.scope("CI::factor_diag"):
        n = A.shape[0]
        comm, ncoll = (
            (0.0, 0)
            if cfg.policy.single_device_compute
            else tracing.replicate_cost(grid, n, n, bc_dtype)
        )
        tracing.emit(
            flops=tracing.potrf_trtri_flops(n), comm_bytes=comm, collectives=ncoll
        )
        # The leaf window's valid content is its upper triangle (Schur
        # windows arriving from mode='pallas' syrk carry only the upper half
        # — summa.syrk uplo semantics; dense-symmetric windows are a
        # superset).  potrf_trtri_upper factors straight from that triangle
        # with all transposes inside layout-opaque Pallas kernels — an
        # XLA-visible leaf `.T` here cascades into full-matrix relayout
        # copies (see ops/lapack.py:potrf_trtri_upper).
        panel = A.astype(bc_dtype)
        if not cfg.policy.single_device_compute:
            panel = lax.with_sharding_constraint(panel, grid.replicated_sharding())
        R, Rinv = lapack.potrf_trtri_upper(panel)
        return grid.pin(R.astype(A.dtype)), grid.pin(Rinv.astype(A.dtype))


def _recurse(
    grid: Grid,
    A: jnp.ndarray,
    node: PlanNode,
    cfg: CholinvConfig,
    top: bool,
    r_blocks: list,
) -> jnp.ndarray:
    """Returns the assembled Rinv window for this recursion window; R's
    blocks are emitted through `r_blocks`.

    Rinv is assembled per level (its blocks feed the parent's trmm phases as
    whole triangular operands), but R's blocks are only ever *outputs* — no
    later phase consumes an assembled interior R — so they are appended to
    `r_blocks` as (row_off, col_off, block) and scattered into the final
    buffer once, in factor().  Assembling R per level too would rebuild the
    full matrix at every recursion depth (~O(n^2) extra HBM traffic per
    level; measured ~15% of wall time at n=16k on v5e).
    """
    if node.is_base:
        R, Rinv = _base_case(grid, A, cfg)
        r_blocks.append((node.off, node.off, R))
        return Rinv

    left, right = node.top
    n1 = left.n
    A11 = A[:n1, :n1]
    A12 = A[:n1, n1:]
    A22 = A[n1:, n1:]

    # 1. recurse on the top-left window (cholinv.hpp:108-111)
    R11inv = _recurse(grid, A11, left, cfg, False, r_blocks)

    # 2. TRSM phase: R12 = R11⁻ᵀ · A12 (cholinv.hpp:116-123, tag CI::trsm).
    # The reference grid-transposes R11inv then trmms; here the transpose is
    # an argument flag and XLA plans the data motion.
    with tracing.scope("CI::trsm"):
        R12 = summa.trmm(
            grid, R11inv, A12,
            TrmmArgs(side="L", uplo="U", trans_a=True, precision=cfg.precision),
            mode=cfg.mode,
        )

    # 3. Schur complement: A22' = A22 − R12ᵀR12 (cholinv.hpp:131-134, CI::tmu)
    with tracing.scope("CI::tmu"):
        S = summa.syrk(
            grid, R12, A22,
            SyrkArgs(trans=True, alpha=-1.0, beta=1.0, precision=cfg.precision),
            mode=cfg.mode,
        )
    r_blocks.append((node.off, node.off + n1, R12))

    # 4. recurse on the trailing window (cholinv.hpp:139-142)
    R22inv = _recurse(grid, S, right, cfg, False, r_blocks)

    # 5. inverse completion: R⁻¹12 = −R11inv·R12·R22inv (cholinv.hpp:147-156),
    # skipped at the top level when complete_inv=False.
    zeros12 = jnp.zeros_like(R12)
    if cfg.complete_inv or not top:
        with tracing.scope("CI::inv"):
            T = summa.trmm(
                grid, R11inv, R12,
                TrmmArgs(side="L", uplo="U", precision=cfg.precision), mode=cfg.mode,
            )
            R12inv = summa.trmm(
                grid, R22inv, T,
                TrmmArgs(side="R", uplo="U", alpha=-1.0, precision=cfg.precision),
                mode=cfg.mode,
            )
    else:
        R12inv = zeros12

    zeros21 = jnp.zeros((A.shape[0] - n1, n1), dtype=A.dtype)
    Rinv = jnp.block([[R11inv, R12inv], [zeros21, R22inv]])
    return grid.pin(Rinv)


def factor(
    grid: Grid, A: jnp.ndarray, cfg: CholinvConfig = CholinvConfig()
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Factor SPD A into (R, Rinv): A = RᵀR, Rinv = R⁻¹ (upper triangular).

    Equivalent of cholesky::cholinv::factor (cholinv.hpp:6-28); jit-friendly.
    When complete_inv=False the returned Rinv has its top-level off-diagonal
    block zeroed (only the two diagonal inverse blocks are valid), matching
    the reference's contract.
    """
    n = A.shape[0]
    if A.shape[0] != A.shape[1]:
        raise ValueError(f"cholinv needs a square matrix, got {A.shape}")
    p = padded_dim(n, cfg.base_case_dim)
    if p != n:
        # SPD-safe pad: diag(A, I) factors to diag(R, I) without cross-talk.
        pad = ((0, p - n), (0, p - n))
        Ap = jnp.pad(A, pad)
        ii = jnp.arange(p)
        Ap = Ap + jnp.diag((ii >= n).astype(A.dtype))
    else:
        Ap = A
    Ap = grid.pin(Ap)
    r_blocks: list = []
    Rinv = _recurse(grid, Ap, plan(p, cfg), cfg, True, r_blocks)
    # Scatter R's blocks once (each written exactly once; XLA aliases the
    # chain of updates in place) instead of re-assembling per level.
    R = jnp.zeros((p, p), dtype=A.dtype)
    for i, j, blk in r_blocks:
        R = lax.dynamic_update_slice(R, blk, (i, j))
    R = grid.pin(R)
    if p != n:
        R, Rinv = R[:n, :n], Rinv[:n, :n]
    return R, Rinv


def spd_inverse(
    grid: Grid, A: jnp.ndarray, cfg: CholinvConfig = CholinvConfig()
) -> jnp.ndarray:
    """A⁻¹ = R⁻¹·R⁻ᵀ for SPD A — the 'SPD inverse via Cholesky' capability
    (BASELINE.md config row 5)."""
    cfg = dataclasses.replace(cfg, complete_inv=True)
    _, Rinv = factor(grid, A, cfg)
    return summa.gemm(
        grid, Rinv, Rinv,
        args=summa.GemmArgs(trans_b=True, precision=cfg.precision), mode=cfg.mode
    )
