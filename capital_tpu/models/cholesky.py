"""cholinv: communication-optimal recursive Cholesky + triangular inverse.

The flagship algorithm (reference src/alg/cholesky/cholinv/), re-designed for
TPU.  For SPD A it computes the upper-triangular factor R (A = RᵀR) and,
simultaneously, R⁻¹ — the pair that lets CholeskyQR2 and the SPD inverse
avoid distributed triangular solves.

Reference schedule (cholinv.hpp:87-165), preserved here:

    recurse(A):
      1. R11, R11inv = recurse(A11)                       # top-left
      2. R12 = R11⁻ᵀ · A12                                # TRSM phase (trmm)
      3. A22' = A22 − R12ᵀ·R12                            # Schur update (syrk)
      4. R22, R22inv = recurse(A22')
      5. R12inv = −R11inv · R12 · R22inv                  # inverse completion
         (skipped at the top level when complete_inv=False)

TPU re-design decisions (SURVEY §7.1):

* The reference's runtime window recursion over matrix views
  (`_restrict_`/cursor arithmetic, cholinv.hpp:107-142) becomes **trace-time
  Python recursion over static slices**: each (n, config) pair traces once
  and compiles to a single XLA program.  The reference's two-pass
  simulate/execute split (allocation dry-run at cholinv.hpp:22-26) maps to
  plan (host Python, `plan()`) vs execute (the traced `factor()`).
* Power-of-two padding (reference get_next_power2, util.hpp:249-264, and the
  trueLocalDimension plumbing) becomes one SPD-safe global pad: embed A in
  [[A, 0], [0, I]], factor, crop — the identity block factors to itself and
  never pollutes the A block.
* Base-case gather over the slice communicator + block↔cyclic repack + local
  LAPACK (policy.h:160-224) becomes a sharding constraint (XLA emits the
  all_gather) + lax.linalg on the replicated panel.  See
  utils/config.py:BaseCasePolicy for how the reference's four replication
  policies map.
* Mixed precision: trailing updates run in the input dtype (bf16-friendly);
  the base-case factorization runs in `base_case_dtype` (default f32 for
  low-precision inputs) — panel factorizations are the numerically fragile
  step, trailing matmuls are not.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from capital_tpu.ops import lapack, pallas_tpu
from capital_tpu.parallel import summa
from capital_tpu.robust import detect
from capital_tpu.robust.config import RobustConfig
from capital_tpu.parallel.summa import SyrkArgs, TrmmArgs
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import jax_compat, tracing
from capital_tpu.utils.config import BaseCasePolicy


@dataclasses.dataclass(frozen=True)
class CholinvConfig:
    """User configuration — mirrors cholesky::cholinv::info inputs
    (reference cholinv.h:16-44).

    complete_inv: compute the full R⁻¹ (True) or leave the off-diagonal
        block of the top-level inverse zero (False) — callers like cacqr's
        blocked solve use the diagonal inverse blocks + R12 instead
        (cacqr.hpp:46-73).
    split: recursion split shift — the top window is n >> split, so split=1
        halves (reference cholinv.hpp:15-18 semantics).
    base_case_dim: recursion bottoms out at windows <= this size.  Replaces
        the reference's sign/multiplier encoding (bc_mult_dim) with the size
        itself.
    policy: base-case replication strategy (see BaseCasePolicy).
    mode: SUMMA execution mode for the trmm/syrk phases
        ('xla'|'explicit'|'pallas' — 'pallas' skips dead triangular blocks
        on the MXU for single-device grids, parallel/summa.py).
    base_case_dtype: dtype for the base-case potrf+trtri; None means f32
        when the input is narrower than f32, else the input dtype.
    """

    complete_inv: bool = True
    split: int = 1
    base_case_dim: int = 256
    policy: BaseCasePolicy = BaseCasePolicy.REPLICATE_COMM_COMP
    mode: str = "xla"
    base_case_dtype: Optional[jnp.dtype] = None
    precision: Optional[str] = "highest"  # matmul precision for f32 inputs on
    # TPU: 'highest' keeps the trmm/syrk phases at full f32 (the MXU default
    # of bf16 passes costs ~3 decimal digits in the factor); set None to
    # inherit the context default when chasing raw throughput
    balance: str = "block"  # 'tile_cyclic' routes the EXPLICIT-mode
    # trmm/syrk phases through the tile-cyclic balanced schedules
    # (parallel/summa.py) for windows >= balance_min_window: the
    # critical-path device then executes ~the volumetric mean instead of
    # the full dense contraction.  Per-call row-shuffles are O(window²)
    # against O(window³) compute, so only large windows net positive —
    # small ones keep the block schedule (and side-R completion trmms
    # always do; the balanced form is side-L/syrk only).  No effect
    # outside explicit mode.
    # 'tile_cyclic_persistent' instead permutes the WHOLE matrix into the
    # symmetric tile-cyclic layout ONCE at factor entry (tile =
    # base_case_dim // d, so every recursion window stays aligned) and
    # un-permutes R / Rinv once at exit: three lifetime shuffles replace
    # the 2-3 per trmm/syrk call of 'tile_cyclic', every phase (including
    # the side-R completion trmms and the base-case windows) runs
    # balanced, and the per-call min_window economics disappear — so
    # balance_min_window is ignored.  Requires mode='explicit'; topologies
    # the layout cannot cover (d==1, c>1, non-square faces, base_case_dim
    # not divisible by d, or an unaligned split plan) fall back to the
    # block schedule with a 'cholinv::persistent_fallback' tracing note.
    balance_min_window: int = 8192
    schur_in_place: bool = False  # write each Schur complement back into the
    # input buffer (summa.syrk in_place) instead of materializing the
    # Σ(n/2ᵏ)² ≈ n²/3 chain of fresh trailing windows.  Peak memory drops
    # from ~3.35·n² to 3·n² — the knob that fits the n=49152 flagship on one
    # v5e (the reference's FlushIntermediates policy, policy.h:21-156,
    # re-imagined as buffer aliasing).  CONSUMES the caller's A: only safe
    # when A has no later use in the enclosing jit — if it does (e.g. the
    # standard bench loop carrying A across iterations, or a validation
    # reading A afterwards), XLA inserts a full-buffer copy that costs the
    # memory back plus an HBM pass, which is why this is opt-in.
    tail_fuse_depth: int = 0  # fuse recursion-tail subtrees into ONE pallas
    # megakernel (ops/pallas_tpu.fused_tail): any plan() window of size
    # <= base_case_dim << tail_fuse_depth that passes the trace-time gate
    # (_tail_fusible: single device, 128-aligned window, VMEM envelope via
    # batched_small.tail_eligible, f32-or-narrower dtype — f64 always
    # falls back to the unfused recursion) runs potrf, trsm, syrk and the
    # inverse-completion trmms as one launch with the panel VMEM-resident
    # across phases.  0 disables (the default: the fused sweep trades
    # ~12x executed flops for zero inter-phase HBM/launch cost, a win only
    # where the tail is latency-bound — autotune sweeps the depth).
    # depth=1 fuses base-case leaves (5 launches -> 1); each +1 fuses one
    # more recursion level.  Applies in every mode including the d=1
    # explicit path; ignored on multi-device grids and under the
    # persistent tile-cyclic layout.
    base_prefetch: int = 2  # base-case write-back streams in flight: 2
    # routes the leaf's R / R⁻¹ transposes through ONE pallas_call with
    # both output streams live per tile step (pallas_tpu.transpose_pair —
    # the second stream's block loads overlap the first's compute/store,
    # and one kernel launch replaces two); 1 keeps the sequential
    # two-kernel spelling.  Single-device only; bitwise-identical results.
    robust: Optional[RobustConfig] = None  # breakdown DETECTION: factor()
    # returns (R, Rinv, info) with a LAPACK-style int32 status of R
    # (robust/detect.factor_info) instead of NaN-filling silently on a
    # non-SPD input.  Detection only — no shifted rescue here: shifting a
    # user's gram inside cholinv would change the problem being solved;
    # the shifted-CholeskyQR recovery lives in models/qr.factor where the
    # shift is an internal implementation detail of the sweep.


# --------------------------------------------------------------------------
# plan: the host-side schedule (reference `simulate`, cholinv.hpp:50-83)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PlanNode:
    """One recursion window: [off, off+n) on the diagonal."""

    off: int
    n: int
    is_base: bool
    top: tuple["PlanNode", "PlanNode"] | None = None  # (A11-node, A22-node)


def padded_dim(n: int, base_case_dim: int) -> int:
    """Smallest base_case_dim * 2^k >= n (reference pads to a power of two,
    util.hpp:249-264; anchoring at the base-case size keeps every window an
    exact multiple of it)."""
    p = min(base_case_dim, n)
    while p < n:
        p *= 2
    return p


def pad_embed_identity(X: jnp.ndarray, n: int, p: int) -> jnp.ndarray:
    """Embed the n x n matrix X in diag(X, I) of size p — the structure-safe
    pad (reference pads to a power of two, util.hpp:249-264): SPD stays SPD
    and factors to diag(R, I); triangular stays triangular and inverts to
    diag(X⁻¹, I).  Shared by cholinv and rectri so padding policy cannot
    drift between them."""
    if p == n:
        return X
    Xp = jnp.pad(X, ((0, p - n), (0, p - n)))
    ii = jnp.arange(p)
    return Xp + jnp.diag((ii >= n).astype(X.dtype))


def top_split(n: int, cfg: CholinvConfig) -> int:
    """Column index where factor()'s top-level recursion splits the (cropped)
    n x n output — i.e. the boundary of the zeroed off-diagonal block of Rinv
    when complete_inv=False.  Shared by cacqr's blocked solve so the two
    modules cannot drift apart on padding/plan details.  Returns n when the
    whole matrix is a single base-case window (no split)."""
    node = plan(padded_dim(n, cfg.base_case_dim), cfg)
    return n if node.is_base else min(node.top[0].n, n)


def _zeros_plan(grid: Grid, node: PlanNode, cfg: CholinvConfig) -> int:
    """The buffer-initialization decision shared by factor() and
    factor_buffers(): returns the zeros_dead_lower tile size when the
    aligned sparse-init path applies (single device, every leaf window a
    tile multiple), else 0 (plain jnp.zeros).  One function so the two
    callers cannot drift — factor assumes out_buffers satisfy exactly the
    contract factor_buffers built them under."""

    def aligned(nd: PlanNode, tile: int) -> bool:
        if nd.is_base:
            return nd.off % tile == 0 and nd.n % tile == 0
        return all(aligned(c, tile) for c in nd.top)

    tile = min(512, cfg.base_case_dim)
    return tile if grid.num_devices == 1 and aligned(node, tile) else 0


def plan(n: int, cfg: CholinvConfig, off: int = 0) -> PlanNode:
    """Build the recursion schedule for a (padded) window of size n.

    Pure host computation — this is the analog of the reference's simulate
    pass: everything shape-dependent is decided here, once, before tracing.
    """
    if cfg.split < 1:
        raise ValueError(f"split must be >= 1 (split={cfg.split} would not shrink the window)")
    if n <= cfg.base_case_dim:
        return PlanNode(off=off, n=n, is_base=True)
    n1 = max(cfg.base_case_dim, n >> cfg.split)
    left = plan(n1, cfg, off)
    right = plan(n - n1, cfg, off + n1)
    return PlanNode(off=off, n=n, is_base=False, top=(left, right))


def persistent_tile(grid: Grid, node: PlanNode, cfg: CholinvConfig) -> int:
    """The layout tile for balance='tile_cyclic_persistent', or 0 when the
    topology/plan cannot hold the layout.  t = base_case_dim // d makes the
    layout's alignment quantum d*t == base_case_dim, and since every window
    of an aligned plan sits on a base_case_dim boundary, EVERY view of the
    recursion extracts/updates cleanly (parallel/summa.cyclic_window) —
    this is what lets one entry permute serve the whole factorization."""
    d = grid.dx
    if not (
        cfg.mode == "explicit"
        and grid.c == 1
        and grid.dy == d
        and d > 1
        and max(1, grid.num_chunks) == 1
        and cfg.base_case_dim % d == 0
    ):
        return 0

    bc = cfg.base_case_dim

    def aligned(nd: PlanNode) -> bool:
        if nd.off % bc or nd.n % bc:
            return False
        return nd.is_base or all(aligned(c) for c in nd.top)

    return bc // d if aligned(node) else 0


# --------------------------------------------------------------------------
# execute: the traced recursion (reference `invoke`, cholinv.hpp:87-165)
# --------------------------------------------------------------------------


def _base_case_into(
    grid: Grid,
    buf: jnp.ndarray,
    off: int,
    n: int,
    dest: int,
    cfg: CholinvConfig,
    Rp: jnp.ndarray,
    RIp: jnp.ndarray,
    ptile: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Leaf factorization: gather + local potrf/trtri (policy.h:160-224),
    reading the window (off, off, n, n) of `buf` (upper triangle valid) and
    writing the R / R⁻¹ blocks into Rp / RIp at diagonal offset `dest`.

    ptile != 0 (balance='tile_cyclic_persistent'): all three buffers are in
    the symmetric tile-cyclic storage layout — the window is extracted with
    a chunk-local reshape (summa.cyclic_window), locally un-permuted on the
    replicated panel (a bc x bc gather, free next to the potrf), factored,
    re-permuted, and written back in layout (band-sized update, no
    whole-buffer dus).

    The panel is replicated (XLA emits one all_gather over the mesh); which
    devices then FACTOR it is the policy (see _scoped_base_factor): every
    chip redundantly (REPLICATE_COMM_COMP, the TPU-optimal default), the
    z=0 layer + depth broadcast (REPLICATE_COMP), or the root device + mesh
    broadcast (NO_REPLICATION[_OVERLAP]).

    Single-device path: the window read, the symmetric-panel rebuild, and
    both output writes run through the layout-opaque Pallas transpose kernel
    with views/in-place aliasing (no slice or scatter materialization, and
    no XLA-visible `.T` — see ops/lapack.py:potrf_trtri_upper for why that
    matters).  Multi-device grids materialize the window (the panel is being
    replicated across the mesh anyway).
    """
    bc_dtype = cfg.base_case_dtype
    if bc_dtype is None:
        bc_dtype = buf.dtype if jnp.dtype(buf.dtype).itemsize >= 4 else jnp.float32
    # phase tag CI::factor_diag (reference cholinv.hpp:94-99)
    with tracing.scope("CI::factor_diag"):
        scope_ = cfg.policy.compute_scope
        comm, ncoll = tracing.replicate_cost(grid, n, n, bc_dtype)
        if grid.num_devices > 1 and scope_ != "all":
            # result broadcast: psum of the masked pair over 'z' (layer) or
            # the whole mesh (root)
            p = grid.c if scope_ == "layer" else grid.num_devices
            bcomm, bcoll = tracing.allreduce_cost(
                grid, n, n, bc_dtype, axes="z" if scope_ == "layer" else "all"
            )
            if p > 1:
                comm, ncoll = comm + 2 * bcomm, ncoll + 2 * bcoll
        tracing.emit(
            flops=tracing.potrf_trtri_flops(n), comm_bytes=comm, collectives=ncoll
        )
        if grid.num_devices == 1:
            # cholesky reads only the lower triangle (symmetrize_input=False)
            # = the transpose of the window's valid upper half
            P_low = pallas_tpu.transpose(
                buf, in_view=(off, off, n, n), out_uplo="L", out_dtype=bc_dtype
            )
            L = lax.linalg.cholesky(P_low, symmetrize_input=False)
            Linv = lax.linalg.triangular_solve(
                L, jnp.eye(n, dtype=bc_dtype), left_side=True, lower=True
            )
            if cfg.base_prefetch >= 2:
                # double-buffered write-back: both transposes in one
                # launch, two aliased output streams in flight per tile
                # step (bitwise-identical math — see transpose_pair)
                return pallas_tpu.transpose_pair(L, Linv, Rp, RIp, dest=dest)
            Rp = pallas_tpu.transpose(L, out_uplo="U", out=Rp, out_off=(dest, dest))
            RIp = pallas_tpu.transpose(
                Linv, out_uplo="U", out=RIp, out_off=(dest, dest)
            )
            return Rp, RIp
        if ptile:
            wperm, winv = summa.tile_cyclic_perm(n, grid.dx, ptile)
            window = summa.cyclic_window(
                buf, (off, off, n, n), grid.dx, ptile
            ).astype(bc_dtype)
            window = lax.with_sharding_constraint(
                window, grid.replicated_sharding()
            )
            iw = jnp.asarray(winv)
            R, Rinv = _scoped_base_factor(grid, window[iw][:, iw], scope_)
            pw = jnp.asarray(wperm)
            Rp = summa.cyclic_window_update(
                Rp, R.astype(Rp.dtype)[pw][:, pw], (dest, dest, n, n),
                grid.dx, ptile,
            )
            RIp = summa.cyclic_window_update(
                RIp, Rinv.astype(RIp.dtype)[pw][:, pw], (dest, dest, n, n),
                grid.dx, ptile,
            )
            return grid.pin(Rp), grid.pin(RIp)
        window = lax.slice(buf, (off, off), (off + n, off + n)).astype(bc_dtype)
        window = lax.with_sharding_constraint(window, grid.replicated_sharding())
        R, Rinv = _scoped_base_factor(grid, window, scope_)
        # i32 start indices: under x64 a Python-int index lowers as s64 and
        # the SPMD partitioner compares it against its own s32 shard offsets
        # (hlo-verifier rejection on the 0.4.x line)
        d32 = jnp.int32(dest)
        Rp = lax.dynamic_update_slice(Rp, R.astype(Rp.dtype), (d32, d32))
        RIp = lax.dynamic_update_slice(RIp, Rinv.astype(RIp.dtype), (d32, d32))
        return grid.pin(Rp), grid.pin(RIp)


def _scoped_base_factor(
    grid: Grid, window: jnp.ndarray, scope_: str
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """potrf+trtri of a replicated panel, executed by the devices the policy
    names (reference cholinv policy.h:160-514):

      'all'   — every device factors redundantly (no further collective)
      'layer' — only the z=0 depth layer factors; the pair is broadcast down
                'z' as a psum of the layer-masked value (≙ the reference's
                MPI_Bcast over the depth comm, policy.h:288-305)
      'root'  — only device (0,0,0) factors; the pair is broadcast over the
                whole mesh (≙ gather-to-root compute + scatter + bcast,
                policy.h:307-414; the OVERLAP variant's hand-rolled
                communication/compute overlap belongs to XLA's scheduler)

    The cond guards only local compute, never a collective; the zero branch
    is pcast to the varying type the psum needs.
    """
    if grid.num_devices == 1:
        return lapack.potrf_trtri_upper(window)
    if scope_ == "all" or (scope_ == "layer" and grid.c == 1):
        # multi-device redundant factorization: the XLA spelling, not the
        # Pallas-transpose one — Mosaic custom calls cannot be partitioned
        # by GSPMD over a replicated multi-device panel (found by the
        # round-4 AOT compile against a deviceless v5e-8 topology; the CPU
        # mesh hid it because interpret-mode pallas lowers to plain HLO),
        # and the layout-cascade rationale for the kernel is a single-chip
        # flagship concern
        from capital_tpu.ops import masking

        return lapack.potrf_trtri(masking.symmetrize_from(window, "U"), uplo="U")

    axes = ("z",) if scope_ == "layer" else ("x", "y", "z")

    def kernel(w):
        on = jnp.asarray(True)
        for a in axes:
            on = jnp.logical_and(on, lax.axis_index(a) == 0)

        def compute():
            # no pallas inside the shard_map body (vma annotations) — the
            # panel is a small replicated bc x bc block, so the jnp-level
            # symmetrize is fine here
            from capital_tpu.ops import masking

            R, Rinv = lapack.potrf_trtri(
                masking.symmetrize_from(w, "U"), uplo="U"
            )
            return (
                jax_compat.pcast(R, axes, to="varying"),
                jax_compat.pcast(Rinv, axes, to="varying"),
            )

        def zeros():
            z = jnp.zeros_like(w)
            return (
                jax_compat.pcast(z, axes, to="varying"),
                jax_compat.pcast(z, axes, to="varying"),
            )

        R, Rinv = lax.cond(on, compute, zeros)
        return lax.psum(R, axes), lax.psum(Rinv, axes)

    return jax_compat.shard_map(
        kernel,
        mesh=grid.mesh,
        in_specs=P(),
        out_specs=(P(), P()),
    )(window)


def _tail_fusible(
    grid: Grid,
    buf: jnp.ndarray,
    off: int,
    node: PlanNode,
    cfg: CholinvConfig,
    top: bool,
    Rp: jnp.ndarray,
    ptile: int,
) -> bool:
    """Trace-time gate for collapsing this plan() subtree into the fused
    megakernel (pallas_tpu.fused_tail).  Every condition is static:

    * the knob is on and the window is within the fused size budget;
    * single device, block layout (the kernel addresses flat buffers);
    * a top-level window with complete_inv=False stays unfused (the fused
      kernel always assembles the full window inverse, which would fill
      the block the contract promises stays zero);
    * the window and both destination buffers are 128-lane aligned and
      whole-block addressable (power-of-two split=1 plans always are;
      split>=2 subtrees mis-align and fall back — correctly);
    * dtype within the kernel's f32 compute envelope — f64 falls back to
      the unfused path AT TRACE TIME, the PR 6 dispatch-gate lesson;
    * the working set fits VMEM (batched_small.tail_eligible)."""
    from capital_tpu.ops import batched_small

    if cfg.tail_fuse_depth <= 0:
        return False
    if node.n > cfg.base_case_dim << cfg.tail_fuse_depth:
        return False
    if grid.num_devices != 1 or ptile:
        return False
    if top and not cfg.complete_inv:
        return False
    if node.n % 128:
        return False
    if (off % node.n or node.off % node.n or buf.shape[0] % node.n
            or buf.shape[1] % node.n or Rp.shape[0] % node.n):
        return False
    if not batched_small.dtype_capable(buf.dtype):
        return False
    return batched_small.tail_eligible(node.n, buf.dtype)


def _recurse(
    grid: Grid,
    buf: jnp.ndarray,
    off: int,
    node: PlanNode,
    cfg: CholinvConfig,
    top: bool,
    Rp: jnp.ndarray,
    RIp: jnp.ndarray,
    ptile: int = 0,
    tail_infos: list | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One recursion window: input is the (off, off, node.n, node.n) window
    of `buf` (upper triangle valid — Schur windows from the uplo='U' syrk
    carry only that half), output blocks land in the preallocated p x p
    factor buffers Rp / RIp at the window's *absolute* diagonal offset
    node.off.  Returns the updated (buf, Rp, RIp); ALL passed-in values are
    consumed (in-place aliased writes on the pallas path — with
    schur_in_place the returned buf carries this window's Schur updates,
    and continuing from the pre-call value would force XLA to copy the
    whole buffer; see step 1 below).

    Working against two flat buffers instead of assembling per-level is a
    deliberate departure from the reference's per-window serialize calls: a
    per-level `jnp.block` of Rinv plus a final scatter of R cost ~5ms/iter
    of pure HBM traffic at n=16k on v5e (concatenate fusions + pad +
    dynamic-update-slice chains); with buffer views every block is written
    exactly once, in place, and the trmm/syrk operands read straight from
    the buffers through offset index maps (parallel/summa.py views).
    """
    if _tail_fusible(grid, buf, off, node, cfg, top, Rp, ptile):
        # the whole subtree — potrf panels, trsm, syrk, inverse-completion
        # trmms (and for a base node the leaf's five launches) — as ONE
        # pallas_call with the panel VMEM-resident across phases
        with tracing.scope("CI::tail_fused"):
            tracing.emit(flops=tracing.fused_tail_flops(node.n))
            Rp, RIp, kinfo = pallas_tpu.fused_tail(
                buf, Rp, RIp, off=off, n=node.n, dest=node.off,
                precision=cfg.precision,
            )
        if tail_infos is not None:
            tail_infos.append((node.off, node.n, kinfo))
        return buf, Rp, RIp

    if node.is_base:
        Rp, RIp = _base_case_into(
            grid, buf, off, node.n, node.off, cfg, Rp, RIp, ptile
        )
        return buf, Rp, RIp

    left, right = node.top
    n1, n2 = left.n, right.n
    d0 = node.off

    # 1. recurse on the top-left window (cholinv.hpp:108-111).  The child's
    # returned buf (identical unless schur_in_place wrote deeper Schur
    # updates into it) MUST replace ours: continuing from the pre-recursion
    # value would give that value a second use after the child's aliased
    # write consumed it, and XLA would restore single-assignment with a
    # full-buffer copy per spine level (measured: compile-time OOM at
    # n=49152 — 27.02G of 15.75G — from exactly this).
    buf, Rp, RIp = _recurse(
        grid, buf, off, left, cfg, False, Rp, RIp, ptile, tail_infos
    )

    # balanced schedules for the large explicit-mode windows (see
    # CholinvConfig.balance); summa falls back with a note where the
    # balanced form does not apply.  With the persistent layout there is no
    # per-window choice: the buffers ARE tile-cyclic, so every call states
    # the storage contract (min_window economics vanished with the
    # per-call shuffles)
    def _bal(win: int) -> str:
        if ptile:
            return "tile_cyclic_persistent"
        return (
            "tile_cyclic"
            if (
                cfg.balance == "tile_cyclic"
                and cfg.mode == "explicit"
                and win >= cfg.balance_min_window
            )
            else "block"
        )

    # 2. TRSM phase: R12 = R11⁻ᵀ · A12 (cholinv.hpp:116-123, tag CI::trsm).
    # The reference grid-transposes R11inv then trmms; here the transpose is
    # an argument flag and XLA plans the data motion.
    with tracing.scope("CI::trsm"):
        Rp = summa.trmm(
            grid, RIp, buf,
            TrmmArgs(side="L", uplo="U", trans_a=True, precision=cfg.precision),
            mode=cfg.mode,
            a_view=(d0, d0, n1, n1),
            b_view=(off, off + n1, n1, n2),
            out=Rp, out_off=(d0, d0 + n1),
            balance=_bal(n1), cyclic_tile=ptile,
        )

    # 3. Schur complement: A22' = A22 − R12ᵀR12 (cholinv.hpp:131-134, CI::tmu).
    # schur_in_place writes the update back into buf's own trailing window
    # (no fresh (n2, n2) buffer) and step 4 recurses on that window; the
    # default materializes the update and recurses on it at offset 0.
    with tracing.scope("CI::tmu"):
        S = summa.syrk(
            grid, Rp, buf,
            SyrkArgs(trans=True, alpha=-1.0, beta=1.0, precision=cfg.precision),
            mode=cfg.mode,
            a_view=(d0, d0 + n1, n1, n2),
            c_view=(off + n1, off + n1, n2, n2),
            in_place=cfg.schur_in_place,
            balance=_bal(n2), cyclic_tile=ptile,
        )

    # 4. recurse on the trailing window (cholinv.hpp:139-142).  In-place
    # mode: S IS the updated buf (the Schur update landed in buf's trailing
    # window), so thread it onward as this node's buffer value.
    s_off = off + n1 if cfg.schur_in_place else 0
    S, Rp, RIp = _recurse(
        grid, S, s_off, right, cfg, False, Rp, RIp, ptile, tail_infos
    )
    if cfg.schur_in_place:
        buf = S

    # 5. inverse completion: R⁻¹12 = −R11inv·R12·R22inv (cholinv.hpp:147-156),
    # skipped at the top level when complete_inv=False (the block stays the
    # zeros the buffer was initialized with, matching the reference contract).
    if cfg.complete_inv or not top:
        with tracing.scope("CI::inv"):
            T = summa.trmm(
                grid, RIp, Rp,
                TrmmArgs(side="L", uplo="U", precision=cfg.precision),
                mode=cfg.mode,
                a_view=(d0, d0, n1, n1),
                b_view=(d0, d0 + n1, n1, n2),
                balance=_bal(n1), cyclic_tile=ptile,
            )
            RIp = summa.trmm(
                grid, RIp, T,
                TrmmArgs(side="R", uplo="U", alpha=-1.0, precision=cfg.precision),
                mode=cfg.mode,
                a_view=(right.off, right.off, n2, n2),
                out=RIp, out_off=(d0, d0 + n1),
                # the side-R completion trmm never takes the per-call
                # balanced schedule (see CholinvConfig.balance), but under
                # the persistent layout it MUST state the storage contract
                balance="tile_cyclic_persistent" if ptile else "block",
                cyclic_tile=ptile,
            )
    return buf, Rp, RIp


# The fused-tail info min-combine lives in robust/detect.combine_block_infos
# — shared with the per-chain-block infos of models/blocktri.py.


@pallas_tpu.scoped_by_grid
def factor(
    grid: Grid,
    A: jnp.ndarray,
    cfg: CholinvConfig = CholinvConfig(),
    out_buffers: tuple[jnp.ndarray, jnp.ndarray] | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Factor SPD A into (R, Rinv): A = RᵀR, Rinv = R⁻¹ (upper triangular).

    Equivalent of cholesky::cholinv::factor (cholinv.hpp:6-28); jit-friendly.
    When complete_inv=False the returned Rinv has its top-level off-diagonal
    block zeroed (only the two diagonal inverse blocks are valid), matching
    the reference's contract.

    out_buffers: optional (Rp, RIp) p x p working buffers to factor INTO
    (consumed — aliased writes).  Contract: their strictly-lower halves are
    zero and p == padded_dim(n, bc) with complete_inv=True.  The intended
    source is a PREVIOUS factor's outputs (a timed loop carrying them):
    the recursion rewrites every upper tile and never touches the dead
    lower zeros, so last iteration's results are exactly the
    initialization the next one needs — without this, XLA hoists the
    loop-invariant zero-init out of a benchmark loop and re-COPIES the
    buffers every iteration before the first aliased write (measured 2 x
    3.27 ms/iter at n=49152).

    With cfg.robust set the return is (R, Rinv, info): info is the int32
    breakdown status of the (cropped) factor — 0 clean, else the LAPACK
    potrf convention (robust/detect.factor_info)."""
    n = A.shape[0]
    if A.shape[0] != A.shape[1]:
        raise ValueError(f"cholinv needs a square matrix, got {A.shape}")
    if cfg.balance not in ("block", "tile_cyclic", "tile_cyclic_persistent"):
        raise ValueError(f"unknown balance {cfg.balance!r}")
    if cfg.balance.startswith("tile_cyclic") and cfg.mode != "explicit":
        # the balanced schedules exist only in the explicit schedule; a
        # silent block fallback here would mis-attribute a whole
        # load-balance experiment
        raise ValueError(f"balance={cfg.balance!r} requires mode='explicit'")
    p = padded_dim(n, cfg.base_case_dim)
    # SPD-safe pad: diag(A, I) factors to diag(R, I) without cross-talk.
    Ap = grid.pin(pad_embed_identity(A, n, p))
    node = plan(p, cfg)
    # fused-tail windows report breakdown through in-kernel info scalars
    # (collected at trace time, combined with the post-hoc scan below —
    # the guarded sweep produces no NaNs for factor_info to catch)
    tail_infos: list | None = [] if cfg.robust is not None else None

    # persistent tile-cyclic layout: permute ONCE here (V = Ap[perm][:, perm]
    # — a symmetric permutation, so SPD and the triangular-R contract of the
    # unchanged elimination order survive), run the whole recursion in
    # layout, un-permute R / Rinv once at exit.  Three lifetime shuffles
    # priced as grid transposes (the entry shuffle here, the two exit
    # shuffles below) replace the 2-3 shuffles PER trmm/syrk call of
    # balance='tile_cyclic'.
    ptile = 0
    unperm = None
    if cfg.balance == "tile_cyclic_persistent":
        ptile = persistent_tile(grid, node, cfg)
        if ptile:
            perm, pinv = summa.tile_cyclic_perm(p, grid.dx, ptile)
            pj = jnp.asarray(perm)
            unperm = jnp.asarray(pinv)
            Ap = grid.pin(Ap[pj][:, pj])
            cbytes, ncoll = tracing.transpose_cost(grid, p, p, Ap.dtype)
            tracing.emit(comm_bytes=3 * cbytes, collectives=3 * ncoll)
        else:
            tracing.note("cholinv::persistent_fallback")

    if out_buffers is not None:
        Rp, RIp = out_buffers
        if Rp.shape != (p, p) or RIp.shape != (p, p):
            raise ValueError(
                f"out_buffers must be ({p}, {p}) for n={n}, "
                f"bc={cfg.base_case_dim}; got {Rp.shape}, {RIp.shape}"
            )
        if not cfg.complete_inv:
            raise ValueError(
                "out_buffers requires complete_inv=True (the skipped "
                "off-diagonal window would keep the previous contents)"
            )
        if ptile:
            # out_buffers arrive in ORIGINAL order (factor returns
            # un-permuted results); bring them into storage layout like Ap.
            # Zeros are permutation-invariant and every live cell is
            # rewritten, so the reuse contract holds — at the price of two
            # extra shuffles, which is why the flagship out_buffers loop
            # and the persistent layout are documented as an either/or
            # (docs/DISTRIBUTED.md).
            Rp = grid.pin(Rp[pj][:, pj])
            RIp = grid.pin(RIp[pj][:, pj])
            cbytes, ncoll = tracing.transpose_cost(grid, p, p, Rp.dtype)
            tracing.emit(comm_bytes=2 * cbytes, collectives=2 * ncoll)
        _, R, Rinv = _recurse(
        grid, Ap, 0, node, cfg, True, Rp, RIp, ptile, tail_infos
    )
        if ptile:
            R = R[unperm][:, unperm]
            Rinv = Rinv[unperm][:, unperm]
        R, Rinv = grid.pin(R), grid.pin(Rinv)
        if p != n:
            R, Rinv = R[:n, :n], Rinv[:n, :n]
        if cfg.robust is not None:
            info = detect.factor_info(R)
            if tail_infos:
                info = detect.combine_block_infos(info, tail_infos, n)
            return R, Rinv, info
        return R, Rinv

    tile = _zeros_plan(grid, node, cfg)
    if tile:
        # every tile of the upper triangle (diag leaf windows + TRSM /
        # inverse-completion panels) is written exactly once by the
        # recursion, on the aligned-pallas AND fallback paths alike — only
        # the dead lower half (plus the skipped top-right Rinv window when
        # complete_inv=False) needs actual zeros.  Gated on leaf/tile
        # alignment (_zeros_plan): split>=2 plans produce leaves smaller
        # than the tile, a diagonal tile then contains sub-diagonal area
        # outside every leaf window, and skipping jnp.zeros would return
        # hardware garbage there (invisible on CPU interpret, which
        # zero-fills unvisited blocks).
        with tracing.scope("CI::buffers"):
            Rp = pallas_tpu.zeros_dead_lower(p, A.dtype, tile)
            extra = (
                ()
                if cfg.complete_inv or node.is_base
                else ((0, node.top[0].n, node.top[0].n, p - node.top[0].n),)
            )
            RIp = pallas_tpu.zeros_dead_lower(p, A.dtype, tile, extra=extra)
    else:
        Rp = grid.pin(jnp.zeros((p, p), dtype=A.dtype))
        RIp = grid.pin(jnp.zeros((p, p), dtype=A.dtype))
    _, R, Rinv = _recurse(
        grid, Ap, 0, node, cfg, True, Rp, RIp, ptile, tail_infos
    )
    if ptile:
        R = R[unperm][:, unperm]
        Rinv = Rinv[unperm][:, unperm]
    R, Rinv = grid.pin(R), grid.pin(Rinv)
    if p != n:
        R, Rinv = R[:n, :n], Rinv[:n, :n]
    if cfg.robust is not None:
        info = detect.factor_info(R)
        if tail_infos:
            info = detect.combine_block_infos(info, tail_infos, n)
        return R, Rinv, info
    return R, Rinv


def factor_buffers(
    grid: Grid, n: int, dtype, cfg: CholinvConfig = CholinvConfig()
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Freshly-initialized (Rp, RIp) working buffers satisfying factor's
    out_buffers contract — build ONCE outside a timed loop, then thread
    each iteration's outputs back in as the next iteration's buffers."""
    p = padded_dim(n, cfg.base_case_dim)
    node = plan(p, cfg)
    tile = _zeros_plan(grid, node, cfg)
    with pallas_tpu.platform_scope(grid.platform):
        if tile:
            with tracing.scope("CI::buffers"):
                return (
                    pallas_tpu.zeros_dead_lower(p, dtype, tile),
                    pallas_tpu.zeros_dead_lower(p, dtype, tile),
                )
    # two DISTINCT buffers: sharing one value between two aliased consumer
    # chains would be the multi-use copy hazard this API exists to avoid
    return (
        grid.pin(jnp.zeros((p, p), dtype=dtype)),
        grid.pin(jnp.zeros((p, p), dtype=dtype)),
    )


def solve(
    grid: Grid,
    A: jnp.ndarray,
    B: jnp.ndarray,
    cfg: CholinvConfig = CholinvConfig(),
):
    """SPD solve A·X = B: cholinv factor + the two-trsm potrs sweeps
    (ops/lapack.potrs) — the posv capability serve.api rides (docs/SERVING.md).

    Runs the factorization with complete_inv=False: the solve consumes only
    R (potrs back-substitutes), so the inverse-completion trmms of the full
    R⁻¹ are skipped work here.  With cfg.robust set the return is (X, info)
    — info the int32 breakdown status of the factor (0 clean); X is
    garbage when info != 0 and must not be trusted.  Callers that already
    hold a factor should call lapack.potrs directly."""
    if B.shape[0] != A.shape[0]:
        raise ValueError(f"shape mismatch: A {A.shape} vs B {B.shape}")
    ccfg = dataclasses.replace(cfg, complete_inv=False)
    if cfg.robust is not None:
        R, _, info = factor(grid, A, ccfg)
        return lapack.potrs(R, B, uplo="U"), info
    R, _ = factor(grid, A, ccfg)
    return lapack.potrs(R, B, uplo="U")


def spd_inverse(
    grid: Grid, A: jnp.ndarray, cfg: CholinvConfig = CholinvConfig()
) -> jnp.ndarray:
    """A⁻¹ = R⁻¹·R⁻ᵀ for SPD A — the 'SPD inverse via Cholesky' capability
    (BASELINE.md config row 5)."""
    cfg = dataclasses.replace(cfg, complete_inv=True, robust=None)
    _, Rinv = factor(grid, A, cfg)
    return summa.gemm(
        grid, Rinv, Rinv,
        args=summa.GemmArgs(trans_b=True, precision=cfg.precision), mode=cfg.mode
    )
