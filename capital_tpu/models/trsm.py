"""Distributed triangular solve (TRSM).

The reference's trsm::diaginvert is a stub — `solve` is
``static_assert(0, "not implemented")`` (reference src/alg/trsm/diaginvert/
diaginvert.hpp:9) and the only working triangular solve is the 2x2 blocked
special case buried in cacqr (cacqr.hpp:46-73).  This module implements the
capability properly: a recursive blocked TRSM on the device grid, with all
four side/uplo combinations and transpose support.

Schedule (lower-triangular, side='L' shown; others by symmetry):

    [L11  0 ] [X1]   [B1]      X1 = trsm(L11, B1)
    [L21 L22] [X2] = [B2]  ->  X2 = trsm(L22, B2 − L21·X1)

The recursion is trace-time (static windows, like models/cholesky.py).  Two
leaf policies (TrsmConfig.leaf): 'invert' (default) precomputes ALL
diagonal-block inverses in one batched kernel and turns every leaf into an
MXU gemm — the design the reference subsystem's name (diaginvert) promises;
'solve' replicates the triangular panel and runs
lax.linalg.triangular_solve on every chip — same policy argument as the
cholinv base case (SURVEY §7.1: replicate-and-recompute is the TPU-optimal
base-case strategy), kept for ill-conditioned diagonal blocks.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from capital_tpu.parallel import summa
from capital_tpu.parallel.summa import GemmArgs
from capital_tpu.parallel.topology import Grid
from capital_tpu.utils import tracing


@dataclasses.dataclass(frozen=True)
class TrsmConfig:
    """Blocked-TRSM knobs (the reference's diaginvert policies were only
    forward-declared, trsm/diaginvert/policy.h:8-9; these are the working
    equivalents).

    leaf='invert' is the design the reference subsystem's NAME promises
    (trsm::diaginvert — invert the diagonal blocks, then substitute): all
    n/bc diagonal-block inverses are computed up front in ONE batched
    kernel (they are independent — the parallelism the sequential
    triangular_solve leaves throw away), and every leaf becomes an MXU
    gemm against its precomputed inverse.  leaf='solve' keeps the
    replicated lax.linalg.triangular_solve leaf — the numerically
    stricter substitution form, for ill-conditioned diagonal blocks
    (explicit-inverse multiply pays cond(D)·eps per leaf; the batched
    inverses themselves are computed by substitution at >= f32)."""

    base_case_dim: int = 256
    mode: str = "xla"
    precision: str | None = "highest"
    leaf: str = "invert"


def _diag_block_inverses(
    grid: Grid,
    A: jnp.ndarray,
    bc: int,
    lower: bool,
    unit_diag: bool,
    cfg: TrsmConfig,
) -> jnp.ndarray:
    """(p/bc, bc, bc) stack of diagonal-block inverses of tri(A) — the
    diaginvert precompute, replicated.  Total flops are p·bc² (negligible
    next to the p²·nrhs substitution).  Inversion goes through
    lapack.trtri_stack: the batched custom call serializes its batch on
    TPU (measured 3.2 ms of a 53 ms solve at n=32768), so the call is
    confined to 128-sub-blocks and merged up with batched MXU products."""
    from capital_tpu.ops import lapack

    D = lapack.diag_block_stack(A, 0, bc, bc)
    D = jnp.tril(D) if lower else jnp.triu(D)
    Dinv = lapack.trtri_stack(
        D, uplo="L" if lower else "U", unit_diag=unit_diag,
        precision=cfg.precision,
    )
    return lax.with_sharding_constraint(Dinv, grid.replicated_sharding())


def _base_solve(
    grid: Grid,
    T: jnp.ndarray,
    B: jnp.ndarray,
    lower: bool,
    left: bool,
    unit_diag: bool,
) -> jnp.ndarray:
    Tr = lax.with_sharding_constraint(T, grid.replicated_sharding())
    X = lax.linalg.triangular_solve(
        Tr, B, left_side=left, lower=lower, unit_diagonal=unit_diag
    )
    return grid.pin(X)


def solve(
    grid: Grid,
    A: jnp.ndarray,
    B: jnp.ndarray,
    side: str = "L",
    uplo: str = "L",
    trans_a: bool = False,
    cfg: TrsmConfig = TrsmConfig(),
    *,
    unit_diag: bool = False,
) -> jnp.ndarray:
    """X with op(tri(A)) @ X = B (side='L') or X @ op(tri(A)) = B (side='R').

    The working replacement for trsm::diaginvert::solve
    (reference diaginvert.hpp:9).  jit-friendly; recursion is trace-time.
    unit_diag treats tri(A)'s diagonal as ones without reading it — the
    reference BLAS surface's Diag::AblasUnit (src/blas/engine.h:23-52),
    honored here like summa.trmm's TrmmArgs.diag.
    """
    if side not in ("L", "R"):
        raise ValueError(f"side must be 'L' or 'R', got {side!r}")
    if uplo not in ("L", "U"):
        raise ValueError(f"uplo must be 'L' or 'U', got {uplo!r}")
    if cfg.leaf not in ("invert", "solve"):
        raise ValueError(f"leaf must be 'invert' or 'solve', got {cfg.leaf!r}")
    n = A.shape[0]
    if A.shape[0] != A.shape[1]:
        raise ValueError(f"triangular operand must be square, got {A.shape}")
    need = B.shape[0] if side == "L" else B.shape[1]
    if need != n:
        raise ValueError(f"shape mismatch: A {A.shape} vs B {B.shape} side={side}")

    lower = uplo == "L"
    if trans_a:
        # op(T) x = b  <=>  solve with the transposed triangle; fold the
        # transpose into the effective uplo and recurse untransposed.
        return solve(
            grid, summa.transpose(grid, A), B, side, "U" if lower else "L",
            False, cfg, unit_diag=unit_diag,
        )

    # Padding (diag(A, I) — stays triangular, solves the zero-padded RHS
    # rows/cols to zeros).  Distributed grids pad to bc·2^k so every
    # recursion window divides the grid face; odd halving would otherwise
    # drop each window's placement to XLA with a per-call Grid.pin
    # fallback warning (VERDICT r2 weak #5).  A single device has no face
    # layout to preserve, so the invert leaf only needs bc-ALIGNED
    # windows: pad to the next multiple of bc (< bc rows for any n — a
    # bc·2^k pad would near-quadruple the substitution flops at n just
    # past a power of two) and let _solve_into split at block boundaries.
    # Single-device leaf='solve' runs stay unpadded (misaligned windows
    # already take the materializing fallbacks; padding would only cost
    # flops).
    bc = cfg.base_case_dim
    p = n
    if grid.num_devices > 1:
        from capital_tpu.models.cholesky import pad_embed_identity, padded_dim

        p = padded_dim(n, bc)
    elif cfg.leaf == "invert" and n > bc:
        from capital_tpu.models.cholesky import pad_embed_identity

        p = -(-n // bc) * bc
    if p != n:
        A = pad_embed_identity(A, n, p)
        pad = ((0, p - n), (0, 0)) if side == "L" else ((0, 0), (0, p - n))
        B = jnp.pad(B, pad)
    A = grid.pin(A)

    Dinv = None
    if cfg.leaf == "invert" and p >= cfg.base_case_dim and p % cfg.base_case_dim == 0:
        with tracing.scope("TS::dinv"):
            Dinv = _diag_block_inverses(
                grid, A, cfg.base_case_dim, lower, unit_diag, cfg
            )

    # solved blocks land in a flat X buffer at their final offsets (no
    # per-level concatenate assembly — the cholinv/rectri flat-buffer
    # design); the updated right-hand sides still flow down as values,
    # which is inherent to the substitution order.
    X = grid.pin(jnp.zeros_like(B))
    X = _solve_into(grid, A, B, X, 0, p, side, lower, unit_diag, cfg, Dinv)
    X = grid.pin(X)
    if p != n:
        X = X[:n, :] if side == "L" else X[:, :n]
    return X


def _solve_into(
    grid: Grid,
    A: jnp.ndarray,
    B: jnp.ndarray,
    X: jnp.ndarray,
    off: int,
    size: int,
    side: str,
    lower: bool,
    unit_diag: bool,
    cfg: TrsmConfig,
    Dinv: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Solve the (off, off, size, size) window of tri(A) against the current
    right-hand-side value B (already narrowed to this window's rows/cols),
    writing the solution block into X at offset `off` along the solve axis.
    Returns the updated X (consumed)."""

    def _xwin(o: int, s: int) -> jnp.ndarray:
        if side == "L":
            return lax.slice(X, (o, 0), (o + s, X.shape[1]))
        return lax.slice(X, (0, o), (X.shape[0], o + s))

    def _put(Xbuf: jnp.ndarray, val: jnp.ndarray, o: int) -> jnp.ndarray:
        # i32 starts: under x64 a Python-int index lowers as s64 and the
        # 0.4.x SPMD partitioner compares it against its own s32 shard
        # offsets (hlo-verifier rejection)
        o32 = jnp.int32(o)
        at = (o32, jnp.int32(0)) if side == "L" else (jnp.int32(0), o32)
        return lax.dynamic_update_slice(Xbuf, val.astype(Xbuf.dtype), at)

    if size <= cfg.base_case_dim:
        if Dinv is not None and size == cfg.base_case_dim:
            # diaginvert leaf: one MXU gemm against the precomputed
            # diagonal-block inverse (trace-time offset -> static index).
            D = lax.index_in_dim(Dinv, off // cfg.base_case_dim, keepdims=False)
            gargs = GemmArgs(precision=cfg.precision)
            with tracing.scope("TS::leaf"):
                if side == "L":
                    V = summa.gemm(grid, D, B, None, gargs, mode=cfg.mode)
                else:
                    V = summa.gemm(grid, B, D, None, gargs, mode=cfg.mode)
            return _put(X, V, off)
        Tw = lax.slice(A, (off, off), (off + size, off + size))
        return _put(
            X,
            _base_solve(grid, Tw, B, lower, left=(side == "L"), unit_diag=unit_diag),
            off,
        )

    # Split at a block-aligned boundary when the window is a whole number
    # of base-case blocks, so every leaf lands exactly bc-sized at a
    # bc-aligned offset (the invert leaf's indexing premise).  On meshes
    # (p = bc·2^k) this coincides with plain halving; on a single device
    # it is what lets p be any multiple of bc.
    bc = cfg.base_case_dim
    if size % bc == 0:
        n1 = (size // bc // 2) * bc
    else:
        n1 = size // 2
    n2 = size - n1
    o1, o2 = off, off + n1
    gargs = GemmArgs(alpha=-1.0, beta=1.0, precision=cfg.precision)

    if side == "L" and lower:
        A21 = lax.slice(A, (o2, o1), (o2 + n2, o1 + n1))
        X = _solve_into(grid, A, B[:n1, :], X, o1, n1, side, lower, unit_diag, cfg, Dinv)
        with tracing.scope("TS::update"):
            B2 = summa.gemm(grid, A21, _xwin(o1, n1), B[n1:, :], gargs, mode=cfg.mode)
        X = _solve_into(grid, A, B2, X, o2, n2, side, lower, unit_diag, cfg, Dinv)
    elif side == "L" and not lower:
        A12 = lax.slice(A, (o1, o2), (o1 + n1, o2 + n2))
        X = _solve_into(grid, A, B[n1:, :], X, o2, n2, side, lower, unit_diag, cfg, Dinv)
        with tracing.scope("TS::update"):
            B1 = summa.gemm(grid, A12, _xwin(o2, n2), B[:n1, :], gargs, mode=cfg.mode)
        X = _solve_into(grid, A, B1, X, o1, n1, side, lower, unit_diag, cfg, Dinv)
    elif side == "R" and lower:
        A21 = lax.slice(A, (o2, o1), (o2 + n2, o1 + n1))
        X = _solve_into(grid, A, B[:, n1:], X, o2, n2, side, lower, unit_diag, cfg, Dinv)
        with tracing.scope("TS::update"):
            B1 = summa.gemm(grid, _xwin(o2, n2), A21, B[:, :n1], gargs, mode=cfg.mode)
        X = _solve_into(grid, A, B1, X, o1, n1, side, lower, unit_diag, cfg, Dinv)
    else:  # side == "R", upper
        A12 = lax.slice(A, (o1, o2), (o1 + n1, o2 + n2))
        X = _solve_into(grid, A, B[:, :n1], X, o1, n1, side, lower, unit_diag, cfg, Dinv)
        with tracing.scope("TS::update"):
            B2 = summa.gemm(grid, _xwin(o1, n1), A12, B[:, n1:], gargs, mode=cfg.mode)
        X = _solve_into(grid, A, B2, X, o2, n2, side, lower, unit_diag, cfg, Dinv)
    return X
