"""Distributed triangular solve (TRSM).

The reference's trsm::diaginvert is a stub — `solve` is
``static_assert(0, "not implemented")`` (reference src/alg/trsm/diaginvert/
diaginvert.hpp:9) and the only working triangular solve is the 2x2 blocked
special case buried in cacqr (cacqr.hpp:46-73).  This module implements the
capability properly: a recursive blocked TRSM on the device grid, with all
four side/uplo combinations and transpose support.

Schedule (lower-triangular, side='L' shown; others by symmetry):

    [L11  0 ] [X1]   [B1]      X1 = trsm(L11, B1)
    [L21 L22] [X2] = [B2]  ->  X2 = trsm(L22, B2 − L21·X1)

The recursion is trace-time (static windows, like models/cholesky.py); the
base case replicates the triangular panel and runs
lax.linalg.triangular_solve on every chip — same policy argument as the
cholinv base case (SURVEY §7.1: replicate-and-recompute is the TPU-optimal
base-case strategy).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from capital_tpu.parallel import summa
from capital_tpu.parallel.summa import GemmArgs
from capital_tpu.parallel.topology import Grid


@dataclasses.dataclass(frozen=True)
class TrsmConfig:
    """Blocked-TRSM knobs (the reference's diaginvert policies were only
    forward-declared, trsm/diaginvert/policy.h:8-9; these are the working
    equivalents)."""

    base_case_dim: int = 256
    mode: str = "xla"
    precision: str | None = "highest"


def _base_solve(
    grid: Grid, T: jnp.ndarray, B: jnp.ndarray, lower: bool, left: bool
) -> jnp.ndarray:
    Tr = lax.with_sharding_constraint(T, grid.replicated_sharding())
    X = lax.linalg.triangular_solve(Tr, B, left_side=left, lower=lower)
    return grid.pin(X)


def solve(
    grid: Grid,
    A: jnp.ndarray,
    B: jnp.ndarray,
    side: str = "L",
    uplo: str = "L",
    trans_a: bool = False,
    cfg: TrsmConfig = TrsmConfig(),
) -> jnp.ndarray:
    """X with op(tri(A)) @ X = B (side='L') or X @ op(tri(A)) = B (side='R').

    The working replacement for trsm::diaginvert::solve
    (reference diaginvert.hpp:9).  jit-friendly; recursion is trace-time.
    """
    if side not in ("L", "R"):
        raise ValueError(f"side must be 'L' or 'R', got {side!r}")
    if uplo not in ("L", "U"):
        raise ValueError(f"uplo must be 'L' or 'U', got {uplo!r}")
    n = A.shape[0]
    if A.shape[0] != A.shape[1]:
        raise ValueError(f"triangular operand must be square, got {A.shape}")
    need = B.shape[0] if side == "L" else B.shape[1]
    if need != n:
        raise ValueError(f"shape mismatch: A {A.shape} vs B {B.shape} side={side}")

    lower = uplo == "L"
    if trans_a:
        # op(T) x = b  <=>  solve with the transposed triangle; fold the
        # transpose into the effective uplo and recurse untransposed.
        return solve(
            grid, summa.transpose(grid, A), B, side, "U" if lower else "L", False, cfg
        )

    if n <= cfg.base_case_dim:
        return _base_solve(grid, A, B, lower, left=(side == "L"))

    n1 = n // 2
    A11 = A[:n1, :n1]
    A22 = A[n1:, n1:]
    gargs = GemmArgs(alpha=-1.0, beta=1.0, precision=cfg.precision)

    if side == "L" and lower:
        A21 = A[n1:, :n1]
        X1 = solve(grid, A11, B[:n1, :], side, uplo, False, cfg)
        B2 = summa.gemm(grid, A21, X1, B[n1:, :], gargs, mode=cfg.mode)
        X2 = solve(grid, A22, B2, side, uplo, False, cfg)
    elif side == "L" and not lower:
        A12 = A[:n1, n1:]
        X2 = solve(grid, A22, B[n1:, :], side, uplo, False, cfg)
        B1 = summa.gemm(grid, A12, X2, B[:n1, :], gargs, mode=cfg.mode)
        X1 = solve(grid, A11, B1, side, uplo, False, cfg)
    elif side == "R" and lower:
        A21 = A[n1:, :n1]
        X2 = solve(grid, A22, B[:, n1:], side, uplo, False, cfg)
        B1 = summa.gemm(grid, X2, A21, B[:, :n1], gargs, mode=cfg.mode)
        X1 = solve(grid, A11, B1, side, uplo, False, cfg)
    else:  # side == "R", upper
        A12 = A[:n1, n1:]
        X1 = solve(grid, A11, B[:, :n1], side, uplo, False, cfg)
        B2 = summa.gemm(grid, X1, A12, B[:, n1:], gargs, mode=cfg.mode)
        X2 = solve(grid, A22, B2, side, uplo, False, cfg)

    axis = 0 if side == "L" else 1
    X = jnp.concatenate([X1, X2], axis=axis)
    return grid.pin(X)
