"""Distributed matrix inversion: recursive triangular inverse + Newton-Schulz.

Two capabilities from the reference's inverse family, both finished here
(the reference left them incomplete):

* ``rectri`` — recursive triangular inversion.  The reference's
  inverse::rectri wrote the nested-grid redistribution (`simulate`,
  rectri.hpp:36-58) but `invert` only performs the deepest local trtri —
  the cross-level assembly never landed (a commented-out sketch,
  rectri.hpp:70-99).  DECISION, pinned by tests/test_inverse_trsm.py::
  TestRectri::test_cross_level_assembly_pinned: this repo implements the
  assembly in full, as windowed triangular products over one flat output
  buffer — for lower-triangular L

      L⁻¹ = [[     L11⁻¹     ,   0  ]
             [−L22⁻¹·L21·L11⁻¹, L22⁻¹]]

  as a trace-time recursion whose merge trmms read/write views of the flat
  buffers (`_rectri_into`) — and deliberately does NOT port the
  reference's nested-grid Alltoall redistribution (shrinking subcube
  meshes per level): that machinery has no TPU analog worth keeping, since
  windows shrink but stay on the full mesh and XLA reshards slices as
  needed (SURVEY §7.3 item 5).  What the sketch called "assembly" is here
  exactly two trmms per merge plus one leaf trtri per base case, every
  window written once, the never-written upper triangle exactly zero.

* ``newton`` — Newton-Schulz iterative inversion.  The reference's version
  is bit-rotted and does not compile (newton.h:16-18 invalid ctor syntax;
  newton.hpp:14-35 calls a matrix API that no longer exists).  The working
  re-implementation is a jitted lax.while_loop: X ← X(2I − AX) with the
  spectral-safe initialization X₀ = Aᵀ/(‖A‖₁·‖A‖∞) and early exit on
  ‖I − AX‖_F < tol — the same iteration newton.hpp:42-53 sketches, including
  its early-exit residual check.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from capital_tpu.ops import lapack, pallas_tpu
from capital_tpu.parallel import summa
from capital_tpu.parallel.summa import GemmArgs, TrmmArgs
from capital_tpu.parallel.topology import Grid


@dataclasses.dataclass(frozen=True)
class RectriConfig:
    """Knobs for the recursive triangular inverse (reference rectri policies,
    rectri/policy.h, reduced to their working essence)."""

    base_case_dim: int = 256
    mode: str = "xla"
    precision: str | None = "highest"
    balance: str = "block"  # 'tile_cyclic' routes the EXPLICIT-mode side-L
    # merge trmm through the tile-cyclic balanced schedule for windows >=
    # balance_min_window — same calculus as CholinvConfig.balance (the
    # side-R product keeps blocks: the balanced form is side-L/syrk only).
    # No effect outside explicit mode (single-device pallas kernels skip
    # dead tiles natively).
    balance_min_window: int = 8192
    batch_below: int = -1  # single-device batched prefix threshold:
    # -1 (default) = auto: batch ONLY the base cases (t = bc) — all p/bc
    # leaf trtris collapse into one lapack.trtri_stack call (slice
    # extraction + inner-block trtri + batched MXU merges) and the
    # depth-first walk starts from stop_at=bc with every leaf already
    # inverted.  Rectri's leaves, unlike cholinv's, have no sequential
    # Schur dependency, so this is pure parallelism recovery.
    # 0 = off.  > 0: ALSO run batched dense matmul merge levels for
    # windows up to the threshold (values below bc clamp up to bc —
    # base-only) — a measured LOSER at t >= 2·bc on this
    # stack even after the gather->slice fix (the dense merges replace
    # efficient trmms at 2x the flops; docs/PERF.md "rectri round 4:
    # batched-prefix negative result"), kept re-measurable in one flag
    # (--batch-below).


def _batched_prefix_size(grid: Grid, p: int, cfg: RectriConfig) -> int:
    """Largest level size t = bc·2^j the global batched sweep should
    produce (t = bc means base cases only — the default), or 0 when
    ineligible (disabled, a mesh — the stacks carry no face layout — or
    bc does not divide p).  Any bc-divisible chain gets at least the
    base-only prefix; levels ABOVE bc additionally require a power-of-two
    block count (they pair equal siblings)."""
    bc = cfg.base_case_dim
    nb = p // bc
    # any enabled setting keeps at least the base-only prefix: a positive
    # batch_below below bc clamps up to bc rather than silently disabling
    # the default win
    limit = bc if cfg.batch_below < 0 else max(cfg.batch_below, bc)
    if not (
        grid.num_devices == 1
        and cfg.batch_below != 0
        and p % bc == 0
        and p >= bc
    ):
        return 0
    # the base-only prefix (t = bc) needs nothing beyond bc | p: the
    # bc-aligned split rule makes every recursion leaf exactly a diagonal
    # bc-block for ANY block count (round 5 — nb=96 at the 49152 bench row
    # previously serialized all 96 leaf trtris, 12.7 ms of the 8% gap to
    # target).  Batched merge LEVELS above bc still pair equal siblings,
    # which only a power-of-two chain provides.
    if nb & (nb - 1):
        return bc
    t = bc
    while t * 2 <= min(limit, p):
        t *= 2
    return t


def _rectri_batched_prefix(
    grid: Grid,
    Tp: jnp.ndarray,
    out: jnp.ndarray,
    p: int,
    t: int,
    cfg: RectriConfig,
) -> jnp.ndarray:
    """Invert ALL diagonal t-windows of Tp into `out` by a global batched
    prefix: ONE lapack.trtri_stack over every base-case block (rectri's
    leaves are independent — the parallelism the depth-first walk
    serializes), then per level (t > bc only) one batched
    A21 @ A11inv / A22inv @ (·) matmul pair over every sibling merge
    matrix-wide.  The recursion above `t` then only performs merges (its
    stop_at windows are already inverted here).  The default is t = bc —
    base cases only; the dense matmul levels above bc are a measured
    loser (2x the trmm flops; docs/PERF.md "rectri round 4")."""
    from capital_tpu.utils import tracing

    bc = cfg.base_case_dim
    with tracing.scope("RT::batch_base"):
        W = lapack.trtri_stack(
            jnp.tril(lapack.diag_block_stack(Tp, 0, bc, bc)), uplo="L",
            precision=cfg.precision,
        )
    s = bc
    while s < t:
        with tracing.scope("RT::batch_merge"):
            A21 = lapack.diag_block_stack(Tp, s, s, 2 * s)
            A11i, A22i = W[0::2], W[1::2]
            M = jnp.matmul(A21, A11i, precision=cfg.precision)
            B21 = -jnp.matmul(A22i, M, precision=cfg.precision)
            W = jnp.concatenate(
                [
                    jnp.concatenate([A11i, jnp.zeros_like(A11i)], axis=2),
                    jnp.concatenate([B21, A22i], axis=2),
                ],
                axis=1,
            )
        s *= 2
    with tracing.scope("RT::batch_write"):
        # in-place aliased block scatter: the dus-chain spelling costs a
        # full `out` copy (~6 ms at the 49152 bench row)
        out = pallas_tpu.write_diag_blocks(out, W)
    return out


def _rectri_into(
    grid: Grid,
    Tp: jnp.ndarray,
    out: jnp.ndarray,
    off: int,
    size: int,
    cfg: RectriConfig,
    stop_at: int = 0,
) -> jnp.ndarray:
    """Invert the lower-triangular window (off, off, size, size) of Tp into
    the same window of the flat buffer `out` (consumed; in-place on the
    pallas path).  Windows <= stop_at are already inverted in `out` (the
    global batched prefix) and pass through untouched."""
    from capital_tpu.utils import tracing

    if size <= stop_at:
        return out

    if size <= cfg.base_case_dim:
        with tracing.scope("RT::base"):
            window = lax.slice(Tp, (off, off), (off + size, off + size))
            if grid.num_devices > 1:
                window = lax.with_sharding_constraint(
                    window, grid.replicated_sharding()
                )
            inv = lapack.trtri(window, uplo="L")
            # i32 starts: x64 Python-int indices lower as s64 and trip the
            # 0.4.x SPMD partitioner's s32 shard-offset compare
            o32 = jnp.int32(off)
            return grid.pin(
                lax.dynamic_update_slice(out, inv.astype(out.dtype), (o32, o32))
            )

    if size % cfg.base_case_dim == 0:
        # split on a base-case boundary: every leaf of the tree is then
        # exactly a bc-aligned diagonal block (the batched prefix inverts
        # all of them in one trtri_stack call, any block count), and every
        # merge view stays 128-aligned for the in-place kernel path
        n1 = (size // cfg.base_case_dim // 2) * cfg.base_case_dim
    else:
        n1 = size // 2
    n2 = size - n1
    out = _rectri_into(grid, Tp, out, off, n1, cfg, stop_at)
    out = _rectri_into(grid, Tp, out, off + n1, n2, cfg, stop_at)
    # B21 = −L22⁻¹ · L21 · L11⁻¹ — the cross-level assembly the reference
    # left as a commented-out sketch (rectri.hpp:70-99; decision documented
    # in the module docstring) — as two triangular products read/written
    # through views of the flat buffers, the cholinv design
    # (models/cholesky.py): no per-level jnp.block assembly, and both trmms
    # skip the triangular operand's dead blocks (pallas single-device;
    # segment-skipping explicit mode on a mesh)
    bal = (
        "tile_cyclic"
        if (
            cfg.balance == "tile_cyclic"
            and cfg.mode == "explicit"
            and n2 >= cfg.balance_min_window
        )
        else "block"
    )
    targs = dict(mode=cfg.mode)
    with tracing.scope("RT::merge"):
        M = summa.trmm(
            grid, out, Tp,
            TrmmArgs(side="R", uplo="L", precision=cfg.precision), **targs,
            a_view=(off, off, n1, n1),          # L11inv
            b_view=(off + n1, off, n2, n1),     # L21
        )
        out = summa.trmm(
            grid, out, M,
            TrmmArgs(side="L", uplo="L", alpha=-1.0, precision=cfg.precision),
            **targs,
            a_view=(off + n1, off + n1, n2, n2),  # L22inv
            out=out, out_off=(off + n1, off),
            balance=bal,
        )
    return out


@pallas_tpu.scoped_by_grid
def rectri(
    grid: Grid,
    T: jnp.ndarray,
    uplo: str = "L",
    cfg: RectriConfig = RectriConfig(),
) -> jnp.ndarray:
    """Inverse of triangular T (the completed inverse::rectri::invoke,
    reference rectri.hpp:60-99).  jit-friendly trace-time recursion over a
    flat output buffer (leaf trtri blocks and off-diagonal trmm panels are
    written exactly once, in place on the pallas path)."""
    if uplo not in ("L", "U"):
        raise ValueError(f"uplo must be 'L' or 'U', got {uplo!r}")
    n = T.shape[0]
    if T.shape[0] != T.shape[1]:
        raise ValueError(f"triangular operand must be square, got {T.shape}")

    if uplo == "U":
        # U⁻¹ = (Lᵀ)⁻¹ = (L⁻¹)ᵀ with L = Uᵀ: one transpose each way keeps a
        # single recursion body (the reference instantiates both via policy).
        return summa.transpose(grid, rectri(grid, summa.transpose(grid, T), "L", cfg))

    from capital_tpu.models.cholesky import pad_embed_identity, padded_dim
    from capital_tpu.utils import tracing

    # Single device: pad to the SMALLER of the bc-chain size (perfectly
    # aligned windows) and plain 256-lane alignment: the recursion handles
    # odd halving, so a forced bc * 2^k pad would cost up to (p/n)^3 ≈ 2.4x
    # the flops for awkward n while buying nothing — misaligned deep-level
    # windows merely take tri_matmul's materializing fallback.  Distributed
    # grids pad the full bc * 2^k chain instead, like cholinv: every
    # recursion window then divides the grid face, where odd halving would
    # drop placement to XLA with per-call Grid.pin fallback warnings
    # (VERDICT r2 weak #5) — alignment is worth more than flops on a mesh.
    # Bench shapes (n = bc * 2^k) get the fully-aligned plan either way.
    p = padded_dim(n, cfg.base_case_dim)
    if grid.num_devices == 1:
        p = min(p, -(-n // 256) * 256)
    # embed diag(T, I): stays lower-triangular, inverts to diag(T⁻¹, I)
    Tp = grid.pin(pad_embed_identity(T, n, p))
    t = _batched_prefix_size(grid, p, cfg)
    if t:
        # the prefix's leaf scatter writes every diagonal t-block in full
        # and the merge panels cover the whole strict-lower triangle, so
        # only the strict-UPPER tiles need the zero fill (~half the init
        # HBM traffic of a dense jnp.zeros; ~3 ms at the 49152 bench row)
        with tracing.scope("RT::buffers"):
            out = grid.pin(
                pallas_tpu.zeros_dead_lower(p, T.dtype, t, dead="upper")
            )
        out = _rectri_batched_prefix(grid, Tp, out, p, t, cfg)
    else:
        out = grid.pin(jnp.zeros((p, p), dtype=T.dtype))
    out = _rectri_into(grid, Tp, out, 0, p, cfg, stop_at=t)
    out = grid.pin(out)
    return out[:n, :n] if p != n else out


@dataclasses.dataclass(frozen=True)
class NewtonConfig:
    """Newton-Schulz iteration knobs (reference inverse::newton::info,
    newton.h:20-29: tolerance + max_iter).

    tol: convergence gate on the *normalized* residual ‖I − AX‖_F/√n.
        None (default) picks 50·eps for the input dtype, so f32/bf16 inputs
        converge instead of silently burning max_iter iterations.
    """

    tol: float | None = None
    max_iter: int = 100
    mode: str = "xla"
    precision: str | None = "highest"


@pallas_tpu.scoped_by_grid
def newton(
    grid: Grid, A: jnp.ndarray, cfg: NewtonConfig = NewtonConfig()
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Iterative inverse of (well-conditioned) A by Newton-Schulz.

    Returns (Ainv, num_iters).  The working replacement for the bit-rotted
    inverse::newton (reference newton.hpp:14-53): X₀ = Aᵀ/(‖A‖₁‖A‖∞)
    guarantees ‖I − AX₀‖ < 1; the loop doubles correct digits per step and
    exits early when the normalized residual ‖I − AX‖_F/√n drops below tol —
    the reference's convergence test at newton.hpp:49-52 — expressed as a
    lax.while_loop (no data-dependent Python control flow under jit).
    """
    n = A.shape[0]
    tol = cfg.tol
    if tol is None:
        # auto-tol from the EFFECTIVE arithmetic, not the storage dtype:
        # f32 on the TPU MXU computes at the precision setting's pass
        # count, and a tol below the reachable residual plateau means the
        # early exit never fires and the loop burns its full budget
        # (measured: 'high' plateaus at 1.3e-5 > 50*eps_f32 at n=8192,
        # 30/30 iterations executed for the same result).  f64 keeps the
        # storage eps — its custom calls compute at full precision.
        eps = float(jnp.finfo(A.dtype).eps)
        if jnp.dtype(A.dtype).itemsize == 4 and cfg.precision == "high":
            eps = max(eps, 2.0**-21)  # bf16x3 split-accumulate roundoff
        tol = 50.0 * eps
    A = grid.pin(A)
    eye = grid.pin(jnp.eye(n, dtype=A.dtype))
    # ‖A‖₁ = max col abs sum, ‖A‖∞ = max row abs sum (the reference computes
    # the row-sum norm via row-comm allreduce + slice max, newton.hpp:27-35;
    # here both are global reductions XLA lowers to the same collectives)
    norm1 = jnp.max(jnp.sum(jnp.abs(A), axis=0))
    norminf = jnp.max(jnp.sum(jnp.abs(A), axis=1))
    X0 = grid.pin(A.T / (norm1 * norminf))

    gargs = GemmArgs(precision=cfg.precision)

    def resid(AX):
        return jnp.linalg.norm(eye - AX) / jnp.sqrt(jnp.asarray(n, A.dtype))

    def cond(state):
        _, _, r, it = state
        return jnp.logical_and(r > tol, it < cfg.max_iter)

    def body(state):
        # carry AX from the previous step: 2 distributed gemms per iteration
        X, AX, _, it = state
        Xn = summa.gemm(grid, X, 2.0 * eye - AX, args=gargs, mode=cfg.mode)  # X(2I−AX)
        AXn = summa.gemm(grid, A, Xn, args=gargs, mode=cfg.mode)
        return (grid.pin(Xn), AXn, resid(AXn), it + 1)

    AX0 = summa.gemm(grid, A, X0, args=gargs, mode=cfg.mode)
    X, _, r, iters = lax.while_loop(
        cond, body, (X0, AX0, resid(AX0), jnp.asarray(0, jnp.int32))
    )
    return X, iters
