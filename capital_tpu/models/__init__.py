from capital_tpu.models import cholesky  # noqa: F401
