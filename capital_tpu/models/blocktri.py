"""Block-tridiagonal Cholesky: scan-of-Pallas-blocks factor/solve.

A block-tridiagonal SPD system (Kalman smoothers, PDE chains, GP /
state-space models — ROADMAP item 3, per *GPU-Accelerated Cholesky
Factorization of Block Tridiagonal Matrices*, 2601.03754) factors in
O(nblocks·b³) work instead of the dense O((nblocks·b)³) — a structural
>1000x useful-flop reduction at (nblocks=64, b=128) against the dense
n=8192 path.  This module is the chain driver: the sequential block
recurrence

    W_i = C_i·L_{i−1}⁻ᵀ          (zero for i = 1)
    L_i = chol(D_i − W_i·W_iᵀ)   (lower)

runs as a `lax.scan` whose body is ONE `ops/blocktri_small` pallas_call
over `seg` chain blocks (impl='pallas', f32/bf16), or a scan of
`lax.linalg` primitives (impl='xla' — the f64 fallback, same dispatch
gate shape as PR 6's batched_small).  Solves are the matching forward /
backward block-bidiagonal sweeps; `posv` fuses factor + forward sweep in
one scan (the diagonal factor stays VMEM-resident across the
factor→solve boundary inside each kernel step).

Operand layout (the serve bucket layout, batch-first):

    D: (batch, nblocks, b, b)   diagonal blocks, symmetric SPD chain
    C: (batch, nblocks, b, b)   sub-diagonal blocks; C[:, 0] is dead and
                                zeroed defensively (the chain has
                                nblocks−1 couplings)
    B: (batch, nblocks, b, k)   right-hand sides

Phases: `BT::factor` wraps the factor scan (fused forward sweep
included for posv — one phase, one price), `BT::solve` the substitution
sweeps.  Emits happen HERE, outside the scans, pricing the whole chain
(`tracing.blocktri_chol_flops` / `blocktri_solve_flops`): an emit inside
a scan body would fire once at trace time while the body executes
nsteps times.  Per-block breakdown info min-combines to one global
LAPACK-convention pivot index via `robust.detect.combine_block_infos`
(block i's local 0/k/b+1 maps to global 0/(i·b+k)/(n+1)), so RobustInfo
and fault containment work per block.

`posv(impl='partitioned')` replaces the O(nblocks) sequential critical
path with the Spike / one-level cyclic-reduction decomposition (the
partitioned chain factorization of 2601.03754, the multi-device story of
JAXMg 2601.14466): the chain splits into P partitions whose LAST block
is a separator, the P interior chains (m−1 = nblocks/P − 1 blocks each)
factor CONCURRENTLY with the partition axis folded into the batched
grid (batch·P problems per pallas_call / scan step), one widened
substitution pass produces the local solutions g = A_p⁻¹b_p and the two
spikes Φ = A_p⁻¹F_p, Ψ = A_p⁻¹G_p, the P-block reduced interface system
(a block-tridiagonal SPD Schur complement over the separators) rides
the EXISTING sequential scan, and back-substitution is one batched gemm
pair — sequential depth O(nblocks/P + P) against the scan's O(nblocks),
work still O(nblocks·b³) plus the spike widening.  Phases:
`BT::partition` (interiors + back-substitution), `BT::reduce` (interface
assembly + reduced chain).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from capital_tpu.ops import blocktri_small, pallas_tpu
from capital_tpu.robust import detect
from capital_tpu.utils import tracing

IMPLS = ("auto", "pallas", "xla", "partitioned")

# auto resolves to 'partitioned' only above this chain length: below it the
# reduced-system overhead (spike widening + P-block interface solve) eats
# the depth win — the PR 6 "auto picks the winner, forcing is explicit"
# contract, measured in docs/PERF.md round 13
PARTITION_MIN_NBLOCKS = 16

# inner-impl vocabulary of the partitioned driver (the sequential scans it
# runs per partition interior and on the reduced chain)
PARTITION_INNER = ("auto", "pallas", "xla")

# the serve-side ALGORITHM vocabulary (ServeConfig.blocktri_impl): which
# chain algorithm posv_blocktri buckets compile — orthogonal to the kernel
# flavor the serve-wide impl picks
ALGORITHMS = ("auto", "scan", "partitioned")


def resolve_seg(nblocks: int, seg: int = 0) -> int:
    """Scan-segment length: chain blocks per pallas_call.  Default 8
    (launch amortization without blowing the VMEM step envelope),
    decremented to the nearest divisor of nblocks so the scan is
    rectangular — the autotune space sweeps this knob."""
    s = min(seg or 8, nblocks)
    while nblocks % s:
        s -= 1
    return max(s, 1)


def resolve_partitions(nblocks: int, partitions: int = 0) -> int:
    """Partition count for impl='partitioned': a divisor of nblocks (the
    separators are the last block of every partition, so the P interior
    chains stay uniform for batch-folding) with at least one interior
    block per partition (m = nblocks/P ≥ 2).  A requested value
    decrements to the nearest valid divisor (the `resolve_seg` idiom —
    the autotune space sweeps this knob); the default is the largest
    valid divisor ≤ √nblocks, balancing the P-step reduced chain against
    the m-step interiors (8 at the flagship nblocks=64).  Returns 1 when
    the chain cannot split (nblocks < 4, or prime) — the caller falls
    back to the sequential scan."""
    cap = nblocks // 2
    p = min(partitions or math.isqrt(nblocks), cap)
    while p > 1 and nblocks % p:
        p -= 1
    return max(p, 1)


def _steps(X, nsteps: int, seg: int):
    """(batch, nblocks, ...) -> (nsteps, batch, seg, ...) scan xs."""
    b = X.shape[0]
    return jnp.moveaxis(X.reshape((b, nsteps, seg) + X.shape[2:]), 1, 0)


def _unsteps(Y):
    """Inverse of `_steps`: (nsteps, batch, seg, ...) -> (batch, nblocks, ...)."""
    Z = jnp.moveaxis(Y, 0, 1)
    return Z.reshape((Z.shape[0], Z.shape[1] * Z.shape[2]) + Z.shape[3:])


def _check_chain(D, C, B=None, op="blocktri"):
    if D.ndim != 4 or D.shape[2] != D.shape[3]:
        raise ValueError(
            f"{op}: D must be (batch, nblocks, b, b), got {D.shape}")
    if C.shape != D.shape:
        raise ValueError(
            f"{op}: C {C.shape} must match D {D.shape}")
    if B is not None:
        if B.ndim != 4 or B.shape[:3] != D.shape[:3]:
            raise ValueError(
                f"{op}: B must be (batch, nblocks, b, k) riding D "
                f"{D.shape}, got {B.shape}")


def _partitioned_auto(nblocks: int, partitions: int, dtype) -> bool:
    """Does `auto` resolve to the partitioned driver?  Only when the
    split exists AND amortizes: an explicit `partitions` request opts in
    at any length, otherwise the chain must clear PARTITION_MIN_NBLOCKS.
    f64 keeps the sequential xla scan under auto (the PR 6 contract —
    forcing impl='partitioned' is the explicit opt-in there, and its
    inner scans resolve to the exact-dtype xla path, no downgrade)."""
    if not blocktri_small.dtype_capable(dtype):
        return False
    if resolve_partitions(nblocks, partitions) < 2:
        return False
    return bool(partitions) or nblocks >= PARTITION_MIN_NBLOCKS


def _resolve_impl(impl: str, dtype, b: int, k: int, seg: int,
                  interpret, *, nblocks: int = 0, partitions: int = 0,
                  allow_partitioned: bool = False, op: str = "blocktri") -> str:
    if impl not in IMPLS:
        raise ValueError(f"blocktri impl must be one of {IMPLS}, got {impl!r}")
    if impl == "partitioned":
        if not allow_partitioned:
            # factor/solve/extend carry the sequential (L, Wt)
            # representation across the call boundary; the partitioned
            # driver's spikes never materialize it — only the fused posv
            # can ride the split
            raise ValueError(
                f"{op}: impl='partitioned' is a posv-only algorithm (the "
                "factored representation is sequential); use posv() or "
                "impl in ('auto', 'pallas', 'xla')")
        if resolve_partitions(nblocks, partitions) < 2:
            # chain too short (or prime) to split — sequential semantics,
            # exact dtype, same resolve-don't-raise shape as f64 pallas
            return blocktri_small.default_impl(b, k, seg, dtype,
                                               interpret=interpret)
        return impl
    if impl == "auto":
        if allow_partitioned and _partitioned_auto(nblocks, partitions,
                                                   dtype):
            return "partitioned"
        return blocktri_small.default_impl(b, k, seg, dtype,
                                           interpret=interpret)
    if impl == "pallas" and not blocktri_small.dtype_capable(dtype):
        # the PR 6 dispatch-gate contract: the kernels compute in f32, so
        # honoring a forced 'pallas' for f64 would silently downgrade the
        # precision the caller paid for — fall back like api._batched_pallas
        return "xla"
    return impl


def posv_algorithm(nblocks: int, dtype, *, impl: str = "auto",
                   partitions: int = 0) -> str:
    """Which ALGORITHM `posv()` runs for this geometry: 'partitioned' or
    'scan'.  Static resolution (shapes/dtypes only — the zero-recompile
    invariant), shared by the serve engine's impl-split stats and the
    bench driver's A/B labeling."""
    if impl not in IMPLS:
        raise ValueError(f"blocktri impl must be one of {IMPLS}, got {impl!r}")
    if impl == "partitioned":
        return ("partitioned"
                if resolve_partitions(nblocks, partitions) >= 2 else "scan")
    if impl == "auto" and _partitioned_auto(nblocks, partitions, dtype):
        return "partitioned"
    return "scan"


def _combine(infos, nblocks: int, b: int, offset: int = 0):
    """Per-block infos (batch, nblocks) local 0/k/b+1 -> global (batch,)
    potrf status over n = offset + nblocks·b (shared fused-tail
    convention).  `offset` shifts the blocks' diagonal positions — the
    extend() path reports pivots relative to an already-factored prefix
    of that many rows (0 keeps indices local to the appended blocks, the
    serve route's choice: a per-prefix-length offset would be a fresh
    traced constant per prefix, i.e. one recompile per chain length)."""
    n = offset + nblocks * b
    start = jnp.zeros(infos.shape[:1], jnp.int32)
    tails = [(offset + i * b, b, infos[:, i]) for i in range(nblocks)]
    return detect.combine_block_infos(start, tails, n)


def _zero_first_coupling(C):
    """The chain has nblocks−1 couplings; a non-zero C[:, 0] would be
    silently multiplied into the first Schur complement (L_0 = I), so it
    is dead weight zeroed here — which is also what makes the first scan
    step uniform with the rest."""
    return C.at[:, 0].set(0)


def _eye_carry(batch: int, b: int, dtype):
    return jnp.broadcast_to(jnp.eye(b, dtype=dtype), (batch, b, b))


# --------------------------------------------------------------------------
# XLA fallback: scan of lax.linalg primitives (exact dtype — the f64 path)
# --------------------------------------------------------------------------


def _tri_solve(L, R, transpose: bool = False):
    """Batched lower-triangular left solve for the scan bodies.  XLA:CPU
    lowers BATCHED triangular_solve to an in-HLO blocked loop (measured
    2.5 ms per 128x128 block vs 0.18 ms for the unbatched LAPACK trsm
    custom call); a batched LU solve stays on LAPACK custom calls and
    runs ~4.5x faster, so the CPU rig takes that route — same solution,
    the operand is exactly triangular either way.  TPU/GPU keep the
    native triangular_solve.

    The platform probe rides `pallas_tpu._platform()` — the mesh/grid
    scope stack when one is active, the process default backend only
    outside any scope — because `jax.default_backend()` at trace time
    initializes the process-default client, which the hermetic dryrun
    contract forbids (a CPU-mesh dry run in a TPU-default process must
    never touch the TPU client; tests/test_multichip_hermetic.py)."""
    if pallas_tpu._platform() == "cpu":
        A = jnp.swapaxes(L, -1, -2) if transpose else L
        return jnp.linalg.solve(A, R)
    return jax.lax.linalg.triangular_solve(
        L, R, left_side=True, lower=True, transpose_a=transpose)


def _xla_factor_scan(D, C, precision, carry0=None):
    batch, nblocks, b, _ = D.shape

    def body(Lp, xs):
        d, c = xs
        ct = jnp.swapaxes(c, -1, -2)
        wt = _tri_solve(Lp, ct)
        s = d - jnp.einsum("zij,zik->zjk", wt, wt, precision=precision)
        L = jnp.linalg.cholesky(s)
        info = jax.vmap(detect.factor_info)(L)
        return L, (L, wt, info)

    if carry0 is None:
        carry0 = _eye_carry(batch, b, D.dtype)
    _, (Ls, Wts, infos) = jax.lax.scan(
        body, carry0, (jnp.moveaxis(D, 1, 0), jnp.moveaxis(C, 1, 0)))
    return (jnp.moveaxis(Ls, 0, 1), jnp.moveaxis(Wts, 0, 1),
            jnp.moveaxis(infos, 0, 1))


def _xla_forward_scan(L, Wt, B, precision):
    batch, nblocks, b, _ = L.shape
    k = B.shape[-1]

    def body(yp, xs):
        l, wt, rhs = xs
        r = rhs - jnp.einsum("zij,zik->zjk", wt, yp, precision=precision)
        y = _tri_solve(l, r)
        return y, y

    _, ys = jax.lax.scan(
        body, jnp.zeros((batch, b, k), B.dtype),
        (jnp.moveaxis(L, 1, 0), jnp.moveaxis(Wt, 1, 0),
         jnp.moveaxis(B, 1, 0)))
    return jnp.moveaxis(ys, 0, 1)


def _xla_backward_scan(L, Wt, Y, precision):
    batch, nblocks, b, _ = L.shape
    k = Y.shape[-1]
    Wtn = jnp.concatenate(
        [Wt[:, 1:], jnp.zeros_like(Wt[:, :1])], axis=1)

    def body(xn, xs):
        l, wtn, y = xs
        r = y - jnp.einsum("zij,zjk->zik", wtn, xn, precision=precision)
        x = _tri_solve(l, r, transpose=True)
        return x, x

    _, xs_out = jax.lax.scan(
        body, jnp.zeros((batch, b, k), Y.dtype),
        (jnp.moveaxis(L, 1, 0), jnp.moveaxis(Wtn, 1, 0),
         jnp.moveaxis(Y, 1, 0)), reverse=True)
    return jnp.moveaxis(xs_out, 0, 1)


# --------------------------------------------------------------------------
# pallas scan paths
# --------------------------------------------------------------------------


def _pallas_factor_scan(D, C, *, seg, block, precision, interpret,
                        carry0=None):
    batch, nblocks, b, _ = D.shape
    nsteps = nblocks // seg
    Ds, Cs = _steps(D, nsteps, seg), _steps(C, nsteps, seg)

    def body(Lc, xs):
        d, c = xs
        L, Wt, info = blocktri_small.factor_step(
            d, c, Lc, block=block, precision=precision, interpret=interpret)
        return L[:, -1], (L, Wt, info)

    if carry0 is None:
        carry0 = _eye_carry(batch, b, D.dtype)
    _, (Ls, Wts, infos) = jax.lax.scan(body, carry0, (Ds, Cs))
    return _unsteps(Ls), _unsteps(Wts), _unsteps(infos)


def _pallas_forward_scan(L, Wt, B, *, seg, block, precision, interpret):
    batch, nblocks, b, _ = L.shape
    k = B.shape[-1]
    nsteps = nblocks // seg
    xs = (_steps(L, nsteps, seg), _steps(Wt, nsteps, seg),
          _steps(B, nsteps, seg))

    def body(yc, step):
        l, wt, rhs = step
        y = blocktri_small.forward_solve_step(
            l, wt, rhs, yc, block=block, precision=precision,
            interpret=interpret)
        return y[:, -1], y

    _, ys = jax.lax.scan(body, jnp.zeros((batch, b, k), B.dtype), xs)
    return _unsteps(ys)


def _pallas_backward_scan(L, Wt, Y, *, seg, block, precision, interpret):
    batch, nblocks, b, _ = L.shape
    k = Y.shape[-1]
    nsteps = nblocks // seg
    Wtn = jnp.concatenate([Wt[:, 1:], jnp.zeros_like(Wt[:, :1])], axis=1)
    xs = (_steps(L, nsteps, seg), _steps(Wtn, nsteps, seg),
          _steps(Y, nsteps, seg))

    def body(xc, step):
        l, wtn, y = step
        x = blocktri_small.solve_backward_step(
            l, wtn, y, xc, block=block, precision=precision,
            interpret=interpret)
        return x[:, 0], x

    _, xs_out = jax.lax.scan(
        body, jnp.zeros((batch, b, k), Y.dtype), xs, reverse=True)
    return _unsteps(xs_out)


def _pallas_fused_forward(D, C, B, *, seg, block, precision, interpret):
    batch, nblocks, b, _ = D.shape
    k = B.shape[-1]
    nsteps = nblocks // seg
    xs = (_steps(D, nsteps, seg), _steps(C, nsteps, seg),
          _steps(B, nsteps, seg))

    def body(carry, step):
        Lc, yc = carry
        d, c, rhs = step
        L, Wt, y, info = blocktri_small.fused_forward_step(
            d, c, rhs, Lc, yc, block=block, precision=precision,
            interpret=interpret)
        return (L[:, -1], y[:, -1]), (L, Wt, y, info)

    carry0 = (_eye_carry(batch, b, D.dtype),
              jnp.zeros((batch, b, k), B.dtype))
    _, (Ls, Wts, ys, infos) = jax.lax.scan(body, carry0, xs)
    return _unsteps(Ls), _unsteps(Wts), _unsteps(ys), _unsteps(infos)


# --------------------------------------------------------------------------
# partitioned (Spike / one-level cyclic-reduction) driver
# --------------------------------------------------------------------------


def _scan_posv(D, C, B, impl, *, seg, block, precision, interpret):
    """Raw sequential fused posv: (X, per-block infos (batch, nblocks)).
    No scopes, no emits, no info combining — the partitioned driver runs
    this on the folded interiors and on the reduced chain and prices both
    itself (its phase split is partition/reduce, not factor/solve)."""
    if impl == "pallas":
        L, Wt, Y, infos = _pallas_fused_forward(
            D, C, B, seg=seg, block=block, precision=precision,
            interpret=interpret)
        X = _pallas_backward_scan(
            L, Wt, Y, seg=seg, block=block, precision=precision,
            interpret=interpret)
    else:
        L, Wt, infos = _xla_factor_scan(D, C, precision)
        Y = _xla_forward_scan(L, Wt, B, precision)
        X = _xla_backward_scan(L, Wt, Y, precision)
    return X, infos


def _combine_partitioned(infos_in, infos_red, nblocks, b, P, m):
    """Map partition-relative per-block infos to ONE whole-chain potrf
    status: interior block j of partition p sits at global block p·m + j,
    separator p at global block p·m + m − 1 (the PR 12 `extend` offset
    idiom — each tail's `dest` is its global diagonal offset, and
    `combine_block_infos` min-combines with the drop-polluted-windows
    first pass).

    One pollution edge is BACKWARD and must be masked before the
    min-combine: the Schur assembly subtracts E_{p+1}ᵀ·Φ_{p+1} from
    separator p's reduced diagonal, and a broken interior p + 1 turns
    that update into NaN even through zero couplings (0·NaN = NaN) —
    while separator p precedes partition p + 1's interior in chain
    order.  Left alone, the min would report separator p as a spuriously
    EARLIER first-bad pivot than the sequential scan does.  So a reduced
    candidate at separator p is dropped whenever interior p + 1 is
    broken; interior p + 1's own (true, later) position wins instead.
    The cost: if separator p is ALSO genuinely indefinite in that case
    we report the interior's position rather than the separator's — the
    two breakdowns are indistinguishable post-NaN, and the reported
    pivot still flags a genuinely broken leading minor."""
    n = nblocks * b
    start = jnp.zeros(infos_in.shape[:1], jnp.int32)
    red = [infos_red[:, p] for p in range(P)]
    for p in range(P - 1):
        next_broken = infos_in[:, p + 1].max(axis=-1) > 0
        red[p] = jnp.where(next_broken, 0, red[p])
    tails = []
    for p in range(P):
        for j in range(m - 1):
            tails.append(((p * m + j) * b, b, infos_in[:, p, j]))
        tails.append(((p * m + m - 1) * b, b, red[p]))
    return detect.combine_block_infos(start, tails, n)


def _partitioned_posv(D, C, B, *, partitions, inner, block, seg,
                      precision, interpret):
    """The Spike decomposition (docstring at module top).  Separators are
    the LAST block of every partition: s_p = p·m + m − 1, interiors
    J_p = blocks p·m .. p·m + m − 2.  One widened interior substitution
    pass at RHS [B | F | G] (k + 2b columns) yields the local solutions
    and both spikes; the reduced interface system over the P separators
    is itself block-tridiagonal SPD and rides the ordinary sequential
    scan."""
    batch, nblocks, b, _ = D.shape
    k = B.shape[-1]
    P = partitions
    m = nblocks // P
    prec = precision

    Dr = D.reshape(batch, P, m, b, b)
    Cr = C.reshape(batch, P, m, b, b)
    Br = B.reshape(batch, P, m, b, k)
    E = Cr[:, :, 0]            # cross-partition coupling into block p·m
    Csep = Cr[:, :, m - 1]     # separator s_p ← its own interior tail
    Dsep, Bsep = Dr[:, :, m - 1], Br[:, :, m - 1]

    with tracing.scope("BT::partition"):
        tracing.emit(flops=batch * tracing.blocktri_partition_flops(
            nblocks, b, k, P))
        # interior chains, partition axis folded into the batch axis —
        # this is the concurrency: batch·P independent (m−1)-block chains
        # per scan step / pallas grid
        Din = Dr[:, :, :m - 1].reshape(batch * P, m - 1, b, b)
        Cin = (Cr[:, :, :m - 1].at[:, :, 0].set(0)
               .reshape(batch * P, m - 1, b, b))
        # widened RHS [B | F | G]: F_p = E_p in the FIRST interior block,
        # G_p = C_{s_p}ᵀ in the LAST (the two column-blocks whose solves
        # are the spikes Φ_p = A_p⁻¹F_p, Ψ_p = A_p⁻¹G_p); E_0 is dead
        # (C[:, 0] zeroed), so Φ_0 = 0 falls out for free
        R = jnp.zeros((batch, P, m - 1, b, k + 2 * b), B.dtype)
        R = R.at[..., :k].set(Br[:, :, :m - 1])
        R = R.at[:, :, 0, :, k:k + b].set(E)
        R = R.at[:, :, m - 2, :, k + b:].set(jnp.swapaxes(Csep, -1, -2))
        segi = resolve_seg(m - 1, seg)
        Sol, infos_in = _scan_posv(
            Din, Cin, R.reshape(batch * P, m - 1, b, k + 2 * b),
            inner, seg=segi, block=block, precision=prec,
            interpret=interpret)
        Sol = Sol.reshape(batch, P, m - 1, b, k + 2 * b)
        g, Phi, Psi = Sol[..., :k], Sol[..., k:k + b], Sol[..., k + b:]

    with tracing.scope("BT::reduce"):
        tracing.emit(flops=batch * tracing.blocktri_reduce_flops(P, b, k))
        # Schur complement over the separators: eliminate the interiors.
        # S[p,p]   = D_{s_p} − C_{s_p}·Ψ_p[last] − E_{p+1}ᵀ·Φ_{p+1}[first]
        # S[p,p−1] = −C_{s_p}·Φ_p[last]            (dead at p = 0)
        # b̃_p      = B_{s_p} − C_{s_p}·g_p[last] − E_{p+1}ᵀ·g_{p+1}[first]
        ET = jnp.swapaxes(E, -1, -2)
        Sd = Dsep - jnp.einsum("zpij,zpjk->zpik", Csep, Psi[:, :, m - 2],
                               precision=prec)
        Sd = Sd.at[:, :P - 1].add(-jnp.einsum(
            "zpij,zpjk->zpik", ET[:, 1:], Phi[:, 1:, 0], precision=prec))
        Ct = -jnp.einsum("zpij,zpjk->zpik", Csep, Phi[:, :, m - 2],
                         precision=prec)
        Ct = Ct.at[:, 0].set(0)
        bt = Bsep - jnp.einsum("zpij,zpjk->zpik", Csep, g[:, :, m - 2],
                               precision=prec)
        bt = bt.at[:, :P - 1].add(-jnp.einsum(
            "zpij,zpjk->zpik", ET[:, 1:], g[:, 1:, 0], precision=prec))
        xsep, infos_red = _scan_posv(
            Sd, Ct, bt, inner, seg=resolve_seg(P, seg), block=block,
            precision=prec, interpret=interpret)

    with tracing.scope("BT::partition"):
        # back-substitution — batched gemm pair per partition, no scans:
        # x_{J_p} = g_p − Φ_p·x_{s_{p−1}} − Ψ_p·x_{s_p}
        xprev = jnp.concatenate(
            [jnp.zeros_like(xsep[:, :1]), xsep[:, :-1]], axis=1)
        Xin = (g
               - jnp.einsum("zpaij,zpjk->zpaik", Phi, xprev, precision=prec)
               - jnp.einsum("zpaij,zpjk->zpaik", Psi, xsep, precision=prec))
        X = jnp.concatenate([Xin, xsep[:, :, None]], axis=2)
        X = X.reshape(batch, nblocks, b, k)

    infos_in = infos_in.reshape(batch, P, m - 1)
    return X, _combine_partitioned(infos_in, infos_red, nblocks, b, P, m)


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------


def factor(D, C, *, block: int = 0, seg: int = 0,
           precision: str | None = "highest", impl: str = "auto",
           interpret: bool | None = None):
    """Factor the block-tridiagonal SPD chain: A = L̃·L̃ᵀ.

    Returns (L, Wt, info): L (batch, nblocks, b, b) per-block lower
    Cholesky factors, Wt (batch, nblocks, b, b) TRANSPOSED sub-diagonal
    factors (Wt_i = W_iᵀ = L_{i−1}⁻¹·C_iᵀ; Wt_1 = 0 — the representation
    the solve sweeps consume without in-kernel transposes), and info
    (batch,) int32 global potrf status over n = nblocks·b."""
    _check_chain(D, C, op="blocktri factor")
    batch, nblocks, b, _ = D.shape
    seg = resolve_seg(nblocks, seg)
    impl = _resolve_impl(impl, D.dtype, b, b, seg, interpret,
                         op="blocktri factor")
    C = _zero_first_coupling(C)
    with tracing.scope("BT::factor"):
        tracing.emit(flops=batch * tracing.blocktri_chol_flops(nblocks, b))
        if impl == "pallas":
            L, Wt, infos = _pallas_factor_scan(
                D, C, seg=seg, block=block, precision=precision,
                interpret=interpret)
        else:
            L, Wt, infos = _xla_factor_scan(D, C, precision)
    return L, Wt, _combine(infos, nblocks, b)


def extend(D, C, L_last, *, block: int = 0, seg: int = 0,
           precision: str | None = "highest", impl: str = "auto",
           interpret: bool | None = None, offset: int = 0):
    """Append blocks to an ALREADY-FACTORED chain without refactoring the
    prefix: the Schur recurrence is first-order in the diagonal factor, so
    continuing it only needs `L_last` — the final (batch, b, b) diagonal
    factor of the existing chain (ROADMAP item 4's streaming state-space
    case; the serve `blocktri_extend` op, docs/SERVING.md "Factor
    residency").

    D/C are the (batch, nblocks, b, b) APPENDED blocks only.  Unlike
    `factor()`, C[:, 0] is LIVE here — it couples the first appended block
    to the prefix tail; a caller starting a fresh chain (L_last = I) must
    zero it explicitly.  `offset` (static) shifts the returned info's
    pivot indices by the prefix length; the default 0 keeps them local to
    the appended blocks so one compiled program serves every prefix
    length.

    Returns (L, Wt, info) for the appended blocks in the `factor()`
    representation — concatenating onto the prefix's (L, Wt) yields
    bitwise the factor a full refactor of the whole chain would produce
    (the recurrence is identical, step for step; tests/test_update.py
    asserts it)."""
    _check_chain(D, C, op="blocktri extend")
    batch, nblocks, b, _ = D.shape
    if L_last.shape != (batch, b, b):
        raise ValueError(
            f"blocktri extend: L_last must be (batch, b, b) = "
            f"({batch}, {b}, {b}) riding D {D.shape}, got {L_last.shape}")
    seg = resolve_seg(nblocks, seg)
    impl = _resolve_impl(impl, D.dtype, b, b, seg, interpret,
                         op="blocktri extend")
    with tracing.scope("UP::extend"):
        tracing.emit(flops=batch * tracing.blocktri_chol_flops(nblocks, b))
        if impl == "pallas":
            L, Wt, infos = _pallas_factor_scan(
                D, C, seg=seg, block=block, precision=precision,
                interpret=interpret, carry0=L_last)
        else:
            L, Wt, infos = _xla_factor_scan(D, C, precision,
                                            carry0=L_last)
    return L, Wt, _combine(infos, nblocks, b, offset)


def contract(L, Wt, k: int):
    """Drop the `k` OLDEST blocks from an already-factored chain — the
    sliding-window dual of `extend` (ROADMAP item 5's streaming
    state-space sessions; the serve `session_contract` op).

    Elimination runs head→tail, so block i's factors depend only on
    blocks ≤ i: truncating the head leaves every retained factor block
    UNCHANGED, and contract is a pure slice — no kernel, no compile, no
    flops.  The retained representation `(L[:, k:], Wt[:, k:])` is
    bitwise what `extend(D[:, k:], C[:, k:], L[:, k - 1])` would replay
    (tests/test_sessions.py pins it), and `Wt[:, k]` — the coupling into
    the dropped prefix — stays in place untouched: both solve sweeps are
    structurally blind to it (the forward scan starts from a ZERO carry
    and the backward sweep consumes the one-shifted Wt), so `solve` on
    the contracted factor needs no zeroing.

    The matrix the contracted factor represents is the MARGINAL
    (Schur-complemented) precision of the retained window, not the raw
    truncated chain: its head diagonal is D_k − W_k·W_kᵀ = L_k·L_kᵀ,
    computable from the factor alone, with the head coupling gone.  A
    caller maintaining an explicit (D, C) window (the SessionManager's
    residual seam) must set D[:, k] ← L[:, k]·L[:, k]ᵀ and C[:, k] ← 0
    when it slides.

    Returns (L[:, k:], Wt[:, k:]) — views, no copy."""
    _check_chain(L, Wt, op="blocktri contract")
    nblocks = L.shape[1]
    if not 0 <= k < nblocks:
        raise ValueError(
            f"blocktri contract: k must be in [0, nblocks={nblocks}), "
            f"got {k}")
    return L[:, k:], Wt[:, k:]


def solve(L, Wt, B, *, block: int = 0, seg: int = 0,
          precision: str | None = "highest", impl: str = "auto",
          interpret: bool | None = None):
    """Solve A·X = B from a ready factor (`potrs` analog): the forward
    then backward block-bidiagonal sweeps.  Returns X (batch, nblocks,
    b, k)."""
    _check_chain(L, Wt, B, op="blocktri solve")
    batch, nblocks, b, _ = L.shape
    k = B.shape[-1]
    seg = resolve_seg(nblocks, seg)
    impl = _resolve_impl(impl, B.dtype, b, k, seg, interpret,
                         op="blocktri solve")
    with tracing.scope("BT::solve"):
        tracing.emit(
            flops=batch * 2 * tracing.blocktri_solve_flops(nblocks, b, k))
        if impl == "pallas":
            Y = _pallas_forward_scan(
                L, Wt, B, seg=seg, block=block, precision=precision,
                interpret=interpret)
            X = _pallas_backward_scan(
                L, Wt, Y, seg=seg, block=block, precision=precision,
                interpret=interpret)
        else:
            Y = _xla_forward_scan(L, Wt, B, precision)
            X = _xla_backward_scan(L, Wt, Y, precision)
    return X


def posv(D, C, B, *, block: int = 0, seg: int = 0,
         precision: str | None = "highest", impl: str = "auto",
         interpret: bool | None = None, partitions: int = 0,
         partition_inner: str = "auto"):
    """FUSED factor + solve of the block-tridiagonal chain: the factor
    scan consumes each L_i for the forward sweep while it is VMEM-resident
    (one fused kernel per scan step — the serve `posv_blocktri` op), then
    the backward sweep finishes.  Returns (X, info): X (batch, nblocks,
    b, k), info (batch,) int32 global potrf status.

    impl='partitioned' (or 'auto' above PARTITION_MIN_NBLOCKS) runs the
    Spike decomposition instead of the sequential scan — same (X, info)
    contract, sequential depth O(nblocks/P + P).  `partitions` requests
    the split count (0 → resolve_partitions default; the autotune axis);
    `partition_inner` picks the scan flavor of the interior/reduced
    chains ('auto' resolves per `blocktri_small.partition_inner_impl` —
    the VMEM gate at the widened spike RHS; f64 interiors ride the exact-
    dtype xla scan, so forcing 'partitioned' never downgrades
    precision)."""
    _check_chain(D, C, B, op="blocktri posv")
    batch, nblocks, b, _ = D.shape
    k = B.shape[-1]
    seg = resolve_seg(nblocks, seg)
    impl = _resolve_impl(impl, D.dtype, b, k, seg, interpret,
                         nblocks=nblocks, partitions=partitions,
                         allow_partitioned=True, op="blocktri posv")
    C = _zero_first_coupling(C)
    if impl == "partitioned":
        if partition_inner not in PARTITION_INNER:
            raise ValueError(
                f"blocktri posv: partition_inner must be one of "
                f"{PARTITION_INNER}, got {partition_inner!r}")
        P = resolve_partitions(nblocks, partitions)
        if partition_inner == "auto":
            inner = blocktri_small.partition_inner_impl(
                b, k, resolve_seg(nblocks // P - 1, seg), D.dtype,
                interpret=interpret)
        elif (partition_inner == "pallas"
              and not blocktri_small.dtype_capable(D.dtype)):
            inner = "xla"  # the same no-silent-downgrade gate as above
        else:
            inner = partition_inner
        return _partitioned_posv(
            D, C, B, partitions=P, inner=inner, block=block, seg=seg,
            precision=precision, interpret=interpret)
    with tracing.scope("BT::factor"):
        # fused factor + forward sweep: one phase, one price
        tracing.emit(
            flops=batch * (tracing.blocktri_chol_flops(nblocks, b)
                           + tracing.blocktri_solve_flops(nblocks, b, k)))
        if impl == "pallas":
            L, Wt, Y, infos = _pallas_fused_forward(
                D, C, B, seg=seg, block=block, precision=precision,
                interpret=interpret)
        else:
            L, Wt, infos = _xla_factor_scan(D, C, precision)
            Y = _xla_forward_scan(L, Wt, B, precision)
    with tracing.scope("BT::solve"):
        tracing.emit(
            flops=batch * tracing.blocktri_solve_flops(nblocks, b, k))
        if impl == "pallas":
            X = _pallas_backward_scan(
                L, Wt, Y, seg=seg, block=block, precision=precision,
                interpret=interpret)
        else:
            X = _xla_backward_scan(L, Wt, Y, precision)
    return X, _combine(infos, nblocks, b)


def assemble(D, C):
    """Materialize the dense (batch, n, n) matrix the chain represents —
    the test/bench reference seam (O(n²) memory; keep nblocks·b small)."""
    _check_chain(D, C, op="blocktri assemble")
    batch, nblocks, b, _ = D.shape
    n = nblocks * b
    A = jnp.zeros((batch, n, n), D.dtype)
    for i in range(nblocks):
        sl = slice(i * b, (i + 1) * b)
        A = A.at[:, sl, sl].set(D[:, i])
        if i:
            up = slice((i - 1) * b, i * b)
            A = A.at[:, sl, up].set(C[:, i])
            A = A.at[:, up, sl].set(jnp.swapaxes(C[:, i], -1, -2))
    return A
