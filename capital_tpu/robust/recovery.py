"""Shifted-CholeskyQR recovery for broken gram factorizations.

On breakdown (robust/detect.factor_info != 0) the gram matrix G = A^T A is
numerically indefinite.  The sCQR fix (Fukaya, Kannan, Nakatsukasa, Yamamoto,
Yanagisawa, "Shifted Cholesky QR for computing the QR factorization of
ill-conditioned matrices") re-factors the shifted gram

    G + sigma * I,   sigma = c * u * (m*n + n*(n+1)) * tr(G),   c = 11

which is SPD whenever the unshifted factorization can fail in floating
point, and bounds cond(A R^{-1}) <= O(u^{-1/2}) regardless of cond(A) —
small enough that the *next* CholeskyQR sweep is unconditionally safe.
tr(G) = ||A||_F^2 >= ||A||_2^2 serves as the cheap spectral-norm
overestimate the analysis needs.

`guarded_chol` wraps any (G -> (R, Rinv)) factorizer with detection plus a
`lax.cond` shifted retry, so the healthy path pays one O(n^2) status
reduction and the recovery work compiles into the cold branch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from capital_tpu.robust import detect
from capital_tpu.robust.config import CholEvent, RobustConfig
from capital_tpu.utils import tracing


def unit_roundoff(dtype) -> float:
    """u for the *compute* dtype: sub-f32 inputs are factored in f32 by
    ops/lapack (see lapack._compute_dtype), so their effective roundoff is
    f32's."""
    dt = jnp.dtype(dtype)
    if dt.itemsize < jnp.dtype(jnp.float32).itemsize:
        dt = jnp.dtype(jnp.float32)
    return float(jnp.finfo(dt).eps)


def sigma_shift(G, m_rows: int, c: float = 11.0):
    """The sCQR shift sigma = c*u*(m*n + n*(n+1))*tr(G), in G's dtype.

    The trace is read off the diagonal only, so the formula stays valid for
    upper-triangular-valid grams (the dist pipeline's G carries garbage
    below the diagonal)."""
    n = G.shape[-1]
    u = unit_roundoff(G.dtype)
    tr = jnp.sum(jnp.diagonal(G))
    return (c * u * (m_rows * n + n * (n + 1))) * tr


def guarded_chol(G, m_rows: int, rcfg: RobustConfig | None, chol_fn):
    """Factor G via chol_fn with breakdown detection + shifted retry.

    chol_fn: G -> (R, Rinv).  Returns (R, Rinv, CholEvent).  With rcfg None
    or rcfg.recover False this is detect-only: the unshifted factor is
    returned with its status and sigma = 0.

    The shifted branch re-runs chol_fn under tracing.muted(): both lax.cond
    branches are traced, so without muting every guarded site would
    double-count its phase flops in the cost model.  The audit layer still
    sees the recovery ops in the compiled program (bench/trace buckets them
    from the HLO, not from emit()).
    """
    R, Rinv = chol_fn(G)
    info = detect.factor_info(R)
    if rcfg is None or not rcfg.recover:
        zero = jnp.zeros((), G.dtype)
        return R, Rinv, CholEvent(info=info, sigma=zero, info_after=info)

    sigma = sigma_shift(G, m_rows, c=rcfg.shift_c)

    def _shifted(_):
        with tracing.muted():
            n = G.shape[-1]
            Gs = G + sigma * jnp.eye(n, dtype=G.dtype)
            return chol_fn(Gs)

    def _keep(_):
        return R, Rinv

    R2, Rinv2 = lax.cond(info != 0, _shifted, _keep, operand=None)
    applied = jnp.where(info != 0, sigma, jnp.zeros((), sigma.dtype))
    return R2, Rinv2, CholEvent(
        info=info, sigma=applied, info_after=detect.factor_info(R2)
    )


# --------------------------------------------------------------------------
# the rung above sCQR3: Householder TSQR escalation (ops/tsqr.py)
# --------------------------------------------------------------------------


def escalation_dtype(dtype):
    """The compute dtype of the TSQR escalation rung: ALWAYS f64 where x64
    is live — escalation means the caller has already paid recovery sweeps
    and wants accuracy, not dtype preservation (cond beyond the f32 shift
    envelope needs u ~ 1e-16 to recover at all).  On x64-disabled rigs the
    rule degrades honestly to f32: canonicalize_dtype reports what the
    runtime can actually represent, the gate measurement then says whether
    that was enough."""
    del dtype  # the rule is unconditional; the arg documents the call sites
    return jnp.dtype(jax.dtypes.canonicalize_dtype(jnp.float64))


def tsqr_escalate(A, *, precision: str | None = "highest"):
    """Re-factor A with the blocked Householder TSQR (ops/tsqr,
    arXiv:0809.2407) at the escalation dtype — the target the robust
    ladder routes to when `RobustInfo.gate == GATE_ORTHO` (the CQR family
    is out of envelope at A's precision but the matrix itself is fine).

    Returns (Q, R, ortho) AT THE ESCALATION DTYPE: ortho is the measured
    final gate ||I − QᵀQ||_F/sqrt(n), never an assumption, so callers
    branch on it exactly like RobustInfo.ortho.  TSQR never forms a gram,
    so at f64 this recovers cond(A) up to ~u⁻¹ ≈ 1e15 where sCQR3 stalls
    (docs/ROBUSTNESS.md escalation ladder)."""
    # local import: robust/__init__ imports this module, and ops/lapack
    # imports the robust package — a top-level ops import here would cycle
    from capital_tpu.ops import tsqr as tsqr_mod

    ct = escalation_dtype(A.dtype)
    Q, R = tsqr_mod.tsqr(A.astype(ct), precision=precision)
    return Q, R, tsqr_mod.ortho_gate(Q, precision)
