"""In-graph breakdown detection for Cholesky factors.

`lax.linalg.cholesky` has no `info` output: on an indefinite input the CPU
LAPACK kernel reports info > 0 and jax converts that to a silent NaN fill;
on TPU the rank-deficient trailing blocks produce NaN/Inf directly.  Either
way the breakdown is recoverable *from the factor itself* — a clean
Cholesky factor has a finite, strictly positive diagonal.  `factor_info`
reduces that predicate to a LAPACK-style int32 scalar that stays inside the
jit program (no host sync), so callers can branch on it with `lax.cond`.
"""

from __future__ import annotations

import jax.numpy as jnp


def factor_info(R) -> jnp.ndarray:
    """LAPACK `potrf`-style status for a triangular factor R (n x n).

    Returns int32:
      0      -- healthy: finite everywhere, diagonal strictly positive.
      k in [1, n] -- 1-based index of the first non-finite or non-positive
                diagonal entry (the LAPACK convention: the leading (k-1)
                minor factored fine, order k did not).
      n + 1  -- diagonal is clean but an off-diagonal entry is non-finite
                (seen when a NaN contaminates the triangular solve rather
                than the factorization itself).

    Works on either triangle convention (only the diagonal sign matters)
    and is jit/vmap-safe: a pure O(n^2) reduction, no host callback.
    """
    d = jnp.diagonal(R)
    bad_diag = ~(jnp.isfinite(d) & (d > 0))
    # argmax on bool gives the first True; guard with any() so an all-good
    # diagonal maps to 0 rather than index-0's "1".
    first_bad = jnp.where(
        jnp.any(bad_diag), jnp.argmax(bad_diag).astype(jnp.int32) + 1, 0
    )
    off_bad = ~jnp.all(jnp.isfinite(R))
    n = R.shape[-1]
    return jnp.where(
        first_bad > 0,
        first_bad,
        jnp.where(off_bad, jnp.int32(n + 1), jnp.int32(0)),
    ).astype(jnp.int32)
