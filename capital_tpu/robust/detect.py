"""In-graph breakdown detection for Cholesky factors.

`lax.linalg.cholesky` has no `info` output: on an indefinite input the CPU
LAPACK kernel reports info > 0 and jax converts that to a silent NaN fill;
on TPU the rank-deficient trailing blocks produce NaN/Inf directly.  Either
way the breakdown is recoverable *from the factor itself* — a clean
Cholesky factor has a finite, strictly positive diagonal.  `factor_info`
reduces that predicate to a LAPACK-style int32 scalar that stays inside the
jit program (no host sync), so callers can branch on it with `lax.cond`.

`combine_block_infos` is the shared min-combine that folds PER-WINDOW
in-kernel info scalars (the fused-tail megakernels of models/cholesky.py,
the per-chain-block infos of models/blocktri.py) into one global
LAPACK-convention status.
"""

from __future__ import annotations

import jax.numpy as jnp


def factor_info(R) -> jnp.ndarray:
    """LAPACK `potrf`-style status for a triangular factor R (n x n).

    Returns int32:
      0      -- healthy: finite everywhere, diagonal strictly positive.
      k in [1, n] -- 1-based index of the first non-finite or non-positive
                diagonal entry (the LAPACK convention: the leading (k-1)
                minor factored fine, order k did not).
      n + 1  -- diagonal is clean but an off-diagonal entry is non-finite
                (seen when a NaN contaminates the triangular solve rather
                than the factorization itself).

    Works on either triangle convention (only the diagonal sign matters)
    and is jit/vmap-safe: a pure O(n^2) reduction, no host callback.
    """
    d = jnp.diagonal(R)
    bad_diag = ~(jnp.isfinite(d) & (d > 0))
    # argmax on bool gives the first True; guard with any() so an all-good
    # diagonal maps to 0 rather than index-0's "1".
    first_bad = jnp.where(
        jnp.any(bad_diag), jnp.argmax(bad_diag).astype(jnp.int32) + 1, 0
    )
    off_bad = ~jnp.all(jnp.isfinite(R))
    n = R.shape[-1]
    return jnp.where(
        first_bad > 0,
        first_bad,
        jnp.where(off_bad, jnp.int32(n + 1), jnp.int32(0)),
    ).astype(jnp.int32)


def combine_block_infos(info, tail_infos: list, n: int) -> jnp.ndarray:
    """Fold per-window in-kernel info scalars into a global potrf status.

    `info` is the starting global status (a post-hoc `factor_info` of the
    assembled factor, or zeros when no post-hoc scan exists — scalar or
    batched, any int dtype); `tail_infos` is a list of ``(dest, nw, w)``
    triples: a window at 1-based diagonal offset `dest` of local size
    `nw` reported local info `w` (0 healthy, k in [1, nw] first bad
    pivot, nw+1 off-diagonal contamination — shaped like `info`); `n` is
    the global live dimension.

    This is NOT redundant with `factor_info`: a guarded in-kernel sweep
    turns a bad pivot into finite garbage (no NaN fill the post-hoc
    diagonal scan is guaranteed to see), and when the garbage DOES
    overflow, one-hot outer products turn inf into 0·inf NaNs across the
    whole window — including rows factored BEFORE the breakdown — so the
    post-hoc first-bad-diagonal position inside a broken window is
    backward pollution, not the true pivot.  The kernel's own info is
    authoritative there: post-hoc pivot positions that fall inside a
    broken window are dropped first, then every window's candidate merges
    in.  Local w in [1, nw] maps to global pivot dest+w (1-based, ignored
    when it falls in the identity pad beyond n); w == nw+1 maps to the
    global n+1.  The global status is the FIRST bad pivot — the minimum
    over all flagged positions, which also ranks any pivot (<= n) above
    the off-diagonal sentinel n+1, matching the factor_info precedence."""
    for dest, nw, w in tail_infos:
        broken = w.astype(info.dtype) > 0
        inside = (info > dest) & (info <= dest + nw) & (info <= n)
        info = jnp.where(broken & inside, 0, info)
    for dest, nw, w in tail_infos:
        w = w.astype(info.dtype)
        piv = jnp.where((w > 0) & (w <= nw) & (dest + w <= n), dest + w, 0)
        offd = jnp.where(w == nw + 1, jnp.asarray(n + 1, info.dtype), 0)
        cand = jnp.where(piv > 0, piv, offd)
        info = jnp.where(
            info == 0, cand,
            jnp.where(cand == 0, info, jnp.minimum(info, cand)),
        )
    return info
