"""Deterministic fault injection keyed on tracing.PHASE_REGISTRY tags.

Breakdown and recovery paths are hard to exercise organically — a TPU pod
OOM or a rank-collapsed gram shows up once a week, not once a test run.
This module plants faults at the phase-tagged taps the ops layer exposes
(`tap(x)` calls inside ops/lapack and models/qr): a `Fault` names a phase
tag from tracing.PHASE_REGISTRY, which occurrence of that tag to hit, and
the corruption to apply.  Injection is positional and host-side, so the
same plan always corrupts the same site — deterministic on the CPU rig.

    with faultinject.active_plan(
        faultinject.Fault(tag="CQR::gram", kind="rank_deficient")
    ) as plan:
        Q, R, info = qr.factor(grid, A, cfg_with_robust)
    assert plan.fired == [("CQR::gram", 0)]

Caveat: taps fire at *trace* time.  Under jit the corruption bakes into
the compiled program (fine for testing recovery); both branches of a
lax.cond are traced, so taps inside guarded recovery branches also fire —
prefer injecting at sites outside the cond (e.g. CQR::gram) when counting
occurrences.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses

import jax
import jax.numpy as jnp

from capital_tpu.utils import tracing

_KINDS = ("nan", "inf", "rank_deficient", "raise")


class FaultInjected(jax.errors.JaxRuntimeError):
    """Raised by kind='raise' faults.  Subclasses JaxRuntimeError (the
    XlaRuntimeError alias) so the bench/autotune containment layer treats
    an injected failure exactly like a real device-side abort."""


@dataclasses.dataclass(frozen=True)
class Fault:
    """One planted fault.

    tag: a phase tag registered in tracing.PHASE_REGISTRY (ValueError
        otherwise — typos must not silently never fire).
    kind: 'nan' / 'inf' poison one element; 'rank_deficient' zeroes the
        last row+column (a singular but finite gram — the shifted-retry
        case); 'raise' throws FaultInjected at trace time (the sweep
        containment case).
    index: which occurrence of `tag` to hit (0-based, counted per plan).
    count: how many consecutive occurrences from `index` to corrupt.
    """

    tag: str
    kind: str = "nan"
    index: int = 0
    count: int = 1

    def __post_init__(self):
        if self.tag not in tracing.PHASE_REGISTRY:
            raise ValueError(
                f"fault tag {self.tag!r} not in tracing.PHASE_REGISTRY; "
                f"known tags: {sorted(tracing.PHASE_REGISTRY)}"
            )
        if self.kind not in _KINDS:
            raise ValueError(f"fault kind {self.kind!r} not in {_KINDS}")


class FaultPlan:
    """Active set of faults plus the deterministic firing record."""

    def __init__(self, faults):
        self.faults = tuple(faults)
        self.hits = collections.Counter()  # tag -> occurrences seen
        self.fired: list[tuple[str, int]] = []  # (tag, occurrence) applied

    def corrupt(self, x, tag: str):
        occ = self.hits[tag]
        self.hits[tag] += 1
        for f in self.faults:
            if f.tag == tag and f.index <= occ < f.index + f.count:
                self.fired.append((tag, occ))
                if f.kind == "raise":
                    raise FaultInjected(
                        f"injected fault at {tag!r} occurrence {occ}"
                    )
                x = _corrupt_array(x, f.kind)
        return x


def _corrupt_array(x, kind: str):
    if kind == "rank_deficient":
        if x.ndim < 2:
            return jnp.zeros_like(x)
        return x.at[..., -1, :].set(0).at[..., :, -1].set(0)
    val = jnp.nan if kind == "nan" else jnp.inf
    return x.at[(0,) * x.ndim].set(jnp.asarray(val, x.dtype))


_PLANS: list[FaultPlan] = []


@contextlib.contextmanager
def active_plan(*faults: Fault):
    """Activate a fault plan for the enclosed region; yields the plan so
    tests can assert on `plan.fired` afterwards."""
    plan = FaultPlan(faults)
    _PLANS.append(plan)
    try:
        yield plan
    finally:
        _PLANS.remove(plan)


def tap(x, point: str | None = None):
    """Fault-injection tap.  Identity when no plan is active (the hot-path
    cost is one list truthiness check).  The site key is `point` if given,
    else the innermost active tracing scope."""
    if not _PLANS:
        return x
    tag = point or tracing.current_scope() or "<top>"
    for plan in _PLANS:
        x = plan.corrupt(x, tag)
    return x
