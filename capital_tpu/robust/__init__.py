"""Breakdown detection, shifted-CholeskyQR recovery, and fault injection.

See docs/ROBUSTNESS.md for the full story: detection semantics
(robust/detect), the shift formula and sCQR3 escalation (robust/recovery),
deterministic fault planting (robust/faultinject), and the sweep failure
containment that lives in bench/harness + autotune/sweep.
"""

from capital_tpu.robust import detect, faultinject, recovery, refine
from capital_tpu.robust.config import CholEvent, RobustConfig, RobustInfo

__all__ = [
    "CholEvent",
    "RobustConfig",
    "RobustInfo",
    "detect",
    "faultinject",
    "recovery",
    "refine",
]
