"""Robustness configuration + the jit-friendly status pytree.

Kept dependency-light (jax only): ops/lapack.py and models/cholesky.py both
import from here, so this module must not import back into the algorithm
layers.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple


@dataclasses.dataclass(frozen=True)
class RobustConfig:
    """Breakdown detection + shifted-CholeskyQR recovery knobs.

    CholeskyQR2's gram squares the condition number, so the method silently
    NaN-fills past cond(A) ~ u^{-1/2} (CA-CQR2, arXiv:1710.08471 §2; unlike
    the unconditionally stable TSQR family, arXiv:0809.2407).  With a
    RobustConfig attached (CacqrConfig.robust / CholinvConfig.robust) every
    Cholesky site returns a LAPACK-`info`-style status, and qr.factor
    recovers in-graph via the shifted CholeskyQR of Fukaya et al.:

        sigma = shift_c * u * (m*n + n*(n+1)) * tr(G)

    re-factoring G + sigma*I bounds cond(A * R^-1) regardless of cond(A),
    and the following sweep(s) restore orthogonality (sCQR3 escalation when
    the gate still exceeds `ortho_tol`).  The healthy path pays only the
    cheap n x n status reductions.

    shift_c: the constant c in the shift formula (11 in the sCQR analysis).
    ortho_tol: escalation gate on ||I - Q^T Q||_F / sqrt(n); None derives
        100 * n * u at the factor's compute dtype.
    recover: False = detect only (status reported, no shifted re-factor).
    escalate: False = never run the third (sCQR3) sweep.
    """

    shift_c: float = 11.0
    ortho_tol: float | None = None
    recover: bool = True
    escalate: bool = True
    #: run the blocked Householder TSQR (ops/tsqr.py, arXiv:0809.2407) as a
    #: final in-graph escalation when the sCQR3 gate STILL fails — the
    #: unconditionally stable refactorization that retires the info=n+2
    #: dead end for matrices it can handle at the escalation compute dtype
    #: (always-f64 where x64 is live).  Off by default: the documented
    #: sentinel contract of the plain ladder is a measured envelope other
    #: callers branch on; TSQR is an opt-in rung above it.
    tsqr: bool = False


class RobustInfo(NamedTuple):
    """Aggregated robust status of one qr.factor call (a pytree of scalars,
    jit/vmap-safe).  `info` follows the LAPACK potrf convention per site
    (see robust/detect.factor_info) aggregated by max AFTER recovery: 0
    means every factor in the pipeline is clean post-recovery."""

    info: object  # int32: max residual factor_info after recovery (0 = ok)
    breakdown: object  # int32: chol sites whose unshifted factor broke
    shifted: object  # int32: sites re-factored with the gram shift
    sigma: object  # float32: largest shift applied (0.0 on the healthy path)
    escalated: object  # int32: 1 = sCQR3 third sweep ran; 2 = TSQR rung ran
    ortho: object  # float32: escalation gate value; -1.0 when not computed
    # WHICH gate a nonzero `info` came from, so escalation routing can
    # distinguish them (GATE_NONE/GATE_ORTHO/GATE_RESIDUAL below): 1 means
    # the orthogonality gate ||I - QᵀQ||_F/sqrt(n) still exceeded tolerance
    # after the ladder (the TSQR-recoverable case), 2 means a residual
    # factor status survived recovery (non-finite/indefinite input — no
    # amount of re-factorization helps).  Defaulted so pre-existing
    # keyword-style constructions stay valid.
    gate: object = 0  # int32


#: RobustInfo.gate vocabulary.
GATE_NONE = 0
GATE_ORTHO = 1  # orthogonality gate failed (escalate via TSQR)
GATE_RESIDUAL = 2  # residual factor status nonzero (operand is bad)


class CholEvent(NamedTuple):
    """Per-site record from robust/recovery.guarded_chol."""

    info: object  # int32 status of the unshifted factor
    sigma: object  # shift actually applied (0 when the factor was healthy)
    info_after: object  # int32 status of the returned (possibly shifted) factor
