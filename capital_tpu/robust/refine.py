"""Mixed-precision iterative refinement: f64-grade answers at bf16/f32
factor throughput.

Single-chip dense throughput is saturated (PERF.md round 13), so the next
hot-path win does the O(n³) work in a CHEAPER precision and buys the
accuracy back with O(n²) sweeps: factor once at a low dtype, then iterate

    r = B − A·X          (residual, HIGH precision — IR::residual)
    d = solve(factor, r) (correction against the resident factor — IR::correct)
    X = X + d

Classic Wilkinson iterative refinement: each sweep contracts the error by
~cond(A)·u_factor, so whenever cond(A) is inside the factor dtype's
envelope a handful of sweeps reach the CORRECTION dtype's backward error —
the f32-factor + f64-correction combo lands f64-grade residuals at f32
factor cost (the `make bench-refine` gate).  The cond≈2e4 point where f32
sCQR3 stalls (docs/ROBUSTNESS.md) is comfortably inside this envelope:
contraction per sweep there is ~2e-3.

Everything is jit-friendly: the sweep loop is a `lax.while_loop` with an
IN-PROGRAM convergence test (per-problem normwise backward error
``‖r‖ / (‖A‖·‖X‖ + ‖B‖)`` against a dtype-derived tolerance), a fixed
iteration cap, and a progress guard — a problem whose error stops halving
freezes immediately, so divergence (cond beyond the factor envelope, or a
broken factor) costs at most one wasted sweep and comes back LOUD as
``RefineInfo.converged == 0`` with the measured final error.  All dtype
resolution is static (trace-time), so serve's zero-recompile invariant
holds; per-problem iteration counts come back as arrays for the stats
layer (serve/stats.Collector `refine` block).

Three flagship drivers, all batched (leading batch axis, the serve bucket
layout):

* ``posv`` — dense SPD; factor rides the PR 6 batched-grid potrf behind
  the dispatch-gate resolver, corrections are two triangular sweeps
  against the VMEM-resident-convention factor.
* ``lstsq`` — tall-skinny least squares via the CQR seam: the gram
  Cholesky R (= A's R factor) plus SEMI-NORMAL-EQUATION corrections
  (Björck): d = R⁻¹R⁻ᵀ·Aᵀr.
* ``posv_blocktri`` — the chain factors once (or reuses a RESIDENT factor
  from PR 12's residency cache via ``factor=``) and each correction sweep
  is the O(n·b²) block-bidiagonal substitution, not a refactor.

The serve tier vocabulary (``accuracy_tier`` ∈ fast/balanced/guaranteed)
resolves here (`plan`): balanced keeps today's program byte-identical,
fast downgrades the factor dtype one notch without refinement (the cheap
tier under overload, ROADMAP item 3), guaranteed pairs a low factor dtype
with an upgraded correction dtype and a sweep cap.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from capital_tpu.utils import tracing

TIERS = ("fast", "balanced", "guaranteed")

#: Sweep cap of the guaranteed tier: IR inside the envelope converges in
#: 2-4 sweeps (contraction ~cond·u_factor per sweep); 8 leaves margin for
#: near-envelope cond without letting a divergent problem spin.
DEFAULT_MAX_ITERS = 8


class RefineInfo(NamedTuple):
    """Per-problem refinement outcome (a pytree of (batch,) arrays,
    jit/vmap-safe — rides the executor's extras slot between X and the
    trailing info, so every request lands with its own counts)."""

    iters: object  # int32: correction sweeps executed
    converged: object  # int32: 1 = backward error met tolerance
    resid: object  # float32: final normwise backward-error estimate


class TierPlan(NamedTuple):
    """Static resolution of one accuracy tier at one request dtype."""

    factor_dtype: object
    correction_dtype: object
    max_iters: int  # 0 = no refinement (the factor answer ships as-is)


def _down1(dtype):
    """One notch down the factor ladder: f64→f32, f32→bf16, bf16 floors."""
    dt = jnp.dtype(dtype)
    if dt == jnp.float64:
        return jnp.dtype(jnp.float32)
    return jnp.dtype(jnp.bfloat16)


def _up(dtype):
    """One notch up for corrections: bf16→f32, f32→f64 (where x64 is
    live — canonicalize_dtype reports what the runtime represents, so the
    resolution stays static AND honest on x64-disabled rigs), f64 ceils."""
    dt = jnp.dtype(dtype)
    if dt.itemsize < 4:
        return jnp.dtype(jnp.float32)
    return jnp.dtype(jax.dtypes.canonicalize_dtype(jnp.float64))


def plan(tier: str, dtype) -> TierPlan:
    """Resolve accuracy_tier → (factor dtype, correction dtype, sweep cap)
    for one request dtype.  Pure static function of (tier, dtype): the
    serve engine hashes the tier into the bucket key and executable
    cfg-hash, and every downstream dispatch reads only these dtypes — the
    zero-recompile invariant survives the precision knob.

    * balanced — today's program, byte-identical (no refinement).
    * fast — factor one notch down, no refinement: the cheap tier the
      SLO-aware scheduler sheds to under overload.
    * guaranteed — low factor + upgraded correction + sweep cap: f64
      requests factor in f32 and correct in f64 (the bench flagship),
      f32 factors in f32 and corrects in f64, bf16 factors in bf16 and
      corrects in f32.
    """
    dt = jnp.dtype(dtype)
    if tier not in TIERS:
        raise ValueError(f"accuracy_tier must be one of {TIERS}, got {tier!r}")
    if tier == "balanced":
        return TierPlan(dt, dt, 0)
    if tier == "fast":
        fd = _down1(dt)
        return TierPlan(fd, fd, 0)
    fd = jnp.dtype(jnp.float32) if dt == jnp.float64 else dt
    return TierPlan(fd, _up(dt), DEFAULT_MAX_ITERS)


def tolerance(n: int, correction_dtype) -> float:
    """Default convergence tolerance on the normwise backward error:
    0.5·sqrt(n)·u at the CORRECTION dtype.  The measured floor of the
    refined error is ~0.02·sqrt(n)·u (residual rounding is a random walk
    over the n·k contraction terms, and the ‖A‖·‖X‖ scale sits in the
    denominator), so this demands a genuinely correction-dtype-grade
    answer — the bench gate compares against a straight f64 factor and
    this tolerance lands within ~1x of it — while keeping ~25x headroom
    above the floor so the progress guard doesn't fire loud false
    failures at the last sweep."""
    return 0.5 * float(n) ** 0.5 * float(
        jnp.finfo(jnp.dtype(correction_dtype)).eps
    )


def _pnorm(X):
    """Per-problem Frobenius norm of a (batch, ...) stack, as f32."""
    flat = X.reshape(X.shape[0], -1)
    return jnp.sqrt(jnp.sum(jnp.square(flat), axis=-1)).astype(jnp.float32)


def _refine_loop(X0, resid_fn, err_fn, correct_fn, *, max_iters: int,
                 tol: float):
    """The shared sweep loop.  resid_fn(X) -> r at the correction dtype;
    err_fn(X, r) -> per-problem (batch,) f32 backward error; correct_fn(r)
    -> d.  Per-problem freezing: a problem stops the moment it converges,
    stops improving (error not halved — divergence comes back loud, not
    spun on), or hits the cap; the while_loop runs until every problem
    froze.  Returns (X, RefineInfo)."""
    batch = X0.shape[0]
    r0 = resid_fn(X0)
    e0 = err_fn(X0, r0)

    def _active(e, prev, it):
        return (e > tol) & (e < 0.5 * prev) & (it < max_iters)

    def cond(carry):
        _, _, e, prev, it = carry
        return jnp.any(_active(e, prev, it))

    def body(carry):
        X, r, e, prev, it = carry
        act = _active(e, prev, it)
        d = correct_fn(r)
        mask = act.reshape((batch,) + (1,) * (X.ndim - 1))
        Xn = X + jnp.where(mask, d, jnp.zeros_like(d))
        rn = resid_fn(Xn)
        en = err_fn(Xn, rn)
        return (
            Xn,
            jnp.where(mask, rn, r),
            jnp.where(act, en, e),
            jnp.where(act, e, prev),
            it + act.astype(jnp.int32),
        )

    X, _, e, _, it = lax.while_loop(
        cond, body,
        (X0, r0, e0, jnp.full((batch,), jnp.inf, jnp.float32),
         jnp.zeros((batch,), jnp.int32)),
    )
    info = RefineInfo(
        iters=it, converged=(e <= tol).astype(jnp.int32), resid=e
    )
    return X, info


# --------------------------------------------------------------------------
# factor/solve routing: the PR 6 dispatch gate, at the FACTOR dtype
# --------------------------------------------------------------------------


def _potrf_route(Af, k: int, impl: str, precision, interpret):
    """Batched potrf at the factor dtype behind the batched_small
    dispatch-gate resolver: (R, info) with R upper.  Static resolution —
    f64 factors always ride the vmap/LAPACK seam (dtype_capable)."""
    from capital_tpu.ops import batched_small, lapack

    batch, n, _ = Af.shape
    pick = impl
    if impl == "auto":
        pick = batched_small.default_impl(
            "posv", Af.shape, (batch, n, k), Af.dtype, interpret=interpret
        )
    elif impl in ("pallas", "pallas_split") and not batched_small.dtype_capable(
        Af.dtype
    ):
        pick = "vmap"
    if pick in ("pallas", "pallas_split"):
        R, info = batched_small.potrf(
            Af, uplo="U", precision=precision, interpret=interpret
        )
        solve = lambda rr, bb: batched_small.potrs(
            rr, bb, uplo="U", precision=precision, interpret=interpret
        )
        return R, info, solve
    with tracing.scope("serve::solve"):
        R, info = jax.vmap(
            lambda a: lapack.potrf(a, uplo="U", with_info=True)
        )(Af)
    return R, info, lambda rr, bb: lapack.potrs(rr, bb, uplo="U")


# --------------------------------------------------------------------------
# the three flagship drivers
# --------------------------------------------------------------------------


def posv(A, B, *, factor_dtype, correction_dtype,
         max_iters: int = DEFAULT_MAX_ITERS, tol: float | None = None,
         impl: str = "auto", precision: str | None = "highest",
         interpret: bool | None = None):
    """Refined batched SPD solve: (batch, n, n) × (batch, n, k) →
    (X, info, RefineInfo) with X at B.dtype, info the (batch,) int32
    factor status (potrf convention — refinement cannot repair a broken
    factor, it reports it)."""
    batch, n, _ = A.shape
    k = B.shape[-1]
    fd, cd = jnp.dtype(factor_dtype), jnp.dtype(correction_dtype)
    if tol is None:
        tol = tolerance(n, cd)

    R, info, solve = _potrf_route(A.astype(fd), k, impl, precision, interpret)
    Ac, Bc = A.astype(cd), B.astype(cd)
    anorm = _pnorm(Ac)
    bnorm = _pnorm(Bc)
    tiny = jnp.float32(jnp.finfo(jnp.float32).tiny)

    with tracing.scope("IR::residual"):
        tracing.emit(flops=batch * 2.0 * n * n * k)
    with tracing.scope("IR::correct"):
        tracing.emit(
            flops=batch * (tracing.refine_sweep_flops(n, k)
                           - 2.0 * n * n * k)
        )

    def resid(X):
        with tracing.scope("IR::residual"):
            return Bc - jnp.matmul(Ac, X, precision=precision)

    def err(X, r):
        return _pnorm(r) / (anorm * _pnorm(X) + bnorm + tiny)

    def correct(r):
        with tracing.scope("IR::correct"):
            return solve(R, r.astype(fd)).astype(cd)

    X0 = correct(Bc - jnp.zeros_like(Bc))  # first solve IS a correction of 0
    X, rinfo = _refine_loop(X0, resid, err, correct,
                            max_iters=max_iters, tol=tol)
    return X.astype(B.dtype), info, rinfo


def lstsq(A, B, *, factor_dtype, correction_dtype,
          max_iters: int = DEFAULT_MAX_ITERS, tol: float | None = None,
          impl: str = "auto", precision: str | None = "highest",
          interpret: bool | None = None):
    """Refined batched least squares via the CQR seam + semi-normal
    corrections: the gram Cholesky R (A's triangular factor up to signs)
    is computed ONCE at the factor dtype, then every sweep solves
    d = R⁻¹R⁻ᵀ·Aᵀr at factor cost O(mnk + n²k) — no re-factorization.
    Convergence is measured on the NORMAL-equation residual Aᵀ(B − AX)
    (the quantity lstsq actually zeroes; the plain residual floors at the
    data's distance from range(A))."""
    batch, m, n = A.shape
    k = B.shape[-1]
    fd, cd = jnp.dtype(factor_dtype), jnp.dtype(correction_dtype)
    if tol is None:
        tol = tolerance(n, cd)

    from capital_tpu.ops import batched_small  # noqa: F401  (route below)

    Af = A.astype(fd)
    with tracing.scope("CQR::gram"):
        G = jnp.matmul(jnp.swapaxes(Af, -1, -2), Af, precision=precision)
    R, info, solve = _potrf_route(G, k, impl, precision, interpret)

    Ac, Bc = A.astype(cd), B.astype(cd)
    At = jnp.swapaxes(Ac, -1, -2)
    C0 = jnp.matmul(At, Bc, precision=precision)  # AᵀB at corr dtype
    anorm2 = jnp.square(_pnorm(Ac))
    cnorm = _pnorm(C0)
    tiny = jnp.float32(jnp.finfo(jnp.float32).tiny)

    with tracing.scope("IR::residual"):
        tracing.emit(flops=batch * 4.0 * m * n * k)
    with tracing.scope("IR::correct"):
        tracing.emit(
            flops=batch * (tracing.refine_lstsq_sweep_flops(m, n, k)
                           - 4.0 * m * n * k)
        )

    def resid(X):
        # the semi-normal residual g = Aᵀ(B − A·X), at the corr dtype
        with tracing.scope("IR::residual"):
            r = Bc - jnp.matmul(Ac, X, precision=precision)
            return jnp.matmul(At, r, precision=precision)

    def err(X, g):
        return _pnorm(g) / (anorm2 * _pnorm(X) + cnorm + tiny)

    def correct(g):
        with tracing.scope("IR::correct"):
            return solve(R, g.astype(fd)).astype(cd)

    X0 = correct(C0)
    X, rinfo = _refine_loop(X0, resid, err, correct,
                            max_iters=max_iters, tol=tol)
    return X.astype(B.dtype), info, rinfo


def _chain_matvec(D, Cz, X, precision):
    """y = A·X for the block-tridiagonal chain (D diagonal blocks, Cz
    sub-diagonal blocks with block 0 ZEROED — the blocktri packing
    convention): y_i = D_i·X_i + C_i·X_{i−1} + C_{i+1}ᵀ·X_{i+1}."""
    y = jnp.matmul(D, X, precision=precision)
    Xdown = jnp.concatenate([jnp.zeros_like(X[:, :1]), X[:, :-1]], axis=1)
    y = y + jnp.matmul(Cz, Xdown, precision=precision)
    CzT = jnp.swapaxes(Cz, -1, -2)
    CzTup = jnp.concatenate(
        [CzT[:, 1:], jnp.zeros_like(CzT[:, :1])], axis=1
    )
    Xup = jnp.concatenate([X[:, 1:], jnp.zeros_like(X[:, :1])], axis=1)
    return y + jnp.matmul(CzTup, Xup, precision=precision)


def posv_blocktri(D, C, B, *, factor_dtype, correction_dtype,
                  max_iters: int = DEFAULT_MAX_ITERS,
                  tol: float | None = None, impl: str = "auto",
                  precision: str | None = "highest",
                  interpret: bool | None = None, factor=None):
    """Refined block-tridiagonal SPD solve: the chain factors ONCE at the
    factor dtype (or reuses a RESIDENT (L, Wt) factor via ``factor=`` —
    the PR 12 residency-cache composition: refinement then never
    refactors at all) and every correction sweep is the O(n·b²)
    block-bidiagonal substitution (models/blocktri.solve, BT::solve).
    Shapes per models/blocktri: D, C (batch, nblocks, b, b), B (batch,
    nblocks, b, k)."""
    from capital_tpu.models import blocktri

    batch, nblocks, b, _ = D.shape
    k = B.shape[-1]
    n = nblocks * b
    fd, cd = jnp.dtype(factor_dtype), jnp.dtype(correction_dtype)
    if tol is None:
        tol = tolerance(n, cd)
    mapped = {"auto": "auto", "pallas": "pallas", "pallas_split": "pallas",
              "vmap": "xla", "xla": "xla"}[impl]

    if factor is None:
        L, Wt, info = blocktri.factor(
            D.astype(fd), C.astype(fd), precision=precision, impl=mapped,
            interpret=interpret,
        )
    else:
        L, Wt = factor
        info = jnp.zeros((batch,), jnp.int32)  # resident factors install clean

    Dc, Cc = D.astype(cd), C.astype(cd)
    # zero the (meaningless) first coupling block at the corr dtype too —
    # the factor path does this internally (blocktri._zero_first_coupling)
    Cz = jnp.concatenate([jnp.zeros_like(Cc[:, :1]), Cc[:, 1:]], axis=1)
    Bc = B.astype(cd)
    anorm = jnp.sqrt(
        jnp.square(_pnorm(Dc)) + 2.0 * jnp.square(_pnorm(Cz))
    )
    bnorm = _pnorm(Bc)
    tiny = jnp.float32(jnp.finfo(jnp.float32).tiny)

    with tracing.scope("IR::residual"):
        tracing.emit(flops=batch * nblocks * (2.0 * b * b * k * 3.0))
    with tracing.scope("IR::correct"):
        tracing.emit(
            flops=batch * 2.0 * tracing.blocktri_solve_flops(nblocks, b, k)
        )

    def resid(X):
        with tracing.scope("IR::residual"):
            return Bc - _chain_matvec(Dc, Cz, X, precision)

    def err(X, r):
        return _pnorm(r) / (anorm * _pnorm(X) + bnorm + tiny)

    def correct(r):
        with tracing.scope("IR::correct"):
            d = blocktri.solve(L, Wt, r.astype(fd), precision=precision,
                               impl=mapped, interpret=interpret)
            return d.astype(cd)

    X0 = correct(Bc)
    X, rinfo = _refine_loop(X0, resid, err, correct,
                            max_iters=max_iters, tol=tol)
    return X.astype(B.dtype), info, rinfo
