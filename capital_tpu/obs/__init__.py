"""Unified observability layer: XLA program audits + the run ledger.

One queryable record format over what used to be four disconnected views:

* the analytic alpha-beta cost model (utils/tracing.Recorder),
* compiled-program facts (collective inventory, flops/bytes, peak HBM —
  obs/xla_audit.ProgramAudit),
* measured wall time (bench/harness JSON lines),
* residual gates (bench/drivers --validate).

`xla_audit` promotes the HLO collective inventory out of
tests/test_collective_audit.py into a library and adds the
model-vs-compiled drift classifier; `ledger` defines the versioned JSONL
record every bench/autotune run can append (--ledger PATH) and the diff
engine that flags regressions between two ledgers.  The CLI lives in
``python -m capital_tpu.obs`` (audit / diff subcommands); the schema and
tolerance policy are documented in docs/OBSERVABILITY.md.
"""

__all__ = ["ledger", "spans", "xla_audit"]

# PEP 562 lazy submodule exports (same pattern as capital_tpu/serve):
# xla_audit imports jax at module level, and the host-only serve dispatch
# plane (router.py) imports `capital_tpu.obs.spans` — an eager import
# here would drag jax into every router/replica process and break the
# round-10 host-only contract the lint host-only-dispatch rule pins.


def __getattr__(name: str):
    if name not in __all__:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    import importlib

    value = importlib.import_module(f"{__name__}.{name}")
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
