"""Unified observability layer: XLA program audits + the run ledger.

One queryable record format over what used to be four disconnected views:

* the analytic alpha-beta cost model (utils/tracing.Recorder),
* compiled-program facts (collective inventory, flops/bytes, peak HBM —
  obs/xla_audit.ProgramAudit),
* measured wall time (bench/harness JSON lines),
* residual gates (bench/drivers --validate).

`xla_audit` promotes the HLO collective inventory out of
tests/test_collective_audit.py into a library and adds the
model-vs-compiled drift classifier; `ledger` defines the versioned JSONL
record every bench/autotune run can append (--ledger PATH) and the diff
engine that flags regressions between two ledgers.  The CLI lives in
``python -m capital_tpu.obs`` (audit / diff subcommands); the schema and
tolerance policy are documented in docs/OBSERVABILITY.md.
"""

from capital_tpu.obs import ledger, xla_audit  # noqa: F401

__all__ = ["ledger", "xla_audit"]
