"""The versioned JSONL run ledger — one record per run, queryable forever.

Every benchmark, autotune trial, and audit can append ONE structured record
here (the ``--ledger PATH`` opt-in), carrying together what used to live in
four disconnected places:

* a **manifest** — schema_version, device kind, platform, mesh/grid, dtype,
  config dataclass dump, jax version — enough to refuse apples-to-oranges
  comparisons;
* the Recorder's per-phase **model costs** (flops / comm bytes /
  collectives, the alpha-beta decomposition);
* the compiled-program **audit** (collective inventory, flops, peak HBM —
  obs/xla_audit.ProgramAudit) and its **drift** report;
* **measured** wall-clock results (the harness JSON line: TFLOP/s,
  achieved-vs-target fraction, seconds);
* **residuals** when ``--validate`` ran.

`diff(a, b)` compares two ledgers record-by-record (matched on a stable
config key) and returns the regressions: measured-throughput drops,
collective-count increases, and peak-HBM growth beyond tolerance.  Records
with mismatched schema_version or device kind raise `LedgerIncompatible`
rather than producing a silent garbage comparison.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
from typing import Any, Iterable, Optional

import jax

from capital_tpu.utils import tracing

#: Bump on any breaking change to the record layout.  diff() refuses to
#: compare records of different schema versions.
SCHEMA_VERSION = 1


class LedgerIncompatible(RuntimeError):
    """Two ledger records cannot be meaningfully compared (schema_version or
    device-kind mismatch)."""


# --------------------------------------------------------------------------
# record construction
# --------------------------------------------------------------------------


def _jsonable(obj: Any) -> Any:
    """Best-effort JSON coercion for config dataclass dumps: enums by name,
    dtypes/callables/devices by str."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            "__class__": type(obj).__name__,
            **{
                f.name: _jsonable(getattr(obj, f.name))
                for f in dataclasses.fields(obj)
            },
        }
    if isinstance(obj, enum.Enum):
        return obj.name
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    return str(obj)


def manifest(
    grid=None, dtype=None, config=None, **extra
) -> dict:
    """The run manifest: everything needed to decide whether two records
    are comparable, plus the config that produced the run."""
    dev = jax.devices()[0]
    man = {
        "schema_version": SCHEMA_VERSION,
        "jax_version": jax.__version__,
        "platform": jax.default_backend(),
        "device": getattr(dev, "device_kind", dev.platform),
        "num_devices": len(jax.devices()),
        "grid": repr(grid) if grid is not None else None,
        "dtype": str(jax.numpy.dtype(dtype)) if dtype is not None else None,
        "config": _jsonable(config) if config is not None else None,
    }
    if grid is not None:
        man["grid_shape"] = [grid.dx, grid.dy, grid.c]
    man.update(_jsonable(extra))
    return man


def model_costs(
    rec: tracing.Recorder,
    spec: Optional[tracing.DeviceSpec] = None,
    dtype=None,
) -> dict:
    """The Recorder's decomposition as a JSON block: per-phase raw costs
    plus the alpha-beta second estimates when a dtype is given."""
    out: dict = {
        "phases": {
            tag: dataclasses.asdict(s) for tag, s in rec.stats.items()
        },
        "totals": dataclasses.asdict(rec.total()),
    }
    if dtype is not None:
        est = rec.estimate_seconds(spec or tracing.device_spec(), dtype)
        out["estimate_s"] = {
            tag: {"comp_s": c, "comm_s": m} for tag, (c, m) in est.items()
        }
    return out


def record(
    kind: str,
    man: dict,
    *,
    model: Optional[dict] = None,
    audit: Optional[dict] = None,
    drift: Optional[dict] = None,
    measured: Optional[dict] = None,
    residuals: Optional[dict] = None,
    **extra,
) -> dict:
    """Assemble one ledger record.  `man` comes from manifest(); `model`
    from model_costs(); `audit`/`drift` from ProgramAudit.asdict() /
    DriftReport.asdict(); `measured` is the harness.report JSON line;
    `residuals` maps gate name -> value."""
    rec = {
        "record": "capital_tpu.ledger",
        "kind": kind,
        "manifest": man,
        "model": model,
        "audit": audit,
        "drift": drift,
        "measured": measured,
        "residuals": residuals,
    }
    rec.update(_jsonable(extra))
    return rec


def append(path: str, rec: dict) -> None:
    """Append one record as a JSON line (creating parent dirs)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


def read(path: str) -> list[dict]:
    """Load every record of a JSONL ledger (blank lines skipped)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# --------------------------------------------------------------------------
# diff
# --------------------------------------------------------------------------


@dataclasses.dataclass
class Regression:
    """One out-of-tolerance change between matched records."""

    key: str
    field: str
    a: float
    b: float
    note: str

    def line(self) -> str:
        return f"REGRESSION {self.key} {self.field}: {self.a} -> {self.b} ({self.note})"


def _key(rec: dict) -> str:
    """Stable identity of what a record measured: kind + problem shape +
    topology + dtype + config id.  Two runs sharing a key are comparable
    trials of the same configuration."""
    man = rec.get("manifest") or {}
    meas = rec.get("measured") or {}
    cfg = man.get("config") or {}
    parts = [
        rec.get("kind", "?"),
        meas.get("metric") or "",
        man.get("grid") or "",
        man.get("dtype") or "",
        str(cfg.get("__class__", "")),
        str(man.get("config_id", "")),
    ]
    for dim in ("n", "m", "k", "nrhs", "variant", "bc", "mode"):
        if dim in meas:
            parts.append(f"{dim}={meas[dim]}")
        elif dim in man:
            parts.append(f"{dim}={man[dim]}")
    return " ".join(p for p in parts if p)


def _check_comparable(a: dict, b: dict) -> None:
    ma, mb = a.get("manifest") or {}, b.get("manifest") or {}
    # legacy bare harness lines carry schema_version at top level
    sa = ma.get("schema_version", a.get("schema_version"))
    sb = mb.get("schema_version", b.get("schema_version"))
    if sa != sb:
        raise LedgerIncompatible(
            f"schema_version mismatch: {sa!r} vs {sb!r} — re-run the older "
            "side with the current tooling rather than comparing across "
            "schema changes"
        )
    da = ma.get("device", a.get("device"))
    db = mb.get("device", b.get("device"))
    if da != db:
        raise LedgerIncompatible(
            f"device-kind mismatch: {da!r} vs {db!r} — cross-device "
            "comparisons are not regressions; use separate ledgers"
        )


#: request_stats schema (serve/stats.Collector.snapshot): required keys and
#: the nested latency/cache shapes.  diff() VALIDATES these instead of
#: metric-comparing them — a served mix's latency profile is workload, but a
#: malformed record means the producer and the tooling have drifted apart.
_REQ_STATS_COUNTS = ("requests", "ok", "flagged", "failed",
                     "queue_depth_max", "batches")
_REQ_STATS_PCTS = ("p50", "p95", "p99")
_REQ_STATS_CACHE = ("hits", "misses", "warmup_compiles", "hit_rate")
#: per-op request counters (serve/stats.Collector.ops): every key must be a
#: serve op this tooling knows (batching.OPS, inlined so obs never imports
#: serve) — an unknown key means the producer and the tooling drifted apart.
_REQ_STATS_OPS = ("posv", "lstsq", "inv", "posv_blocktri",
                  "chol_update", "chol_downdate", "posv_cached",
                  "blocktri_extend", "posv_arrowhead",
                  "session_open", "session_append", "session_solve",
                  "session_contract", "session_close")
#: factor_cache counter block (serve/factorcache.FactorCache.stats):
#: attached to request_stats only by engines that served factor-token
#: traffic — records without it stay valid unchanged.
_REQ_STATS_FACTOR_COUNTS = ("hits", "misses", "evictions", "installs",
                            "released", "downdate_degrades", "entries",
                            "bytes", "budget_bytes")


def validate_request_stats(block) -> list[str]:
    """Schema problems of one request_stats block ([] = valid).  Checked by
    diff() on every record carrying the block and by ``obs serve-report``;
    kept as a problem list (not an exception) so the CLI can print all of
    them at once."""
    if not isinstance(block, dict):
        return [f"request_stats is {type(block).__name__}, expected object"]
    probs = []
    if block.get("schema_version") != SCHEMA_VERSION:
        probs.append(
            f"schema_version {block.get('schema_version')!r} != "
            f"{SCHEMA_VERSION}"
        )
    for key in _REQ_STATS_COUNTS:
        v = block.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            probs.append(f"{key} must be a non-negative int, got {v!r}")
    lat = block.get("latency_ms")
    if not isinstance(lat, dict):
        probs.append(f"latency_ms must be an object, got {lat!r}")
    else:
        for p in _REQ_STATS_PCTS:
            if not isinstance(lat.get(p), (int, float)):
                probs.append(f"latency_ms.{p} missing or non-numeric")
    cache = block.get("cache")
    if not isinstance(cache, dict):
        probs.append(f"cache must be an object, got {cache!r}")
    else:
        for c in _REQ_STATS_CACHE:
            if not isinstance(cache.get(c), (int, float)):
                probs.append(f"cache.{c} missing or non-numeric")
        hr = cache.get("hit_rate")
        if isinstance(hr, (int, float)) and not 0.0 <= hr <= 1.0:
            probs.append(f"cache.hit_rate {hr!r} outside [0, 1]")
    occ = block.get("batch_occupancy_mean")
    if not isinstance(occ, (int, float)) or not 0.0 <= occ <= 1.0:
        probs.append(
            f"batch_occupancy_mean must be in [0, 1], got {occ!r}"
        )
    # optional per-op counters (Collector.ops, present since the op mix
    # grew past posv/lstsq): records that predate them stay valid unchanged
    if "ops" in block:
        ops = block["ops"]
        if not isinstance(ops, dict):
            probs.append(f"ops must be an object, got {ops!r}")
        else:
            for name, v in ops.items():
                if name not in _REQ_STATS_OPS:
                    probs.append(
                        f"ops key {name!r} is not a known serve op "
                        f"{_REQ_STATS_OPS}"
                    )
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(
                        f"ops[{name!r}] must be a non-negative int, got {v!r}"
                    )
    # optional posv_blocktri algorithm split (PR 13 — Collector
    # .blocktri_impls): which chain driver the compiled programs ran,
    # 'scan' vs 'partitioned'.  Absent without blocktri traffic; when
    # present, keys must come from that two-word vocabulary.
    if "blocktri_impls" in block:
        bti = block["blocktri_impls"]
        if not isinstance(bti, dict):
            probs.append(f"blocktri_impls must be an object, got {bti!r}")
        else:
            for name, v in bti.items():
                if name not in ("scan", "partitioned"):
                    probs.append(
                        f"blocktri_impls key {name!r} is not a chain "
                        "algorithm ('scan', 'partitioned')"
                    )
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(
                        f"blocktri_impls[{name!r}] must be a non-negative "
                        f"int, got {v!r}"
                    )
    # optional percentile blocks, validated whenever present, same posture
    # as the rest of the block:
    #   latency_ms_small — small-N split (serve small_n_impl pallas
    #     routes); absent on engines that never served a small bucket;
    #   queue_wait_ms / device_ms — the continuous scheduler's latency
    #     split (executor timing contract, PR 7); absent on records from
    #     engines that never dispatched, and on pre-split records, which
    #     stay valid unchanged.
    for name in ("latency_ms_small", "queue_wait_ms", "device_ms"):
        if name not in block:
            continue
        lat_o = block[name]
        if not isinstance(lat_o, dict):
            probs.append(f"{name} must be an object, got {lat_o!r}")
            continue
        for p in _REQ_STATS_PCTS:
            if not isinstance(lat_o.get(p), (int, float)):
                probs.append(f"{name}.{p} missing or non-numeric")
    if "requests_small" in block:
        rs = block["requests_small"]
        if not isinstance(rs, int) or isinstance(rs, bool) or rs < 0:
            probs.append(
                f"requests_small must be a non-negative int, got {rs!r}"
            )
    # optional factor-residency counters (serve/factorcache.py, PR 12):
    # present only on engines that served factor-token traffic
    # (stats.snapshot attaches the block when lookups or installs
    # happened); its gate is ``obs serve-report --min-residency-hit-rate``.
    if "factor_cache" in block:
        fc = block["factor_cache"]
        if not isinstance(fc, dict):
            probs.append(f"factor_cache must be an object, got {fc!r}")
        else:
            for key in _REQ_STATS_FACTOR_COUNTS:
                v = fc.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(
                        f"factor_cache.{key} must be a non-negative int, "
                        f"got {v!r}"
                    )
            hr = fc.get("hit_rate")
            if not isinstance(hr, (int, float)) or not 0.0 <= hr <= 1.0:
                probs.append(
                    f"factor_cache.hit_rate must be in [0, 1], got {hr!r}"
                )
            h, m = fc.get("hits"), fc.get("misses")
            if (isinstance(h, int) and isinstance(m, int)
                    and isinstance(hr, (int, float)) and h + m > 0
                    and abs(hr - h / (h + m)) > 1e-6):
                probs.append(
                    f"factor_cache.hit_rate {hr!r} inconsistent with "
                    f"hits={h} misses={m} (expected {h / (h + m):.6f})"
                )
            # optional per-entry byte map + eviction-age histogram
            # (PR 19 session eviction-pressure view): additive keys —
            # pre-PR-19 records (and merged snapshots, which fold only
            # the scalar counters) stay valid without them.
            if "entry_bytes" in fc:
                eb = fc["entry_bytes"]
                if not isinstance(eb, dict):
                    probs.append(
                        f"factor_cache.entry_bytes must be an object, "
                        f"got {eb!r}")
                else:
                    for t, v in eb.items():
                        if (not isinstance(v, int) or isinstance(v, bool)
                                or v < 0):
                            probs.append(
                                f"factor_cache.entry_bytes[{t!r}] must be "
                                f"a non-negative int, got {v!r}")
                    ent, by = fc.get("entries"), fc.get("bytes")
                    if isinstance(ent, int) and len(eb) != ent:
                        probs.append(
                            f"factor_cache.entry_bytes has {len(eb)} "
                            f"entries but entries={ent}")
                    if (isinstance(by, int) and eb
                            and all(isinstance(v, int) for v in eb.values())
                            and sum(eb.values()) != by):
                        probs.append(
                            f"factor_cache.entry_bytes sums to "
                            f"{sum(eb.values())} but bytes={by}")
            if "eviction_age_hist" in fc:
                eh = fc["eviction_age_hist"]
                if not isinstance(eh, dict):
                    probs.append(
                        f"factor_cache.eviction_age_hist must be an "
                        f"object, got {eh!r}")
                else:
                    for bkt, v in eh.items():
                        if not (isinstance(bkt, str) and bkt.isdigit()):
                            probs.append(
                                f"factor_cache.eviction_age_hist key "
                                f"{bkt!r} is not a stringified age bucket")
                        if (not isinstance(v, int) or isinstance(v, bool)
                                or v < 0):
                            probs.append(
                                f"factor_cache.eviction_age_hist[{bkt!r}] "
                                f"must be a non-negative int, got {v!r}")
                    ev = fc.get("evictions")
                    if (isinstance(ev, int) and eh
                            and all(isinstance(v, int) for v in eh.values())
                            and sum(eh.values()) != ev):
                        probs.append(
                            f"factor_cache.eviction_age_hist sums to "
                            f"{sum(eh.values())} but evictions={ev}")
    # optional guaranteed-tier refinement telemetry (PR 14 —
    # Collector.note_refine): measured sweep counts and the worst landed
    # backward error.  Absent without accuracy_tier='guaranteed' traffic;
    # its gates are ``obs serve-report --max-refine-iters`` /
    # ``--min-converged-frac``.
    if "refine" in block:
        rf = block["refine"]
        if not isinstance(rf, dict):
            probs.append(f"refine must be an object, got {rf!r}")
        else:
            for key in ("requests", "converged", "nonconverged",
                        "iters_max"):
                v = rf.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(
                        f"refine.{key} must be a non-negative int, got {v!r}"
                    )
            cf = rf.get("converged_frac")
            if not isinstance(cf, (int, float)) or not 0.0 <= cf <= 1.0:
                probs.append(
                    f"refine.converged_frac must be in [0, 1], got {cf!r}"
                )
            it = rf.get("iters")
            if not isinstance(it, dict):
                probs.append(f"refine.iters must be an object, got {it!r}")
            else:
                for p in _REQ_STATS_PCTS:
                    if not isinstance(it.get(p), (int, float)):
                        probs.append(f"refine.iters.{p} missing or "
                                     "non-numeric")
            rm = rf.get("resid_max")
            if not isinstance(rm, (int, float)) or isinstance(rm, bool) \
                    or rm < 0:
                probs.append(
                    f"refine.resid_max must be a non-negative number, "
                    f"got {rm!r}"
                )
    # multi-replica tags (serve/router.py, PR 9): a per-replica record
    # carries replica_id; the router's aggregate record carries replicas
    # (how many snapshots merged) and replica_ids.  Single-engine records
    # carry none of them and stay valid unchanged.
    if "replica_id" in block and not isinstance(block["replica_id"], str):
        probs.append(
            f"replica_id must be a string, got {block['replica_id']!r}"
        )
    if "replicas" in block:
        n = block["replicas"]
        if not isinstance(n, int) or isinstance(n, bool) or n < 1:
            probs.append(f"replicas must be a positive int, got {n!r}")
    if "replica_ids" in block:
        ids = block["replica_ids"]
        if (not isinstance(ids, list)
                or not all(isinstance(i, str) for i in ids)):
            probs.append(
                f"replica_ids must be a list of strings, got {ids!r}"
            )
    if "samples" in block:
        # raw latency populations (Collector.snapshot(samples=True)) are a
        # router-internal pooling vehicle; a ledger record carrying them
        # is a producer bug (unbounded growth), so flag rather than allow
        probs.append(
            "samples block present — raw populations are for in-memory "
            "aggregation (stats.merge_snapshots), strip before append"
        )
    return probs


#: lint_report blocks (capital_tpu.lint rules.Report.block) get the same
#: treatment as request_stats: structurally validated on every diff, never
#: metric-compared — a lint outcome is a property of the *source tree*, not
#: of a kernel's speed, and its gate lives in ``obs lint-report``.
_LINT_PASSES = ("program", "source", "concurrency")
_LINT_FAIL_ON = ("warn", "error")
_LINT_COUNT_KEYS = ("error", "warn", "info")
_LINT_FINDING_KEYS = ("rule", "severity", "target", "message", "fingerprint")


def validate_lint_report(block) -> list[str]:
    """Schema problems of one lint_report block ([] = valid).  Checked by
    diff() on every record carrying the block and by ``obs lint-report``;
    a problem list (not an exception) so the CLI can print all of them."""
    if not isinstance(block, dict):
        return [f"lint_report is {type(block).__name__}, expected object"]
    probs = []
    if block.get("schema_version") != SCHEMA_VERSION:
        probs.append(
            f"schema_version {block.get('schema_version')!r} != "
            f"{SCHEMA_VERSION}"
        )
    if block.get("pass") not in _LINT_PASSES:
        probs.append(
            f"pass must be one of {_LINT_PASSES}, got {block.get('pass')!r}"
        )
    if block.get("fail_on") not in _LINT_FAIL_ON:
        probs.append(
            f"fail_on must be one of {_LINT_FAIL_ON}, "
            f"got {block.get('fail_on')!r}"
        )
    if not isinstance(block.get("ok"), bool):
        probs.append(f"ok must be a bool, got {block.get('ok')!r}")
    counts = block.get("counts")
    if not isinstance(counts, dict):
        probs.append(f"counts must be an object, got {counts!r}")
    else:
        for key in _LINT_COUNT_KEYS:
            v = counts.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                probs.append(
                    f"counts.{key} must be a non-negative int, got {v!r}"
                )
    sup = block.get("suppressed")
    if not isinstance(sup, int) or isinstance(sup, bool) or sup < 0:
        probs.append(f"suppressed must be a non-negative int, got {sup!r}")
    findings = block.get("findings")
    if not isinstance(findings, list):
        probs.append(f"findings must be a list, got {findings!r}")
    else:
        for i, f in enumerate(findings):
            if not isinstance(f, dict):
                probs.append(f"findings[{i}] is not an object")
                continue
            for key in _LINT_FINDING_KEYS:
                if not isinstance(f.get(key), str) or not f.get(key):
                    probs.append(
                        f"findings[{i}].{key} missing or not a string"
                    )
    return probs


def validate_phase_seconds(measured) -> list[str]:
    """Schema problems of a measured block carrying phase attribution
    ([] = valid) — the bench:trace producer's ``phase_seconds`` /
    ``bubble_frac`` fields (bench/trace.phase_attribution).  Same posture
    as request_stats / lint_report: structurally validated on every diff
    whenever PRESENT, never required — records that predate the fields
    stay valid unchanged.  The per-phase split itself is workload shape,
    not a metric; its drift gate is measured.value (the attributed
    fraction), which diff() compares normally."""
    if not isinstance(measured, dict):
        return [f"measured is {type(measured).__name__}, expected object"]
    probs = []
    ps = measured.get("phase_seconds")
    if ps is not None:
        if not isinstance(ps, dict):
            probs.append(f"phase_seconds must be an object, got {ps!r}")
        else:
            for tag, v in ps.items():
                if not isinstance(tag, str) or not tag:
                    probs.append(f"phase_seconds key {tag!r} not a string")
                if (
                    not isinstance(v, (int, float))
                    or isinstance(v, bool)
                    or not v >= 0.0
                    or v != v
                    or v == float("inf")
                ):
                    probs.append(
                        f"phase_seconds[{tag!r}] must be a finite "
                        f"non-negative number, got {v!r}"
                    )
    bf = measured.get("bubble_frac")
    if bf is not None:
        if (
            not isinstance(bf, (int, float))
            or isinstance(bf, bool)
            or not 0.0 <= bf <= 1.0
        ):
            probs.append(f"bubble_frac must be in [0, 1], got {bf!r}")
        if ps is None:
            probs.append(
                "bubble_frac without phase_seconds — the fraction is "
                "meaningless without the attribution that produced it"
            )
    return probs


#: blocktri chain impls the bench driver can report (models/blocktri.IMPLS).
_BLOCKTRI_IMPLS = ("auto", "pallas", "xla", "partitioned")


def validate_blocktri_measured(measured) -> list[str]:
    """Schema problems of a bench:blocktri measured block ([] = valid) —
    the chain-geometry fields the blocktri driver emits (nblocks / block /
    n consistency, the speedup column, the wall_ms split).  Same
    exemption-with-validation posture as request_stats: diff() validates
    every record carrying a blocktri metric (malformed ->
    LedgerIncompatible) while the metric itself still compares normally —
    both blocktri metrics are rate-shaped (TFLOP/s, batch/s), so a
    value drop reads as "slower" like every other bench row."""
    if not isinstance(measured, dict):
        return [f"measured is {type(measured).__name__}, expected object"]
    probs = []
    for key in ("nblocks", "block", "n", "batch", "nrhs"):
        v = measured.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            probs.append(f"{key} must be a positive int, got {v!r}")
    nb, b, n = (measured.get(k) for k in ("nblocks", "block", "n"))
    if (isinstance(nb, int) and isinstance(b, int) and isinstance(n, int)
            and n != nb * b):
        probs.append(f"n {n} != nblocks*block {nb * b}")
    if measured.get("impl") not in _BLOCKTRI_IMPLS:
        probs.append(
            f"impl must be one of {_BLOCKTRI_IMPLS}, "
            f"got {measured.get('impl')!r}"
        )
    if "speedup" in measured:
        sp = measured["speedup"]
        if (not isinstance(sp, (int, float)) or isinstance(sp, bool)
                or not sp > 0):
            probs.append(f"speedup must be a positive number, got {sp!r}")
    wm = measured.get("wall_ms")
    if wm is not None:
        if not isinstance(wm, dict):
            probs.append(f"wall_ms must be an object, got {wm!r}")
        else:
            for p in _REQ_STATS_PCTS:
                if not isinstance(wm.get(p), (int, float)):
                    probs.append(f"wall_ms.{p} missing or non-numeric")
    # partitioned-driver fields (PR 13): optional — present on rows the
    # driver ran with --impl partitioned (and on the sequential A/B
    # baseline rows, which carry `depth` only).  When present they must
    # be well-formed: partitions a positive int, the depth trio positive
    # (depth/depth_seq are jaxpr scan-trip counts, depth_reduction their
    # ratio — the ≥4x gate of `make bench-blocktri-par`).
    if "partitions" in measured:
        p = measured["partitions"]
        if not isinstance(p, int) or isinstance(p, bool) or p < 1:
            probs.append(f"partitions must be a positive int, got {p!r}")
    for key in ("depth", "depth_seq"):
        if key in measured:
            d = measured[key]
            if not isinstance(d, int) or isinstance(d, bool) or d < 1:
                probs.append(f"{key} must be a positive int, got {d!r}")
    if "depth_reduction" in measured:
        dr = measured["depth_reduction"]
        if (not isinstance(dr, (int, float)) or isinstance(dr, bool)
                or not dr > 0):
            probs.append(
                f"depth_reduction must be a positive number, got {dr!r}")
    return probs


def validate_arrowhead_measured(measured) -> list[str]:
    """Schema problems of a bench:arrowhead measured block ([] = valid) —
    the arrowhead-geometry fields the driver emits (nblocks / block /
    border / n consistency, the chain impl, the structural-speedup
    column of the ≥10x ``make bench-arrowhead`` gate).  Same
    exemption-with-validation posture as blocktri / update / refine:
    diff() validates every record whose metric starts with "arrowhead"
    (malformed -> LedgerIncompatible) while the metric itself still
    compares normally — the value is a speedup ratio over dense batched
    posv, so a drop reads as "slower" like every other bench row."""
    if not isinstance(measured, dict):
        return [f"measured is {type(measured).__name__}, expected object"]
    probs = []
    for key in ("nblocks", "block", "border", "n", "batch", "nrhs"):
        v = measured.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            probs.append(f"{key} must be a positive int, got {v!r}")
    nb, b, s, n = (measured.get(k)
                   for k in ("nblocks", "block", "border", "n"))
    if (isinstance(nb, int) and isinstance(b, int) and isinstance(s, int)
            and isinstance(n, int) and n != nb * b + s):
        probs.append(f"n {n} != nblocks*block+border {nb * b + s}")
    if measured.get("impl") not in _BLOCKTRI_IMPLS:
        probs.append(
            f"impl must be one of {_BLOCKTRI_IMPLS}, "
            f"got {measured.get('impl')!r}"
        )
    # a speedup row (the arrowhead_tflops shape; arrowhead_latency rows
    # carry neither) must bring the whole proof bundle: both wall
    # comparands AND the f64 reference residuals it gated on (factor =
    # Schur completion vs a NumPy reference, solve = whole-matrix
    # backward error) — a speedup row that never proved its answers is
    # not a row this ledger wants
    if "speedup" in measured:
        for key in ("speedup", "arrow_ms", "dense_ms"):
            v = measured.get(key)
            if (not isinstance(v, (int, float)) or isinstance(v, bool)
                    or not v > 0):
                probs.append(f"{key} must be a positive number, got {v!r}")
        for key in ("factor_resid", "solve_resid"):
            v = measured.get(key)
            if (not isinstance(v, (int, float)) or isinstance(v, bool)
                    or v < 0):
                probs.append(
                    f"{key} must be a non-negative number, got {v!r}")
    wm = measured.get("wall_ms")
    if wm is not None:
        if not isinstance(wm, dict):
            probs.append(f"wall_ms must be an object, got {wm!r}")
        else:
            for p in _REQ_STATS_PCTS:
                if not isinstance(wm.get(p), (int, float)):
                    probs.append(f"wall_ms.{p} missing or non-numeric")
    return probs


#: update impls the bench driver can report (ops/update_small.IMPLS).
_UPDATE_IMPLS = ("auto", "pallas", "xla")


def validate_update_measured(measured) -> list[str]:
    """Schema problems of a bench:update_speedup measured block ([] =
    valid) — the online factor-maintenance fields the update driver emits
    (the n/k geometry, the update-vs-refactor speedup columns, and the
    optional serve_smoke residency block).  Same exemption-with-validation
    posture as request_stats / blocktri: diff() validates every record
    whose metric starts with "update" (malformed -> LedgerIncompatible)
    while the metric itself still compares normally — the value is
    rate-shaped (TFLOP/s over the useful 2kn² flops), so a drop reads as
    "slower" like every other bench row."""
    if not isinstance(measured, dict):
        return [f"measured is {type(measured).__name__}, expected object"]
    probs = []
    for key in ("n", "k", "batch"):
        v = measured.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            probs.append(f"{key} must be a positive int, got {v!r}")
    if measured.get("impl") not in _UPDATE_IMPLS:
        probs.append(
            f"impl must be one of {_UPDATE_IMPLS}, "
            f"got {measured.get('impl')!r}"
        )
    for key in ("speedup", "refactor_ms", "update_ms"):
        v = measured.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) or not v > 0:
            probs.append(f"{key} must be a positive number, got {v!r}")
    wm = measured.get("wall_ms")
    if not isinstance(wm, dict):
        probs.append(f"wall_ms must be an object, got {wm!r}")
    else:
        for p in _REQ_STATS_PCTS:
            if not isinstance(wm.get(p), (int, float)):
                probs.append(f"wall_ms.{p} missing or non-numeric")
    # the serve residency smoke rides along only when the driver ran it
    # (--min-hit-rate); absent blocks stay valid unchanged
    if "serve_smoke" in measured:
        sm = measured["serve_smoke"]
        if not isinstance(sm, dict):
            probs.append(f"serve_smoke must be an object, got {sm!r}")
        else:
            for key in ("requests", "recompiles"):
                v = sm.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(
                        f"serve_smoke.{key} must be a non-negative int, "
                        f"got {v!r}"
                    )
            hr = sm.get("hit_rate")
            if not isinstance(hr, (int, float)) or not 0.0 <= hr <= 1.0:
                probs.append(
                    f"serve_smoke.hit_rate must be in [0, 1], got {hr!r}"
                )
    return probs


def validate_refine_measured(measured) -> list[str]:
    """Schema problems of a bench:refine measured block ([] = valid) — the
    mixed-precision iterative-refinement fields the refine driver emits
    (the n/nrhs/batch geometry, the dtype pair, the f32-factor+IR vs
    f64-factor speedup with its matched-residual ratio, and the TSQR
    orthogonality probe).  Same exemption-with-validation posture as
    blocktri / update: diff() validates every record whose metric starts
    with "refine" (malformed -> LedgerIncompatible) while the metric
    itself still compares normally — the value is a speedup ratio, so a
    drop reads as "slower" like every other bench row."""
    if not isinstance(measured, dict):
        return [f"measured is {type(measured).__name__}, expected object"]
    probs = []
    for key in ("n", "nrhs", "batch"):
        v = measured.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            probs.append(f"{key} must be a positive int, got {v!r}")
    for key in ("factor_dtype", "correction_dtype"):
        v = measured.get(key)
        if not isinstance(v, str) or not v:
            probs.append(f"{key} must be a non-empty string, got {v!r}")
    for key in ("speedup", "refined_ms", "baseline_ms"):
        v = measured.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or not v > 0:
            probs.append(f"{key} must be a positive number, got {v!r}")
    # how far the refined residual sits from the straight high-dtype
    # factor's (1.0 = identical); the bench gate bounds it above
    rr = measured.get("resid_ratio")
    if not isinstance(rr, (int, float)) or isinstance(rr, bool) or rr < 0:
        probs.append(
            f"resid_ratio must be a non-negative number, got {rr!r}"
        )
    it = measured.get("iters")
    if not isinstance(it, int) or isinstance(it, bool) or it < 0:
        probs.append(f"iters must be a non-negative int, got {it!r}")
    if "tsqr_ortho" in measured:
        v = measured["tsqr_ortho"]
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
            probs.append(
                f"tsqr_ortho must be a non-negative number, got {v!r}"
            )
    wm = measured.get("wall_ms")
    if not isinstance(wm, dict):
        probs.append(f"wall_ms must be an object, got {wm!r}")
    else:
        for p in _REQ_STATS_PCTS:
            if not isinstance(wm.get(p), (int, float)):
                probs.append(f"wall_ms.{p} missing or non-numeric")
    # the tier serve smoke rides along only when the driver ran it;
    # absent blocks stay valid unchanged
    if "serve_smoke" in measured:
        sm = measured["serve_smoke"]
        if not isinstance(sm, dict):
            probs.append(f"serve_smoke must be an object, got {sm!r}")
        else:
            for key in ("requests", "recompiles"):
                v = sm.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(
                        f"serve_smoke.{key} must be a non-negative int, "
                        f"got {v!r}"
                    )
    return probs


def validate_serve_trace(block) -> list[str]:
    """Schema problems of one serve_trace block ([] = valid) — the
    per-request span-chain record `obs.spans.TraceLog.emit` writes.  Same
    exemption-with-validation posture as request_stats: diff() validates
    every record carrying the block (malformed -> LedgerIncompatible)
    while never metric-comparing it — a trace waterfall is a workload's
    shape; its gates are ``obs serve-report --min-trace-complete`` and the
    in-run smoke gate.  Chain validation itself delegates to
    `spans.trace_dict_problems`, the SAME code the producer's `complete`
    verdict ran, so the ledger check and the in-run gate can never
    disagree about what a complete chain is."""
    from capital_tpu.obs import spans

    if not isinstance(block, dict):
        return [f"serve_trace is {type(block).__name__}, expected object"]
    probs = []
    if block.get("schema_version") != SCHEMA_VERSION:
        probs.append(
            f"schema_version {block.get('schema_version')!r} != "
            f"{SCHEMA_VERSION}"
        )
    tol = block.get("bubble_tol_ms")
    if not isinstance(tol, (int, float)) or isinstance(tol, bool) \
            or not tol >= 0:
        probs.append(
            f"bubble_tol_ms must be a non-negative number, got {tol!r}"
        )
        tol = spans.DEFAULT_BUBBLE_TOL_MS
    for key in ("requests", "complete", "dropped", "violations"):
        v = block.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            probs.append(f"{key} must be a non-negative int, got {v!r}")
    traces = block.get("traces")
    if not isinstance(traces, list):
        probs.append(f"traces must be a list, got {traces!r}")
        return probs
    req = block.get("requests")
    if isinstance(req, int) and req != len(traces):
        probs.append(f"requests {req} != len(traces) {len(traces)}")
    n_complete = 0
    for i, t in enumerate(traces):
        t_probs = spans.trace_dict_problems(t, float(tol))
        if not t_probs:
            n_complete += 1
        else:
            # structural breakage (non-dict / non-numeric spans) is a
            # schema problem; an INCOMPLETE but well-formed chain is data
            # the completeness gate judges, not a malformed record
            for p in t_probs:
                if ("not a dict" in p or "not a string" in p
                        or "non-numeric" in p or "not an int" in p
                        or "not a list" in p):
                    probs.append(f"traces[{i}]: {p}")
    comp = block.get("complete")
    if isinstance(comp, int) and not probs and comp != n_complete:
        probs.append(
            f"complete {comp} disagrees with recount {n_complete} under "
            f"bubble_tol_ms={tol}"
        )
    viol = block.get("violations")
    n_viol = sum(1 for t in traces
                 if isinstance(t, dict) and t.get("violated"))
    if isinstance(viol, int) and viol != n_viol:
        probs.append(f"violations {viol} != recount {n_viol}")
    return probs


def validate_serve_window(block) -> list[str]:
    """Schema problems of one serve_window block ([] = valid) — a
    `serve.telemetry.WindowAggregator` closed window.  Same posture as
    serve_trace: structurally validated on every diff, never
    metric-compared (a window's latency profile is live traffic; its gate
    is ``obs serve-report --min-windows``).  Coherence checks pin the
    invariants the aggregator promises: ok + failed + shed == requests,
    histogram counts sum to the latencied population, percentiles ordered.
    A window may legitimately carry requests == 0 with batches > 0 (a
    batch dispatched in this window whose requests landed in the next),
    so counts are checked for consistency, never positivity."""
    if not isinstance(block, dict):
        return [f"serve_window is {type(block).__name__}, expected object"]
    probs = []
    if block.get("schema_version") != SCHEMA_VERSION:
        probs.append(
            f"schema_version {block.get('schema_version')!r} != "
            f"{SCHEMA_VERSION}"
        )
    ws = block.get("window_s")
    if not isinstance(ws, (int, float)) or isinstance(ws, bool) \
            or not ws > 0:
        probs.append(f"window_s must be a positive number, got {ws!r}")
    t0, t1 = block.get("t_start_s"), block.get("t_end_s")
    for key, v in (("t_start_s", t0), ("t_end_s", t1)):
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            probs.append(f"{key} must be a number, got {v!r}")
    if (isinstance(t0, (int, float)) and isinstance(t1, (int, float))
            and t1 < t0):
        probs.append(f"t_end_s {t1} < t_start_s {t0}")
    for key in ("requests", "ok", "failed", "shed", "sampled",
                "queue_depth_max", "batches"):
        v = block.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            probs.append(f"{key} must be a non-negative int, got {v!r}")
    req, ok, failed, shed = (block.get(k)
                             for k in ("requests", "ok", "failed", "shed"))
    counted = all(isinstance(v, int) and not isinstance(v, bool)
                  for v in (req, ok, failed, shed))
    if counted and ok + failed + shed != req:
        probs.append(
            f"ok {ok} + failed {failed} + shed {shed} != requests {req}"
        )
    lat = block.get("latency_ms")
    if not isinstance(lat, dict):
        probs.append(f"latency_ms must be an object, got {lat!r}")
    else:
        for p in _REQ_STATS_PCTS:
            if not isinstance(lat.get(p), (int, float)):
                probs.append(f"latency_ms.{p} missing or non-numeric")
        pcts = [lat.get(p) for p in _REQ_STATS_PCTS]
        if (all(isinstance(v, (int, float)) for v in pcts)
                and not pcts[0] <= pcts[1] <= pcts[2]):
            probs.append(
                f"percentiles out of order: p50 {pcts[0]} <= p95 "
                f"{pcts[1]} <= p99 {pcts[2]} fails"
            )
    hist = block.get("hist_ms")
    if not isinstance(hist, dict):
        probs.append(f"hist_ms must be an object, got {hist!r}")
    else:
        edges, counts = hist.get("edges"), hist.get("counts")
        if (not isinstance(edges, list)
                or not all(isinstance(e, (int, float)) for e in edges)
                or sorted(edges) != edges):
            probs.append(f"hist_ms.edges must be ascending numbers, "
                         f"got {edges!r}")
        if (not isinstance(counts, list)
                or not all(isinstance(c, int) and not isinstance(c, bool)
                           and c >= 0 for c in counts)):
            probs.append(f"hist_ms.counts must be non-negative ints, "
                         f"got {counts!r}")
        elif isinstance(edges, list) and len(counts) != len(edges) + 1:
            probs.append(
                f"hist_ms.counts has {len(counts)} bins for "
                f"{len(edges)} edges (need len(edges) + 1)"
            )
        elif counted and sum(counts) != ok + failed:
            probs.append(
                f"hist_ms.counts sum {sum(counts)} != ok + failed "
                f"{ok + failed}"
            )
    sm = block.get("sampled")
    if (counted and isinstance(sm, int) and not isinstance(sm, bool)
            and sm > ok + failed):
        probs.append(f"sampled {sm} > ok + failed {ok + failed}")
    if not isinstance(block.get("samples_capped"), bool):
        probs.append(
            f"samples_capped must be a bool, "
            f"got {block.get('samples_capped')!r}"
        )
    occ = block.get("occupancy_mean")
    if not isinstance(occ, (int, float)) or isinstance(occ, bool) \
            or not 0.0 <= occ <= 1.0:
        probs.append(f"occupancy_mean must be in [0, 1], got {occ!r}")
    ops = block.get("ops")
    if not isinstance(ops, dict):
        probs.append(f"ops must be an object, got {ops!r}")
    else:
        for name, v in ops.items():
            if name not in _REQ_STATS_OPS:
                probs.append(
                    f"ops key {name!r} is not a known serve op "
                    f"{_REQ_STATS_OPS}"
                )
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                probs.append(
                    f"ops[{name!r}] must be a non-negative int, got {v!r}"
                )
    pb = block.get("per_bucket")
    if not isinstance(pb, dict):
        probs.append(f"per_bucket must be an object, got {pb!r}")
    else:
        for label, cell in pb.items():
            if not isinstance(cell, dict):
                probs.append(f"per_bucket[{label!r}] is not an object")
                continue
            for key in ("requests", "shed", "batches"):
                v = cell.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(
                        f"per_bucket[{label!r}].{key} must be a "
                        f"non-negative int, got {v!r}"
                    )
            co = cell.get("occupancy_mean")
            if not isinstance(co, (int, float)) or isinstance(co, bool) \
                    or not 0.0 <= co <= 1.0:
                probs.append(
                    f"per_bucket[{label!r}].occupancy_mean must be in "
                    f"[0, 1], got {co!r}"
                )
    return probs


#: session_stats schema (serve/sessions.SessionManager.stats): required
#: counter keys of one serve:session_stats record.
_SESSION_STATS_COUNTS = ("opens", "reseeds", "appends", "solves",
                         "contracts", "closes", "failures",
                         "evicted_failures", "hits", "misses",
                         "sessions_open", "sessions_known",
                         "blocks_appended", "blocks_dropped")


def validate_session_stats(block) -> list[str]:
    """Schema problems of one session_stats block ([] = valid) — a
    `serve.sessions.SessionManager` counter snapshot (PR 19, docs/
    SERVING.md 'Streaming sessions').  Same posture as request_stats:
    structurally validated on every diff, never metric-compared — a
    session workload's hit-rate is the workload's property; its gates are
    ``obs serve-report --min-session-hit-rate / --max-reseeds``.
    Coherence checks pin the manager's promises: hit_rate consistent
    with hits/misses, misses == evicted_failures (the only miss is an
    evicted factor), reseeds <= opens, sessions_open <= sessions_known,
    blocks_dropped <= blocks_appended (a chain cannot contract blocks it
    never streamed)."""
    if not isinstance(block, dict):
        return [f"session_stats is {type(block).__name__}, expected object"]
    probs = []
    if block.get("schema_version") != SCHEMA_VERSION:
        probs.append(
            f"schema_version {block.get('schema_version')!r} != "
            f"{SCHEMA_VERSION}"
        )
    for key in _SESSION_STATS_COUNTS:
        v = block.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            probs.append(f"{key} must be a non-negative int, got {v!r}")
    hr = block.get("hit_rate")
    if not isinstance(hr, (int, float)) or isinstance(hr, bool) \
            or not 0.0 <= hr <= 1.0:
        probs.append(f"hit_rate must be in [0, 1], got {hr!r}")
    h, m = block.get("hits"), block.get("misses")
    ints = all(isinstance(v, int) and not isinstance(v, bool)
               for v in (h, m))
    if (ints and isinstance(hr, (int, float)) and h + m > 0
            and abs(hr - h / (h + m)) > 1e-6):
        probs.append(
            f"hit_rate {hr!r} inconsistent with hits={h} misses={m} "
            f"(expected {h / (h + m):.6f})"
        )
    ev = block.get("evicted_failures")
    if (isinstance(m, int) and isinstance(ev, int)
            and not isinstance(m, bool) and m != ev):
        probs.append(
            f"misses {m} != evicted_failures {ev} (the only session "
            "miss is an evicted resident factor)"
        )
    rs, op = block.get("reseeds"), block.get("opens")
    if isinstance(rs, int) and isinstance(op, int) and rs > op:
        probs.append(f"reseeds {rs} > opens {op}")
    so, sk = block.get("sessions_open"), block.get("sessions_known")
    if isinstance(so, int) and isinstance(sk, int) and so > sk:
        probs.append(f"sessions_open {so} > sessions_known {sk}")
    ba, bd = block.get("blocks_appended"), block.get("blocks_dropped")
    if isinstance(ba, int) and isinstance(bd, int) and bd > ba:
        probs.append(f"blocks_dropped {bd} > blocks_appended {ba}")
    return probs


def _event_status(rec: dict) -> Optional[str]:
    """The robustness status of a record, when it carries one.

    'failed' / 'recovered' come from an explicit event block (sweep failure
    containment, autotune/sweep.py); 'recovery' is derived from a robust
    block with nonzero breakdown/shift/escalation counters (a bench run
    that went through the shifted-CholeskyQR path).  Records with a status
    are exempt from the measured-value comparison in diff(): a run that
    paid recovery sweeps (or failed outright) is slower BY DESIGN, and
    reading that as a throughput regression would teach people to strip
    the robust path before benchmarking.  'serve' marks request_stats
    records (serve/stats.py): a served workload's latency mix is the
    workload's property, not a kernel's — its regression story is
    ``obs serve-report`` gates, not the bench metric check.  'lint' marks
    lint_report records (capital_tpu.lint CLI) for the same reason — their
    gate is ``obs lint-report``."""
    if rec.get("request_stats") is not None:
        return "serve"
    if rec.get("session_stats") is not None:
        # streaming-session counter records (serve/sessions.py): gated
        # by ``obs serve-report --min-session-hit-rate / --max-reseeds``
        return "serve"
    if rec.get("serve_trace") is not None \
            or rec.get("serve_window") is not None:
        # span-chain / rolling-window telemetry records (obs/spans.py,
        # serve/telemetry.py): same story as request_stats — their gates
        # are ``obs serve-report --min-trace-complete/--min-windows``
        return "serve"
    if rec.get("lint_report") is not None:
        return "lint"
    ev = rec.get("event")
    if isinstance(ev, dict) and ev.get("status"):
        return str(ev["status"])
    rb = rec.get("robust")
    if isinstance(rb, dict) and any(
        rb.get(k) for k in ("breakdown", "shifted", "escalated")
    ):
        return "recovery"
    return None


def diff(
    a_recs: Iterable[dict],
    b_recs: Iterable[dict],
    tol_metric: float = 0.10,
    tol_hbm: float = 0.05,
    tol_collective: int = 0,
) -> list[Regression]:
    """Regressions going from ledger `a` (baseline) to ledger `b`.

    * measured value (e.g. TFLOP/s): b below a by more than tol_metric;
    * collective counts by kind: b above a by more than tol_collective;
    * peak HBM: b above a by more than tol_hbm (fractional).

    Only keys present in BOTH ledgers are compared (a missing row is a
    coverage change, not a regression); multiple records per key compare
    last-against-last (the ledger is append-ordered, so the last record is
    the freshest trial).  Records carrying a failure/recovery status
    (_event_status) skip ONLY the measured-value check — their walls
    include recovery work or are absent entirely; the structural checks
    (collectives, peak HBM) still apply.  request_stats records are exempt
    the same way, but their block must VALIDATE
    (validate_request_stats) — a malformed one raises LedgerIncompatible
    like any other apples-to-oranges input."""
    a_recs, b_recs = list(a_recs), list(b_recs)
    for r in (*a_recs, *b_recs):
        rs = r.get("request_stats")
        if rs is not None:
            probs = validate_request_stats(rs)
            if probs:
                raise LedgerIncompatible(
                    "malformed request_stats record: " + "; ".join(probs)
                )
        st = r.get("serve_trace")
        if st is not None:
            probs = validate_serve_trace(st)
            if probs:
                raise LedgerIncompatible(
                    "malformed serve_trace record: " + "; ".join(probs)
                )
        sw = r.get("serve_window")
        if sw is not None:
            probs = validate_serve_window(sw)
            if probs:
                raise LedgerIncompatible(
                    "malformed serve_window record: " + "; ".join(probs)
                )
        ss = r.get("session_stats")
        if ss is not None:
            probs = validate_session_stats(ss)
            if probs:
                raise LedgerIncompatible(
                    "malformed session_stats record: " + "; ".join(probs)
                )
        lr = r.get("lint_report")
        if lr is not None:
            probs = validate_lint_report(lr)
            if probs:
                raise LedgerIncompatible(
                    "malformed lint_report record: " + "; ".join(probs)
                )
        meas = r.get("measured")
        if isinstance(meas, dict) and (
            "phase_seconds" in meas or "bubble_frac" in meas
        ):
            probs = validate_phase_seconds(meas)
            if probs:
                raise LedgerIncompatible(
                    "malformed phase attribution record: " + "; ".join(probs)
                )
        if isinstance(meas, dict) and str(
            meas.get("metric", "")
        ).startswith("blocktri"):
            probs = validate_blocktri_measured(meas)
            if probs:
                raise LedgerIncompatible(
                    "malformed blocktri bench record: " + "; ".join(probs)
                )
        if isinstance(meas, dict) and str(
            meas.get("metric", "")
        ).startswith("arrowhead"):
            probs = validate_arrowhead_measured(meas)
            if probs:
                raise LedgerIncompatible(
                    "malformed arrowhead bench record: " + "; ".join(probs)
                )
        if isinstance(meas, dict) and str(
            meas.get("metric", "")
        ).startswith("update"):
            probs = validate_update_measured(meas)
            if probs:
                raise LedgerIncompatible(
                    "malformed update bench record: " + "; ".join(probs)
                )
        if isinstance(meas, dict) and str(
            meas.get("metric", "")
        ).startswith("refine"):
            probs = validate_refine_measured(meas)
            if probs:
                raise LedgerIncompatible(
                    "malformed refine bench record: " + "; ".join(probs)
                )
    a_by = {_key(r): r for r in a_recs}
    b_by = {_key(r): r for r in b_recs}
    out: list[Regression] = []
    for key in sorted(set(a_by) & set(b_by)):
        a, b = a_by[key], b_by[key]
        _check_comparable(a, b)
        am, bm = a.get("measured") or {}, b.get("measured") or {}
        av, bv = am.get("value"), bm.get("value")
        exempt = _event_status(a) or _event_status(b)
        if not exempt and av and bv and bv < av * (1.0 - tol_metric):
            out.append(
                Regression(
                    key, "measured.value", av, bv,
                    f"{am.get('unit', '')} dropped >{tol_metric:.0%}",
                )
            )
        aa, ba = a.get("audit") or {}, b.get("audit") or {}
        for kind, ac in (aa.get("collective_counts") or {}).items():
            bc = (ba.get("collective_counts") or {}).get(kind)
            if bc is not None and bc > ac + tol_collective:
                out.append(
                    Regression(
                        key, f"collectives.{kind}", ac, bc,
                        "compiled program gained collectives",
                    )
                )
        ah, bh = aa.get("peak_hbm_bytes"), ba.get("peak_hbm_bytes")
        if ah and bh and bh > ah * (1.0 + tol_hbm):
            out.append(
                Regression(
                    key, "peak_hbm_bytes", ah, bh,
                    f"peak memory grew >{tol_hbm:.0%}",
                )
            )
    return out
