"""Compiled-program audit: collective inventory, cost facts, model drift.

The collective-inventory scan started life as regex helpers inside
tests/test_collective_audit.py, where the pinned counts lived in hand-derived
snapshot comments ("44 gathers = the model's 31 schedule collectives plus
GSPMD window materializations").  This module makes that audit a library:

* `audit(fn, *args)` compiles a jitted fn and returns a `ProgramAudit` —
  collective counts by kind (lowered-HLO text scan, the same
  ``= ... kind(`` convention the pinned tests use), per-collective operand
  byte totals, per-phase attribution via the named-scope metadata every op
  carries (utils/tracing.scope), flops / bytes-accessed from XLA's
  ``cost_analysis()``, and peak-memory facts from ``memory_analysis()``.

* `drift(audit, recorder)` compares the compiled facts against the analytic
  Recorder model phase by phase and classifies each phase —
  ``within-tolerance`` / ``model-undercounts`` / ``compiled-extra`` —
  replacing the snapshot comments with a machine-checkable report.  The
  tolerance policy (docs/OBSERVABILITY.md): compiled may exceed the model
  by GSPMD data motion (sharding-constraint permutes, window slices,
  base-case replication gathers) bounded by ``tol_ratio``x + ``slack``;
  a phase the model prices at zero that compiles collectives anyway is
  ``compiled-extra`` (informational — that's where pure-GSPMD motion
  lands); fewer compiled than modeled means XLA merged collectives and is
  within tolerance by definition.
"""

from __future__ import annotations

import dataclasses
import logging
import re
from typing import Callable, Iterable, Optional

import jax

from capital_tpu.utils import tracing

_log = logging.getLogger(__name__)

#: Collective kinds inventoried, matching the pinned audit tests.  The scan
#: counts both the sync form (``all-gather(``) and the async pair's start op
#: (``all-gather-start(``) under one kind, so TPU async lowering and the CPU
#: rig's sync lowering report the same inventory.
KINDS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

_ITEMSIZE = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]*)\[([0-9,]*)\]")
_COLL_RE = re.compile(
    r"= (?P<res>[^=]*?)\s?"
    r"(?P<kind>" + "|".join(KINDS) + r")(?P<async>-start)?"
    r"\((?P<ops>[^)]*)"
)


def _shape_bytes(segment: str) -> float:
    """Total bytes of every ``dtype[d0,d1,...]`` shape token in `segment`."""
    total = 0.0
    for dtype, dims in _SHAPE_RE.findall(segment):
        item = _ITEMSIZE.get(dtype)
        if item is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * item
    return total


def _phase_of(line: str) -> str:
    """Longest registered phase tag mentioned anywhere in the HLO line (the
    op's own %name or its op_name metadata path carries the named-scope
    chain) — the same longest-first attribution the trace tool uses.  Ops
    outside every registered scope land in 'other': that is where pure
    GSPMD data motion (resharding permutes etc.) shows up."""
    best = None
    for tag in tracing.PHASE_REGISTRY:
        dot = tag.replace("::", ".")
        if dot in line and (best is None or len(dot) > len(best.replace("::", "."))):
            best = tag
    return best or "other"


@dataclasses.dataclass
class CollectiveOp:
    """One emitted collective: kind, owning phase tag, operand payload bytes."""

    kind: str
    phase: str
    operand_bytes: float


@dataclasses.dataclass
class ProgramAudit:
    """Structured facts about one compiled XLA program."""

    collective_counts: dict[str, int]
    collective_bytes: dict[str, float]  # operand payload bytes by kind
    phase_collectives: dict[str, int]  # phase tag (or 'other') -> count
    phase_comm_bytes: dict[str, float]
    flops: float
    bytes_accessed: float
    peak_hbm_bytes: float  # argument + output + temp (XLA memory_analysis)
    argument_bytes: float
    output_bytes: float
    temp_bytes: float
    ops: list[CollectiveOp] = dataclasses.field(default_factory=list, repr=False)

    def total_collectives(self) -> int:
        return sum(self.collective_counts.values())

    def asdict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("ops")  # per-op detail is derivable and bloats ledger lines
        return d


def scan_collectives(hlo_text: str) -> list[CollectiveOp]:
    """Inventory every collective in (post-optimization) HLO text.

    Pure text logic, unit-testable without a mesh.  Operand payload bytes
    come from the typed operand list (``all-gather(f32[2,4]{1,0} %p)``);
    lines whose operands are bare ``%refs`` fall back to the result shape."""
    out: list[CollectiveOp] = []
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if m is None:
            continue
        nbytes = _shape_bytes(m.group("ops")) or _shape_bytes(m.group("res"))
        out.append(CollectiveOp(m.group("kind"), _phase_of(line), nbytes))
    return out


def audit_text(hlo_text: str) -> ProgramAudit:
    """ProgramAudit of HLO text alone (no cost/memory analysis facts)."""
    counts = {k: 0 for k in KINDS}
    kbytes = {k: 0.0 for k in KINDS}
    pcount: dict[str, int] = {}
    pbytes: dict[str, float] = {}
    ops = scan_collectives(hlo_text)
    for op in ops:
        counts[op.kind] += 1
        kbytes[op.kind] += op.operand_bytes
        pcount[op.phase] = pcount.get(op.phase, 0) + 1
        pbytes[op.phase] = pbytes.get(op.phase, 0.0) + op.operand_bytes
    return ProgramAudit(
        collective_counts=counts,
        collective_bytes=kbytes,
        phase_collectives=pcount,
        phase_comm_bytes=pbytes,
        flops=0.0,
        bytes_accessed=0.0,
        peak_hbm_bytes=0.0,
        argument_bytes=0.0,
        output_bytes=0.0,
        temp_bytes=0.0,
        ops=ops,
    )


def _cost_analysis(compiled) -> dict:
    try:
        ca = compiled.cost_analysis()
    except Exception as e:
        # some backends/jax versions simply don't implement it; the audit
        # degrades to zero flops facts, but the swallow must stay visible
        _log.debug("cost_analysis unavailable: %s: %s", type(e).__name__, e)
        return {}
    if isinstance(ca, (list, tuple)):  # older jax returns one dict per device
        ca = ca[0] if ca else {}
    return dict(ca or {})


def audit_compiled(compiled) -> ProgramAudit:
    """ProgramAudit of an already-compiled executable (jit(...).lower(...)
    .compile() product)."""
    audit = audit_text(compiled.as_text())
    ca = _cost_analysis(compiled)
    audit.flops = float(ca.get("flops", 0.0))
    audit.bytes_accessed = float(ca.get("bytes accessed", 0.0))
    try:
        ma = compiled.memory_analysis()
        audit.argument_bytes = float(ma.argument_size_in_bytes)
        audit.output_bytes = float(ma.output_size_in_bytes)
        audit.temp_bytes = float(ma.temp_size_in_bytes)
        audit.peak_hbm_bytes = (
            audit.argument_bytes + audit.output_bytes + audit.temp_bytes
        )
    except Exception as e:
        # backends without memory_analysis keep the zero defaults
        _log.debug("memory_analysis unavailable: %s: %s",
                   type(e).__name__, e)
    return audit


def audit(fn: Callable, *args, jit_kwargs: Optional[dict] = None) -> ProgramAudit:
    """Compile ``jit(fn)(*args)`` and audit the resulting program.

    A fresh jit wrapper per call: auditing must not poison (or hit) the
    caller's jit cache entry."""
    compiled = jax.jit(fn, **(jit_kwargs or {})).lower(*args).compile()
    return audit_compiled(compiled)


def trace_model(fn: Callable, *args) -> tracing.Recorder:
    """Capture the analytic Recorder model for one program by tracing only
    (jax.eval_shape — phase emits fire at trace time, nothing executes).

    The trace runs through a FRESH wrapper function each call: jax caches
    traces by function identity, so re-tracing a function that was already
    traced (by an earlier trace_model, or by audit()'s jit/lower on the
    same object) would hit the cache, skip the Python bodies, and return
    an empty Recorder — model totals of 0 instead of the schedule's."""
    rec = tracing.Recorder()

    def _fresh(*a):
        return fn(*a)

    with rec:
        jax.eval_shape(_fresh, *args)
    return rec


# --------------------------------------------------------------------------
# drift classification
# --------------------------------------------------------------------------

WITHIN = "within-tolerance"
UNDERCOUNT = "model-undercounts"
EXTRA = "compiled-extra"


@dataclasses.dataclass
class PhaseDrift:
    """Model-vs-compiled comparison for one phase tag."""

    phase: str
    model_collectives: int
    compiled_collectives: int
    model_comm_bytes: float
    compiled_comm_bytes: float
    classification: str


@dataclasses.dataclass
class DriftReport:
    phases: list[PhaseDrift]
    model_flops: float  # homogeneous model, summed over phases (per device)
    compiled_flops: float  # XLA cost_analysis whole-program count
    model_collectives_total: int
    compiled_collectives_total: int
    peak_hbm_bytes: float
    tol_ratio: float
    slack: int
    flops_tol_ratio: float

    @property
    def flops_within(self) -> bool:
        """Compiled flops within [model/r, model*r].  Skipped (True) when
        either side reports zero — cost_analysis is unavailable on some
        backends, and a trace with no emits has no model to drift from."""
        if self.model_flops <= 0 or self.compiled_flops <= 0:
            return True
        r = self.compiled_flops / self.model_flops
        return 1.0 / self.flops_tol_ratio <= r <= self.flops_tol_ratio

    @property
    def ok(self) -> bool:
        """In tolerance: no phase where the model books collectives but the
        compiled program exceeds them beyond the GSPMD allowance, and the
        whole-program flop counts agree within flops_tol_ratio."""
        return self.flops_within and all(
            p.classification != UNDERCOUNT for p in self.phases
        )

    def asdict(self) -> dict:
        return {
            "ok": self.ok,
            "flops_within": self.flops_within,
            "model_flops": self.model_flops,
            "compiled_flops": self.compiled_flops,
            "model_collectives_total": self.model_collectives_total,
            "compiled_collectives_total": self.compiled_collectives_total,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "tol_ratio": self.tol_ratio,
            "slack": self.slack,
            "flops_tol_ratio": self.flops_tol_ratio,
            "phases": [dataclasses.asdict(p) for p in self.phases],
        }

    def lines(self) -> list[str]:
        """Human-readable report, one line per phase."""
        out = [
            f"drift: model {self.model_collectives_total} collectives vs "
            f"compiled {self.compiled_collectives_total}; flops model "
            f"{self.model_flops:.3e} vs compiled {self.compiled_flops:.3e} "
            f"({'ok' if self.flops_within else 'OUT OF TOLERANCE'}); "
            f"peak mem {self.peak_hbm_bytes / 1e6:.1f} MB"
        ]
        for p in sorted(self.phases, key=lambda p: p.phase):
            out.append(
                f"  {p.phase:18s} model {p.model_collectives:4d} coll "
                f"{p.model_comm_bytes:11.3e} B   compiled "
                f"{p.compiled_collectives:4d} coll "
                f"{p.compiled_comm_bytes:11.3e} B   {p.classification}"
            )
        out.append(f"  -> {'WITHIN TOLERANCE' if self.ok else 'DRIFT DETECTED'}")
        return out


def drift(
    audit: ProgramAudit,
    recorder: tracing.Recorder,
    tol_ratio: float = 4.0,
    slack: int = 8,
    flops_tol_ratio: float = 2.0,
) -> DriftReport:
    """Classify per-phase drift between the compiled program and the model.

    Per phase with model count ``m`` and compiled count ``c``:

    * ``m == 0 and c > 0`` -> compiled-extra (pure GSPMD motion; the c=1
      cholinv's 55 sharding-constraint permutes live here);
    * ``c > m * tol_ratio + slack`` -> model-undercounts (the failure this
      report exists to catch: a schedule change silently adding
      collectives);
    * otherwise within-tolerance (including ``c < m`` — XLA merging or
      eliding modeled collectives costs nothing).

    Defaults encode the audited flagship ratios (compiled/model 2.2-3.2x,
    tests/test_collective_audit.py snapshots) with headroom; the policy is
    documented in docs/OBSERVABILITY.md.
    """
    phases: list[PhaseDrift] = []
    tags: Iterable[str] = sorted(
        set(recorder.stats) | set(audit.phase_collectives)
    )
    for tag in tags:
        m = recorder.stats[tag].collectives if tag in recorder.stats else 0
        mb = recorder.stats[tag].comm_bytes if tag in recorder.stats else 0.0
        c = audit.phase_collectives.get(tag, 0)
        cb = audit.phase_comm_bytes.get(tag, 0.0)
        if m == 0 and c > 0:
            cls = EXTRA
        elif c > m * tol_ratio + slack:
            cls = UNDERCOUNT
        else:
            cls = WITHIN
        phases.append(PhaseDrift(tag, m, c, mb, cb, cls))
    total = recorder.total()
    return DriftReport(
        phases=phases,
        model_flops=total.flops,
        compiled_flops=audit.flops,
        model_collectives_total=total.collectives,
        compiled_collectives_total=audit.total_collectives(),
        peak_hbm_bytes=audit.peak_hbm_bytes,
        tol_ratio=tol_ratio,
        slack=slack,
        flops_tol_ratio=flops_tol_ratio,
    )


def audit_and_drift(
    fn: Callable, *args, tol_ratio: float = 4.0, slack: int = 8,
    flops_tol_ratio: float = 2.0,
) -> tuple[ProgramAudit, tracing.Recorder, DriftReport]:
    """One-call convenience: model trace + compiled audit + drift report for
    a jit-able fn.  The model is captured on a fresh trace (eval_shape) so a
    warm jit cache cannot starve the Recorder."""
    rec = trace_model(fn, *args)
    a = audit(fn, *args)
    return a, rec, drift(
        a, rec, tol_ratio=tol_ratio, slack=slack,
        flops_tol_ratio=flops_tol_ratio,
    )


# --------------------------------------------------------------------------
# sequential scan-depth (jaxpr trip-length count — docs/PERF.md round 13)
# --------------------------------------------------------------------------


def _sub_jaxprs(params: dict):
    """Every sub-jaxpr hanging off an eqn's params (pjit/scan/cond/custom
    derivatives all stash theirs under different keys — structural duck
    typing beats a primitive-name switch across jax versions)."""
    for v in params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for w in vs:
            if hasattr(w, "eqns"):
                yield w
            elif hasattr(w, "jaxpr") and hasattr(w.jaxpr, "eqns"):
                yield w.jaxpr


def scan_depth(jaxpr) -> int:
    """Total `lax.scan` trip count along the program: each scan contributes
    length × max(1, depth of its body), nested control flow recursed
    (cond branches take the max — only one executes).  This is the honest
    sequential-depth metric on the 1-core CI rig, where wall-clock cannot
    distinguish a 192-step chain from a 45-step one: scans are the ONLY
    sequential construct these programs emit, every trip is a dependent
    step, and independent work (the partitioned interiors) folds into the
    batch axis of a single scan rather than adding trips.  The bench
    driver's depth column and `make bench-blocktri-par`'s ≥4x reduction
    gate both read this."""
    jx = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    depth = 0
    for eqn in jx.eqns:
        subs = list(_sub_jaxprs(eqn.params))
        name = eqn.primitive.name
        if name == "scan":
            length = int(eqn.params.get("length", 1))
            inner = max((scan_depth(s) for s in subs), default=0)
            depth += length * max(inner, 1)
        elif name == "cond":
            depth += max((scan_depth(s) for s in subs), default=0)
        else:
            depth += sum(scan_depth(s) for s in subs)
    return depth


def sequential_depth(fn: Callable, *args) -> int:
    """`scan_depth` of ``fn(*args)``'s jaxpr.  Fresh wrapper per call for
    the same trace-cache reason as `trace_model`."""

    def _fresh(*a):
        return fn(*a)

    return scan_depth(jax.make_jaxpr(_fresh)(*args))
