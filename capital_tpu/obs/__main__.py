"""CLI: ``python -m capital_tpu.obs {audit,diff} ...``

``audit`` runs a driver config through the model trace + compiled-program
audit and prints the drift report plus ONE ledger JSON record (appended to
--ledger when given); it exits non-zero on out-of-tolerance drift, so
``make audit`` is a CI gate that needs no TPU (compile-only: nothing is
executed or timed).

``diff`` compares two ledger JSONL files and exits non-zero when a measured
metric, collective count, or peak-HBM regression beyond tolerance appears;
exit 2 means the ledgers are not comparable (schema/device mismatch).

``robust-gate`` is the CI self-check for the robustness exemption: a
breakdown-recovery/failure record must pass diff un-flagged while the same
value drop WITHOUT the status still flags (docs/ROBUSTNESS.md).

``serve-report`` summarizes the serve:request_stats records of a ledger
(serve/stats.py; docs/SERVING.md) and optionally gates on cache hit-rate /
p99 latency — the second half of ``make serve-smoke``.

``lint-report`` summarizes the lint:report records of a ledger
(capital_tpu.lint CLI; docs/STATIC_ANALYSIS.md) and gates on each report's
own pass/fail outcome — the second half of ``make lint``.

``trace-report`` summarizes the phase-attribution records of a ledger
(bench:trace:* producers; bench/trace.phase_attribution) — the per-phase
wall split plus bubble_frac — and optionally gates on bubble_frac
(docs/OBSERVABILITY.md "Phase-level wall-time attribution").

``timeline`` renders the serve:trace records of a ledger (obs/spans.py;
``serve smoke --trace`` / ``loadgen --trace`` producers): per-run chain
completeness, the per-span duration split, SLO-violation attribution, and
— with ``--chrome out.json`` — a Chrome-trace-event export for
chrome://tracing / Perfetto waterfall inspection.  It exits 1 when the
ledger carries NO serve:trace records (a dead timeline never reads as a
quiet pass) and 2 on a malformed one.

Examples::

    python -m capital_tpu.obs audit cholinv --n 4096
    python -m capital_tpu.obs audit cacqr --m 65536 --n 512 --ledger runs.jsonl
    python -m capital_tpu.obs diff baseline.jsonl current.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys

import jax


def _build(algo: str, args, grid):
    """(step, operand, cfg, dtype) for one driver config — the same
    construction the bench drivers use, minus the measurement loop."""
    import jax.numpy as jnp

    from capital_tpu.bench import drivers
    from capital_tpu.models import cholesky, inverse, qr, trsm as trsm_mod
    from capital_tpu.parallel import summa

    dtype = jnp.dtype(args.dtype)
    mode = drivers._resolve_mode(args.mode, grid)
    prec = drivers._precision(args, dtype)
    if algo in ("cholinv", "spd_inverse"):
        bc = drivers.pick_bc(args.n, args.bc)
        cfg = cholesky.CholinvConfig(base_case_dim=bc, mode=mode, precision=prec)
        A = drivers._spd(args.n, dtype)
        if algo == "cholinv":
            def step(a):
                R, Rinv = cholesky.factor(grid, a, cfg)
                return R + Rinv
        else:
            def step(a):
                return cholesky.spd_inverse(grid, a, cfg)
        return step, A, cfg, dtype
    if algo == "cacqr":
        bc = drivers.pick_bc(args.n, args.bc)
        cfg = qr.CacqrConfig(
            num_iter=args.variant, regime=args.regime, mode=mode,
            cholinv=cholesky.CholinvConfig(
                base_case_dim=bc, mode=mode, precision=prec
            ),
            precision=prec,
        )
        A = jax.block_until_ready(
            jax.random.normal(jax.random.key(0), (args.m, args.n), dtype=dtype)
        )

        def step(a):
            Q, R = qr.factor(grid, a, cfg)
            return Q.at[: R.shape[0], : R.shape[1]].add(R.astype(Q.dtype))

        return step, A, cfg, dtype
    if algo == "rectri":
        bc = drivers.pick_bc(args.n, args.bc, cholinv_family=False)
        cfg = inverse.RectriConfig(base_case_dim=bc, mode=mode, precision=prec)
        L = drivers._tri_operand(args.n, dtype)

        def step(a):
            return inverse.rectri(grid, a, "L", cfg)

        return step, L, cfg, dtype
    if algo == "trsm":
        bc = drivers.pick_bc(args.n, args.bc, cholinv_family=False)
        cfg = trsm_mod.TrsmConfig(base_case_dim=bc, mode=mode, precision=prec)
        L = drivers._tri_operand(args.n, dtype)
        nrhs = min(args.m, args.n)
        B = jax.block_until_ready(
            jax.random.normal(jax.random.key(1), (args.n, nrhs), dtype=dtype)
        )

        def step(lo, b):
            return trsm_mod.solve(grid, lo, b, side="L", uplo="L", cfg=cfg)

        return step, (L, B), cfg, dtype
    if algo == "summa_gemm":
        gargs = summa.GemmArgs(precision=prec)
        A = jax.random.normal(jax.random.key(0), (args.n, args.n), dtype)

        def step(a):
            return summa.gemm(grid, a, a, args=gargs, mode=mode)

        return step, A, gargs, dtype
    raise SystemExit(f"unknown audit target {algo!r}")


def _audit(args) -> int:
    import jax.numpy as jnp  # noqa: F401  (dtype resolution inside _build)

    from capital_tpu.bench import drivers
    from capital_tpu.obs import ledger, xla_audit

    grid = drivers._grid(args)
    step, operand, cfg, dtype = _build(args.algo, args, grid)
    op_args = operand if isinstance(operand, tuple) else (operand,)
    rec = xla_audit.trace_model(step, *op_args)
    audit = xla_audit.audit(step, *op_args)
    rep = xla_audit.drift(
        audit, rec, tol_ratio=args.tol_ratio, slack=args.slack,
        flops_tol_ratio=args.flops_tol,
    )
    for line in rep.lines():
        print(f"# {line}")
    row = ledger.record(
        f"audit:{args.algo}",
        ledger.manifest(
            grid=grid, dtype=dtype, config=cfg,
            n=args.n, m=args.m, mode=drivers._resolve_mode(args.mode, grid),
        ),
        model=ledger.model_costs(rec, dtype=dtype),
        audit=audit.asdict(),
        drift=rep.asdict(),
    )
    print(json.dumps(row))
    if args.ledger:
        ledger.append(args.ledger, row)
    if not rep.ok and not args.no_strict:
        print("# drift out of tolerance (use --no-strict to report only)",
              file=sys.stderr)
        return 1
    return 0


def _robust_gate(args) -> int:
    """CI gate: a breakdown-recovery record must round-trip through
    ledger.diff WITHOUT being misread as a metric regression — and the
    exemption must be doing the work (the same records stripped of their
    robust/event blocks MUST flag).  Pure in-memory check, no device."""
    from capital_tpu.obs import ledger

    man = ledger.manifest(dtype="float32", config_id="robust_gate_probe")
    base = ledger.record(
        "bench:cacqr", dict(man),
        measured={"metric": "cacqr", "value": 100.0, "unit": "TFLOP/s"},
    )
    # a recovery run: slower by far more than any tol_metric, carrying both
    # signal shapes (the sweep's event block and the bench robust block)
    recov = ledger.record(
        "bench:cacqr", dict(man),
        measured={"metric": "cacqr", "value": 40.0, "unit": "TFLOP/s"},
        robust={"breakdown": 1, "shifted": 1, "escalated": 1, "info": 0},
        event={"status": "recovered"},
    )
    regs = ledger.diff([base], [recov])
    if regs:
        print("# robust-gate: recovery record misread as regression:",
              file=sys.stderr)
        for r in regs:
            print(r.line(), file=sys.stderr)
        return 1
    stripped = dict(recov)
    stripped.pop("robust", None)
    stripped.pop("event", None)
    if not ledger.diff([base], [stripped]):
        print("# robust-gate: value check is dead — a 60% drop without a "
              "recovery status did not flag", file=sys.stderr)
        return 1
    print("# robust-gate OK: recovery events exempt from the metric check, "
          "plain drops still flag")
    return 0


def _serve_report(args) -> int:
    """Summarize the serve records of a ledger — request_stats snapshots
    plus the serve:trace / serve:window telemetry records — with optional
    gates (the `make serve-smoke` / `make serve-trace` second half).
    Exit 2 on a malformed record, 1 on a gate failure (or gates requested
    with no records to exercise them)."""
    from capital_tpu.obs import ledger

    recs = ledger.read(args.ledger)
    rows = [r for r in recs if r.get("request_stats") is not None]
    trows = [r for r in recs if r.get("serve_trace") is not None]
    wrows = [r for r in recs if r.get("serve_window") is not None]
    srows = [r for r in recs if r.get("session_stats") is not None]
    bad = 0
    for i, r in enumerate(rows):
        for p in ledger.validate_request_stats(r["request_stats"]):
            print(f"malformed request_stats record #{i}: {p}",
                  file=sys.stderr)
            bad += 1
    for i, r in enumerate(trows):
        for p in ledger.validate_serve_trace(r["serve_trace"]):
            print(f"malformed serve_trace record #{i}: {p}",
                  file=sys.stderr)
            bad += 1
    for i, r in enumerate(wrows):
        for p in ledger.validate_serve_window(r["serve_window"]):
            print(f"malformed serve_window record #{i}: {p}",
                  file=sys.stderr)
            bad += 1
    for i, r in enumerate(srows):
        for p in ledger.validate_session_stats(r["session_stats"]):
            print(f"malformed session_stats record #{i}: {p}",
                  file=sys.stderr)
            bad += 1
    if bad:
        return 2
    gates_on = (args.min_hit_rate is not None
                or args.max_p99_ms is not None
                or args.max_p99_ms_small is not None
                or args.min_occupancy is not None
                or args.max_queue_wait_ms is not None
                or args.min_residency_hit_rate is not None
                or args.max_refine_iters is not None
                or args.min_converged_frac is not None
                or args.min_replicas is not None
                or args.min_trace_complete is not None
                or args.min_windows is not None
                or args.min_session_hit_rate is not None
                or args.max_reseeds is not None
                or args.aggregate)
    if not rows and not trows and not wrows and not srows:
        print(f"# no serve records in {args.ledger} "
              f"({len(recs)} records total)")
        return 1 if gates_on else 0
    failures = []
    small_seen = 0
    split_seen = 0
    factor_seen = 0
    refine_seen = 0
    for i, r in enumerate(rows):
        rs = r["request_stats"]
        man = r.get("manifest") or {}
        cache = rs["cache"]
        lat = rs["latency_ms"]
        lat_small = rs.get("latency_ms_small")
        qwait = rs.get("queue_wait_ms")
        fc = rs.get("factor_cache")
        fc_note = (
            f" factor_cache hits={fc['hits']} misses={fc['misses']} "
            f"evictions={fc['evictions']} degrades={fc['downdate_degrades']} "
            f"hit_rate={fc['hit_rate']:.3f}" if fc else ""
        )
        small_note = (
            f" small requests={rs.get('requests_small', 0)} "
            f"p99={lat_small['p99']}" if lat_small else ""
        )
        split_note = (
            f" queue_wait p99={qwait['p99']} "
            f"device p99={rs['device_ms']['p99']}"
            if qwait and rs.get("device_ms") else ""
        )
        # per-op request mix (Collector.ops) — includes posv_blocktri
        # since the chain op joined the serve surface
        ops = rs.get("ops")
        ops_note = (
            " ops " + " ".join(f"{k}={ops[k]}" for k in sorted(ops))
            if ops else ""
        )
        # posv_blocktri algorithm split (scan vs partitioned Spike driver
        # — Collector.blocktri_impls); absent without blocktri traffic
        bti = rs.get("blocktri_impls")
        bti_note = (
            " blocktri " + " ".join(f"{k}={bti[k]}" for k in sorted(bti))
            if bti else ""
        )
        # guaranteed-tier refinement telemetry (Collector.note_refine);
        # absent without accuracy_tier='guaranteed' traffic
        rf = rs.get("refine")
        rf_note = (
            f" refine requests={rf['requests']} "
            f"converged_frac={rf['converged_frac']} "
            f"iters_max={rf['iters_max']} resid_max={rf['resid_max']:.2e}"
            if rf else ""
        )
        print(
            f"# [{i}] {man.get('platform', '?')}/{man.get('device', '?')} "
            f"requests={rs['requests']} ok={rs['ok']} "
            f"flagged={rs['flagged']} failed={rs['failed']} "
            f"latency_ms p50={lat['p50']} p95={lat['p95']} p99={lat['p99']} "
            f"occupancy={rs['batch_occupancy_mean']} "
            f"queue_max={rs['queue_depth_max']} "
            f"cache hits={cache['hits']} misses={cache['misses']} "
            f"hit_rate={cache['hit_rate']:.3f}"
            + small_note + split_note + ops_note + bti_note + rf_note
            + fc_note
        )
        if (args.min_hit_rate is not None
                and cache["hit_rate"] < args.min_hit_rate):
            failures.append(
                f"record #{i}: hit_rate {cache['hit_rate']:.3f} < "
                f"{args.min_hit_rate}"
            )
        if args.max_p99_ms is not None and lat["p99"] > args.max_p99_ms:
            failures.append(
                f"record #{i}: p99 {lat['p99']}ms > {args.max_p99_ms}ms"
            )
        if (args.min_occupancy is not None
                and rs["batch_occupancy_mean"] < args.min_occupancy):
            failures.append(
                f"record #{i}: batch occupancy "
                f"{rs['batch_occupancy_mean']} < {args.min_occupancy} "
                "(batches flushing too empty — widen max_delay_s or the "
                "bucket ladders, or raise offered load)"
            )
        if lat_small is not None:
            small_seen += 1
            if (args.max_p99_ms_small is not None
                    and lat_small["p99"] > args.max_p99_ms_small):
                failures.append(
                    f"record #{i}: small-bucket p99 {lat_small['p99']}ms > "
                    f"{args.max_p99_ms_small}ms"
                )
        if fc is not None:
            factor_seen += 1
            if (args.min_residency_hit_rate is not None
                    and fc["hit_rate"] < args.min_residency_hit_rate):
                failures.append(
                    f"record #{i}: factor-residency hit_rate "
                    f"{fc['hit_rate']:.3f} < {args.min_residency_hit_rate} "
                    "(tokens evicted under the byte budget, or clients "
                    "updating factors that were never seeded — see "
                    "docs/SERVING.md 'Factor residency')"
                )
        if rf is not None:
            refine_seen += 1
            if (args.max_refine_iters is not None
                    and rf["iters_max"] > args.max_refine_iters):
                failures.append(
                    f"record #{i}: refine iters_max {rf['iters_max']} > "
                    f"{args.max_refine_iters} (guaranteed-tier requests "
                    "burning more correction sweeps than the latency "
                    "budget planned for — operands more ill-conditioned "
                    "than the tier's factor dtype expects?)"
                )
            if (args.min_converged_frac is not None
                    and rf["converged_frac"] < args.min_converged_frac):
                failures.append(
                    f"record #{i}: refine converged_frac "
                    f"{rf['converged_frac']} < {args.min_converged_frac} "
                    "(guaranteed-tier requests failing loudly instead of "
                    "converging — see docs/SERVING.md 'Accuracy tiers')"
                )
        if qwait is not None:
            split_seen += 1
            if (args.max_queue_wait_ms is not None
                    and qwait["p99"] > args.max_queue_wait_ms):
                failures.append(
                    f"record #{i}: queue-wait p99 {qwait['p99']}ms > "
                    f"{args.max_queue_wait_ms}ms (scheduling delay, not "
                    "device time — check flush policy / in-flight window)"
                )
    if args.max_p99_ms_small is not None and not small_seen:
        # same posture as gates-with-no-records: a requested gate that
        # nothing exercised is a silently-dead gate, so it fails loudly.
        failures.append(
            "--max-p99-ms-small requested but no record carries a "
            "latency_ms_small block (no small-bucket traffic served?)"
        )
    # per-request span traces (serve:trace records — obs/spans.py): the
    # --min-trace-complete gate reads each record's complete/requests
    # verdict, computed under the record's own pinned bubble tolerance.
    for i, r in enumerate(trows):
        st = r["serve_trace"]
        print(
            f"# trace[{i}] requests={st['requests']} "
            f"complete={st['complete']} dropped={st['dropped']} "
            f"violations={st['violations']} "
            f"bubble_tol_ms={st['bubble_tol_ms']}"
        )
    if args.min_trace_complete is not None:
        if not trows:
            failures.append(
                "--min-trace-complete requested but no record carries a "
                "serve_trace block (run the producer with --trace?)"
            )
        for i, r in enumerate(trows):
            st = r["serve_trace"]
            if st["requests"] == 0:
                failures.append(
                    f"trace record #{i}: zero traced requests — an empty "
                    "trace log can never satisfy --min-trace-complete"
                )
                continue
            frac = st["complete"] / st["requests"]
            if frac < args.min_trace_complete:
                from capital_tpu.obs import spans

                broken = [
                    t.get("request_id")
                    for t in st["traces"]
                    if spans.trace_dict_problems(t, st["bubble_tol_ms"])
                ]
                failures.append(
                    f"trace record #{i}: {st['complete']}/{st['requests']} "
                    f"chains complete ({frac:.3f} < "
                    f"{args.min_trace_complete}); incomplete request ids: "
                    f"{broken[:8]}"
                )
    # rolling windows (serve:window records — serve/telemetry.py): the
    # --min-windows gate counts RECORDS, one per closed non-empty window,
    # so it fails loudly both when telemetry was never enabled and when
    # the run was too short to close enough windows.
    if wrows:
        wreq = sum(r["serve_window"]["requests"] for r in wrows)
        worst = max(r["serve_window"]["latency_ms"]["p99"] for r in wrows)
        shed = sum(r["serve_window"]["shed"] for r in wrows)
        print(
            f"# windows: {len(wrows)} record(s) requests={wreq} "
            f"shed={shed} worst p99={worst}ms "
            f"window_s={wrows[0]['serve_window']['window_s']}"
        )
    if args.min_windows is not None and len(wrows) < args.min_windows:
        failures.append(
            f"{len(wrows)} serve_window record(s) < --min-windows "
            f"{args.min_windows} (telemetry not enabled via --window-s, "
            "or the run closed too few non-empty windows)"
        )
    # streaming-session protocol counters (serve:session_stats records —
    # serve/sessions.py SessionManager.emit_session_stats): hit_rate is
    # the fraction of resident requests that found their chain still in
    # the FactorCache, reseeds counts re-opens of evicted sessions.  Both
    # gates fail loudly when requested with no session_stats record in
    # the ledger — a gate nothing exercised is a silently-dead gate
    # (docs/SERVING.md 'Streaming sessions').
    for i, r in enumerate(srows):
        ss = r["session_stats"]
        print(
            f"# session[{i}] opens={ss['opens']} reseeds={ss['reseeds']} "
            f"appends={ss['appends']} solves={ss['solves']} "
            f"contracts={ss['contracts']} closes={ss['closes']} "
            f"failures={ss['failures']} evicted={ss['evicted_failures']} "
            f"hit_rate={ss['hit_rate']:.3f} "
            f"blocks +{ss['blocks_appended']}/-{ss['blocks_dropped']}"
        )
        if (args.min_session_hit_rate is not None
                and ss["hit_rate"] < args.min_session_hit_rate):
            failures.append(
                f"session record #{i}: session hit_rate "
                f"{ss['hit_rate']:.3f} < {args.min_session_hit_rate} "
                "(resident chains evicted under cache pressure mid-"
                "session — raise factor_cache_bytes or contract sooner; "
                "docs/SERVING.md 'Streaming sessions')"
            )
        if (args.max_reseeds is not None
                and ss["reseeds"] > args.max_reseeds):
            failures.append(
                f"session record #{i}: {ss['reseeds']} reseed(s) > "
                f"--max-reseeds {args.max_reseeds} (clients re-opening "
                "evicted sessions — each reseed re-ships and re-factors "
                "the whole window the protocol exists to avoid)"
            )
    if (args.min_session_hit_rate is not None
            or args.max_reseeds is not None) and not srows:
        failures.append(
            "--min-session-hit-rate/--max-reseeds requested but no record "
            "carries a session_stats block (no session traffic served, or "
            "the producer never called emit_session_stats?)"
        )
    # cross-replica aggregation (docs/SERVING.md "Multi-replica serving"):
    # fold every replica-TAGGED record through stats.merge_snapshots and
    # report the fleet view — summed counts, worst tail, summed router-block
    # QPS, and a per-replica occupancy table.  --min-replicas is the
    # it-really-was-multi-replica gate: it fails loudly when the ledger
    # carries fewer distinct replica tags than claimed (or none at all).
    if args.aggregate or args.min_replicas is not None:
        from capital_tpu.serve import stats as serve_stats

        tagged = [r for r in rows if r["request_stats"].get("replica_id")]
        ids = sorted({r["request_stats"]["replica_id"] for r in tagged})
        if not tagged:
            failures.append(
                "--aggregate/--min-replicas requested but no record "
                "carries a replica_id tag (single-engine ledger, or the "
                "router never emitted stats?)"
            )
        else:
            merged = serve_stats.merge_snapshots(
                [r["request_stats"] for r in tagged])
            qps = [r["router"]["qps"] for r in recs
                   if isinstance(r.get("router"), dict)
                   and isinstance(r["router"].get("qps"), (int, float))]
            qps_note = (f" qps_sum={round(sum(qps), 3)}"
                        f" (over {len(qps)} router block(s))" if qps else "")
            print(
                f"# aggregate[{len(tagged)} records, "
                f"{len(ids)} replica(s) {ids}]: "
                f"requests={merged['requests']} ok={merged['ok']} "
                f"failed={merged['failed']} "
                f"worst p99={merged['latency_ms']['p99']}ms "
                f"cache hits={merged['cache']['hits']} "
                f"misses={merged['cache']['misses']} "
                f"hit_rate={merged['cache']['hit_rate']:.3f} "
                f"compiles={merged['cache'].get('compiles', 0)}" + qps_note
            )
            for r in tagged:
                rs = r["request_stats"]
                print(
                    f"#   replica {rs['replica_id']}: "
                    f"requests={rs['requests']} batches={rs['batches']} "
                    f"occupancy={rs['batch_occupancy_mean']} "
                    f"p99={rs['latency_ms']['p99']}ms"
                )
            if (args.min_replicas is not None
                    and len(ids) < args.min_replicas):
                failures.append(
                    f"{len(ids)} distinct replica tag(s) {ids} < "
                    f"--min-replicas {args.min_replicas}"
                )
            if (args.min_hit_rate is not None
                    and merged["cache"]["hit_rate"] < args.min_hit_rate):
                # name the offenders: a fleet-level number alone sends the
                # operator hunting through every replica's log — the
                # per-replica rates say WHICH engine's cache went cold
                per = {
                    r["request_stats"]["replica_id"]:
                        r["request_stats"]["cache"]["hit_rate"]
                    for r in tagged
                }
                offenders = sorted(
                    rid for rid, hr in per.items()
                    if hr < args.min_hit_rate
                )
                per_note = " ".join(
                    f"{rid}={per[rid]:.3f}" for rid in sorted(per)
                )
                who = (str(offenders) if offenders
                       else "(none individually — the merged union "
                            "fell below the gate)")
                failures.append(
                    f"aggregate hit_rate {merged['cache']['hit_rate']:.3f} "
                    f"< {args.min_hit_rate} (per-replica: {per_note}; "
                    f"offending replica_id(s): {who})"
                )
    if args.min_residency_hit_rate is not None and not factor_seen:
        failures.append(
            "--min-residency-hit-rate requested but no record carries a "
            "factor_cache block (no factor-token traffic served?)"
        )
    if args.max_queue_wait_ms is not None and not split_seen:
        failures.append(
            "--max-queue-wait-ms requested but no record carries a "
            "queue_wait_ms block (records predate the latency split, or "
            "nothing dispatched?)"
        )
    if (args.max_refine_iters is not None
            or args.min_converged_frac is not None) and not refine_seen:
        failures.append(
            "--max-refine-iters/--min-converged-frac requested but no "
            "record carries a refine block (no accuracy_tier='guaranteed' "
            "traffic served?)"
        )
    for f in failures:
        print(f"serve-report gate FAIL: {f}", file=sys.stderr)
    if failures:
        return 1
    print(f"# serve-report OK ({len(rows)} request_stats, "
          f"{len(trows)} serve_trace, {len(wrows)} serve_window, "
          f"{len(srows)} session_stats record(s))")
    return 0


def _lint_report(args) -> int:
    """Summarize the lint:report records of a ledger (the `make lint`
    second half).  Exit 2 on a malformed record, 1 when any report's gate
    failed (or --require-pass names a pass with no record)."""
    from capital_tpu.obs import ledger

    recs = ledger.read(args.ledger)
    rows = [r for r in recs if r.get("lint_report") is not None]
    bad = 0
    for i, r in enumerate(rows):
        for p in ledger.validate_lint_report(r["lint_report"]):
            print(f"malformed lint_report record #{i}: {p}", file=sys.stderr)
            bad += 1
    if bad:
        return 2
    required = set(args.require_pass or [])
    if not rows:
        print(f"# no lint_report records in {args.ledger} "
              f"({len(recs)} records total)")
        return 1 if required else 0
    failures = []
    seen = set()
    for i, r in enumerate(rows):
        lr = r["lint_report"]
        seen.add(lr["pass"])
        counts = lr["counts"]
        print(
            f"# [{i}] pass={lr['pass']} fail_on={lr['fail_on']} "
            f"ok={lr['ok']} errors={counts['error']} warns={counts['warn']} "
            f"info={counts['info']} suppressed={lr['suppressed']}"
        )
        for f in lr["findings"]:
            print(f"#     {f['severity']} {f['rule']} {f['target']}: "
                  f"{f['message']}")
        if not lr["ok"]:
            failures.append(
                f"record #{i}: {lr['pass']} pass failed its "
                f"fail_on={lr['fail_on']} gate "
                f"({counts['error']} error(s), {counts['warn']} warn(s))"
            )
    for name in sorted(required - seen):
        failures.append(f"required pass {name!r} has no lint_report record")
    for f in failures:
        print(f"lint-report gate FAIL: {f}", file=sys.stderr)
    if failures:
        return 1
    print(f"# lint-report OK ({len(rows)} lint_report record(s))")
    return 0


def _trace_report(args) -> int:
    """Summarize the phase-attribution records of a ledger (bench:trace
    producers).  Exit 2 on a malformed phase_seconds block, 1 on a gate
    failure — including a requested gate with no records to exercise it
    (same no-silently-dead-gates posture as serve-report's split gates)."""
    from capital_tpu.obs import ledger

    recs = ledger.read(args.ledger)
    rows = [
        r for r in recs
        if isinstance(r.get("measured"), dict)
        and r["measured"].get("phase_seconds") is not None
    ]
    bad = 0
    for i, r in enumerate(rows):
        for p in ledger.validate_phase_seconds(r["measured"]):
            print(f"malformed phase attribution record #{i}: {p}",
                  file=sys.stderr)
            bad += 1
    if bad:
        return 2
    if not rows:
        print(f"# no phase_seconds records in {args.ledger} "
              f"({len(recs)} records total)")
        return 1 if args.max_bubble_frac is not None else 0
    failures = []
    for i, r in enumerate(rows):
        meas = r["measured"]
        man = r.get("manifest") or {}
        ps = meas["phase_seconds"]
        total = sum(ps.values())
        bf = meas.get("bubble_frac")
        print(
            f"# [{i}] {r.get('kind', '?')} {man.get('platform', '?')}/"
            f"{man.get('device', '?')} n={meas.get('n', '?')} "
            f"attributed={total * 1e3:.3f} ms/iter "
            f"bubble_frac={bf if bf is not None else '?'}"
        )
        for tag, v in sorted(ps.items(), key=lambda kv: -kv[1]):
            pct = 100 * v / total if total > 0 else 0.0
            print(f"#     {tag:16s} {v * 1e3:9.3f} ms/iter  {pct:5.1f}%")
        if args.max_bubble_frac is not None:
            if bf is None:
                failures.append(
                    f"record #{i}: carries phase_seconds but no bubble_frac"
                )
            elif bf > args.max_bubble_frac:
                failures.append(
                    f"record #{i}: bubble_frac {bf} > {args.max_bubble_frac} "
                    "(unattributed wall grew — see the phase split above)"
                )
    for f in failures:
        print(f"trace-report gate FAIL: {f}", file=sys.stderr)
    if failures:
        return 1
    print(f"# trace-report OK ({len(rows)} phase-attribution record(s))")
    return 0


def _timeline(args) -> int:
    """Render the serve:trace records of a ledger: per-run completeness,
    the per-span duration split, the slowest requests, SLO-violation
    attribution, and (with --chrome) the Chrome-trace-event export.  Exit
    2 on a malformed record; exit 1 when the ledger carries NO serve_trace
    records — a timeline with nothing to show is a producer wiring bug
    (--trace not passed), never a quiet pass."""
    from collections import Counter, defaultdict

    from capital_tpu.obs import ledger, spans

    recs = ledger.read(args.ledger)
    rows = [r for r in recs if r.get("serve_trace") is not None]
    bad = 0
    for i, r in enumerate(rows):
        for p in ledger.validate_serve_trace(r["serve_trace"]):
            print(f"malformed serve_trace record #{i}: {p}",
                  file=sys.stderr)
            bad += 1
    if bad:
        return 2
    if not rows:
        print(
            f"timeline: no serve_trace records in {args.ledger} "
            f"({len(recs)} records total) — run the serve producer with "
            "--trace to emit them", file=sys.stderr,
        )
        return 1
    traces = []
    for i, r in enumerate(rows):
        st = r["serve_trace"]
        print(
            f"# [{i}] requests={st['requests']} complete={st['complete']} "
            f"dropped={st['dropped']} violations={st['violations']} "
            f"bubble_tol_ms={st['bubble_tol_ms']}"
        )
        traces.extend(st["traces"])
    # where a request's life goes, per span name across every trace
    durs = defaultdict(list)
    for t in traces:
        for sp in t.get("spans", ()):
            durs[sp["name"]].append(sp["dur_ms"])
    total = sum(sum(v) for v in durs.values())
    for name in spans.CHAIN:
        if name not in durs:
            continue
        v = durs[name]
        share = 100.0 * sum(v) / total if total else 0.0
        print(
            f"#   {name:12s} n={len(v):5d} mean={sum(v) / len(v):9.3f} ms "
            f"max={max(v):9.3f} ms  {share:5.1f}%"
        )
    for t in sorted(traces, key=lambda t: -t.get("latency_ms", 0.0)
                    )[: args.top]:
        chain = " ".join(
            f"{sp['name']}={sp['dur_ms']:.3f}" for sp in t.get("spans", ())
        )
        print(
            f"#   slow request {t.get('request_id')} "
            f"[{t.get('kind')}/{t.get('op')}"
            f"{'/' + t['replica_id'] if t.get('replica_id') else ''}] "
            f"{t.get('latency_ms')}ms: {chain}"
        )
    viol = [t for t in traces if t.get("violated")]
    if viol:
        attr = Counter(str(t.get("attribution")) for t in viol)
        print(
            f"#   SLO violations: {len(viol)}/{len(traces)} — attribution "
            + " ".join(f"{k}={n}" for k, n in attr.most_common())
        )
    if args.chrome:
        chrome = spans.to_chrome(traces)
        with open(args.chrome, "w") as f:
            json.dump(chrome, f)
        print(
            f"# chrome trace: {len(chrome['traceEvents'])} events -> "
            f"{args.chrome} (open in chrome://tracing or "
            "https://ui.perfetto.dev)"
        )
    print(f"# timeline OK ({len(rows)} serve_trace record(s), "
          f"{len(traces)} trace(s))")
    return 0


def _diff(args) -> int:
    from capital_tpu.obs import ledger

    a = ledger.read(args.a)
    b = ledger.read(args.b)
    try:
        regs = ledger.diff(
            a, b, tol_metric=args.tol_metric, tol_hbm=args.tol_hbm,
            tol_collective=args.tol_collective,
        )
    except ledger.LedgerIncompatible as e:
        print(f"incomparable ledgers: {e}", file=sys.stderr)
        return 2
    for r in regs:
        print(r.line())
    if regs:
        return 1
    print(f"# no regressions ({len(a)} vs {len(b)} records)")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="capital_tpu.obs")
    sub = p.add_subparsers(dest="cmd", required=True)

    a = sub.add_parser("audit", help="model-vs-compiled drift check")
    a.add_argument(
        "algo",
        choices=["cholinv", "cacqr", "rectri", "trsm", "spd_inverse",
                 "summa_gemm"],
    )
    a.add_argument("--n", type=int, default=4096)
    a.add_argument("--m", type=int, default=65536)
    a.add_argument("--bc", type=int, default=0)
    a.add_argument("--dtype", default="bfloat16")
    a.add_argument("--mode", default="auto",
                   choices=["auto", "xla", "explicit", "pallas"])
    a.add_argument("--variant", type=int, default=2)
    a.add_argument("--regime", default="auto", choices=["auto", "1d", "dist"])
    a.add_argument("--c", type=int, default=1)
    a.add_argument("--devices", type=int, default=0)
    a.add_argument("--layout", type=int, default=0, choices=[0, 1, 2])
    a.add_argument("--chunks", type=int, default=0)
    a.add_argument("--precision", default=None,
                   choices=["default", "high", "highest"])
    a.add_argument("--ledger", default=None,
                   help="append the record to this JSONL ledger")
    a.add_argument("--tol-ratio", type=float, default=4.0,
                   help="per-phase compiled/model collective allowance")
    a.add_argument("--slack", type=int, default=8,
                   help="absolute per-phase collective allowance")
    a.add_argument("--flops-tol", type=float, default=2.0,
                   help="whole-program flops ratio allowance")
    a.add_argument("--no-strict", action="store_true",
                   help="report drift without failing the process")
    a.add_argument("--platform", default=None)
    a.add_argument("--host-devices", type=int, default=0)
    a.set_defaults(fn=_audit)

    d = sub.add_parser("diff", help="compare two ledger JSONL files")
    d.add_argument("a")
    d.add_argument("b")
    d.add_argument("--tol-metric", type=float, default=0.10)
    d.add_argument("--tol-hbm", type=float, default=0.05)
    d.add_argument("--tol-collective", type=int, default=0)
    d.set_defaults(fn=_diff)

    s = sub.add_parser(
        "serve-report",
        help="summarize serve request_stats records (optional gates)",
    )
    s.add_argument("ledger")
    s.add_argument("--min-hit-rate", type=float, default=None,
                   help="fail unless every record's cache hit_rate >= this")
    s.add_argument("--max-p99-ms", type=float, default=None,
                   help="fail when any record's p99 latency exceeds this")
    s.add_argument("--min-occupancy", type=float, default=None,
                   help="gate: fail when any record's batch_occupancy_mean "
                        "falls below this (batches flushing too empty)")
    s.add_argument("--max-queue-wait-ms", type=float, default=None,
                   help="gate: fail when any record's queue_wait_ms.p99 "
                        "exceeds this; fails loudly when no record carries "
                        "the queue-wait/device latency split")
    s.add_argument("--min-residency-hit-rate", type=float, default=None,
                   help="fail when any record's factor_cache.hit_rate "
                   "(serve/factorcache.py residency counters) is below "
                   "this; fails loudly when NO record carries the block")
    s.add_argument("--max-refine-iters", type=int, default=None,
                   help="gate: fail when any record's refine.iters_max "
                        "(guaranteed-tier correction sweeps, "
                        "Collector.note_refine) exceeds this; fails loudly "
                        "when NO record carries the refine block")
    s.add_argument("--min-converged-frac", type=float, default=None,
                   help="gate: fail when any record's refine.converged_frac "
                        "is below this; fails loudly when NO record "
                        "carries the refine block")
    s.add_argument("--max-p99-ms-small", type=float, default=None,
                   help="gate the small-N bucket latency split separately: "
                        "fail when any record's latency_ms_small.p99 "
                        "exceeds this, or when no record carries the split")
    s.add_argument("--aggregate", action="store_true",
                   help="fold replica-tagged records through "
                        "stats.merge_snapshots and report the fleet view "
                        "(summed counts + router-block QPS, worst tail, "
                        "per-replica occupancy); fails loudly when no "
                        "record carries a replica_id tag")
    s.add_argument("--min-replicas", type=int, default=None,
                   help="fail unless the ledger carries at least this many "
                        "distinct replica_id tags (the it-really-was-"
                        "multi-replica gate for make serve-replicas)")
    s.add_argument("--min-trace-complete", type=float, default=None,
                   metavar="FRAC",
                   help="fail unless every serve_trace record's "
                        "complete/requests fraction >= this (1.0 = every "
                        "span chain complete under the record's pinned "
                        "bubble tolerance); fails loudly when no record "
                        "carries a serve_trace block or it is empty")
    s.add_argument("--min-windows", type=int, default=None,
                   help="fail unless the ledger carries at least this many "
                        "serve_window records (one per closed non-empty "
                        "telemetry window); fails loudly when telemetry "
                        "was never enabled")
    s.add_argument("--min-session-hit-rate", type=float, default=None,
                   help="fail when any session_stats record's hit_rate "
                        "(serve/sessions.py resident-chain residency) is "
                        "below this; fails loudly when NO record carries "
                        "a session_stats block")
    s.add_argument("--max-reseeds", type=int, default=None,
                   help="fail when any session_stats record counts more "
                        "than this many reseeds (re-opens of evicted "
                        "sessions); fails loudly when NO record carries "
                        "a session_stats block")
    s.set_defaults(fn=_serve_report)

    lr = sub.add_parser(
        "lint-report",
        help="summarize lint:report records (gate on per-pass outcomes)",
    )
    lr.add_argument("ledger")
    lr.add_argument("--require-pass", action="append", default=None,
                    metavar="PASS",
                    help="fail unless a record for this pass exists "
                         "(repeatable: program, source, concurrency)")
    lr.set_defaults(fn=_lint_report)

    tr = sub.add_parser(
        "trace-report",
        help="summarize phase-attribution records (per-phase wall split "
             "+ bubble_frac, optional gate)",
    )
    tr.add_argument("ledger")
    tr.add_argument("--max-bubble-frac", type=float, default=None,
                    help="fail when any record's bubble_frac exceeds this, "
                         "or when no record carries phase_seconds at all")
    tr.set_defaults(fn=_trace_report)

    tl = sub.add_parser(
        "timeline",
        help="render serve:trace span records (per-span split, slowest "
             "requests, SLO attribution, optional Chrome-trace export)",
    )
    tl.add_argument("ledger")
    tl.add_argument("--chrome", default=None, metavar="OUT.json",
                    help="write the traces as Chrome-trace-event JSON "
                         "(chrome://tracing / Perfetto)")
    tl.add_argument("--top", type=int, default=3,
                    help="print the N slowest requests' full span chains")
    tl.set_defaults(fn=_timeline)

    g = sub.add_parser(
        "robust-gate",
        help="verify recovery/failure events round-trip through diff "
             "without reading as metric regressions",
    )
    g.add_argument("--platform", default=None)
    g.add_argument("--host-devices", type=int, default=0)
    g.set_defaults(fn=_robust_gate)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "host_devices", 0):
        import os

        flags = [
            f
            for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(
            f"--xla_force_host_platform_device_count={args.host_devices}"
        )
        os.environ["XLA_FLAGS"] = " ".join(flags)
    if getattr(args, "platform", None):
        jax.config.update("jax_platforms", args.platform)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
