"""Per-request span tracing for the serve tier.

Every request the SolveEngine admits carries a `RequestTrace`: an ordered
chain of monotonic-clock spans covering the request's whole life —

    admit -> enqueue -> cache_lookup -> batch_form -> device
          [-> refine] -> respond

`admit` is validation + fault tap + pad + stage (submit() entry to
scheduler admission); `enqueue` is time parked in the bucket queue until a
flush starts; `cache_lookup` is executable resolution (near-zero on a
cache hit — a compile shows up HERE, which is exactly the attribution the
zero-recompile gates want); `batch_form` is assemble + async dispatch
issue; `device` is dispatch to landing (`jax.block_until_ready`
observed); `refine` is the landing sink when one ran (guaranteed-tier
refinement bookkeeping, factor installs, arrowhead re-pack); `respond` is
Response construction + stats stamping.  Oversize singles skip the
queue/batch spans (kind "single"), never-dispatched failures collapse to
admit -> respond (kind "failed").

Everything here is HOST-side pure Python — `time.monotonic()` stamps
around the dispatch path, never a device sync (the lint no-host-sync rule
pins that via the ``serve_traced`` ProgramTarget), and the module imports
neither jax nor numpy so the host-only router/replica modules can carry
trace dicts freely.

The ledger surface is the schema-tagged ``serve:trace`` record (one per
run, `build_block`/`emit`): per-trace tags (bucket/op/tier/replica/
cfg-hash), per-span start/duration, completeness + monotonicity verdicts
under a pinned bubble tolerance, and — when the request carried a
``deadline_ms`` — slack-at-dispatch and SLO-violation *attribution* (the
span that ate the budget), the signal ROADMAP item 3's shed/downgrade
policy keys on.  `to_chrome` exports the same traces as Chrome-trace-event
JSON (``obs timeline RUNS.jsonl --chrome out.json``) for waterfall
inspection in chrome://tracing or Perfetto.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

#: The full span vocabulary, in chain order.  Validation rejects names
#: outside it and out-of-order stamping within it.
CHAIN = ("admit", "enqueue", "cache_lookup", "batch_form", "device",
         "refine", "respond")

#: Required sub-chain per trace kind.  "refine" is optional everywhere
#: (present only when a landing sink ran).
REQUIRED = {
    "batched": ("admit", "enqueue", "cache_lookup", "batch_form",
                "device", "respond"),
    "single": ("admit", "cache_lookup", "device", "respond"),
    "failed": ("admit", "respond"),
    # host-side session administrative ops (session_contract/close):
    # residency resolves on the host, nothing is dispatched — the chain
    # collapses to the lookup (docs/SERVING.md 'Streaming sessions')
    "session": ("admit", "cache_lookup", "respond"),
}

#: Pinned bubble tolerance: the largest host-side gap (seconds of
#: un-spanned time between consecutive spans) a chain may carry and still
#: count as complete.  Spans are stamped contiguously (each starts where
#: the previous ended), so real gaps only appear when a stamping site is
#: missed or the host stalls between stamps — 25 ms absorbs GC pauses on
#: a loaded CPU rig while still catching a dropped span site.
DEFAULT_BUBBLE_TOL_MS = 25.0

#: Allowance for the float rounding `asdict` applies (µs-scale), used by
#: the overlap check — NOT a gap budget.
_OVERLAP_EPS_S = 1e-5

#: Default bound on traces a TraceLog retains (oldest dropped first, with
#: a visible `dropped` counter) — bounded memory for long-running
#: replicas, comfortably above any smoke/loadgen run's request count.
DEFAULT_TRACE_CAP = 4096


class Span:
    """One contiguous phase of a request's life, on the monotonic clock."""

    __slots__ = ("name", "t_start", "t_end")

    def __init__(self, name: str, t_start: float, t_end: float):
        self.name = name
        self.t_start = t_start
        self.t_end = t_end

    @property
    def dur_s(self) -> float:
        return self.t_end - self.t_start

    def __repr__(self) -> str:  # debugging aid only
        return f"Span({self.name!r}, {self.dur_s * 1e3:.3f}ms)"


class RequestTrace:
    """The span chain + tags for one request.

    Stamping contract: `extend(name)` appends a span running from the
    previous span's end (or `t_enq` for the first) to now — the serve
    path stamps chains contiguously, so chain gaps measure *missed
    stamping sites*, not scheduling (scheduling time lives INSIDE the
    enqueue/device spans).  `span(name, t0, t1)` exists for explicit
    intervals (tests, replay)."""

    __slots__ = ("request_id", "op", "kind", "t_enq", "deadline_ms",
                 "tags", "spans")

    def __init__(self, request_id: int, op: str, t_enq: float, *,
                 deadline_ms: Optional[float] = None, **tags):
        self.request_id = request_id  # guarded-by: <frozen>
        self.op = op  # guarded-by: <frozen>
        self.kind = "batched"  # guarded-by: <owner-thread>  (rewritten by the single/failed routes)
        self.t_enq = t_enq  # guarded-by: <frozen>
        self.deadline_ms = deadline_ms  # guarded-by: <frozen>
        # bucket / tier / replica_id / cfg_hash ride here (str or None)
        self.tags = {k: v for k, v in tags.items() if v is not None}  # guarded-by: <owner-thread>
        self.spans: list[Span] = []  # guarded-by: <owner-thread>

    # ---- stamping ----------------------------------------------------------

    def tag(self, **kv) -> None:
        for k, v in kv.items():
            if v is not None:
                self.tags[k] = v

    @property
    def last_end(self) -> float:
        return self.spans[-1].t_end if self.spans else self.t_enq

    def span(self, name: str, t_start: float, t_end: float) -> None:
        self.spans.append(Span(name, t_start, t_end))

    def extend(self, name: str, t_end: Optional[float] = None) -> None:
        t_end = time.monotonic() if t_end is None else t_end
        self.spans.append(Span(name, self.last_end, t_end))

    # ---- derived signals ---------------------------------------------------

    @property
    def latency_ms(self) -> float:
        return (self.last_end - self.t_enq) * 1e3

    def _device_start(self) -> Optional[float]:
        for sp in self.spans:
            if sp.name == "device":
                return sp.t_start
        return None

    @property
    def slack_at_dispatch_ms(self) -> Optional[float]:
        """Deadline budget left when the request hit the device — the
        number a deadline-aware scheduler sheds/downgrades on.  None
        without a deadline or before dispatch."""
        d0 = self._device_start()
        if self.deadline_ms is None or d0 is None:
            return None
        return self.deadline_ms - (d0 - self.t_enq) * 1e3

    @property
    def violated(self) -> bool:
        return (self.deadline_ms is not None
                and self.latency_ms > self.deadline_ms)

    @property
    def attribution(self) -> Optional[str]:
        """Which span ate the budget: the longest one, reported only for
        violated requests (attribution of a met deadline is noise)."""
        if not self.violated or not self.spans:
            return None
        return max(self.spans, key=lambda sp: sp.dur_s).name

    # ---- validation --------------------------------------------------------

    def problems(self, bubble_tol_ms: float = DEFAULT_BUBBLE_TOL_MS
                 ) -> list[str]:
        return _chain_problems(
            [(sp.name, sp.t_start, sp.t_end) for sp in self.spans],
            self.kind, self.t_enq, bubble_tol_ms,
        )

    def complete(self, bubble_tol_ms: float = DEFAULT_BUBBLE_TOL_MS
                 ) -> bool:
        return not self.problems(bubble_tol_ms)

    # ---- export ------------------------------------------------------------

    def asdict(self) -> dict:
        """The per-trace dict inside a ``serve:trace`` record (also the
        wire form a replica marshals back to the router).  Times stay on
        the monotonic clock — CLOCK_MONOTONIC is shared across processes
        on one host, so replica traces normalize alongside engine ones at
        export time."""
        return {
            "request_id": int(self.request_id),
            "op": self.op,
            "kind": self.kind,
            "bucket": self.tags.get("bucket"),
            "tier": self.tags.get("tier"),
            "replica_id": self.tags.get("replica_id"),
            "cfg_hash": self.tags.get("cfg_hash"),
            "deadline_ms": self.deadline_ms,
            "t_enq_s": round(self.t_enq, 6),
            "latency_ms": round(self.latency_ms, 4),
            "slack_at_dispatch_ms": (
                round(self.slack_at_dispatch_ms, 4)
                if self.slack_at_dispatch_ms is not None else None
            ),
            "violated": bool(self.violated),
            "attribution": self.attribution,
            "spans": [
                {"name": sp.name, "t_start_s": round(sp.t_start, 6),
                 "dur_ms": round(max(0.0, sp.dur_s) * 1e3, 4)}
                for sp in self.spans
            ],
        }


def _chain_problems(spans: list[tuple], kind: str, t_enq: float,
                    bubble_tol_ms: float) -> list[str]:
    """Shared chain validation over (name, t_start, t_end) triples —
    RequestTrace objects and ledger trace dicts both route here, so the
    in-run gate and `ledger.validate_serve_trace` can never disagree."""
    probs: list[str] = []
    if kind not in REQUIRED:
        return [f"unknown trace kind {kind!r}"]
    if not spans:
        return [f"empty span chain (kind {kind!r})"]
    names = [n for n, _, _ in spans]
    for n in names:
        if n not in CHAIN:
            probs.append(f"unknown span name {n!r}")
    order = [CHAIN.index(n) for n in names if n in CHAIN]
    if order != sorted(order):
        probs.append(f"span names out of chain order: {names}")
    it = iter(names)
    if not all(req in it for req in REQUIRED[kind]):
        probs.append(
            f"incomplete chain for kind {kind!r}: have {names}, need "
            f"{list(REQUIRED[kind])}"
        )
    tol_s = bubble_tol_ms / 1e3
    prev_end = t_enq
    for name, t0, t1 in spans:
        if t1 < t0 - _OVERLAP_EPS_S:
            probs.append(f"span {name!r} ends before it starts "
                         f"({t1:.6f} < {t0:.6f})")
        if t0 < prev_end - _OVERLAP_EPS_S:
            probs.append(
                f"span {name!r} starts at {t0:.6f}, before the previous "
                f"span ended ({prev_end:.6f}) — non-monotonic chain"
            )
        gap = t0 - prev_end
        if gap > tol_s:
            probs.append(
                f"{gap * 1e3:.3f} ms un-spanned gap before {name!r} "
                f"exceeds the {bubble_tol_ms} ms bubble tolerance"
            )
        prev_end = max(prev_end, t1)
    return probs


def trace_dict_problems(t: dict,
                        bubble_tol_ms: float = DEFAULT_BUBBLE_TOL_MS
                        ) -> list[str]:
    """Structural + chain validation of one exported trace dict (the
    `traces` entries of a ``serve:trace`` block).  Returns problem
    strings, [] when valid — the obs.ledger validator convention."""
    probs: list[str] = []
    if not isinstance(t, dict):
        return [f"trace entry is {type(t).__name__}, not a dict"]
    if not isinstance(t.get("request_id"), int):
        probs.append(f"request_id {t.get('request_id')!r} is not an int")
    if not isinstance(t.get("op"), str):
        probs.append(f"op {t.get('op')!r} is not a string")
    spans = t.get("spans")
    if not isinstance(spans, list):
        return probs + [f"spans is {type(spans).__name__}, not a list"]
    triples = []
    for i, sp in enumerate(spans):
        if not isinstance(sp, dict):
            probs.append(f"spans[{i}] is not a dict")
            continue
        name, t0, dur = sp.get("name"), sp.get("t_start_s"), sp.get("dur_ms")
        if not isinstance(name, str):
            probs.append(f"spans[{i}].name {name!r} is not a string")
            continue
        if not isinstance(t0, (int, float)) \
                or not isinstance(dur, (int, float)):
            probs.append(f"span {name!r} has non-numeric timing "
                         f"(t_start_s={t0!r}, dur_ms={dur!r})")
            continue
        if dur < 0:
            probs.append(f"span {name!r} has negative duration {dur}")
            continue
        triples.append((name, float(t0), float(t0) + float(dur) / 1e3))
    if not probs:
        t_enq = t.get("t_enq_s")
        t_enq = float(t_enq) if isinstance(t_enq, (int, float)) else (
            triples[0][1] if triples else 0.0)
        probs.extend(_chain_problems(triples, t.get("kind", "batched"),
                                     t_enq, bubble_tol_ms))
    dl = t.get("deadline_ms")
    if dl is not None and not isinstance(dl, (int, float)):
        probs.append(f"deadline_ms {dl!r} is not numeric")
    return probs


class TraceLog:
    """Bounded accumulator of a run's traces.  The engine `start()`s one
    RequestTrace per submitted request; a router `add()`s the already-
    exported dicts its replicas marshal back.  Oldest traces drop first
    past `cap`, counted visibly (`dropped`) so a truncated export can
    never read as a complete run."""

    def __init__(self, cap: int = DEFAULT_TRACE_CAP):
        if cap < 1:
            raise ValueError(f"trace cap must be >= 1, got {cap}")
        # single-owner by default; the Router shares ONE TraceLog between
        # its pump thread and client threads and guards every call with
        # its RLock (see serve/router.py emit_trace)
        self.cap = cap  # guarded-by: <frozen>
        self.total = 0  # guarded-by: <owner-thread>
        self._traces: deque = deque(maxlen=cap)  # guarded-by: <owner-thread>

    def start(self, request_id: int, op: str, t_enq: float, *,
              deadline_ms: Optional[float] = None, **tags) -> RequestTrace:
        tr = RequestTrace(request_id, op, t_enq,
                          deadline_ms=deadline_ms, **tags)
        self.total += 1
        self._traces.append(tr)
        return tr

    def add(self, trace_dict: dict) -> None:
        self.total += 1
        self._traces.append(trace_dict)

    @property
    def dropped(self) -> int:
        return self.total - len(self._traces)

    def trace_dicts(self) -> list[dict]:
        return [t.asdict() if isinstance(t, RequestTrace) else dict(t)
                for t in self._traces]

    def __len__(self) -> int:
        return len(self._traces)

    def block(self, bubble_tol_ms: float = DEFAULT_BUBBLE_TOL_MS) -> dict:
        return build_block(self.trace_dicts(), bubble_tol_ms=bubble_tol_ms,
                           dropped=self.dropped)

    def emit(self, path: Optional[str] = None, *, grid=None, config=None,
             bubble_tol_ms: float = DEFAULT_BUBBLE_TOL_MS,
             **extra) -> dict:
        """One schema-tagged ``serve:trace`` ledger record carrying the
        whole log (appended to `path` when given) — same manifest
        discipline as serve:request_stats."""
        from capital_tpu.obs import ledger

        rec = ledger.record(
            "serve:trace",
            ledger.manifest(grid=grid, config=config),
            serve_trace=self.block(bubble_tol_ms),
            **extra,
        )
        if path:
            ledger.append(path, rec)
        return rec


def build_block(trace_dicts: list[dict], *,
                bubble_tol_ms: float = DEFAULT_BUBBLE_TOL_MS,
                dropped: int = 0) -> dict:
    """The ``serve_trace`` record block: the traces plus the aggregate
    verdicts the gates read (complete count under the pinned bubble
    tolerance, SLO violations)."""
    from capital_tpu.obs.ledger import SCHEMA_VERSION

    complete = sum(
        1 for t in trace_dicts if not trace_dict_problems(t, bubble_tol_ms)
    )
    return {
        "schema_version": SCHEMA_VERSION,
        "bubble_tol_ms": float(bubble_tol_ms),
        "requests": len(trace_dicts),
        "complete": complete,
        "dropped": int(dropped),
        "violations": sum(1 for t in trace_dicts if t.get("violated")),
        "traces": trace_dicts,
    }


def to_chrome(trace_dicts: list[dict]) -> dict:
    """Chrome-trace-event JSON (the chrome://tracing / Perfetto format):
    one complete ("ph": "X") event per span, requests as threads, engines/
    replicas as named processes, timestamps normalized to the earliest
    span.  Deadline signals ride the event args so the waterfall shows
    which span ate a violated request's budget."""
    events: list[dict] = []
    pids: dict[str, int] = {}
    t0 = min(
        (sp["t_start_s"] for t in trace_dicts for sp in t.get("spans", ())
         if isinstance(sp.get("t_start_s"), (int, float))),
        default=0.0,
    )
    for t in trace_dicts:
        label = t.get("replica_id") or "engine"
        if label not in pids:
            pids[label] = len(pids) + 1
            events.append({
                "ph": "M", "name": "process_name", "pid": pids[label],
                "tid": 0, "args": {"name": f"serve:{label}"},
            })
        pid = pids[label]
        args = {
            "op": t.get("op"), "kind": t.get("kind"),
            "bucket": t.get("bucket"), "tier": t.get("tier"),
            "cfg_hash": t.get("cfg_hash"),
            "deadline_ms": t.get("deadline_ms"),
            "slack_at_dispatch_ms": t.get("slack_at_dispatch_ms"),
            "violated": t.get("violated", False),
            "attribution": t.get("attribution"),
        }
        for sp in t.get("spans", ()):
            events.append({
                "ph": "X",
                "name": sp["name"],
                "cat": str(t.get("op")),
                "ts": round((sp["t_start_s"] - t0) * 1e6, 3),
                "dur": round(sp["dur_ms"] * 1e3, 3),
                "pid": pid,
                "tid": int(t.get("request_id", 0)),
                "args": args,
            })
    return {"displayTimeUnit": "ms", "traceEvents": events}
