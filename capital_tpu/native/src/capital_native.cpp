// capital_native: host-side native engine for the capital-tpu framework.
//
// The reference (tbennun/capital) is header-only C++ end to end; on TPU the
// compute path belongs to XLA/Pallas, and what remains host-side native is:
//
//   1. the data engine — deterministic coordinate-seeded matrix fillers
//      (reference src/matrix/structure.hpp:68-130) and the block/cyclic +
//      packed-triangular repacks (src/util/util.hpp:56-230,
//      src/matrix/serialize.h) used at the import/export boundary;
//   2. the schedule planner — an alpha-beta cost evaluator over the cholinv
//      recursion plan (the predictive half of the reference's autotune
//      sweeps, autotune/*/tune.cpp), searching (policy, base-case) spaces
//      before any measurement runs.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).  All matrix
// buffers are row-major contiguous doubles; the Python layer owns
// allocation.  Compile: g++ -O3 -std=c++17 -shared -fPIC [-fopenmp].

#include <cstdint>
#include <cmath>
#include <cstring>
#include <algorithm>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// --------------------------------------------------------------------------
// rand48 / splitmix64 primitives (bit-parity with utils/rand48.py)
// --------------------------------------------------------------------------

static inline double drand48_from_seed(uint64_t seed) {
  // POSIX rand48: X = (seed<<16)|0x330E; X' = (a*X + c) mod 2^48; X'/2^48.
  const uint64_t A = 0x5DEECE66DULL, C = 0xBULL, MASK = (1ULL << 48) - 1;
  uint64_t x = ((seed << 16) | 0x330EULL) & MASK;
  x = (A * x + C) & MASK;
  return (double)x / 281474976710656.0;  // 2^48
}

static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// Symmetric SPD-ready filler: element (r, c) seeded with
// max(r,c) + n*min(r,c); +n on the diagonal when diag_dom (reference
// distribute_symmetric, structure.hpp:68-105).  Fills the [r0,r1) x [c0,c1)
// sub-block into `out` (row-major (r1-r0) x (c1-c0)).
void fill_symmetric(double* out, int64_t n, int64_t r0, int64_t r1,
                    int64_t c0, int64_t c1, int32_t diag_dom) {
  const int64_t cols = c1 - c0;
#pragma omp parallel for schedule(static)
  for (int64_t r = r0; r < r1; ++r) {
    double* row = out + (r - r0) * cols;
    for (int64_t c = c0; c < c1; ++c) {
      uint64_t lo = (uint64_t)std::min(r, c), hi = (uint64_t)std::max(r, c);
      double v = drand48_from_seed(hi + (uint64_t)n * lo);
      if (diag_dom && r == c) v += (double)n;
      row[c - c0] = v;
    }
  }
}

// Grid-independent uniform filler (utils/rand48.py `random`): coordinate
// seed -> splitmix64 -> top 53 bits -> [0,1).
void fill_random(double* out, int64_t m, int64_t n, uint64_t key,
                 int64_t r0, int64_t r1, int64_t c0, int64_t c1) {
  const int64_t cols = c1 - c0;
  const uint64_t base =
      splitmix64(splitmix64(key) ^ (((uint64_t)m << 32) | (uint64_t)n));
#pragma omp parallel for schedule(static)
  for (int64_t r = r0; r < r1; ++r) {
    double* row = out + (r - r0) * cols;
    for (int64_t c = c0; c < c1; ++c) {
      uint64_t s = base + (uint64_t)r * (uint64_t)n + (uint64_t)c;
      row[c - c0] = (double)(splitmix64(s) >> 11) / 9007199254740992.0;  // 2^53
    }
  }
}

// --------------------------------------------------------------------------
// layout repacks (reference util.hpp:56-230 / serialize.h; row-major here)
// --------------------------------------------------------------------------

// blocked[(x,y) tile-major, tiles (M/dx) x (N/dy)] -> natural global order,
// where tile (x, y) holds the elements of the element-cyclic distribution:
// global (i, j) lives at tile (i % dx, j % dy), local (i / dx, j / dy).
void block_to_cyclic(const double* blocked, double* cyclic, int64_t M,
                     int64_t N, int64_t dx, int64_t dy) {
  const int64_t m = M / dx, n = N / dy;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < M; ++i) {
    const int64_t x = i % dx, k = i / dx;
    for (int64_t j = 0; j < N; ++j) {
      const int64_t y = j % dy, l = j / dy;
      cyclic[i * N + j] = blocked[(x * m + k) * N + (y * n + l)];
    }
  }
}

void cyclic_to_block(const double* cyclic, double* blocked, int64_t M,
                     int64_t N, int64_t dx, int64_t dy) {
  const int64_t m = M / dx, n = N / dy;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < M; ++i) {
    const int64_t x = i % dx, k = i / dx;
    for (int64_t j = 0; j < N; ++j) {
      const int64_t y = j % dy, l = j / dy;
      blocked[(x * m + k) * N + (y * n + l)] = cyclic[i * N + j];
    }
  }
}

// Column-packed triangular storage (reference structure.h:37-72): upper
// column j contributes rows 0..j; lower column j contributes rows j..n-1.
void pack_upper(const double* A, double* packed, int64_t n) {
  int64_t w = 0;
  for (int64_t j = 0; j < n; ++j)
    for (int64_t i = 0; i <= j; ++i) packed[w++] = A[i * n + j];
}

void unpack_upper(const double* packed, double* A, int64_t n) {
  std::memset(A, 0, sizeof(double) * n * n);
  int64_t w = 0;
  for (int64_t j = 0; j < n; ++j)
    for (int64_t i = 0; i <= j; ++i) A[i * n + j] = packed[w++];
}

void pack_lower(const double* A, double* packed, int64_t n) {
  int64_t w = 0;
  for (int64_t j = 0; j < n; ++j)
    for (int64_t i = j; i < n; ++i) packed[w++] = A[i * n + j];
}

void unpack_lower(const double* packed, double* A, int64_t n) {
  std::memset(A, 0, sizeof(double) * n * n);
  int64_t w = 0;
  for (int64_t j = 0; j < n; ++j)
    for (int64_t i = j; i < n; ++i) A[i * n + j] = packed[w++];
}

// --------------------------------------------------------------------------
// schedule planner: alpha-beta cost of the cholinv recursion
// --------------------------------------------------------------------------
//
// Walks the same plan the Python side traces (models/cholesky.py plan():
// window w splits at n1 = max(bc, w >> split) until w <= bc) and accumulates
// the model of utils/tracing.py: per distributed matmul, SUMMA-schedule
// flops/comm (gemm_cost); per base case, redundant potrf+trtri flops plus
// the replication collective (replicate_cost).  Units: seconds, via
// (peak_flops, bw_Bps, alpha_s).

struct Cost { double flops, comm, ncoll, copy; };

static inline double ring_bytes(double bytes, int64_t p) {
  return p > 1 ? bytes * (double)(p - 1) / (double)p : 0.0;
}
static inline double allreduce_bytes(double bytes, int64_t p) {
  return p > 1 ? 2.0 * bytes * (double)(p - 1) / (double)p : 0.0;
}

// SUMMA gemm model (tracing.gemm_cost): C[M,N] += A[M,K]B[K,N].
// Mirrors the explicit schedule's two distribution encodings
// (parallel/summa.py:_explicit_matmul): c == 1 amortized ring all_gathers;
// c > 1 per-step masked-psum broadcasts of the layer's d/c panels.
static Cost gemm_cost(int64_t M, int64_t N, int64_t K, int64_t dx, int64_t dy,
                      int64_t c, int64_t item, double tri_frac,
                      int64_t num_chunks) {
  const int64_t p = dx * dy * c;
  const int64_t d = std::max(dx, dy);
  Cost r;
  r.flops = tri_frac * 2.0 * (double)M * N * K / (double)p;
  double c_blk = ((double)M / dx) * ((double)N / dy) * item;
  if (c <= 1) {
    double a_row = ((double)M / dx) * (double)K * item;
    double b_col = (double)K * ((double)N / dy) * item;
    r.comm = ring_bytes(a_row, dy) + ring_bytes(b_col, dx);
    r.ncoll = (dy > 1 ? 1.0 : 0.0) + (dx > 1 ? 1.0 : 0.0);
  } else {
    const int64_t steps = std::max<int64_t>(1, d / c);
    double a_pan = ((double)M / dx) * ((double)K / d) * item;
    double b_pan = ((double)K / d) * ((double)N / dy) * item;
    r.comm = steps * (allreduce_bytes(a_pan, dy) + allreduce_bytes(b_pan, dx));
    r.ncoll = steps * ((dy > 1 ? 1.0 : 0.0) + (dx > 1 ? 1.0 : 0.0));
  }
  r.comm += allreduce_bytes(c_blk, c);
  r.ncoll += c > 1 ? 1.0 : 0.0;
  // num_chunks pipelining (the reference's Ibcast/Iallreduce slices,
  // summa.hpp:196-248): same bytes, q-fold more collective launches --
  // the alpha term is where chunking costs (and where overlap pays; the
  // model prices the launches, XLA's scheduler owns the overlap)
  if (num_chunks > 1) r.ncoll *= (double)num_chunks;
  return r;
}

static void add(Cost* acc, Cost c) {
  acc->flops += c.flops; acc->comm += c.comm; acc->ncoll += c.ncoll;
}

// Schedule-inserted HBM motion in ELEMENTS (caller multiplies by item),
// mirroring tracing's copy_bytes emissions (parallel/summa.py; 2.0 = one
// read + one write of the moved array).  A single device rides the
// copy-free aliasing kernels: no copy term at all.
static inline void add_copy(Cost* acc, int64_t p, int64_t item, double elems) {
  if (p > 1) acc->copy += elems * (double)item;
}

// Recursion over the window; mirrors plan()/_recurse() phase structure.
// `balance`: 0 = materializing block schedule (take_triangle masks, window
// slices, whole-buffer dynamic_update_slice round-trips), 1 = persistent
// tile-cyclic layout (band-sized residual motion; the lifetime permutes
// are priced by the caller on the comm side).
static void cholinv_walk(int64_t w, int64_t bc, int64_t split, int64_t dx,
                         int64_t dy, int64_t c, int64_t item, int32_t policy,
                         int32_t complete_inv, int64_t num_chunks,
                         int32_t balance, double P2, Cost* acc) {
  const int64_t p = dx * dy * c;
  if (w <= bc) {
    // base case (models/cholesky.py:_base_case_into): the panel is
    // replicated (allgather over the mesh); the policy then decides who
    // factors it — policy 0 every device (no further collective), policy 1
    // the z=0 layer + 2 result psums over depth, policies 2/3 the root
    // device + 2 result psums over the whole mesh
    acc->flops += 2.0 * (double)w * w * w / 3.0;
    if (p > 1) {
      double panel = (double)w * w * item;
      acc->comm += ring_bytes(panel, p);
      acc->ncoll += 1.0;
      if (policy == 1 && c > 1) {
        acc->comm += 2.0 * allreduce_bytes(panel, c);
        acc->ncoll += 2.0;
      } else if (policy >= 2) {
        acc->comm += 2.0 * allreduce_bytes(panel, p);
        acc->ncoll += 2.0;
      }
    }
    // window extraction + the R/Rinv write-backs: two whole-buffer dus
    // round-trips when materializing, band-sized under the persistent layout
    add_copy(acc, p, item,
             4.0 * (double)w * w
                 + (balance ? 8.0 * (double)w * w : 4.0 * P2));
    return;
  }
  int64_t n1 = std::max(bc, w >> split);
  int64_t m2 = w - n1;
  cholinv_walk(n1, bc, split, dx, dy, c, item, policy, 1, num_chunks, balance,
               P2, acc);
  // TRSM phase: R12 = R11^-T A12 (trmm, triangular operand halves the flops);
  // copies: triangle mask + a_view + trans_a (3 x n1^2), b_view (n1 x m2),
  // then the result lands in Rp
  add(acc, gemm_cost(n1, m2, n1, dx, dy, c, item, 0.5, num_chunks));
  add_copy(acc, p, item,
           6.0 * (double)n1 * n1 + 2.0 * (double)n1 * m2
               + (balance ? 4.0 * (double)n1 * m2 : 2.0 * P2));
  // Schur: A22 -= R12^T R12 (syrk: symmetric output halves useful flops);
  // copies: operand .T + a_view (2 x n1 m2), symmetrize (4 m2^2) + c_view
  // (2 m2^2), update back into buf
  add(acc, gemm_cost(m2, m2, n1, dx, dy, c, item, 0.5, num_chunks));
  add_copy(acc, p, item,
           4.0 * (double)n1 * m2 + 6.0 * (double)m2 * m2
               + (balance ? 4.0 * (double)m2 * m2 : 2.0 * P2));
  cholinv_walk(m2, bc, split, dx, dy, c, item, policy, 1, num_chunks, balance,
               P2, acc);
  if (complete_inv) {  // inverse completion: two trmms
    add(acc, gemm_cost(n1, m2, n1, dx, dy, c, item, 0.5, num_chunks));
    add_copy(acc, p, item, 4.0 * (double)n1 * n1 + 2.0 * (double)n1 * m2);
    add(acc, gemm_cost(n1, m2, m2, dx, dy, c, item, 0.5, num_chunks));
    add_copy(acc, p, item,
             4.0 * (double)m2 * m2
                 + (balance ? 4.0 * (double)n1 * m2 : 2.0 * P2));
  }
}

// Predicted seconds for each (policy, bc) config; out is row-major
// [num_pol][num_bc].  Returns the flat argmin.
int64_t cholinv_predict(int64_t n, int64_t dx, int64_t dy, int64_t c,
                        double peak_flops, double bw_Bps, double alpha_s,
                        int64_t itemsize, const int64_t* bcs, int64_t num_bc,
                        const int32_t* policies, int64_t num_pol,
                        int64_t split, int32_t complete_inv,
                        int64_t num_chunks, int32_t balance, double hbm_Bps,
                        double* out_seconds) {
  const int64_t p = dx * dy * c;
  int64_t best = 0;
  for (int64_t ip = 0; ip < num_pol; ++ip) {
    for (int64_t ib = 0; ib < num_bc; ++ib) {
      // pad n to a multiple chain of bc like padded_dim()
      int64_t bc = bcs[ib], padded = std::min(bc, n);
      while (padded < n) padded *= 2;
      double P2 = (double)padded * padded;
      Cost acc{0, 0, 0, 0};
      if (balance && p > 1) {
        // persistent layout: three lifetime permutes (A in, R and Rinv
        // out), priced like grid transposes (per-device block exchange)
        acc.comm += 3.0 * P2 / (double)(dx * dy) * itemsize;
        acc.ncoll += 3.0;
      }
      cholinv_walk(padded, bc, split, dx, dy, c, itemsize, policies[ip],
                   complete_inv, num_chunks, balance, P2, &acc);
      double s = acc.flops / peak_flops + acc.comm / bw_Bps +
                 acc.ncoll * alpha_s + acc.copy / (double)p / hbm_Bps;
      out_seconds[ip * num_bc + ib] = s;
      if (s < out_seconds[best]) best = ip * num_bc + ib;
    }
  }
  return best;
}

int32_t capital_native_abi_version(void) { return 3; }

}  // extern "C"
