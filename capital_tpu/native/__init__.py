"""Native host engine: ctypes bindings over capital_native.cpp.

Builds `libcapital_native.so` lazily with g++ (cached by source hash under
~/.cache/capital_tpu/), binds it with ctypes, and exposes the same-named
functions as utils/rand48 and utils/layout — every entry point has a pure
NumPy fallback, so the package works (slower) without a toolchain.

Why native at all, on a TPU framework: the reference's whole runtime is
C++ (SURVEY §2 note) — on TPU the compute path belongs to XLA/Pallas, and
the host-side remainder that benefits from native code is the data engine
(filling/validating N=65536² matrices element-seeded takes seconds of
vectorized NumPy and allocates 3x transients; the OpenMP loop streams it) and
the autotune planner's inner search loop.  See native/src/capital_native.cpp.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "src", "capital_native.cpp")
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False


def _cache_dir() -> str:
    return os.environ.get(
        "CAPITAL_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "capital_tpu"),
    )


def _build() -> str | None:
    """Compile the shared library, keyed by source hash; returns path or None."""
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"libcapital_native_{tag}.so")
    if os.path.exists(out):
        return out
    os.makedirs(_cache_dir(), exist_ok=True)
    tmp = out + f".tmp{os.getpid()}"
    base = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", tmp]
    for cmd in (base + ["-fopenmp"], base):  # retry without OpenMP
        try:
            r = subprocess.run(cmd, capture_output=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if r.returncode == 0:
            os.replace(tmp, out)
            return out
    return None


def _lib() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        path = _build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        i64, u64, i32 = ctypes.c_int64, ctypes.c_uint64, ctypes.c_int32
        dp = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.fill_symmetric.argtypes = [dp, i64, i64, i64, i64, i64, i32]
        lib.fill_random.argtypes = [dp, i64, i64, u64, i64, i64, i64, i64]
        lib.block_to_cyclic.argtypes = [dp, dp, i64, i64, i64, i64]
        lib.cyclic_to_block.argtypes = [dp, dp, i64, i64, i64, i64]
        for f in (lib.pack_upper, lib.unpack_upper, lib.pack_lower, lib.unpack_lower):
            f.argtypes = [dp, dp, i64]
        lib.cholinv_predict.argtypes = [
            i64, i64, i64, i64,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            i64, i64p, i64, i32p, i64, i64, i32, i64, i32,
            ctypes.c_double, dp,
        ]
        lib.cholinv_predict.restype = i64
        lib.capital_native_abi_version.restype = i32
        if lib.capital_native_abi_version() != 3:
            # stale cached .so from an older source tree (the cache is
            # keyed by source hash, so this only trips on manual cache
            # surgery) — fall back to the NumPy model rather than call a
            # mismatched signature
            return None
        _LIB = lib
        return _LIB


def available() -> bool:
    return _lib() is not None


# --------------------------------------------------------------------------
# fillers (bit-parity with utils/rand48; fall back to it)
# --------------------------------------------------------------------------


def _norm(sl: slice | None, n: int) -> tuple[int, int]:
    if sl is None:
        return 0, n
    start, stop, step = sl.indices(n)
    if step != 1:
        raise ValueError("native fillers need contiguous slices")
    return start, stop


def symmetric(
    n: int,
    diagonally_dominant: bool = True,
    dtype=np.float64,
    rows: slice | None = None,
    cols: slice | None = None,
) -> np.ndarray:
    lib = _lib()
    if lib is None:
        from capital_tpu.utils import rand48

        return rand48.symmetric(n, diagonally_dominant, dtype, rows, cols)
    r0, r1 = _norm(rows, n)
    c0, c1 = _norm(cols, n)
    out = np.empty((r1 - r0, c1 - c0), dtype=np.float64)
    lib.fill_symmetric(out, n, r0, r1, c0, c1, int(diagonally_dominant))
    return out.astype(dtype, copy=False)


def random(
    m: int,
    n: int,
    key: int = 0,
    dtype=np.float64,
    rows: slice | None = None,
    cols: slice | None = None,
) -> np.ndarray:
    lib = _lib()
    if lib is None:
        from capital_tpu.utils import rand48

        return rand48.random(m, n, key, dtype, rows, cols)
    r0, r1 = _norm(rows, m)
    c0, c1 = _norm(cols, n)
    out = np.empty((r1 - r0, c1 - c0), dtype=np.float64)
    lib.fill_random(out, m, n, key, r0, r1, c0, c1)
    return out.astype(dtype, copy=False)


# --------------------------------------------------------------------------
# repacks (fall back to utils/layout)
# --------------------------------------------------------------------------


def _repack(fn_name, G: np.ndarray, dx: int, dy: int) -> np.ndarray:
    if G.shape[0] % dx or G.shape[1] % dy:
        # validated here (not only in the NumPy fallback) so the native path
        # errors identically instead of silently scrambling the remainder
        raise ValueError(
            f"{fn_name}: shape {G.shape} not divisible by grid ({dx}, {dy})"
        )
    lib = _lib()
    if lib is None:
        from capital_tpu.utils import layout

        return getattr(layout, fn_name)(np.ascontiguousarray(G, np.float64), dx, dy)
    G = np.ascontiguousarray(G, dtype=np.float64)
    out = np.empty_like(G)
    getattr(lib, fn_name)(G, out, G.shape[0], G.shape[1], dx, dy)
    return out


def block_to_cyclic(G: np.ndarray, dx: int, dy: int) -> np.ndarray:
    return _repack("block_to_cyclic", G, dx, dy)


def cyclic_to_block(G: np.ndarray, dx: int, dy: int) -> np.ndarray:
    return _repack("cyclic_to_block", G, dx, dy)


def pack_upper(A: np.ndarray) -> np.ndarray:
    lib = _lib()
    n = A.shape[0]
    if lib is None:
        from capital_tpu.utils import layout

        return layout.pack_upper(np.asarray(A, np.float64))
    A = np.ascontiguousarray(A, np.float64)
    out = np.empty(n * (n + 1) // 2, np.float64)
    lib.pack_upper(A, out, n)
    return out


def unpack_upper(packed: np.ndarray, n: int) -> np.ndarray:
    lib = _lib()
    if lib is None:
        from capital_tpu.utils import layout

        return layout.unpack_upper(np.asarray(packed, np.float64), n)
    packed = np.ascontiguousarray(packed, np.float64)
    out = np.empty((n, n), np.float64)
    lib.unpack_upper(packed, out, n)
    return out


def pack_lower(A: np.ndarray) -> np.ndarray:
    lib = _lib()
    n = A.shape[0]
    if lib is None:
        from capital_tpu.utils import layout

        return layout.pack_lower(np.asarray(A, np.float64))
    A = np.ascontiguousarray(A, np.float64)
    out = np.empty(n * (n + 1) // 2, np.float64)
    lib.pack_lower(A, out, n)
    return out


def unpack_lower(packed: np.ndarray, n: int) -> np.ndarray:
    lib = _lib()
    if lib is None:
        from capital_tpu.utils import layout

        return layout.unpack_lower(np.asarray(packed, np.float64), n)
    packed = np.ascontiguousarray(packed, np.float64)
    out = np.empty((n, n), np.float64)
    lib.unpack_lower(packed, out, n)
    return out


# --------------------------------------------------------------------------
# planner
# --------------------------------------------------------------------------


def cholinv_predict(
    n: int,
    grid_shape: tuple[int, int, int],
    bc_dims,
    policies,
    peak_flops: float,
    bw_bytes_per_s: float = 4.5e10,
    alpha_s: float = 1e-6,
    itemsize: int = 2,
    split: int = 1,
    complete_inv: bool = True,
    num_chunks: int = 0,
    balance: str | int = "block",
    hbm_bytes_per_s: float = 8.2e11,
):
    """Predicted seconds per (policy, bc) config from the alpha-beta model;
    returns (seconds[num_pol, num_bc], (best_policy_idx, best_bc_idx)).

    The native predictive half of autotune: prune the measured sweep to the
    model's frontier before spending device time (the reference instead
    measures every config, tune.cpp:239-253).  num_chunks models the
    reference's Ibcast/Iallreduce pipelining (summa.hpp:196-248): same
    bytes, chunk-fold more collective launches — only the alpha term moves
    (round-3 deliberately ignored chunks; a chunks-axis sweep would have
    ranked every q identically).

    balance prices the schedule's COPY term (the data motion the cost
    model used to ignore, mirrored from tracing's copy_bytes emissions at
    hbm_bytes_per_s): 'block'/'tile_cyclic' walk the materializing
    explicit schedule (take_triangle masks, window slices, whole-buffer
    dynamic_update_slice round-trips per phase);
    'tile_cyclic_persistent' prices the persistent layout — three
    lifetime permutes on the comm side and band-sized residual motion on
    the copy side.  On a single device the copy term is ~0 either way
    (the d==1 explicit route rides the aliasing pallas kernels)."""
    lib = _lib()
    bcs = np.asarray(list(bc_dims), dtype=np.int64)
    pols = np.asarray([int(getattr(p, "value", p)) for p in policies], dtype=np.int32)
    out = np.empty((len(pols), len(bcs)), dtype=np.float64)
    dx, dy, c = grid_shape
    bal = (
        balance
        if isinstance(balance, int)
        else (1 if balance == "tile_cyclic_persistent" else 0)
    )
    if lib is not None:
        best = lib.cholinv_predict(
            n, dx, dy, c, peak_flops, bw_bytes_per_s, alpha_s, itemsize,
            bcs, len(bcs), pols, len(pols), split, int(complete_inv),
            num_chunks, bal, hbm_bytes_per_s, out,
        )
        return out, (int(best) // len(bcs), int(best) % len(bcs))
    # NumPy fallback: same model (kept in lock-step with the C++ by
    # tests/test_native.py::test_predict_matches_fallback)
    for ip, pol in enumerate(pols):
        for ib, bc in enumerate(bcs):
            out[ip, ib] = _predict_py(
                n, dx, dy, c, peak_flops, bw_bytes_per_s, alpha_s, itemsize,
                int(bc), int(pol), split, complete_inv, num_chunks,
                bal, hbm_bytes_per_s,
            )
    best = int(np.argmin(out))
    return out, (best // len(bcs), best % len(bcs))


def _predict_py(
    n, dx, dy, c, peak, bw, alpha, item, bc, pol, split, complete_inv,
    num_chunks=0, balance=0, hbm=8.2e11,
):
    def ring(b, p):
        return b * (p - 1) / p if p > 1 else 0.0

    def allred(b, p):
        return 2.0 * b * (p - 1) / p if p > 1 else 0.0

    def gemm(M, N, K, tri=0.5):
        # mirrors tracing.gemm_cost: c==1 amortized ring all_gathers; c>1
        # per-step masked-psum broadcasts of the layer's d/c panels.
        # num_chunks: same bytes, q-fold collective launches (alpha term).
        p = dx * dy * c
        d = max(dx, dy)
        fl = tri * 2.0 * M * N * K / p
        if c <= 1:
            comm = ring(M / dx * K * item, dy) + ring(K * N / dy * item, dx)
            nc = (1.0 if dy > 1 else 0.0) + (1.0 if dx > 1 else 0.0)
        else:
            steps = max(1, d // c)
            comm = steps * (
                allred(M / dx * K / d * item, dy)
                + allred(K / d * N / dy * item, dx)
            )
            nc = steps * ((1.0 if dy > 1 else 0.0) + (1.0 if dx > 1 else 0.0))
        comm += allred(M / dx * N / dy * item, c)
        nc += 1.0 if c > 1 else 0.0
        if num_chunks > 1:
            nc *= num_chunks
        return fl, comm, nc

    p = dx * dy * c
    acc = [0.0, 0.0, 0.0, 0.0]  # flops, comm_bytes, collectives, copy_bytes

    def add(t):
        acc[0] += t[0]; acc[1] += t[1]; acc[2] += t[2]

    padded = min(bc, n)
    while padded < n:
        padded *= 2
    P2 = float(padded) * padded  # whole-buffer dus round-trips move this

    def copy(bytes_):
        # schedule-inserted HBM motion, mirroring tracing's copy_bytes
        # emissions (parallel/summa.py, 2.0 = read + write per moved
        # array).  A single device rides the copy-free aliasing kernels —
        # no term at all; that IS the d==1 explicit uplift.
        if p > 1:
            acc[3] += bytes_ * item

    def walk(w, top):
        if w <= bc:
            # replicate + policy-scoped factorization (utils/config.py):
            # policy 1 adds 2 result psums over depth, 2/3 over the mesh
            acc[0] += 2.0 * w**3 / 3.0
            if p > 1:
                panel = w * w * item
                acc[1] += ring(panel, p)
                acc[2] += 1.0
                if pol == 1 and c > 1:
                    acc[1] += 2.0 * allred(panel, c)
                    acc[2] += 2.0
                elif pol >= 2:
                    acc[1] += 2.0 * allred(panel, p)
                    acc[2] += 2.0
            # window extraction + the R/Rinv write-backs: two whole-buffer
            # dus round-trips when materializing, band-sized under the
            # persistent layout
            copy(4.0 * w * w + (8.0 * w * w if balance else 4.0 * P2))
            return
        n1 = max(bc, w >> split)
        m2 = w - n1
        walk(n1, False)
        # TRSM trmm: triangle mask + a_view + trans_a (3 x n1²), b_view
        # (n1 x m2), result into Rp — whole-buffer dus vs band write-back
        add(gemm(n1, m2, n1))
        copy(6.0 * n1 * n1 + 2.0 * n1 * m2
             + (4.0 * n1 * m2 if balance else 2.0 * P2))
        # Schur syrk: operand .T + a_view (2 x n1 m2), symmetrize (4 m2²)
        # + c_view (2 m2²), update back into buf
        add(gemm(m2, m2, n1))
        copy(4.0 * n1 * m2 + 6.0 * m2 * m2
             + (4.0 * m2 * m2 if balance else 2.0 * P2))
        walk(m2, False)
        if complete_inv or not top:
            # completion trmms: T (no out), then side-R into RIp
            add(gemm(n1, m2, n1))
            copy(4.0 * n1 * n1 + 2.0 * n1 * m2)
            add(gemm(n1, m2, m2))
            copy(4.0 * m2 * m2
                 + (4.0 * n1 * m2 if balance else 2.0 * P2))

    if balance and p > 1:
        # persistent layout: three lifetime permutes (A in, R and Rinv
        # out), priced like grid transposes — per-device block exchange
        acc[1] += 3.0 * P2 / (dx * dy) * item
        acc[2] += 3.0
    walk(padded, True)
    return (
        acc[0] / peak + acc[1] / bw + acc[2] * alpha + acc[3] / p / hbm
    )
