"""Blocked Householder TSQR: the unconditionally stable tall-skinny QR.

CholeskyQR-family methods square the condition number through the gram
(models/qr.py; CA-CQR2 arXiv:1710.08471), so past cond(A) ~ u^{-1/2} even
the shifted sCQR3 ladder stalls and the robust path returns the honest
`info = n + 2` sentinel (docs/ROBUSTNESS.md).  TSQR (Demmel, Grigori,
Hoemmen, Langou, "Communication-optimal parallel and sequential QR",
arXiv:0809.2407) never forms a gram: a tree of small Householder QRs is
backward stable for ANY cond(A) the dtype can represent, at ~2x the flops
of one CholeskyQR sweep.  This is the escalation target that retires the
sentinel for matrices the compute dtype can handle at all
(robust/recovery.tsqr_escalate).

Shape of the computation:

* **leaves** — A's rows are padded with zero rows to `leaves * panel`
  (leaves a power of two) and split into (panel, n) row panels; each panel
  gets an independent Householder QR.  Zero-row padding is exact: a padded
  row of A = Q·R forces the matching Q rows to zero (R is invertible for
  full-rank A), so the unpadded Q is a plain slice.
* **reduction** — pairs of (n, n) R factors stack into (2n, n) panels and
  re-factor, halving the count per level; ``log2(leaves)`` levels leave ONE
  R.  Each level's thin-Q blocks multiply into the per-leaf Q accumulators
  (a batched gemm), so the final Q assembles top-down without ever
  materializing an (m, m) factor.

Leaf/reduction panel QRs have two interchangeable implementations behind
the PR 6 dispatch-gate resolver (`default_impl`, mirroring
ops/batched_small): a batched-grid Pallas Householder kernel (batch of
panels on the grid, each panel VMEM-resident through both the reflector
sweep and the thin-Q assembly — f32 compute, one-hot contractions and
iota masks only) for small f32/bf16 panels, and a batched
``lax.linalg.qr`` fallback.  f64 ALWAYS takes the XLA route — the Pallas
kernels compute in f32, and honoring a forced impl='pallas' on f64 input
would silently downgrade precision behind f64-labeled outputs
(batched_small.dtype_capable, the PR 6 contract).  All resolution reads
static shapes/dtypes only, so callers keep the zero-recompile invariant.

Like the other Pallas ops the kernels run in interpret mode off-TPU and
the VMEM gate is bypassed there (CPU CI rides the same route the hardware
does).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from capital_tpu.ops import batched_small
from capital_tpu.ops.batched_small import (
    _batched_call,
    _gdot,
    _iota,
    _oh_row,
    _resolve_block,
)
from capital_tpu.ops.pallas_tpu import _device_budget, _interpret_default
from capital_tpu.utils import tracing

IMPLS = ("auto", "pallas", "xla")

#: Largest panel column count the auto resolver routes to the Pallas leaf
#: kernel — the same boundary as the batched small-N solves (above it the
#: reflector sweep's executed-flop overhead outweighs the launch saving).
SMALL_N_MAX = batched_small.SMALL_N_MAX


def _compute_dtype(dtype):
    # panel QRs run at >= f32 exactly like the LAPACK seam
    # (ops/lapack._compute_dtype; restated to keep this module free of the
    # lapack -> robust import chain)
    return jnp.float32 if jnp.dtype(dtype).itemsize < 4 else jnp.dtype(dtype)


def resolve_panel(m: int, n: int, panel: int = 0) -> int:
    """Leaf panel row count: requested `panel` clamped to >= n (a leaf must
    be at least square to produce an (n, n) R), default 2n rounded up to
    128 — tall enough that the reduction tree stays shallow, small enough
    that a leaf panel is VMEM-resident at serve's bucket sizes."""
    if panel:
        return max(panel, n)
    return max(2 * n, 128)


def resolve_leaves(m: int, n: int, panel: int = 0) -> int:
    """Leaf count: ceil(m / panel) rounded UP to a power of two, so the
    pairwise reduction closes without remainder handling (the extra
    leaves are all-zero pads, whose R factors are exact zeros)."""
    p = resolve_panel(m, n, panel)
    raw = max(-(-m // p), 1)
    return 1 << (raw - 1).bit_length()


def eligible(rows: int, n: int, dtype, *,
             interpret: bool | None = None) -> bool:
    """VMEM-envelope gate for ONE (rows, n) panel of the batched-grid
    kernel: the panel at `dtype` plus the f32 working set (live panel W,
    reflector store V, thin-Q accumulator E, and the sweep temporaries).
    Interpret mode bypasses — batched_small.eligible discipline."""
    if interpret is None:
        interpret = _interpret_default()
    if interpret:
        return True
    limit = 0.85 * (_device_budget()[1] or (16 << 20))
    item = jnp.dtype(dtype).itemsize
    need = 2 * rows * n * item + 4 * (4 * rows * n + n * n)
    return need <= limit


def default_impl(rows: int, n: int, dtype, *,
                 interpret: bool | None = None) -> str:
    """Resolve impl='auto' for one batch of (rows, n) panels: 'pallas'
    where the batched-grid kernel owns the latency (narrow dtype, small n,
    VMEM-eligible), else 'xla'.  f64 ALWAYS takes xla (dtype_capable)."""
    if not batched_small.dtype_capable(dtype):
        return "xla"
    if n > SMALL_N_MAX:
        return "xla"
    return ("pallas" if eligible(rows, n, dtype, interpret=interpret)
            else "xla")


# --------------------------------------------------------------------------
# panel QR: batched XLA reference + batched-grid Pallas kernel
# --------------------------------------------------------------------------


def _qr_xla(P, precision):
    """Batched thin Householder QR via lax.linalg.qr — the exact-dtype
    reference and the mandatory f64 route."""
    del precision  # lax.linalg.qr has no precision knob
    Q, R = lax.linalg.qr(P, full_matrices=False)
    return Q, jnp.triu(R)


def _house_panel(a, *, block: int, precision):
    """In-kernel Householder QR of ONE f32 (p, n) panel VALUE: ascending
    reflector sweep (column j's below-diagonal part -> unit v_j, stored in
    column j of V; H_j = I − 2·v_j·v_jᵀ applied to the live panel), then a
    descending sweep applies the stored reflectors to I_{p×n} for the thin
    Q.  Every step is a one-hot contraction or an iota-masked elementwise
    op (the batched_small Mosaic discipline — no dynamic lane slicing).
    A zero column below the diagonal yields v = 0 (H = identity), so
    zero-padded panels factor EXACTLY to (Q=anything·0-safe, R=0) and
    rank deficiency degrades like LAPACK's (zero R diagonal, no NaN)."""
    p, n = a.shape
    W0, V0 = a, jnp.zeros_like(a)

    def col_step(j, W, V):
        colw = _gdot(W, _oh_row(j, n), 1, 1, precision)  # W[:, j] as (p, 1)
        rows = _iota((p, 1), 0)
        x = colw * (rows >= j).astype(jnp.float32)
        ohj = (rows == j).astype(jnp.float32)
        xj = jnp.sum(x * ohj)
        sig = jnp.sqrt(jnp.sum(x * x))
        alpha = -jnp.where(xj >= 0, 1.0, -1.0) * sig
        v = x - alpha * ohj
        vn2 = jnp.sum(v * v)
        v = v * jnp.where(
            vn2 > 0, lax.rsqrt(jnp.where(vn2 > 0, vn2, jnp.float32(1.0))),
            jnp.float32(0.0),
        )
        vtW = _gdot(v, W, 0, 0, precision)  # (1, n)
        W = W - 2.0 * _gdot(v, vtW, 1, 0, precision)
        V = V + _gdot(v, _oh_row(j, n), 1, 0, precision)  # place v at col j
        return W, V

    def sweep_body(q, carry):
        W, V = carry
        for t in range(block):
            W, V = col_step(q * block + t, W, V)
        return W, V

    W, V = jax.lax.fori_loop(0, n // block, sweep_body, (W0, V0))

    # R = top n rows of the swept panel, upper-masked (sub-diagonal residue
    # is reflector roundoff, exactly like geqrf's packed storage)
    sel = (_iota((n, p), 0) == _iota((n, p), 1)).astype(jnp.float32)
    R = _gdot(sel, W, 1, 0, precision)
    R = jnp.where(_iota((n, n), 0) <= _iota((n, n), 1), R, 0.0)

    # thin Q: apply H_{n-1}..H_0 to the first n columns of I_p
    E0 = (_iota((p, n), 0) == _iota((p, n), 1)).astype(jnp.float32)

    def q_step(j, E):
        v = _gdot(V, _oh_row(j, n), 1, 1, precision)  # (p, 1)
        vtE = _gdot(v, E, 0, 0, precision)
        return E - 2.0 * _gdot(v, vtE, 1, 0, precision)

    def q_body(q, E):
        for t in range(block):
            E = q_step(n - 1 - (q * block + t), E)
        return E

    Q = jax.lax.fori_loop(0, n // block, q_body, E0)
    return Q, R


def _qr_pallas(P, *, block: int, precision, interpret):
    """Batched-grid panel QR: ONE pallas_call with the panel batch on the
    grid; each grid step's panel stays VMEM-resident through the reflector
    sweep, the R extraction, and the thin-Q assembly."""
    batch, p, n = P.shape
    bs = _resolve_block(n, block)

    def kernel(a_ref, q_ref, r_ref):
        a = a_ref[0].astype(jnp.float32)
        Q, R = _house_panel(a, block=bs, precision=precision)
        q_ref[0] = Q.astype(a_ref.dtype)
        r_ref[0] = R.astype(a_ref.dtype)

    Q, R = _batched_call(
        kernel, [P],
        [((batch, p, n), P.dtype), ((batch, n, n), P.dtype)],
        interpret=interpret,
        flops=batch * 6.0 * p * n * n,
        bytes_accessed=batch * (2 * p * n + n * n)
        * jnp.dtype(P.dtype).itemsize,
    )
    return Q, R


def _qr_batch(P, impl: str, *, block: int, precision, interpret):
    """One batch of (rows, n) panels through the resolved route.  A forced
    'pallas' on an incapable dtype (f64) still takes xla — never a silent
    precision downgrade (the batched_small fallback contract)."""
    rows, n = P.shape[-2], P.shape[-1]
    pick = impl
    if impl == "auto":
        pick = default_impl(rows, n, P.dtype, interpret=interpret)
    elif impl == "pallas" and not batched_small.dtype_capable(P.dtype):
        pick = "xla"
    if pick == "pallas":
        return _qr_pallas(P, block=block, precision=precision,
                          interpret=interpret)
    return _qr_xla(P, precision)


# --------------------------------------------------------------------------
# the tree
# --------------------------------------------------------------------------


def tsqr(A, *, panel: int = 0, block: int = 0,
         precision: str | None = "highest", impl: str = "auto",
         interpret: bool | None = None):
    """Blocked Householder TSQR of tall-skinny A: returns (Q, R) with
    A = Q·R, Q (m, n) with orthonormal columns to working precision at ANY
    cond(A), R (n, n) upper triangular.  Computes at the >= f32 dtype and
    casts back once (the ops-layer convention); callers needing the
    always-f64 escalation grade go through robust/recovery.tsqr_escalate,
    which upcasts BEFORE calling."""
    if A.ndim != 2 or A.shape[0] < A.shape[1]:
        raise ValueError(f"tsqr expects one tall-skinny matrix, got {A.shape}")
    if impl not in IMPLS:
        raise ValueError(f"tsqr impl must be one of {IMPLS}, got {impl!r}")
    m, n = A.shape
    if interpret is None:
        interpret = _interpret_default()
    p = resolve_panel(m, n, panel)
    leaves = resolve_leaves(m, n, panel)

    with tracing.scope("QR::tsqr"):
        tracing.emit(flops=tracing.tsqr_flops(m, n, leaves))
        ct = _compute_dtype(A.dtype)
        Ap = A.astype(ct)
        mp = leaves * p
        if mp > m:
            Ap = jnp.pad(Ap, ((0, mp - m), (0, 0)))
        panels = Ap.reshape(leaves, p, n)
        Qacc, Rs = _qr_batch(panels, impl, block=block,
                             precision=precision, interpret=interpret)
        level_count = leaves
        while level_count > 1:
            S = jnp.concatenate([Rs[0::2], Rs[1::2]], axis=1)  # (L/2, 2n, n)
            Qp, Rs = _qr_batch(S, impl, block=block,
                               precision=precision, interpret=interpret)
            # per-child (n, n) factor: node i's top block belongs to child
            # 2i, bottom block to child 2i+1 — every ORIGINAL leaf under a
            # child multiplies its accumulator by that child's factor
            F = jnp.stack([Qp[:, :n], Qp[:, n:]], axis=1)
            F = F.reshape(level_count, n, n)
            group = leaves // level_count
            Qacc = jnp.matmul(
                Qacc.reshape(level_count, group, p, n), F[:, None],
                precision=precision,
            ).reshape(leaves, p, n)
            level_count //= 2
        Q = Qacc.reshape(mp, n)[:m]
        R = Rs[0]
    return Q.astype(A.dtype), R.astype(A.dtype)


def ortho_gate(Q, precision: str | None = "highest"):
    """The ladder's orthogonality measurement ||I − QᵀQ||_F / sqrt(n) at
    Q's own dtype — shared by the escalation wiring and the bench gate so
    the two can never drift apart."""
    n = Q.shape[-1]
    G = jnp.matmul(Q.T, Q, precision=precision)
    return (jnp.linalg.norm(G - jnp.eye(n, dtype=G.dtype))
            / jnp.sqrt(jnp.asarray(n, G.dtype))).astype(jnp.float32)
