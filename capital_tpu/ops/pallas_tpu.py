"""Pallas (Mosaic) TPU kernels: triangular-predicated blocked matmul.

The performance problem this solves (SURVEY §7.3 item 2): the reference saves
half the flops of its trmm/syrk phases through packed triangular storage and
BLAS triangular routines (summa.hpp:47-161); the TPU-idiomatic dense+mask
design (ops/masking.py) keeps the MXU fed but *executes* the dead half of
every triangular product — roughly 2x the useful flops across cholinv's
TRSM/Schur/inverse-completion phases.

This module restores the 2x with **live-tile enumeration** instead of packed
storage: the set of (output-tile, k-step) pairs that touch the stored
triangle is computed at trace time (shapes are static under jit), flattened
into one grid dimension, and fed to the kernel through scalar-prefetch index
arrays (`pltpu.PrefetchScalarGridSpec`) that the BlockSpec index maps read.
Dead tiles are never visited — no wasted MXU steps, no wasted DMA —
which is what it takes to actually beat the dense matmul on hardware
(predicating a rectangular grid with `@pl.when` leaves ~1 us of per-step
overhead and loses most of the 2x).  Tiles straddling the diagonal are
masked elementwise against their global indices (unconditional `jnp.where`:
O(tile) VPU work next to the tile's MXU work; a `lax.cond` would put
divergent control flow in the hot loop).

Three kernels share one accumulate body:
  * dense       — no structure flags: plain (M/bm, N/bn, K/bk) blocked matmul
  * tri-operand — A or B triangular: grid (other-dim, live (tile,k) pairs),
                  per-pair first/last flags drive accumulator init/flush
  * tri-output  — out_uplo (syrk-style): grid (live out tiles, K/bk)

Supported structure flags (at most one triangular operand):
  a_uplo/a_trans — A triangular ('U'/'L' of the *untransposed* operand,
                   BLAS trmm semantics, reference blas::ArgPack_trmm
                   engine.h:96-112); a_trans contracts over A's first axis
                   without materializing Aᵀ (the index map fetches the
                   transposed tile, dot_general contracts axis 0)
  b_uplo/b_trans — B triangular
  out_uplo       — only the named triangle of C is computed, rest zeroed
                   (syrk semantics, engine.h:114-130: C = AᵀA is symmetric,
                   so cholinv's Schur phase keeps/reads only the upper
                   triangle — models/cholesky.py)

Entries in an operand's dead triangle are treated as zero regardless of
buffer contents.  Accumulation is f32 (input dtype if wider, off-TPU) in
VMEM scratch.  On non-TPU backends everything runs in interpreter mode so
the CPU mesh test rig exercises identical semantics (tests/conftest.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _device_budget() -> tuple[int, int | None]:
    """(max square tile, vmem_limit_bytes) for this backend's chips.

    v5e/v6 measured: Mosaic's default scoped-VMEM budget (16MB) rejects
    1024-square double-buffered tiles, but these chips accept a raised limit
    and the large tiles are what reach peak — at 8192^2 bf16,
    (1024,1024,1024) @ 100MB runs the dense kernel at 171 TF/s vs 160 for
    (512,512,2048) @ default (XLA's own gemm: 167), trmm 140 / syrk 142 TF/s
    useful vs 124/132.  Other/unknown chips keep the conservative 512 tiles
    and Mosaic's own limit, which fit everywhere."""
    if jax.default_backend() != "tpu":
        return 512, None
    kind = jax.devices()[0].device_kind.lower()
    if any(t in kind for t in ("v5 lite", "v5e", "v5p", "v6")):
        return 1024, 100 * 2**20
    return 512, None


def default_blocks(
    m: int, k: int, n: int, itemsize: int = 2, tri_operand: bool = False
) -> tuple[int, int, int]:
    """(bm, bn, bk) block shape, shrunk to each dim's padded size for small
    operands; multiples of 128 throughout (MXU/lane alignment).  The output
    tile budget is device-gated (_device_budget); the K depth is
    dtype-budgeted everywhere (bf16 affords bk=2048, f32 half that — within
    the raised vmem_limit on big-tile chips, ~10MB of scoped VMEM on the
    conservative ones).

    tri_operand is accepted for call-site symmetry but currently does not
    change the choice: at 8192^2 bf16 on v5e (80-iteration in-jit timing),
    deep K wins for every kernel shape — dense 193 vs 176 TF/s, trmm 152 vs
    139 useful, syrk 144 vs 134 at bk=2048 vs 1024.  trmm's remaining gap to
    dense is exactly the masked half-tiles of the bk/2-wide diagonal band
    (live-pair fraction x dense time predicts the measurement within 2%), so
    finer K trades that band against dense efficiency and loses."""
    cap, _ = _device_budget()
    bm = max(128, min(cap, _round_up(m, 128)))
    bn = max(128, min(cap, _round_up(n, 128)))
    dtype_bk = 2048 if itemsize <= 2 else 1024
    bk = max(128, min(dtype_bk, _round_up(k, 128)))
    return bm, bn, bk


def _global_tri_mask(tile, r0, c0, uplo: str):
    """Mask `tile` against the global triangle: keep element (r, c) iff
    r0+r <= c0+c ('U') / >= ('L')."""
    r = jax.lax.broadcasted_iota(jnp.int32, tile.shape, 0) + r0
    c = jax.lax.broadcasted_iota(jnp.int32, tile.shape, 1) + c0
    keep = (r <= c) if uplo == "U" else (r >= c)
    return jnp.where(keep, tile, jnp.zeros_like(tile))


def _a_live(i: int, k: int, bm: int, bk: int, uplo: str, trans: bool) -> bool:
    """Is logical-A tile (block-row i, block-k k) not entirely in the dead
    triangle?  Element ranges: untransposed A tile spans rows [i*bm, +bm),
    cols [k*bk, +bk); a_trans swaps the roles."""
    if (uplo == "U") != trans:
        return i * bm < (k + 1) * bk
    return k * bk < (i + 1) * bm


def _b_live(j: int, k: int, bn: int, bk: int, uplo: str, trans: bool) -> bool:
    """Logical B tile spans rows [k*bk, +bk), cols [j*bn, +bn)."""
    if (uplo == "U") != trans:
        return k * bk < (j + 1) * bn
    return j * bn < (k + 1) * bk


def _make_accumulate(
    *, a_uplo, a_trans, b_uplo, b_trans, bm, bn, bk, acc_dtype, precision
):
    """The shared inner body: mask diagonal-straddling tiles against global
    indices, contract on the MXU, accumulate into VMEM scratch."""

    def accumulate(a_ref, b_ref, acc_ref, i, j, k):
        a = a_ref[:]
        b = b_ref[:]
        if a_uplo is not None:
            r0, c0 = i * bm, k * bk
            if a_trans:  # buffer holds the transposed tile
                a = _global_tri_mask(a, c0, r0, a_uplo)
            else:
                a = _global_tri_mask(a, r0, c0, a_uplo)
        if b_uplo is not None:
            r0, c0 = k * bk, j * bn
            if b_trans:
                b = _global_tri_mask(b, c0, r0, b_uplo)
            else:
                b = _global_tri_mask(b, r0, c0, b_uplo)
        dn = (((0 if a_trans else 1,), (1 if b_trans else 0,)), ((), ()))
        acc_ref[:] += jax.lax.dot_general(
            a, b, dimension_numbers=dn, preferred_element_type=acc_dtype,
            precision=precision,
        )

    return accumulate


def _flush(acc_ref, out_ref, alpha, out_uplo, r0, c0):
    res = acc_ref[:]
    if alpha != 1.0:
        res = alpha * res
    if out_uplo is not None:
        res = _global_tri_mask(res, r0, c0, out_uplo)
    out_ref[:] = res.astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("out_uplo", "interpret")
)
def transpose(
    X: jnp.ndarray, *, out_uplo: str | None = None, interpret: bool | None = None
) -> jnp.ndarray:
    """Xᵀ as an opaque custom call, optionally keeping only `out_uplo` of the
    result (dead half zeroed regardless of input buffer contents).

    Why a kernel for something XLA does natively: a bare `.T` in the traced
    graph invites layout assignment to satisfy it with a *bitcast* — flipping
    the consumer chain to column-major and re-materializing row-major copies
    at every Mosaic boundary (Mosaic kernels pin {1,0} operands).  Measured on
    cholinv at n=16k/v5e, the leaf-sized `L.T`s in the base case cascaded into
    ~4.7ms/iter of full-matrix relayout copies (a 536MB transposed copy of A
    among them).  A custom call is layout-opaque: the transpose stays exactly
    as big as the tensor it transposes."""
    if interpret is None:
        interpret = _interpret_default()
    m, n = X.shape
    bm = max(128, min(512, _round_up(m, 128)))
    bn = max(128, min(512, _round_up(n, 128)))
    M, N = _round_up(m, bm), _round_up(n, bn)
    Xp = jnp.pad(X, ((0, M - m), (0, N - n))) if (M != m or N != n) else X

    def kernel(x_ref, out_ref):
        i, j = pl.program_id(0), pl.program_id(1)  # out tile (i, j): (bn, bm)
        t = x_ref[:].T
        if out_uplo is not None:
            t = _global_tri_mask(t, i * bn, j * bm, out_uplo)
        out_ref[:] = t

    out = pl.pallas_call(
        kernel,
        grid=(N // bn, M // bm),
        in_specs=[pl.BlockSpec((bm, bn), lambda i, j: (j, i), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((bn, bm), lambda i, j: (i, j), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((N, M), X.dtype),
        interpret=interpret,
    )(Xp)
    return out[:n, :m] if (M != m or N != n) else out


@functools.partial(
    jax.jit,
    static_argnames=(
        "a_uplo", "a_trans", "b_uplo", "b_trans", "out_uplo", "alpha",
        "blocks", "interpret", "vmem_limit", "precision",
    ),
)
def tri_matmul(
    A: jnp.ndarray,
    B: jnp.ndarray,
    *,
    a_uplo: str | None = None,
    a_trans: bool = False,
    b_uplo: str | None = None,
    b_trans: bool = False,
    out_uplo: str | None = None,
    alpha: float = 1.0,
    blocks: tuple[int, int, int] | None = None,
    interpret: bool | None = None,
    vmem_limit: int | None = None,
    precision: str | None = None,
) -> jnp.ndarray:
    """C = alpha * op(A) @ op(B) with dead blocks of triangular operands /
    results never visited.  See module docstring.

    precision: MXU precision for the in-kernel dot_general ('highest' runs
    f32 operands through full-precision passes).  Without it f32 inputs get
    the MXU default (bf16-grade mantissa per pass): measured 7e-4 relative
    residual on an n=1000 f32 cholinv vs 2e-7 with 'highest'."""
    if a_uplo is not None and b_uplo is not None:
        raise ValueError("at most one triangular operand")
    if out_uplo is not None and (a_uplo is not None or b_uplo is not None):
        raise ValueError("out_uplo cannot combine with a triangular operand")
    if interpret is None:
        interpret = _interpret_default()
    if vmem_limit is None and not interpret:
        vmem_limit = _device_budget()[1]

    (am, ak) = A.shape if not a_trans else A.shape[::-1]
    (bkd, bnd) = B.shape if not b_trans else B.shape[::-1]
    if ak != bkd:
        raise ValueError(f"contraction mismatch: {A.shape} x {B.shape}")

    bm, bn, bk = blocks or default_blocks(
        am, ak, bnd,
        jnp.dtype(jnp.result_type(A, B)).itemsize,
        tri_operand=(a_uplo is not None or b_uplo is not None),
    )
    M, K, N = _round_up(am, bm), _round_up(ak, bk), _round_up(bnd, bn)
    pa = (M - am, K - ak) if not a_trans else (K - ak, M - am)
    pb = (K - bkd, N - bnd) if not b_trans else (N - bnd, K - bkd)
    Ap = jnp.pad(A, ((0, pa[0]), (0, pa[1]))) if any(pa) else A
    Bp = jnp.pad(B, ((0, pb[0]), (0, pb[1]))) if any(pb) else B

    nm, nk, nn = M // bm, K // bk, N // bn
    out_dtype = jnp.result_type(A, B)
    acc_dtype = jnp.promote_types(out_dtype, jnp.float32)
    if jnp.dtype(acc_dtype).itemsize > 4 and jax.default_backend() == "tpu":
        acc_dtype = jnp.float32

    accumulate = _make_accumulate(
        a_uplo=a_uplo, a_trans=a_trans, b_uplo=b_uplo, b_trans=b_trans,
        bm=bm, bn=bn, bk=bk, acc_dtype=acc_dtype, precision=precision,
    )
    a_shape = (bk, bm) if a_trans else (bm, bk)
    b_shape = (bn, bk) if b_trans else (bk, bn)
    common = dict(
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
        cost_estimate=pl.CostEstimate(
            flops=2 * M * N * K,
            bytes_accessed=(M * K + K * N + M * N) * jnp.dtype(out_dtype).itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )

    if a_uplo is None and b_uplo is None and out_uplo is None:
        # ---- dense: plain revisit-k blocked matmul -----------------------
        def dense_kernel(a_ref, b_ref, out_ref, acc_ref):
            i, j, k = pl.program_id(0), pl.program_id(1), pl.program_id(2)

            @pl.when(k == 0)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)

            accumulate(a_ref, b_ref, acc_ref, i, j, k)

            @pl.when(k == nk - 1)
            def _():
                _flush(acc_ref, out_ref, alpha, None, 0, 0)

        out = pl.pallas_call(
            dense_kernel,
            grid=(nm, nn, nk),
            in_specs=[
                pl.BlockSpec(
                    a_shape,
                    (lambda i, j, k: (k, i)) if a_trans else (lambda i, j, k: (i, k)),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    b_shape,
                    (lambda i, j, k: (j, k)) if b_trans else (lambda i, j, k: (k, j)),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (bm, bn), lambda i, j, k: (i, j), memory_space=pltpu.VMEM
            ),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"),
                vmem_limit_bytes=vmem_limit,
            ),
            **common,
        )(Ap, Bp)

    elif out_uplo is not None:
        # ---- tri-output (syrk): enumerate live output tiles --------------
        pairs = [
            (i, j)
            for i in range(nm)
            for j in range(nn)
            if (i * bm < (j + 1) * bn if out_uplo == "U" else j * bn < (i + 1) * bm)
        ]
        io = jnp.asarray(np.array([p[0] for p in pairs], np.int32))
        jo = jnp.asarray(np.array([p[1] for p in pairs], np.int32))

        def syrk_kernel(io_ref, jo_ref, a_ref, b_ref, out_ref, acc_ref):
            p, k = pl.program_id(0), pl.program_id(1)
            i, j = io_ref[p], jo_ref[p]

            @pl.when(k == 0)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)

            accumulate(a_ref, b_ref, acc_ref, i, j, k)

            @pl.when(k == nk - 1)
            def _():
                _flush(acc_ref, out_ref, alpha, out_uplo, i * bm, j * bn)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(len(pairs), nk),
            in_specs=[
                pl.BlockSpec(
                    a_shape,
                    (lambda p, k, io, jo: (k, io[p]))
                    if a_trans
                    else (lambda p, k, io, jo: (io[p], k)),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    b_shape,
                    (lambda p, k, io, jo: (jo[p], k))
                    if b_trans
                    else (lambda p, k, io, jo: (k, jo[p])),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (bm, bn), lambda p, k, io, jo: (io[p], jo[p]), memory_space=pltpu.VMEM
            ),
            scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
        )
        out = pl.pallas_call(
            syrk_kernel,
            grid_spec=grid_spec,
            out_shape=common["out_shape"],
            cost_estimate=common["cost_estimate"],
            interpret=interpret,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary", "arbitrary"),
                vmem_limit_bytes=vmem_limit,
            ),
        )(io, jo, Ap, Bp)
        # tiles in the dead half are never written by the kernel; Mosaic
        # zero-initializes outputs only per-visited-block, so blank the dead
        # half explicitly (cheap elementwise, fuses with the crop below)
        out = _global_tri_mask(out, 0, 0, out_uplo)

    else:
        # ---- tri-operand (trmm): enumerate live (tile-row, k) pairs ------
        if a_uplo is not None:
            pairs = [
                (i, k)
                for i in range(nm)
                for k in range(nk)
                if _a_live(i, k, bm, bk, a_uplo, a_trans)
            ]
            # grid: (nn, pairs) — pairs innermost so the out tile (i, j)
            # is revisited consecutively across its live k run
            to = jnp.asarray(np.array([p[0] for p in pairs], np.int32))
            ko = jnp.asarray(np.array([p[1] for p in pairs], np.int32))
            first = np.zeros(len(pairs), np.int32)
            last = np.zeros(len(pairs), np.int32)
            for idx, (i, _) in enumerate(pairs):
                if idx == 0 or pairs[idx - 1][0] != i:
                    first[idx] = 1
                if idx == len(pairs) - 1 or pairs[idx + 1][0] != i:
                    last[idx] = 1
        else:
            pairs = [
                (j, k)
                for j in range(nn)
                for k in range(nk)
                if _b_live(j, k, bn, bk, b_uplo, b_trans)
            ]
            to = jnp.asarray(np.array([p[0] for p in pairs], np.int32))
            ko = jnp.asarray(np.array([p[1] for p in pairs], np.int32))
            first = np.zeros(len(pairs), np.int32)
            last = np.zeros(len(pairs), np.int32)
            for idx, (j, _) in enumerate(pairs):
                if idx == 0 or pairs[idx - 1][0] != j:
                    first[idx] = 1
                if idx == len(pairs) - 1 or pairs[idx + 1][0] != j:
                    last[idx] = 1
        first = jnp.asarray(first)
        last = jnp.asarray(last)
        a_is_tri = a_uplo is not None

        def trmm_kernel(to_ref, ko_ref, fi_ref, la_ref, a_ref, b_ref, out_ref, acc_ref):
            q, p = pl.program_id(0), pl.program_id(1)
            t, k = to_ref[p], ko_ref[p]
            i, j = (t, q) if a_is_tri else (q, t)

            @pl.when(fi_ref[p] == 1)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)

            accumulate(a_ref, b_ref, acc_ref, i, j, k)

            @pl.when(la_ref[p] == 1)
            def _():
                _flush(acc_ref, out_ref, alpha, None, 0, 0)

        if a_is_tri:
            a_map = (
                (lambda q, p, to, ko, fi, la: (ko[p], to[p]))
                if a_trans
                else (lambda q, p, to, ko, fi, la: (to[p], ko[p]))
            )
            b_map = (
                (lambda q, p, to, ko, fi, la: (q, ko[p]))
                if b_trans
                else (lambda q, p, to, ko, fi, la: (ko[p], q))
            )
            out_map = lambda q, p, to, ko, fi, la: (to[p], q)
            n_outer = nn
        else:
            a_map = (
                (lambda q, p, to, ko, fi, la: (ko[p], q))
                if a_trans
                else (lambda q, p, to, ko, fi, la: (q, ko[p]))
            )
            b_map = (
                (lambda q, p, to, ko, fi, la: (to[p], ko[p]))
                if b_trans
                else (lambda q, p, to, ko, fi, la: (ko[p], to[p]))
            )
            out_map = lambda q, p, to, ko, fi, la: (q, to[p])
            n_outer = nm

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(n_outer, len(pairs)),
            in_specs=[
                pl.BlockSpec(a_shape, a_map, memory_space=pltpu.VMEM),
                pl.BlockSpec(b_shape, b_map, memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((bm, bn), out_map, memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
        )
        out = pl.pallas_call(
            trmm_kernel,
            grid_spec=grid_spec,
            out_shape=common["out_shape"],
            cost_estimate=common["cost_estimate"],
            interpret=interpret,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary"),
                vmem_limit_bytes=vmem_limit,
            ),
        )(to, ko, first, last, Ap, Bp)

    return out[:am, :bnd] if (M != am or N != bnd) else out
