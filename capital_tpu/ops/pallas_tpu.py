"""Pallas (Mosaic) TPU kernels: triangular-predicated blocked matmul.

The performance problem this solves (SURVEY §7.3 item 2): the reference saves
half the flops of its trmm/syrk phases through packed triangular storage and
BLAS triangular routines (summa.hpp:47-161); the TPU-idiomatic dense+mask
design (ops/masking.py) keeps the MXU fed but *executes* the dead half of
every triangular product — roughly 2x the useful flops across cholinv's
TRSM/Schur/inverse-completion phases.

This module restores the 2x with **live-tile enumeration** instead of packed
storage: the set of (output-tile, k-step) pairs that touch the stored
triangle is computed at trace time (shapes are static under jit), flattened
into one grid dimension, and fed to the kernel through scalar-prefetch index
arrays (`pltpu.PrefetchScalarGridSpec`) that the BlockSpec index maps read.
Dead tiles are never visited — no wasted MXU steps, no wasted DMA —
which is what it takes to actually beat the dense matmul on hardware
(predicating a rectangular grid with `@pl.when` leaves ~1 us of per-step
overhead and loses most of the 2x).  Tiles straddling the diagonal are
masked elementwise against their global indices (unconditional `jnp.where`:
O(tile) VPU work next to the tile's MXU work; a `lax.cond` would put
divergent control flow in the hot loop).

Three kernels share one accumulate body:
  * dense       — no structure flags: plain (M/bm, N/bn, K/bk) blocked matmul
  * tri-operand — A or B triangular: grid (other-dim, live (tile,k) pairs),
                  per-pair first/last flags drive accumulator init/flush
  * tri-output  — out_uplo (syrk-style): grid (live out tiles, K/bk)

Supported structure flags (at most one triangular operand):
  a_uplo/a_trans — A triangular ('U'/'L' of the *untransposed* operand,
                   BLAS trmm semantics, reference blas::ArgPack_trmm
                   engine.h:96-112); a_trans contracts over A's first axis
                   without materializing Aᵀ (the index map fetches the
                   transposed tile, dot_general contracts axis 0)
  b_uplo/b_trans — B triangular
  out_uplo       — only the named triangle of the result is computed; the
                   rest is zeroed with beta=0 and UNDEFINED with the fused
                   c/beta accumulate (syrk semantics, engine.h:114-130:
                   C = AᵀA is symmetric, so cholinv's Schur phase keeps/reads
                   only the upper triangle — models/cholesky.py)

Entries in an operand's dead triangle are treated as zero regardless of
buffer contents.  Accumulation is f32 (input dtype if wider, off-TPU) in
VMEM scratch.  On non-TPU backends everything runs in interpreter mode so
the CPU mesh test rig exercises identical semantics (tests/conftest.py).

**Buffer views and in-place outputs** (tri_matmul, transpose): operands can
be static windows of larger buffers (offset index maps — no slice
materialization) and results can be written into a window of an existing
buffer via `input_output_aliases`, preserving every untouched region.  The
combination lets a blocked algorithm keep its factors in flat buffers and
run each phase straight against them — cholinv's recursion reads R11inv /
R12 / R22inv through views and writes leaf, TRSM, and inverse-completion
panels in place, which removed ~6ms/iter of assembly HBM traffic at n=16k
on v5e (per-level concatenates, scatter chains, relayout copies).  Windows
whose sizes/offsets don't fit a viable block size transparently fall back
to materializing.  `zeros_dead_lower` rounds this out by zero-filling only
the tiles the algorithm will never write.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from capital_tpu.utils import jax_compat

# Platform resolution for interpret/tile decisions.  The process default
# backend is the wrong thing to key off in a mixed environment: a CPU mesh in
# a TPU-backed process (the driver's dryrun_multichip with
# --xla_force_host_platform_device_count) would pick the Mosaic lowering and
# die with "Only interpret mode is supported on CPU backend".  Kernels must
# follow the platform of the devices that will run them — threaded from the
# Grid via `platform_scope` (every grid-taking entry point is wrapped with
# `scoped_by_grid`); direct kernel calls without a scope fall back to the
# process default.
# A ContextVar, not a module list: JAX permits tracing from multiple
# threads, and a shared stack would leak one thread's platform into
# another's kernels.
_PLATFORM_SCOPE: contextvars.ContextVar[tuple[str, ...]] = contextvars.ContextVar(
    "capital_tpu_platform_scope", default=()
)


def _default_backend() -> str:
    # separate symbol so tests can simulate a TPU-default process on a
    # CPU-only box by monkeypatching this, without touching jax internals
    return jax.default_backend()


@contextlib.contextmanager
def platform_scope(platform: str | None):
    """Resolve interpret-mode and tile-budget decisions against `platform`
    (e.g. the mesh devices' platform) instead of jax.default_backend()."""
    if platform is None:
        yield
        return
    token = _PLATFORM_SCOPE.set(_PLATFORM_SCOPE.get() + (platform,))
    try:
        yield
    finally:
        _PLATFORM_SCOPE.reset(token)


def scoped_by_grid(fn):
    """Decorator for `fn(grid, ...)` entry points: every Pallas call traced
    inside runs under the grid's platform scope, so a CPU mesh gets the
    interpreter even when the process default backend is a TPU."""

    @functools.wraps(fn)
    def wrapper(grid, *args, **kwargs):
        with platform_scope(grid.platform):
            return fn(grid, *args, **kwargs)

    return wrapper


def _platform() -> str:
    stack = _PLATFORM_SCOPE.get()
    return stack[-1] if stack else _default_backend()


def _interpret_default() -> bool:
    return _platform() != "tpu"


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _device_budget() -> tuple[int, int | None]:
    """(max square tile, vmem_limit_bytes) for this backend's chips.

    v5e/v6 measured: Mosaic's default scoped-VMEM budget (16MB) rejects
    1024-square double-buffered tiles, but these chips accept a raised limit
    and the large tiles are what reach peak — at 8192^2 bf16,
    (1024,1024,1024) @ 100MB runs the dense kernel at 171 TF/s vs 160 for
    (512,512,2048) @ default (XLA's own gemm: 167), trmm 140 / syrk 142 TF/s
    useful vs 124/132.  Other/unknown chips keep the conservative 512 tiles
    and Mosaic's own limit, which fit everywhere."""
    if _platform() != "tpu":
        return 512, None
    kind = jax.devices("tpu")[0].device_kind.lower()
    if any(t in kind for t in ("v5 lite", "v5e", "v5p", "v6")):
        return 1024, 100 * 2**20
    return 512, None


def default_blocks(
    m: int, k: int, n: int, itemsize: int = 2, tri_operand: bool = False
) -> tuple[int, int, int]:
    """(bm, bn, bk) block shape, shrunk to each dim's padded size for small
    operands; multiples of 128 throughout (MXU/lane alignment).  The output
    tile budget is device-gated (_device_budget); the K depth is
    dtype-budgeted everywhere (bf16 affords bk=2048, f32 half that — within
    the raised vmem_limit on big-tile chips, ~10MB of scoped VMEM on the
    conservative ones).

    tri_operand halves the K depth (bk=1024 bf16 / 512 f32): a triangular
    operand's masked diagonal band is bk wide, so its wasted half-tiles cost
    ~bk/n1 of the useful flops, and inside cholinv most trmm windows are
    small enough that the band dominates.  Device-trace totals over the full
    n=16384 factor (v5e, per-kernel own time): CI kernels 21.27 ms/iter at
    bk=2048, 19.77 at bk=512, 19.46 at bk=1024 — the band saving beats the
    deep-K dense-efficiency loss at 1024 but not 512.  (Standalone 8192^2
    single-kernel timings preferred deep K — dense 193 vs 176 TF/s, trmm 152
    vs 139 — which is why this was previously left uniform; the standalone
    shape under-weights the small-window kernels where the band bites.  A
    two-phase band/bulk split at fine tiles was also tried and rejected: the
    masked single-phase kernel already sustains ~185 TF/s on executed flops,
    fine 512 band tiles only reach ~120, and the bulk phase's aliased
    read-accumulate forced XLA to copy the full buffer once per self-update
    call — 7 x 1.63 ms/iter at n=16k.)

    The standalone-vs-in-context conflict at the 8192 window is unresolved
    (same shape, opposite winner); the default follows the in-context
    numbers because the recursion is the framework's only pallas-mode trmm
    consumer (rectri/trsm default to mode='xla').  Callers with one big
    standalone triangular product can pass blocks=(bm, bn, 2048) to get the
    deep-K configuration back."""
    cap, _ = _device_budget()
    bm = max(128, min(cap, _round_up(m, 128)))
    bn = max(128, min(cap, _round_up(n, 128)))
    dtype_bk = 2048 if itemsize <= 2 else 1024
    if tri_operand:
        dtype_bk //= 2
        # window-adaptive depth: the masked band costs ~bk/2k of executed
        # flops, so small-K windows (cholinv's deep recursion levels, which
        # run at 50-85 TF/s useful vs 151-165 at L0) take finer K; k//4
        # caps the band waste at ~12.5% while leaving every window >= 4096
        # at the measured-optimal 1024 depth
        dtype_bk = min(dtype_bk, max(256, _round_up(k, 128) // 512 * 128))
    bk = max(128, min(dtype_bk, _round_up(k, 128)))
    return bm, bn, bk


def _global_tri_mask(tile, r0, c0, uplo: str):
    """Mask `tile` against the global triangle: keep element (r, c) iff
    r0+r <= c0+c ('U') / >= ('L')."""
    r = jax.lax.broadcasted_iota(jnp.int32, tile.shape, 0) + r0
    c = jax.lax.broadcasted_iota(jnp.int32, tile.shape, 1) + c0
    keep = (r <= c) if uplo == "U" else (r >= c)
    return jnp.where(keep, tile, jnp.zeros_like(tile))


def _a_live(i: int, k: int, bm: int, bk: int, uplo: str, trans: bool) -> bool:
    """Is logical-A tile (block-row i, block-k k) not entirely in the dead
    triangle?  Element ranges: untransposed A tile spans rows [i*bm, +bm),
    cols [k*bk, +bk); a_trans swaps the roles."""
    if (uplo == "U") != trans:
        return i * bm < (k + 1) * bk
    return k * bk < (i + 1) * bm


def _b_live(j: int, k: int, bn: int, bk: int, uplo: str, trans: bool) -> bool:
    """Logical B tile spans rows [k*bk, +bk), cols [j*bn, +bn)."""
    if (uplo == "U") != trans:
        return k * bk < (j + 1) * bn
    return j * bn < (k + 1) * bk


def _split_bf16(x):
    """hi + lo bf16 decomposition of an f32 value: hi = round(x), lo =
    round(x - hi).  hi·hi + hi·lo + lo·hi recovers ~f32-grade products from
    three bf16 MXU passes (the classic 3-pass split XLA calls precision
    HIGH)."""
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def precision_dot(a, b, dimension_numbers, acc_dtype, precision):
    """dot_general with the Mosaic-safe precision rules — the ONE copy of
    the rule set shared by every in-kernel contraction (here and
    ops/qr_fused; the two copies had already diverged once):

    * f32 x f32 at 'high' into an f32 accumulator: the in-kernel bf16x3
      split-accumulate — each operand decomposes into bf16 hi+lo and three
      bf16 MXU passes accumulate hi·hi + hi·lo + lo·hi (lo·lo is below
      f32 roundoff).  Mosaic's dot_general has no HIGH lowering
      (NotImplementedError on hardware); ~2x the 6-pass 'highest'
      throughput at f32-grade accuracy (VERDICT r3 #3).
    * any other 'high' shape rounds up to 'highest' (full passes, never an
      error);
    * a sub-f32 operand drops the request entirely: single-pass exact into
      the f32 accumulator, and Mosaic rejects fp32 contract precision on
      bf16 inputs outright ("Bad lhs type")."""
    if (
        precision == "high"
        and a.dtype == jnp.float32
        and b.dtype == jnp.float32
        and jnp.dtype(acc_dtype) == jnp.float32
    ):
        ah, al = _split_bf16(a)
        bh, bl = _split_bf16(b)

        def d(x, y):
            return jax.lax.dot_general(
                x, y, dimension_numbers=dimension_numbers,
                preferred_element_type=acc_dtype,
            )

        return d(ah, bh) + (d(ah, bl) + d(al, bh))
    if precision == "high":
        precision = "highest"
    if precision is not None and (
        jnp.dtype(a.dtype).itemsize < 4 or jnp.dtype(b.dtype).itemsize < 4
    ):
        precision = None
    return jax.lax.dot_general(
        a, b, dimension_numbers=dimension_numbers,
        preferred_element_type=acc_dtype, precision=precision,
    )


def _make_accumulate(
    *, a_uplo, a_trans, b_uplo, b_trans, bm, bn, bk, acc_dtype, precision,
    operand_dtypes=(),
):
    """The shared inner body: mask diagonal-straddling tiles against global
    indices, contract on the MXU via precision_dot (which owns the
    Mosaic-safe precision rules), accumulate into VMEM scratch.
    operand_dtypes is kept for signature stability; the precision decision
    now reads the actual tile dtypes per call (statically identical)."""

    def accumulate(a_ref, b_ref, acc_ref, i, j, k):
        a = a_ref[:]
        b = b_ref[:]
        if a_uplo is not None:
            r0, c0 = i * bm, k * bk
            if a_trans:  # buffer holds the transposed tile
                a = _global_tri_mask(a, c0, r0, a_uplo)
            else:
                a = _global_tri_mask(a, r0, c0, a_uplo)
        if b_uplo is not None:
            r0, c0 = k * bk, j * bn
            if b_trans:
                b = _global_tri_mask(b, c0, r0, b_uplo)
            else:
                b = _global_tri_mask(b, r0, c0, b_uplo)
        dn = (((0 if a_trans else 1,), (1 if b_trans else 0,)), ((), ()))
        acc_ref[:] += precision_dot(a, b, dn, acc_dtype, precision)

    return accumulate


def _flush(acc_ref, out_ref, alpha, out_uplo, r0, c0, c_ref=None, beta=0.0):
    res = acc_ref[:]
    if alpha != 1.0:
        res = alpha * res
    if out_uplo is not None:
        res = _global_tri_mask(res, r0, c0, out_uplo)
    if c_ref is not None:
        # add at the promoted dtype so a wider C keeps its precision (and a
        # narrower one — the flagship's bf16 Schur operand next to the f32
        # accumulator — is promoted into it), matching the unfused AB+beta*C
        ct = c_ref[:]
        add_dtype = jnp.promote_types(res.dtype, ct.dtype)
        res = res.astype(add_dtype) + beta * ct.astype(add_dtype)
    out_ref[:] = res.astype(out_ref.dtype)


def _fit_block(b: int, *quantities: int) -> int:
    """Largest multiple of 128 that is <= b and divides every nonzero
    quantity (sizes and offsets of buffer views).  Returns 0 when no such
    block exists — the caller falls back to materializing the view."""
    g = 0
    for q in quantities:
        g = math.gcd(g, q)
    if g == 0:
        g = b
    if g % 128:
        return 0
    d = min(b, g) // 128 * 128
    while d >= 128 and g % d:
        d -= 128
    return d if d >= 128 else 0


def _window(buf: jnp.ndarray, view: tuple[int, int, int, int]) -> jnp.ndarray:
    r0, c0, rows, cols = view
    return lax.slice(buf, (r0, c0), (r0 + rows, c0 + cols))


def zeros_dead_lower(
    p: int,
    dtype,
    tile: int,
    extra: tuple[tuple[int, int, int, int], ...] = (),
    interpret: bool | None = None,
    dead: str = "lower",
) -> jnp.ndarray:
    """A p x p buffer whose strictly-sub-diagonal `tile`-blocks — or the
    strictly-SUPER-diagonal ones with dead='upper' (the rectri output's
    orientation) — plus any `extra` (r0, c0, rows, cols) windows are
    zero-filled; every OTHER tile is left unwritten, i.e. undefined garbage
    on hardware.

    For callers that overwrite the whole live triangle anyway (cholinv's
    factor buffers: leaf windows + TRSM/inverse-completion panels cover it
    exactly; rectri's leaf-block scatter + merge panels likewise), this
    halves the buffer-initialization HBM traffic vs jnp.zeros — ~0.8ms/iter
    at n=16k bf16 on v5e, 2x that at 32k.  Falls back to a plain jnp.zeros
    when the tiling cannot be expressed."""
    if interpret is None:
        interpret = _interpret_default()
    if tile % 128 or p % tile or tile < 128:
        return jnp.zeros((p, p), dtype)
    nt = p // tile
    if dead == "lower":
        tiles = [(i, j) for i in range(nt) for j in range(nt) if i > j]
    else:
        tiles = [(i, j) for i in range(nt) for j in range(nt) if i < j]
    for (r0, c0, rr, cc) in extra:
        if r0 % tile or c0 % tile or rr % tile or cc % tile:
            return jnp.zeros((p, p), dtype)
        tiles += [
            (r0 // tile + i, c0 // tile + j)
            for i in range(rr // tile)
            for j in range(cc // tile)
        ]
    if not tiles:
        return jnp.zeros((p, p), dtype)
    tiles = sorted(set(tiles))
    io = jnp.asarray(np.array([t[0] for t in tiles], np.int32))
    jo = jnp.asarray(np.array([t[1] for t in tiles], np.int32))

    def kernel(io_ref, jo_ref, out_ref):
        del io_ref, jo_ref
        out_ref[:] = jnp.zeros_like(out_ref)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(len(tiles),),
        in_specs=[],
        out_specs=pl.BlockSpec(
            (tile, tile), lambda q, io, jo: (io[q], jo[q]), memory_space=pltpu.VMEM
        ),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((p, p), dtype),
        interpret=interpret,
    )(io, jo)


def sched_matmul(
    A: jnp.ndarray,
    B: jnp.ndarray,
    to: jnp.ndarray,
    ko: jnp.ndarray,
    first: jnp.ndarray,
    last: jnp.ndarray,
    *,
    tri_side: str = "a",
    blocks: tuple[int, int, int],
    precision: str | None = None,
    interpret: bool | None = None,
    vmem_limit: int | None = None,
) -> jnp.ndarray:
    """C = A @ B visiting ONLY the (tile, k-tile) pairs listed in the
    RUNTIME scalar-prefetch arrays — the device-indexed schedule that
    makes per-shard tile skipping work on d > 1 meshes (round 5): each
    device of a shard_map body selects its own row of a stacked schedule
    (jnp.take by lax.axis_index) and hands it here; the grid length is
    the padded maximum, so SPMD lockstep costs nothing extra (wall time
    is the fullest device either way).

    tri_side='a': pairs are (row-tile of A/C, k-tile) — the side-L trmm
    shape; 'b': (col-tile of B/C, k-tile) — side-R.  `first`/`last` mark
    each tile's first/last live k-step (accumulator zero/flush).  Pad
    entries must REPEAT the final real pair with first=0, last=0: they
    re-accumulate into the scratch accumulator after its last flush and
    are never written back.  Operands must be pre-masked (dead triangles
    zero) — the kernel applies no intra-tile masks, so boundary tiles
    multiply zeros, exactly like the K-segment schedule it replaces."""
    if interpret is None:
        interpret = _interpret_default()
    if vmem_limit is None and not interpret:
        vmem_limit = _device_budget()[1]
    (M, K), (_, N) = A.shape, B.shape
    bm, bn, bk = blocks
    nm, nn, nk = M // bm, N // bn, K // bk
    acc_dtype = jnp.promote_types(jnp.result_type(A, B), jnp.float32)
    if jnp.dtype(acc_dtype).itemsize > 4 and _platform() == "tpu":
        acc_dtype = jnp.float32
    accumulate = _make_accumulate(
        a_uplo=None, a_trans=False, b_uplo=None, b_trans=False,
        bm=bm, bn=bn, bk=bk, acc_dtype=acc_dtype, precision=precision,
        operand_dtypes=(A.dtype, B.dtype),
    )
    a_is_tri = tri_side == "a"
    out_dtype = jnp.result_type(A, B)

    def kernel(to_ref, ko_ref, fi_ref, la_ref, a_ref, b_ref, out_ref, acc_ref):
        q, p = pl.program_id(0), pl.program_id(1)
        t, k = to_ref[p], ko_ref[p]
        i, j = (t, q) if a_is_tri else (q, t)

        @pl.when(fi_ref[p] == 1)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        accumulate(a_ref, b_ref, acc_ref, i, j, k)

        @pl.when(la_ref[p] == 1)
        def _():
            _flush(acc_ref, out_ref, 1.0, None, 0, 0)

    if a_is_tri:
        a_map = lambda q, p, to, ko, fi, la: (to[p], ko[p])
        b_map = lambda q, p, to, ko, fi, la: (ko[p], q)
        out_map = lambda q, p, to, ko, fi, la: (to[p], q)
        n_outer = nn
    else:
        a_map = lambda q, p, to, ko, fi, la: (q, ko[p])
        b_map = lambda q, p, to, ko, fi, la: (ko[p], to[p])
        out_map = lambda q, p, to, ko, fi, la: (q, to[p])
        n_outer = nm

    # callers run this under shard_map with replication checking disabled
    # (the interpret-mode carry-vma limitation), so the out_shape carries
    # no varying-axes annotation
    out_struct = jax.ShapeDtypeStruct((M, N), out_dtype)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(n_outer, to.shape[0]),
        in_specs=[
            pl.BlockSpec((bm, bk), a_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), b_map, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), out_map, memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_struct,
        cost_estimate=pl.CostEstimate(
            flops=2 * M * N * K,
            bytes_accessed=(M * K + K * N + M * N)
            * jnp.dtype(out_dtype).itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
        compiler_params=jax_compat.pallas_compiler_params(
            pltpu,
            # q sweeps distinct output tiles of the dense side — no
            # cross-step VMEM state, so it is parallel (same semantics as
            # the static trmm_kernel below); only the pair dimension p
            # carries the accumulator and must stay sequential.  Parallel
            # outer steps let Mosaic prefetch the next q's blocks while the
            # current accumulation runs instead of serializing the sweep.
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=vmem_limit,
        ),
    )(to, ko, first, last, A, B)


def write_diag_blocks(
    out: jnp.ndarray,
    W: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Write stack W[i] (count, s, s) onto the diagonal blocks
    ``out[i*s:(i+1)*s, i*s:(i+1)*s]`` in place (input_output_aliases:
    every other region of `out` is preserved, no full-buffer copy).  The
    dynamic_update_slice chain spelling of the same write costs a whole
    `out` copy (~6 ms on a 49152² bf16 buffer — the rectri batched-prefix
    write-back, round 5); this kernel touches only the visited blocks.
    The caller must treat the passed `out` as consumed.  Falls back to the
    dus chain when the block size cannot tile (s % 128 or shape mismatch).
    """
    if interpret is None:
        interpret = _interpret_default()
    count, s, s2 = W.shape
    if s != s2 or s % 128 or out.shape[0] < count * s or out.shape[0] != out.shape[1]:
        res = out
        for i in range(count):
            res = lax.dynamic_update_slice(
                res, lax.index_in_dim(W, i, keepdims=False).astype(out.dtype),
                (i * s, i * s),
            )
        return res

    def kernel(w_ref, oin_ref, out_ref):
        del oin_ref  # aliased storage; never read
        out_ref[:] = w_ref[0]

    return pl.pallas_call(
        kernel,
        grid=(count,),
        in_specs=[
            pl.BlockSpec((1, s, s), lambda q: (q, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((s, s), lambda q: (q, q), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(out.shape, out.dtype),
        input_output_aliases={1: 0},
        interpret=interpret,
    )(W.astype(out.dtype), out)


def transpose(
    X: jnp.ndarray,
    *,
    in_view: tuple[int, int, int, int] | None = None,
    out_uplo: str | None = None,
    out: jnp.ndarray | None = None,
    out_off: tuple[int, int] = (0, 0),
    out_dtype=None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Windowᵀ as an opaque custom call, optionally masked to `out_uplo` of
    the result (dead half zeroed regardless of input buffer contents).

    Why a kernel for something XLA does natively: a bare `.T` in the traced
    graph invites layout assignment to satisfy it with a *bitcast* — flipping
    the consumer chain to column-major and re-materializing row-major copies
    at every Mosaic boundary (Mosaic kernels pin {1,0} operands).  Measured on
    cholinv at n=16k/v5e, the leaf-sized `L.T`s in the base case cascaded into
    ~4.7ms/iter of full-matrix relayout copies (a 536MB transposed copy of A
    among them).  A custom call is layout-opaque: the transpose stays exactly
    as big as the window it transposes.

    View/in-place extensions (all offsets static):
      in_view  — (r0, c0, rows, cols): transpose that window of X instead of
                 all of X (no slice materialization; the index map offsets).
      out/out_off — write the (cols x rows) result into `out` at out_off and
                 return the whole updated buffer.  The write is in place
                 (pallas input_output_aliases): untouched regions of `out`
                 are preserved, so the caller must treat the passed-in value
                 as consumed.  `out is X` (self-update) is allowed when the
                 two windows are disjoint.
      out_dtype — cast inside the kernel (e.g. read a bf16 window, emit the
                 f32 panel the base-case factorization wants)."""
    if interpret is None:
        interpret = _interpret_default()
    ir0, ic0, m, n = in_view if in_view is not None else (0, 0, *X.shape)
    res_dtype = out.dtype if out is not None else (out_dtype or X.dtype)

    if in_view is None and out is None:
        # standalone: pad to lane alignment, transpose, crop
        bm = max(128, min(512, _round_up(m, 128)))
        bn = max(128, min(512, _round_up(n, 128)))
        M, N = _round_up(m, bm), _round_up(n, bn)
        if M != m or N != n:
            Xp = jnp.pad(X.astype(res_dtype), ((0, M - m), (0, N - n)))
            res = transpose(Xp, out_uplo=out_uplo, interpret=interpret)
            return res[:n, :m]
    else:
        bm = _fit_block(512, m, ir0, out_off[1])
        bn = _fit_block(512, n, ic0, out_off[0])
        if bm == 0 or bn == 0:
            # unaligned window/offsets: materialize and retry without views
            Xw = X if in_view is None else _window(X, in_view)
            res = transpose(
                Xw, out_uplo=out_uplo, out_dtype=res_dtype, interpret=interpret
            )
            if out is not None:
                return lax.dynamic_update_slice(out, res.astype(out.dtype), out_off)
            return res

    def kernel(x_ref, *rest):
        out_ref = rest[-1]
        i, j = pl.program_id(0), pl.program_id(1)  # out tile (i, j): (bn, bm)
        t = x_ref[:].T
        if out_uplo is not None:
            t = _global_tri_mask(t, i * bn, j * bm, out_uplo)
        out_ref[:] = t.astype(out_ref.dtype)

    oa = (ir0 // bm, ic0 // bn)
    oo = (out_off[0] // bn, out_off[1] // bm)
    in_specs = [
        pl.BlockSpec(
            (bm, bn), lambda i, j: (j + oa[0], i + oa[1]), memory_space=pltpu.VMEM
        )
    ]
    operands = [X]
    aliases = {}
    if out is None:
        out_shape = jax.ShapeDtypeStruct((n, m), res_dtype)
    else:
        out_shape = jax.ShapeDtypeStruct(out.shape, out.dtype)
        if out is X:
            aliases = {0: 0}
        else:
            in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
            operands.append(out)
            aliases = {1: 0}
    res = pl.pallas_call(
        kernel,
        grid=(n // bn, m // bm),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (bn, bm), lambda i, j: (i + oo[0], j + oo[1]), memory_space=pltpu.VMEM
        ),
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )(*operands)
    return res


def transpose_pair(
    L: jnp.ndarray,
    Linv: jnp.ndarray,
    Rp: jnp.ndarray,
    RIp: jnp.ndarray,
    *,
    dest: int,
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Both base-case write-back transposes in ONE pallas_call: Lᵀ masked to
    'U' lands in `Rp` at (dest, dest), Linvᵀ in `RIp`, each through its own
    input_output_alias (untouched regions preserved; the caller must treat
    the passed-in buffers as consumed).

    This is the double-buffered form of the two sequential `transpose`
    calls `_base_case_into` used to issue: one grid sweep keeps BOTH
    write-back DMA streams in flight per tile step (the second stream's
    block loads overlap the first's compute/store) and drops a whole kernel
    launch from every leaf.  Math is identical per tile — same `.T`, same
    `_global_tri_mask`, same single output cast — so the results are
    bitwise-equal to the unpaired spelling.  Falls back to two `transpose`
    calls when the window/offset cannot tile."""
    if interpret is None:
        interpret = _interpret_default()
    n = L.shape[0]
    if L.shape != (n, n) or Linv.shape != (n, n) or Rp.shape != RIp.shape:
        raise ValueError(
            f"transpose_pair wants square panels and matching buffers, got "
            f"L{L.shape} Linv{Linv.shape} Rp{Rp.shape} RIp{RIp.shape}"
        )
    bm = _fit_block(512, n, dest)
    bn = _fit_block(512, n, dest)
    if bm == 0 or bn == 0:
        Rp = transpose(L, out_uplo="U", out=Rp, out_off=(dest, dest),
                       interpret=interpret)
        RIp = transpose(Linv, out_uplo="U", out=RIp, out_off=(dest, dest),
                        interpret=interpret)
        return Rp, RIp

    def kernel(l_ref, li_ref, rp_ref, rip_ref, r_out, ri_out):
        del rp_ref, rip_ref  # aliased storage; never read
        i, j = pl.program_id(0), pl.program_id(1)
        t = _global_tri_mask(l_ref[:].T, i * bn, j * bm, "U")
        u = _global_tri_mask(li_ref[:].T, i * bn, j * bm, "U")
        r_out[:] = t.astype(r_out.dtype)
        ri_out[:] = u.astype(ri_out.dtype)

    oo = (dest // bn, dest // bm)
    return pl.pallas_call(
        kernel,
        grid=(n // bn, n // bm),
        in_specs=[
            pl.BlockSpec((bm, bn), lambda i, j: (j, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, bn), lambda i, j: (j, i), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(
                (bn, bm), lambda i, j: (i + oo[0], j + oo[1]),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (bn, bm), lambda i, j: (i + oo[0], j + oo[1]),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(Rp.shape, Rp.dtype),
            jax.ShapeDtypeStruct(RIp.shape, RIp.dtype),
        ],
        input_output_aliases={2: 0, 3: 1},
        interpret=interpret,
    )(L, Linv, Rp, RIp)


def fused_tail(
    buf: jnp.ndarray,
    Rp: jnp.ndarray,
    RIp: jnp.ndarray,
    *,
    off: int,
    n: int,
    dest: int,
    block: int = 0,
    precision: str | None = "highest",
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """An ENTIRE cholinv recursion subtree as ONE pallas_call: reads the
    (off, off, n, n) window of `buf` (upper triangle valid), factors it
    A = RᵀR and inverts the factor, writing triu(R) / triu(R⁻¹) into the
    (dest, dest, n, n) windows of `Rp` / `RIp` in place (aliased — callers
    must treat the passed-in buffers as consumed).  Returns
    (Rp, RIp, info) with info a scalar int32 in the potrf 0/k/n+1
    convention, computed in-kernel (O(n²) next to the O(n³) sweep).

    Why one kernel subsumes the whole subtree: the recursion's potrf
    panels, trsm panels, syrk trailing updates and inverse-completion
    trmms are algebraically a blocked elimination of the window — and the
    masked column sweep (`batched_small._chol`, rank-1 updates through
    one-hot contractions) IS that elimination at block size 1, while the
    back-substitution of the identity (`_bwd_solve`) assembles R⁻¹ the
    same way the completion trmms do.  Executing it as one kernel keeps
    the panel VMEM-resident across every phase boundary: no HBM
    round-trip between potrf/trsm/syrk/trmm, no per-phase launch, no
    schedule-inserted copies at the seams.  The sweep executes ~12n³
    flops against the ~n³ useful count (tracing.fused_tail_flops) — the
    same latency-over-throughput trade the batched small-N kernels make,
    and the reason the `tail_fuse_depth` gate keeps n small.

    The caller gates eligibility (`models/cholesky._tail_fusible`:
    alignment, VMEM envelope via `batched_small.tail_eligible`, dtype —
    f64 falls back to the unfused recursion at trace time).  Alignment
    contract here: off, dest and both buffer dims must be multiples of n
    (the window is addressed as one whole BlockSpec block)."""
    if interpret is None:
        interpret = _interpret_default()
    if (off % n or dest % n or buf.shape[0] % n or buf.shape[1] % n
            or Rp.shape[0] % n or Rp.shape[1] % n or Rp.shape != RIp.shape):
        raise ValueError(
            f"fused_tail alignment: off={off} dest={dest} n={n} "
            f"buf{buf.shape} Rp{Rp.shape} RIp{RIp.shape} must all be "
            "multiples of the window"
        )
    # lazy imports: batched_small imports this module at top level (the
    # shared precision_dot / budget helpers), so the building-block reuse
    # must run the other way at call time
    from capital_tpu.ops import batched_small
    from capital_tpu.utils import tracing

    bs = batched_small._resolve_block(n, block)
    io, do = off // n, dest // n

    def kernel(w_ref, rp_ref, rip_ref, r_out, ri_out, info_ref):
        del rp_ref, rip_ref  # aliased storage; never read
        w = w_ref[:].astype(jnp.float32)
        r = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
        c = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
        # symmetrize from the valid upper half (Schur windows carry only it)
        S = jnp.where(r <= c, w, w.T)
        R, info = batched_small._chol(
            S, uplo="U", block=bs, precision=precision
        )
        eye = (r == c).astype(jnp.float32)
        Rinv = batched_small._bwd_solve(
            R, eye, from_upper=True, block=bs, precision=precision
        )
        upper = r <= c
        r_out[:] = jnp.where(upper, R, 0.0).astype(r_out.dtype)
        ri_out[:] = jnp.where(upper, Rinv, 0.0).astype(ri_out.dtype)
        info_ref[0, 0] = info

    Rp2, RIp2, info = pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[
            pl.BlockSpec((n, n), lambda q: (io, io), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((n, n), lambda q: (do, do), memory_space=pltpu.VMEM),
            pl.BlockSpec((n, n), lambda q: (do, do), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda q: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(Rp.shape, Rp.dtype),
            jax.ShapeDtypeStruct(RIp.shape, RIp.dtype),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        input_output_aliases={1: 0, 2: 1},
        cost_estimate=pl.CostEstimate(
            flops=int(tracing.fused_tail_flops(n)),
            bytes_accessed=3 * n * n * jnp.dtype(Rp.dtype).itemsize,
            transcendentals=n,
        ),
        compiler_params=jax_compat.pallas_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_device_budget()[1],
        ),
        interpret=interpret,
    )(buf, Rp, RIp)
    return Rp2, RIp2, info[0, 0]


# NOTE: deliberately NOT wrapped in jax.jit.  The in-place `out` path decides
# between "alias an operand" and "append a donated buffer operand" by object
# identity (`out is A` / `out is B`); a jit boundary would hand the function
# fresh tracers for each argument, the identity test would always fail, and
# every self-updating call (e.g. cholinv's inverse completion writing one
# window of Rinv while reading another) would silently pay a full-buffer XLA
# copy — measured 31 x 1.6ms/iter at n=16k.  Callers jit the enclosing
# computation instead.
def tri_matmul(
    A: jnp.ndarray,
    B: jnp.ndarray,
    *,
    a_uplo: str | None = None,
    a_trans: bool = False,
    b_uplo: str | None = None,
    b_trans: bool = False,
    out_uplo: str | None = None,
    alpha: float = 1.0,
    blocks: tuple[int, int, int] | None = None,
    interpret: bool | None = None,
    vmem_limit: int | None = None,
    precision: str | None = None,
    a_view: tuple[int, int, int, int] | None = None,
    b_view: tuple[int, int, int, int] | None = None,
    out: jnp.ndarray | None = None,
    out_off: tuple[int, int] = (0, 0),
    c: jnp.ndarray | None = None,
    c_view: tuple[int, int, int, int] | None = None,
    beta: float = 0.0,
) -> jnp.ndarray:
    """C = alpha * op(A) @ op(B) with dead blocks of triangular operands /
    results never visited.  See module docstring.

    precision: MXU precision for the in-kernel dot_general ('highest' runs
    f32 operands through full-precision passes).  Without it f32 inputs get
    the MXU default (bf16-grade mantissa per pass): measured 7e-4 relative
    residual on an n=1000 f32 cholinv vs 2e-7 with 'highest'.

    Buffer views (all offsets/sizes static):
      a_view/b_view — (r0, c0, rows, cols): the operand is that window of the
        passed buffer (still transposed by the *_trans flag).  No slice is
        materialized; the BlockSpec index maps are offset by whole blocks.
      out/out_off — write the (m x n) result into `out` at out_off in place
        and return the whole updated buffer (pallas input_output_aliases:
        untouched regions are preserved; the caller must treat the passed-in
        `out` value as consumed).  `out` may be the same buffer as A or B
        (e.g. writing one window of a triangular factor while reading
        another) provided the read and write windows are disjoint.
        With out_uplo, the ONE supported in-place form is the syrk
        read-modify-write: out IS the C operand and out_off == the c_view
        origin — each live tile is read (beta term) and rewritten in place
        (cholinv's schur_in_place memory mode); anything else raises.

    Views require every window size/offset to be divisible by a viable block
    size (>= 128); otherwise the call transparently falls back to
    materializing the windows (and a dynamic_update_slice for `out`).

    c/c_view/beta (tri-output path only): accumulate `beta * C-window` into
    the live triangle at flush time, inside the kernel — the fused form of
    syrk's beta*C term (one C-tile read per live output tile instead of a
    full-matrix slice + add + mask pass downstream; ~3 HBM passes saved per
    call at cholinv's Schur sizes).  With beta != 0 the dead triangle of the
    result is UNDEFINED (live tiles are the only ones visited; on the
    misaligned materializing fallback it happens to hold beta*C) — callers
    must read only the out_uplo triangle.  Rounding is path-dependent for
    mixed dtypes: the aligned kernel adds C onto the f32 accumulator before
    the single output cast, while the misaligned fallback first rounds the
    product to the operand dtype and then adds at the jnp-promoted dtype
    (mode='xla' semantics) — the same call can differ by one bf16 ulp
    depending on 128-alignment of the views."""
    if a_uplo is not None and b_uplo is not None:
        raise ValueError("at most one triangular operand")
    if out_uplo is not None and (a_uplo is not None or b_uplo is not None):
        raise ValueError("out_uplo cannot combine with a triangular operand")
    inplace_rmw = (
        out_uplo is not None
        and out is not None
        and beta != 0.0
        and out is c
        and out_off == ((c_view[0], c_view[1]) if c_view is not None else (0, 0))
    )
    if out_uplo is not None and out is not None and not inplace_rmw:
        # the one supported in-place tri-output form is the syrk
        # read-modify-write: out IS the C buffer and the windows coincide,
        # so each live tile is read (beta term) and rewritten in place —
        # a single aliased operand, no copy hazard.  Anything else (fresh C
        # elsewhere, shifted windows) would need a second full-buffer
        # operand aliased against a partially-written output.
        raise ValueError(
            "in-place `out` with out_uplo requires out to BE the C operand "
            "with out_off == the c_view origin (syrk RMW)"
        )
    if beta != 0.0 and (out_uplo is None or c is None):
        raise ValueError("beta accumulation needs out_uplo and the C operand")
    if interpret is None:
        interpret = _interpret_default()
    if vmem_limit is None and not interpret:
        vmem_limit = _device_budget()[1]

    has_view = a_view is not None or b_view is not None or out is not None
    cr0, cc0 = (c_view[0], c_view[1]) if c_view is not None else (0, 0)
    ar0, ac0, arr, acc_ = a_view if a_view is not None else (0, 0, *A.shape)
    br0, bc0, brr, bcc = b_view if b_view is not None else (0, 0, *B.shape)
    (am, ak) = (acc_, arr) if a_trans else (arr, acc_)
    (bkd, bnd) = (bcc, brr) if b_trans else (brr, bcc)
    if ak != bkd:
        raise ValueError(
            f"contraction mismatch: {(am, ak)} x {(bkd, bnd)} "
            f"(A{A.shape} view {a_view}, B{B.shape} view {b_view})"
        )
    if beta != 0.0 and c is not None:
        c_dims = (c_view[2], c_view[3]) if c_view is not None else c.shape
        if c_dims != (am, bnd):
            raise ValueError(
                f"C operand {c_dims} does not match the {(am, bnd)} result"
            )

    bm, bn, bk = blocks or default_blocks(
        am, ak, bnd,
        jnp.dtype(jnp.result_type(A, B)).itemsize,
        tri_operand=(a_uplo is not None or b_uplo is not None),
    )

    fused_c = beta != 0.0 and c is not None
    if has_view or fused_c:
        # no padding possible on views: blocks must divide every window
        # size and offset exactly, else materialize and retry
        bm = _fit_block(bm, am, ac0 if a_trans else ar0,
                        out_off[0] if out is not None else 0,
                        cr0 if fused_c else 0)
        bk = _fit_block(bk, ak, ar0 if a_trans else ac0,
                        bc0 if b_trans else br0)
        bn = _fit_block(bn, bnd, br0 if b_trans else bc0,
                        out_off[1] if out is not None else 0,
                        cc0 if fused_c else 0)
        if min(bm, bn, bk) == 0:
            Am = A if a_view is None else _window(A, a_view)
            Bm = B if b_view is None else _window(B, b_view)
            res = tri_matmul(
                Am, Bm, a_uplo=a_uplo, a_trans=a_trans, b_uplo=b_uplo,
                b_trans=b_trans, out_uplo=out_uplo, alpha=alpha, blocks=blocks,
                interpret=interpret, vmem_limit=vmem_limit, precision=precision,
            )
            if fused_c:
                Cw = c if c_view is None else _window(c, c_view)
                res = res + beta * Cw  # jnp promotion: agrees with mode='xla'
            if out is not None:
                return lax.dynamic_update_slice(out, res.astype(out.dtype), out_off)
            return res
        M, K, N = am, ak, bnd
        Ap, Bp = A, B
    else:
        M, K, N = _round_up(am, bm), _round_up(ak, bk), _round_up(bnd, bn)
        pa = (M - am, K - ak) if not a_trans else (K - ak, M - am)
        pb = (K - bkd, N - bnd) if not b_trans else (N - bnd, K - bkd)
        Ap = jnp.pad(A, ((0, pa[0]), (0, pa[1]))) if any(pa) else A
        Bp = jnp.pad(B, ((0, pb[0]), (0, pb[1]))) if any(pb) else B

    nm, nk, nn = M // bm, K // bk, N // bn
    if out is not None:
        out_dtype = out.dtype
    elif fused_c:
        # C participates in the result: promote like the unfused `AB + beta*C`
        # would, so the fused path agrees with mode='xla' on mixed dtypes
        out_dtype = jnp.result_type(A, B, c)
    else:
        out_dtype = jnp.result_type(A, B)
    acc_dtype = jnp.promote_types(jnp.result_type(A, B), jnp.float32)
    if jnp.dtype(acc_dtype).itemsize > 4 and _platform() == "tpu":
        acc_dtype = jnp.float32

    accumulate = _make_accumulate(
        a_uplo=a_uplo, a_trans=a_trans, b_uplo=b_uplo, b_trans=b_trans,
        bm=bm, bn=bn, bk=bk, acc_dtype=acc_dtype, precision=precision,
        operand_dtypes=(A.dtype, B.dtype),
    )
    a_shape = (bk, bm) if a_trans else (bm, bk)
    b_shape = (bn, bk) if b_trans else (bk, bn)
    # static block offsets of each view, in that operand's buffer axes
    oa = (ar0 // a_shape[0], ac0 // a_shape[1])
    ob = (br0 // b_shape[0], bc0 // b_shape[1])
    oo = (out_off[0] // bm, out_off[1] // bn) if out is not None else (0, 0)

    if out is None:
        out_shape = jax.ShapeDtypeStruct((M, N), out_dtype)
    else:
        out_shape = jax.ShapeDtypeStruct(out.shape, out.dtype)

    def alias_setup(n_scalars: int):
        """(extra operand list, input_output_aliases) for the in-place out."""
        if out is None:
            return [], {}
        if out is A:
            return [], {n_scalars: 0}
        if out is B:
            return [], {n_scalars + 1: 0}
        return [out], {n_scalars + 2: 0}

    common = dict(
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
        cost_estimate=pl.CostEstimate(
            flops=2 * M * N * K,
            bytes_accessed=(M * K + K * N + M * N)
            * jnp.dtype(jnp.result_type(A, B)).itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )

    if a_uplo is None and b_uplo is None and out_uplo is None:
        # ---- dense: plain revisit-k blocked matmul -----------------------
        def dense_kernel(a_ref, b_ref, *rest):
            out_ref, acc_ref = rest[-2], rest[-1]
            i, j, k = pl.program_id(0), pl.program_id(1), pl.program_id(2)

            @pl.when(k == 0)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)

            accumulate(a_ref, b_ref, acc_ref, i, j, k)

            @pl.when(k == nk - 1)
            def _():
                _flush(acc_ref, out_ref, alpha, None, 0, 0)

        extra, aliases = alias_setup(0)
        in_specs = [
            pl.BlockSpec(
                a_shape,
                (lambda i, j, k: (k + oa[0], i + oa[1]))
                if a_trans
                else (lambda i, j, k: (i + oa[0], k + oa[1])),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                b_shape,
                (lambda i, j, k: (j + ob[0], k + ob[1]))
                if b_trans
                else (lambda i, j, k: (k + ob[0], j + ob[1])),
                memory_space=pltpu.VMEM,
            ),
        ] + [pl.BlockSpec(memory_space=pl.ANY) for _ in extra]
        res = pl.pallas_call(
            dense_kernel,
            grid=(nm, nn, nk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (bm, bn),
                lambda i, j, k: (i + oo[0], j + oo[1]),
                memory_space=pltpu.VMEM,
            ),
            input_output_aliases=aliases,
            compiler_params=jax_compat.pallas_compiler_params(
                pltpu,
                dimension_semantics=("parallel", "parallel", "arbitrary"),
                vmem_limit_bytes=vmem_limit,
            ),
            **common,
        )(Ap, Bp, *extra)

    elif out_uplo is not None:
        # ---- tri-output (syrk): enumerate live output tiles --------------
        pairs = [
            (i, j)
            for i in range(nm)
            for j in range(nn)
            if (i * bm < (j + 1) * bn if out_uplo == "U" else j * bn < (i + 1) * bm)
        ]
        io = jnp.asarray(np.array([p[0] for p in pairs], np.int32))
        jo = jnp.asarray(np.array([p[1] for p in pairs], np.int32))
        oc = (cr0 // bm, cc0 // bn)

        def syrk_kernel(io_ref, jo_ref, a_ref, b_ref, *rest):
            out_ref, acc_ref = rest[-2], rest[-1]
            p, k = pl.program_id(0), pl.program_id(1)
            i, j = io_ref[p], jo_ref[p]

            @pl.when(k == 0)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)

            accumulate(a_ref, b_ref, acc_ref, i, j, k)

            @pl.when(k == nk - 1)
            def _():
                _flush(
                    acc_ref, out_ref, alpha, out_uplo, i * bm, j * bn,
                    c_ref=rest[0] if fused_c else None, beta=beta,
                )

        in_specs = [
            pl.BlockSpec(
                a_shape,
                (lambda p, k, io, jo: (k + oa[0], io[p] + oa[1]))
                if a_trans
                else (lambda p, k, io, jo: (io[p] + oa[0], k + oa[1])),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                b_shape,
                (lambda p, k, io, jo: (jo[p] + ob[0], k + ob[1]))
                if b_trans
                else (lambda p, k, io, jo: (k + ob[0], jo[p] + ob[1])),
                memory_space=pltpu.VMEM,
            ),
        ]
        operands = [io, jo, Ap, Bp]
        if fused_c:
            # C tile fetched once per output tile (index map ignores k, so
            # consecutive k-steps revisit the same block without re-DMA)
            in_specs.append(
                pl.BlockSpec(
                    (bm, bn),
                    lambda p, k, io, jo: (io[p] + oc[0], jo[p] + oc[1]),
                    memory_space=pltpu.VMEM,
                )
            )
            operands.append(c)
        # in-place RMW (out is the C buffer): each live tile is read once
        # (the beta term, at its c_view offset) and written back at the same
        # absolute offset — operand index 4 = 2 scalar-prefetch args + A + B.
        # Tile-local: no other tile of the aliased buffer is ever read by
        # this call (A/B come from different buffers), so grid order is free
        # and no XLA copy is forced.  Untouched (dead-triangle) tiles keep
        # the buffer's previous contents.
        aliases = {4: 0} if inplace_rmw else {}
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(len(pairs), nk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (bm, bn),
                lambda p, k, io, jo: (io[p] + oo[0], jo[p] + oo[1]),
                memory_space=pltpu.VMEM,
            ),
            scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
        )
        res = pl.pallas_call(
            syrk_kernel,
            grid_spec=grid_spec,
            out_shape=common["out_shape"],
            cost_estimate=common["cost_estimate"],
            input_output_aliases=aliases,
            interpret=interpret,
            compiler_params=jax_compat.pallas_compiler_params(
                pltpu,
                dimension_semantics=("arbitrary", "arbitrary"),
                vmem_limit_bytes=vmem_limit,
            ),
        )(*operands)
        if not fused_c:
            # tiles in the dead half are never written by the kernel; Mosaic
            # zero-initializes outputs only per-visited-block, so blank the
            # dead half explicitly (cheap elementwise, fuses with the crop
            # below).  With fused beta*C the dead half stays UNDEFINED by
            # contract — no full-matrix mask pass.
            res = _global_tri_mask(res, 0, 0, out_uplo)

    else:
        # ---- tri-operand (trmm): enumerate live (tile-row, k) pairs ------
        if a_uplo is not None:
            pairs = [
                (i, k)
                for i in range(nm)
                for k in range(nk)
                if _a_live(i, k, bm, bk, a_uplo, a_trans)
            ]
        else:
            pairs = [
                (j, k)
                for j in range(nn)
                for k in range(nk)
                if _b_live(j, k, bn, bk, b_uplo, b_trans)
            ]
        # grid: (other-dim, pairs) — pairs innermost so each out tile is
        # revisited consecutively across its live k run
        to = jnp.asarray(np.array([p[0] for p in pairs], np.int32))
        ko = jnp.asarray(np.array([p[1] for p in pairs], np.int32))
        first = np.zeros(len(pairs), np.int32)
        last = np.zeros(len(pairs), np.int32)
        for idx, (t, _) in enumerate(pairs):
            if idx == 0 or pairs[idx - 1][0] != t:
                first[idx] = 1
            if idx == len(pairs) - 1 or pairs[idx + 1][0] != t:
                last[idx] = 1
        first = jnp.asarray(first)
        last = jnp.asarray(last)
        a_is_tri = a_uplo is not None

        def trmm_kernel(to_ref, ko_ref, fi_ref, la_ref, a_ref, b_ref, *rest):
            out_ref, acc_ref = rest[-2], rest[-1]
            q, p = pl.program_id(0), pl.program_id(1)
            t, k = to_ref[p], ko_ref[p]
            i, j = (t, q) if a_is_tri else (q, t)

            @pl.when(fi_ref[p] == 1)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)

            accumulate(a_ref, b_ref, acc_ref, i, j, k)

            @pl.when(la_ref[p] == 1)
            def _():
                _flush(acc_ref, out_ref, alpha, None, 0, 0)

        if a_is_tri:
            a_map = (
                (lambda q, p, to, ko, fi, la: (ko[p] + oa[0], to[p] + oa[1]))
                if a_trans
                else (lambda q, p, to, ko, fi, la: (to[p] + oa[0], ko[p] + oa[1]))
            )
            b_map = (
                (lambda q, p, to, ko, fi, la: (q + ob[0], ko[p] + ob[1]))
                if b_trans
                else (lambda q, p, to, ko, fi, la: (ko[p] + ob[0], q + ob[1]))
            )
            out_map = lambda q, p, to, ko, fi, la: (to[p] + oo[0], q + oo[1])
            n_outer = nn
        else:
            a_map = (
                (lambda q, p, to, ko, fi, la: (ko[p] + oa[0], q + oa[1]))
                if a_trans
                else (lambda q, p, to, ko, fi, la: (q + oa[0], ko[p] + oa[1]))
            )
            b_map = (
                (lambda q, p, to, ko, fi, la: (to[p] + ob[0], ko[p] + ob[1]))
                if b_trans
                else (lambda q, p, to, ko, fi, la: (ko[p] + ob[0], to[p] + ob[1]))
            )
            out_map = lambda q, p, to, ko, fi, la: (q + oo[0], to[p] + oo[1])
            n_outer = nm

        extra, aliases = alias_setup(4)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(n_outer, len(pairs)),
            in_specs=[
                pl.BlockSpec(a_shape, a_map, memory_space=pltpu.VMEM),
                pl.BlockSpec(b_shape, b_map, memory_space=pltpu.VMEM),
            ]
            + [pl.BlockSpec(memory_space=pl.ANY) for _ in extra],
            out_specs=pl.BlockSpec((bm, bn), out_map, memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
        )
        res = pl.pallas_call(
            trmm_kernel,
            grid_spec=grid_spec,
            out_shape=common["out_shape"],
            cost_estimate=common["cost_estimate"],
            input_output_aliases=aliases,
            interpret=interpret,
            compiler_params=jax_compat.pallas_compiler_params(
                pltpu,
                dimension_semantics=("parallel", "arbitrary"),
                vmem_limit_bytes=vmem_limit,
            ),
        )(to, ko, first, last, Ap, Bp, *extra)

    if out is not None:
        return res
    return res[:am, :bnd] if (M != am or N != bnd) else res
