"""Rank-k Cholesky update / downdate kernels: online factor maintenance.

A served workload that repeatedly modifies a matrix it already factored
(Kalman smoothers, online GPs, recursive least-squares — ROADMAP item 4)
should not pay the O(n³/3) refactor on every step: given the upper factor
R of A = RᵀR and a rank-k perturbation A' = A ± V·Vᵀ, the factor R' of A'
is reachable in O(kn²) by a sweep of (hyperbolic) rotations — the
structural latency win on top of PR 6's kernel-level one.

Two implementations behind the PR 6 dispatch contract:

* ``impl='pallas'`` — the batched-grid rotation sweep, ONE ``pallas_call``
  over ``grid=(batch,)`` (batch axis on the grid, one problem per grid
  step, f32 compute).  Per rank q and column j the classic scalar
  recurrence runs as full-width one-hot contractions (the Mosaic-safe
  idiom of ops/batched_small, whose helpers this module reuses):

      t  = v_j / R_jj
      c  = sqrt(1 + σ·t²)            σ = +1 update, −1 downdate
      R'_j,: = (R_j,: + σ·t·v) / c
      v' = (v − t·R_j,:) / c

  A downdate loses positive-definiteness exactly where c² = 1 − t² ≤ 0;
  the in-kernel info follows the potrf convention — 0 healthy, j (1-based
  column) at the first bad rotation, n+1 for off-diagonal contamination —
  and the guarded divisor keeps the sweep total so info flags, NaNs tell
  (the ops/batched_small `_chol` discipline).

* ``impl='xla'`` — a blocked J-orthogonal panel scan in the operand's own
  dtype (the f64 route: `dtype_capable` gates f64 OUT of the pallas
  kernels unconditionally, and a forced ``impl='pallas'`` falls back here
  rather than silently downgrading the precision the caller paid for —
  the no-silent-downgrade dispatch contract).  Instead of n·k explicit
  rotations, each row-panel of width p is transformed at once: with
  P = R[j:j+p, j:j+p] the pivot block and Pv = Vᵀ[:, j:j+p],

      M  = PᵀP + σ·PvᵀPv            (the updated panel gram)
      R'[j:j+p, :] = chol(M)⁻ᵀ · (Pᵀ·R[j:j+p, :] + σ·Pvᵀ·Vᵀ)
      K  = I_k − σ·Pv·M⁻¹·Pvᵀ
      Vᵀ' = chol(K)⁻¹ · (Vᵀ − (M⁻¹Pvᵀ)ᵀ·(Pᵀ·R[j:j+p, :] + σ·Pvᵀ·Vᵀ))

  Any J-orthogonal completion of the panel transform yields the same R'
  (Vᵀ' is unique up to a k×k orthogonal rotation, which the recurrence
  never observes), so the panel form is exact — and it is all level-3
  matmuls, ~(4p + 4k + 2k²/p)·n² flops at panel width p ≈ k.  Breakdown
  surfaces through chol(M)/chol(K) (robust/detect.factor_info per panel,
  min-combined to a global potrf index at panel resolution).

Serve threads these through `serve/factorcache.py` residency (the ops
become `chol_update`/`chol_downdate` bucket programs against resident
factors — docs/SERVING.md "Factor residency"); a failed downdate degrades
to a fresh refactor at the landing hook, never a silent wrong answer
(docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from capital_tpu.ops.batched_small import (
    SMALL_N_MAX,
    _batched_call,
    _gdot,
    _iota,
    _oh_row,
    _oh_col,
    _resolve_block,
    _safe_div,
    _triu,
    dtype_capable,
)
from capital_tpu.ops.pallas_tpu import _device_budget, _interpret_default
from capital_tpu.robust import detect
from capital_tpu.utils import tracing

IMPLS = ("auto", "pallas", "xla")

__all__ = [
    "IMPLS",
    "chol_update",
    "chol_downdate",
    "eligible",
    "default_impl",
    "resolve_panel",
    "dtype_capable",
]


def eligible(n: int, k: int, dtype, *,
             interpret: bool | None = None) -> bool:
    """VMEM-envelope gate for ONE problem of the rotation-sweep kernel:
    R in/out at dtype + V at dtype + the f32 working set (live factor,
    carried v row, one-hot temporaries).  Same 0.85x budget headroom and
    interpret-mode bypass as batched_small.eligible."""
    if interpret is None:
        interpret = _interpret_default()
    if interpret:
        return True
    limit = 0.85 * (_device_budget()[1] or (16 << 20))
    item = jnp.dtype(dtype).itemsize
    need = (2 * n * n + n * k) * item + 4 * (2 * n * n + 3 * n)
    return need <= limit


def default_impl(n: int, k: int, dtype, *,
                 interpret: bool | None = None) -> str:
    """Resolve impl='auto': 'pallas' where the batched-grid sweep owns the
    latency (small n, VMEM-eligible, f32-or-narrower), else 'xla'.  f64
    ALWAYS takes xla (dtype_capable) — exact dtype, no downgrade."""
    if not dtype_capable(dtype):
        return "xla"
    if n > SMALL_N_MAX:
        return "xla"
    return "pallas" if eligible(n, k, dtype, interpret=interpret) else "xla"


def resolve_panel(n: int, k: int, panel: int = 0) -> int:
    """Panel width for the blocked XLA path: ~2k rows per panel (the flop
    count is (2p + 4k + 2k²/p)n² but the trsm/cholesky dispatch overhead
    per panel pushes the measured optimum above the flop optimum of k),
    clamped to [4, 64] and decremented to the nearest divisor of n so the
    scan is rectangular — the knob the update autotune space sweeps."""
    p = min(panel or max(4, min(64, 2 * k)), n)
    while n % p:
        p -= 1
    return max(p, 1)


def _check_update(R, V, op):
    if R.ndim != 3 or R.shape[1] != R.shape[2]:
        raise ValueError(
            f"{op}: factor batch must be (batch, n, n), got {R.shape}")
    if V.ndim != 3 or V.shape[:2] != R.shape[:2]:
        raise ValueError(
            f"{op}: rank-k batch must be (batch, n, k) riding factor "
            f"{R.shape}, got {V.shape}")


def _resolve_impl(impl: str, dtype, n: int, k: int, interpret) -> str:
    if impl not in IMPLS:
        raise ValueError(f"update impl must be one of {IMPLS}, got {impl!r}")
    if impl == "auto":
        return default_impl(n, k, dtype, interpret=interpret)
    if impl == "pallas" and not dtype_capable(dtype):
        # the no-silent-downgrade dispatch contract (PR 6): the kernels
        # compute in f32, so honoring a forced 'pallas' for f64 would
        # silently downgrade the precision the caller paid for
        return "xla"
    return impl


# --------------------------------------------------------------------------
# pallas rotation sweep
# --------------------------------------------------------------------------


def _pallas_sweep(R, V, sign: float, *, block, precision, interpret):
    batch, n, _ = R.shape
    k = V.shape[-1]
    bs = _resolve_block(n, block)
    s = float(sign)  # python scalar: weak-typed in-kernel, no captured const

    def kernel(r_ref, v_ref, out_ref, info_ref):
        Rm = r_ref[0].astype(jnp.float32)
        Vm = v_ref[0].astype(jnp.float32)

        def col_step(j, carry):
            Rc, v, info = carry
            ohr = _oh_row(j, n)
            ohc = _oh_col(j, n)
            rrow = _gdot(ohr, Rc, 1, 0, precision)  # R[j, :] as (1, n)
            d = jnp.sum(rrow * ohr)
            vj = jnp.sum(v * ohr)
            t = vj / _safe_div(d)
            c2 = 1.0 + s * t * t
            good = jnp.isfinite(d) & (d > 0) & jnp.isfinite(c2) & (c2 > 0)
            info = jnp.where((info == 0) & ~good,
                             jnp.asarray(j + 1, jnp.int32), info)
            cinv = jax.lax.rsqrt(jnp.where(good, c2, jnp.float32(1.0)))
            # row j lives in columns >= j; mask the rotation's sub-diagonal
            # roundoff residue so the factor stays exactly upper
            after = (_iota((1, n), 1) >= j).astype(jnp.float32)
            newrow = (rrow + (s * t) * v) * cinv * after
            vnew = (v - t * rrow) * cinv
            Rc = Rc + _gdot(ohc, newrow - rrow, 1, 0, precision)
            return Rc, vnew, info

        def col_block(p, carry):
            for t in range(bs):
                carry = col_step(p * bs + t, carry)
            return carry

        def rank_step(q, carry):
            Rc, info = carry
            v = _gdot(_oh_row(q, k), Vm, 1, 1, precision)  # V[:, q] as row
            Rc, _, info = jax.lax.fori_loop(
                0, n // bs, col_block, (Rc, v, info))
            return Rc, info

        Rm, info = jax.lax.fori_loop(
            0, k, rank_step, (Rm, jnp.int32(0)))
        off_bad = ~jnp.all(jnp.isfinite(Rm))
        info = jnp.where((info == 0) & off_bad, jnp.int32(n + 1), info)
        out_ref[0] = _triu(Rm).astype(r_ref.dtype)
        info_ref[0, 0] = info

    R2, info = _batched_call(
        kernel, [R, V],
        [((batch, n, n), R.dtype), ((batch, 1), jnp.int32)],
        interpret=interpret,
        flops=batch * tracing.chol_update_flops(n, k),
        bytes_accessed=batch * (2 * n * n + n * k)
        * jnp.dtype(R.dtype).itemsize,
    )
    return R2, info.reshape(batch)


# --------------------------------------------------------------------------
# XLA blocked J-orthogonal panel scan (exact dtype — the f64 path)
# --------------------------------------------------------------------------


def _tri_lsolve(L, B):
    """Batched lower-triangular left solve L·X = B.  Unlike the long-n
    solves in models/blocktri (where XLA:CPU's batched triangular_solve
    degrades to an in-HLO loop), the (p, p)/(k, k) operands here are small
    enough that the batched trsm custom call wins — measured ~1.6x over
    the whole sweep vs. routing the same solves through batched LU."""
    return jax.lax.linalg.triangular_solve(
        L, B, left_side=True, lower=True, transpose_a=False)


def _xla_panel_scan(R, V, sign: float, *, panel, precision):
    batch, n, _ = R.shape
    k = V.shape[-1]
    p = resolve_panel(n, k, panel)
    npan = n // p
    s = jnp.asarray(sign, R.dtype)
    Vt0 = jnp.swapaxes(V, 1, 2)  # (batch, k, n)
    # row-panels of R; panel i's rows are untouched until the scan reaches
    # it (each rotation only modifies the current row and v), so the
    # original panels ARE the scan xs
    Rp = jnp.moveaxis(R.reshape(batch, npan, p, n), 1, 0)
    j0s = jnp.arange(npan, dtype=jnp.int32) * p

    def body(carry, xs):
        Vt, info = carry
        rp, j0 = xs  # (batch, p, n), scalar panel offset
        Pp = jax.lax.dynamic_slice_in_dim(rp, j0, p, axis=2)
        Pv = jax.lax.dynamic_slice_in_dim(Vt, j0, p, axis=2)
        M = (jnp.einsum("zij,zil->zjl", Pp, Pp, precision=precision)
             + s * jnp.einsum("zkj,zkl->zjl", Pv, Pv, precision=precision))
        Lm = jnp.linalg.cholesky(M)
        li = jax.vmap(detect.factor_info)(Lm)
        Z = (jnp.einsum("zij,zin->zjn", Pp, rp, precision=precision)
             + s * jnp.einsum("zkj,zkn->zjn", Pv, Vt, precision=precision))
        newrows = _tri_lsolve(Lm, Z)
        # Reuse Lm instead of a second factorization of M: with
        # Q = Lm⁻¹Pvᵀ the capacitance K = I − σ·PvM⁻¹Pvᵀ = I − σ·QᵀQ and
        # the carry correction WᵀZ = Pv·M⁻¹·Z = Qᵀ·newrows.
        Q = _tri_lsolve(Lm, jnp.swapaxes(Pv, 1, 2))  # (batch, p, k)
        K = (jnp.eye(k, dtype=R.dtype)
             - s * jnp.einsum("zjk,zjl->zkl", Q, Q, precision=precision))
        Lk = jnp.linalg.cholesky(K)
        ki = jax.vmap(detect.factor_info)(Lk)
        Vt = _tri_lsolve(Lk, Vt - jnp.einsum("zjk,zjn->zkn", Q, newrows,
                                             precision=precision))
        # panel-resolution breakdown info: chol(M)'s local pivot maps to
        # the exact global column j0+li; a chol(K) failure implicates the
        # whole panel and reports its first column.  First failure wins
        # (the sweep order is the rotation order).
        gi = jnp.where(li == 0, 0,
                       jnp.where(li <= p, j0 + li, jnp.int32(n + 1)))
        gi = jnp.where((gi == 0) & (ki != 0), j0 + 1, gi)
        info = jnp.where((info == 0) & (gi != 0), gi.astype(jnp.int32),
                         info)
        return (Vt, info), newrows

    (_, info), rows = jax.lax.scan(
        body, (Vt0, jnp.zeros((batch,), jnp.int32)), (Rp, j0s))
    R2 = jnp.moveaxis(rows, 0, 1).reshape(batch, n, n)
    tri = _iota((n, n), 0) <= _iota((n, n), 1)
    R2 = jnp.where(tri, R2, jnp.zeros((), R.dtype))
    off_bad = ~jnp.all(jnp.isfinite(R2), axis=(1, 2))
    info = jnp.where((info == 0) & off_bad, jnp.int32(n + 1), info)
    return R2, info


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------


def _apply(R, V, sign: float, tag: str, op: str, *, block, panel,
           precision, impl, interpret):
    _check_update(R, V, op)
    batch, n, _ = R.shape
    k = V.shape[-1]
    if interpret is None:
        interpret = _interpret_default()
    impl = _resolve_impl(impl, R.dtype, n, k, interpret)
    with tracing.scope(tag):
        tracing.emit(flops=batch * tracing.chol_update_flops(n, k))
        if impl == "pallas":
            return _pallas_sweep(R, V, sign, block=block,
                                 precision=precision, interpret=interpret)
        return _xla_panel_scan(R, V, sign, panel=panel,
                               precision=precision)


def chol_update(R, V, *, block: int = 0, panel: int = 0,
                precision: str | None = "highest", impl: str = "auto",
                interpret: bool | None = None):
    """Rank-k Cholesky UPDATE: given upper R with A = RᵀR, return
    (R', info) with R'ᵀR' = A + V·Vᵀ.  R (batch, n, n) upper, V
    (batch, n, k).  info (batch,) int32 potrf convention — an update of a
    healthy factor cannot break down, so nonzero info here means the
    input factor was already bad (non-positive diagonal)."""
    return _apply(R, V, +1.0, "UP::update", "chol_update", block=block,
                  panel=panel, precision=precision, impl=impl,
                  interpret=interpret)


def chol_downdate(R, V, *, block: int = 0, panel: int = 0,
                  precision: str | None = "highest", impl: str = "auto",
                  interpret: bool | None = None):
    """Rank-k Cholesky DOWNDATE: (R', info) with R'ᵀR' = A − V·Vᵀ.  Loses
    positive-definiteness when A − V·Vᵀ is not SPD: info flags the first
    bad rotation column (pallas) or panel pivot (xla) in the potrf
    convention, and R' is flagged garbage there — the serve landing hook
    degrades a flagged downdate to a fresh refactor from the still-intact
    resident factor (docs/ROBUSTNESS.md), never a silent wrong answer."""
    return _apply(R, V, -1.0, "UP::downdate", "chol_downdate", block=block,
                  panel=panel, precision=precision, impl=impl,
                  interpret=interpret)
