"""Local factorization kernels — the LAPACK seam of the framework.

TPU-native equivalent of the reference's LAPACK engine
(src/lapack/interface.hpp:30-89), which funnels every local factorization
through four wrappers: potrf, trtri, geqrf, orgqr.  Here the same seam maps
to lax.linalg primitives, which XLA compiles to MXU-friendly blocked
routines:

    LAPACKE_dpotrf  ->  potrf   (lax.linalg.cholesky)
    LAPACKE_dtrtri  ->  trtri   (lax.linalg.triangular_solve vs identity)
    LAPACKE_dgeqrf  ->  geqrf   (jnp.linalg.qr)   [reference wrappers exist
    LAPACKE_dorgqr  ->  orgqr   (jnp.linalg.qr)    but no algorithm calls
                                                   them — kept for parity]

These operate on *local/replicated* values: distributed algorithms gather or
replicate a panel first (see models/cholesky.py base case), exactly where the
reference gathers panels across the slice communicator before its local
LAPACK call (cholinv policy.h:160-224).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

# robust.{detect,faultinject} depend only on jax + tracing, so this import
# cannot cycle back here.  The taps are identity when no fault plan is
# active; with_info=False keeps every wrapper's signature unchanged.
from capital_tpu.robust import detect, faultinject


def _compute_dtype(dtype):
    """Panel factorizations run at >= f32: sub-f32 inputs (bf16/f16) are the
    numerically fragile case for potrf/trtri (cholinv's base_case_dtype
    principle, models/cholesky.py), and the CPU backend's LAPACK custom
    calls reject them outright — observed as NotImplementedError from a bf16
    gram in cacqr's 1d sweep on the test rig.  Results cast back to the
    input dtype."""
    return jnp.float32 if jnp.dtype(dtype).itemsize < 4 else jnp.dtype(dtype)


def potrf(A: jnp.ndarray, uplo: str = "U", with_info: bool = False):
    """Cholesky factor of SPD A: upper R with A = RᵀR (uplo='U') or lower L
    with A = LLᵀ (uplo='L').  Reference lapack::engine::_potrf
    (interface.hpp:30-44).

    with_info=True additionally returns the LAPACK-style int32 status of
    the factor (robust/detect.factor_info; 0 = clean) — lax.linalg.cholesky
    itself NaN-fills silently on breakdown."""
    A = faultinject.tap(A)
    L = lax.linalg.cholesky(A.astype(_compute_dtype(A.dtype)))
    L = L.astype(A.dtype)
    T = L.T if uplo == "U" else L
    return (T, detect.factor_info(T)) if with_info else T


def potrs(T: jnp.ndarray, B: jnp.ndarray, uplo: str = "U") -> jnp.ndarray:
    """SPD solve A·X = B from an EXISTING Cholesky factor via two triangular
    (trsm) sweeps — LAPACKE_dpotrs for this seam.  With uplo='U'
    (A = RᵀR, T = R): solve Rᵀ·Y = B then R·X = Y; with uplo='L'
    (A = LLᵀ, T = L): L·Y = B then Lᵀ·X = Y.

    Leading batch dimensions of (T, B) solve as a stack (both sweeps are one
    batched triangular_solve each), which is what serve's vmap micro-batching
    rides.  Runs at the >= f32 compute dtype like the factor itself and casts
    back once."""
    if uplo not in ("U", "L"):
        raise ValueError(f"uplo must be 'U' or 'L', got {uplo!r}")
    ct = _compute_dtype(T.dtype)
    Tc, Bc = T.astype(ct), B.astype(ct)
    lower = uplo == "L"
    # the transposed sweep comes first for 'U' (Rᵀ then R), second for 'L'
    # (L then Lᵀ); `lower` describes the stored triangle of T in both.
    Y = lax.linalg.triangular_solve(
        Tc, Bc, left_side=True, lower=lower, transpose_a=not lower
    )
    X = lax.linalg.triangular_solve(
        Tc, Y, left_side=True, lower=lower, transpose_a=lower
    )
    return X.astype(B.dtype)


def trtri(T: jnp.ndarray, uplo: str = "U", unit_diag: bool = False) -> jnp.ndarray:
    """Inverse of a triangular matrix.  Reference lapack::engine::_trtri
    (interface.hpp:46-59).  Leading batch dimensions invert as a stack in
    one batched solve (the TRSM diaginvert leaf's precompute)."""
    ct = _compute_dtype(T.dtype)
    eye = jnp.broadcast_to(jnp.eye(T.shape[-1], dtype=ct), T.shape)
    out = lax.linalg.triangular_solve(
        T.astype(ct), eye, left_side=True, lower=(uplo == "L"),
        unit_diagonal=unit_diag,
    )
    return out.astype(T.dtype)


def trtri_newton(
    D: jnp.ndarray,
    unit_diag: bool = False,
    precision: str | None = "highest",
) -> jnp.ndarray:
    """EXACT inverse of a (..., s, s) LOWER-triangular stack by the
    finite-termination Newton iteration — all batched MXU matmuls, no
    XLA:TPU triangular_solve custom call (which serializes its batch: a
    384-stack of 512-blocks runs as 384 sequential solves, ~3.9 ms at the
    rectri 49152 row vs ~0.2 ms for this spelling).

    With X₀ = diag(D)⁻¹, the residual I − D·X₀ is STRICTLY lower
    triangular, hence nilpotent of index s; the Newton step
    X ← X·(2I − D·X) squares the residual, so ⌈log₂ s⌉ steps terminate
    with the exact inverse (in exact arithmetic — in floats, to the same
    roundoff class as substitution).  Products of lower triangles are
    lower triangles even in floating point, so the structural zeros hold
    without masking.  Runs at the >= f32 compute dtype, casts back once."""
    ct = _compute_dtype(D.dtype)
    s = D.shape[-1]
    if unit_diag:
        # never read the stored diagonal (by unit-diag convention it is
        # meaningless and may be inf/nan)
        Dm = jnp.tril(D, -1).astype(ct) + jnp.eye(s, dtype=ct)
        d = jnp.ones(D.shape[:-1], dtype=ct)
    else:
        Dm = jnp.tril(D).astype(ct)
        d = jnp.diagonal(Dm, axis1=-2, axis2=-1)
    X = (1.0 / d)[..., :, None] * jnp.eye(s, dtype=ct)
    two_eye = 2.0 * jnp.eye(s, dtype=ct)
    steps = max(1, (s - 1).bit_length())
    for _ in range(steps):
        DX = jnp.matmul(Dm, X, precision=precision)
        X = jnp.matmul(X, two_eye - DX, precision=precision)
    return X.astype(D.dtype)


def diag_block_stack(X: jnp.ndarray, o: int, s: int, stride: int) -> jnp.ndarray:
    """(count, s, s) stack of the diagonal-band blocks
    ``X[..., i*stride + o : i*stride + o + s, i*stride : i*stride + s]``,
    flattened over any leading batch dim (o=0 gives the diagonal blocks
    themselves; o=s, stride=2s gives the per-pair subdiagonal blocks of a
    merge level).  Built from static lax.slice per block, NOT
    reshape+fancy-indexing: the gather form lowers to a scan of the WHOLE
    operand (measured ~2.6 ms scanning a 2.1 GB matrix for 33 MB of
    blocks — the trsm TS::dinv lesson, docs/PERF.md).  Shared by
    trtri_stack, the trsm diaginvert precompute, and the rectri batched
    prefix so the lowering fix cannot drift apart."""
    count = X.shape[-2] // stride
    lo = (0,) * (X.ndim - 2)
    parts = [
        lax.slice(
            X,
            lo + (i * stride + o, i * stride),
            X.shape[:-2] + (i * stride + o + s, i * stride + s),
        )
        for i in range(count)
    ]
    return jnp.stack(parts, axis=X.ndim - 2).reshape((-1, s, s))


def trtri_stack(
    D: jnp.ndarray,
    uplo: str = "L",
    unit_diag: bool = False,
    inner: int = 128,
    precision: str | None = None,
) -> jnp.ndarray:
    """Inverse of a (nb, bc, bc) stack of triangular blocks.

    XLA:TPU's batched triangular_solve custom call serializes its batch
    internally (measured: a batch-32 trtri of 512-blocks costs the same as
    32 sequential calls — docs/PERF.md "rectri round 4: batched-prefix
    negative result"), so the custom call is confined to `inner`-sized
    sub-blocks (16x less serialized work at bc=512/inner=128) and the
    bc-block inverses are assembled by batched MXU matmul merge levels:

        [A11  0 ]^-1   [   A11inv     0   ]
        [A21 A22]    = [-A22inv·A21·A11inv A22inv]

    `inner` is a ceiling, not an exact size: the call uses the largest
    bc/2^j <= inner (bc=384 -> 96, bc=512 -> 128), falling back to the
    plain batched trtri when halving cannot reach the ceiling (odd bc
    above it).  unit_diag applies to the stored diagonal of the inner
    blocks (Diag::AblasUnit semantics, engine.h:23-52)."""
    nb, bc = D.shape[0], D.shape[-1]
    d = bc
    while inner > 0 and d > inner and d % 2 == 0:
        d //= 2
    k = bc // d if 0 < d <= inner else 0
    inner = d
    if k <= 1:
        return trtri(D, uplo=uplo, unit_diag=unit_diag)
    lower = uplo == "L"
    if not lower:
        # one transpose each way keeps a single (lower) merge body
        return jnp.swapaxes(
            trtri_stack(
                jnp.swapaxes(D, -1, -2), "L", unit_diag, inner, precision
            ),
            -1, -2,
        )
    # the whole chain runs at the >= f32 compute dtype and casts back ONCE
    # (the module invariant): rounding W to a sub-f32 input dtype between
    # merge levels measurably compounds (1.7x the plain-trtri error on a
    # bf16 bc=256 stack).  Sub-f32 inputs also force >= 3-pass merge
    # products — the upcast buys nothing if the matmuls drop back to
    # 1-pass bf16.
    ct = _compute_dtype(D.dtype)
    if precision is None:
        # never let the merge products run at TPU-default (one-pass bf16)
        # grade — that silently degrades the block inverses below what the
        # plain batched trtri delivers (ADVICE r4).  Callers wanting speed
        # over accuracy must opt in explicitly.
        precision = "highest"
    Dm = jnp.tril(D).astype(ct)

    # inner blocks via the exact-termination Newton iteration: the batched
    # triangular_solve custom call serializes even the inner batch (round 5
    # — it was the remaining serial term of the rectri/trsm base phase)
    W = trtri_newton(
        diag_block_stack(Dm, 0, inner, inner), unit_diag=unit_diag,
        precision=precision,
    )
    s = inner
    while s < bc:
        A21 = diag_block_stack(Dm, s, s, 2 * s)
        A11i, A22i = W[0::2], W[1::2]
        M = jnp.matmul(A21, A11i, precision=precision)
        B21 = -jnp.matmul(A22i, M, precision=precision)
        W = jnp.concatenate(
            [
                jnp.concatenate([A11i, jnp.zeros_like(A11i)], axis=2),
                jnp.concatenate([B21, A22i], axis=2),
            ],
            axis=1,
        )
        s *= 2
    return W.astype(D.dtype)


def potrf_trtri(A: jnp.ndarray, uplo: str = "U", with_info: bool = False):
    """Fused base-case pair: factor + triangular inverse in one call — the
    reference base case always computes both back to back
    (cholinv policy.h:197-201).  The factor stays at the compute dtype
    between the two steps (no intermediate downcast).

    with_info=True appends the int32 breakdown status of the factor."""
    A = faultinject.tap(A)
    ct = _compute_dtype(A.dtype)
    L = lax.linalg.cholesky(A.astype(ct))
    T = L.T if uplo == "U" else L
    eye = jnp.eye(A.shape[-1], dtype=ct)
    Tinv = lax.linalg.triangular_solve(
        T, eye, left_side=True, lower=(uplo == "L")
    )
    T, Tinv = T.astype(A.dtype), Tinv.astype(A.dtype)
    return (T, Tinv, detect.factor_info(T)) if with_info else (T, Tinv)


def potrf_trtri_upper(P: jnp.ndarray, with_info: bool = False):
    """(R, R⁻¹) upper-triangular from a symmetric panel whose **upper**
    triangle holds the valid content (the lower half may be garbage — e.g. a
    Schur window produced by an uplo='U' syrk).

    Functionally potrf_trtri(symmetrize_from(P, 'U')), but with every
    transpose routed through the layout-opaque Pallas kernel
    (ops/pallas_tpu.transpose): the naive spelling plants `.T` ops at every
    recursion leaf, and XLA layout assignment answers leaf-sized transposes
    with whole-graph column-major flips + full-matrix relayout copies
    (~4.7ms/iter at n=16k on v5e).  Here cholesky/triangular_solve run in
    their native lower form (no symmetrize pass: cholesky with
    symmetrize_input=False reads only the lower triangle) and the three
    transposes stay panel-sized.

    with_info=True appends the int32 breakdown status of R."""
    from capital_tpu.ops import pallas_tpu

    P = faultinject.tap(P)
    ct = _compute_dtype(P.dtype)
    P_low = pallas_tpu.transpose(P, out_uplo="L", out_dtype=ct)
    L = lax.linalg.cholesky(P_low, symmetrize_input=False)
    eye = jnp.eye(P.shape[-1], dtype=ct)
    Linv = lax.linalg.triangular_solve(L, eye, left_side=True, lower=True)
    R = pallas_tpu.transpose(L, out_uplo="U", out_dtype=P.dtype)
    Rinv = pallas_tpu.transpose(Linv, out_uplo="U", out_dtype=P.dtype)
    return (R, Rinv, detect.factor_info(R)) if with_info else (R, Rinv)


def geqrf(A: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Householder QR returning (Q, R) — the combined geqrf+orgqr capability
    (reference interface.hpp:61-89; upstream never calls these, see
    SURVEY §2 row 9)."""
    Q, R = jnp.linalg.qr(A.astype(_compute_dtype(A.dtype)), mode="reduced")
    return Q.astype(A.dtype), R.astype(A.dtype)


def orgqr(A: jnp.ndarray) -> jnp.ndarray:
    """Explicit Q from a Householder factorization (parity wrapper)."""
    return geqrf(A)[0]
