"""Block-tridiagonal scan-step Pallas kernels: SEG chain blocks per launch.

`models/blocktri.py` factors a block-tridiagonal SPD chain

    A = [[D_1, C_2ᵀ            ],
         [C_2, D_2, C_3ᵀ       ],
         [     C_3, D_3, ...   ],
         [          ...        ]]

as A = L̃·L̃ᵀ with L̃_ii = L_i (lower Cholesky of the Schur complement
S_i = D_i − W_i·W_iᵀ) and L̃_{i,i−1} = W_i = C_i·L_{i−1}⁻ᵀ — O(nblocks·b³)
work against the dense O((nblocks·b)³).  The chain is inherently
sequential, so the models layer drives it as a `lax.scan`; THESE kernels
are the scan body: ONE ``pallas_call`` over ``grid=(batch,)`` processes
``seg`` consecutive chain blocks per problem, with the running diagonal
factor (and, fused, the running forward solution) carried in VMEM across
the in-kernel block loop — block i's factor is born in VMEM and consumed
by block i+1's triangular solve without an HBM round-trip.  ``seg`` is
the scan-segment-length knob the blocktri autotune space sweeps
(launches-per-chain vs VMEM residency); ``block`` is the same column
unroll `batched_small` sweeps.

Carried representation: the kernels store and carry **Wt = Wᵀ**, not W.
Wt_i solves the FORWARD system L_{i−1}·Wt_i = C_iᵀ (`_fwd_solve`, no
transposed-operand solve needed), the Schur update is the one-hot-safe
contraction Wtᵀ·Wt = W·Wᵀ, the forward coupling is Wtᵀ·y = W·y, and the
backward coupling is the plain product Wt_{i+1}·x_{i+1} = W_{i+1}ᵀ·x_{i+1}
— every step is a `_gdot` contraction; the single explicit transpose per
block (C_i → C_iᵀ) is an identity-matrix contraction, the one transpose
spelling Mosaic lowers well.

Uniformity contract (models layer): C_1 must be zero and the carry into
the first block is (L_0 = I, y_0 = 0), so step one computes Wt_1 = 0 and
S_1 = D_1 exactly — no special-cased first iteration, which is what lets
bucket padding prepend/append identity blocks bitwise-inertly.

Like `batched_small`, compute is f32 (sub-f32 operands upcast on VMEM
load, outputs round back on store), f64 is gated out by `dtype_capable`,
the kernels run in interpret mode off-TPU, and each problem owns its grid
step's VMEM blocks — an injected NaN corrupts exactly one problem, and
within a problem the chain only propagates it FORWARD (blocks before the
injection stay bitwise-correct).  Per-block potrf info (0 / k / b+1) is
computed in-kernel; the models layer min-combines it to a global pivot
index via `robust.detect.combine_block_infos`.

These kernels carry NO tracing scopes or emits: they run inside a
`lax.scan` body, where an emit would fire once at trace time while the
kernel executes `nsteps` times — the models layer prices the whole chain
(`tracing.blocktri_chol_flops` / `blocktri_solve_flops`) outside the scan
instead.  Only the per-call `CostEstimate` lives here.
"""

from __future__ import annotations

import jax.numpy as jnp

from capital_tpu.utils import tracing
from capital_tpu.ops.pallas_tpu import _device_budget, _interpret_default
from capital_tpu.ops.batched_small import (
    _batched_call,
    _bwd_solve,
    _chol,
    _fwd_solve,
    _gdot,
    _iota,
    _resolve_block,
    dtype_capable,
)

__all__ = [
    "step_eligible",
    "default_impl",
    "partition_inner_impl",
    "fused_forward_step",
    "factor_step",
    "forward_solve_step",
    "solve_backward_step",
    "dtype_capable",
]


def step_eligible(b: int, k: int, seg: int, dtype,
                  *, interpret: bool | None = None) -> bool:
    """VMEM-envelope gate for ONE problem of a scan-step kernel: the step's
    `seg` blocks of operands and outputs, the (b, b)/(b, k) carries, and
    the f32 working set of one block iteration (Schur complement, live
    factor, Wt, coupling temporaries) must fit the device budget.  Same
    0.85x headroom and interpret-mode bypass as `batched_small.eligible`
    — the CPU rig must ride the same route hardware does."""
    if interpret is None:
        interpret = _interpret_default()
    if interpret:
        return True
    limit = 0.85 * (_device_budget()[1] or (16 << 20))
    item = jnp.dtype(dtype).itemsize
    per_block = 2 * b * b + b * k          # D + C + B of one chain block
    need = (
        item * (2 * seg * per_block + b * b + b * k)  # in + out + carries
        + 4 * (6 * b * b + 3 * b * k)                 # f32 working set
    )
    return need <= limit


def default_impl(b: int, k: int, seg: int, dtype,
                 *, interpret: bool | None = None) -> str:
    """Resolve impl='auto' for a blocktri chain: 'pallas' where the
    scan-step kernels own the latency (f32-or-narrower, VMEM-eligible),
    else 'xla' (scan of lax.linalg primitives — the f64 fallback, same
    dispatch-gate shape as PR 6's batched_small.default_impl)."""
    if not dtype_capable(dtype):
        return "xla"
    return ("pallas"
            if step_eligible(b, k, seg, dtype, interpret=interpret)
            else "xla")


def partition_inner_impl(b: int, k: int, seg: int, dtype,
                         *, interpret: bool | None = None) -> str:
    """Resolve the INNER impl of the partitioned (Spike) chain driver:
    its interior chains substitute a widened RHS [B | F | G] of k + 2b
    columns (the two spike column-blocks ride the same sweep as the local
    solutions), so the VMEM step envelope must be checked at that width —
    a chain whose sequential posv is pallas-eligible at width k can still
    overflow the step budget once the spikes widen it.  Same f64 → xla
    gate as `default_impl`; the partition axis folds into the batch axis
    of the grid, which costs no VMEM per step."""
    return default_impl(b, k + 2 * b, seg, dtype, interpret=interpret)


# --------------------------------------------------------------------------
# in-kernel block recurrence
# --------------------------------------------------------------------------


def _eye_f32(b: int):
    return (_iota((b, b), 0) == _iota((b, b), 1)).astype(jnp.float32)


def _lower(M):
    b = M.shape[0]
    return jnp.where(_iota((b, b), 0) >= _iota((b, b), 1), M, 0.0)


def _factor_block(d, c, Lp, *, bs: int, precision):
    """One chain block of the factor recurrence, all f32 VALUES:
    Wt = Lp⁻¹·cᵀ, S = d − Wtᵀ·Wt, (L, info) = chol(S) masked lower."""
    b = d.shape[0]
    ct = _gdot(c, _eye_f32(b), 0, 0, precision)        # cᵀ via identity dot
    wt = _fwd_solve(Lp, ct, from_upper=False, block=bs, precision=precision)
    s = d - _gdot(wt, wt, 0, 0, precision)             # Wtᵀ·Wt = W·Wᵀ
    L, info = _chol(s, uplo="L", block=bs, precision=precision)
    return _lower(L), wt, info


def _check_steps(name, seg_operands, carries, b, k=None):
    for nm, x, nd in seg_operands:
        if x.ndim != 4 or x.shape[2:] != (b, b):
            raise ValueError(f"{name}: {nm} must be (batch, seg, b, b), "
                             f"got {x.shape}")
    for nm, x, shape in carries:
        if x.shape != shape:
            raise ValueError(f"{name}: carry {nm} must be {shape}, "
                             f"got {x.shape}")


# --------------------------------------------------------------------------
# scan-step kernels
# --------------------------------------------------------------------------


def fused_forward_step(D, C, B, Lc, yc, *, block: int = 0,
                       precision: str | None = "highest",
                       interpret: bool | None = None):
    """FUSED factor + forward-solve scan step: for each of `seg` chain
    blocks, factor S_i and immediately consume L_i for the forward sweep
    y_i = L_i⁻¹(b_i − Wtᵀ_i·y_{i−1}) while it is VMEM-resident — the
    factor→solve boundary of `posv_blocktri` never touches HBM.

    D, C: (batch, seg, b, b) chain blocks; B: (batch, seg, b, k) RHS;
    Lc: (batch, b, b) carried factor (I before block 1); yc: (batch, b, k)
    carried forward solution (0 before block 1).  Returns
    (L, Wt, y, info): per-block factors (batch, seg, b, b), transposed
    subdiagonal factors, forward solutions (batch, seg, b, k), and
    per-block potrf info (batch, seg) int32."""
    batch, seg, b, _ = D.shape
    k = B.shape[-1]
    _check_steps("fused_forward_step",
                 [("D", D, 4), ("C", C, 4)],
                 [("Lc", Lc, (batch, b, b)), ("yc", yc, (batch, b, k))], b)
    if B.shape != (batch, seg, b, k):
        raise ValueError(f"fused_forward_step: B must be (batch, seg, b, k),"
                         f" got {B.shape}")
    bs = _resolve_block(b, block)
    if interpret is None:
        interpret = _interpret_default()

    def kernel(d_ref, c_ref, b_ref, lc_ref, yc_ref,
               l_ref, wt_ref, y_ref, info_ref):
        Lp = lc_ref[0].astype(jnp.float32)
        yp = yc_ref[0].astype(jnp.float32)
        for s in range(seg):
            d = d_ref[0, s].astype(jnp.float32)
            c = c_ref[0, s].astype(jnp.float32)
            rhs = b_ref[0, s].astype(jnp.float32)
            L, wt, info = _factor_block(d, c, Lp, bs=bs, precision=precision)
            r = rhs - _gdot(wt, yp, 0, 0, precision)   # Wtᵀ·y_{i−1}
            y = _fwd_solve(L, r, from_upper=False, block=bs,
                           precision=precision)
            l_ref[0, s] = L.astype(d_ref.dtype)
            wt_ref[0, s] = wt.astype(d_ref.dtype)
            y_ref[0, s] = y.astype(b_ref.dtype)
            info_ref[0, s] = info
            Lp, yp = L, y

    item = jnp.dtype(B.dtype).itemsize
    L, Wt, y, info = _batched_call(
        kernel, [D, C, B, Lc, yc],
        [((batch, seg, b, b), D.dtype), ((batch, seg, b, b), D.dtype),
         ((batch, seg, b, k), B.dtype), ((batch, seg), jnp.int32)],
        interpret=interpret,
        flops=batch * (tracing.blocktri_chol_flops(seg, b)
                       + tracing.blocktri_solve_flops(seg, b, k)),
        bytes_accessed=batch * item
        * (2 * seg * (2 * b * b + b * k) + b * b + b * k),
    )
    return L, Wt, y, info


def factor_step(D, C, Lc, *, block: int = 0,
                precision: str | None = "highest",
                interpret: bool | None = None):
    """Factor-only scan step (the unfused reference the autotune space
    measures the fusion win against): `seg` blocks of the Schur-complement
    Cholesky recurrence.  Returns (L, Wt, info) shaped as in
    `fused_forward_step`."""
    batch, seg, b, _ = D.shape
    _check_steps("factor_step", [("D", D, 4), ("C", C, 4)],
                 [("Lc", Lc, (batch, b, b))], b)
    bs = _resolve_block(b, block)
    if interpret is None:
        interpret = _interpret_default()

    def kernel(d_ref, c_ref, lc_ref, l_ref, wt_ref, info_ref):
        Lp = lc_ref[0].astype(jnp.float32)
        for s in range(seg):
            d = d_ref[0, s].astype(jnp.float32)
            c = c_ref[0, s].astype(jnp.float32)
            L, wt, info = _factor_block(d, c, Lp, bs=bs, precision=precision)
            l_ref[0, s] = L.astype(d_ref.dtype)
            wt_ref[0, s] = wt.astype(d_ref.dtype)
            info_ref[0, s] = info
            Lp = L

    item = jnp.dtype(D.dtype).itemsize
    L, Wt, info = _batched_call(
        kernel, [D, C, Lc],
        [((batch, seg, b, b), D.dtype), ((batch, seg, b, b), D.dtype),
         ((batch, seg), jnp.int32)],
        interpret=interpret,
        flops=batch * tracing.blocktri_chol_flops(seg, b),
        bytes_accessed=batch * item * (4 * seg * b * b + b * b),
    )
    return L, Wt, info


def forward_solve_step(L, Wt, B, yc, *, block: int = 0,
                       precision: str | None = "highest",
                       interpret: bool | None = None):
    """Forward block-bidiagonal sweep from a ready factor: for each of
    `seg` blocks, y_i = L_i⁻¹(b_i − Wtᵀ_i·y_{i−1}).  Returns y
    (batch, seg, b, k)."""
    batch, seg, b, _ = L.shape
    k = B.shape[-1]
    _check_steps("forward_solve_step", [("L", L, 4), ("Wt", Wt, 4)],
                 [("yc", yc, (batch, b, k))], b)
    bs = _resolve_block(b, block)
    if interpret is None:
        interpret = _interpret_default()

    def kernel(l_ref, wt_ref, b_ref, yc_ref, y_ref):
        yp = yc_ref[0].astype(jnp.float32)
        for s in range(seg):
            Lf = l_ref[0, s].astype(jnp.float32)
            wt = wt_ref[0, s].astype(jnp.float32)
            rhs = b_ref[0, s].astype(jnp.float32)
            r = rhs - _gdot(wt, yp, 0, 0, precision)
            y = _fwd_solve(Lf, r, from_upper=False, block=bs,
                           precision=precision)
            y_ref[0, s] = y.astype(b_ref.dtype)
            yp = y

    item = jnp.dtype(B.dtype).itemsize
    (y,) = _batched_call(
        kernel, [L, Wt, B, yc],
        [((batch, seg, b, k), B.dtype)],
        interpret=interpret,
        flops=batch * tracing.blocktri_solve_flops(seg, b, k),
        bytes_accessed=batch * item
        * (seg * (2 * b * b + 2 * b * k) + b * k),
    )
    return y


def solve_backward_step(L, Wtn, Y, xc, *, block: int = 0,
                        precision: str | None = "highest",
                        interpret: bool | None = None):
    """Backward block-bidiagonal sweep, blocks processed in DESCENDING
    chain order inside the step (the models layer scans steps with
    ``reverse=True``): x_i = L_i⁻ᵀ(y_i − Wt_{i+1}·x_{i+1}).  `Wtn` is Wt
    shifted down one block (Wtn[:, s] = Wt of chain block s+1; the final
    chain block gets zeros, models layer contract).  `xc` carries
    x_{i+1} of the block after this step's last (0 past the chain end).
    Returns x (batch, seg, b, k)."""
    batch, seg, b, _ = L.shape
    k = Y.shape[-1]
    _check_steps("solve_backward_step", [("L", L, 4), ("Wtn", Wtn, 4)],
                 [("xc", xc, (batch, b, k))], b)
    bs = _resolve_block(b, block)
    if interpret is None:
        interpret = _interpret_default()

    def kernel(l_ref, wtn_ref, y_ref, xc_ref, x_ref):
        xn = xc_ref[0].astype(jnp.float32)
        for s in reversed(range(seg)):
            Lf = l_ref[0, s].astype(jnp.float32)
            wtn = wtn_ref[0, s].astype(jnp.float32)
            y = y_ref[0, s].astype(jnp.float32)
            r = y - _gdot(wtn, xn, 1, 0, precision)    # Wt_{i+1}·x_{i+1}
            x = _bwd_solve(Lf, r, from_upper=False, block=bs,
                           precision=precision)
            x_ref[0, s] = x.astype(y_ref.dtype)
            xn = x

    item = jnp.dtype(Y.dtype).itemsize
    (x,) = _batched_call(
        kernel, [L, Wtn, Y, xc],
        [((batch, seg, b, k), Y.dtype)],
        interpret=interpret,
        flops=batch * tracing.blocktri_solve_flops(seg, b, k),
        bytes_accessed=batch * item
        * (seg * (2 * b * b + 2 * b * k) + b * k),
    )
    return x
