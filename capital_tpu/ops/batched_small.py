"""Batched small-problem Pallas kernels: the BATCH axis on the grid.

serve's bucketed requests (n <= ~1024, latency-bound — ROADMAP item 5) ran
as a `jax.vmap` over the single-problem LAPACK seam (serve/api.py): every
problem of a bucket pays its own kernel dispatch, and every *phase*
(factor, then solve) round-trips the factor through HBM between two
launches.  At bench's flagship n=49152 that overhead is noise; at n=64 it
IS the latency.  These kernels invert the layout:

* **batch axis on the grid** — ONE ``pallas_call`` with ``grid=(batch,)``
  processes every problem of a bucket; grid step b owns problem b's VMEM
  blocks, so problems never read each other's data (an injected NaN in one
  problem corrupts exactly that grid step — the serve fault-containment
  contract survives fusion for free).
* **fused factor+solve** — ``posv`` runs the Cholesky factor AND both
  triangular-solve sweeps inside one grid step: the factor is born in
  VMEM, is consumed in VMEM, and never exists in HBM at all.  ``lstsq``
  fuses the whole CholeskyQR2 normal-equations pipeline (gram, two
  Cholesky sweeps, four triangular sweeps) the same way.  The standalone
  ``potrf`` / ``trsm`` / ``potrs`` kernels are the unfused batched-grid
  reference the autotune latency space measures the fusion win against.

In-kernel factorization strategy: the problems are small enough that a
whole (n, n) matrix is VMEM-resident, so the factor is a column sweep of
rank-1 outer-product updates over the full matrix — every step is a
one-hot contraction (``precision_dot``) or an iota-masked elementwise op,
the two families Mosaic lowers without dynamic lane slicing.  The sweep
executes ~6n³ flops against the n³/3 useful count; that trade is the
point: at small n the kernel is dispatch/HBM-bound, not MXU-bound, and
the sweep keeps every operand in VMEM.  ``block`` (columns per
``fori_loop`` iteration, a static unroll) is the tile knob the latency
autotune space sweeps (autotune/sweep.py::tune_small).

Numerics: compute is f32 (sub-f32 operands upcast on VMEM load, outputs
round back on store), contractions ride ``pallas_tpu.precision_dot`` (the
one Mosaic-safe precision rule set).  Identity problems — and the
identity-tail blocks ``masking.embed_identity_tail`` pads real problems
with — factor and solve EXACTLY (all products are 0·x or 1·x, all
divisors 1.0), so bucket padding stays invisible: zero-RHS tails solve to
exact zeros, fill problems report info=0.  Each problem carries a LAPACK
``potrf``-convention int32 info (robust/detect.factor_info: 0 healthy,
k for the first bad pivot, n+1 for off-diagonal contamination), computed
in-program — O(n²) against the O(n³) solve, always on.

Like ops/qr_fused.py, the kernels run in interpret mode off-TPU (the
tier-1 CPU rig executes the same programs) and the VMEM envelope gate
(`eligible`) is bypassed there — interpret mode has no VMEM, and routing
CPU CI differently from hardware would silently drop coverage.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from capital_tpu.utils import jax_compat, tracing
from capital_tpu.ops.pallas_tpu import (
    _device_budget,
    _interpret_default,
    precision_dot,
)

#: Largest bucket n the "auto" impl routes to these kernels.  Above it the
#: column sweep's executed-flop overhead (~18x useful) outweighs the
#: launch/HBM saving and the vmap-over-LAPACK path wins; below it the
#: problem is dispatch-bound and one fused launch owns the latency.  The
#: serve config can force either side (ServeConfig.small_n_impl).
SMALL_N_MAX = 128

IMPLS = ("auto", "vmap", "pallas", "pallas_split")


def pick_block(n: int) -> int:
    """Default column-block unroll: largest power of two <= 8 dividing n
    (bucket ladders are powers of two, so this is 8 in practice)."""
    for b in (8, 4, 2):
        if n % b == 0:
            return b
    return 1


def _resolve_block(n: int, block: int) -> int:
    b = block or pick_block(n)
    while n % b:
        b -= 1
    return max(b, 1)


def eligible(op: str, a_shape: tuple, b_shape: tuple | None, dtype,
             *, interpret: bool | None = None) -> bool:
    """VMEM-envelope gate for ONE problem of a batched-grid kernel: the
    operands plus the f32 working set of one grid step must fit the device
    budget.  Shapes are the BATCHED (batch, m, n) / (batch, n, k) bucket
    shapes every caller (api.batched 'auto', engine._small_route) holds;
    only the trailing two dims feed the per-problem footprint — the batch
    axis lives on the grid, one problem resident at a time.  Interpret mode
    bypasses (no VMEM to exhaust; CPU CI must run the same route the
    hardware does — qr_fused.fused_plan discipline)."""
    if interpret is None:
        interpret = _interpret_default()
    if interpret:
        return True
    limit = 0.85 * (_device_budget()[1] or (16 << 20))
    item = jnp.dtype(dtype).itemsize
    n = a_shape[-1]
    k = b_shape[-1] if b_shape is not None else n
    if op == "lstsq":
        m = a_shape[-2]
        # A + B blocks at dtype; gram/factor/solve working set in f32
        need = m * (n + k) * item + 4 * (4 * n * n + 3 * n * k)
    else:
        need = n * (n + k) * item + 4 * (3 * n * n + 2 * n * k)
    return need <= limit


def tail_eligible(n: int, dtype, *, interpret: bool | None = None) -> bool:
    """VMEM-envelope gate for the fused recursion-tail megakernel
    (pallas_tpu.fused_tail): one (n, n) window at `dtype` in, two (n, n)
    windows out, plus the f32 working set of the in-kernel sweep — the
    symmetrized copy, the live factor, its inverse, and the fori_loop's
    rank-1 temporaries (~5 f32 matrices, conservatively).  Same 0.85x
    budget headroom and interpret-mode bypass as `eligible` — CPU CI must
    ride the same fused route the hardware does."""
    if interpret is None:
        interpret = _interpret_default()
    if interpret:
        return True
    limit = 0.85 * (_device_budget()[1] or (16 << 20))
    item = jnp.dtype(dtype).itemsize
    need = 3 * n * n * item + 4 * (5 * n * n)
    return need <= limit


def dtype_capable(dtype) -> bool:
    """Whether the batched-grid kernels can serve this dtype without
    precision loss.  They compute in f32 (Mosaic's accumulator width), so
    f64 is OUT — unconditionally, even under a forced impl='pallas':
    routing an f64 request through them would silently downgrade the
    precision the caller paid for behind f64-labeled outputs."""
    return jnp.dtype(dtype).itemsize <= 4


def default_impl(op: str, a_shape: tuple, b_shape: tuple | None, dtype,
                 *, interpret: bool | None = None) -> str:
    """Resolve impl='auto' for one bucket from its BATCHED (batch, m, n)
    shapes: 'pallas' where the batched-grid kernels own the latency (small
    n, VMEM-eligible, f32-or-narrower), else 'vmap'.  f64 buckets ALWAYS
    take vmap (dtype_capable).  `interpret` threads to the VMEM gate —
    tests force interpret=False to exercise the hardware resolution the
    CPU rig's interpret bypass would otherwise skip."""
    if op not in ("posv", "lstsq"):
        return "vmap"
    if not dtype_capable(dtype):
        return "vmap"
    if a_shape[-1] > SMALL_N_MAX:
        return "vmap"
    return ("pallas"
            if eligible(op, a_shape, b_shape, dtype, interpret=interpret)
            else "vmap")


# --------------------------------------------------------------------------
# in-kernel building blocks.  All state is a VALUE (fori_loop carries), all
# contractions are one-hot dot_generals, all masks are 2D broadcasted_iota —
# no dynamic lane slicing, no transposes, nothing Mosaic lowers poorly.
# --------------------------------------------------------------------------


def _gdot(a, b, ca: int, cb: int, precision):
    """f32-accumulating contraction of dims (ca of a) x (cb of b) through
    the one Mosaic-safe precision rule set (pallas_tpu.precision_dot)."""
    return precision_dot(
        a, b, (((ca,), (cb,)), ((), ())), jnp.float32, precision
    )


def _iota(shape, dim):
    return jax.lax.broadcasted_iota(jnp.int32, shape, dim)


def _oh_row(j, n):
    """One-hot (1, n) f32 row selecting column j."""
    return (_iota((1, n), 1) == j).astype(jnp.float32)


def _oh_col(j, n):
    """One-hot (n, 1) f32 column selecting row j."""
    return (_iota((n, 1), 0) == j).astype(jnp.float32)


def _triu(M):
    n = M.shape[0]
    return jnp.where(_iota((n, n), 0) <= _iota((n, n), 1), M, 0.0)


def _chol(S, *, uplo: str, block: int, precision):
    """Column-sweep Cholesky of a symmetric f32 (n, n) VALUE: at column j,
    u = S[:, j]·rsqrt(S[j, j]) becomes row j of R (uplo='U'; column j of L
    for 'L') and the full rank-1 update S -= u·uᵀ zeroes row/column j, so
    leading entries of later pivots are already ~0 and the factor comes out
    triangular without masking.  Both triangles of S are read (the serve
    buckets embed exactly-symmetric operands).  Returns (factor, info) with
    the LAPACK potrf info convention; on a bad pivot the divisor is
    guarded to 1.0 and the contaminated values propagate like the raw
    lax.linalg.cholesky path would — info flags, NaNs tell.

    ``block`` columns run per fori_loop iteration (static unroll) — the
    latency-autotune tile knob (loop overhead vs program size)."""
    n = S.shape[0]

    def col_step(j, S, R, info):
        oh = _oh_row(j, n)
        ohc = _oh_col(j, n)
        col = _gdot(S, oh, 1, 1, precision)  # S[:, j] as (n, 1)
        d = jnp.sum(col * ohc)
        good = jnp.isfinite(d) & (d > 0)
        bad_at = jnp.asarray(j + 1, jnp.int32)  # 1-based potrf convention
        info = jnp.where((info == 0) & ~good, bad_at, info)
        u = col * jax.lax.rsqrt(jnp.where(good, d, jnp.float32(1.0)))
        if uplo == "U":
            R = R + _gdot(ohc, u, 1, 1, precision)  # row j := uᵀ
        else:
            R = R + _gdot(u, ohc, 1, 1, precision)  # col j := u
        S = S - _gdot(u, u, 1, 1, precision)
        return S, R, info

    def body(p, carry):
        S, R, info = carry
        for t in range(block):
            S, R, info = col_step(p * block + t, S, R, info)
        return S, R, info

    S, R, info = jax.lax.fori_loop(
        0, n // block, body, (S, jnp.zeros_like(S), jnp.int32(0))
    )
    # off-diagonal contamination with a clean diagonal: the factor_info
    # n+1 convention (robust/detect.py)
    off_bad = ~jnp.all(jnp.isfinite(R))
    info = jnp.where((info == 0) & off_bad, jnp.int32(n + 1), info)
    return R, info


def _safe_div(d):
    return jnp.where((d != 0) & jnp.isfinite(d), d, jnp.float32(1.0))


def _fwd_solve(T, B, *, from_upper: bool, block: int, precision):
    """Forward substitution L·Y = B where L is Tᵀ (T stored upper,
    from_upper=True) or T itself (stored lower).  Column j's multipliers
    are a one-hot row/column extraction of T, strictly-below-diagonal
    masked, so dead-triangle roundoff residue in T never participates."""
    n = T.shape[0]

    def col_step(j, Y):
        oh = _oh_row(j, n)
        ohc = _oh_col(j, n)
        # Tᵀ[:, j] = T[j, :] (row as column) when upper-stored, else T[:, j]
        lcol = _gdot(T, oh, 0 if from_upper else 1, 1, precision)
        d = jnp.sum(lcol * ohc)
        yrow = _gdot(oh, Y, 1, 0, precision) / _safe_div(d)  # (1, k)
        below = (_iota((n, 1), 0) > j).astype(jnp.float32)
        upd = _gdot(lcol * below, yrow, 1, 0, precision)
        return jnp.where(_iota((n, 1), 0) == j, yrow, Y - upd)

    def body(p, Y):
        for t in range(block):
            Y = col_step(p * block + t, Y)
        return Y

    return jax.lax.fori_loop(0, n // block, body, B)


def _bwd_solve(T, Y, *, from_upper: bool, block: int, precision):
    """Back substitution U·X = Y where U is T (stored upper) or Tᵀ
    (stored lower)."""
    n = T.shape[0]

    def col_step(j, Y):
        oh = _oh_row(j, n)
        ohc = _oh_col(j, n)
        ucol = _gdot(T, oh, 1 if from_upper else 0, 1, precision)
        d = jnp.sum(ucol * ohc)
        xrow = _gdot(oh, Y, 1, 0, precision) / _safe_div(d)
        above = (_iota((n, 1), 0) < j).astype(jnp.float32)
        upd = _gdot(ucol * above, xrow, 1, 0, precision)
        return jnp.where(_iota((n, 1), 0) == j, xrow, Y - upd)

    def body(p, Y):
        for t in range(block):
            Y = col_step(n - 1 - (p * block + t), Y)
        return Y

    return jax.lax.fori_loop(0, n // block, body, Y)


def _rsolve_upper(R, V, *, block: int, precision):
    """Right-side solve W·R = V for upper-triangular R (column sweep
    ascending: W[:, j] = V'[:, j]/R[j, j], then V'[:, l>j] -= W[:, j]·R[j, l])."""
    n = R.shape[0]

    def col_step(j, W):
        oh = _oh_row(j, n)
        ohc = _oh_col(j, n)
        d = jnp.sum(_gdot(R, oh, 1, 1, precision) * ohc)  # R[j, j]
        wcol = _gdot(W, oh, 1, 1, precision) / _safe_div(d)  # (n, 1)
        rrow = _gdot(oh, R, 1, 0, precision)  # R[j, :] as (1, n)
        after = (_iota((1, n), 1) > j).astype(jnp.float32)
        upd = _gdot(wcol, rrow * after, 1, 0, precision)
        return jnp.where(_iota((1, n), 1) == j, wcol, W - upd)

    def body(p, W):
        for t in range(block):
            W = col_step(p * block + t, W)
        return W

    return jax.lax.fori_loop(0, n // block, body, V)


# --------------------------------------------------------------------------
# pallas_call plumbing
# --------------------------------------------------------------------------


def _out_struct(shape, dtype, *operands):
    """qr_fused discipline: outputs carry the union of the operands'
    varying mesh axes so the kernels stay legal inside shard_map bodies."""
    vma: frozenset = frozenset()
    for r in operands:
        vma |= jax_compat.vma_of(r)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _bspec(shape):
    """Per-problem BlockSpec: block (1, *problem) at batch index b."""
    nd = len(shape)
    return pl.BlockSpec(
        (1,) + tuple(shape[1:]),
        lambda b, _nd=nd: (b,) + (0,) * (_nd - 1),
        memory_space=pltpu.VMEM,
    )


def _batched_call(kernel, inputs, out_shapes, *, interpret, flops,
                  bytes_accessed, alias_rhs=False):
    """One pallas_call over grid=(batch,): grid step b reads/writes ONLY
    problem b's blocks.  alias_rhs declares input 1 -> output 0 in-place
    reuse (posv/trsm: the RHS batch becomes the solution batch — the real
    buffer behind the engine's TPU-side RHS donation); skipped in interpret
    mode, which has no buffer assignment to alias."""
    batch = inputs[0].shape[0]
    kw = {}
    if alias_rhs and not interpret:
        kw["input_output_aliases"] = {1: 0}
    return pl.pallas_call(
        kernel,
        grid=(batch,),
        in_specs=[_bspec(a.shape) for a in inputs],
        out_specs=[_bspec(s) for s, _ in out_shapes],
        out_shape=[_out_struct(s, d, *inputs) for s, d in out_shapes],
        compiler_params=jax_compat.pallas_compiler_params(
            pltpu,
            # problems are independent: the batch dimension is parallel
            # (no cross-step VMEM state — each step's blocks are its own)
            dimension_semantics=("parallel",),
            vmem_limit_bytes=_device_budget()[1],
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(flops), bytes_accessed=int(bytes_accessed),
            transcendentals=0,
        ),
        interpret=interpret,
        **kw,
    )(*inputs)


def _check_batched(A, B=None, *, square=True, op="batched_small"):
    if A.ndim != 3 or (square and A.shape[1] != A.shape[2]):
        raise ValueError(
            f"{op}: operand batch must be (batch, n, n), got {A.shape}"
        )
    if B is not None:
        if B.ndim != 3 or B.shape[0] != A.shape[0] or B.shape[1] != A.shape[1]:
            raise ValueError(
                f"{op}: RHS batch {B.shape} does not ride operand batch "
                f"{A.shape}"
            )


# --------------------------------------------------------------------------
# public kernels
# --------------------------------------------------------------------------


def potrf(A, *, uplo: str = "U", block: int = 0,
          precision: str | None = "highest", interpret: bool | None = None):
    """Batched Cholesky: (batch, n, n) symmetric SPD -> (R, info) with R
    (batch, n, n) triangular per `uplo` (dead triangle exactly zero) and
    info (batch,) int32 in the potrf convention.  ONE pallas_call."""
    _check_batched(A, op="batched potrf")
    if uplo not in ("U", "L"):
        raise ValueError(f"uplo must be 'U' or 'L', got {uplo!r}")
    batch, n, _ = A.shape
    bs = _resolve_block(n, block)
    if interpret is None:
        interpret = _interpret_default()

    def kernel(a_ref, r_ref, info_ref):
        a = a_ref[0].astype(jnp.float32)
        R, info = _chol(a, uplo=uplo, block=bs, precision=precision)
        mask = (_iota((n, n), 0) <= _iota((n, n), 1)) if uplo == "U" else (
            _iota((n, n), 0) >= _iota((n, n), 1))
        r_ref[0] = jnp.where(mask, R, 0.0).astype(a_ref.dtype)
        info_ref[0, 0] = info

    with tracing.scope("OP::batched_small"):
        tracing.emit(flops=batch * tracing.batched_chol_flops(n))
        R, info = _batched_call(
            kernel, [A],
            [((batch, n, n), A.dtype), ((batch, 1), jnp.int32)],
            interpret=interpret,
            flops=batch * tracing.batched_chol_flops(n),
            bytes_accessed=batch * 2 * n * n * jnp.dtype(A.dtype).itemsize,
        )
    return R, info.reshape(batch)


def trsm(T, B, *, uplo: str = "U", trans: bool = False, block: int = 0,
         precision: str | None = "highest", interpret: bool | None = None):
    """Batched triangular solve op(T)·X = B over (batch, n, n) factors and
    (batch, n, k) RHS: op is T (trans=False) or Tᵀ.  ONE pallas_call."""
    _check_batched(T, B, op="batched trsm")
    if uplo not in ("U", "L"):
        raise ValueError(f"uplo must be 'U' or 'L', got {uplo!r}")
    batch, n, _ = T.shape
    k = B.shape[-1]
    bs = _resolve_block(n, block)
    if interpret is None:
        interpret = _interpret_default()
    # effective structure of op(T): upper·X = B back-substitutes
    forward = (uplo == "L") ^ trans

    def kernel(t_ref, b_ref, x_ref):
        t = t_ref[0].astype(jnp.float32)
        b = b_ref[0].astype(jnp.float32)
        if forward:
            x = _fwd_solve(t, b, from_upper=(uplo == "U"), block=bs,
                           precision=precision)
        else:
            x = _bwd_solve(t, b, from_upper=(uplo == "U"), block=bs,
                           precision=precision)
        x_ref[0] = x.astype(b_ref.dtype)

    with tracing.scope("OP::batched_small"):
        tracing.emit(flops=batch * tracing.batched_trsm_flops(n, k))
        (X,) = _batched_call(
            kernel, [T, B],
            [((batch, n, k), B.dtype)],
            interpret=interpret, alias_rhs=True,
            flops=batch * tracing.batched_trsm_flops(n, k),
            bytes_accessed=batch * (n * n + 2 * n * k)
            * jnp.dtype(B.dtype).itemsize,
        )
    return X


def potrs(T, B, *, uplo: str = "U", block: int = 0,
          precision: str | None = "highest", interpret: bool | None = None):
    """Batched SPD solve from a ready factor: both triangular sweeps in ONE
    pallas_call (the factor is read into VMEM once, both sweeps consume it
    there).  T per `uplo` convention: A = RᵀR ('U') or L·Lᵀ ('L')."""
    _check_batched(T, B, op="batched potrs")
    if uplo not in ("U", "L"):
        raise ValueError(f"uplo must be 'U' or 'L', got {uplo!r}")
    batch, n, _ = T.shape
    k = B.shape[-1]
    bs = _resolve_block(n, block)
    if interpret is None:
        interpret = _interpret_default()

    def kernel(t_ref, b_ref, x_ref):
        t = t_ref[0].astype(jnp.float32)
        b = b_ref[0].astype(jnp.float32)
        y = _fwd_solve(t, b, from_upper=(uplo == "U"), block=bs,
                       precision=precision)
        x = _bwd_solve(t, y, from_upper=(uplo == "U"), block=bs,
                       precision=precision)
        x_ref[0] = x.astype(b_ref.dtype)

    with tracing.scope("OP::batched_small"):
        tracing.emit(flops=batch * 2 * tracing.batched_trsm_flops(n, k))
        (X,) = _batched_call(
            kernel, [T, B],
            [((batch, n, k), B.dtype)],
            interpret=interpret, alias_rhs=True,
            flops=batch * 2 * tracing.batched_trsm_flops(n, k),
            bytes_accessed=batch * (n * n + 2 * n * k)
            * jnp.dtype(B.dtype).itemsize,
        )
    return X


def posv(A, B, *, uplo: str = "U", block: int = 0,
         precision: str | None = "highest", interpret: bool | None = None):
    """FUSED batched SPD solve: factor + both substitution sweeps in ONE
    pallas_call per bucket batch.  The factor never exists in HBM — it is
    produced and consumed inside grid step b's VMEM residency, which is
    the inter-phase round-trip the vmap-over-LAPACK path pays twice per
    problem.  Returns (X, info): X (batch, n, k), info (batch,) int32."""
    _check_batched(A, B, op="batched posv")
    if uplo not in ("U", "L"):
        raise ValueError(f"uplo must be 'U' or 'L', got {uplo!r}")
    batch, n, _ = A.shape
    k = B.shape[-1]
    bs = _resolve_block(n, block)
    if interpret is None:
        interpret = _interpret_default()

    def kernel(a_ref, b_ref, x_ref, info_ref):
        a = a_ref[0].astype(jnp.float32)
        b = b_ref[0].astype(jnp.float32)
        R, info = _chol(a, uplo=uplo, block=bs, precision=precision)
        y = _fwd_solve(R, b, from_upper=(uplo == "U"), block=bs,
                       precision=precision)
        x = _bwd_solve(R, y, from_upper=(uplo == "U"), block=bs,
                       precision=precision)
        x_ref[0] = x.astype(b_ref.dtype)
        info_ref[0, 0] = info

    with tracing.scope("SV::fused_posv"):
        tracing.emit(flops=batch * tracing.fused_posv_flops(n, k))
        X, info = _batched_call(
            kernel, [A, B],
            [((batch, n, k), B.dtype), ((batch, 1), jnp.int32)],
            interpret=interpret, alias_rhs=True,
            flops=batch * tracing.fused_posv_flops(n, k),
            bytes_accessed=batch * (n * n + 2 * n * k)
            * jnp.dtype(B.dtype).itemsize,
        )
    return X, info.reshape(batch)


def lstsq(A, B, *, block: int = 0, precision: str | None = "highest",
          interpret: bool | None = None):
    """FUSED batched CholeskyQR2 least squares in ONE pallas_call: per grid
    step, gram G = AᵀA and C = AᵀB are taken once from the VMEM-resident
    operand, then the whole CQR2 correction runs on (n, n) state without
    touching HBM: R1 = chol(G), G2 = R1⁻ᵀ·G·R1⁻¹ (algebraically Q1ᵀQ1 —
    A is never re-read), R2 = chol(G2), X = (R2·R1)⁻¹·R2⁻ᵀ·R1⁻ᵀ·C.
    Returns (X, info): X (batch, n, k), info = max(info1, info2)."""
    _check_batched(A, B, square=False, op="batched lstsq")
    if A.shape[1] < A.shape[2]:
        raise ValueError(
            f"batched lstsq wants tall problems, got {A.shape[1:]}"
        )
    batch, m, n = A.shape
    k = B.shape[-1]
    bs = _resolve_block(n, block)
    if interpret is None:
        interpret = _interpret_default()

    def kernel(a_ref, b_ref, x_ref, info_ref):
        a = a_ref[0].astype(jnp.float32)
        b = b_ref[0].astype(jnp.float32)
        G = _gdot(a, a, 0, 0, precision)  # AᵀA
        C = _gdot(a, b, 0, 0, precision)  # AᵀB
        R1, i1 = _chol(G, uplo="U", block=bs, precision=precision)
        V = _fwd_solve(R1, G, from_upper=True, block=bs, precision=precision)
        G2 = _rsolve_upper(R1, V, block=bs, precision=precision)
        R2, i2 = _chol(G2, uplo="U", block=bs, precision=precision)
        t1 = _fwd_solve(R1, C, from_upper=True, block=bs, precision=precision)
        t2 = _fwd_solve(R2, t1, from_upper=True, block=bs,
                        precision=precision)
        R = _gdot(_triu(R2), _triu(R1), 1, 0, precision)  # R2·R1, upper
        x = _bwd_solve(R, t2, from_upper=True, block=bs, precision=precision)
        x_ref[0] = x.astype(b_ref.dtype)
        info_ref[0, 0] = jnp.maximum(i1, i2)

    with tracing.scope("SV::fused_lstsq"):
        tracing.emit(flops=batch * tracing.fused_lstsq_flops(m, n, k))
        X, info = _batched_call(
            kernel, [A, B],
            [((batch, n, k), B.dtype), ((batch, 1), jnp.int32)],
            interpret=interpret,
            flops=batch * tracing.fused_lstsq_flops(m, n, k),
            bytes_accessed=batch * (m * n + m * k + n * k)
            * jnp.dtype(B.dtype).itemsize,
        )
    return X, info.reshape(batch)
