from capital_tpu.ops import masking  # noqa: F401
