"""Triangular masks — the TPU replacement for packed triangular storage.

The reference stores triangular matrices packed (uppertri/lowertri structure
policies, src/matrix/structure.h:37-72) to save memory and uses trmm/syrk to
save flops.  On TPU, packed storage defeats MXU tiling; the idiomatic design
(SURVEY §7.1) is dense storage + masks: masking is elementwise, fuses into the
surrounding matmul, and costs no extra HBM traffic.  These helpers are the
whole of what remains of the reference's structure-policy axis.

All functions are shard-transparent: on a P('x','y')-sharded global array the
mask computation is purely local to each shard (XLA partitions the iota).
"""

from __future__ import annotations

import jax.numpy as jnp


def triu_mask(n: int, dtype=bool) -> jnp.ndarray:
    r = jnp.arange(n)
    return (r[:, None] <= r[None, :]).astype(dtype)


def tril_mask(n: int, dtype=bool) -> jnp.ndarray:
    r = jnp.arange(n)
    return (r[:, None] >= r[None, :]).astype(dtype)


def take_triangle(A: jnp.ndarray, uplo: str) -> jnp.ndarray:
    """Zero the dead half — reference util::remove_triangle (util.hpp:266-293),
    which zeroes the half *not* kept; here `uplo` names the half to keep."""
    if uplo == "U":
        return jnp.triu(A)
    if uplo == "L":
        return jnp.tril(A)
    raise ValueError(f"uplo must be 'U' or 'L', got {uplo!r}")


def cyclic_index(n: int, d: int, tile: int) -> jnp.ndarray:
    """orig[i] = ORIGINAL row/col index stored at position i of a tile-cyclic
    layout over d devices (parallel/summa.tile_cyclic_perm): storage is d
    contiguous device chunks, chunk s holding original tiles ≡ s (mod d) in
    ascending order.  Pure iota arithmetic — shard-transparent like the
    other masks (the per-shard slice of the index vector is local)."""
    if n % (d * tile):
        raise ValueError(f"cyclic_index: {d} devices x tile {tile} must tile {n}")
    i = jnp.arange(n)
    chunk, j = i // (n // d), i % (n // d)
    return ((j // tile) * d + chunk) * tile + (j % tile)


def take_triangle_cyclic(
    A: jnp.ndarray, uplo: str, d: int, tile: int, strict: bool = False
) -> jnp.ndarray:
    """take_triangle for a matrix whose BOTH axes are stored tile-cyclically
    (the persistent layout V = X[perm][:, perm]): the triangle lives at
    ORIGINAL indices, so the mask compares the cyclic index maps instead of
    raw positions.  Elementwise like every other mask here — fuses.
    strict=True drops the diagonal (the symmetrize helper's second term)."""
    r = cyclic_index(A.shape[0], d, tile)
    c = cyclic_index(A.shape[1], d, tile)
    if uplo == "U":
        m = r[:, None] < c[None, :] if strict else r[:, None] <= c[None, :]
    elif uplo == "L":
        m = r[:, None] > c[None, :] if strict else r[:, None] >= c[None, :]
    else:
        raise ValueError(f"uplo must be 'U' or 'L', got {uplo!r}")
    return A * m.astype(A.dtype)


def embed_identity_tail(X: jnp.ndarray, rows: int, cols: int) -> jnp.ndarray:
    """Zero-pad the (m, n) matrix X to (rows, cols) and put ones where padded
    row m+j meets padded column n+j — the rank-safe rectangular pad behind
    serve's shape bucketing (serve/batching.py).

    For square X with rows == cols this is exactly diag(X, I) — the SPD-safe
    pad of models/cholesky.pad_embed_identity (diag(A, I) factors to
    diag(R, I) with no cross-talk).  For tall X the appended unit columns
    live entirely in the appended rows, so the padded gram is diag(XᵀX, I):
    full column rank is preserved and a least-squares solve against
    zero-padded RHS rows returns the original solution in X[:n].  Requires
    rows - m >= cols - n (enough new rows to host the new columns' ones).
    Pure iota masking like everything here — fuses, shard-transparent."""
    m, n = X.shape
    if rows < m or cols < n or rows - m < cols - n:
        raise ValueError(
            f"cannot embed {X.shape} into ({rows}, {cols}): need "
            f"rows >= {m} and rows - {m} >= cols - {n}"
        )
    if (rows, cols) == (m, n):
        return X
    Xp = jnp.pad(X, ((0, rows - m), (0, cols - n)))
    r = jnp.arange(rows)[:, None]
    c = jnp.arange(cols)[None, :]
    tail = (r - m == c - n) & (c >= n)
    return Xp + tail.astype(X.dtype)


def with_unit_diagonal(A: jnp.ndarray) -> jnp.ndarray:
    """Force ones on the diagonal (trmm/trsm 'Diag::AblasUnit' support,
    reference blas::Diag, engine.h:23-52)."""
    eye = jnp.eye(A.shape[-2], A.shape[-1], dtype=A.dtype)
    return A * (1 - eye) + eye


def symmetrize_from(A: jnp.ndarray, uplo: str) -> jnp.ndarray:
    """Fill the dead half from the stored half: A_sym = tri + triᵀ − diag."""
    T = take_triangle(A, uplo)
    d = jnp.diagonal(T)
    return T + T.T - jnp.diag(d)
