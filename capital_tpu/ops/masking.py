"""Triangular masks — the TPU replacement for packed triangular storage.

The reference stores triangular matrices packed (uppertri/lowertri structure
policies, src/matrix/structure.h:37-72) to save memory and uses trmm/syrk to
save flops.  On TPU, packed storage defeats MXU tiling; the idiomatic design
(SURVEY §7.1) is dense storage + masks: masking is elementwise, fuses into the
surrounding matmul, and costs no extra HBM traffic.  These helpers are the
whole of what remains of the reference's structure-policy axis.

All functions are shard-transparent: on a P('x','y')-sharded global array the
mask computation is purely local to each shard (XLA partitions the iota).
"""

from __future__ import annotations

import jax.numpy as jnp


def triu_mask(n: int, dtype=bool) -> jnp.ndarray:
    r = jnp.arange(n)
    return (r[:, None] <= r[None, :]).astype(dtype)


def tril_mask(n: int, dtype=bool) -> jnp.ndarray:
    r = jnp.arange(n)
    return (r[:, None] >= r[None, :]).astype(dtype)


def take_triangle(A: jnp.ndarray, uplo: str) -> jnp.ndarray:
    """Zero the dead half — reference util::remove_triangle (util.hpp:266-293),
    which zeroes the half *not* kept; here `uplo` names the half to keep."""
    if uplo == "U":
        return jnp.triu(A)
    if uplo == "L":
        return jnp.tril(A)
    raise ValueError(f"uplo must be 'U' or 'L', got {uplo!r}")


def with_unit_diagonal(A: jnp.ndarray) -> jnp.ndarray:
    """Force ones on the diagonal (trmm/trsm 'Diag::AblasUnit' support,
    reference blas::Diag, engine.h:23-52)."""
    eye = jnp.eye(A.shape[-2], A.shape[-1], dtype=A.dtype)
    return A * (1 - eye) + eye


def symmetrize_from(A: jnp.ndarray, uplo: str) -> jnp.ndarray:
    """Fill the dead half from the stored half: A_sym = tri + triᵀ − diag."""
    T = take_triangle(A, uplo)
    d = jnp.diagonal(T)
    return T + T.T - jnp.diag(d)
