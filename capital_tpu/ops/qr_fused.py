"""Fused Pallas kernels for CholeskyQR2's tall-skinny passes.

The 1d CQR2 pipeline (models/qr.py:_sweep_1d, reference cacqr.hpp:82-116)
is HBM-bound around three tall passes over the m x n operand:

    G1 = AᵀA          (gram, sweep 1)
    Q1 = A·R1⁻¹       (scale, sweep 1)
    G2 = Q1ᵀQ1        (gram, sweep 2)
    Q  = Q1·R2⁻¹      (scale, sweep 2)

Round-2 ran these as separate XLA/pallas products: the g=2 block-row gram
reads 1.5x the operand (the [*, nb:] trailing slab overlaps the [*, :nb]
head), and sweep 2's gram re-reads all of Q1 from HBM right after the scale
wrote it.  These kernels remove both redundancies (VERDICT r2 #3 — the
"fused gram+scaling kernel" docs/PERF.md names as the remaining lever):

* ``gram_blocked`` — one pass over A per gram: each (bm, n) row block is
  read ONCE into VMEM and the g upper block-row products are taken from it
  (G[jc:(j+1)c, jc:] += A_blk[:, jc:(j+1)c]ᵀ·A_blk[:, jc:]), accumulating
  into a VMEM-resident f32 (n, n) output revisited by every grid step.
  HBM traffic: m·n reads exactly (was 1.5 m·n).
* ``scale_gram`` — sweep 1's scale and sweep 2's gram in ONE pass: read a
  row block of A, Q_blk = A_blk·R⁻¹ via g column-block products (the
  zero lower blocks of the upper-triangular R⁻¹ are never touched:
  (g+1)/2g of dense flops), round Q_blk to the output dtype, write it, and
  accumulate G2 += Q_blkᵀQ_blk (upper block-rows) from the registers —
  sweep 2's gram costs ZERO extra HBM traffic (was a full m·n read of Q1).

The column split ``g`` is an IN-KERNEL knob (round-4, VERDICT r3 #1): all
operands of every sub-product are already VMEM-resident, so finer splits
reduce executed flops — (g+1)/2g of dense: 0.75 at g=2, 0.625 at g=4,
0.5625 at g=8 — at zero extra HBM traffic, unlike the measured XLA-level
g=4 loser (5x A reads + relayout copies, models/qr.py:_col_blocks).  The
per-dot shapes stay MXU-aligned (every block dim a 128-multiple >= 128).

Kernels require n % (g*128) == 0 and bm | m; callers fall back to the
unfused path otherwise.  The gram accumulates over row blocks in f32 (same
reduction values as the unfused blocked gram, different association order:
bitwise parity is NOT guaranteed, agreement is to roundoff —
tests/test_qr_fused.py).  The gram is taken from the ROUNDED Q_blk, exactly
like the unfused pipeline which re-reads the written bf16 Q1, so
fused/unfused see the same operand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from capital_tpu.utils import jax_compat
from capital_tpu.ops.pallas_tpu import (
    _device_budget,
    _interpret_default,
    _platform,
    platform_scope,
)


def _acc_dtype(dtype):
    """f32 accumulation for sub-f32 operands; wider operands keep their
    width (clamped to f32 on real TPU hardware, like pallas_tpu)."""
    acc = jnp.promote_types(dtype, jnp.float32)
    if jnp.dtype(acc).itemsize > 4 and _platform() == "tpu":
        acc = jnp.float32
    return acc


def _dot(a, b, acc, *, trans_a=False, precision=None):
    # the Mosaic-safe precision rules (bf16x3 for f32 'high', round-up,
    # sub-f32 drop) live in ONE place: pallas_tpu.precision_dot.  The
    # per-call bf16 split is O(bm·n) VPU work against O(bm·n²) of MXU
    # flops (~0.1% of kernel time) — hoisting it out of the g-loop is
    # deliberately not done.
    from capital_tpu.ops.pallas_tpu import precision_dot

    dn = (((0 if trans_a else 1,), (0,)), ((), ()))
    return precision_dot(a, b, dn, acc, precision)


def _out_struct(shape, dtype, *operands):
    """Out-shape struct carrying the union of the operands' varying mesh
    axes: pallas_call outputs inside a shard_map body must declare their
    vma under replication checking (check_vma) — outside shard_map the vma
    set is empty and this is a plain ShapeDtypeStruct."""
    vma: frozenset = frozenset()
    for r in operands:
        vma |= jax_compat.vma_of(r)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _pick_bm(m: int, preferred: int) -> int:
    bm = preferred
    while bm >= 256 and m % bm:
        bm //= 2
    return bm if m % bm == 0 else 0


def live_fraction(g: int) -> float:
    """Executed fraction of the dense contraction at column split g."""
    return (g + 1) / (2.0 * g) if g > 1 else 1.0


def _eligible(m: int, n: int, bm: int = 1024, g: int = 2) -> int:
    """The ONE eligibility rule for every fused tall-pass kernel (and for
    fused_ok): the g-way column split needs every block a 128-multiple of
    at least 128 (g=2 additionally demands n/2 >= 256 — at n = 512 the
    split's saving measured below its bookkeeping) and a row block that
    tiles m.  Returns the picked bm, or 0 if ineligible."""
    if g < 2 or n % (g * 128):
        return 0
    if g == 2 and n // 2 < 256:
        return 0
    return _pick_bm(m, bm)


def _shape_gate(name: str, m: int, n: int, bm: int, g: int) -> int:
    bm = _eligible(m, n, bm, g)
    if bm == 0:
        raise ValueError(
            f"{name} needs bm | m and a {g}-way 128-aligned column split "
            f"(n % {g * 128} == 0), got {(m, n)}"
        )
    return bm


def pick_g(n: int, override: int = 0) -> int:
    """Column-split auto-pick for the fused kernels: the largest g whose
    blocks stay 128-wide.  Measured on v5e (docs/PERF.md round-4 table):
    executed flops drop with g ((g+1)/2g) and the curve stays monotone to
    the 128-wide eligibility limit — 1M x 1024: 39.05/33.42/30.91 ms for
    g=2/4/8 (g=16 ineligible); 512k x 2048: 62.27 (g=8) vs 55.09 (g=16)
    ms.  Power-of-two n >= 256 take g = n/128 via the same rule; the gain
    per doubling shrinks ((g+1)/2g -> 1/2) while per-dot shapes hold at
    128, so 'largest eligible' stays right."""
    if override:
        return override if _eligible(1 << 20, n, 1024, override) else 0
    g = 2
    while n % (2 * g * 128) == 0:  # divisibility implies 128-wide blocks
        g *= 2
    return g if _eligible(1 << 20, n, 1024, g) else 0


def gram_blocked(
    A: jnp.ndarray,
    *,
    bm: int = 1024,
    g: int = 2,
    precision: str | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Upper-block-row gram of tall-skinny A at the g-way split: returns
    f32 (n, n) with block row j valid from column j·(n/g) (the strictly
    lower block triangle is zero — callers assemble the symmetric gram
    with assemble_sym).  One HBM read of A total."""
    if interpret is None:
        interpret = _interpret_default()
    m, n = A.shape
    c = n // g
    bm = _shape_gate("gram_blocked", m, n, bm, g)
    nsteps = m // bm
    acc = _acc_dtype(A.dtype)

    def kernel(a_ref, g_ref):
        i = pl.program_id(0)
        a = a_ref[:]

        @pl.when(i == 0)
        def _():
            g_ref[:] = jnp.zeros_like(g_ref)

        for j in range(g):
            g_ref[j * c:(j + 1) * c, j * c:] += _dot(
                a[:, j * c:(j + 1) * c], a[:, j * c:], acc,
                trans_a=True, precision=precision,
            )

    return pl.pallas_call(
        kernel,
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((n, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=_out_struct((n, n), acc, A),
        compiler_params=jax_compat.pallas_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_device_budget()[1],
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(2 * m * n * n * live_fraction(g)),
            bytes_accessed=m * n * jnp.dtype(A.dtype).itemsize + 4 * n * n,
            transcendentals=0,
        ),
        interpret=interpret,
    )(A)


def scale_gram(
    A: jnp.ndarray,
    Rinv: jnp.ndarray,
    *,
    bm: int = 1024,
    g: int = 2,
    precision: str | None = None,
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(Q, G) = (A @ Rinv, upper-block-row gram of Q) in one pass over A.

    Rinv must be upper triangular with true zeros below the diagonal (the
    kernel exploits the zero lower column-blocks structurally; pass it
    through jnp.triu if unsure).  Q has A's dtype (rounded before the gram
    — the operand sweep 2 would otherwise re-read); G is f32 with the same
    valid region as gram_blocked."""
    if interpret is None:
        interpret = _interpret_default()
    m, n = A.shape
    if Rinv.shape != (n, n):
        raise ValueError(f"Rinv {Rinv.shape} does not match A {A.shape}")
    c = n // g
    bm = _shape_gate("scale_gram", m, n, bm, g)
    nsteps = m // bm
    acc = _acc_dtype(A.dtype)

    def kernel(a_ref, r_ref, q_ref, g_ref):
        i = pl.program_id(0)
        a = a_ref[:]
        # Q = A @ Rinv with the g-way structure: column block j of
        # upper-triangular Rinv has zeros below row (j+1)c, so it sees
        # only A's leading (j+1)c columns — (g+1)/2g of dense flops,
        # no masking
        q = jnp.concatenate(
            [
                _dot(
                    a[:, : (j + 1) * c],
                    r_ref[0:(j + 1) * c, j * c:(j + 1) * c],
                    acc, precision=precision,
                )
                for j in range(g)
            ],
            axis=1,
        ).astype(q_ref.dtype)
        q_ref[:] = q

        @pl.when(i == 0)
        def _():
            g_ref[:] = jnp.zeros_like(g_ref)

        # sweep-2 gram from the rounded block, straight from registers
        for j in range(g):
            g_ref[j * c:(j + 1) * c, j * c:] += _dot(
                q[:, j * c:(j + 1) * c], q[:, j * c:], acc,
                trans_a=True, precision=precision,
            )

    Q, G = pl.pallas_call(
        kernel,
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((n, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((n, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _out_struct((m, n), A.dtype, A, Rinv),
            _out_struct((n, n), acc, A, Rinv),
        ],
        compiler_params=jax_compat.pallas_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_device_budget()[1],
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(2 * m * n * n * 2 * live_fraction(g)),  # scale + gram
            bytes_accessed=2 * m * n * jnp.dtype(A.dtype).itemsize + 4 * n * n,
            transcendentals=0,
        ),
        interpret=interpret,
    )(A, Rinv)
    return Q, G


def scale_blocked(
    A: jnp.ndarray,
    Rinv: jnp.ndarray,
    *,
    bm: int = 1024,
    g: int = 2,
    precision: str | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Q = A @ Rinv (upper-triangular Rinv with true zeros below, g-way
    structure) — the scale half of scale_gram without the gram.  Used for
    CQR2's FINAL scale: same column-block-dot structure that measures
    191 TF/s executed on v5e at g=2, vs 153 for the live-tile trmm kernel
    at (1024, 512, 512) blocks on the same math (the trmm kernel pays
    per-pair bookkeeping and a bk=512 K-split; this shape needs neither)."""
    if interpret is None:
        interpret = _interpret_default()
    m, n = A.shape
    if Rinv.shape != (n, n):
        raise ValueError(f"Rinv {Rinv.shape} does not match A {A.shape}")
    c = n // g
    bm = _shape_gate("scale_blocked", m, n, bm, g)
    acc = _acc_dtype(A.dtype)

    def kernel(a_ref, r_ref, q_ref):
        a = a_ref[:]
        q_ref[:] = jnp.concatenate(
            [
                _dot(
                    a[:, : (j + 1) * c],
                    r_ref[0:(j + 1) * c, j * c:(j + 1) * c],
                    acc, precision=precision,
                )
                for j in range(g)
            ],
            axis=1,
        ).astype(q_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((n, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=_out_struct((m, n), A.dtype, A, Rinv),
        compiler_params=jax_compat.pallas_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_device_budget()[1],
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(2 * m * n * n * live_fraction(g)),
            bytes_accessed=2 * m * n * jnp.dtype(A.dtype).itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(A, Rinv)


def assemble_sym(Gu: jnp.ndarray, c: int) -> jnp.ndarray:
    """Symmetric gram from the upper-block-row form with block width c
    (every strictly lower block is the transpose of its mirror) — n²
    elementwise, negligible next to the tall passes."""
    n = Gu.shape[0]
    for i in range(1, n // c):
        Gu = Gu.at[i * c:(i + 1) * c, : i * c].set(Gu[: i * c, i * c:(i + 1) * c].T)
    return Gu


def fused_plan(grid, m: int, n: int, mode: str, bm: int = 1024, g: int = 2,
               *, dtype) -> str | None:
    """Which fused CQR2 pipeline can run?  Returns

      'full'  — the three-kernel pipeline with scale_gram (sweep 1's scale
                and sweep 2's gram share one pass; 5 HBM passes total);
      'split' — the wide-n streaming tier (round 5, VERDICT r4 #3):
                scale_gram's envelope (A block + Rinv + Q block + f32 gram
                all VMEM-resident, ~112 MB at n=4096 bf16 — a compile-time
                vmem OOM) is exceeded, but gram_blocked's (one row block +
                the gram) and scale_blocked's (two row blocks + Rinv) still
                fit, so sweep 2's gram runs as its own gram_blocked pass
                over the written Q1.  Costs ONE extra read of Q1 (6 passes
                instead of 5) and keeps every in-kernel g-way flop saving —
                at wide n the pipeline is MXU-bound (arithmetic intensity
                ~n/6 flops/byte), so the extra pass is noise next to the
                (g+1)/2g executed-flop drop;
      None    — fall back to the unfused blocked sweeps.

    Gating: pallas mode, the shared kernel eligibility rule (_eligible)
    applied to the PER-SHARD row extent (on a mesh the kernels run per
    shard inside shard_map — models/qr.py _cqr2_fused_sharded — so
    eligibility is about each device's m/p rows), and the per-kernel VMEM
    envelopes above."""
    p = grid.num_devices
    if p > 1 and m % p:
        return None  # shard_map needs the row axis to divide evenly
    bm_ok = _eligible(m // p, n, bm, g)
    if not (mode == "pallas" and bm_ok):
        return None
    # resolve interpret/VMEM against the GRID's platform, not the process
    # default: callers outside a scoped entry point (e.g. the multichip
    # dryrun probing eligibility) must not touch the default backend
    with platform_scope(getattr(grid, "platform", None)):
        if _interpret_default():
            # interpret mode has no VMEM: applying the hardware envelope
            # here would route the CPU test rig differently from v5e (fused
            # wide-n coverage would silently vanish from CI)
            return "full"
        item = jnp.dtype(dtype).itemsize
        limit = 0.85 * (_device_budget()[1] or (16 << 20))
        if 2 * bm_ok * n * item + n * n * (item + 4) <= limit:
            return "full"
        gram_res = bm_ok * n * item + 4 * n * n
        scale_res = 2 * bm_ok * n * item + n * n * item
        if max(gram_res, scale_res) <= limit:
            return "split"
        if n % 512 == 0:
            # beyond every kernel envelope: the XLA-level panel pipeline
            # (models/qr.py _cqr2_panels) — same (g+1)/2g saving, no VMEM
            # constraint; at these widths the pipeline is MXU-bound
            # (arithmetic intensity ~n/(g+1) flops/byte), so the extra
            # panel reads the round-4 n=1024 measurement rejected are
            # noise here
            return "panels"
        return None


def fused_ok(grid, m: int, n: int, mode: str, bm: int = 1024, g: int = 2,
             *, dtype) -> bool:
    """True when ANY fused pipeline tier can run (see fused_plan)."""
    return fused_plan(grid, m, n, mode, bm, g, dtype=dtype) is not None
