"""Fused Pallas kernels for CholeskyQR2's tall-skinny passes.

The 1d CQR2 pipeline (models/qr.py:_sweep_1d, reference cacqr.hpp:82-116)
is HBM-bound around three tall passes over the m x n operand:

    G1 = AᵀA          (gram, sweep 1)
    Q1 = A·R1⁻¹       (scale, sweep 1)
    G2 = Q1ᵀQ1        (gram, sweep 2)
    Q  = Q1·R2⁻¹      (scale, sweep 2)

Round-2 ran these as separate XLA/pallas products: the g=2 block-row gram
reads 1.5x the operand (the [*, nb:] trailing slab overlaps the [*, :nb]
head), and sweep 2's gram re-reads all of Q1 from HBM right after the scale
wrote it.  These kernels remove both redundancies (VERDICT r2 #3 — the
"fused gram+scaling kernel" docs/PERF.md names as the remaining lever):

* ``gram_blocked`` — one pass over A per gram: each (bm, n) row block is
  read ONCE into VMEM and both upper block-row products are taken from it
  (G[:nb, :] += A_blkᵀ[:, :nb]·A_blk and G[nb:, nb:] += the trailing
  square), accumulating into a VMEM-resident f32 (n, n) output revisited
  by every grid step.  HBM traffic: m·n reads exactly (was 1.5 m·n).
* ``scale_gram`` — sweep 1's scale and sweep 2's gram in ONE pass: read a
  row block of A, Q_blk = A_blk·R⁻¹ via two column-block products (the
  zero lower blocks of the upper-triangular R⁻¹ are never touched: 3/4 of
  dense flops), round Q_blk to the output dtype, write it, and accumulate
  G2 += Q_blkᵀQ_blk (upper block-rows) from the registers — sweep 2's
  gram costs ZERO extra HBM traffic (was a full m·n read of Q1).

Both kernels require the g=2 column split (n/2 a 128-multiple — the only
split that wins, models/qr.py:_col_blocks) and bm | m; callers fall back
to the unfused path otherwise.  The gram accumulates over row blocks in
f32 (same reduction values as the unfused blocked gram, different
association order: bitwise parity is NOT guaranteed, agreement is to
roundoff — tests/test_qr_fused.py).  The gram is taken from the ROUNDED
Q_blk, exactly like the unfused pipeline which re-reads the written bf16
Q1, so fused/unfused see the same operand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from capital_tpu.ops.pallas_tpu import _device_budget, _interpret_default, _platform


def _acc_dtype(dtype):
    """f32 accumulation for sub-f32 operands; wider operands keep their
    width (clamped to f32 on real TPU hardware, like pallas_tpu)."""
    acc = jnp.promote_types(dtype, jnp.float32)
    if jnp.dtype(acc).itemsize > 4 and _platform() == "tpu":
        acc = jnp.float32
    return acc


def _dot(a, b, acc, *, trans_a=False, precision=None):
    dn = (((0 if trans_a else 1,), (0,)), ((), ()))
    return jax.lax.dot_general(
        a, b, dimension_numbers=dn,
        preferred_element_type=acc, precision=precision,
    )


def _pick_bm(m: int, preferred: int) -> int:
    bm = preferred
    while bm >= 256 and m % bm:
        bm //= 2
    return bm if m % bm == 0 else 0


def _eligible(m: int, n: int, bm: int = 1024) -> int:
    """The ONE eligibility rule for every fused tall-pass kernel (and for
    fused_ok): g=2 column split (n % 256 == 0, n/2 a 128-multiple of at
    least 256 — the only split that wins, models/qr.py:_col_blocks) and a
    row block that tiles m.  Returns the picked bm, or 0 if ineligible."""
    if n % 256 or (n // 2) % 128 or n // 2 < 256:
        return 0
    return _pick_bm(m, bm)


def _shape_gate(name: str, m: int, n: int, bm: int) -> int:
    bm = _eligible(m, n, bm)
    if bm == 0:
        raise ValueError(
            f"{name} needs bm | m and the g=2 split (n % 256 == 0, "
            f"n/2 >= 256), got {(m, n)}"
        )
    return bm


def gram_blocked(
    A: jnp.ndarray,
    *,
    bm: int = 1024,
    precision: str | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Upper-block-row gram of tall-skinny A at the g=2 split: returns f32
    (n, n) with rows [:nb] full and the [nb:, nb:] trailing square valid
    (the strictly-lower [nb:, :nb] block is zero — callers assemble the
    symmetric gram with one small transpose).  One HBM read of A total."""
    if interpret is None:
        interpret = _interpret_default()
    m, n = A.shape
    nb = n // 2
    bm = _shape_gate("gram_blocked", m, n, bm)
    nsteps = m // bm
    acc = _acc_dtype(A.dtype)

    def kernel(a_ref, g_ref):
        i = pl.program_id(0)
        a = a_ref[:]

        @pl.when(i == 0)
        def _():
            g_ref[:] = jnp.zeros_like(g_ref)

        g_ref[0:nb, :] += _dot(a[:, 0:nb], a, acc, trans_a=True, precision=precision)
        g_ref[nb:, nb:] += _dot(
            a[:, nb:], a[:, nb:], acc, trans_a=True, precision=precision
        )

    return pl.pallas_call(
        kernel,
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((n, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, n), acc),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_device_budget()[1],
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * n * 3 // 4,
            bytes_accessed=m * n * jnp.dtype(A.dtype).itemsize + 4 * n * n,
            transcendentals=0,
        ),
        interpret=interpret,
    )(A)


def scale_gram(
    A: jnp.ndarray,
    Rinv: jnp.ndarray,
    *,
    bm: int = 1024,
    precision: str | None = None,
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(Q, G) = (A @ Rinv, upper-block-row gram of Q) in one pass over A.

    Rinv must be upper triangular with true zeros below the diagonal (the
    kernel exploits the zero lower column-blocks structurally; pass it
    through jnp.triu if unsure).  Q has A's dtype (rounded before the gram
    — the operand sweep 2 would otherwise re-read); G is f32 with the same
    valid region as gram_blocked."""
    if interpret is None:
        interpret = _interpret_default()
    m, n = A.shape
    if Rinv.shape != (n, n):
        raise ValueError(f"Rinv {Rinv.shape} does not match A {A.shape}")
    nb = n // 2
    bm = _shape_gate("scale_gram", m, n, bm)
    nsteps = m // bm
    acc = _acc_dtype(A.dtype)

    def kernel(a_ref, r_ref, q_ref, g_ref):
        i = pl.program_id(0)
        a = a_ref[:]
        # Q = A @ Rinv with the g=2 structure: the lower-left (nb, nb)
        # block of upper-triangular Rinv is zero, so the head columns see
        # only A's head columns — 3/4 of the dense flops, no masking
        q_head = _dot(a[:, 0:nb], r_ref[0:nb, 0:nb], acc, precision=precision)
        q_tail = _dot(a, r_ref[:, nb:], acc, precision=precision)
        q = jnp.concatenate([q_head, q_tail], axis=1).astype(q_ref.dtype)
        q_ref[:] = q

        @pl.when(i == 0)
        def _():
            g_ref[:] = jnp.zeros_like(g_ref)

        # sweep-2 gram from the rounded block, straight from registers
        g_ref[0:nb, :] += _dot(q[:, 0:nb], q, acc, trans_a=True, precision=precision)
        g_ref[nb:, nb:] += _dot(
            q[:, nb:], q[:, nb:], acc, trans_a=True, precision=precision
        )

    Q, G = pl.pallas_call(
        kernel,
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((n, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((n, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), A.dtype),
            jax.ShapeDtypeStruct((n, n), acc),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_device_budget()[1],
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * n * 3 // 2,  # 3/4 scale + 3/4 gram
            bytes_accessed=2 * m * n * jnp.dtype(A.dtype).itemsize + 4 * n * n,
            transcendentals=0,
        ),
        interpret=interpret,
    )(A, Rinv)
    return Q, G


def scale_blocked(
    A: jnp.ndarray,
    Rinv: jnp.ndarray,
    *,
    bm: int = 1024,
    precision: str | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Q = A @ Rinv (upper-triangular Rinv with true zeros below, g=2
    structure) — the scale half of scale_gram without the gram.  Used for
    CQR2's FINAL scale: same two-dot column-block structure that measures
    191 TF/s executed on v5e, vs 153 for the live-tile trmm kernel at
    (1024, 512, 512) blocks on the same math (the trmm kernel pays
    per-pair bookkeeping and a bk=512 K-split; this shape needs neither)."""
    if interpret is None:
        interpret = _interpret_default()
    m, n = A.shape
    if Rinv.shape != (n, n):
        raise ValueError(f"Rinv {Rinv.shape} does not match A {A.shape}")
    nb = n // 2
    bm = _shape_gate("scale_blocked", m, n, bm)
    acc = _acc_dtype(A.dtype)

    def kernel(a_ref, r_ref, q_ref):
        a = a_ref[:]
        q_head = _dot(a[:, 0:nb], r_ref[0:nb, 0:nb], acc, precision=precision)
        q_tail = _dot(a, r_ref[:, nb:], acc, precision=precision)
        q_ref[:] = jnp.concatenate([q_head, q_tail], axis=1).astype(q_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((n, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), A.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_device_budget()[1],
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * n * 3 // 4,
            bytes_accessed=2 * m * n * jnp.dtype(A.dtype).itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(A, Rinv)


def assemble_sym(Gu: jnp.ndarray, nb: int) -> jnp.ndarray:
    """Symmetric gram from the upper-block-row form (lower-left block is
    the transpose of the upper-right) — n² elementwise, negligible next to
    the tall passes."""
    return Gu.at[nb:, :nb].set(Gu[:nb, nb:].T)


def fused_ok(grid, m: int, n: int, mode: str, bm: int = 1024) -> bool:
    """Can the fused CQR2 pipeline run?  Single-device pallas mode plus the
    shared kernel eligibility rule (_eligible)."""
    return (
        mode == "pallas"
        and grid.num_devices == 1
        and _eligible(m, n, bm) != 0
    )
