"""FactorCache: a bounded, byte-budgeted pool of resident factors.

The serve-side half of online factor maintenance (docs/SERVING.md "Factor
residency"): clients name a factor with a token of their choosing, seed it
once (`posv_cached` on a miss refactors and installs; `blocktri_extend` on
a fresh token seeds an identity-carry chain), then mutate it in O(kn²)
(`chol_update` / `chol_downdate`) or O(nblocks·b³) (`blocktri_extend`) and
solve against it (`posv_cached`) without ever re-shipping the matrix — the
wire protocol for the update ops carries only the rank-k panel V.

Policy, deliberately boring:

* **LRU over a byte budget** — `put` evicts least-recently-used entries
  until the pool fits `budget_bytes`; the newest entry is kept even when
  it alone exceeds the budget (a pool that rejects every factor larger
  than the budget would turn every update into a loud miss with no way
  out).  `lookup` refreshes recency.
* **Tombstones** — an evicted token is remembered.  The engine uses the
  distinction to fail evicted-token traffic LOUDLY (an update against a
  silently re-seeded identity factor would be a wrong answer) while
  letting never-seen `blocktri_extend` tokens seed fresh chains.
  `release` (the client's explicit drop) clears the tombstone too: a
  released token is free for honest reuse.
* **Counters, not policy** — hits / misses / evictions / installs /
  released / downdate_degrades accumulate here and surface through
  `stats.Collector.snapshot(factor_cache=...)` into the
  `serve:request_stats` ledger record, where `obs serve-report
  --min-residency-hit-rate` gates them (the residency hit-rate is the
  cost model's whole justification: a miss is priced as a full refactor).

The cache is host-side state keyed by client tokens: it never enters a
traced program, so residency changes NEVER recompile anything — the
bucket executables are keyed by shape alone, and the engine's config hash
deliberately excludes the byte budget (ServeConfig.factor_cache_bytes is
runtime policy: WHERE factors live, not WHAT was compiled).
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Optional

import jax
import jax.numpy as jnp


def _nbytes(arrays) -> int:
    return int(sum(a.size * jnp.dtype(a.dtype).itemsize for a in arrays))


@dataclasses.dataclass
class FactorEntry:
    """One resident factor.  `kind` is 'dense' (arrays = (R,), upper
    A = RᵀR), 'blocktri' (arrays = (L, Wt, carry): the appended-so-far
    chain factor in the models/blocktri representation plus the running
    (b, b) diagonal carry the next extend continues from), or 'session'
    (same arrays as 'blocktri', owned by the streaming-session protocol —
    serve/sessions.py).  `meta` is engine bookkeeping (shapes/dtype used
    for request validation).  `born` is the install position on the
    cache's deterministic operation clock — eviction ages derive from it
    (operations, not wall time, so the histogram is reproducible)."""

    kind: str
    arrays: tuple
    nbytes: int
    meta: dict
    born: int = 0


class FactorCache:
    """See module docstring.  Not thread-safe, like the engine that owns
    one (a single dispatch loop)."""

    def __init__(self, budget_bytes: int = 256 << 20):
        if budget_bytes <= 0:
            raise ValueError(
                f"factor cache budget must be positive, got {budget_bytes}")
        self.budget_bytes = int(budget_bytes)  # guarded-by: <frozen>
        self._entries: "OrderedDict[str, FactorEntry]" = OrderedDict()  # guarded-by: <owner-thread>
        self._tombstones: set[str] = set()  # guarded-by: <owner-thread>
        self.hits = 0  # guarded-by: <owner-thread>
        self.misses = 0  # guarded-by: <owner-thread>
        self.evictions = 0  # guarded-by: <owner-thread>
        self.installs = 0  # guarded-by: <owner-thread>
        self.released = 0  # guarded-by: <owner-thread>
        self.downdate_degrades = 0  # guarded-by: <owner-thread>
        # deterministic operation clock (ticks on lookup/put): eviction
        # ages are measured on it so the age histogram is reproducible
        # under test and load replay — wall clocks are not
        self._op_clock = 0  # guarded-by: <owner-thread>
        # eviction-age histogram: key = smallest power-of-two upper bound
        # on the evicted entry's age in cache operations (stringified for
        # JSON), value = count.  Young evictions (small keys) mean the
        # budget is thrashing; old ones mean honest retirement.
        self._evict_age_hist: dict[str, int] = {}  # guarded-by: <owner-thread>

    # ---- residency ---------------------------------------------------------

    def lookup(self, token: str) -> Optional[FactorEntry]:
        """Resident entry for `token` (refreshes LRU recency) or None.
        Counts a hit or a miss — call exactly once per request."""
        self._op_clock += 1
        e = self._entries.get(token)
        if e is None:
            self.misses += 1
            return None
        self.hits += 1
        self._entries.move_to_end(token)
        return e

    def peek(self, token: str) -> Optional[FactorEntry]:
        """lookup without counters or recency (engine internals/tests)."""
        return self._entries.get(token)

    def evicted(self, token: str) -> bool:
        """Whether `token` WAS resident and got evicted (tombstoned) —
        the loud-failure predicate for stateful ops whose fresh-token
        path would otherwise silently restart from the wrong state."""
        return token in self._tombstones

    def put(self, token: str, kind: str, arrays, meta: dict) -> list[str]:
        """Install (or overwrite) a resident factor; evicts LRU entries
        until the pool fits the byte budget (never the entry just
        installed).  Returns the evicted tokens."""
        self._op_clock += 1
        arrays = tuple(jax.device_put(a) for a in arrays)
        prior = self._entries.get(token)
        e = FactorEntry(kind=kind, arrays=arrays, nbytes=_nbytes(arrays),
                        meta=dict(meta),
                        born=(prior.born if prior is not None
                              else self._op_clock))
        self._entries[token] = e
        self._entries.move_to_end(token)
        self._tombstones.discard(token)
        self.installs += 1
        evicted = []
        while (self.resident_bytes() > self.budget_bytes
               and len(self._entries) > 1):
            victim, v = self._entries.popitem(last=False)
            self._tombstones.add(victim)
            self.evictions += 1
            age = max(0, self._op_clock - v.born)
            key = str(1 << age.bit_length())
            self._evict_age_hist[key] = self._evict_age_hist.get(key, 0) + 1
            evicted.append(victim)
        return evicted

    def release(self, token: str) -> bool:
        """Explicit client drop.  Clears any tombstone — a released token
        is free for honest reuse.  Returns whether an entry was resident."""
        self._tombstones.discard(token)
        if token in self._entries:
            del self._entries[token]
            self.released += 1
            return True
        return False

    # ---- accounting --------------------------------------------------------

    def note_downdate_degrade(self) -> None:
        """A flagged downdate was degraded to a fresh refactor at landing
        (docs/ROBUSTNESS.md) — counted here so the residency stats block
        carries it even when no RobustConfig is attached."""
        self.downdate_degrades += 1

    def resident_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, token: str) -> bool:
        return token in self._entries

    def stats(self) -> dict:
        """The factor_cache counter block of `serve:request_stats`
        (obs.ledger.validate_request_stats validates it; `obs
        serve-report --min-residency-hit-rate` gates hit_rate)."""
        lookups = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "installs": self.installs,
            "released": self.released,
            "downdate_degrades": self.downdate_degrades,
            "entries": len(self._entries),
            "bytes": self.resident_bytes(),
            "budget_bytes": self.budget_bytes,
            "hit_rate": (self.hits / lookups) if lookups else 1.0,
            # per-entry byte sizes (token -> bytes) and the eviction-age
            # histogram (power-of-two operation-age bucket -> count):
            # the session eviction-pressure view (PR 19).  Additive keys —
            # merge_snapshots folds only the scalar counters above, and
            # the validator checks these only when present.
            "entry_bytes": {t: e.nbytes for t, e in self._entries.items()},
            "eviction_age_hist": dict(self._evict_age_hist),
        }
