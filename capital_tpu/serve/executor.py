"""Dispatch, donation, fault containment, and result landing.

The executor is the piece of the PR 4 engine that actually touches the
device: it turns an assembled bucket batch into a dispatched executable
call, and a dispatched call into per-request `Response`s.  Splitting it
from admission (scheduler.py) is what makes continuous batching possible —
`dispatch()` returns an `InFlight` handle *without synchronizing* (jax
dispatch is async), so the scheduler can stage and dispatch the next
bucket while this one executes, and `land()` blocks only when someone
needs the results (an aged `pump()`, a `Ticket.result()`, the in-flight
cap, or `drain()`).

Timing contract (the queue-wait/device split serve/stats.py reports):

* ``t_enq`` — request enqueue time (set at `submit()`, carried on the
  Ticket and the pending entry);
* ``t0`` — dispatch time (set here when the executable is invoked; also
  stamped onto each Ticket);
* landing time — when `land()` observed the outputs ready.

``queue_wait_s = t0 - t_enq`` is scheduling policy (flush thresholds,
ladder fit, in-flight backpressure); ``device_s = t_land - t0`` is
compute + transfer + any async slack the scheduler chose not to collect
earlier.  Both populations feed `serve:request_stats` percentiles, so
`obs serve-report` can tell a mis-tuned flush policy (queue-wait grows)
from a slow kernel (device grows) without re-running anything.

Donation stays exactly PR 4's contract: engine-built batch buffers only,
TPU-only by default, posv RHS / inv operand only (lstsq's (m, nrhs) RHS
can never alias its (n, nrhs) solution).  Fault containment likewise:
`fail()` lands host-side ingest faults as failed Responses, and the
per-problem `info` vector flags breakdowns one request at a time.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp

from capital_tpu.obs import spans
from capital_tpu.robust.config import RobustInfo
from capital_tpu.serve import batching
from capital_tpu.utils import tracing


@dataclasses.dataclass
class Response:
    """One finished request.  `x` is the cropped solution (None only when
    `ok` is False with `error` set — an ingest fault or a rejected
    request).  `info` is a RobustInfo under ServeConfig.robust (breakdown
    != 0 means x is flagged garbage), else None.  `latency_s` is
    enqueue-to-landing; `queue_wait_s`/`device_s` are its two halves
    (None when no dispatch happened, e.g. an ingest fault)."""

    request_id: int
    op: str
    ok: bool
    x: Optional[jnp.ndarray]
    info: Optional[RobustInfo]
    error: Optional[str]
    bucket: Optional[tuple]
    batched: bool
    latency_s: float
    queue_wait_s: Optional[float] = None
    device_s: Optional[float] = None
    trace: Optional[spans.RequestTrace] = None


class Ticket:
    """Handle returned by submit().  Carries the request's clock marks
    (`t_enq` at submit, `t0` at dispatch) and resolves when its batch
    lands.  Under the continuous scheduler a capacity flush DISPATCHES the
    batch without waiting for it: the ticket is `done` (its results are in
    flight and will materialize), and `result()` lands the batch on demand
    if `pump()`/`drain()` hasn't already."""

    __slots__ = ("request_id", "t_enq", "t0", "response", "trace",
                 "deadline_ms", "_entry", "_land")

    def __init__(self, request_id: int, t_enq: float = 0.0):
        self.request_id = request_id
        self.t_enq = t_enq
        self.t0: Optional[float] = None  # stamped at dispatch
        self.response: Optional[Response] = None
        self.trace: Optional[spans.RequestTrace] = None
        self.deadline_ms: Optional[float] = None
        self._entry = None  # InFlight carrying this ticket, once dispatched
        self._land = None  # scheduler callback that lands _entry

    @property
    def done(self) -> bool:
        """True once the request's fate is sealed: a Response landed, or
        its batch is dispatched and in flight (result() will land it)."""
        return self.response is not None or self._entry is not None

    def result(self) -> Response:
        if self.response is None:
            if self._entry is None:
                raise RuntimeError(
                    f"request {self.request_id} not flushed yet — call "
                    "engine.pump() (deadline flush) or engine.drain()"
                )
            self._land(self._entry)  # lands the whole batch, fills response
        return self.response


@dataclasses.dataclass
class _Pending:
    """One queued request: its ticket plus the padded, staged operands.

    The factor-residency fields ride along host-side (serve/factorcache):
    `client_op` is the op the CLIENT submitted when the bucket runs an
    internal program on its behalf (posv_cached_miss buckets land as
    posv_cached responses/stats); `sink` is the engine's landing hook —
    called with (cropped_x, extra_outputs, raw_info), it installs/updates
    the resident factor and may REWRITE the landed result (the downdate
    degrade path) or fail it loudly; returns (x, info, error)."""

    ticket: Ticket
    pa: jnp.ndarray
    pb: Optional[jnp.ndarray]
    a_shape: tuple[int, ...]
    b_shape: Optional[tuple[int, ...]]
    t_enq: float
    client_op: Optional[str] = None
    sink: Optional[object] = None


@dataclasses.dataclass
class InFlight:
    """One dispatched-but-not-landed bucket batch."""

    bucket: batching.Bucket
    pending: list[_Pending]
    outputs: tuple  # (X, info) device arrays, possibly still computing
    t0: float  # dispatch time
    small: bool  # served by the batched-grid small-N kernels (stats split)
    landed: bool = False


class Executor:
    """Dispatch + landing.  Owns no queues and no cache — the scheduler
    decides *when*, the engine decides *what program*; this class only
    runs it and lands the results into Responses/stats."""

    def __init__(self, cfg, grid, stats):
        self.cfg = cfg
        self.grid = grid
        self.stats = stats

    # ---- donation ----------------------------------------------------------

    def donate(self) -> bool:
        d = self.cfg.donate
        return self.grid.platform == "tpu" if d is None else d

    def donate_argnums(self, bucket: batching.Bucket) -> tuple[int, ...]:
        """The jit donation declaration for one bucket program: posv's RHS
        batch, inv's operand batch, nothing for lstsq (its (m, nrhs) RHS
        cannot alias the (n, nrhs) solution — XLA would silently drop the
        declaration; the lint donation-honored rule's point).

        Factor-residency buckets: chol_update/chol_downdate donate the
        assembled FACTOR batch (argnum 0 — shaped exactly like the R'
        output, and an engine-built stack of padded copies, so the
        resident originals in the FactorCache stay intact); posv_cached
        donates its RHS like posv.  The miss and extend programs donate
        nothing (3-output / carry-shaped operands XLA would drop the
        declaration for).

        Tiered buckets donate nothing: the fast program downcasts the
        request-dtype inputs before factoring (different itemsize — XLA
        would drop the alias), and the guaranteed program keeps BOTH
        operands live across every refinement sweep's residual."""
        if not self.donate():
            return ()
        if bucket.tier != "balanced":
            return ()
        if bucket.op in ("chol_update", "chol_downdate"):
            return (0,)
        if bucket.op == "posv_cached":
            return (1,)
        if bucket.op in ("posv_cached_miss", "blocktri_extend",
                         "session_extend", "session_solve"):
            # session_solve's 4-stack operand CONTAINS the FactorCache-
            # resident (L, Wt) — donating it would let XLA scribble over
            # the session's resident factor; the extend programs donate
            # nothing for the blocktri_extend reasons above
            return ()
        if bucket.b_shape is not None:
            return (1,) if bucket.op == "posv" else ()
        return (0,)

    # ---- batched dispatch + landing ---------------------------------------

    def dispatch(self, bucket: batching.Bucket, exe,
                 pending: list[_Pending], small: bool) -> InFlight:
        """Assemble and invoke one bucket batch WITHOUT synchronizing.
        The returned InFlight's outputs are device arrays that may still
        be computing; land() collects them."""
        Ab, Bb, occupancy = batching.assemble(
            [p.pa for p in pending], [p.pb for p in pending], bucket,
        )
        with tracing.scope("SV::dispatch"):
            outputs = exe(Ab) if Bb is None else exe(Ab, Bb)
        t0 = time.monotonic()
        fl = InFlight(bucket=bucket, pending=list(pending), outputs=outputs,
                      t0=t0, small=small)
        for p in pending:
            p.ticket.t0 = t0
            if p.ticket.trace is not None:
                # assemble + async invoke issue; host-side stamp only
                p.ticket.trace.extend("batch_form", t0)
        self.stats.note_batch(occupancy, bucket=batching.bucket_label(bucket))
        return fl

    def ready(self, fl: InFlight) -> bool:
        """Non-blocking readiness probe (jax.Array.is_ready).  Platforms
        whose arrays lack the probe report ready, degrading the continuous
        scheduler's opportunistic pump-landing to land-on-pump — correct,
        just less overlapped."""
        try:
            return all(
                x.is_ready() for x in jax.tree_util.tree_leaves(fl.outputs)
            )
        except AttributeError:
            return True

    def land(self, fl: InFlight) -> None:
        """Block on one in-flight batch and land every request in it:
        crop, robust-flag, stamp the queue-wait/device split, feed stats.
        Idempotent (the scheduler, a Ticket.result(), and drain() may all
        try)."""
        if fl.landed:
            return
        fl.landed = True
        # programs return (X, info) — the factor-residency miss program
        # returns (X, R, info); everything between the primary output and
        # the trailing info batch is an extra the landing sink consumes
        *xs, info = jax.block_until_ready(fl.outputs)
        t_land = time.monotonic()
        for i, p in enumerate(fl.pending):
            tr = p.ticket.trace
            if tr is not None:
                tr.extend("device", t_land)
            xi = batching.crop(fl.bucket.op, xs[0][i], p.a_shape, p.b_shape)
            ri = info[i]
            err = None
            if p.sink is not None:
                xi, ri, err = p.sink(xi, tuple(x[i] for x in xs[1:]), ri)
                if tr is not None:
                    tr.extend("refine")  # sink bookkeeping ran host-side
            op = p.client_op or fl.bucket.op
            if err is not None:
                # the sink refused the result (double-failed downdate
                # degrade): land it as a LOUD failure, never a silent
                # wrong answer (docs/ROBUSTNESS.md)
                lat = t_land - p.t_enq
                p.ticket.response = Response(
                    request_id=p.ticket.request_id, op=op, ok=False,
                    x=None, info=self._norm_info(ri), error=err,
                    bucket=fl.bucket.key, batched=True, latency_s=lat,
                    queue_wait_s=max(0.0, fl.t0 - p.t_enq),
                    device_s=max(0.0, t_land - fl.t0),
                    trace=tr,
                )
                if tr is not None:
                    tr.extend("respond")
                self.stats.record_request(
                    op, lat, ok=False, failed=True,
                    bucket=batching.bucket_label(fl.bucket))
                continue
            self._finish(
                p.ticket, op, xi, ri, fl.bucket.key,
                batched=True, t_enq=p.t_enq, t0=fl.t0, t_land=t_land,
                small=fl.small,
            )
        fl.pending = []
        fl.outputs = ()  # release the batch buffers

    # ---- single-problem (oversize) route ----------------------------------

    def run_single(self, ticket: Ticket, op: str, A, B, exe,
                   t_enq: float) -> None:
        """Oversize requests stay synchronous: one exact-shape problem
        through the models/ schedules, landed immediately (no batch to
        overlap against, and the models paths carry their own internal
        pipelining)."""
        t0 = time.monotonic()
        ticket.t0 = t0
        x, raw = exe(A) if B is None else exe(A, B)
        x, raw = jax.block_until_ready((x, raw))
        t_land = time.monotonic()
        if ticket.trace is not None:
            ticket.trace.extend("device", t_land)
        self._finish(ticket, op, x, raw, None, batched=False, t_enq=t_enq,
                     t0=t0, t_land=t_land)

    # ---- landing internals -------------------------------------------------

    def fail(self, ticket: Ticket, op: str, error: str,
             t_enq: float) -> None:
        """Land a request that never reached a device: ingest fault or
        oversize-reject.  No queue-wait/device split exists for it."""
        now = time.monotonic()
        lat = now - t_enq
        tr = ticket.trace
        if tr is not None:
            # collapse to the failed chain: admit covers submit-to-fault,
            # respond is the Response/stats stamp happening right here
            tr.kind = "failed"
            if not tr.spans:
                tr.extend("admit", now)
            tr.extend("respond")
        ticket.response = Response(
            request_id=ticket.request_id, op=op, ok=False, x=None,
            info=None, error=error, bucket=None, batched=False,
            latency_s=lat, trace=tr,
        )
        self.stats.record_request(op, lat, ok=False, failed=True)

    def _norm_info(self, raw) -> Optional[RobustInfo]:
        if self.cfg.robust is None:
            return None
        if isinstance(raw, RobustInfo):
            return RobustInfo(
                info=int(raw.info), breakdown=int(raw.breakdown),
                shifted=int(raw.shifted), sigma=float(raw.sigma),
                escalated=int(raw.escalated), ortho=float(raw.ortho),
                gate=int(raw.gate),
            )
        i = int(raw)
        # detect-only sites surface the potrf convention; no recovery ran
        # (and no gate was evaluated — gate stays GATE_NONE)
        return RobustInfo(info=i, breakdown=int(i != 0), shifted=0,
                          sigma=0.0, escalated=0, ortho=-1.0)

    def _finish(self, ticket: Ticket, op: str, x, raw_info,
                bucket_key: Optional[tuple], batched: bool, t_enq: float,
                t0: float, t_land: float, small: bool = False) -> None:
        info = self._norm_info(raw_info)
        ok = info is None or info.info == 0
        queue_wait = max(0.0, t0 - t_enq)
        device = max(0.0, t_land - t0)
        ticket.response = Response(
            request_id=ticket.request_id, op=op, ok=ok, x=x, info=info,
            error=None, bucket=bucket_key, batched=batched,
            latency_s=t_land - t_enq,
            queue_wait_s=queue_wait, device_s=device,
            trace=ticket.trace,
        )
        if ticket.trace is not None:
            ticket.trace.extend("respond")
        self.stats.record_request(
            op, t_land - t_enq, ok=ok,
            flagged=(info is not None and not ok), small=small,
            queue_wait_s=queue_wait, device_s=device,
            bucket=(batching.bucket_label(bucket_key)
                    if bucket_key is not None else None),
        )
