"""Continuous-batching admission: in-flight bucket batches, overlapped
dispatch, deadline flushes.

PR 4's loop was stop-and-go: a flush called the bucket executable and the
host sat inside that call until the device finished, so the device idled
while the host padded/stacked the next batch and the host idled while the
device solved.  The CAPITAL thesis — the *schedule*, not the local kernel,
decides delivered performance — applies to this axis exactly like it does
to inter-node traffic: overlap the phases instead of alternating them.

The continuous scheduler (``ServeConfig.scheduler="continuous"``):

* **admission into in-flight batches** — `admit()` queues per bucket; a
  capacity-full bucket dispatches immediately, but `flush()` returns as
  soon as the executable call is *issued* (jax dispatch is async) — the
  batch goes onto the in-flight deque instead of blocking the host;
* **overlapping dispatch of consecutive buckets** — while batch k
  executes, the host stages, pads, and dispatches batch k+1; there is no
  `block_until_ready` between flushes;
* **bounded in-flight depth** — at most `max_inflight` unlanded batches;
  beyond that the oldest is landed (collected) first, so device queueing
  and batch-buffer memory stay bounded under a submit storm;
* **opportunistic landing** — `pump()` lands any in-flight batch whose
  outputs report ready (`jax.Array.is_ready`, non-blocking) in addition
  to running deadline flushes, so results materialize as the device
  produces them rather than in one stall at `drain()`.

``scheduler="sync"`` reproduces the PR 4 submit/pump/drain behavior
exactly (dispatch + immediate land, no staging, no in-flight window) —
kept as the A/B baseline `serve/loadgen.py` measures the overlap win
against and as the conservative posture for platforms where async
dispatch is a liability.

This module owns no executables and no padding: the engine resolves the
bucket program via its cache (`get_exe` callback) and pads/stages at
submit; the executor dispatches and lands.  Single-problem (oversize)
requests never enter the scheduler.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

from capital_tpu.serve import batching
from capital_tpu.serve.executor import Executor, InFlight, _Pending


class Scheduler:
    """Per-bucket queues + the in-flight window.  `get_exe(bucket)`
    returns ``(executable, small_route)`` — the engine's cache lookup."""

    def __init__(self, cfg, executor: Executor,
                 get_exe: Callable[[batching.Bucket], tuple]):
        self.cfg = cfg  # guarded-by: <frozen>
        self.executor = executor  # guarded-by: <frozen>
        self._get_exe = get_exe  # guarded-by: <frozen>
        self._queues: dict[batching.Bucket, list[_Pending]] = {}  # guarded-by: <owner-thread>
        self._inflight: deque[InFlight] = deque()  # guarded-by: <owner-thread>

    # ---- admission ---------------------------------------------------------

    def admit(self, bucket: batching.Bucket, p: _Pending) -> None:
        """Queue one padded request; dispatch the bucket when it reaches
        capacity (the capacity-flush path — inside submit())."""
        q = self._queues.setdefault(bucket, [])
        q.append(p)
        if len(q) >= bucket.capacity:
            self.flush(bucket)

    def queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    @property
    def inflight_depth(self) -> int:
        return sum(1 for fl in self._inflight if not fl.landed)

    # ---- dispatch ----------------------------------------------------------

    def flush(self, bucket: batching.Bucket) -> bool:
        """Dispatch one bucket's queue.  Continuous: issue and return
        (results land later); sync: land before returning (the PR 4
        behavior).  Returns True when a batch was dispatched."""
        q = self._queues.pop(bucket, [])
        if not q:
            return False
        t_form = time.monotonic()
        exe, small = self._get_exe(bucket)
        t_exe = time.monotonic()
        for p in q:
            if p.ticket.trace is not None:
                # enqueue = parked in the bucket queue until this flush;
                # cache_lookup = executable resolution (a compile lands
                # its full cost HERE — the attribution the zero-recompile
                # gates key on)
                p.ticket.trace.extend("enqueue", t_form)
                p.ticket.trace.extend("cache_lookup", t_exe)
        fl = self.executor.dispatch(bucket, exe, q, small)
        if self.cfg.scheduler == "sync":
            self.executor.land(fl)
            return True
        for p in q:
            p.ticket._entry = fl
            p.ticket._land = self.land
        self._inflight.append(fl)
        # bound the window: collect the oldest before over-queuing the
        # device (also bounds live batch-buffer memory)
        while self.inflight_depth > self.cfg.max_inflight:
            self.land(self._oldest_unlanded())
        return True

    def _oldest_unlanded(self) -> InFlight:
        for fl in self._inflight:
            if not fl.landed:
                return fl
        raise AssertionError("no unlanded in-flight batch")  # unreachable

    # ---- landing -----------------------------------------------------------

    def land(self, fl: InFlight) -> None:
        """Land one in-flight batch (idempotent; also the Ticket.result()
        callback) and drop collected entries from the window."""
        self.executor.land(fl)
        while self._inflight and self._inflight[0].landed:
            self._inflight.popleft()

    def reap(self) -> int:
        """Land every in-flight batch whose outputs report ready — the
        non-blocking half of pump().  Returns the number landed."""
        n = 0
        for fl in list(self._inflight):
            if not fl.landed and self.executor.ready(fl):
                self.land(fl)
                n += 1
        return n

    # ---- the loop verbs ----------------------------------------------------

    def pump(self, now: float) -> int:
        """Deadline flush + opportunistic landing.  Returns the number of
        batches flushed (deadline-triggered), matching the PR 4 pump()
        contract."""
        flushed = 0
        for bucket in list(self._queues):
            q = self._queues.get(bucket)
            if q and now - q[0].t_enq >= self.cfg.max_delay_s:
                if self.flush(bucket):
                    flushed += 1
        self.reap()
        return flushed

    def drain(self) -> int:
        """Flush every non-empty queue and land every in-flight batch
        (shutdown / test barrier).  Returns the number of batches flushed
        by this call."""
        flushed = 0
        for bucket in list(self._queues):
            if self.flush(bucket):
                flushed += 1
        while self._inflight:
            self.land(self._inflight[0])
        return flushed
