"""The engine's executable cache: in-memory AOT entries backed by an
optional on-disk persistent store.

The memory tier is PR 4's cache unchanged — one compiled executable per
key, hit/miss/warmup counters that make "steady-state traffic hits zero
recompiles" assertable.  The persistent tier answers the cold-start half of
that story: a process restart (or a fresh replica pointed at a shared cache
directory) re-pays every warmup compile, which for a full bucket ladder is
tens of seconds of dead time per process.  `persist_dir` spills every
compiled executable to disk via ``jax.experimental.serialize_executable``
so the NEXT engine's warmup deserializes instead of compiling — the
`make serve-smoke` cold-start proof is ``compiles == 0`` on the second run.

Disk entries are keyed by ``sha1(repr(cache key) + repr(fingerprint))``
where the cache key already carries the engine's config-hash and grid
topology, and the fingerprint pins jax/jaxlib versions, platform, and
device kind — an executable compiled by a different jaxlib or for a
different chip must never load (PJRT serialization is not stable across
versions).  Every disk failure mode degrades to *compile-and-overwrite*:

* **missing / stale entry** (fingerprint or key drift inside the file) →
  counted in ``disk_misses``, recompile, overwrite;
* **corrupt entry** (unpicklable bytes, truncated write, deserialization
  error) → counted in ``disk_errors``, recompile, overwrite;
* **unserializable executable or unwritable dir** on store → counted in
  ``disk_errors``, the in-memory entry still serves;
* **non-persistable program** (on CPU, anything reaching a LAPACK/BLAS
  custom call — PJRT serializes those as process-local addresses and a
  deserialized copy segfaults elsewhere) → never written, counted in
  ``disk_skips``, memory-only (`persistable_program`).

Writes are atomic (`os.replace` of a uniquely-named temp file), so two
engines sharing a cache directory race benignly: a reader never observes a
half-written file, and a writer that finds a valid same-fingerprint entry
already present (the multi-replica warmup pattern — every cold replica
compiles the same first-touch programs) skips the redundant write and
counts it in ``disk_races``, keeping ``disk_errors`` a real-failure
signal.  Nothing in this module raises to the caller for a disk reason.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import uuid
from typing import Callable, Optional

import jax

_log = logging.getLogger(__name__)

#: Bump when the on-disk entry layout changes; part of the fingerprint so
#: old entries read as stale, not corrupt.
ENTRY_VERSION = 1


def persistable_program(exe) -> bool:
    """Whether one compiled executable may spill to disk.  On CPU, PJRT
    serialization records custom-call targets (the LAPACK/BLAS FFI
    handlers) as process-local host addresses, so a deserialized program
    that reaches one SEGFAULTS in any other process — not an exception the
    never-raise contract could absorb.  Only pure-HLO programs persist on
    CPU (the pallas interpret kernels discharge to plain HLO and are
    safe); accelerator backends serialize their kernels by payload, not
    address.  A skipped program still caches in memory and is counted
    (``disk.skips``) so a cold-start audit can see why an entry recompiled.
    """
    if jax.default_backend() != "cpu":
        return True
    try:
        return "custom-call" not in exe.as_text()
    except Exception as e:  # noqa: BLE001 — unserializable introspection
        # means "cannot prove safe": keep it off disk and say why.
        _log.warning("cannot inspect executable for persistability "
                     "(%s: %s); keeping it memory-only", type(e).__name__, e)
        return False


def fingerprint() -> dict:
    """What must match for a serialized executable to be loadable: the
    compiler that produced it and the device it was compiled for."""
    import jaxlib

    dev = jax.devices()[0]
    return {
        "entry_version": ENTRY_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": jax.default_backend(),
        "device": getattr(dev, "device_kind", dev.platform),
    }


class ExecutableCache:
    """Two-tier executable cache.  `get(key, build)` resolves memory ->
    disk -> ``build()`` (a fresh ``jit().lower().compile()``), maintaining
    the counters `SolveEngine.cache_stats()` reports:

    * ``hits`` / ``misses`` — request-driven MEMORY lookups (the
      steady-state zero-recompile gate reads these; a disk load still
      counts as a memory miss, because the request paid a load);
    * ``warmup_compiles`` — fresh compiles during warmup lookups (kept
      out of hit_rate, PR 4 semantics);
    * ``compiles`` — every fresh XLA compile, warmup or not: the number
      the cold-start proof pins at 0 for a warm persistent dir;
    * ``disk_hits`` / ``disk_misses`` / ``disk_errors`` — persistent-tier
      outcomes (errors = corrupt entries and failed stores, both
      non-fatal by contract).
    """

    def __init__(self, persist_dir: Optional[str] = None):
        self.persist_dir = persist_dir
        self._mem: dict[tuple, object] = {}
        self._fp = fingerprint() if persist_dir else None
        self.hits = 0
        self.misses = 0
        self.warmup_compiles = 0
        self.compiles = 0
        self.disk_hits = 0
        self.disk_misses = 0
        self.disk_errors = 0
        self.disk_skips = 0  # programs persistable_program() kept off disk
        # benign lost writer races: another engine sharing the dir already
        # stored a valid entry for this exact (key, fingerprint) — the
        # write is redundant, not broken.  Counted apart from disk_errors
        # so N replicas warming one shared dir don't read as N-1 disk
        # failures and the --max-compiles 0 warm gate stays meaningful.
        self.disk_races = 0

    def __len__(self) -> int:
        return len(self._mem)

    def __contains__(self, key: tuple) -> bool:
        return key in self._mem

    # ---- the one entry point ----------------------------------------------

    def get(self, key: tuple, build: Callable[[], object], *,
            warmup: bool = False, persistable: bool = True):
        """Resolve `key` to an executable.  `build()` compiles one fresh
        (only called on a full miss).  `warmup` keeps the lookup out of the
        hit/miss counters; `persistable=False` opts a key out of the disk
        tier (nothing in serve uses it today — the hook exists so a future
        non-serializable program class degrades explicitly, not by
        error-counting on every warmup)."""
        exe = self._mem.get(key)
        if exe is not None:
            if not warmup:
                self.hits += 1
            return exe
        if not warmup:
            self.misses += 1
        if self.persist_dir and persistable:
            exe = self._load(key)
            if exe is not None:
                self._mem[key] = exe
                return exe
        self.compiles += 1
        if warmup:
            self.warmup_compiles += 1
        exe = build()
        self._mem[key] = exe
        if self.persist_dir and persistable:
            self._store(key, exe)
        return exe

    def stats(self) -> dict:
        """The cache block of `SolveEngine.cache_stats()` /
        serve:request_stats.  hit_rate covers request-driven lookups only
        (warmup excluded), PR 4 semantics."""
        lookups = self.hits + self.misses
        out = {
            "hits": self.hits,
            "misses": self.misses,
            "warmup_compiles": self.warmup_compiles,
            "compiles": self.compiles,
            "entries": len(self._mem),
            "hit_rate": (self.hits / lookups) if lookups else 1.0,
        }
        if self.persist_dir:
            out["disk"] = {
                "hits": self.disk_hits,
                "misses": self.disk_misses,
                "errors": self.disk_errors,
                "skips": self.disk_skips,
                "races": self.disk_races,
            }
        return out

    # ---- persistent tier ---------------------------------------------------

    def entry_path(self, key: tuple) -> str:
        ident = repr(key) + repr(self._fp)
        name = hashlib.sha1(ident.encode()).hexdigest()
        return os.path.join(self.persist_dir, f"{name}.exe")

    def _load(self, key: tuple):
        """One disk lookup; None on miss/stale/corrupt (counters tell the
        three apart, behavior does not: all three recompile)."""
        from jax.experimental import serialize_executable

        path = self.entry_path(key)
        if not os.path.exists(path):
            self.disk_misses += 1
            return None
        try:
            with open(path, "rb") as f:
                entry = pickle.load(f)
            # the filename hash already covers key+fingerprint; re-checking
            # the in-file copies catches a hash collision or a tool that
            # rewrote the file in place (the jaxlib-mismatch failure mode)
            if (entry.get("fingerprint") != self._fp
                    or entry.get("key") != repr(key)):
                self.disk_misses += 1
                return None
            exe = serialize_executable.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"],
            )
            self.disk_hits += 1
            return exe
        except Exception as e:  # noqa: BLE001 — any disk/pickle/PJRT
            # failure means "treat as absent and recompile"; the fallback
            # IS the contract (a poisoned cache file must never take the
            # serving process down), so log and count rather than raise.
            _log.warning("persistent cache entry %s unreadable (%s: %s); "
                         "recompiling and overwriting", path,
                         type(e).__name__, e)
            self.disk_errors += 1
            return None

    def _peek_valid(self, key: tuple) -> bool:
        """Whether a valid entry for (key, fingerprint) already sits on
        disk — metadata check only (no PJRT deserialization), used to tell
        a benign lost race from a real store failure.  False on ANY doubt:
        a wrong answer here only misfiles one counter."""
        try:
            with open(self.entry_path(key), "rb") as f:
                entry = pickle.load(f)
            return (entry.get("fingerprint") == self._fp
                    and entry.get("key") == repr(key)
                    and entry.get("payload") is not None)
        except Exception:  # lint: allow-broad-except — absent/corrupt/
            # unreadable all mean "no valid entry", which is the answer
            return False

    def _store(self, key: tuple, exe) -> None:
        """Spill one compiled executable; atomic via temp-file + replace so
        concurrent writers sharing the dir never expose torn entries.  A
        writer that LOST the race (a valid same-fingerprint entry is
        already there — N replicas warming one shared dir all compile the
        same first-touch programs) skips the redundant write and counts
        ``disk_races``, not ``disk_errors``."""
        from jax.experimental import serialize_executable

        if not persistable_program(exe):
            self.disk_skips += 1
            return
        if self._peek_valid(key):
            self.disk_races += 1
            return
        try:
            payload, in_tree, out_tree = serialize_executable.serialize(exe)
            blob = pickle.dumps({
                "fingerprint": self._fp,
                "key": repr(key),
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            })
            os.makedirs(self.persist_dir, exist_ok=True)
            path = self.entry_path(key)
            tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except Exception as e:  # noqa: BLE001 — a store failure costs the
            # NEXT process a compile, never this one a crash; log + count.
            if self._peek_valid(key):
                # lost the race mid-write (e.g. tmp replace under a
                # concurrent writer): a valid entry is there, so the next
                # cold start is still covered — benign, not an error
                self.disk_races += 1
                return
            _log.warning("persistent cache store for %r failed (%s: %s); "
                         "entry serves from memory only", key,
                         type(e).__name__, e)
            self.disk_errors += 1
