"""Router: the client-facing front end over N EngineReplicas sharing one
persistent AOT cache (docs/SERVING.md "Multi-replica serving").

The router owns submit/result tickets and three policies the single-engine
facade never needed:

* **dispatch** — `least_loaded` (fewest outstanding requests wins: best
  latency under a mixed load) or `bucket_affinity` (rendezvous-hash the
  (op, bucket) signature over the healthy replicas, so each replica's
  executable cache serves a stable bucket subset and stays hot; highest-
  random-weight hashing means a replica's death remaps ONLY its buckets,
  and with a shared ``persist_dir`` the remapped bucket is a disk hit on
  its new owner, not a compile);
* **health** — liveness (`alive()`), a heartbeat (async pings with a pong
  deadline), and a consecutive-failure circuit; a replica that trips ANY
  of them is failed: its outbox is swept one final time (results that
  raced the crash still count, first-wins), and every ticket still
  unanswered is RE-DISPATCHED to a healthy replica — or parked until one
  registers — never dropped;
* **drain lifecycle** — `drain_replica()` stops admission to one replica
  and lands its whole window (the rolling-restart barrier); `resume`/
  `stop_replica`/`add_replica` complete the restart story.

HOST-ONLY MODULE: the router never touches a device — it moves numpy
arrays between client and replica transports.  The lint
``host-only-dispatch`` rule statically asserts no jax import here; the
bucket signature is therefore a pure-python re-derivation of the ladder
lookup in serve/batching.bucket_for (same smallest-rung-that-fits rule),
read from the replicas' own ServeConfig so the two can't disagree.

Threading: every public method is safe under the internal lock.  `pump()`
makes progress (poll outboxes, land results, run health checks, flush the
parked queue); call it from your dispatch loop, or `start()` a background
pump thread (the loadgen client modes do).
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from typing import Optional

import numpy as np

from capital_tpu.obs import spans
from capital_tpu.serve.replica import EngineReplica, Result

POLICIES = ("least_loaded", "bucket_affinity")


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Router policy knobs.

    policy: dispatch policy (POLICIES above).
    max_consecutive_failures: heartbeat misses that trip the circuit.
    ping_interval_s: heartbeat cadence (0 disables the heartbeat; liveness
        via alive() still runs every pump).
    ping_timeout_s: pong deadline before a miss is counted.  Generous by
        default — a replica mid-compile answers late, not never, and the
        circuit exists for dead workers, not busy ones.
    """

    policy: str = "least_loaded"
    max_consecutive_failures: int = 3
    ping_interval_s: float = 0.25
    ping_timeout_s: float = 5.0


class RouterTicket:
    """Client handle for one routed request.  Keeps the host-side operands
    so a replica death can re-dispatch the request — the router's no-drop
    contract is exactly this copy."""

    __slots__ = ("request_id", "op", "A", "B", "tier", "deadline_ms",
                 "affinity", "t_enq", "replica_id", "attempts", "response",
                 "_event")

    def __init__(self, request_id: int, op: str, A, B,
                 tier: str = "balanced",
                 deadline_ms: Optional[float] = None,
                 affinity: Optional[str] = None):
        self.request_id = request_id       # guarded-by: <frozen>
        self.op = op                       # guarded-by: <frozen>
        self.A = A                         # guarded-by: <frozen>
        self.B = B                         # guarded-by: <frozen>
        self.tier = tier                   # guarded-by: <frozen>
        self.deadline_ms = deadline_ms     # guarded-by: <frozen>
        self.affinity = affinity           # guarded-by: <frozen>
        self.t_enq = time.monotonic()      # guarded-by: <frozen>
        # current owner; mutated only by the Router under ITS lock
        self.replica_id: Optional[str] = None  # guarded-by: <router-lock>
        self.attempts = 0                  # guarded-by: <router-lock>
        # written once (under the router lock) BEFORE _event.set(); the
        # client's read in result() is ordered by the event wait
        self.response: Optional[Result] = None  # guarded-by: <published-by: self._event>
        self._event = threading.Event()    # guarded-by: <self-sync>

    @property
    def done(self) -> bool:
        return self.response is not None

    def result(self, timeout: Optional[float] = None) -> Result:
        """Block until the result lands (someone must be pumping — the
        router's pump thread, or the caller between checks)."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} ({self.op}) not landed within "
                f"{timeout}s (is anything pumping the router?)"
            )
        return self.response


class _ReplicaState:
    """Router-side bookkeeping for one replica."""

    __slots__ = ("replica", "outstanding", "draining", "dead", "dispatched",
                 "completed", "consecutive_failures", "ping_pending",
                 "ping_sent_at", "last_pong")

    def __init__(self, replica: EngineReplica):
        self.replica = replica                   # guarded-by: <frozen>
        self.outstanding: dict[int, RouterTicket] = {}  # guarded-by: <router-lock>
        self.draining = False                    # guarded-by: <router-lock>
        self.dead = False                        # guarded-by: <router-lock>
        self.dispatched = 0                      # guarded-by: <router-lock>
        self.completed = 0                       # guarded-by: <router-lock>
        self.consecutive_failures = 0            # guarded-by: <router-lock>
        self.ping_pending: Optional[int] = None  # guarded-by: <router-lock>
        self.ping_sent_at = 0.0                  # guarded-by: <router-lock>
        self.last_pong = time.monotonic()        # guarded-by: <router-lock>


def _rung(ladder, v: int) -> Optional[int]:
    """Smallest ladder rung >= v (batching._pick's rule, re-derived pure)."""
    best = None
    for r in ladder:
        if r >= v and (best is None or r < best):
            best = r
    return best


def bucket_signature(op: str, a_shape, b_shape, dtype: str,
                     ladders: dict, tier: str = "balanced",
                     affinity: Optional[str] = None) -> tuple:
    """The affinity key: the (op, padded-shape) class this request batches
    into, derived from the same ladders the engine buckets with.  Oversize
    requests key on their exact shape — each oversize shape is its own
    executable anyway, so exact-shape affinity is the cache-friendly
    answer there too.  The accuracy tier joins the key because tiered
    requests compile (and batch in) their own bucket programs — affinity
    must steer a guaranteed request to the replica whose cache holds the
    guaranteed executable, not merely the same-shape balanced one.

    An explicit `affinity` token DOMINATES the signature: every request
    carrying the same token keys identically, regardless of op, shape,
    dtype or tier.  This is the session-sticky contract (docs/SERVING.md
    'Streaming sessions'): a session's resident factor lives in exactly
    one replica's FactorCache, so ALL of its traffic — open, append,
    solve at any tier, contract, close, with their different operand
    shapes — must single-home to that replica.  Rendezvous hashing keeps
    the stickiness membership-stable: a replica death remaps only the
    sessions it owned (those re-seed loudly via SessionEvicted); every
    other session stays put."""
    if affinity is not None:
        return ("affinity", str(affinity))
    n_r = _rung(ladders["buckets"],
                a_shape[1] if op == "lstsq" else a_shape[0])
    k_r = (_rung(ladders["nrhs_buckets"], b_shape[1])
           if b_shape is not None else None)
    m_r = _rung(ladders["rows_buckets"], a_shape[0]) if op == "lstsq" else 0
    if n_r is None or m_r is None or (b_shape is not None and k_r is None):
        return ("oversize", op, str(dtype), tuple(a_shape),
                tuple(b_shape) if b_shape is not None else None)
    return (op, str(dtype), n_r, k_r, m_r, str(tier))


def _rendezvous(sig: tuple, replica_ids) -> str:
    """Highest-random-weight choice: every (sig, replica) pair hashes to a
    weight, the max wins.  Stable under membership change — removing one
    replica remaps only the signatures it owned."""
    best_id, best_w = None, b""
    for rid in replica_ids:
        w = hashlib.sha1(f"{rid}|{sig!r}".encode()).digest()
        if best_id is None or w > best_w:
            best_id, best_w = rid, w
    return best_id


class Router:
    """See module docstring.  Replicas register via add_replica (started if
    they aren't yet); ladders for the affinity signature come from the
    first replica's config and every later one must agree."""

    def __init__(self, cfg: RouterConfig = RouterConfig()):
        if cfg.policy not in POLICIES:
            raise ValueError(
                f"unknown dispatch policy {cfg.policy!r}: expected one of "
                f"{POLICIES}"
            )
        self.cfg = cfg                           # guarded-by: <frozen>
        self._lock = threading.RLock()           # guarded-by: <lock>
        self._states: dict[str, _ReplicaState] = {}  # guarded-by: self._lock
        self._tickets: dict[int, RouterTicket] = {}  # guarded-by: self._lock
        self._parked: list[RouterTicket] = []    # guarded-by: self._lock
        self._next_id = 0                        # guarded-by: self._lock
        self._ladders: Optional[dict] = None     # guarded-by: self._lock
        self._pump_thread: Optional[threading.Thread] = None  # guarded-by: self._lock
        self._pump_stop = threading.Event()      # guarded-by: <self-sync>
        # counters (docs/SERVING.md): completed counts first results only —
        # completed + len(parked) + sum(outstanding) always equals
        # dispatched-distinct, which is the no-drop invariant the tests pin
        # (lint/invariants.py router-no-drop states it formally)
        self.dispatched = 0    # guarded-by: self._lock (distinct requests)
        self.completed = 0     # guarded-by: self._lock
        self.redispatched = 0  # guarded-by: self._lock (post-failure re-sends)
        self.duplicates = 0    # guarded-by: self._lock (crash-race seconds)
        self.failed_replicas = 0  # guarded-by: self._lock
        # exported span chains from every landed Result (spans.py is pure
        # Python — no jax enters this host-only module); emit_stats adds a
        # serve:trace record when any rode back.  The pump thread add()s
        # under the lock, so emit_trace must take it too.
        self.trace_log = spans.TraceLog()        # guarded-by: self._lock

    # ---- membership --------------------------------------------------------

    def add_replica(self, replica: EngineReplica, *, start: bool = True):
        with self._lock:
            rid = replica.replica_id
            if rid in self._states and not self._states[rid].dead:
                raise ValueError(f"replica id {rid!r} already registered")
            if start and not replica.alive():
                replica.start()
            lad = replica.ladders()
            if self._ladders is None:
                self._ladders = lad
            elif lad != self._ladders:
                raise ValueError(
                    f"replica {rid!r} ladders {lad} disagree with the "
                    f"router's {self._ladders} — affinity and bucketing "
                    "would diverge"
                )
            self._states[rid] = _ReplicaState(replica)
            self._flush_parked()
            return replica

    def replica_ids(self, *, healthy_only: bool = False) -> list[str]:
        with self._lock:
            return [rid for rid, st in self._states.items()
                    if not st.dead and (not healthy_only or not st.draining)]

    # ---- client surface ----------------------------------------------------

    def submit(self, op: str, A, B=None, *,
               accuracy_tier: str = "balanced",
               deadline_ms: Optional[float] = None,
               affinity: Optional[str] = None) -> RouterTicket:
        """Dispatch one request to a healthy replica; raises RuntimeError
        when none admits (every replica dead or draining) — admission
        control, not silent queueing.  Work already admitted is never
        subject to this: a failure re-dispatch parks instead.

        `accuracy_tier` rides the ticket (and the re-dispatch copy) to the
        replica's engine.submit — tier validation is the engine's job, so
        an invalid tier lands as a failed Result, not a router raise.

        `affinity` is the session-sticky token (typically the session id):
        under bucket_affinity it dominates the rendezvous signature so
        every request carrying it — regardless of op/shape/tier — routes
        to the one replica holding that session's resident factor (see
        bucket_signature)."""
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            t = RouterTicket(rid, op, np.asarray(A),
                             np.asarray(B) if B is not None else None,
                             tier=accuracy_tier, deadline_ms=deadline_ms,
                             affinity=affinity)
            st = self._pick(t)
            if st is None:
                raise RuntimeError(
                    "no healthy replica admits requests (all dead or "
                    "draining)"
                )
            self._tickets[rid] = t
            self.dispatched += 1
            self._dispatch(st, t)
            return t

    def pump(self, now: Optional[float] = None) -> int:
        """One progress round: poll every replica, land results, run the
        health checks, re-dispatch off dead replicas, flush the parked
        queue.  Returns results landed this round."""
        now = time.monotonic() if now is None else now
        with self._lock:
            landed = 0
            for st in list(self._states.values()):
                if st.dead:
                    continue
                for msg in st.replica.poll():
                    landed += self._on_message(st, msg, now)
                if st.replica.fatal is not None or not st.replica.alive():
                    self._fail_replica(st)
                    continue
                self._heartbeat(st, now)
            self._flush_parked()
            return landed

    def drain(self, timeout: float = 120.0) -> None:
        """Land everything everywhere: flush parked work, drain every live
        replica, collect the results (shutdown / test barrier)."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                self._flush_parked()
                live = [st for st in self._states.values() if not st.dead]
                for st in live:
                    # deliberate roundtrip under the lock: a concurrent
                    # pump() polling the same outbox would steal the ack
                    st.replica.drain(  # lint: allow-blocking-under-lock
                        timeout=max(0.1, deadline - time.monotonic()))
                self.pump()
                if not self._parked and not any(
                    st.outstanding for st in self._states.values()
                    if not st.dead
                ):
                    return
                # snapshot under the lock: the timeout report below runs
                # outside it, and unlocked len() reads would race the pump
                parked = len(self._parked)
                outstanding = sum(len(st.outstanding)
                                  for st in self._states.values())
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"router drain incomplete after {timeout}s: "
                    f"{parked} parked, {outstanding} outstanding"
                )
            time.sleep(1e-3)

    # ---- replica lifecycle (rolling restarts) ------------------------------

    def drain_replica(self, replica_id: str, timeout: float = 60.0) -> bool:
        """Stop admission to one replica and land its whole window.  The
        replica stays registered and alive (resume_replica re-admits) —
        this is the barrier a rolling restart runs behind."""
        with self._lock:
            st = self._states[replica_id]
            st.draining = True
            # hold the lock across the sync roundtrip: a concurrent pump()
            # polling the same outbox would steal the "drained" ack
            ok = st.replica.drain(timeout)  # lint: allow-blocking-under-lock
            self.pump()
            return ok

    def resume_replica(self, replica_id: str) -> None:
        with self._lock:
            self._states[replica_id].draining = False
            self._flush_parked()

    def stop_replica(self, replica_id: str, timeout: float = 60.0) -> None:
        """Graceful removal: drain, stop, sweep the outbox, deregister.
        Anything still unanswered (it shouldn't be, after a clean drain)
        re-dispatches rather than drops."""
        self.drain_replica(replica_id, timeout)
        with self._lock:
            st = self._states[replica_id]
            # sync stop ack under the lock, same reason as drain_replica
            st.replica.stop(timeout)  # lint: allow-blocking-under-lock
            self._sweep_and_redispatch(st)
            st.dead = True

    def kill_replica(self, replica_id: str) -> None:
        """Abrupt kill (tests / fault injection): the next pump() observes
        the death and re-dispatches the replica's in-flight requests."""
        with self._lock:
            self._states[replica_id].replica.kill()

    def start(self, interval_s: float = 0.002) -> None:
        """Run pump() on a background thread — the mode concurrent clients
        (loadgen) use: submit from any thread, block on ticket.result()."""
        with self._lock:
            if self._pump_thread is not None:
                return
            self._pump_stop.clear()

            def loop():
                while not self._pump_stop.is_set():
                    self.pump()
                    time.sleep(interval_s)

            self._pump_thread = threading.Thread(
                target=loop, name="router-pump", daemon=True)
            self._pump_thread.start()

    def stop(self, timeout: float = 60.0) -> None:
        """Stop pumping and gracefully stop every live replica."""
        with self._lock:
            t, self._pump_thread = self._pump_thread, None
        if t is not None:
            self._pump_stop.set()
            t.join(timeout)  # outside the lock: the pump loop takes it
        with self._lock:
            for rid in self.replica_ids():
                self.stop_replica(rid, timeout)

    # ---- warmup / stats ----------------------------------------------------

    def warmup(self, specs, timeout: float = 300.0) -> dict:
        """Warm every live replica over `specs`; {replica_id: fresh-compile
        count (None = no ack)}.  With a shared persist_dir only the first
        cold replica should report fresh > 0."""
        out = {}
        with self._lock:  # keep pump() off the outboxes mid-roundtrip
            for rid in self.replica_ids():
                info = self._states[rid].replica.warmup(  # lint: allow-blocking-under-lock
                    specs, timeout)
                out[rid] = info["fresh"] if info else None
        return out

    def replica_stats(self, timeout: float = 30.0) -> dict:
        """{replica_id: request_stats snapshot (with raw sample
        populations)} for every live replica."""
        out = {}
        with self._lock:  # keep pump() off the outboxes mid-roundtrip
            for rid in self.replica_ids():
                snap = self._states[rid].replica.request_stats(  # lint: allow-blocking-under-lock
                    timeout)
                if snap is not None:
                    out[rid] = snap
        return out

    def counters(self) -> dict:
        with self._lock:
            return {
                "policy": self.cfg.policy,
                "replicas": len(self.replica_ids()),
                "dispatched": self.dispatched,
                "completed": self.completed,
                "redispatched": self.redispatched,
                "duplicates": self.duplicates,
                "failed_replicas": self.failed_replicas,
                "parked": len(self._parked),
                "per_replica": {
                    rid: {"dispatched": st.dispatched,
                          "completed": st.completed,
                          "outstanding": len(st.outstanding),
                          "draining": st.draining}
                    for rid, st in self._states.items() if not st.dead
                },
            }

    def emit_stats(self, path: Optional[str] = None, **extra) -> list[dict]:
        """One replica-tagged serve:request_stats record per live replica
        plus ONE aggregate record (stats.merge_snapshots) carrying the
        router block — the records `obs serve-report --aggregate` sums.
        Returns the records; appends them to `path` when given."""
        from capital_tpu.obs import ledger
        from capital_tpu.serve import stats as stats_mod

        per = self.replica_stats()
        recs = []
        for rid, snap in per.items():
            clean = {k: v for k, v in snap.items() if k != "samples"}
            recs.append(ledger.record(
                "serve:request_stats",
                ledger.manifest(config=self.cfg),
                request_stats=clean,
            ))
        if per:
            merged = stats_mod.merge_snapshots(list(per.values()))
            recs.append(ledger.record(
                "serve:request_stats",
                ledger.manifest(config=self.cfg),
                request_stats=merged,
                router={**self.counters(), **extra.pop("router", {})},
                **extra,
            ))
        if path:
            for rec in recs:
                ledger.append(path, rec)
        return recs

    def emit_trace(self, path: Optional[str] = None, **extra) -> dict:
        """One serve:trace record covering every trace the replicas
        marshalled back (replica-tagged span chains) — the multi-replica
        counterpart of SolveEngine.emit_trace.  Kept separate from
        emit_stats so consumers iterating its request_stats records never
        meet a foreign record kind."""
        with self._lock:  # the pump thread add()s traces under the lock
            return self.trace_log.emit(path, config=self.cfg, **extra)

    # ---- internals ---------------------------------------------------------

    def _healthy(self) -> list[_ReplicaState]:  # lock-held: self._lock
        return [st for st in self._states.values()
                if not st.dead and not st.draining
                and st.replica.fatal is None]

    def _pick(self, t: RouterTicket) -> Optional[_ReplicaState]:  # lock-held: self._lock
        healthy = self._healthy()
        if not healthy:
            return None
        if self.cfg.policy == "bucket_affinity" and self._ladders:
            sig = bucket_signature(
                t.op, t.A.shape, t.B.shape if t.B is not None else None,
                t.A.dtype, self._ladders, tier=t.tier, affinity=t.affinity,
            )
            rid = _rendezvous(sig, sorted(st.replica.replica_id
                                          for st in healthy))
            return self._states[rid]
        return min(healthy, key=lambda st: (len(st.outstanding),
                                            st.replica.replica_id))

    def _dispatch(self, st: _ReplicaState, t: RouterTicket) -> None:  # lock-held: self._lock
        """Hand one ticket to one replica; a transport failure fails the
        replica and re-routes (bounded by membership — each attempt
        removes the failed replica from the healthy set)."""
        while True:
            try:
                st.replica.submit(t.request_id, t.op, t.A, t.B,
                                  tier=t.tier, deadline_ms=t.deadline_ms)
            except OSError:
                self._fail_replica(st)
                nxt = self._pick(t)
                if nxt is None:
                    self._parked.append(t)
                    return
                st = nxt
                continue
            st.outstanding[t.request_id] = t
            st.dispatched += 1
            t.replica_id = st.replica.replica_id
            t.attempts += 1
            return

    def _on_message(self, st: _ReplicaState, msg: tuple, now: float) -> int:  # lock-held: self._lock
        kind = msg[0]
        if kind == "result":
            return self._land(st, msg[1], msg[2])
        if kind == "pong":
            st.last_pong = now
            st.consecutive_failures = 0
            if st.ping_pending == msg[1]:
                st.ping_pending = None
        # "fatal" is recorded on replica.fatal by poll(); stray sync acks
        # ("warmed"/"stats"/"drained") mean a sync caller timed out — inert
        return 0

    def _land(self, st: _ReplicaState, rid: int, payload: dict) -> int:  # lock-held: self._lock
        st.outstanding.pop(rid, None)
        t = self._tickets.get(rid)
        if t is None or t.response is not None:
            # crash race: the old owner answered after a re-dispatch (or
            # after the client already got the re-dispatched result).
            # First result wins; this one is dropped, visibly.
            self.duplicates += 1
            return 0
        t.response = Result(**payload, replica_id=st.replica.replica_id)
        trace = payload.get("trace")
        if trace is not None:
            # the replica's engine tagged its own replica_id; keep it
            # authoritative but fill it in when absent (older payloads)
            if not trace.get("replica_id"):
                trace = dict(trace, replica_id=st.replica.replica_id)
            self.trace_log.add(trace)
        t._event.set()
        st.completed += 1
        self.completed += 1
        return 1

    def _heartbeat(self, st: _ReplicaState, now: float) -> None:  # lock-held: self._lock
        if self.cfg.ping_interval_s <= 0:
            return
        if st.ping_pending is not None:
            if now - st.ping_sent_at > self.cfg.ping_timeout_s:
                st.consecutive_failures += 1
                st.ping_pending = None
                if (st.consecutive_failures
                        >= self.cfg.max_consecutive_failures):
                    self._fail_replica(st)
            return
        if now - st.ping_sent_at >= self.cfg.ping_interval_s:
            try:
                st.ping_pending = st.replica.ping_async()
            except OSError:
                self._fail_replica(st)
                return
            st.ping_sent_at = now

    def _fail_replica(self, st: _ReplicaState) -> None:  # lock-held: self._lock
        """Circuit open: final outbox sweep (crash-raced results still
        land), then re-dispatch everything unanswered; never drop."""
        if st.dead:
            return
        st.dead = True
        self.failed_replicas += 1
        self._sweep_and_redispatch(st)
        try:
            st.replica.kill()
        except OSError:
            pass

    def _sweep_and_redispatch(self, st: _ReplicaState) -> None:  # lock-held: self._lock
        for msg in st.replica.poll():
            self._on_message(st, msg, time.monotonic())
        pending = [t for t in st.outstanding.values() if t.response is None]
        st.outstanding.clear()
        for t in pending:
            self.redispatched += 1
            nxt = self._pick(t)
            if nxt is None:
                self._parked.append(t)
            else:
                self._dispatch(nxt, t)

    def _flush_parked(self) -> None:  # lock-held: self._lock
        if not self._parked or not self._healthy():
            return
        parked, self._parked = self._parked, []
        for t in parked:
            if t.response is not None:
                continue
            st = self._pick(t)
            if st is None:
                self._parked.append(t)
            else:
                self._dispatch(st, t)
