"""capital_tpu.serve — the solve engine that turns the factorizations into
a service (docs/SERVING.md).

    from capital_tpu import serve

    eng = serve.SolveEngine(grid, serve.ServeConfig(robust=RobustConfig()))
    eng.warmup([("posv", (500, 500), (500, 4), "float32")])
    ticket = eng.submit("posv", A, B)
    eng.pump()            # deadline flushes (or: capacity flushes happen
    resp = eng.drain() or ticket.result()   # inside submit)

The engine is a facade over three pieces (PR 7): `scheduler.py`
(continuous-batching admission, in-flight window, deadline flushes),
`cache.py` (AOT executable cache with the optional persistent disk tier —
``ServeConfig.persist_dir``), and `executor.py` (dispatch, donation, fault
containment, landing).  `loadgen.py` is the closed-loop A/B + SLO harness.

Smoke workload + gates: ``python -m capital_tpu.serve smoke`` /
``make serve-smoke``; A/B throughput: ``python -m capital_tpu.serve
loadgen`` / ``make serve-bench``.
"""

from capital_tpu.serve.cache import ExecutableCache  # noqa: F401
from capital_tpu.serve.engine import (  # noqa: F401
    Response,
    ServeConfig,
    SolveEngine,
    Ticket,
)
from capital_tpu.serve.executor import Executor  # noqa: F401
from capital_tpu.serve.scheduler import Scheduler  # noqa: F401
