"""capital_tpu.serve — the solve engine that turns the factorizations into
a service (docs/SERVING.md).

    from capital_tpu import serve

    eng = serve.SolveEngine(grid, serve.ServeConfig(robust=RobustConfig()))
    eng.warmup([("posv", (500, 500), (500, 4), "float32")])
    ticket = eng.submit("posv", A, B)
    eng.pump()            # deadline flushes (or: capacity flushes happen
    resp = eng.drain() or ticket.result()   # inside submit)

Smoke workload + gates: ``python -m capital_tpu.serve smoke`` /
``make serve-smoke``.
"""

from capital_tpu.serve.engine import (  # noqa: F401
    Response,
    ServeConfig,
    SolveEngine,
    Ticket,
)
