"""capital_tpu.serve — the solve engine that turns the factorizations into
a service (docs/SERVING.md).

    from capital_tpu import serve

    eng = serve.SolveEngine(grid, serve.ServeConfig(robust=RobustConfig()))
    eng.warmup([("posv", (500, 500), (500, 4), "float32")])
    ticket = eng.submit("posv", A, B)
    eng.pump()            # deadline flushes (or: capacity flushes happen
    resp = eng.drain() or ticket.result()   # inside submit)

The engine is a facade over three pieces (PR 7): `scheduler.py`
(continuous-batching admission, in-flight window, deadline flushes),
`cache.py` (AOT executable cache with the optional persistent disk tier —
``ServeConfig.persist_dir``), and `executor.py` (dispatch, donation, fault
containment, landing).  `loadgen.py` is the closed-loop A/B + SLO harness.

Multi-replica (PR 9): `router.py` is the client-facing front end over N
`replica.py` workers — in-process threads or spawned engine processes —
sharing one persistent cache directory, with pluggable dispatch
(least_loaded / bucket_affinity), per-replica health + re-dispatch, and a
drain lifecycle for rolling restarts.

    r = serve.Router(serve.RouterConfig(policy="bucket_affinity"))
    for i in range(2):
        r.add_replica(serve.make_replica("process", f"r{i}", cfg))
    r.warmup(specs); r.start()
    x = r.submit("posv", A, B).result(timeout=60)

Exports resolve lazily (PEP 562): the engine names pull in jax on first
touch, while Router/replica/loadgen stay importable from host-only
processes (router pumps, loadgen clients) that must never pay — or
crash on — a device runtime import.

Smoke workload + gates: ``python -m capital_tpu.serve smoke`` /
``make serve-smoke``; A/B throughput: ``python -m capital_tpu.serve
loadgen`` / ``make serve-bench``; multi-replica: ``python -m
capital_tpu.serve replicas`` / ``make serve-replicas``.
"""

from __future__ import annotations

#: attribute -> defining submodule; the engine-side names import jax
#: transitively, the router side stays host-only.
_EXPORTS = {
    "ExecutableCache": "capital_tpu.serve.cache",
    "Response": "capital_tpu.serve.engine",
    "ServeConfig": "capital_tpu.serve.engine",
    "SolveEngine": "capital_tpu.serve.engine",
    "Ticket": "capital_tpu.serve.engine",
    "Executor": "capital_tpu.serve.executor",
    "Scheduler": "capital_tpu.serve.scheduler",
    "EngineReplica": "capital_tpu.serve.replica",
    "ProcessReplica": "capital_tpu.serve.replica",
    "Result": "capital_tpu.serve.replica",
    "ThreadReplica": "capital_tpu.serve.replica",
    "make_replica": "capital_tpu.serve.replica",
    "Router": "capital_tpu.serve.router",
    "RouterConfig": "capital_tpu.serve.router",
    "RouterTicket": "capital_tpu.serve.router",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        module = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    value = getattr(importlib.import_module(module), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
