"""EngineReplica: one SolveEngine behind a message transport.

A replica is the unit the Router (serve/router.py) dispatches to: a worker
that owns ONE engine exclusively (the engine is not thread-safe — "a single
dispatch loop owns it", engine.py) and speaks a small tuple protocol over
an inbox/outbox pair.  Two transports implement it:

* `ThreadReplica` — the engine worker is a daemon thread in this process,
  the transport a pair of ``queue.Queue``s.  This is the mode tier-1 tests
  exercise the full router logic in: deterministic, no process-spawn
  flakiness, and a `kill()` that abandons in-flight work exactly the way a
  crashed process would (the worker exits without landing or acking).
* `ProcessReplica` — the engine worker is a spawned subprocess, the
  transport a duplex ``multiprocessing.Pipe``.  The deployment mode: N
  processes sidestep the GIL, and a shared ``ServeConfig.persist_dir``
  means every replica past the first warms from disk, not from XLA.

Protocol (plain tuples, picklable for the pipe transport)::

    inbox:  ("submit", rid, op, A, B)     one request; A/B numpy
            ("warmup", tok, specs)        engine.warmup() over specs
            ("ping", tok)                 health probe
            ("stats", tok)                request_stats snapshot + cache
            ("drain", tok)                land the whole window, then ack
            ("stop",)                     drain, ack, exit clean
    outbox: ("result", rid, payload)      payload: plain-dict Response
            ("warmed", tok, info)         {"fresh": compiles, "cache": ...}
            ("pong", tok, info)           {"outstanding": n, "queue_depth": n}
            ("stats", tok, snapshot)      stats.Collector.snapshot(...)
            ("drained", tok)
            ("stopped",)
            ("fatal", message)            worker died constructing/serving

The worker marshals every Response to a plain dict (`Result` on the router
side): ``x`` becomes a host numpy array, ``info`` a plain dict — nothing
device-resident crosses the transport, which is also what makes the pipe
mode possible at all.

HOST-ONLY MODULE: the dispatch path must never build a device program, so
this file must not import jax (the lint ``host-only-dispatch`` rule pins
that statically).  The engine — which of course uses jax — is imported
lazily inside the worker, and in the process mode only ever inside the
child, AFTER the env overrides land in ``os.environ`` (jax reads
``JAX_PLATFORMS``/``XLA_FLAGS`` at import; ``jax.config.update`` in the
parent does not propagate to a spawned child).
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

#: Per-iteration wait bound for the worker loop's single blocking point —
#: small enough that a deadline flush (max_delay_s) is never late by more
#: than this, large enough not to spin an idle replica.
_IDLE_WAIT_S = 0.02


@dataclasses.dataclass
class Result:
    """One finished request as the router sees it: executor.Response with
    every field marshalled host-side (`x` numpy, `info` a plain dict), plus
    the id of the replica that served it."""

    request_id: int
    op: str
    ok: bool
    x: Optional[np.ndarray]
    info: Optional[dict]
    error: Optional[str]
    bucket: Optional[tuple]
    batched: bool
    latency_s: float
    queue_wait_s: Optional[float] = None
    device_s: Optional[float] = None
    replica_id: Optional[str] = None
    #: the request's exported span chain (spans.RequestTrace.asdict() —
    #: plain JSON-safe dict, so it crosses the pipe transport freely)
    trace: Optional[dict] = None


def _marshal(rid: int, resp) -> dict:
    """Response -> plain picklable dict (the ("result", rid, payload)
    payload).  rid is the ROUTER's request id — the engine's internal
    ticket ids are per-replica and meaningless across the transport."""
    info = resp.info
    if info is not None and dataclasses.is_dataclass(info):
        info = dataclasses.asdict(info)
    trace = getattr(resp, "trace", None)
    if trace is not None:
        trace = dict(trace.asdict(), request_id=rid)
    return {
        "request_id": rid,
        "op": resp.op,
        "ok": bool(resp.ok),
        "x": np.asarray(resp.x) if resp.x is not None else None,
        "info": info,
        "error": resp.error,
        "bucket": tuple(resp.bucket) if resp.bucket is not None else None,
        "batched": bool(resp.batched),
        "latency_s": float(resp.latency_s),
        "queue_wait_s": resp.queue_wait_s,
        "device_s": resp.device_s,
        "trace": trace,
    }


def _serve_loop(replica_id: str, cfg_kwargs: dict,
                recv: Callable[[float], Optional[tuple]],
                send: Callable[[tuple], None],
                killed: Callable[[], bool]) -> None:
    """The worker: one engine, one loop.  `recv(timeout)` returns the next
    inbox tuple or None; `send` posts to the outbox; `killed()` polled each
    iteration simulates (thread mode) or observes (process mode never needs
    it) an abrupt crash — the loop exits WITHOUT landing or acking, which
    is exactly the failure the router's re-dispatch path exists for."""
    from capital_tpu.serve.engine import ServeConfig, SolveEngine

    robust = cfg_kwargs.get("robust")
    if isinstance(robust, dict):
        from capital_tpu.robust.config import RobustConfig

        cfg_kwargs = dict(cfg_kwargs, robust=RobustConfig(**robust))
    eng = SolveEngine(cfg=ServeConfig(**cfg_kwargs))
    eng.stats.replica_id = replica_id
    outstanding: dict[int, object] = {}  # rid -> Ticket, insertion-ordered

    def flush() -> bool:
        landed = [rid for rid, t in outstanding.items()
                  if t.response is not None]
        for rid in landed:
            t = outstanding.pop(rid)
            send(("result", rid, _marshal(rid, t.response)))
        return bool(landed)

    def handle(msg: tuple) -> bool:
        """Apply one inbox message; True means exit the loop."""
        kind = msg[0]
        if kind == "submit":
            # 5-tuple is the pre-tier wire format; trailing elements are
            # [tier] or [tier, deadline_ms] (tier sent explicitly — even
            # "balanced" — whenever a deadline rides along, so mixed
            # router/replica versions interoperate on plain traffic)
            _, rid, op, A, B, *rest = msg
            tier = rest[0] if rest else "balanced"
            deadline = rest[1] if len(rest) > 1 else None
            try:
                outstanding[rid] = eng.submit(op, A, B,
                                              accuracy_tier=tier,
                                              deadline_ms=deadline)
            except ValueError as e:
                send(("result", rid, {
                    "request_id": rid, "op": op, "ok": False, "x": None,
                    "info": None, "error": f"{type(e).__name__}: {e}",
                    "bucket": None, "batched": False, "latency_s": 0.0,
                    "queue_wait_s": None, "device_s": None,
                    "trace": None,
                }))
        elif kind == "warmup":
            fresh = eng.warmup(msg[2])
            send(("warmed", msg[1], {
                "fresh": fresh, "cache": eng.cache_stats(),
            }))
        elif kind == "ping":
            send(("pong", msg[1], {
                "outstanding": len(outstanding),
                "queue_depth": eng.queue_depth(),
            }))
        elif kind == "stats":
            send(("stats", msg[1],
                  eng.stats.snapshot(eng.cache_stats(), samples=True)))
        elif kind == "drain":
            eng.drain()
            flush()
            send(("drained", msg[1]))
        elif kind == "stop":
            eng.drain()
            flush()
            send(("stopped",))
            return True
        return False

    while True:
        if killed():
            return  # crash: outstanding work is abandoned, no acks
        wait = min(_IDLE_WAIT_S, eng.cfg.max_delay_s) \
            if outstanding or eng.queue_depth() else _IDLE_WAIT_S
        msg = recv(wait)
        try:
            while msg is not None:
                if handle(msg):
                    return
                if killed():
                    return
                msg = recv(0.0)
            eng.pump()
            if flush() or not outstanding:
                continue
            # stalled tail: nothing landed, nothing queued behind a
            # deadline — force the oldest dispatched batch to land so a
            # closed-loop client is never wedged behind the in-flight
            # window (same forcing rule as loadgen.run_closed_loop)
            if eng.queue_depth() == 0:
                oldest = next(iter(outstanding.values()))
                if oldest.done:
                    oldest.result()
                    flush()
        except Exception as e:  # noqa: BLE001 — the worker must report its
            # death through the transport (the router's circuit breaker is
            # the handler), never die silently holding the outbox.
            try:
                send(("fatal", f"{type(e).__name__}: {e}"))
            except Exception:  # lint: allow-broad-except — transport gone
                pass
            return


class EngineReplica:
    """Parent-side handle: lifecycle + transport for one engine worker.

    Subclasses provide `_send` / `_recv_nowait` / `alive` / `start` /
    `kill` / `join`; everything protocol-shaped lives here.  `poll()`
    returns every pending outbox message — the router interprets them; the
    synchronous helpers (`ping`/`warmup`/`request_stats`/`drain`) buffer
    non-matching messages so a sync call never swallows a result."""

    def __init__(self, replica_id: str, cfg):
        self.replica_id = replica_id
        self.cfg = cfg
        self._tok = 0
        self._buffered: list[tuple] = []
        self.fatal: Optional[str] = None

    # -- transport hooks (subclass) ---------------------------------------

    def start(self) -> None:
        raise NotImplementedError

    def alive(self) -> bool:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError

    def join(self, timeout: Optional[float] = None) -> None:
        raise NotImplementedError

    def _send(self, msg: tuple) -> None:
        raise NotImplementedError

    def _recv_nowait(self) -> Optional[tuple]:
        raise NotImplementedError

    # -- protocol ---------------------------------------------------------

    def ladders(self) -> dict:
        """The bucket ladders the router's affinity hash keys on — read
        from the replica's config, so router and replica can never
        disagree about what a bucket is."""
        return {
            "buckets": tuple(self.cfg.buckets),
            "rows_buckets": tuple(self.cfg.rows_buckets),
            "nrhs_buckets": tuple(self.cfg.nrhs_buckets),
        }

    def submit(self, rid: int, op: str, A, B=None,
               tier: str = "balanced",
               deadline_ms: Optional[float] = None) -> None:
        msg = ("submit", rid, op, np.asarray(A),
               np.asarray(B) if B is not None else None)
        if deadline_ms is not None:
            # deadline rides after the tier, so the tier goes on the wire
            # explicitly (even "balanced") whenever a deadline does
            msg = msg + (tier, float(deadline_ms))
        elif tier != "balanced":
            # trailing element only when non-balanced: balanced traffic
            # keeps the pre-tier 5-tuple wire format
            msg = msg + (tier,)
        self._send(msg)

    def poll(self) -> list[tuple]:
        """Every pending outbox message (buffered ones first).  A
        ("fatal", msg) is recorded on self.fatal and passed through."""
        out, self._buffered = self._buffered, []
        while True:
            msg = self._recv_nowait()
            if msg is None:
                break
            out.append(msg)
        for m in out:
            if m[0] == "fatal":
                self.fatal = m[1]
        return out

    def _await(self, kind: str, tok: int, timeout: float) -> Optional[tuple]:
        """Wait for one (kind, tok, ...) reply, buffering everything else
        for the next poll().  None on timeout or worker death."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            msg = self._recv_nowait()
            if msg is None:
                if not self.alive():
                    return None
                time.sleep(1e-3)
                continue
            if msg[0] == kind and len(msg) > 1 and msg[1] == tok:
                return msg
            if msg[0] == "fatal":
                self.fatal = msg[1]
            self._buffered.append(msg)
        return None

    def _roundtrip(self, req: str, reply: str, timeout: float,
                   *payload) -> Optional[tuple]:
        self._tok += 1
        tok = self._tok
        try:
            self._send((req, tok) + payload)
        except (OSError, ValueError):  # broken pipe / closed queue
            return None
        return self._await(reply, tok, timeout)

    def ping(self, timeout: float = 5.0) -> Optional[dict]:
        msg = self._roundtrip("ping", "pong", timeout)
        return msg[2] if msg else None

    def ping_async(self) -> int:
        """Fire-and-forget heartbeat: send a ping, return its token; the
        ("pong", token, info) arrives through poll() — the router's
        heartbeat uses this so a slow replica never blocks the pump."""
        self._tok += 1
        self._send(("ping", self._tok))
        return self._tok

    def warmup(self, specs, timeout: float = 300.0) -> Optional[dict]:
        """Warm the replica's engine over `specs` ((op, a_shape, b_shape,
        dtype) tuples); {"fresh": n, "cache": ...} or None on failure.
        Generous timeout: a cold replica really compiles here — a warm
        shared persist_dir is exactly what makes it fast."""
        msg = self._roundtrip("warmup", "warmed", timeout, list(specs))
        return msg[2] if msg else None

    def request_stats(self, timeout: float = 30.0) -> Optional[dict]:
        msg = self._roundtrip("stats", "stats", timeout)
        return msg[2] if msg else None

    def drain(self, timeout: float = 60.0) -> bool:
        """Land the whole in-flight window (results become pollable), ack.
        The replica stays alive — this is the rolling-restart barrier, not
        shutdown."""
        return self._roundtrip("drain", "drained", timeout) is not None

    def stop(self, timeout: float = 60.0) -> None:
        """Graceful shutdown: drain, ack, exit; then join the worker."""
        try:
            self._send(("stop",))
        except (OSError, ValueError):
            pass
        else:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline and self.alive():
                msg = self._recv_nowait()
                if msg is None:
                    time.sleep(1e-3)
                elif msg[0] != "stopped":
                    self._buffered.append(msg)
                else:
                    break
        self.join(timeout)


class ThreadReplica(EngineReplica):
    """In-process replica: engine worker on a daemon thread, queue
    transport.  The tier-1 test mode — full router semantics, no process
    spawn.  `kill()` flips a flag the worker polls between messages and
    exits on WITHOUT landing anything: the closest a thread can come to a
    process crash (results already posted to the outbox stay visible,
    which is exactly the crash race the router's first-wins rule covers).
    """

    def __init__(self, replica_id: str, cfg):
        super().__init__(replica_id, cfg)
        self._inbox: queue.Queue = queue.Queue()
        self._outbox: queue.Queue = queue.Queue()
        self._killed = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        cfg_kwargs = dataclasses.asdict(self.cfg)

        def recv(timeout: float) -> Optional[tuple]:
            try:
                return self._inbox.get(timeout=timeout) if timeout > 0 \
                    else self._inbox.get_nowait()
            except queue.Empty:
                return None

        self._thread = threading.Thread(
            target=_serve_loop,
            args=(self.replica_id, cfg_kwargs, recv, self._outbox.put,
                  self._killed.is_set),
            name=f"replica-{self.replica_id}",
            daemon=True,
        )
        self._thread.start()

    def alive(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and not self._killed.is_set())

    def kill(self) -> None:
        self._killed.set()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _send(self, msg: tuple) -> None:
        if self._killed.is_set():
            raise OSError(f"replica {self.replica_id} is dead")
        self._inbox.put(msg)

    def _recv_nowait(self) -> Optional[tuple]:
        try:
            return self._outbox.get_nowait()
        except queue.Empty:
            return None


def _process_worker(conn, replica_id: str, cfg_kwargs: dict,
                    env: Optional[dict]) -> None:
    """Child main for ProcessReplica.  Top-level (spawn target must be
    picklable by reference) and takes only plain kwargs: unpickling a
    ServeConfig here would import the engine — and therefore jax — before
    the env overrides land, baking the parent's platform into the child."""
    if env:
        os.environ.update(env)

    def recv(timeout: float) -> Optional[tuple]:
        try:
            if conn.poll(timeout):
                return conn.recv()
        except (EOFError, OSError):
            raise SystemExit(0) from None  # parent went away
        return None

    def send(msg: tuple) -> None:
        conn.send(msg)

    _serve_loop(replica_id, cfg_kwargs, recv, send, lambda: False)


class ProcessReplica(EngineReplica):
    """Subprocess replica over a duplex Pipe, spawn context.  `env` entries
    land in the child's os.environ BEFORE anything imports jax — pass
    {"JAX_PLATFORMS": ...} when the parent picked its platform through
    jax.config (which a spawned child never inherits) rather than the
    environment (which it does)."""

    def __init__(self, replica_id: str, cfg, env: Optional[dict] = None):
        super().__init__(replica_id, cfg)
        self.env = dict(env) if env else None
        self._proc = None
        self._conn = None

    def start(self) -> None:
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        self._conn, child = ctx.Pipe(duplex=True)
        self._proc = ctx.Process(
            target=_process_worker,
            args=(child, self.replica_id, dataclasses.asdict(self.cfg),
                  self.env),
            name=f"replica-{self.replica_id}",
            daemon=True,
        )
        self._proc.start()
        child.close()  # parent keeps only its end

    def alive(self) -> bool:
        return self._proc is not None and self._proc.is_alive()

    def kill(self) -> None:
        if self._proc is not None:
            self._proc.kill()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._proc is not None:
            self._proc.join(timeout)

    def _send(self, msg: tuple) -> None:
        if self._conn is None:
            raise OSError(f"replica {self.replica_id} not started")
        self._conn.send(msg)

    def _recv_nowait(self) -> Optional[tuple]:
        try:
            if self._conn is not None and self._conn.poll(0):
                return self._conn.recv()
        except (EOFError, OSError):
            return None
        return None


def make_replica(mode: str, replica_id: str, cfg,
                 env: Optional[dict] = None) -> EngineReplica:
    """'thread' or 'process' -> a started replica handle (not yet
    start()ed — the router starts what it registers)."""
    if mode == "thread":
        return ThreadReplica(replica_id, cfg)
    if mode == "process":
        return ProcessReplica(replica_id, cfg, env=env)
    raise ValueError(f"unknown replica mode {mode!r}: expected 'thread' "
                     "or 'process'")
