"""The served solve kernels: posv / lstsq / inv, batched and single-problem.

Two routes per op, chosen by the engine:

* **batched** — the whole bucket batch in one program, with TWO
  interchangeable implementations behind the `impl` switch:

  - ``vmap`` — a vmap over per-problem kernels built directly on the
    LAPACK seam (ops/lapack) and lax.linalg, which batch natively.  The
    models/ schedules are NOT vmapped: they carry sharding constraints and
    trace-time cost-model emits sized for one distributed problem, neither
    of which means anything replicated over a batch axis:

        posv   potrf(A) + the two-trsm potrs sweeps        (lapack.potrs)
        lstsq  CholeskyQR2 on the gram + triangular solve  (the CQR2
               pipeline of models/qr.py collapsed to single-problem form)
        inv    potrf_trtri + R⁻¹·R⁻ᵀ                       (spd_inverse's
               core)

  - ``pallas`` — the batched-grid kernels of ops/batched_small: ONE
    pallas_call with the batch axis on the grid, factor kept VMEM-resident
    between factor and solve (fused posv / fused CQR2 lstsq).  This is the
    small-N latency path; ``pallas_split`` is its unfused two-call variant
    (separate factor and solve launches — the A/B reference the latency
    autotune measures the fusion win against; lstsq has no split form and
    routes to the fused kernel).  ``auto`` resolves per bucket at trace
    time from the STATIC batch shapes (batched_small.default_impl: pallas
    iff posv/lstsq, n <= SMALL_N_MAX and VMEM-eligible, else vmap) — no
    runtime value feeds the choice, so the engine's zero-recompile
    invariant is untouched.

    inv rides the posv kernel: the serve contract guarantees an SPD
    operand (`submit` rejects anything else), so A⁻¹ = posv(A, Iₙ) — the
    auto resolution treats an inv bucket as a posv with an n-column RHS
    (batched_small itself keeps its "inv goes vmap" contract; the identity
    trick is serve policy, decided here).  Beyond the latency win on small
    buckets, this keeps the program pure HLO, which the persistent
    executable cache needs on CPU: LAPACK custom calls do not survive
    serialization across processes (serve/cache.py).

  Every batched kernel returns (X, info) with info the per-problem int32
  breakdown status — LAPACK with_info on the vmap path, the in-kernel
  O(n²) pivot/off-diagonal checks on the pallas paths (same 0/k/n+1
  convention, robust/detect.factor_info) — detection is O(n²) against the
  O(n³) solve, so it is always on; the engine decides whether to surface
  it (ServeConfig.robust) or let NaNs pass like the raw lax paths would.

* **single** — oversize requests (beyond every bucket ladder) run unbatched
  through the REAL models/ paths (cholesky.solve, qr.factor + triangular
  solve, cholinv factor + SUMMA gemm), so a giant request still gets the
  distributed schedules and, under robust, the full shifted-CholeskyQR
  recovery rather than detect-only flagging.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from capital_tpu.models import arrowhead, blocktri, cholesky, qr
from capital_tpu.ops import batched_small, blocktri_small, lapack, update_small
from capital_tpu.parallel import summa
from capital_tpu.utils import tracing


def _tri_solve_upper(R, B, precision):
    """R·X = B for upper-triangular R at the >= f32 compute dtype."""
    del precision  # triangular_solve has no precision knob; upcast covers it
    ct = lapack._compute_dtype(R.dtype)
    X = lax.linalg.triangular_solve(
        R.astype(ct), B.astype(ct), left_side=True, lower=False
    )
    return X.astype(B.dtype)


def _one_posv(precision):
    def f(a, b):
        with tracing.scope("serve::solve"):
            R, info = lapack.potrf(a, uplo="U", with_info=True)
            return lapack.potrs(R, b, uplo="U"), info

    return f


def _one_lstsq(precision):
    def f(a, b):
        with tracing.scope("serve::solve"):
            # CQR2 (models/qr.py single-problem form): two gram-Cholesky
            # sweeps; Q = A·R1⁻¹·R2⁻¹, R = R2·R1; then solve R·X = QᵀB.
            g = jnp.matmul(a.T, a, precision=precision)
            r1, r1i, i1 = lapack.potrf_trtri(g, uplo="U", with_info=True)
            q1 = jnp.matmul(a, jnp.triu(r1i), precision=precision)
            g2 = jnp.matmul(q1.T, q1, precision=precision)
            r2, r2i, i2 = lapack.potrf_trtri(g2, uplo="U", with_info=True)
            R = jnp.matmul(jnp.triu(r2), jnp.triu(r1), precision=precision)
            qtb = jnp.matmul(
                jnp.triu(r2i).T,
                jnp.matmul(q1.T, b, precision=precision),
                precision=precision,
            )
            return _tri_solve_upper(R, qtb, precision), jnp.maximum(i1, i2)

    return f


def _one_inv(precision):
    def f(a):
        with tracing.scope("serve::solve"):
            _, rinv, info = lapack.potrf_trtri(a, uplo="U", with_info=True)
            tri = jnp.triu(rinv)
            return jnp.matmul(tri, tri.T, precision=precision), info

    return f


def _batched_vmap(op: str, precision):
    """The vmap-over-LAPACK batch program: correctness reference and
    pure-XLA fallback for the pallas paths."""
    if op == "inv":
        return jax.vmap(_one_inv(precision))
    one = {"posv": _one_posv, "lstsq": _one_lstsq}[op](precision)
    return jax.vmap(one)


def _batched_pallas(op: str, precision, split: bool):
    """The batched-grid route: whole bucket batch in one (fused) or two
    (split) pallas_calls.  Resolution happened at trace time on static
    shapes, so the returned callable is shape-monomorphic like the vmap
    one — the engine AOT-compiles it per bucket exactly the same way.

    f64 buckets ALWAYS fall back to the vmap program, even when the impl
    was forced: the kernels compute in f32, so honoring impl='pallas' on
    an f64 bucket would silently downgrade precision behind f64-labeled
    outputs (batched_small.dtype_capable — the 'f64 always vmap'
    contract).  The check reads only the static dtype, so the fallback
    resolves at trace time and the zero-recompile invariant holds."""
    if op == "inv":
        # SPD inverse as posv against the identity (module docstring);
        # split runs the factor and the n-column solve as two launches.
        def kernel(a):
            eye = jnp.broadcast_to(
                jnp.eye(a.shape[-1], dtype=a.dtype), a.shape
            )
            if split:
                R, info = batched_small.potrf(
                    a, uplo="U", precision=precision
                )
                return batched_small.potrs(
                    R, eye, uplo="U", precision=precision
                ), info
            return batched_small.posv(a, eye, uplo="U", precision=precision)

        def f_inv(a):
            if not batched_small.dtype_capable(a.dtype):
                return _batched_vmap(op, precision)(a)
            return kernel(a)

        return f_inv
    if op == "lstsq":
        def kernel(a, b):
            return batched_small.lstsq(a, b, precision=precision)
    elif split:
        def kernel(a, b):
            R, info = batched_small.potrf(a, uplo="U", precision=precision)
            return batched_small.potrs(R, b, uplo="U",
                                       precision=precision), info
    else:
        def kernel(a, b):
            return batched_small.posv(a, b, uplo="U", precision=precision)

    def f(a, b):
        if not batched_small.dtype_capable(a.dtype):
            return _batched_vmap(op, precision)(a, b)
        return kernel(a, b)

    return f


def _batched_blocktri(precision, impl: str, blocktri_impl: str = "auto",
                      partitions: int = 0):
    """The block-tridiagonal bucket program: unpack the (batch, 2,
    nblocks, b, b) chain packing (A[:, 0] = diagonal blocks, A[:, 1] =
    sub-diagonal blocks) and run the fused scan-of-Pallas-blocks posv
    (models/blocktri).  The serve-wide impl vocabulary (batched_small.
    IMPLS, what ServeConfig.small_n_impl speaks) maps onto blocktri's
    own: 'vmap' means the pure lax.linalg scan ('xla' — there is no
    per-problem LAPACK route for the chain), 'pallas_split' means
    'pallas' (the chain has no split form; the scan IS the split).

    `blocktri_impl` is the ALGORITHM knob (ServeConfig.blocktri_impl,
    config-hashed): 'partitioned' forces the Spike driver with the
    serve-wide impl picking its inner scan flavor, 'scan' pins the
    sequential scan even where posv's auto would split, 'auto' leaves
    the choice to models/blocktri (auto kernel flavor only — a forced
    'pallas'/'vmap' engine keeps today's sequential program).  All
    resolution reads only static shapes/dtypes (models/blocktri
    ._resolve_impl incl. the f64-always-xla gate, resolve_partitions),
    so the engine's zero-recompile invariant holds."""
    mapped = {"auto": "auto", "pallas": "pallas",
              "pallas_split": "pallas", "vmap": "xla"}[impl]
    if blocktri_impl not in blocktri.ALGORITHMS:
        raise ValueError(
            f"unknown blocktri_impl {blocktri_impl!r}: expected one of "
            f"{blocktri.ALGORITHMS}")

    def f(a, b):
        if blocktri_impl == "partitioned":
            return blocktri.posv(a[:, 0], a[:, 1], b, precision=precision,
                                 impl="partitioned", partitions=partitions,
                                 partition_inner=mapped)
        if blocktri_impl == "scan" and mapped == "auto":
            # pin the sequential algorithm but keep per-bucket kernel
            # resolution: static-shape trace-time pick, like auto()
            nblocks, bs = a.shape[2], a.shape[3]
            pick = blocktri_small.default_impl(
                bs, b.shape[-1], blocktri.resolve_seg(nblocks), a.dtype)
            return blocktri.posv(a[:, 0], a[:, 1], b,
                                 precision=precision, impl=pick)
        return blocktri.posv(a[:, 0], a[:, 1], b, precision=precision,
                             impl=mapped, partitions=partitions)

    return f


def _batched_arrowhead(precision, impl: str, blocktri_impl: str = "auto",
                       partitions: int = 0):
    """The block-arrowhead bucket program: chain pack A = (batch, 2,
    nblocks, b, b) like posv_blocktri, plus the packed tail operand
    B = (batch, nblocks·b + s, s + k) (models/arrowhead.pack — border
    transpose, corner, and both RHS halves in one array; every geometry
    re-derives from the STATIC shapes, so bucket resolution and the
    zero-recompile invariant are untouched).

    THREE outputs (X_chain, X_corner, info): the chain half stays BLOCKED
    (batch, nblocks, b, k) so `batching.crop` unpads it by plain slicing
    like posv_blocktri's; the (batch, s, k) corner half rides the
    executor's extras slot to the engine's arrowhead landing sink, which
    crops and concatenates the flat (nblocks·b + s, k) response.

    The impl vocabulary and the `blocktri_impl` algorithm knob map
    exactly like `_batched_blocktri` — they reach the ONE widened chain
    solve inside arrowhead.posv (k + s columns), so 'partitioned' runs
    the Spike driver under the border solve."""
    mapped = {"auto": "auto", "pallas": "pallas",
              "pallas_split": "pallas", "vmap": "xla"}[impl]
    if blocktri_impl not in blocktri.ALGORITHMS:
        raise ValueError(
            f"unknown blocktri_impl {blocktri_impl!r}: expected one of "
            f"{blocktri.ALGORITHMS}")

    def f(a, b):
        nblocks, bs = a.shape[2], a.shape[3]
        F, S, B, Bs = arrowhead.unpack(b, nblocks, bs)
        if blocktri_impl == "partitioned":
            return arrowhead.posv(a[:, 0], a[:, 1], F, S, B, Bs,
                                  precision=precision, impl="partitioned",
                                  partitions=partitions,
                                  partition_inner=mapped)
        if blocktri_impl == "scan" and mapped == "auto":
            # pin the sequential algorithm, keep per-bucket kernel
            # resolution — at the WIDENED k + s column count the chain
            # sweeps actually run at (_batched_blocktri's idiom)
            pick = blocktri_small.default_impl(
                bs, B.shape[-1] + F.shape[2],
                blocktri.resolve_seg(nblocks), a.dtype)
            return arrowhead.posv(a[:, 0], a[:, 1], F, S, B, Bs,
                                  precision=precision, impl=pick)
        return arrowhead.posv(a[:, 0], a[:, 1], F, S, B, Bs,
                              precision=precision, impl=mapped,
                              partitions=partitions)

    return f


#: serve-wide impl vocabulary -> the two-impl modules' own ('vmap' means
#: the pure-XLA route; 'pallas_split' collapses to 'pallas' — neither the
#: update sweep nor the chain scan has a split form).
_TWO_IMPL_MAP = {"auto": "auto", "pallas": "pallas",
                 "pallas_split": "pallas", "vmap": "xla"}


def _batched_update(op: str, precision, impl: str):
    """chol_update / chol_downdate bucket program: (resident factor batch,
    rank-k panel batch) -> (R', info).  Impl resolution (incl. the
    f64-always-xla gate) lives in ops/update_small._resolve_impl and
    reads only static shapes/dtypes — zero-recompile safe."""
    mapped = _TWO_IMPL_MAP[impl]
    fn = (update_small.chol_update if op == "chol_update"
          else update_small.chol_downdate)

    def f(r, v):
        return fn(r, v, precision=precision, impl=mapped)

    return f


def _batched_posv_cached(precision, impl: str):
    """Solve against a RESIDENT factor: (R, B) -> (X, info≡0).  No
    factorization happens, so info is identically zero (a resident factor
    was healthy when installed — landing refuses to install flagged
    ones); the program is potrs alone, the whole point of residency."""
    def pallas_f(r, b):
        X = batched_small.potrs(r, b, uplo="U", precision=precision)
        return X, jnp.zeros(r.shape[0], jnp.int32)

    def vmap_f(r, b):
        with tracing.scope("serve::solve"):
            X = jax.vmap(lambda rr, bb: lapack.potrs(rr, bb, uplo="U"))(r, b)
        return X, jnp.zeros(r.shape[0], jnp.int32)

    if impl == "vmap":
        return vmap_f
    if impl in ("pallas", "pallas_split"):
        return lambda r, b: (
            pallas_f(r, b) if batched_small.dtype_capable(r.dtype)
            else vmap_f(r, b))

    def auto(r, b):
        pick = batched_small.default_impl("posv", r.shape, b.shape, r.dtype)
        return vmap_f(r, b) if pick == "vmap" else pallas_f(r, b)

    return auto


def _batched_posv_cached_miss(precision, impl: str):
    """The residency-miss (seeding) program: full (A, B) operands, THREE
    outputs (X, R, info) so landing can install the fresh factor under
    the request's token — a posv that also hands back its factor.  Priced
    as a full refactor (the cost-model point of the residency hit-rate
    gate)."""
    def pallas_f(a, b):
        R, info = batched_small.potrf(a, uplo="U", precision=precision)
        X = batched_small.potrs(R, b, uplo="U", precision=precision)
        return X, R, info

    def one_vmap(a, b):
        with tracing.scope("serve::solve"):
            R, info = lapack.potrf(a, uplo="U", with_info=True)
            return lapack.potrs(R, b, uplo="U"), R, info

    vmap_f = jax.vmap(one_vmap)
    if impl == "vmap":
        return vmap_f
    if impl in ("pallas", "pallas_split"):
        return lambda a, b: (
            pallas_f(a, b) if batched_small.dtype_capable(a.dtype)
            else vmap_f(a, b))

    def auto(a, b):
        pick = batched_small.default_impl("posv", a.shape, b.shape, a.dtype)
        return vmap_f(a, b) if pick == "vmap" else pallas_f(a, b)

    return auto


def _batched_extend(precision, impl: str):
    """The chain-extension bucket program: (appended chain packing
    (batch, 2, nblocks, b, b), resident carry (batch, b, b)) -> (stacked
    [L; Wt] (batch, 2, nblocks, b, b), info).  C[:, 0] arrives LIVE (the
    coupling into the prefix tail; the engine zeroes it host-side for
    fresh-token seeds, so ONE compiled program serves both cases)."""
    mapped = _TWO_IMPL_MAP[impl]

    def f(a, carry):
        L, Wt, info = blocktri.extend(a[:, 0], a[:, 1], carry,
                                      precision=precision, impl=mapped)
        return jnp.stack([L, Wt], axis=1), info

    return f


def _batched_session_extend(precision, impl: str):
    """The session open/append bucket program (docs/SERVING.md "Streaming
    sessions"): same operands and outputs as `_batched_extend` — ONE
    compiled program serves both session_open (engine zeroes C[:, 0] and
    seeds an identity carry host-side) and session_append (resident
    carry, live coupling).  The interior extend traces muted() under the
    SS::extend scope so the chain work is priced exactly once, under the
    session tag the session stats attribute by."""
    mapped = _TWO_IMPL_MAP[impl]

    def f(a, carry):
        nblocks, bs = a.shape[2], a.shape[3]
        with tracing.scope("SS::extend"):
            tracing.emit(flops=a.shape[0]
                         * tracing.blocktri_chol_flops(nblocks, bs))
            with tracing.muted():
                L, Wt, info = blocktri.extend(a[:, 0], a[:, 1], carry,
                                              precision=precision,
                                              impl=mapped)
        return jnp.stack([L, Wt], axis=1), info

    return f


def _batched_session_solve(precision, impl: str):
    """The resident-factor session solve: the 4-stack operand packing
    A = (batch, 4, nblocks, b, b) = [D; C; L; Wt] carries the session's
    explicit window (for the guaranteed tier's residual operator) AND its
    resident factor in one bucket-shaped array; the balanced program
    reads only the factor half — two block-bidiagonal sweeps, no
    factorization, info identically zero (residency installs only
    healthy factors, the posv_cached contract)."""
    mapped = _TWO_IMPL_MAP[impl]

    def f(a, b):
        nblocks, bs = a.shape[2], a.shape[3]
        with tracing.scope("SS::solve"):
            tracing.emit(flops=a.shape[0] * 2 * tracing.blocktri_solve_flops(
                nblocks, bs, b.shape[-1]))
            with tracing.muted():
                X = blocktri.solve(a[:, 2], a[:, 3], b,
                                   precision=precision, impl=mapped)
        return X, jnp.zeros(a.shape[0], jnp.int32)

    return f


def _batched_refine(op: str, precision, impl: str, tier: str):
    """The guaranteed-tier bucket program: mixed-precision iterative
    refinement (robust/refine) over the flagship solve.  FIVE outputs —
    (X, iters, converged, resid, info) — so the executor's extras slot
    carries each request's refinement facts to the engine's refine sink
    (stats + the loud non-convergence contract).  All dtype resolution
    (refine.plan) reads only the static operand dtype, so one compile per
    (bucket, tier) and the zero-recompile invariant holds."""
    from capital_tpu.robust import refine

    def f(a, b):
        p = refine.plan(tier, a.dtype)
        kw = dict(factor_dtype=p.factor_dtype,
                  correction_dtype=p.correction_dtype,
                  max_iters=p.max_iters, impl=impl, precision=precision)
        if op == "posv":
            X, info, ri = refine.posv(a, b, **kw)
        elif op == "lstsq":
            X, info, ri = refine.lstsq(a, b, **kw)
        elif op == "session_solve":
            # resident-factor refinement (PR 14's factor= seam): the
            # session's (L, Wt) ride the 4-stack packing (a[:, 2:4]) at
            # the plan's factor dtype — correct() sweeps against them,
            # the explicit (D, C) window half drives the high-precision
            # residual operator, and no refactor happens at all
            X, info, ri = refine.posv_blocktri(
                a[:, 0], a[:, 1], b,
                factor=(a[:, 2].astype(p.factor_dtype),
                        a[:, 3].astype(p.factor_dtype)), **kw)
        else:  # posv_blocktri (bucket packing: a[:, 0]=D, a[:, 1]=C)
            X, info, ri = refine.posv_blocktri(a[:, 0], a[:, 1], b, **kw)
        return X, ri.iters, ri.converged, ri.resid, info

    return f


#: the ops the accuracy-tier vocabulary applies to — the three flagship
#: solves refine.py wraps, plus the session resident-factor solve (its
#: guaranteed tier rides refine's factor= seam).  Everything else (inv,
#: the factor-residency ops) rejects a non-balanced tier loudly rather
#: than silently serving the balanced program under a tier label.
TIER_OPS = ("posv", "lstsq", "posv_blocktri", "session_solve")


def batched(op: str, precision: str | None = "highest",
            impl: str = "auto", *, blocktri_impl: str = "auto",
            blocktri_partitions: int = 0, tier: str = "balanced"):
    """The function the engine AOT-compiles for one bucket: maps the fixed
    (capacity, *problem) batch through the per-problem kernel, returning
    (X, info) stacks.

    `impl` picks the batch program: 'vmap' (LAPACK-seam reference),
    'pallas' (fused batched-grid kernels), 'pallas_split' (unfused
    batched-grid factor + solve, two launches), or 'auto' (resolve per
    bucket from the static batch shapes at trace time — small VMEM-
    eligible posv/lstsq buckets go pallas, everything else vmap).
    `blocktri_impl` / `blocktri_partitions` reach only the posv_blocktri
    program (`_batched_blocktri` — the partitioned-vs-scan algorithm
    knob; config-hashed by the engine).

    `tier` is the request's accuracy tier (robust/refine.TIERS, part of
    the bucket key): 'balanced' compiles today's program byte-identical;
    'fast' runs it with the factor dtype one notch down (refine._down1 —
    bf16/f32 factor throughput, answers cast back to the request dtype,
    NO refinement: the overload-shedding tier); 'guaranteed' compiles the
    iterative-refinement program (`_batched_refine` — low-precision
    factor, high-precision correction sweeps, five outputs).  Only the
    flagship TIER_OPS accept a non-balanced tier.
    """
    if impl not in batched_small.IMPLS:
        raise ValueError(
            f"unknown batched impl {impl!r}: expected one of "
            f"{batched_small.IMPLS}"
        )
    if tier != "balanced":
        from capital_tpu.robust import refine

        if tier not in refine.TIERS:
            raise ValueError(
                f"accuracy_tier must be one of {refine.TIERS}, got {tier!r}"
            )
        if op not in TIER_OPS:
            raise ValueError(
                f"accuracy_tier={tier!r} applies only to {TIER_OPS}; "
                f"op {op!r} serves the balanced program only"
            )
        if tier == "guaranteed":
            return _batched_refine(op, precision, impl, tier)
        inner = batched(op, precision, impl, blocktri_impl=blocktri_impl,
                        blocktri_partitions=blocktri_partitions)

        def fast(a, b):
            fd = refine.plan("fast", a.dtype).factor_dtype
            X, info = inner(a.astype(fd), b.astype(fd))
            return X.astype(a.dtype), info

        return fast
    if op == "posv_blocktri":
        return _batched_blocktri(precision, impl, blocktri_impl,
                                 blocktri_partitions)
    if op == "posv_arrowhead":
        return _batched_arrowhead(precision, impl, blocktri_impl,
                                  blocktri_partitions)
    if op in ("chol_update", "chol_downdate"):
        return _batched_update(op, precision, impl)
    if op == "posv_cached":
        return _batched_posv_cached(precision, impl)
    if op == "posv_cached_miss":
        return _batched_posv_cached_miss(precision, impl)
    if op == "blocktri_extend":
        return _batched_extend(precision, impl)
    if op == "session_extend":
        return _batched_session_extend(precision, impl)
    if op == "session_solve":
        return _batched_session_solve(precision, impl)
    if impl == "vmap":
        return _batched_vmap(op, precision)
    if impl in ("pallas", "pallas_split"):
        return _batched_pallas(op, precision, split=(impl == "pallas_split"))
    if op == "inv":
        # auto for inv: eligibility of the identity-RHS posv (the RHS is
        # the n-column identity, so the VMEM question is posv's with
        # b_shape == a_shape) — batched_small's own default_impl keeps
        # routing op='inv' to vmap; this resolution is serve policy.
        def auto_inv(a):
            pick = batched_small.default_impl(
                "posv", a.shape, a.shape, a.dtype
            )
            if pick == "vmap":
                return _batched_vmap(op, precision)(a)
            return _batched_pallas(op, precision, split=False)(a)

        return auto_inv

    def auto(a, b):
        b_shape = getattr(b, "shape", None)
        pick = batched_small.default_impl(op, a.shape, b_shape, a.dtype)
        if pick == "vmap":
            return _batched_vmap(op, precision)(a, b)
        return _batched_pallas(op, precision, split=False)(a, b)

    return auto


def single(op: str, grid, precision: str | None = "highest", robust=None,
           tail_fuse_depth: int = 0):
    """The oversize route: one exact-shape problem through the models/
    schedules on the engine's grid.  Uniform return contract (X, info):
    info is a scalar int32 (posv/inv) or a RobustInfo pytree (lstsq under
    robust); jnp.int32(0) when robust is None (the engine ignores it then).
    `tail_fuse_depth` threads ServeConfig's fused-recursion-tail knob into
    every CholinvConfig built here — it changes the compiled program, so
    the engine keys it into the cache config-hash.
    """
    if op == "posv":
        ccfg = cholesky.CholinvConfig(precision=precision, robust=robust,
                                      tail_fuse_depth=tail_fuse_depth)

        def f(a, b):
            out = cholesky.solve(grid, a, b, ccfg)
            return out if robust is not None else (out, jnp.int32(0))

        return f
    if op == "lstsq":
        qcfg = qr.CacqrConfig(
            precision=precision, robust=robust,
            cholinv=cholesky.CholinvConfig(precision=precision,
                                           tail_fuse_depth=tail_fuse_depth),
        )

        def f(a, b):
            out = qr.factor(grid, a, qcfg)
            if robust is not None:
                Q, R, rinfo = out
            else:
                (Q, R), rinfo = out, jnp.int32(0)
            qtb = qr.apply_QT(grid, Q, b, precision=precision)
            return _tri_solve_upper(R, qtb, precision), rinfo

        return f
    if op == "inv":
        ccfg = cholesky.CholinvConfig(precision=precision, robust=robust,
                                      tail_fuse_depth=tail_fuse_depth)

        def f(a):
            if robust is not None:
                _, rinv, info = cholesky.factor(grid, a, ccfg)
            else:
                _, rinv = cholesky.factor(grid, a, ccfg)
                info = jnp.int32(0)
            ainv = summa.gemm(
                grid, rinv, rinv,
                args=summa.GemmArgs(trans_b=True, precision=precision),
                mode=ccfg.mode,
            )
            return ainv, info

        return f
    if op == "posv_blocktri":
        # oversize chains run as a batch of one through the models
        # dispatch — impl='auto' picks the partitioned (Spike) driver
        # above PARTITION_MIN_NBLOCKS, exactly where oversize chains
        # live, cutting the critical path the batch of one cannot hide
        # (`grid` is accepted for signature uniformity).
        def f(a, b):
            X, info = blocktri.posv(a[None, 0], a[None, 1], b[None],
                                    precision=precision)
            return X[0], (info[0] if robust is not None else jnp.int32(0))

        return f
    if op == "posv_arrowhead":
        # oversize arrowheads run as a batch of one, like posv_blocktri
        # (impl='auto' picks the partitioned driver above
        # PARTITION_MIN_NBLOCKS).  The single route has no extras slot,
        # so the flat (nblocks·b + s, k) solution is assembled HERE —
        # the same response layout the engine's arrowhead sink produces
        # for batched requests.
        def f(a, b):
            nblocks, bs = a.shape[1], a.shape[2]
            F, S, B, Bs = arrowhead.unpack(b[None], nblocks, bs)
            X, Xs, info = arrowhead.posv(a[None, 0], a[None, 1], F, S, B,
                                         Bs, precision=precision)
            flat = jnp.concatenate(
                [X[0].reshape(nblocks * bs, X.shape[-1]), Xs[0]], axis=0)
            return flat, (info[0] if robust is not None else jnp.int32(0))

        return f
    raise ValueError(f"unknown serve op {op!r}")
