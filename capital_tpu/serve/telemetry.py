"""Rolling-window live telemetry for the serve tier.

`stats.Collector` is an end-of-run snapshot: one request_stats block per
run, percentiles over everything that ever happened.  A deadline-aware
scheduler (ROADMAP item 3) and a closed-loop re-tuner (ROADMAP item 6)
both need the STREAMING view instead — what does the traffic look like
*right now* — which is what the `WindowAggregator` provides: fixed-size
time windows on the monotonic clock, each closing into an immutable dict
with

* request/ok/failed/shed counts and a per-op split;
* a fixed-bin latency histogram (`HIST_EDGES_MS` log-spaced edges; exact
  counts, bounded memory) next to nearest-rank percentiles from a
  reservoir-capped raw-sample population (`sampled`/`samples_capped`
  mark the population honestly when the cap bit);
* per-bucket occupancy/batch/shed counters and the window's max queue
  depth — the per-bucket signal a ladder re-tuner mines.

Feeding is push-based and host-side pure Python: the engine's Collector
forwards every `record_request`/`note_batch`/`note_queue_depth` to an
attached aggregator (`SolveEngine.enable_telemetry`), so the hot path
gains three method calls and no device work.  Windows roll lazily on the
note-side clock — no background thread — and `emit()` appends one
schema-tagged ``serve:window`` ledger record PER closed window (the
record count is the `obs serve-report --min-windows` gate's subject;
`ledger.validate_serve_window` pins each record's internal coherence,
including p50 <= p95 <= p99).
"""

from __future__ import annotations

import time
from collections import Counter
from typing import Optional

from capital_tpu.bench.harness import percentiles
from capital_tpu.serve.stats import Reservoir

#: Fixed log-spaced histogram bin edges (milliseconds).  Counts live in
#: len(edges) + 1 bins: (-inf, e0], (e0, e1], ..., (e_last, +inf) — fixed
#: bins so windows from different runs/replicas sum without re-binning.
HIST_EDGES_MS = (0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                 250.0, 500.0, 1000.0, 2500.0, 10000.0)

#: Default per-window reservoir cap for the raw-sample population the
#: percentiles read — windows are short, so a modest cap is exact for
#: normal traffic and degrades visibly (samples_capped) under a storm.
DEFAULT_WINDOW_SAMPLE_CAP = 512


def _hist_index(latency_ms: float) -> int:
    for i, edge in enumerate(HIST_EDGES_MS):
        if latency_ms <= edge:
            return i
    return len(HIST_EDGES_MS)


class _Window:
    """One open window's mutable accumulators."""

    __slots__ = ("t_start", "requests", "ok", "failed", "shed", "ops",
                 "hist", "samples", "queue_depth_max", "batches",
                 "occupancies", "per_bucket")

    def __init__(self, t_start: float, sample_cap: int):
        self.t_start = t_start  # guarded-by: <frozen>
        self.requests = 0  # guarded-by: <owner-thread>
        self.ok = 0  # guarded-by: <owner-thread>
        self.failed = 0  # guarded-by: <owner-thread>
        self.shed = 0  # guarded-by: <owner-thread>
        self.ops: Counter = Counter()  # guarded-by: <owner-thread>
        self.hist = [0] * (len(HIST_EDGES_MS) + 1)  # guarded-by: <owner-thread>
        self.samples = Reservoir(sample_cap)  # guarded-by: <owner-thread>
        self.queue_depth_max = 0  # guarded-by: <owner-thread>
        self.batches = 0  # guarded-by: <owner-thread>
        self.occupancies: list[float] = []  # guarded-by: <owner-thread>
        # str(bucket) -> {"requests", "shed", "batches", "occupancies"}
        self.per_bucket: dict[str, dict] = {}  # guarded-by: <owner-thread>

    @property
    def empty(self) -> bool:
        return self.requests == 0 and self.batches == 0

    def bucket_cell(self, bucket) -> dict:
        key = str(bucket)
        cell = self.per_bucket.get(key)
        if cell is None:
            cell = {"requests": 0, "shed": 0, "batches": 0,
                    "occupancies": []}
            self.per_bucket[key] = cell
        return cell


class WindowAggregator:
    """See module docstring.  One aggregator per engine; not thread-safe
    (it rides the engine's single dispatch loop, like the Collector)."""

    def __init__(self, window_s: float = 1.0, *,
                 sample_cap: int = DEFAULT_WINDOW_SAMPLE_CAP,
                 clock=time.monotonic):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if sample_cap < 1:
            raise ValueError(f"sample_cap must be >= 1, got {sample_cap}")
        self.window_s = float(window_s)  # guarded-by: <frozen>
        self.sample_cap = int(sample_cap)  # guarded-by: <frozen>
        self._clock = clock  # guarded-by: <frozen>
        self._open: Optional[_Window] = None  # guarded-by: <owner-thread>
        self._closed: list[dict] = []  # guarded-by: <owner-thread>
        self._emitted = 0  # guarded-by: <owner-thread>  (prefix of _closed already on a ledger)

    # ---- feeding -----------------------------------------------------------

    def _roll(self, now: float) -> _Window:
        """Close the open window if `now` is past its end and open the one
        containing `now`.  Empty windows are skipped, not emitted — the
        ≥3-non-empty-windows gate counts traffic, never idle wall time."""
        w = self._open
        if w is not None and now - w.t_start >= self.window_s:
            self._close(w, min(now, w.t_start + self.window_s))
            self._open = w = None
        if w is None:
            w = _Window(now, self.sample_cap)
            self._open = w
        return w

    def note_request(self, op: str, latency_s: Optional[float], *,
                     ok: bool = True, failed: bool = False,
                     shed: bool = False, bucket=None,
                     t: Optional[float] = None) -> None:
        """One finished (or shed) request.  Shed requests carry no
        latency — they never ran — and count in `shed` only."""
        now = self._clock() if t is None else t
        w = self._roll(now)
        w.requests += 1
        w.ops[str(op)] += 1
        cell = w.bucket_cell(bucket) if bucket is not None else None
        if shed:
            w.shed += 1
            if cell is not None:
                cell["shed"] += 1
            return
        if failed:
            w.failed += 1
        else:
            w.ok += 1
        lat_ms = float(latency_s) * 1e3
        w.hist[_hist_index(lat_ms)] += 1
        w.samples.append(lat_ms)
        if cell is not None:
            cell["requests"] += 1

    def note_batch(self, occupancy: float, *, bucket=None,
                   t: Optional[float] = None) -> None:
        now = self._clock() if t is None else t
        w = self._roll(now)
        w.batches += 1
        w.occupancies.append(float(occupancy))
        if bucket is not None:
            cell = w.bucket_cell(bucket)
            cell["batches"] += 1
            cell["occupancies"].append(float(occupancy))

    def note_queue_depth(self, depth: int,
                         t: Optional[float] = None) -> None:
        now = self._clock() if t is None else t
        w = self._roll(now)
        w.queue_depth_max = max(w.queue_depth_max, int(depth))

    # ---- closing / reporting ----------------------------------------------

    def _close(self, w: _Window, t_end: float) -> None:
        if w.empty:
            return
        from capital_tpu.obs.ledger import SCHEMA_VERSION

        samples = list(w.samples)
        lat = (
            {k: round(v, 4) for k, v in percentiles(samples).items()}
            if samples else {"p50": 0.0, "p95": 0.0, "p99": 0.0}
        )
        occ = w.occupancies
        block = {
            "schema_version": SCHEMA_VERSION,
            "window_s": self.window_s,
            "t_start_s": round(w.t_start, 6),
            "t_end_s": round(t_end, 6),
            "requests": w.requests,
            "ok": w.ok,
            "failed": w.failed,
            "shed": w.shed,
            "ops": dict(w.ops),
            "latency_ms": lat,
            "hist_ms": {"edges": list(HIST_EDGES_MS),
                        "counts": list(w.hist)},
            "sampled": len(w.samples),
            "samples_capped": bool(w.samples.capped),
            "queue_depth_max": w.queue_depth_max,
            "batches": w.batches,
            "occupancy_mean": (round(sum(occ) / len(occ), 4)
                               if occ else 0.0),
            "per_bucket": {
                key: {
                    "requests": cell["requests"],
                    "shed": cell["shed"],
                    "batches": cell["batches"],
                    "occupancy_mean": (
                        round(sum(cell["occupancies"])
                              / len(cell["occupancies"]), 4)
                        if cell["occupancies"] else 0.0
                    ),
                }
                for key, cell in sorted(w.per_bucket.items())
            },
        }
        self._closed.append(block)

    def flush(self, t: Optional[float] = None) -> None:
        """Force-close the open window (end-of-run barrier before emit —
        a final partial window is data, not garbage)."""
        w = self._open
        if w is not None:
            self._close(w, self._clock() if t is None else t)
            self._open = None

    def windows(self) -> list[dict]:
        return list(self._closed)

    def emit(self, path: Optional[str] = None, *, grid=None, config=None,
             **extra) -> list[dict]:
        """Flush, then append one ``serve:window`` record per closed
        window not yet emitted (incremental — safe to call periodically
        from a serving loop).  Returns the records written this call."""
        from capital_tpu.obs import ledger

        self.flush()
        fresh = self._closed[self._emitted:]
        self._emitted = len(self._closed)
        recs = []
        for block in fresh:
            rec = ledger.record(
                "serve:window",
                ledger.manifest(grid=grid, config=config),
                serve_window=block,
                **extra,
            )
            if path:
                ledger.append(path, rec)
            recs.append(rec)
        return recs
