"""Shape bucketing + micro-batch assembly for the solve engine.

Every distinct operand shape is a fresh trace + compile; served traffic with
free-form shapes would recompile forever.  The classic serving answer
(bucketed paddings — the same trick XLA serving stacks use for sequence
lengths) applies cleanly to the CAPITAL solves because the repo already owns
a *structure-safe* pad: `masking.embed_identity_tail` generalizes
cholesky.pad_embed_identity's diag(X, I) embed, so a padded SPD matrix stays
SPD (factors to diag(R, I)) and a padded tall operand keeps full column rank
(the appended unit columns live in appended rows).  Padded right-hand sides
are zero-filled, so the identity tail solves to exact zeros and cropping
recovers the original solution bit-for-bit in exact arithmetic.

A `Bucket` is the padded per-problem shape plus the batch capacity; the
engine compiles ONE executable per bucket at the fixed batch shape
(capacity, *problem) and short batches are topped up with benign identity
fill problems — fixed shapes are the whole point (a dynamic batch dimension
would reintroduce one compile per batch size).

This module is policy-free about ladders: `bucket_for` reads them from the
engine's ServeConfig (duck-typed: .buckets / .rows_buckets / .nrhs_buckets /
.max_batch) so batching never imports engine.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from capital_tpu.ops import masking
from capital_tpu.utils import tracing

OPS = ("posv", "lstsq", "inv", "posv_blocktri", "posv_arrowhead",
       "chol_update", "chol_downdate", "posv_cached", "blocktri_extend")

#: ops that require a resident factor (engine.submit factor_token=...).
FACTOR_OPS = ("chol_update", "chol_downdate", "posv_cached",
              "blocktri_extend")

#: engine-internal bucket op: a posv_cached whose token was NOT resident
#: rides the full (A, B) operands through a 3-output refactor program
#: (X, R, info) so landing can install R — the seeding route, priced as a
#: residency miss.  Never a client-visible submit op.
MISS_OPS = ("posv_cached_miss",)

#: the streaming-session protocol ops (serve/sessions.py, docs/SERVING.md
#: "Streaming sessions") — all require factor_token = session id.
#: session_open and session_append normalize to the ONE engine-internal
#: `session_extend` bucket op (one compiled program serves both: the
#: engine zeroes C[:, 0] and seeds an identity carry for opens);
#: session_solve buckets under its own name with the 4-stack operand
#: packing A = (4, nblocks, b, b) = [D; C; L; Wt].  session_contract and
#: session_close are HOST-side administrative ops (a pure factor slice /
#: a residency release) that never touch a compiled program — they
#: bucket to None and land through the engine's host path.
SESSION_OPS = ("session_open", "session_append", "session_solve",
               "session_contract", "session_close")

#: engine-internal session bucket ops (the compiled halves of SESSION_OPS).
SESSION_BUCKET_OPS = ("session_extend", "session_solve")


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One executable-cache shape class: the padded per-problem operand
    shapes plus the micro-batch capacity.  Hashable (dict key for the
    executable cache and the per-bucket queues)."""

    op: str
    dtype: str
    a_shape: tuple[int, ...]
    b_shape: tuple[int, ...] | None
    capacity: int
    #: requested accuracy tier (robust/refine.TIERS).  Part of the key:
    #: tiers compile DIFFERENT programs (factor dtype, refinement loop),
    #: so same-shape requests at different tiers must land in different
    #: buckets — mixing them would either refine everyone (latency tax on
    #: fast traffic) or no one (silent accuracy downgrade).  Defaulted so
    #: pre-tier constructions and cache keys stay valid.
    tier: str = "balanced"

    @property
    def key(self) -> tuple:
        return (self.op, self.dtype, self.a_shape, self.b_shape,
                self.capacity, self.tier)


def bucket_label(bucket) -> str:
    """Compact human-stable bucket name for span/telemetry tags and
    chrome-trace args, e.g. ``posv/f32/a256x256/b256x8/c8`` — the key's
    information without tuple-repr noise (and JSON-safe).  Accepts a
    Bucket or its `.key` tuple (the form Responses/stats carry)."""
    if isinstance(bucket, tuple):
        bucket = Bucket(*bucket)
    a = "x".join(str(d) for d in bucket.a_shape)
    b = ("" if bucket.b_shape is None
         else "/b" + "x".join(str(d) for d in bucket.b_shape))
    tier = "" if bucket.tier == "balanced" else f"/{bucket.tier}"
    dt = str(bucket.dtype).replace("float", "f").replace("bfloat", "bf")
    return f"{bucket.op}/{dt}/a{a}{b}/c{bucket.capacity}{tier}"


def _pick(ladder: tuple[int, ...], v: int) -> int | None:
    """Smallest ladder rung >= v, or None (oversize)."""
    best = None
    for r in ladder:
        if r >= v and (best is None or r < best):
            best = r
    return best


def bucket_for(op: str, a_shape, b_shape, dtype: str, cfg,
               *, tier: str = "balanced") -> Bucket | None:
    """Resolve a request's operand shapes to a bucket, or None when any
    dimension exceeds its ladder (the engine then routes the request
    unbatched through the models/ paths — `oversize` policy).

    `tier` stamps the accuracy tier into the bucket key (geometry is
    tier-independent — tiers change the PROGRAM, not the padded shapes).

    lstsq rows bucket at `m + (nb - n)`: the column pad appends one unit
    column PER padded column and each needs its own appended row
    (masking.embed_identity_tail's rows - m >= cols - n contract).

    posv_blocktri packs the chain as A = (2, nblocks, b, b) — A[0] the
    diagonal blocks, A[1] the sub-diagonal blocks (A[1, 0] dead) — and
    B = (nblocks, b, nrhs), bucketing nblocks and b on their own ladders
    (cfg.nblocks_buckets / cfg.block_buckets); nrhs shares the dense
    ladder.

    posv_arrowhead rides the same chain pack for A and ONE packed tail
    operand B = (nblocks·b + s, s + nrhs) (models/arrowhead.pack: columns
    [:s] are the dense system's last s columns [Bᵀ; S], columns [s:] the
    full RHS).  The chain buckets like posv_blocktri, the border width s
    gets its OWN ladder (cfg.border_buckets — s is a structural rank, not
    an RHS count), nrhs shares the dense ladder; the bucketed tail shape
    is (nbb·bb + sb, sb + kb), from which the program re-derives every
    geometry statically.

    The factor-residency ops bucket on the ENGINE-COMPOSED operands, not
    the wire payload: chol_update/chol_downdate as (resident R (n, n),
    V (n, k)) with k on the nrhs ladder; posv_cached as (resident R,
    RHS) with posv's exact geometry (posv_cached_miss: (A, RHS), same
    shapes, different program); blocktri_extend as (appended chain
    (2, nblocks, b, b), resident carry (b, b))."""
    if op not in OPS and op not in MISS_OPS and op not in SESSION_BUCKET_OPS:
        raise ValueError(f"unknown serve op {op!r}; expected one of {OPS}")
    if tier != "balanced":
        from capital_tpu.robust import refine

        if tier not in refine.TIERS:
            raise ValueError(
                f"accuracy_tier must be one of {refine.TIERS}, got {tier!r}"
            )
        b = bucket_for(op, a_shape, b_shape, dtype, cfg)
        return None if b is None else dataclasses.replace(b, tier=tier)
    if op in ("chol_update", "chol_downdate"):
        nb = _pick(cfg.buckets, a_shape[0])
        kb = _pick(cfg.nrhs_buckets, b_shape[1])
        if nb is None or kb is None:
            return None
        return Bucket(op, dtype, (nb, nb), (nb, kb), cfg.max_batch)
    if op in ("posv_cached", "posv_cached_miss"):
        nb = _pick(cfg.buckets, a_shape[0])
        kb = _pick(cfg.nrhs_buckets, b_shape[1])
        if nb is None or kb is None:
            return None
        return Bucket(op, dtype, (nb, nb), (nb, kb), cfg.max_batch)
    if op in ("blocktri_extend", "session_extend"):
        _, nblocks, b, _ = a_shape
        nbb = _pick(cfg.nblocks_buckets, nblocks)
        bb = _pick(cfg.block_buckets, b)
        if nbb is None or bb is None:
            return None
        return Bucket(op, dtype, (2, nbb, bb, bb), (bb, bb),
                      cfg.max_batch)
    if op == "session_solve":
        # 4-stack session pack: [D; C; L; Wt] — the explicit window AND
        # the resident factor in one bucket-shaped operand (api.py
        # `_batched_session_solve`); geometry buckets like posv_blocktri
        _, nblocks, b, _ = a_shape
        nbb = _pick(cfg.nblocks_buckets, nblocks)
        bb = _pick(cfg.block_buckets, b)
        kb = _pick(cfg.nrhs_buckets, b_shape[2])
        if nbb is None or bb is None or kb is None:
            return None
        return Bucket(op, dtype, (4, nbb, bb, bb), (nbb, bb, kb),
                      cfg.max_batch)
    if op == "posv_blocktri":
        _, nblocks, b, _ = a_shape
        nbb = _pick(cfg.nblocks_buckets, nblocks)
        bb = _pick(cfg.block_buckets, b)
        kb = _pick(cfg.nrhs_buckets, b_shape[2])
        if nbb is None or bb is None or kb is None:
            return None
        return Bucket(op, dtype, (2, nbb, bb, bb), (nbb, bb, kb),
                      cfg.max_batch)
    if op == "posv_arrowhead":
        _, nblocks, b, _ = a_shape
        s = b_shape[0] - nblocks * b
        k = b_shape[1] - s
        nbb = _pick(cfg.nblocks_buckets, nblocks)
        bb = _pick(cfg.block_buckets, b)
        sb = _pick(cfg.border_buckets, s)
        kb = _pick(cfg.nrhs_buckets, k)
        if nbb is None or bb is None or sb is None or kb is None:
            return None
        return Bucket(op, dtype, (2, nbb, bb, bb),
                      (nbb * bb + sb, sb + kb), cfg.max_batch)
    if op in ("posv", "inv"):
        n = a_shape[0]
        nb = _pick(cfg.buckets, n)
        if nb is None:
            return None
        if op == "inv":
            return Bucket(op, dtype, (nb, nb), None, cfg.max_batch)
        kb = _pick(cfg.nrhs_buckets, b_shape[1])
        if kb is None:
            return None
        return Bucket(op, dtype, (nb, nb), (nb, kb), cfg.max_batch)
    m, n = a_shape
    nb = _pick(cfg.buckets, n)
    if nb is None:
        return None
    mb = _pick(cfg.rows_buckets, m + (nb - n))
    kb = _pick(cfg.nrhs_buckets, b_shape[1])
    if mb is None or kb is None:
        return None
    return Bucket(op, dtype, (mb, nb), (mb, kb), cfg.max_batch)


def pad_operands(op: str, A, B, bucket: Bucket):
    """Pad one request's concrete operands to the bucket's per-problem
    shapes: identity-tail embed for the factored operand, zero-fill for the
    RHS.  Host-side eager (submit time), tagged serve::pad so profiler
    traces attribute the pad cost to the serving layer."""
    with tracing.scope("serve::pad"):
        if op == "posv_blocktri":
            return _pad_blocktri(A, B, bucket)
        if op == "posv_arrowhead":
            return _pad_arrowhead(A, B, bucket)
        if op in ("blocktri_extend", "session_extend"):
            return _pad_blocktri_extend(A, B, bucket)
        if op == "session_solve":
            return _pad_session_solve(A, B, bucket)
        if op in ("chol_update", "chol_downdate"):
            # diag(R, I) stays a valid upper factor (of diag(A, I)) and
            # the zero-filled V rows/columns make every padded rotation a
            # t = 0 no-op — the pad is a fixed point of the update, so
            # cropping recovers the true R' exactly
            pa = masking.embed_identity_tail(A, *bucket.a_shape)
            n, k = B.shape
            pb = jnp.pad(B, ((0, bucket.b_shape[0] - n),
                             (0, bucket.b_shape[1] - k)))
            return pa, pb
        pa = masking.embed_identity_tail(A, *bucket.a_shape)
        pb = None
        if bucket.b_shape is not None:
            m, k = B.shape
            pb = jnp.pad(
                B, ((0, bucket.b_shape[0] - m), (0, bucket.b_shape[1] - k))
            )
        return pa, pb


def _pad_blocktri(A, B, bucket: Bucket):
    """Structure-safe pad for the block-tridiagonal chain: every diagonal
    block gets the per-block identity-tail embed diag(D_i, I) (the Schur
    chain preserves diag(·, I) exactly — all products are 0·x or 1·x),
    sub-diagonal and RHS blocks zero-pad, and appended chain blocks are
    pure identity problems with zero couplings — the padded operand stays
    block-tridiagonal SPD and the real blocks' solution is BITWISE the
    unpadded one (the chain is sequential, so trailing identity blocks
    never feed back; their forward/backward carries are exact zeros)."""
    _, nblocks, b, _ = A.shape
    nbb, bb = bucket.a_shape[1], bucket.a_shape[2]
    kb = bucket.b_shape[2]
    pa = jnp.pad(A, ((0, 0), (0, nbb - nblocks),
                     (0, bb - b), (0, bb - b)))
    eye = jnp.eye(bb, dtype=A.dtype)
    # real blocks complete to diag(D_i, I); appended blocks become I
    tail = jnp.where(jnp.arange(bb) >= b, eye, jnp.zeros_like(eye))
    blk = (jnp.arange(nbb) < nblocks)[:, None, None]
    pa = pa.at[0].add(jnp.where(blk, tail, eye))
    pb = jnp.pad(B, ((0, nbb - nblocks), (0, bb - b),
                     (0, kb - B.shape[2])))
    return pa, pb


def _pad_arrowhead(A, P, bucket: Bucket):
    """Structure-safe pad for the block-arrowhead operands: the chain pack
    pads exactly like `_pad_blocktri` (diag(D_i, I) embeds, zero
    couplings, appended identity blocks); in the packed tail operand the
    border columns zero-pad (appended border columns couple to nothing),
    the corner embeds as diag(S, I) (masking.embed_identity_tail), and
    every RHS entry zero-pads.  The padded dense system is
    diag(A_real_embedded, I): the appended border rows are all-zero, so
    the padded Schur complement is diag(S̃, I) and the appended corner
    rows solve to exact zeros.  For chain-LENGTH padding (nblocks only)
    the real solution is BITWISE the unpadded one, the PR 10 chain-pad
    claim extended through the completion: the appended blocks' border
    couplings are exact zeros, so every Schur/back-substitution
    contraction term they add is 0·x (tests/test_arrowhead.py asserts
    it); block-size / border / nrhs padding is tight but not bitwise (the
    contraction lengths change).

    The chain rows of the tail operand are RE-BLOCKED before padding
    (reshape to (nblocks, b, ·), pad each axis, re-flatten): a flat row
    pad would interleave the appended block-tail rows wrongly when
    bb > b."""
    _, nblocks, b, _ = A.shape
    nbb, bb = bucket.a_shape[1], bucket.a_shape[2]
    n_t = nblocks * b
    s = P.shape[0] - n_t
    k = P.shape[1] - s
    sb = bucket.b_shape[0] - nbb * bb
    kb = bucket.b_shape[1] - sb
    pa = jnp.pad(A, ((0, 0), (0, nbb - nblocks),
                     (0, bb - b), (0, bb - b)))
    eye = jnp.eye(bb, dtype=A.dtype)
    tail = jnp.where(jnp.arange(bb) >= b, eye, jnp.zeros_like(eye))
    blk = (jnp.arange(nbb) < nblocks)[:, None, None]
    pa = pa.at[0].add(jnp.where(blk, tail, eye))
    top = P[:n_t].reshape(nblocks, b, s + k)
    ptop = jnp.concatenate(
        [jnp.pad(top[..., :s],
                 ((0, nbb - nblocks), (0, bb - b), (0, sb - s))),
         jnp.pad(top[..., s:],
                 ((0, nbb - nblocks), (0, bb - b), (0, kb - k)))],
        axis=-1).reshape(nbb * bb, sb + kb)
    pbot = jnp.concatenate(
        [masking.embed_identity_tail(P[n_t:, :s], sb, sb),
         jnp.pad(P[n_t:, s:], ((0, sb - s), (0, kb - k)))], axis=-1)
    return pa, jnp.concatenate([ptop, pbot], axis=0)


def _pad_blocktri_extend(A, carry, bucket: Bucket):
    """Structure-safe pad for the chain-extension operands: the appended
    blocks pad exactly like `_pad_blocktri` (diag(D_i, I) embeds, zero
    couplings, appended identity blocks), and the resident carry L_last
    embeds as diag(L_last, I) — a valid lower factor of diag(S_last, I),
    so the first appended block's coupling solve W₁ = C̃₁·L̃₀⁻ᵀ is exact
    block-diagonal arithmetic (the zero-padded C rows never touch the
    identity tail).  Bitwise-inert like every serve pad."""
    _, nblocks, b, _ = A.shape
    nbb, bb = bucket.a_shape[1], bucket.a_shape[2]
    pa = jnp.pad(A, ((0, 0), (0, nbb - nblocks),
                     (0, bb - b), (0, bb - b)))
    eye = jnp.eye(bb, dtype=A.dtype)
    tail = jnp.where(jnp.arange(bb) >= b, eye, jnp.zeros_like(eye))
    blk = (jnp.arange(nbb) < nblocks)[:, None, None]
    pa = pa.at[0].add(jnp.where(blk, tail, eye))
    pcarry = masking.embed_identity_tail(carry, bb, bb)
    return pa, pcarry


def _pad_session_solve(A, B, bucket: Bucket):
    """Structure-safe pad for the session 4-stack [D; C; L; Wt]: the
    window half pads exactly like `_pad_blocktri` (diag(D_i, I) embeds,
    zero couplings, appended identity blocks), and the factor half pads
    CONSISTENTLY with it — diag(L_i, I) is the Cholesky factor of
    diag(S_i, I) and the zero-padded Wt rows/columns keep both solve
    sweeps' padded carries exact zeros, so the real blocks' solution is
    BITWISE the unpadded one and the guaranteed tier's residual operator
    sees residual ≡ 0 on every padded row (zero RHS against identity
    diagonal blocks)."""
    _, nblocks, b, _ = A.shape
    nbb, bb = bucket.a_shape[1], bucket.a_shape[2]
    kb = bucket.b_shape[2]
    pa = jnp.pad(A, ((0, 0), (0, nbb - nblocks),
                     (0, bb - b), (0, bb - b)))
    eye = jnp.eye(bb, dtype=A.dtype)
    tail = jnp.where(jnp.arange(bb) >= b, eye, jnp.zeros_like(eye))
    blk = (jnp.arange(nbb) < nblocks)[:, None, None]
    emb = jnp.where(blk, tail, eye)
    pa = pa.at[0].add(emb)   # D -> diag(D_i, I), appended blocks I
    pa = pa.at[2].add(emb)   # L -> diag(L_i, I), appended blocks I
    pb = jnp.pad(B, ((0, nbb - nblocks), (0, bb - b),
                     (0, kb - B.shape[2])))
    return pa, pb


def fill_problem(bucket: Bucket):
    """The benign problem that tops a short batch up to capacity: an
    identity operand (SPD for posv/inv, orthonormal columns for lstsq —
    its gram is I, so every op factors it cleanly) against a zero RHS.
    For posv_blocktri the fill is the identity CHAIN: identity diagonal
    blocks, zero couplings — every block factors to L = I exactly; the
    arrowhead fill couples that chain to an identity corner through a
    zero border (the whole fill matrix is I)."""
    dt = jnp.dtype(bucket.dtype)
    if bucket.op == "posv_arrowhead":
        _, nbb, bb, _ = bucket.a_shape
        eyes = jnp.broadcast_to(jnp.eye(bb, dtype=dt), (nbb, bb, bb))
        fa = jnp.stack([eyes, jnp.zeros((nbb, bb, bb), dt)])
        sb = bucket.b_shape[0] - nbb * bb
        fb = jnp.zeros(bucket.b_shape, dt)
        fb = fb.at[nbb * bb:, :sb].set(jnp.eye(sb, dtype=dt))
        return fa, fb
    if bucket.op in ("posv_blocktri", "blocktri_extend", "session_extend",
                     "session_solve"):
        _, nbb, bb, _ = bucket.a_shape
        eyes = jnp.broadcast_to(jnp.eye(bb, dtype=dt), (nbb, bb, bb))
        zeros = jnp.zeros((nbb, bb, bb), dt)
        if bucket.op == "session_solve":
            # identity window with its own factor: L = I, Wt = 0 is
            # exactly factor(I-chain), so both sweeps and the residual
            # operator are no-ops on fill slots
            fa = jnp.stack([eyes, zeros, eyes, zeros])
            return fa, jnp.zeros(bucket.b_shape, dtype=dt)
        fa = jnp.stack([eyes, zeros])
        if bucket.op in ("blocktri_extend", "session_extend"):
            # identity carry: extending the identity chain from L = I
            # factors every fill block to L = I exactly
            return fa, jnp.eye(bb, dtype=dt)
        return fa, jnp.zeros(bucket.b_shape, dtype=dt)
    fa = jnp.eye(*bucket.a_shape, dtype=dt)
    fb = None
    if bucket.b_shape is not None:
        fb = jnp.zeros(bucket.b_shape, dtype=dt)
    return fa, fb


def assemble(padded_a, padded_b, bucket: Bucket):
    """Stack per-request padded operands into the bucket's fixed batch
    shape, topping up with fill problems.  Returns (Ab, Bb | None,
    occupancy) — occupancy is the real-request fraction of capacity, the
    number stats.py reports (chronically low occupancy means the flush
    policy or the ladder is mis-tuned)."""
    nreq = len(padded_a)
    if not 0 < nreq <= bucket.capacity:
        raise ValueError(f"{nreq} requests for capacity {bucket.capacity}")
    fa, fb = fill_problem(bucket)
    Ab = jnp.stack(list(padded_a) + [fa] * (bucket.capacity - nreq))
    Bb = None
    if bucket.b_shape is not None:
        Bb = jnp.stack(list(padded_b) + [fb] * (bucket.capacity - nreq))
    return Ab, Bb, nreq / bucket.capacity


def crop(op: str, X, a_shape, b_shape):
    """Slice one padded per-problem solution back to the request's true
    shape (the unpad half of the masking contract: the identity tail's
    rows of X are exact zeros and are dropped here)."""
    if op in ("posv", "posv_cached", "posv_cached_miss"):
        return X[: a_shape[0], : b_shape[1]]
    if op == "lstsq":
        return X[: a_shape[1], : b_shape[1]]
    if op in ("posv_blocktri", "session_solve"):
        return X[: a_shape[1], : a_shape[2], : b_shape[2]]
    if op == "posv_arrowhead":
        # X is the CHAIN half (nbb, bb, kb) — blocked, so plain slicing
        # unpads; the corner half rides the program's extras slot and the
        # engine's arrowhead sink crops + concatenates it (engine.py)
        nblocks, b = a_shape[1], a_shape[2]
        s = b_shape[0] - nblocks * b
        return X[:nblocks, :b, : b_shape[1] - s]
    if op in ("blocktri_extend", "session_extend"):
        # stacked (2, nbb, bb, bb) [L; Wt] back to the appended blocks
        return X[:, : a_shape[1], : a_shape[2], : a_shape[2]]
    # inv / chol_update / chol_downdate: square (n, n) principal window
    return X[: a_shape[0], : a_shape[0]]
