"""SolveEngine: the AOT-cached, shape-bucketed, micro-batching solve service.

The serving loop the ROADMAP's "heavy traffic" north star needs, built from
what PRs 1-3 already provide (docs/SERVING.md has the full lifecycle):

* **AOT executable cache** — every program the engine runs is compiled once
  via ``jax.jit(fn).lower(ShapeDtypeStruct...).compile()`` (the aot65536
  pattern) and cached under an explicit key (op, dtype, shape-bucket,
  mesh/topology, config-hash).  Hit/miss counters make "steady-state traffic
  hits zero recompiles" an *assertable* property, not a hope: `warmup()`
  pre-compiles the bucket ladder without touching the counters, after which
  a clean run shows misses == 0 / hit_rate == 1.0 (tests/test_serve.py,
  `make serve-smoke`).

* **Shape bucketing + micro-batching** — requests pad to bucket ladders
  (serve/batching.py) and queue per bucket; a batch flushes when it reaches
  `max_batch` (at submit) or when its oldest request ages past `max_delay_s`
  (at `pump()`/`drain()`).  Oversize requests bypass batching and run
  through the real models/ schedules, AOT-cached per exact shape.

* **Robust routing** — with ServeConfig.robust, each response carries a
  RobustInfo and a breakdown flags ONE request (`ok=False`) instead of
  killing the engine; fault injection enters host-side at the
  ``serve::ingest`` tap on the concrete per-request operand, so a planted
  fault can never bake into a cached executable (the trace-time-tap hazard
  faultinject's docstring warns about).

* **Donation** — batched RHS / operand buffers are donated on TPU only
  (ServeConfig.donate=None auto): CPU's runtime ignores donation with a
  warning per executable, and the engine builds those batch arrays itself
  so donating them is always safe.  Only aliasable buffers are declared:
  posv donates its RHS batch (solution is shape-for-shape), inv its operand
  batch; lstsq donates nothing — its (m, nrhs) RHS cannot alias the
  (n, nrhs) solution, and XLA would silently drop the declaration.
  ``SolveEngine(validate=True)`` asserts the compiled input_output_alias
  honors every declared donation at cache-insert time (the lint
  donation-honored rule; docs/STATIC_ANALYSIS.md).  The single-problem
  models route never donates: schedules like cholinv's schur_in_place carry
  their own aliasing contracts on caller buffers.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Optional

import jax
import jax.numpy as jnp

from capital_tpu.ops import batched_small
from capital_tpu.parallel.topology import Grid
from capital_tpu.robust import faultinject
from capital_tpu.robust.config import RobustConfig, RobustInfo
from capital_tpu.serve import api, batching, stats


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine policy knobs.

    buckets: the n ladder (SPD dimension / lstsq columns).
    rows_buckets: the lstsq m ladder (requests bucket at m + column-pad).
    nrhs_buckets: the RHS-columns ladder.
    max_batch: per-bucket batch capacity — one executable per bucket at
        this fixed batch size; also the submit-time flush threshold.
    max_delay_s: oldest-request age that forces a flush at pump() — the
        latency bound a half-full batch is allowed to cost.
    precision: matmul precision inside the kernels ('highest' matches the
        models/ defaults; see CholinvConfig.precision).
    robust: attach per-request breakdown flagging (batched: detect-only;
        oversize lstsq: the full shifted-CholeskyQR recovery).
    donate: donate engine-built batch inputs to their executables; None =
        auto (TPU yes, CPU no — the CPU runtime warns and ignores).
    oversize: 'models' routes beyond-ladder requests through the unbatched
        models/ paths; 'reject' fails them (a hard-real-time posture where
        an unexpected compile is worse than an error).
    small_n_impl: which batched implementation the bucket executables use
        (serve/api.batched): 'auto' resolves per bucket at trace time
        (small VMEM-eligible posv/lstsq buckets take the fused batched-
        grid pallas kernels of ops/batched_small, the rest vmap-over-
        LAPACK); 'vmap' / 'pallas' / 'pallas_split' force one route for
        every bucket.  Joins the config hash — two engines differing here
        compile different programs and must never share cache entries.
    """

    buckets: tuple[int, ...] = (256, 512, 1024)
    rows_buckets: tuple[int, ...] = (4096, 16384, 65536)
    nrhs_buckets: tuple[int, ...] = (1, 8, 64)
    max_batch: int = 8
    max_delay_s: float = 0.005
    precision: Optional[str] = "highest"
    robust: Optional[RobustConfig] = None
    donate: Optional[bool] = None
    oversize: str = "models"
    small_n_impl: str = "auto"


@dataclasses.dataclass
class Response:
    """One finished request.  `x` is the cropped solution (None only when
    `ok` is False with `error` set — an ingest fault or a rejected
    request).  `info` is a RobustInfo under ServeConfig.robust (breakdown
    != 0 means x is flagged garbage), else None."""

    request_id: int
    op: str
    ok: bool
    x: Optional[jnp.ndarray]
    info: Optional[RobustInfo]
    error: Optional[str]
    bucket: Optional[tuple]
    batched: bool
    latency_s: float


class Ticket:
    """Handle returned by submit(); resolves when its batch flushes."""

    __slots__ = ("request_id", "response")

    def __init__(self, request_id: int):
        self.request_id = request_id
        self.response: Optional[Response] = None

    @property
    def done(self) -> bool:
        return self.response is not None

    def result(self) -> Response:
        if self.response is None:
            raise RuntimeError(
                f"request {self.request_id} not flushed yet — call "
                "engine.pump() (deadline flush) or engine.drain()"
            )
        return self.response


@dataclasses.dataclass
class _Pending:
    ticket: Ticket
    pa: jnp.ndarray
    pb: Optional[jnp.ndarray]
    a_shape: tuple[int, ...]
    b_shape: Optional[tuple[int, ...]]
    t_enq: float


class SolveEngine:
    """See module docstring.  One engine per (grid, ServeConfig); not
    thread-safe (a single dispatch loop owns it, like a jax program)."""

    def __init__(self, grid: Optional[Grid] = None,
                 cfg: ServeConfig = ServeConfig(), *,
                 validate: bool = False):
        if cfg.oversize not in ("models", "reject"):
            raise ValueError(f"unknown oversize policy {cfg.oversize!r}")
        if cfg.small_n_impl not in batched_small.IMPLS:
            raise ValueError(
                f"unknown small_n_impl {cfg.small_n_impl!r}: expected one "
                f"of {batched_small.IMPLS}"
            )
        self.grid = grid or Grid.square(c=1, devices=jax.devices()[:1])
        self.cfg = cfg
        # validate: run the lint donation-honored rule on every executable at
        # cache-insert time — a declared donate_argnums that XLA silently
        # drops (shape mismatch with every output) raises instead of leaving
        # the batch buffer double-resident for the cache entry's lifetime.
        self.validate = validate
        self.stats = stats.Collector()
        self._exe: dict[tuple, object] = {}
        self._queues: dict[batching.Bucket, list[_Pending]] = {}
        self._hits = 0
        self._misses = 0
        self._warmup_compiles = 0
        self._next_id = 0
        # config-hash: everything that changes the compiled programs or the
        # padding geometry — two engines differing here must never share
        # cache entries, and the key makes that structural.
        ident = repr((cfg.buckets, cfg.rows_buckets, cfg.nrhs_buckets,
                      cfg.max_batch, cfg.precision, cfg.robust,
                      cfg.small_n_impl))
        self._cfg_hash = hashlib.sha1(ident.encode()).hexdigest()[:12]
        self._grid_key = (self.grid.dx, self.grid.dy, self.grid.c,
                          self.grid.platform)

    # ---- cache -------------------------------------------------------------

    def _donate(self) -> bool:
        d = self.cfg.donate
        return self.grid.platform == "tpu" if d is None else d

    def _small_route(self, bucket: batching.Bucket) -> bool:
        """Whether this bucket's executable runs the batched-grid small-N
        kernels — the same static-shape resolution api.batched('auto')
        makes at trace time, re-derived here so the stats collector can
        split small-bucket latency (latency_ms_small) from the rest."""
        impl = self.cfg.small_n_impl
        if bucket.op == "inv" or impl == "vmap":
            return False
        if not batched_small.dtype_capable(bucket.dtype):
            # forced pallas included: api._batched_pallas falls back to the
            # vmap program for f64, so the executable is NOT small-route
            return False
        if impl in ("pallas", "pallas_split"):
            return True
        a_shape = (bucket.capacity,) + bucket.a_shape
        b_shape = ((bucket.capacity,) + bucket.b_shape
                   if bucket.b_shape is not None else None)
        return batched_small.default_impl(
            bucket.op, a_shape, b_shape, bucket.dtype
        ) == "pallas"

    def _get_batched(self, bucket: batching.Bucket, warmup: bool = False):
        key = ("batch", bucket.key, self._grid_key, self._cfg_hash)
        exe = self._exe.get(key)
        if exe is not None:
            if not warmup:
                self._hits += 1
            return exe
        if warmup:
            self._warmup_compiles += 1
        else:
            self._misses += 1
        dt = jnp.dtype(bucket.dtype)
        specs = [jax.ShapeDtypeStruct((bucket.capacity,) + bucket.a_shape, dt)]
        dn: tuple[int, ...] = ()
        if bucket.b_shape is not None:
            specs.append(
                jax.ShapeDtypeStruct((bucket.capacity,) + bucket.b_shape, dt)
            )
            # Only posv's solution aliases its RHS shape-for-shape.  lstsq's
            # (m, nrhs) RHS can never alias the (n, nrhs) solution, so XLA
            # would silently drop that donation (lint rule donation-honored)
            # and the batch would sit double-resident in HBM.
            if self._donate() and bucket.op == "posv":
                dn = (1,)
        elif self._donate():
            dn = (0,)  # inv: the operand batch aliases the inverse batch
        fn = api.batched(bucket.op, self.cfg.precision,
                         self.cfg.small_n_impl)
        exe = jax.jit(fn, donate_argnums=dn).lower(*specs).compile()
        if self.validate and dn:
            from capital_tpu.lint import program as lint_program

            probs = lint_program.check_donation(
                exe, dn, target=f"serve:{bucket.key}",
            )
            if probs:
                raise AssertionError(
                    "donation dropped at cache insert: "
                    + "; ".join(f.message for f in probs)
                )
        self._exe[key] = exe
        return exe

    def _get_single(self, op: str, a_sds, b_sds, warmup: bool = False):
        key = ("single", op, str(a_sds.dtype), a_sds.shape,
               b_sds.shape if b_sds is not None else None,
               self._grid_key, self._cfg_hash)
        exe = self._exe.get(key)
        if exe is not None:
            if not warmup:
                self._hits += 1
            return exe
        if warmup:
            self._warmup_compiles += 1
        else:
            self._misses += 1
        fn = api.single(op, self.grid, self.cfg.precision, self.cfg.robust)
        specs = (a_sds,) if b_sds is None else (a_sds, b_sds)
        exe = jax.jit(fn).lower(*specs).compile()
        self._exe[key] = exe
        return exe

    def cache_stats(self) -> dict:
        """Hit/miss counters over request-driven executable lookups.
        warmup() compiles count separately — hit_rate measures steady-state
        traffic, and the acceptance gate is hit_rate == 1.0 after warmup."""
        lookups = self._hits + self._misses
        return {
            "hits": self._hits,
            "misses": self._misses,
            "warmup_compiles": self._warmup_compiles,
            "entries": len(self._exe),
            "hit_rate": (self._hits / lookups) if lookups else 1.0,
        }

    def warmup(self, specs) -> int:
        """Pre-compile executables for example request shapes.  `specs` is
        an iterable of (op, a_shape, b_shape, dtype) — b_shape None for
        inv.  Shapes resolve through the SAME bucket ladder as submit(),
        so warming one representative per bucket covers every shape that
        maps there; oversize shapes warm their exact-shape single route.
        Returns the number of fresh compiles."""
        before = self._warmup_compiles
        for op, a_shape, b_shape, dtype in specs:
            dt = jnp.dtype(dtype)
            bucket = batching.bucket_for(
                op, tuple(a_shape), tuple(b_shape) if b_shape else None,
                str(dt), self.cfg,
            )
            if bucket is not None:
                self._get_batched(bucket, warmup=True)
            elif self.cfg.oversize == "models":
                a_sds = jax.ShapeDtypeStruct(tuple(a_shape), dt)
                b_sds = (jax.ShapeDtypeStruct(tuple(b_shape), dt)
                         if b_shape else None)
                self._get_single(op, a_sds, b_sds, warmup=True)
        return self._warmup_compiles - before

    # ---- request path ------------------------------------------------------

    def submit(self, op: str, A, B=None) -> Ticket:
        """Enqueue one solve request; returns a Ticket that resolves when
        its batch flushes (possibly within this call: capacity flush, or
        immediately for oversize requests)."""
        t0 = time.monotonic()
        tid = self._next_id
        self._next_id += 1
        ticket = Ticket(tid)
        A = jnp.asarray(A)
        B = jnp.asarray(B) if B is not None else None
        if op not in batching.OPS:
            raise ValueError(
                f"unknown serve op {op!r}; expected one of {batching.OPS}"
            )
        if op in ("posv", "lstsq") and (B is None or B.ndim != 2
                                        or B.shape[0] != A.shape[0]):
            raise ValueError(
                f"{op} needs a 2D RHS with {A.shape[0]} rows, got "
                f"{None if B is None else B.shape}"
            )
        if op in ("posv", "inv") and A.shape[0] != A.shape[1]:
            raise ValueError(f"{op} needs a square SPD operand, got {A.shape}")
        if op == "lstsq" and A.shape[0] < A.shape[1]:
            raise ValueError(f"lstsq expects tall input, got {A.shape}")
        try:
            # HOST-side per-request fault tap on the concrete operand:
            # deterministic per submit() occurrence, and — critically —
            # never part of a traced program, so a fault corrupts exactly
            # one request and leaves the executable cache clean.
            A = faultinject.tap(A, point="serve::ingest")
        except faultinject.FaultInjected as e:
            self._fail(ticket, op, str(e), t0)
            return ticket
        bucket = batching.bucket_for(
            op, A.shape, B.shape if B is not None else None,
            str(A.dtype), self.cfg,
        )
        if bucket is None:
            if self.cfg.oversize == "reject":
                self._fail(
                    ticket, op,
                    f"no bucket for {op} {A.shape} and oversize='reject'",
                    t0,
                )
            else:
                self._run_single(ticket, op, A, B, t0)
            return ticket
        pa, pb = batching.pad_operands(op, A, B, bucket)
        q = self._queues.setdefault(bucket, [])
        q.append(_Pending(
            ticket, pa, pb, tuple(A.shape),
            tuple(B.shape) if B is not None else None, t0,
        ))
        self.stats.note_queue_depth(self.queue_depth())
        if len(q) >= bucket.capacity:
            self._flush(bucket)
        return ticket

    def pump(self, now: Optional[float] = None) -> int:
        """Deadline flush: run every bucket whose oldest request has aged
        past max_delay_s.  Call from the dispatch loop between submits;
        returns the number of batches flushed."""
        now = time.monotonic() if now is None else now
        flushed = 0
        for bucket in list(self._queues):
            q = self._queues.get(bucket)
            if q and now - q[0].t_enq >= self.cfg.max_delay_s:
                self._flush(bucket)
                flushed += 1
        return flushed

    def drain(self) -> int:
        """Flush every non-empty queue regardless of age (shutdown / test
        barrier).  Returns the number of batches flushed."""
        flushed = 0
        for bucket in list(self._queues):
            if self._queues.get(bucket):
                self._flush(bucket)
                flushed += 1
        return flushed

    def solve(self, op: str, A, B=None) -> Response:
        """Convenience synchronous path: submit + drain + result."""
        ticket = self.submit(op, A, B)
        if not ticket.done:
            self.drain()
        return ticket.result()

    def queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def emit_stats(self, path: Optional[str] = None, **extra) -> dict:
        """Snapshot telemetry + cache counters into one serve:request_stats
        ledger record (appended to `path` when given)."""
        return self.stats.emit(
            path, grid=self.grid, config=self.cfg,
            cache=self.cache_stats(), **extra,
        )

    # ---- internals ---------------------------------------------------------

    def _fail(self, ticket: Ticket, op: str, error: str, t0: float) -> None:
        lat = time.monotonic() - t0
        ticket.response = Response(
            request_id=ticket.request_id, op=op, ok=False, x=None,
            info=None, error=error, bucket=None, batched=False,
            latency_s=lat,
        )
        self.stats.record_request(op, lat, ok=False, failed=True)

    def _norm_info(self, raw) -> Optional[RobustInfo]:
        if self.cfg.robust is None:
            return None
        if isinstance(raw, RobustInfo):
            return RobustInfo(
                info=int(raw.info), breakdown=int(raw.breakdown),
                shifted=int(raw.shifted), sigma=float(raw.sigma),
                escalated=int(raw.escalated), ortho=float(raw.ortho),
            )
        i = int(raw)
        # detect-only sites surface the potrf convention; no recovery ran
        return RobustInfo(info=i, breakdown=int(i != 0), shifted=0,
                          sigma=0.0, escalated=0, ortho=-1.0)

    def _finish(self, ticket: Ticket, op: str, x, raw_info,
                bucket_key: Optional[tuple], batched: bool,
                t0: float, small: bool = False) -> None:
        info = self._norm_info(raw_info)
        ok = info is None or info.info == 0
        lat = time.monotonic() - t0
        ticket.response = Response(
            request_id=ticket.request_id, op=op, ok=ok, x=x, info=info,
            error=None, bucket=bucket_key, batched=batched, latency_s=lat,
        )
        self.stats.record_request(op, lat, ok=ok,
                                  flagged=(info is not None and not ok),
                                  small=small)

    def _flush(self, bucket: batching.Bucket) -> None:
        q = self._queues.pop(bucket, [])
        if not q:
            return
        exe = self._get_batched(bucket)
        Ab, Bb, occupancy = batching.assemble(
            [p.pa for p in q], [p.pb for p in q], bucket,
        )
        X, info = exe(Ab) if Bb is None else exe(Ab, Bb)
        self.stats.note_batch(occupancy)
        small = self._small_route(bucket)
        for i, p in enumerate(q):
            xi = batching.crop(bucket.op, X[i], p.a_shape, p.b_shape)
            self._finish(p.ticket, bucket.op, xi, info[i], bucket.key,
                         True, p.t_enq, small=small)

    def _run_single(self, ticket: Ticket, op: str, A, B, t0: float) -> None:
        a_sds = jax.ShapeDtypeStruct(A.shape, A.dtype)
        b_sds = (jax.ShapeDtypeStruct(B.shape, B.dtype)
                 if B is not None else None)
        exe = self._get_single(op, a_sds, b_sds)
        x, raw = exe(A) if B is None else exe(A, B)
        self._finish(ticket, op, x, raw, None, False, t0)
